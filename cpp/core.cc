#include "core.h"

#include <pthread.h>
#include <unistd.h>

#include <algorithm>
#include <cstring>
#include <sstream>

#include "bayes_opt.h"
#include "logging.h"
#include "timeline.h"
#include "wire.h"

namespace hvd {

namespace {
// Tag space per coordination domain: domain*16 + channel
constexpr int kTagNegotiate = 0;  // worker -> coordinator request lists
constexpr int kTagResponse = 1;   // coordinator -> worker response lists
constexpr int kTagData = 2;       // collective payload (uses +1 too)
constexpr int kTagAdasum = 8;     // VHDD channels [8, 12]
constexpr int kTagBarrier = 13;

int32_t DomTag(int domain, int channel) { return domain * 16 + channel; }

constexpr size_t kAlign = 64;  // fusion alignment (reference common.h:146)
size_t AlignUp(size_t n) { return (n + kAlign - 1) & ~(kAlign - 1); }
}  // namespace

// ---------------------------------------------------------------------------
// TensorQueue (reference: tensor_queue.cc)
// ---------------------------------------------------------------------------

bool TensorQueue::Push(TensorTableEntry entry, Request req) {
  std::lock_guard<std::mutex> lk(mu_);
  if (table_.count(entry.name)) return false;  // reference: DUPLICATE_NAME
  table_[entry.name] = std::move(entry);
  requests_.push_back(std::move(req));
  return true;
}

std::vector<Request> TensorQueue::PopRequests() {
  std::lock_guard<std::mutex> lk(mu_);
  std::vector<Request> out(requests_.begin(), requests_.end());
  requests_.clear();
  return out;
}

bool TensorQueue::Take(const std::string& name, TensorTableEntry* out) {
  std::lock_guard<std::mutex> lk(mu_);
  auto it = table_.find(name);
  if (it == table_.end()) return false;
  *out = std::move(it->second);
  table_.erase(it);
  return true;
}

void TensorQueue::FinalizeAllWithError(const Status& s) {
  std::lock_guard<std::mutex> lk(mu_);
  for (auto& kv : table_)
    if (kv.second.callback) kv.second.callback(s);
  table_.clear();
  requests_.clear();
}

size_t TensorQueue::pending() const {
  std::lock_guard<std::mutex> lk(mu_);
  return table_.size();
}

// ---------------------------------------------------------------------------
// ResponseCache (reference: response_cache.cc)
// ---------------------------------------------------------------------------

std::string ResponseCache::Key(const Request& r) {
  std::ostringstream os;
  os << r.name << '|' << (int)r.type << '|' << (int)r.dtype << '|'
     << (int)r.op << '|' << r.root_rank << '|' << r.prescale << '|'
     << r.postscale << '|' << r.group_id << '|' << r.group_size;
  for (auto d : r.shape) os << ',' << d;
  return os.str();
}

int ResponseCache::Lookup(const std::string& key) const {
  auto it = index_.find(key);
  return it == index_.end() ? -1 : it->second;
}

void ResponseCache::Touch(int bit) {
  auto it = lru_pos_.find(bit);
  if (it == lru_pos_.end()) return;
  lru_.erase(it->second);
  lru_.push_front(bit);
  it->second = lru_.begin();
}

int ResponseCache::Insert(const std::string& key, const Response& resp,
                          Response* evicted, bool* did_evict) {
  auto it = index_.find(key);
  if (it != index_.end()) {
    Touch(it->second);  // coordinated point: refresh recency
    return it->second;
  }
  int bit;
  if (entries_.size() < capacity_) {
    bit = (int)entries_.size();
    entries_.emplace_back(key, resp);
  } else {
    // evict the least-recently-used entry and reuse its bit (reference:
    // response_cache.cc eviction; recency only changes at coordinated
    // points, so every rank evicts the same entry on the same cycle)
    if (capacity_ == 0) return -1;
    bit = lru_.back();
    if (evicted) *evicted = entries_[bit].second;
    if (did_evict) *did_evict = true;
    evictions_++;
    index_.erase(entries_[bit].first);
    lru_.pop_back();
    lru_pos_.erase(bit);
    entries_[bit] = {key, resp};
  }
  index_[key] = bit;
  lru_.push_front(bit);
  lru_pos_[bit] = lru_.begin();
  return bit;
}

const Response& ResponseCache::Get(int bit) const {
  return entries_[bit].second;
}

// ---------------------------------------------------------------------------
// StallInspector (reference: stall_inspector.cc)
// ---------------------------------------------------------------------------

void StallInspector::RecordPending(const std::string& name,
                                   const std::vector<int>& ranks, int size) {
  auto it = pending_.find(name);
  if (it == pending_.end()) {
    pending_[name] = {std::chrono::steady_clock::now(), ranks, false};
  } else {
    it->second.ready_ranks = ranks;
  }
}

void StallInspector::RemoveReady(const std::string& name) {
  pending_.erase(name);
}

std::string StallInspector::Check(double warn_seconds, int* newly_warned,
                                  int* currently_stalled) {
  auto now = std::chrono::steady_clock::now();
  std::ostringstream os;
  int warned = 0, stalled = 0;
  for (auto& kv : pending_) {
    double waited =
        std::chrono::duration<double>(now - kv.second.first_seen).count();
    if (waited <= warn_seconds) continue;
    stalled++;
    if (!kv.second.warned) {
      kv.second.warned = true;
      warned++;
      os << "tensor '" << kv.first << "' stalled " << (int)waited
         << "s; ready ranks: ";
      for (int r : kv.second.ready_ranks) os << r << ' ';
      os << '\n';
    }
  }
  if (newly_warned) *newly_warned = warned;
  if (currently_stalled) *currently_stalled = stalled;
  return os.str();
}

std::vector<StallInspector::PendingEntry> StallInspector::Pending() const {
  auto now = std::chrono::steady_clock::now();
  std::vector<PendingEntry> out;
  out.reserve(pending_.size());
  for (auto& kv : pending_) {
    out.push_back(
        {kv.first,
         std::chrono::duration<double>(now - kv.second.first_seen).count(),
         kv.second.ready_ranks});
  }
  return out;
}

std::vector<std::string> StallInspector::FatallyStalled(
    double shutdown_seconds) {
  std::vector<std::string> out;
  if (shutdown_seconds <= 0) return out;
  auto now = std::chrono::steady_clock::now();
  for (auto& kv : pending_) {
    double waited =
        std::chrono::duration<double>(now - kv.second.first_seen).count();
    if (waited > shutdown_seconds) out.push_back(kv.first);
  }
  return out;
}

// ---------------------------------------------------------------------------
// ParameterManager — GP/expected-improvement Bayesian optimization over
// (log fusion threshold, log cycle time), scored by bytes/sec
// (reference: parameter_manager.h + optim/bayesian_optimization.cc)
// ---------------------------------------------------------------------------

namespace {
// normalized [0,1] <-> parameter ranges (log scale)
constexpr double kFusionLogMin = 20.0;   // 2^20 = 1 MB
constexpr double kFusionLogMax = 28.0;   // 2^28 = 256 MB
constexpr double kCycleLogMin = -1.0;    // 2^-1 = 0.5 ms
constexpr double kCycleLogMax = 3.5;     // 2^3.5 ~= 11 ms

int64_t DenormFusion(double u) {
  return (int64_t)std::pow(
      2.0, kFusionLogMin + u * (kFusionLogMax - kFusionLogMin));
}
double DenormCycle(double u) {
  return std::pow(2.0, kCycleLogMin + u * (kCycleLogMax - kCycleLogMin));
}
double NormFusion(int64_t f) {
  double l = std::log2((double)std::max<int64_t>(f, 1));
  return std::min(1.0, std::max(0.0, (l - kFusionLogMin) /
                                          (kFusionLogMax - kFusionLogMin)));
}
double NormCycle(double c) {
  double l = std::log2(std::max(c, 1e-3));
  return std::min(1.0, std::max(0.0, (l - kCycleLogMin) /
                                          (kCycleLogMax - kCycleLogMin)));
}
}  // namespace

void ParameterManager::Enable(int64_t init_fusion, double init_cycle,
                              int warmup_samples, int max_samples,
                              double gp_noise,
                              const std::string& log_path,
                              double window_secs, bool allow_hier) {
  enabled_ = true;
  allow_hier_ = allow_hier;
  warmup_samples_ = warmup_samples;
  max_samples_ = max_samples;
  gp_noise_ = gp_noise;
  window_secs_ = window_secs;
  // sample trace (reference: HOROVOD_AUTOTUNE_LOG, parameter_manager.cc
  // writes a CSV of tried parameters and scores)
  if (log_) {
    fclose(log_);  // elastic re-init: close the previous generation's file
    log_ = nullptr;
  }
  if (!log_path.empty()) log_ = fopen(log_path.c_str(), "w");
  if (log_)
    fprintf(log_,
            "sample,fusion_bytes,cycle_ms,hierarchical,cache,"
            "bytes_per_sec\n");
  // 4-D space: (log fusion, log cycle, hierarchical, cache) — the
  // categorical dims the reference's ParameterManager also explores
  // (parameter_manager.h:42-105)
  bo_ = std::make_shared<BayesianOptimizer>(4, 17, gp_noise_);
  window_start_ = std::chrono::steady_clock::now();
}

void ParameterManager::Record(int64_t bytes) { bytes_acc_ += bytes; }

bool ParameterManager::Tune(int64_t* fusion_bytes, double* cycle_ms,
                            bool* hierarchical, bool* cache_enabled) {
  if (!enabled_) return false;
  auto now = std::chrono::steady_clock::now();
  double secs = std::chrono::duration<double>(now - window_start_).count();
  if (secs < window_secs_) return false;  // scoring window (seconds)
  double score = bytes_acc_ / secs;
  bytes_acc_ = 0;
  window_start_ = now;
  samples_++;
  if (log_) {
    fprintf(log_, "%d,%lld,%g,%d,%d,%g\n", samples_,
            (long long)*fusion_bytes, *cycle_ms, *hierarchical ? 1 : 0,
            *cache_enabled ? 1 : 0, score);
    fflush(log_);
  }
  // discard warmup samples (reference: AUTOTUNE_WARMUP_SAMPLES) so
  // startup transients don't poison the GP
  if (samples_ <= warmup_samples_) return false;
  bo_->AddSample({NormFusion(*fusion_bytes), NormCycle(*cycle_ms),
                  *hierarchical ? 1.0 : 0.0, *cache_enabled ? 1.0 : 0.0},
                 score);
  std::vector<double> x;
  if (samples_ > warmup_samples_ + max_samples_) {  // converge to best
    x = bo_->BestSample();
    enabled_ = false;
  } else {
    x = bo_->NextSample();
  }
  *fusion_bytes = DenormFusion(x[0]);
  *cycle_ms = DenormCycle(x[1]);
  *hierarchical = allow_hier_ && x[2] >= 0.5;
  *cache_enabled = x[3] >= 0.5;
  return true;
}

// ---------------------------------------------------------------------------
// Core
// ---------------------------------------------------------------------------

Core& Core::Get() {
  static Core core;
  return core;
}

Core::~Core() { Shutdown(); }

int Core::NewHandle(TensorTableEntry*) {
  int h = next_handle_.fetch_add(1);
  auto hs = std::make_shared<HandleState>();
  std::lock_guard<std::mutex> lk(handles_mu_);
  handles_[h] = hs;
  return h;
}

std::shared_ptr<Core::HandleState> Core::GetHandle(int h) {
  std::lock_guard<std::mutex> lk(handles_mu_);
  auto it = handles_.find(h);
  return it == handles_.end() ? nullptr : it->second;
}

void Core::PushToDomain(int domain, TensorTableEntry e, Request r) {
  // span bookkeeping FIRST, before any rejection path: the Python layer
  // allocates its span id per eager call unconditionally (spans.py), so
  // the engine must count every attempt too — a DUPLICATE_NAME
  // rejection that only one side counted would desynchronize the two
  // per-name counters for the rest of the run.  Internal names
  // (__barrier__/__join__, _hvd.* plumbing like the clock-sync
  // allgathers) never get Python-side spans and are excluded.
  if (timeline_ && e.name.rfind("__", 0) != 0 &&
      e.name.rfind("_hvd.", 0) != 0)
    timeline_->NoteEnqueue(e.name);
  if (loop_done_.load()) {
    if (e.callback)
      e.callback(Status::Aborted(
          loop_error_.empty()
              ? "hvdcore background loop is not running"
              : "hvdcore background loop is not running: " + loop_error_));
    return;
  }
  std::lock_guard<std::mutex> lk(domains_mu_);
  // re-check under the same lock the dying loop's finalize pass takes:
  // an entry pushed after that pass would otherwise never resolve (its
  // waiter would hang — exactly the failure mode this PR hunts)
  if (loop_done_.load()) {
    if (e.callback)
      e.callback(Status::Aborted(
          loop_error_.empty()
              ? "hvdcore background loop is not running"
              : "hvdcore background loop is not running: " + loop_error_));
    return;
  }
  auto it = domains_.find(domain);
  if (it == domains_.end()) {
    if (e.callback)
      e.callback(Status::Error("unknown process set / coordination domain"));
    return;
  }
  if (it->second->group.my_index < 0) {
    if (e.callback)
      e.callback(Status::Error(
          "this rank is not a member of the process set"));
    return;
  }
  auto cb = e.callback;
  std::string name = e.name;
  if (!it->second->queue.Push(std::move(e), std::move(r))) {
    if (cb)
      cb(Status::Error("duplicate tensor name submitted before previous "
                       "operation on '" + name + "' completed (reference: "
                       "DUPLICATE_NAME error)"));
    return;
  }
  KickCycle();
}

void Core::KickCycle() {
  {
    std::lock_guard<std::mutex> lk(cycle_mu_);
    cycle_kick_ = true;
  }
  cycle_cv_.notify_one();
}

Status Core::Init(const CoreConfig& cfg) {
  if (initialized_) return Status::OK();
  cfg_ = cfg;
  loop_error_.clear();  // a prior generation's exit cause is not ours
  LogRank() = cfg.rank;  // stamp every later log line with our rank
  HVD_LOG(Info) << "core init: size=" << cfg.size << " coordinator="
                << cfg.coord_addr << ":" << cfg.coord_port
                << " fusion=" << cfg.fusion_threshold
                << "B cycle=" << cfg.cycle_time_ms << "ms";
  transport_.reset(
      new Transport(cfg.rank, cfg.size, cfg.coord_addr, cfg.coord_port,
                    cfg.rendezvous_timeout_secs,
                    cfg.transport_timeout_secs,
                    cfg.wire_checksum));
  // fresh transport, fresh per-life counters: re-baseline the mirror
  // so counters_ keeps accumulating instead of absorbing a reset-to-0
  seen_transport_chaos_ = 0;
  seen_transport_checksum_ = 0;
  auto st = transport_->Init();
  if (!st.ok()) return st;
  timeline_.reset(new Timeline(cfg.rank, cfg.timeline_path,
                               cfg.timeline_mark_cycles));
  if (cfg.autotune)
    param_mgr_.Enable(cfg.fusion_threshold, cfg.cycle_time_ms,
                      cfg.autotune_warmup_samples,
                      cfg.autotune_max_samples, cfg.autotune_gp_noise,
                      // only the coordinator tunes (Tune() is rank-0-
                      // gated); a worker opening the same path would
                      // truncate the coordinator's trace on shared
                      // filesystems
                      cfg.rank == 0 ? cfg.autotune_log : std::string(),
                      cfg.autotune_window_secs,
                      /*allow_hier=*/cfg.local_size > 1 &&
                          cfg.size == cfg.local_size * cfg.cross_size);

  auto global = std::unique_ptr<CoordDomain>(new CoordDomain());
  global->id = 0;
  global->group.ranks.resize(cfg.size);
  for (int i = 0; i < cfg.size; ++i) global->group.ranks[i] = i;
  global->group.my_index = cfg.rank;
  global->cache.reset(new ResponseCache(cfg.cache_capacity));
  global->joined_ranks.assign(cfg.size, false);
  {
    std::lock_guard<std::mutex> lk(domains_mu_);
    domains_[0] = std::move(global);
  }
  // hierarchical allreduce topology (reference enables it only on
  // homogeneous clusters — operations.cc:514-538)
  hier_topology_ok_ = cfg.local_size > 1 &&
                      cfg.size == cfg.local_size * cfg.cross_size;
  hier_enabled_ = cfg.hierarchical_allreduce && hier_topology_ok_;
  hier_ag_enabled_ = cfg.hierarchical_allgather && hier_topology_ok_;
  if (hier_topology_ok_) {
    local_group_.ranks.clear();
    for (int i = 0; i < cfg.local_size; ++i)
      local_group_.ranks.push_back(cfg.cross_rank * cfg.local_size + i);
    local_group_.my_index = cfg.local_rank;
    cross_group_.ranks.clear();
    for (int i = 0; i < cfg.cross_size; ++i)
      cross_group_.ranks.push_back(i * cfg.local_size);
    cross_group_.my_index = cfg.cross_rank;
  }
  shutdown_requested_ = false;
  loop_done_ = false;
  last_straggler_report_ = std::chrono::steady_clock::now();
  initialized_ = true;
  loop_ = std::thread([this] { Loop(); });
  HVD_LOG(Debug) << "background loop started"
                 << (hier_enabled_ ? " (hierarchical allreduce on)" : "");
  return Status::OK();
}

void Core::Shutdown(bool force) {
  if (!initialized_) return;
  HVD_LOG(Info) << "core shutdown requested" << (force ? " (forced)" : "");
  shutdown_requested_ = true;
  KickCycle();  // cast the shutdown vote without waiting out a cycle
  // Prefer the negotiated shutdown (all ranks vote, coordinator emits a
  // SHUTDOWN response — reference: operations.cc:994-1005); if a peer died
  // mid-collective the loop may be blocked in Recv, so force-close the
  // transport after a grace period to unblock it. force=true skips the
  // grace entirely — the caller KNOWS a peer is dead (elastic in-place
  // shrink), so consensus can never complete and waiting 10s per
  // survivor would just stall the re-rendezvous.
  for (int i = 0; !force && i < 100 && !loop_done_.load(); ++i)
    std::this_thread::sleep_for(std::chrono::milliseconds(100));
  if (!loop_done_.load() && transport_) transport_->Shutdown();
  if (loop_.joinable()) loop_.join();
  {
    std::lock_guard<std::mutex> lk(domains_mu_);
    for (auto& kv : domains_)
      kv.second->queue.FinalizeAllWithError(
          Status::Aborted("hvdcore shut down"));
  }
  if (timeline_) timeline_->Close();
  if (transport_) transport_->Shutdown();
  initialized_ = false;
}

// -- enqueue ----------------------------------------------------------------

int Core::EnqueueAllreduce(int domain, const std::string& name,
                           const void* in, void* out, DataType dt,
                           const std::vector<int64_t>& shape, ReduceOp op,
                           double prescale, double postscale,
                           int group_id, int group_size) {
  int h = NewHandle(nullptr);
  auto hs = GetHandle(h);
  TensorTableEntry e;
  e.name = name;
  e.type = Request::kAllreduce;
  e.input = in;
  e.output = out;
  e.dtype = dt;
  e.shape = shape;
  e.op = op;
  e.prescale = prescale;
  e.postscale = postscale;
  e.callback = [hs](const Status& s) {
    std::lock_guard<std::mutex> lk(hs->mu);
    hs->status = s;
    hs->done = true;
    hs->cv.notify_all();
  };
  Request r;
  r.type = Request::kAllreduce;
  r.rank = cfg_.rank;
  r.name = name;
  r.dtype = dt;
  r.shape = shape;
  r.op = op;
  r.prescale = prescale;
  r.postscale = postscale;
  r.group_id = group_id;
  r.group_size = group_size;
  PushToDomain(domain, std::move(e), std::move(r));
  return h;
}

int Core::EnqueueAllgather(int domain, const std::string& name,
                           const void* in, DataType dt,
                           const std::vector<int64_t>& shape) {
  int h = NewHandle(nullptr);
  auto hs = GetHandle(h);
  TensorTableEntry e;
  e.name = name;
  e.type = Request::kAllgather;
  e.input = in;
  e.dtype = dt;
  e.shape = shape;
  e.result = std::make_shared<std::vector<uint8_t>>();
  e.result_shape = std::make_shared<std::vector<int64_t>>();
  e.callback = [hs](const Status& s) {
    std::lock_guard<std::mutex> lk(hs->mu);
    hs->status = s;
    hs->done = true;
    hs->cv.notify_all();
  };
  // share the result buffers with the handle so Execute's writes are
  // visible through the handle-query API
  hs->entry = e;
  Request r;
  r.type = Request::kAllgather;
  r.rank = cfg_.rank;
  r.name = name;
  r.dtype = dt;
  r.shape = shape;
  PushToDomain(domain, std::move(e), std::move(r));
  return h;
}

int Core::EnqueueBroadcast(int domain, const std::string& name,
                           const void* in, void* out, int root, DataType dt,
                           const std::vector<int64_t>& shape) {
  int h = NewHandle(nullptr);
  auto hs = GetHandle(h);
  TensorTableEntry e;
  e.name = name;
  e.type = Request::kBroadcast;
  e.input = in;
  e.output = out;
  e.root_rank = root;
  e.dtype = dt;
  e.shape = shape;
  e.callback = [hs](const Status& s) {
    std::lock_guard<std::mutex> lk(hs->mu);
    hs->status = s;
    hs->done = true;
    hs->cv.notify_all();
  };
  Request r;
  r.type = Request::kBroadcast;
  r.rank = cfg_.rank;
  r.name = name;
  r.dtype = dt;
  r.shape = shape;
  r.root_rank = root;
  PushToDomain(domain, std::move(e), std::move(r));
  return h;
}

int Core::EnqueueAlltoall(int domain, const std::string& name,
                          const void* in, const std::vector<int64_t>& splits,
                          DataType dt, const std::vector<int64_t>& shape) {
  int h = NewHandle(nullptr);
  auto hs = GetHandle(h);
  TensorTableEntry e;
  e.name = name;
  e.type = Request::kAlltoall;
  e.input = in;
  e.dtype = dt;
  e.shape = shape;
  e.splits = splits;
  e.result = std::make_shared<std::vector<uint8_t>>();
  e.result_shape = std::make_shared<std::vector<int64_t>>();
  e.recv_splits = std::make_shared<std::vector<int64_t>>();
  e.callback = [hs](const Status& s) {
    std::lock_guard<std::mutex> lk(hs->mu);
    hs->status = s;
    hs->done = true;
    hs->cv.notify_all();
  };
  hs->entry = e;
  Request r;
  r.type = Request::kAlltoall;
  r.rank = cfg_.rank;
  r.name = name;
  r.dtype = dt;
  r.shape = shape;
  PushToDomain(domain, std::move(e), std::move(r));
  return h;
}

int Core::EnqueueJoin(int domain) {
  int h = NewHandle(nullptr);
  auto hs = GetHandle(h);
  TensorTableEntry e;
  e.name = "__join__";
  e.type = Request::kJoin;
  e.callback = [hs](const Status& s) {
    std::lock_guard<std::mutex> lk(hs->mu);
    hs->status = s;
    hs->done = true;
    hs->cv.notify_all();
  };
  Request r;
  r.type = Request::kJoin;
  r.rank = cfg_.rank;
  r.name = "__join__";
  PushToDomain(domain, std::move(e), std::move(r));
  return h;
}

Status Core::ExecBarrier(int domain) {
  int h = NewHandle(nullptr);
  auto hs = GetHandle(h);
  TensorTableEntry e;
  e.name = "__barrier__";
  e.type = Request::kBarrier;
  e.callback = [hs](const Status& s) {
    std::lock_guard<std::mutex> lk(hs->mu);
    hs->status = s;
    hs->done = true;
    hs->cv.notify_all();
  };
  Request r;
  r.type = Request::kBarrier;
  r.rank = cfg_.rank;
  r.name = "__barrier__";
  PushToDomain(domain, std::move(e), std::move(r));
  auto st = WaitHandle(h, 600.0);
  FreeHandle(h);
  return st;
}

// -- handles ----------------------------------------------------------------

bool Core::Poll(int h) {
  auto hs = GetHandle(h);
  if (!hs) return true;
  std::lock_guard<std::mutex> lk(hs->mu);
  return hs->done;
}

Status Core::WaitHandle(int h, double timeout_s) {
  auto hs = GetHandle(h);
  if (!hs) return Status::Error("unknown handle");
  std::unique_lock<std::mutex> lk(hs->mu);
  if (!hs->cv.wait_for(lk, std::chrono::duration<double>(timeout_s),
                       [&] { return hs->done; }))
    return Status{StatusType::kInProgress, "timeout waiting for collective"};
  return hs->status;
}

std::vector<int64_t> Core::ResultShape(int h) {
  auto hs = GetHandle(h);
  if (!hs || !hs->entry.result_shape) return {};
  std::lock_guard<std::mutex> lk(hs->mu);
  return *hs->entry.result_shape;
}

std::vector<int64_t> Core::RecvSplits(int h) {
  auto hs = GetHandle(h);
  if (!hs || !hs->entry.recv_splits) return {};
  std::lock_guard<std::mutex> lk(hs->mu);
  return *hs->entry.recv_splits;
}

Status Core::CopyResult(int h, void* dst, int64_t max_bytes) {
  auto hs = GetHandle(h);
  if (!hs) return Status::Error("unknown handle");
  std::lock_guard<std::mutex> lk(hs->mu);
  if (!hs->entry.result) return Status::Error("handle has no result buffer");
  int64_t n = std::min<int64_t>(max_bytes, hs->entry.result->size());
  memcpy(dst, hs->entry.result->data(), n);
  return Status::OK();
}

void Core::FreeHandle(int h) {
  std::lock_guard<std::mutex> lk(handles_mu_);
  handles_.erase(h);
}

// -- process sets -----------------------------------------------------------

int Core::AddProcessSet(const std::vector<int>& ranks) {
  std::lock_guard<std::mutex> lk(domains_mu_);
  int id = next_domain_++;
  auto d = std::unique_ptr<CoordDomain>(new CoordDomain());
  d->id = id;
  d->group.ranks = ranks;
  std::sort(d->group.ranks.begin(), d->group.ranks.end());
  auto it = std::find(d->group.ranks.begin(), d->group.ranks.end(),
                      cfg_.rank);
  d->group.my_index = it == d->group.ranks.end()
                          ? -1
                          : (int)(it - d->group.ranks.begin());
  d->cache.reset(new ResponseCache(cfg_.cache_capacity));
  d->joined_ranks.assign(d->group.ranks.size(), false);
  // Multi-process: the set stays INACTIVE (no lockstep negotiation rounds)
  // until the domain-0 coordinator confirms every rank registered it; a
  // member cycling a set its peers don't know yet would withhold its
  // domain-0 traffic and deadlock the whole mesh (reference coordinates
  // dynamic registration through the background thread the same way,
  // operations.cc:587-623). Submissions queue and run on activation.
  d->active = cfg_.size <= 1;
  d->registered_at = std::chrono::steady_clock::now();
  domains_[id] = std::move(d);
  return id;
}

void Core::RemoveProcessSet(int id) {
  std::lock_guard<std::mutex> lk(domains_mu_);
  if (id == 0) return;
  auto it = domains_.find(id);
  if (it == domains_.end()) return;
  if (cfg_.size <= 1) {
    domains_.erase(it);
    return;
  }
  // Multi-process: ALWAYS go through retire consensus — even for a
  // still-inactive set. Erasing an inactive set locally races the
  // activation broadcast (this rank may already have announced it; the
  // coordinator could activate it this very cycle, and peers would then
  // block on a member that no longer has the domain). Retiring stops the
  // announcements, so an inactive set simply never activates and is erased
  // everywhere once every rank votes.
  it->second->retiring = true;
}

int Core::last_join_rank(int domain) {
  std::lock_guard<std::mutex> lk(domains_mu_);
  auto it = domains_.find(domain);
  return it == domains_.end() ? -1 : it->second->join_count;
}

// -- dynamic timeline (reference: operations.cc:1011-1041) ------------------

Status Core::StartTimeline(const std::string& path, bool mark_cycles) {
  if (!initialized_ || !timeline_)
    return Status::Error("hvdcore not initialized");
  if (!timeline_->Start(path, mark_cycles))
    return Status::Error("could not open timeline file: " + path);
  return Status::OK();
}

Status Core::StopTimeline() {
  if (!initialized_ || !timeline_)
    return Status::Error("hvdcore not initialized");
  timeline_->Stop();
  return Status::OK();
}

// -- background loop (reference: BackgroundThreadLoop / RunLoopOnce) --------

void Core::Loop() {
  if (cfg_.thread_affinity >= 0) {
    // pin the background loop (reference: HOROVOD_THREAD_AFFINITY)
    cpu_set_t cpus;
    CPU_ZERO(&cpus);
    long ncpu = sysconf(_SC_NPROCESSORS_ONLN);
    CPU_SET(cfg_.thread_affinity % std::max(1L, ncpu), &cpus);
    pthread_setaffinity_np(pthread_self(), sizeof(cpus), &cpus);
  }
  while (RunOnce()) {
    // idle-poll at the (autotunable) cycle time, but wake immediately on
    // a fresh enqueue — a lone eager op should pay the negotiation RTT,
    // not the poll latency
    std::unique_lock<std::mutex> lk(cycle_mu_);
    cycle_cv_.wait_for(
        lk, std::chrono::duration<double, std::milli>(cfg_.cycle_time_ms),
        [this] { return cycle_kick_; });
    cycle_kick_ = false;
  }
  MirrorTransportCounters();
  loop_done_ = true;
  // Abnormal exits (peer death mid-collective) leave waiters pending —
  // finalize them with the real error instead of letting them time out
  // (reference: operations.cc finalizes the tensor queue at shutdown).
  std::string why = loop_error_.empty()
      ? "hvdcore background loop terminated (peer failure or shutdown)"
      : "hvdcore background loop terminated: " + loop_error_;
  if (!loop_error_.empty()) {
    HVD_LOG(Error) << "background loop exiting: " << loop_error_;
  }
  std::lock_guard<std::mutex> lk(domains_mu_);
  for (auto& kv : domains_)
    kv.second->queue.FinalizeAllWithError(Status::Aborted(why));
}

namespace {
// negotiation-phase names (reference: timeline.h NEGOTIATING state +
// activity taxonomy common.h:73-105)
const char* NegotiatePhase(Request::Type t) {
  switch (t) {
    case Request::kAllreduce: return "NEGOTIATE_ALLREDUCE";
    case Request::kAllgather: return "NEGOTIATE_ALLGATHER";
    case Request::kBroadcast: return "NEGOTIATE_BROADCAST";
    case Request::kAlltoall: return "NEGOTIATE_ALLTOALL";
    case Request::kBarrier: return "NEGOTIATE_BARRIER";
    default: return "NEGOTIATE";
  }
}
}  // namespace

void Core::HandleRequests(CoordDomain& d, int from_rank,
                          std::vector<Request>& reqs) {
  int gsize = d.group.size();
  for (auto& r : reqs) {
    if (r.type == Request::kJoin) {
      int idx = (int)(std::find(d.group.ranks.begin(), d.group.ranks.end(),
                                from_rank) -
                      d.group.ranks.begin());
      if (!d.joined_ranks[idx]) {
        d.joined_ranks[idx] = true;
        d.join_count = from_rank;  // last joiner (reference: join returns it)
      }
      continue;
    }
    // Keyed by NAME (reference: controller.cc IncrementTensorCount) —
    // allgather ranks legitimately differ in dim 0.
    auto& slot = d.ready_table_[r.name];
    if (slot.second.empty()) {
      slot.first = r;
      d.announce_time_[r.name] = std::chrono::steady_clock::now();
      // per-tensor negotiation phase opens at the FIRST announcement and
      // closes when all ranks are in (CollectReady) — the coordinator's
      // view of who is holding whom up (reference: timeline.h:48-183)
      if (timeline_ && timeline_->enabled())
        timeline_->Begin(r.name, NegotiatePhase(r.type));
    } else {
      // duplicate announcement from the same rank must not count twice
      if (std::find(slot.second.begin(), slot.second.end(), from_rank) !=
          slot.second.end())
        continue;
      // validate agreement (reference: ConstructResponse mismatch errors)
      const Request& first = slot.first;
      bool mismatch = first.dtype != r.dtype || first.type != r.type ||
                      (int)first.op != (int)r.op ||
                      first.group_id != r.group_id ||
                      first.group_size != r.group_size;
      if (!mismatch && r.type == Request::kAllreduce &&
          first.shape != r.shape)
        mismatch = true;
      if (!mismatch && r.type != Request::kAllreduce) {
        if (first.shape.size() != r.shape.size()) {
          mismatch = true;  // ndim must agree even when dim 0 is ragged
        } else {
          for (size_t k = 1; k < r.shape.size(); ++k)
            if (first.shape[k] != r.shape[k]) mismatch = true;
        }
      }
      if (mismatch)
        d.error_table_[r.name] =
            "mismatched dtype/shape/op for tensor '" + r.name + "'";
    }
    slot.second.push_back(from_rank);
  }
  (void)gsize;
}

void Core::HandleCacheBits(CoordDomain& d, int from_rank,
                           const std::vector<int32_t>& bits) {
  for (auto b : bits) {
    auto& ranks = d.bit_ready_[b];
    if (ranks.empty())
      d.bit_time_[b] = std::chrono::steady_clock::now();
    if (ranks.empty() && timeline_ && timeline_->enabled()) {
      // cached tensors skip negotiation; the wait for the remaining
      // ranks' bits is still visible (reference activity name:
      // WAIT_FOR_OTHER_TENSOR_DATA, common.h:76)
      const Response& cr = d.cache->Get(b);
      if (!cr.names.empty())
        timeline_->Begin(cr.names[0], "WAIT_FOR_OTHER_TENSOR_DATA");
    }
    ranks.push_back(from_rank);
  }
}

std::vector<Response> Core::CollectReady(CoordDomain& d) {
  // A tensor/bit is ready when every non-joined rank announced it
  // (reference: controller.cc IncrementTensorCount).
  int needed = 0;
  for (size_t i = 0; i < d.joined_ranks.size(); ++i)
    if (!d.joined_ranks[i]) needed++;
  auto now = std::chrono::steady_clock::now();
  // negotiation wait = first announce -> all in, charged to the LAST
  // announcing rank — the one everyone else waited on
  auto charge = [&](const std::vector<int>& ranks,
                    std::chrono::steady_clock::time_point first_seen) {
    if (ranks.empty()) return;
    ChargeStraggler(
        ranks.back(),
        std::chrono::duration<double>(now - first_seen).count());
  };

  std::vector<Response> out;
  // 1) steady-state fast path: common cache bits, ascending (identical
  //    caches on every rank → identical responses)
  std::vector<int> ready_bits;
  for (auto it = d.bit_ready_.begin(); it != d.bit_ready_.end();) {
    if ((int)it->second.size() >= needed && needed > 0) {
      ready_bits.push_back(it->first);
      auto ts = d.bit_time_.find(it->first);
      if (ts != d.bit_time_.end()) {
        charge(it->second, ts->second);
        d.bit_time_.erase(ts);
      }
      it = d.bit_ready_.erase(it);
    } else {
      ++it;
    }
  }
  std::sort(ready_bits.begin(), ready_bits.end());
  for (int b : ready_bits) {
    Response resp = d.cache->Get(b);
    resp.from_cache = true;
    if (!resp.names.empty()) {
      d.stall.RemoveReady(resp.names[0]);
      if (timeline_ && timeline_->enabled())
        timeline_->End(resp.names[0]);  // closes WAIT_FOR_OTHER_TENSOR_DATA
    }
    out.push_back(std::move(resp));
  }
  // partial cache bits are stalls too: without this, a cached tensor one
  // rank stops submitting would evade the stall inspector entirely
  for (auto& kv : d.bit_ready_) {
    const Response& r = d.cache->Get(kv.first);
    if (!r.names.empty())
      d.stall.RecordPending(r.names[0], kv.second, d.group.size());
  }

  // 2) negotiated tensors
  std::vector<std::pair<std::string, Request>> ready;
  for (auto it = d.ready_table_.begin(); it != d.ready_table_.end();) {
    if ((int)it->second.second.size() >= needed && needed > 0) {
      ready.emplace_back(it->first, it->second.first);
      d.stall.RemoveReady(it->second.first.name);
      auto ts = d.announce_time_.find(it->first);
      if (ts != d.announce_time_.end()) {
        charge(it->second.second, ts->second);
        d.announce_time_.erase(ts);
      }
      it = d.ready_table_.erase(it);
    } else {
      d.stall.RecordPending(it->second.first.name, it->second.second,
                            d.group.size());
      ++it;
    }
  }
  std::sort(ready.begin(), ready.end(),
            [](auto& a, auto& b) { return a.first < b.first; });
  for (auto& kv : ready) {
    auto& r = kv.second;
    if (timeline_ && timeline_->enabled())
      timeline_->End(r.name);  // closes the NEGOTIATE_* phase
    auto err = d.error_table_.find(r.name);
    bool poisoned = r.group_id >= 0 &&
                    d.poisoned_groups_.count(r.group_id) > 0;
    if (err != d.error_table_.end() || poisoned) {
      Response resp;
      resp.type = Response::kError;
      resp.names = {r.name};
      resp.error_message = err != d.error_table_.end()
                               ? err->second
                               : "another member of this tensor group "
                                 "failed";
      if (err != d.error_table_.end()) d.error_table_.erase(err);
      // error in a group: fail the held members too so no handle waits
      // forever
      if (r.group_id >= 0) {
        d.poisoned_groups_.insert(r.group_id);
        auto git = d.groups_.find(r.group_id);
        if (git != d.groups_.end()) {
          for (auto& held : git->second.second) {
            Response e2;
            e2.type = Response::kError;
            e2.names = held.names;
            e2.error_message = resp.error_message;
            out.push_back(std::move(e2));
          }
          d.groups_.erase(git);
        }
      }
      out.push_back(std::move(resp));
      continue;
    }
    Response resp;
    resp.type = (Response::Type)r.type;
    resp.names = {r.name};
    resp.dtypes = {r.dtype};
    resp.shapes = {r.shape};
    resp.root_rank = r.root_rank;
    resp.op = r.op;
    resp.prescale = r.prescale;
    resp.postscale = r.postscale;
    resp.group_id = r.group_id;
    resp.group_size = r.group_size;
    if (r.type == Request::kAllreduce && r.group_id >= 0) {
      // hold back until the whole group is ready (group-COMPLETE
      // negotiation; reference: GroupTable readiness,
      // controller.cc:207-231). Fusion still bounds unit sizes.
      auto& slot = d.groups_[r.group_id];
      if (slot.first == 0) slot.first = r.group_size;
      slot.second.push_back(std::move(resp));
      if ((int)slot.second.size() >= slot.first && slot.first > 0) {
        std::sort(slot.second.begin(), slot.second.end(),
                  [](const Response& a, const Response& b) {
                    return a.names[0] < b.names[0];
                  });
        for (auto& gr : slot.second) out.push_back(std::move(gr));
        d.groups_.erase(r.group_id);
        d.poisoned_groups_.erase(r.group_id);
      }
      continue;
    }
    out.push_back(std::move(resp));
  }

  // all ranks joined → emit Join response and reset
  bool all_joined =
      !d.joined_ranks.empty() &&
      std::all_of(d.joined_ranks.begin(), d.joined_ranks.end(),
                  [](bool b) { return b; });
  if (all_joined) {
    Response resp;
    resp.type = Response::kJoin;
    resp.last_joined_rank = d.join_count;
    out.push_back(resp);
    std::fill(d.joined_ranks.begin(), d.joined_ranks.end(), false);
  }
  return out;
}

std::vector<Response> Core::FuseResponses(
    const std::vector<Response>& singles) {
  std::vector<Response> out;
  std::map<std::string, Response> open;  // fuse-group key -> accumulating
  std::map<std::string, int64_t> open_bytes;
  for (auto& s : singles) {
    std::string key;
    if (s.type == Response::kAllreduce) {
      std::ostringstream gk;
      gk << "ar|" << (int)s.dtypes[0] << '|' << (int)s.op << '|'
         << s.prescale << '|' << s.postscale;
      if (cfg_.disable_group_fusion)
        gk << "|g" << s.group_id;  // keep groups (and loose tensors) apart
      key = gk.str();
    } else if (s.type == Response::kAllgather) {
      // fused allgathers share one size-exchange + one data round with
      // per-tensor displacement math (reference: controller.cc:793 fuses
      // allgathers; ops/collective_operations.h:209-273); embedding-heavy
      // steps gather many small tensors per cycle
      std::ostringstream gk;
      gk << "ag|" << (int)s.dtypes[0];
      if (cfg_.disable_group_fusion)
        gk << "|g" << s.group_id;  // keep groups (and loose tensors) apart
      key = gk.str();
    } else {
      out.push_back(s);
      continue;
    }
    int64_t sz = DataTypeSize(s.dtypes[0]);
    for (auto dim : s.shapes[0]) sz *= dim;
    auto it = open.find(key);
    if (it != open.end() &&
        open_bytes[key] + sz > cfg_.fusion_threshold) {
      out.push_back(std::move(it->second));
      open.erase(it);
      open_bytes.erase(key);
      it = open.end();
    }
    if (it == open.end()) {
      open[key] = s;
      open_bytes[key] = sz;
    } else {
      it->second.names.push_back(s.names[0]);
      it->second.dtypes.push_back(s.dtypes[0]);
      it->second.shapes.push_back(s.shapes[0]);
      open_bytes[key] += sz;
    }
  }
  for (auto& kv : open) out.push_back(std::move(kv.second));
  return out;
}

namespace {
hvd::Request RequestFromSingleResponse(const hvd::Response& r) {
  // must mirror the Request an announcing rank would send for this op
  hvd::Request q;
  q.type = hvd::Request::kAllreduce;
  q.name = r.names[0];
  q.dtype = r.dtypes[0];
  q.shape = r.shapes[0];
  q.op = r.op;
  q.prescale = r.prescale;
  q.postscale = r.postscale;
  q.root_rank = 0;
  q.group_id = r.group_id;
  q.group_size = r.group_size;
  return q;
}

std::string KeyFromSingleResponse(const hvd::Response& r) {
  // must match ResponseCache::Key(Request) for an allreduce request
  return hvd::ResponseCache::Key(RequestFromSingleResponse(r));
}
}  // namespace

namespace {
uint64_t HashRanks(const std::vector<int>& ranks) {
  uint64_t h = 1469598103934665603ull;  // FNV-1a
  for (int r : ranks) {
    h ^= (uint64_t)(uint32_t)r;
    h *= 1099511628211ull;
  }
  return h;
}
}  // namespace

void Core::ApplyDomainLifecycle(const std::vector<int32_t>& activate,
                                const std::vector<int32_t>& retired) {
  std::lock_guard<std::mutex> lk(domains_mu_);
  for (auto id : activate) {
    auto it = domains_.find(id);
    if (it != domains_.end()) it->second->active = true;
  }
  for (auto id : retired) {
    auto it = domains_.find(id);
    if (it != domains_.end()) {
      it->second->queue.FinalizeAllWithError(
          Status::Aborted("process set removed"));
      domains_.erase(it);
    }
  }
}

// Mirror the transport's chaos-injection and checksum-failure counts
// into the long-lived Counters struct: only the loop thread may touch
// transport_ (the metrics scraper reads counters_ concurrently with
// elastic re-init).  Deltas, not absolute stores — a checksum failure
// tears its transport down, and the replacement transport's 0 must not
// erase the recorded evidence (Init re-baselines seen_*).
void Core::MirrorTransportCounters() {
  if (!transport_) return;
  uint64_t chaos = transport_->chaos_injected();
  if (chaos > seen_transport_chaos_) {
    counters_.transport_chaos_injected.fetch_add(
        chaos - seen_transport_chaos_, std::memory_order_relaxed);
    seen_transport_chaos_ = chaos;
  }
  uint64_t ck = transport_->checksum_failures();
  if (ck > seen_transport_checksum_) {
    counters_.transport_checksum_failures.fetch_add(
        ck - seen_transport_checksum_, std::memory_order_relaxed);
    seen_transport_checksum_ = ck;
  }
}

bool Core::RunOnce() {
  MirrorTransportCounters();
  bool want_shutdown = shutdown_requested_.load();
  counters_.cycles++;
  if (timeline_ && timeline_->enabled() && timeline_->mark_cycles())
    timeline_->Instant("CYCLE_START");  // HOROVOD_TIMELINE_MARK_CYCLES

  std::vector<int> domain_ids;
  std::vector<wire::DomainAnnounce> my_announce;
  std::vector<int32_t> my_retire;
  {
    std::lock_guard<std::mutex> lk(domains_mu_);
    for (auto& kv : domains_) {
      domain_ids.push_back(kv.first);
      CoordDomain* cd = kv.second.get();
      if (cd->retiring) {
        my_retire.push_back(kv.first);
      } else if (!cd->active) {
        wire::DomainAnnounce a;
        a.id = kv.first;
        a.ranks_hash = HashRanks(cd->group.ranks);
        my_announce.push_back(a);
        if (!cd->inactive_warned && cd->queue.pending() > 0 &&
            std::chrono::duration<double>(std::chrono::steady_clock::now() -
                                          cd->registered_at)
                    .count() > cfg_.stall_warning_secs) {
          HVD_LOG(Warning)
              << "collectives pending on process set " << kv.first
              << " which not all ranks have registered after "
              << cfg_.stall_warning_secs << "s";
          cd->inactive_warned = true;
        }
      }
    }
  }

  bool got_shutdown_response = false;
  int cycle_stalled = 0;  // tensors past the warn threshold this cycle
  for (int id : domain_ids) {
    CoordDomain* d;
    {
      std::lock_guard<std::mutex> lk(domains_mu_);
      auto it = domains_.find(id);
      if (it == domains_.end()) continue;  // retired during this cycle
      d = it->second.get();
      // re-read under the lock: the domain-0 phase of THIS cycle may have
      // just activated it (every rank then activates in the same cycle, so
      // all members enter its first negotiate round together)
      if (!d->active) continue;
    }
    if (d->group.my_index < 0) continue;  // not a member

    // partition my requests: allreduce cache hits travel as bits (the
    // steady-state fast path, reference: response_cache.h CacheCoordinator);
    // everything else as full requests
    auto popped = d->queue.PopRequests();
    std::vector<Request> misses;
    std::vector<int32_t> my_bits;
    for (auto& r : popped) {
      if (r.type == Request::kAllreduce && cfg_.cache_enabled) {
        int bit = d->cache->Lookup(ResponseCache::Key(r));
        if (bit >= 0) {
          my_bits.push_back(bit);
          counters_.cache_hits++;
          continue;
        }
        counters_.cache_misses++;
      }
      misses.push_back(r);
    }

    int coord = d->group.global(0);
    bool is_coord = d->group.my_index == 0;

    std::vector<Response> singles;
    if (d->group.size() == 1) {
      HandleRequests(*d, cfg_.rank, misses);
      HandleCacheBits(*d, cfg_.rank, my_bits);
      singles = CollectReady(*d);
      if (want_shutdown && id == 0) got_shutdown_response = true;
      if (id == 0 && has_pending_knobs_) {  // no peers to synchronize with
        ApplyKnobFlags(pending_knob_flags_);
        has_pending_knobs_ = false;
      }
    } else if (is_coord) {
      // gather (lockstep cycle; reference: MPIController::RecvReadyTensors)
      HandleRequests(*d, cfg_.rank, misses);
      HandleCacheBits(*d, cfg_.rank, my_bits);
      auto note_announce = [&](int from,
                               const std::vector<wire::DomainAnnounce>& as) {
        for (auto& a : as) {
          auto& c = announce_table_[a.id];
          if (c.ranks.empty()) c.ranks_hash = a.ranks_hash;
          if (c.ranks_hash != a.ranks_hash && !c.mismatch_warned) {
            HVD_LOG(Error)
                << "ranks disagree on the member list of process set "
                << a.id << "; the set will never activate";
            c.mismatch_warned = true;
          }
          c.ranks.insert(from);
        }
      };
      auto note_retire = [&](int from, const std::vector<int32_t>& rs) {
        for (auto r : rs) retire_table_[r].insert(from);
      };
      if (id == 0) {
        note_announce(cfg_.rank, my_announce);
        note_retire(cfg_.rank, my_retire);
      }
      int shutdown_votes = want_shutdown ? 1 : 0;
      for (int i = 1; i < d->group.size(); ++i) {
        std::vector<uint8_t> buf;
        auto st = transport_->Recv(d->group.global(i),
                                   DomTag(id, kTagNegotiate), &buf);
        if (!st.ok()) { loop_error_ = st.reason; return false; }
        bool sd;
        std::vector<int32_t> bits;
        std::vector<wire::DomainAnnounce> ann;
        std::vector<int32_t> ret;
        auto rl = wire::DecodeRequestList(buf.data(), buf.size(), &sd, &bits,
                                          &ann, &ret);
        if (sd) shutdown_votes++;
        if (id == 0) {
          note_announce(d->group.global(i), ann);
          note_retire(d->group.global(i), ret);
        }
        HandleRequests(*d, d->group.global(i), rl);
        HandleCacheBits(*d, d->group.global(i), bits);
      }
      // registration/retire consensus (domain 0 only): a set goes live —
      // on every rank in THIS cycle — once all ranks announced it
      std::vector<int32_t> activate, retired;
      if (id == 0) {
        for (auto it = announce_table_.begin();
             it != announce_table_.end();) {
          if (!it->second.mismatch_warned &&
              (int)it->second.ranks.size() >= cfg_.size) {
            activate.push_back(it->first);
            it = announce_table_.erase(it);
          } else {
            ++it;
          }
        }
        for (auto it = retire_table_.begin(); it != retire_table_.end();) {
          if ((int)it->second.size() >= cfg_.size) {
            retired.push_back(it->first);
            announce_table_.erase(it->first);  // drop a half-done activation
            it = retire_table_.erase(it);
          } else {
            ++it;
          }
        }
      }
      singles = CollectReady(*d);
      // fatally stalled tensors (some ranks never submitted) error out to
      // their waiters instead of hanging forever (reference:
      // HOROVOD_STALL_SHUTDOWN_TIME_SECONDS; surfaced here as a per-tensor
      // HorovodInternalError so elastic recovery can engage)
      for (auto& name : d->stall.FatallyStalled(cfg_.stall_shutdown_secs)) {
        int group_id = -1;
        auto rit = d->ready_table_.find(name);
        if (rit != d->ready_table_.end()) {
          group_id = rit->second.first.group_id;
          d->ready_table_.erase(rit);
        }
        d->announce_time_.erase(name);
        // the stalled submission may be a partial CACHE BIT
        for (auto it2 = d->bit_ready_.begin();
             it2 != d->bit_ready_.end();) {
          const Response& cr = d->cache->Get(it2->first);
          if (!cr.names.empty() && cr.names[0] == name) {
            group_id = cr.group_id;
            d->bit_time_.erase(it2->first);
            it2 = d->bit_ready_.erase(it2);
          } else {
            ++it2;
          }
        }
        d->stall.RemoveReady(name);
        HVD_LOG(Error) << "tensor '" << name << "' fatally stalled ("
                       << cfg_.stall_shutdown_secs
                       << "s); erroring its waiters";
        if (timeline_ && timeline_->enabled())
          timeline_->End(name);  // close the open NEGOTIATE_*/WAIT_* span
        Response e;
        e.type = Response::kError;
        e.names = {name};
        e.error_message =
            "tensor '" + name + "' stalled beyond "
            "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS (" +
            std::to_string((int)cfg_.stall_shutdown_secs) +
            "s): one or more ranks never submitted it";
        // a stalled GROUP member must fail its held siblings too (same
        // contract as the negotiated-error path: no handle waits forever)
        if (group_id >= 0) {
          d->poisoned_groups_.insert(group_id);
          auto git = d->groups_.find(group_id);
          if (git != d->groups_.end()) {
            for (auto& held : git->second.second) {
              Response e2;
              e2.type = Response::kError;
              e2.names = held.names;
              e2.error_message = e.error_message;
              singles.push_back(std::move(e2));
            }
            d->groups_.erase(git);
          }
        }
        singles.push_back(std::move(e));
      }
      if (id == 0 && shutdown_votes == d->group.size()) {
        Response sd;
        sd.type = Response::kShutdown;
        singles.push_back(sd);
      }
      uint8_t knobs = (id == 0 && has_pending_knobs_)
                          ? pending_knob_flags_ : KnobFlags();
      auto payload = wire::EncodeResponseList(singles, cfg_.fusion_threshold,
                                              activate, retired, knobs);
      for (int i = 1; i < d->group.size(); ++i) {
        auto st = transport_->Send(d->group.global(i),
                                   DomTag(id, kTagResponse), payload.data(),
                                   payload.size());
        if (!st.ok()) { loop_error_ = st.reason; return false; }
      }
      if (id == 0) ApplyDomainLifecycle(activate, retired);
      if (id == 0 && has_pending_knobs_) {
        // apply to ourselves only now that the packet carrying the flags
        // to every worker is on the wire: the whole world flips at this
        // cycle boundary (workers apply at the matching receive)
        ApplyKnobFlags(pending_knob_flags_);
        has_pending_knobs_ = false;
      }
      // stall check (reference: controller.cc:132-143); counts feed the
      // hvd_stall_warnings_total counter and stalled-tensor gauge on
      // /metrics (docs/OBSERVABILITY.md)
      int newly_warned = 0, stalled_now = 0;
      auto warn = d->stall.Check(cfg_.stall_warning_secs, &newly_warned,
                                 &stalled_now);
      if (newly_warned > 0) counters_.stall_warnings += newly_warned;
      cycle_stalled += stalled_now;
      if (!warn.empty()) {
        HVD_LOG(Warning) << "STALL:\n" << warn;
      }
    } else {
      auto payload = wire::EncodeRequestList(
          misses, want_shutdown, my_bits,
          id == 0 ? my_announce : std::vector<wire::DomainAnnounce>{},
          id == 0 ? my_retire : std::vector<int32_t>{});
      auto st = transport_->Send(coord, DomTag(id, kTagNegotiate),
                                 payload.data(), payload.size());
      if (!st.ok()) { loop_error_ = st.reason; return false; }
      std::vector<uint8_t> buf;
      st = transport_->Recv(coord, DomTag(id, kTagResponse), &buf);
      if (!st.ok()) { loop_error_ = st.reason; return false; }
      int64_t coord_threshold = cfg_.fusion_threshold;
      std::vector<int32_t> activate, retired;
      uint8_t knobs = KnobFlags();
      singles = wire::DecodeResponseList(buf.data(), buf.size(),
                                         &coord_threshold, &activate,
                                         &retired, &knobs);
      if (id == 0) ApplyDomainLifecycle(activate, retired);
      // adopt the coordinator's threshold so FuseResponses groups
      // identically on every rank (autotune is coordinator-only), and its
      // categorical knobs at the same cycle boundary the coordinator
      // applied them (the packet that carries them)
      cfg_.fusion_threshold = coord_threshold;
      if (id == 0) ApplyKnobFlags(knobs);
    }

    // every rank inserts newly negotiated allreduce responses in identical
    // (broadcast) order — and Touches cached ones in the same order — so
    // cache bit spaces AND LRU recency stay aligned across ranks
    if (cfg_.cache_enabled) {
      for (auto& s : singles) {
        if (s.type != Response::kAllreduce) continue;
        if (s.from_cache) {
          int bit = d->cache->Lookup(KeyFromSingleResponse(s));
          if (bit >= 0) d->cache->Touch(bit);
          continue;
        }
        Response evicted;
        bool did_evict = false;
        int bit = d->cache->Insert(KeyFromSingleResponse(s), s, &evicted,
                                   &did_evict);
        if (!did_evict) continue;
        counters_.cache_evictions++;
        // Coordinator: a pending (partial) bit announcement for the
        // evicted entry can no longer complete as a bit — the bit now
        // names the new entry, and ranks that miss post-eviction will
        // announce full requests. Migrate the announced ranks into
        // full-request negotiation so the tensor still completes.
        // (Reference coordinates this with explicit invalid-bit sync,
        // response_cache.h:135-139; deterministic eviction lets us
        // migrate locally instead.) Workers have no bit_ready_ state.
        auto bit_it = d->bit_ready_.find(bit);
        if (bit_it == d->bit_ready_.end() || evicted.names.empty())
          continue;
        Request q = RequestFromSingleResponse(evicted);
        auto& slot = d->ready_table_[q.name];
        auto bt = d->bit_time_.find(bit);
        // keep the straggler clock running across the bit->request
        // migration: the wait started at the EARLIEST announcement on
        // either path, and bit ranks that announced before the full
        // request must stay ahead of it in slot order — charge() blames
        // ranks.back(), so appending early announcers last would pin the
        // wait on the wrong rank
        bool bits_first = false;
        if (slot.second.empty()) {
          slot.first = q;
          d->announce_time_[q.name] =
              bt != d->bit_time_.end() ? bt->second
                                       : std::chrono::steady_clock::now();
        } else if (bt != d->bit_time_.end()) {
          auto at = d->announce_time_.find(q.name);
          if (at == d->announce_time_.end() || bt->second < at->second) {
            d->announce_time_[q.name] = bt->second;
            bits_first = true;
          }
        }
        d->bit_time_.erase(bit);
        size_t pos = 0;
        for (int rk : bit_it->second)
          if (std::find(slot.second.begin(), slot.second.end(), rk) ==
              slot.second.end()) {
            if (bits_first)
              slot.second.insert(slot.second.begin() + pos++, rk);
            else
              slot.second.push_back(rk);
          }
        d->bit_ready_.erase(bit_it);
      }
    }

    auto units = FuseResponses(singles);
    for (auto& resp : units) {
      if (resp.names.size() > 1) {
        counters_.fused_units++;
        counters_.tensors_fused += resp.names.size();
      }
    }
    for (auto& resp : units) {
      if (resp.type == Response::kShutdown) {
        got_shutdown_response = true;
        continue;
      }
      Execute(*d, resp);
    }
  }

  if (got_shutdown_response) return false;

  // autotune (reference: RunLoopOnce -> ParameterManager). Coordinator
  // only: workers adopt the tuned fusion threshold from the response list,
  // keeping fusion grouping identical across ranks.
  if (cfg_.rank == 0) {
    int64_t fusion = cfg_.fusion_threshold;
    double cycle = cfg_.cycle_time_ms;
    bool hier = hier_enabled_;
    bool cache = cfg_.cache_enabled;
    if (param_mgr_.Tune(&fusion, &cycle, &hier, &cache)) {
      cfg_.fusion_threshold = fusion;
      cfg_.cycle_time_ms = cycle;
      // categorical knobs must flip on every rank at the same cycle
      // boundary: stage them for the next domain-0 response broadcast
      // instead of applying locally now (see pending_knob_flags_)
      pending_knob_flags_ = (uint8_t)((hier ? 0x1 : 0) | (cache ? 0x2 : 0));
      has_pending_knobs_ = true;
    }
  }
  counters_.stalled_tensors.store(cycle_stalled);
  // mirror the (possibly autotuned) knob values for the metrics scrape
  // thread — every rank, every cycle: workers adopt tuned values via the
  // response fusion threshold + knob flags, so their mirrors track too
  counters_.autotune_fusion_bytes.store(cfg_.fusion_threshold);
  counters_.autotune_cycle_us.store(
      (uint64_t)(cfg_.cycle_time_ms * 1000.0));
  counters_.autotune_hierarchical.store(hier_enabled_ ? 1 : 0);
  counters_.autotune_cache_enabled.store(cfg_.cache_enabled ? 1 : 0);
  // periodic rank-attributed negotiation-wait summary (coordinator only
  // accumulates attribution; HVD_TPU_STRAGGLER_REPORT_SECONDS)
  if (cfg_.rank == 0) MaybeReportStragglers();
  PublishEngineState();
  return true;
}

// Serialize per-domain negotiation state into the published snapshot
// (<=2 Hz; EngineStateJson readers get the latest copy). Runs on the
// loop thread, the only mutator of domain internals.
void Core::PublishEngineState() {
  auto now = std::chrono::steady_clock::now();
  if (std::chrono::duration<double>(now - last_state_pub_).count() < 0.5)
    return;
  last_state_pub_ = now;
  std::ostringstream os;
  os << "{\"rank\":" << cfg_.rank << ",\"size\":" << cfg_.size
     << ",\"coordinator\":" << (cfg_.rank == 0 ? "true" : "false")
     << ",\"domains\":[";
  bool first_d = true;
  {
    std::lock_guard<std::mutex> lk(domains_mu_);
    for (auto& kv : domains_) {
      CoordDomain* d = kv.second.get();
      if (!first_d) os << ",";
      first_d = false;
      os << "{\"id\":" << kv.first << ",\"active\":"
         << (d->active ? "true" : "false")
         << ",\"queue_pending\":" << d->queue.pending()
         << ",\"joined_count\":" << d->join_count << ",\"pending\":[";
      bool first_p = true;
      for (auto& p : d->stall.Pending()) {
        if (!first_p) os << ",";
        first_p = false;
        os << "{\"name\":\"" << JsonEscape(p.name) << "\",\"waited_s\":"
           << p.waited_s << ",\"ready_ranks\":[";
        for (size_t i = 0; i < p.ready_ranks.size(); ++i)
          os << (i ? "," : "") << p.ready_ranks[i];
        os << "],\"missing_ranks\":[";
        // missing = domain members that have not announced this tensor
        bool first_m = true;
        for (int r : d->group.ranks) {
          if (std::find(p.ready_ranks.begin(), p.ready_ranks.end(), r) !=
              p.ready_ranks.end())
            continue;
          os << (first_m ? "" : ",") << r;
          first_m = false;
        }
        os << "]}";
      }
      os << "]}";
    }
  }
  os << "]}";
  std::lock_guard<std::mutex> lk(engine_state_mu_);
  engine_state_json_ = os.str();
}

std::string Core::EngineStateJson() const {
  std::lock_guard<std::mutex> lk(engine_state_mu_);
  return engine_state_json_;
}

bool Core::TimelineEnabled() const {
  return timeline_ && timeline_->enabled();
}

void Core::TimelineMark(const std::string& name, const std::string& span) {
  if (timeline_) timeline_->MarkSpan(name, span);
}

// -- straggler attribution --------------------------------------------------

void Core::ChargeStraggler(int last_rank, double waited) {
  if (waited < 0) waited = 0;
  std::lock_guard<std::mutex> lk(straggler_mu_);
  auto& pr = stragglers_.ranks[last_rank];
  pr.wait_seconds += waited;
  pr.held_count++;
  stragglers_.tensors_timed++;
  stragglers_.total_wait_seconds += waited;
}

void Core::MaybeReportStragglers() {
  if (cfg_.straggler_report_secs <= 0) return;
  auto now = std::chrono::steady_clock::now();
  if (std::chrono::duration<double>(now - last_straggler_report_).count() <
      cfg_.straggler_report_secs)
    return;
  last_straggler_report_ = now;
  std::ostringstream os;
  uint64_t timed = 0;
  {
    std::lock_guard<std::mutex> lk(straggler_mu_);
    timed = stragglers_.tensors_timed;
    for (auto& kv : stragglers_.ranks) {
      if (kv.second.held_count == 0) continue;
      os << " rank " << kv.first << ": last-in for "
         << kv.second.held_count << " tensors, peers waited "
         << kv.second.wait_seconds << "s total;";
    }
  }
  if (timed > 0) {
    HVD_LOG(Info) << "straggler report (" << timed
                  << " tensors timed since init):" << os.str();
  }
}

std::string Core::StragglersJson() const {
  std::ostringstream os;
  std::lock_guard<std::mutex> lk(straggler_mu_);
  os << "{\"tensors_timed\":" << stragglers_.tensors_timed
     << ",\"total_wait_seconds\":" << stragglers_.total_wait_seconds
     << ",\"ranks\":{";
  bool first = true;
  for (auto& kv : stragglers_.ranks) {
    if (!first) os << ',';
    first = false;
    os << '"' << kv.first << "\":{\"wait_seconds\":"
       << kv.second.wait_seconds << ",\"held_count\":"
       << kv.second.held_count << '}';
  }
  os << "}}";
  return os.str();
}

uint8_t Core::KnobFlags() const {
  return (uint8_t)((hier_enabled_ ? 0x1 : 0) |
                   (cfg_.cache_enabled ? 0x2 : 0));
}

void Core::ApplyKnobFlags(uint8_t flags) {
  bool hier = (flags & 0x1) != 0;
  bool cache = (flags & 0x2) != 0;
  if (hier != hier_enabled_ || cache != cfg_.cache_enabled) {
    HVD_LOG(Debug) << "autotune knob flip: hierarchical="
                   << (hier ? 1 : 0) << " cache=" << (cache ? 1 : 0);
  }
  // only honor hier when this rank's topology supports the two-level
  // path (identical on every rank: the coordinator proposes it only when
  // its own — identical — topology config allows)
  hier_enabled_ = hier && hier_topology_ok_;
  cfg_.cache_enabled = cache;
}

// -- execution (reference: PerformOperation, operations.cc:257-306) ---------

void Core::Execute(CoordDomain& d, const Response& r) {
  int id = d.id;
  int32_t dtag = DomTag(id, kTagData);
  counters_.responses_executed++;
  // sub-activity markers nested under EXECUTE on the unit's tid
  // (reference activity taxonomy: MEMCPY_IN_FUSION_BUFFER /
  // MEMCPY_OUT_FUSION_BUFFER / <op> — common.h:73-105)
  bool tl = timeline_ && timeline_->enabled() && !r.names.empty();
  auto act_begin = [&](const char* a) {
    if (tl) timeline_->Begin(r.names[0], a);
  };
  auto act_end = [&] {
    if (tl) timeline_->End(r.names[0]);
  };
  if (tl) timeline_->Begin(r.names[0], "EXECUTE");

  switch (r.type) {
    case Response::kAllreduce: {
      // gather entries; joined ranks contribute zeros
      struct Slot {
        TensorTableEntry e;
        bool have;
        size_t off;
        int64_t bytes;
      };
      std::vector<Slot> slots(r.names.size());
      size_t total = 0;
      for (size_t i = 0; i < r.names.size(); ++i) {
        slots[i].have = d.queue.Take(r.names[i], &slots[i].e);
        int64_t n = DataTypeSize(r.dtypes[i]);
        for (auto dim : r.shapes[i]) n *= dim;
        slots[i].bytes = n;
        slots[i].off = total;
        total += AlignUp(n);
      }
      act_begin("MEMCPY_IN_FUSION_BUFFER");
      std::vector<uint8_t> fusion(total, 0);
      for (auto& s : slots)
        if (s.have)
          memcpy(fusion.data() + s.off, s.e.input, s.bytes);
      act_end();
      int64_t nelem = 0;
      // element count: all same dtype; compute from bytes
      size_t esz = DataTypeSize(r.dtypes[0]);
      nelem = total / esz;
      Status st;
      if (hier_enabled_ && d.id == 0 && d.group.size() > 1 &&
          r.op != ReduceOp::kAdasum) {
        // two-level path: intra-host reduce -> cross-host ring among
        // leaders -> intra-host broadcast
        act_begin("HIERARCHICAL_ALLREDUCE");
        st = HierarchicalAllreduce(*transport_, local_group_, cross_group_,
                                   cfg_.local_rank == 0, dtag,
                                   fusion.data(), nelem, r.dtypes[0], r.op,
                                   r.prescale, r.postscale);
        // counter documents that the path RAN successfully (matches the
        // hier_allgathers guard) — do not count failed attempts
        if (st.ok()) counters_.hier_allreduces++;
        act_end();
      } else if (r.op == ReduceOp::kAdasum && d.group.size() > 1) {
        act_begin("ADASUM_ALLREDUCE");
        ScaleBufferOp(fusion.data(), nelem, r.dtypes[0], r.prescale);
        st = AdasumAllreduce(*transport_, d.group, DomTag(d.id, kTagAdasum),
                             fusion.data(), nelem, r.dtypes[0]);
        ScaleBufferOp(fusion.data(), nelem, r.dtypes[0], r.postscale);
        act_end();
      } else {
        act_begin("RING_ALLREDUCE");
        st = RingAllreduce(*transport_, d.group, dtag, fusion.data(),
                           nelem, r.dtypes[0], r.op, r.prescale,
                           r.postscale);
        act_end();
      }
      param_mgr_.Record(total);
      counters_.bytes_allreduced += (uint64_t)total;
      act_begin("MEMCPY_OUT_FUSION_BUFFER");
      for (auto& s : slots) {
        if (!s.have) continue;
        if (st.ok() && s.e.output)
          memcpy(s.e.output, fusion.data() + s.off, s.bytes);
        if (s.e.callback) s.e.callback(st);
      }
      act_end();
      break;
    }
    case Response::kAllgather: {
      size_t k = r.names.size();
      struct AgSlot {
        TensorTableEntry e;
        bool have;
        int64_t row_bytes;
        int64_t my_bytes;
      };
      std::vector<AgSlot> slots(k);
      for (size_t i = 0; i < k; ++i) {
        slots[i].have = d.queue.Take(r.names[i], &slots[i].e);
        int64_t rb = DataTypeSize(r.dtypes[i]);
        for (size_t j = 1; j < r.shapes[i].size(); ++j)
          rb *= r.shapes[i][j];
        slots[i].row_bytes = std::max<int64_t>(rb, 1);
        slots[i].my_bytes =
            slots[i].have ? (int64_t)slots[i].e.ByteSize() : 0;
      }
      // two-level node-leader path (reference: MPIHierarchicalAllgather,
      // mpi_operations.cc) — global domain only: sub-sets have no
      // topology contract
      bool hier_ag = hier_ag_enabled_ && d.id == 0 && d.group.size() > 1;
      auto allgatherv = [&](const void* send, int64_t send_bytes,
                            std::vector<int64_t>* sizes,
                            std::vector<uint8_t>* out) {
        if (hier_ag)
          return HierarchicalAllgatherV(
              *transport_, local_group_, cross_group_,
              cfg_.local_rank == 0, dtag, send, send_bytes, sizes, out);
        return AllgatherV(*transport_, d.group, dtag, send, send_bytes,
                          sizes, out);
      };
      if (k == 1) {
        // single-tensor fast path: one round; per-rank sizes come back
        // from AllgatherV itself
        auto& s0 = slots[0];
        std::vector<int64_t> sizes;
        std::vector<uint8_t> out;
        static const uint8_t kEmpty = 0;
        act_begin(hier_ag ? "HIERARCHICAL_ALLGATHER" : "ALLGATHERV");
        auto st = allgatherv(
            s0.have && s0.e.input ? s0.e.input : &kEmpty,
            s0.my_bytes, &sizes, &out);
        act_end();
        if (hier_ag && st.ok()) counters_.hier_allgathers++;
        counters_.bytes_allgathered += (uint64_t)out.size();
        if (s0.have) {
          if (st.ok()) {
            *s0.e.result = std::move(out);
            int64_t rows = (int64_t)s0.e.result->size() / s0.row_bytes;
            *s0.e.result_shape = r.shapes[0];
            if (!s0.e.result_shape->empty())
              (*s0.e.result_shape)[0] = rows;
          }
          if (s0.e.callback) s0.e.callback(st);
        }
        break;
      }
      // Fused path (reference: fused allgather displacement math,
      // ops/collective_operations.h:209-273): (1) one fixed-size round
      // exchanging the k per-tensor byte counts of every rank, (2) one
      // data round gathering each rank's concatenated tensors, (3)
      // scatter rank-major slices into per-tensor results. 2 rounds
      // total instead of k.
      int n = d.group.size();
      std::vector<int64_t> my_sizes(k);
      int64_t send_total = 0;
      for (size_t i = 0; i < k; ++i) {
        my_sizes[i] = slots[i].my_bytes;
        send_total += my_sizes[i];
      }
      std::vector<int64_t> size_per_rank;
      std::vector<uint8_t> size_out;
      act_begin("ALLGATHER_SIZES");
      auto st = allgatherv(my_sizes.data(),
                           (int64_t)(k * sizeof(int64_t)), &size_per_rank,
                           &size_out);
      act_end();
      if (st.ok() && size_out.size() != k * sizeof(int64_t) * (size_t)n)
        st = Status::Error("fused allgather size exchange mismatch");
      std::vector<uint8_t> data;
      std::vector<int64_t> rank_off;
      const int64_t* all_sizes = nullptr;  // [n][k] row-major
      if (st.ok()) {
        all_sizes = (const int64_t*)size_out.data();
        act_begin("MEMCPY_IN_FUSION_BUFFER");
        std::vector<uint8_t> send((size_t)send_total);
        int64_t off = 0;
        for (size_t i = 0; i < k; ++i) {
          if (slots[i].have && slots[i].e.input && my_sizes[i] > 0)
            memcpy(send.data() + off, slots[i].e.input, my_sizes[i]);
          off += my_sizes[i];
        }
        act_end();
        std::vector<int64_t> per_rank;
        static const uint8_t kEmptyF = 0;
        act_begin(hier_ag ? "HIERARCHICAL_ALLGATHER" : "ALLGATHERV");
        st = allgatherv(send_total ? send.data() : &kEmptyF, send_total,
                        &per_rank, &data);
        act_end();
        if (st.ok()) {
          rank_off.assign(n + 1, 0);
          for (int rr = 0; rr < n; ++rr)
            rank_off[rr + 1] = rank_off[rr] + per_rank[rr];
          counters_.bytes_allgathered += (uint64_t)data.size();
          if (hier_ag) counters_.hier_allgathers++;  // once per collective
        }
      }
      act_begin("MEMCPY_OUT_FUSION_BUFFER");
      for (size_t i = 0; i < k; ++i) {
        auto& s = slots[i];
        if (!s.have) continue;
        if (st.ok()) {
          int64_t total_i = 0;
          for (int rr = 0; rr < n; ++rr)
            total_i += all_sizes[(size_t)rr * k + i];
          s.e.result->resize((size_t)total_i);
          int64_t dst = 0;
          for (int rr = 0; rr < n; ++rr) {
            // rank rr's block holds its tensors in announce order;
            // tensor i sits after rr's tensors 0..i-1
            int64_t src = rank_off[rr];
            for (size_t j = 0; j < i; ++j)
              src += all_sizes[(size_t)rr * k + j];
            int64_t len = all_sizes[(size_t)rr * k + i];
            if (len > 0)
              memcpy(s.e.result->data() + dst, data.data() + src, len);
            dst += len;
          }
          *s.e.result_shape = r.shapes[i];
          if (!s.e.result_shape->empty())
            (*s.e.result_shape)[0] = total_i / s.row_bytes;
        }
        if (s.e.callback) s.e.callback(st);
      }
      act_end();
      break;
    }
    case Response::kBroadcast: {
      TensorTableEntry e;
      bool have = d.queue.Take(r.names[0], &e);
      int64_t nbytes = DataTypeSize(r.dtypes[0]);
      for (auto dim : r.shapes[0]) nbytes *= dim;
      std::vector<uint8_t> scratch;
      void* buf;
      if (have) {
        if (d.group.global(d.group.my_index) == r.root_rank)
          memcpy(e.output, e.input, nbytes);
        buf = e.output;
      } else {
        scratch.resize(nbytes);
        buf = scratch.data();
      }
      int root_index =
          (int)(std::find(d.group.ranks.begin(), d.group.ranks.end(),
                          r.root_rank) -
                d.group.ranks.begin());
      auto st = Broadcast(*transport_, d.group, dtag, buf, nbytes,
                          root_index);
      if (have && e.callback) e.callback(st);
      break;
    }
    case Response::kAlltoall: {
      TensorTableEntry e;
      bool have = d.queue.Take(r.names[0], &e);
      int64_t row_bytes = DataTypeSize(r.dtypes[0]);
      auto shape = r.shapes[0];
      for (size_t i = 1; i < shape.size(); ++i) row_bytes *= shape[i];
      std::vector<int64_t> splits =
          have ? e.splits : std::vector<int64_t>(d.group.size(), 0);
      std::vector<int64_t> recv_splits;
      std::vector<uint8_t> out;
      static const uint8_t kEmpty2 = 0;
      auto st = AlltoallV(*transport_, d.group, dtag,
                          have && e.input ? e.input : &kEmpty2, splits,
                          row_bytes, &recv_splits, &out);
      if (have) {
        if (st.ok()) {
          *e.result = std::move(out);
          *e.recv_splits = recv_splits;
          int64_t rows = 0;
          for (auto s : recv_splits) rows += s;
          *e.result_shape = shape;
          if (!e.result_shape->empty()) (*e.result_shape)[0] = rows;
        }
        if (e.callback) e.callback(st);
      }
      break;
    }
    case Response::kBarrier: {
      TensorTableEntry e;
      bool have = d.queue.Take(r.names[0], &e);
      auto st = Barrier(*transport_, d.group, DomTag(id, kTagBarrier));
      if (have && e.callback) e.callback(st);
      break;
    }
    case Response::kError: {
      TensorTableEntry e;
      if (d.queue.Take(r.names[0], &e) && e.callback)
        e.callback(Status::Error(r.error_message));
      break;
    }
    case Response::kJoin: {
      TensorTableEntry e;
      bool have = d.queue.Take("__join__", &e);
      d.joined = false;
      d.join_count = r.last_joined_rank;
      if (have && e.callback) e.callback(Status::OK());
      break;
    }
    default:
      break;
  }
  if (tl) timeline_->End(r.names[0]);  // closes EXECUTE
}

}  // namespace hvd
