#include "timeline.h"

namespace hvd {

Timeline::Timeline(int rank, const std::string& path) : rank_(rank) {
  t0_ = std::chrono::steady_clock::now();
  if (path.empty() || rank != 0) return;  // coordinator-only file
  file_ = fopen(path.c_str(), "w");
  if (!file_) return;
  fputs("[\n", file_);
  writer_ = std::thread([this] { WriterLoop(); });
}

Timeline::~Timeline() { Close(); }

double Timeline::Now() {
  return std::chrono::duration<double, std::micro>(
             std::chrono::steady_clock::now() - t0_)
      .count();
}

void Timeline::Begin(const std::string& tid, const std::string& name) {
  if (!file_) return;
  std::lock_guard<std::mutex> lk(mu_);
  q_.push({'B', tid, name, Now()});
  cv_.notify_one();
}

void Timeline::End(const std::string& tid) {
  if (!file_) return;
  std::lock_guard<std::mutex> lk(mu_);
  q_.push({'E', tid, "", Now()});
  cv_.notify_one();
}

void Timeline::Instant(const std::string& name) {
  if (!file_) return;
  std::lock_guard<std::mutex> lk(mu_);
  q_.push({'i', "marker", name, Now()});
  cv_.notify_one();
}

void Timeline::WriterLoop() {
  for (;;) {
    Event ev;
    {
      std::unique_lock<std::mutex> lk(mu_);
      cv_.wait(lk, [&] { return !q_.empty() || closing_; });
      if (q_.empty()) return;
      ev = q_.front();
      q_.pop();
    }
    fprintf(file_,
            "{\"ph\":\"%c\",\"name\":\"%s\",\"pid\":%d,\"tid\":\"%s\","
            "\"ts\":%.3f},\n",
            ev.ph, ev.name.c_str(), rank_, ev.tid.c_str(), ev.ts_us);
  }
}

void Timeline::Close() {
  if (!file_) return;
  {
    std::lock_guard<std::mutex> lk(mu_);
    closing_ = true;
    cv_.notify_all();
  }
  if (writer_.joinable()) writer_.join();
  fputs("{}]\n", file_);
  fclose(file_);
  file_ = nullptr;
}

}  // namespace hvd
