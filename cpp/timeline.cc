#include "timeline.h"

namespace hvd {

Timeline::Timeline(int rank, const std::string& path, bool mark_cycles)
    : rank_(rank) {
  t0_ = std::chrono::steady_clock::now();
  if (!path.empty()) Start(path, mark_cycles);
}

Timeline::~Timeline() { Stop(); }

bool Timeline::Start(const std::string& path, bool mark_cycles) {
  if (rank_ != 0 || path.empty()) return true;  // coordinator-only file
  std::unique_lock<std::mutex> lk(mu_);
  StopLocked(lk);
  file_ = fopen(path.c_str(), "w");
  if (!file_) return false;
  fputs("[\n", file_);
  closing_ = false;
  writer_ = std::thread([this] { WriterLoop(); });
  mark_cycles_.store(mark_cycles, std::memory_order_relaxed);
  enabled_.store(true, std::memory_order_relaxed);
  return true;
}

void Timeline::Stop() {
  std::unique_lock<std::mutex> lk(mu_);
  StopLocked(lk);
}

// caller holds lk on mu_; returns with it re-held
void Timeline::StopLocked(std::unique_lock<std::mutex>& lk) {
  if (!file_) return;
  enabled_.store(false, std::memory_order_relaxed);
  closing_ = true;
  cv_.notify_all();
  if (writer_.joinable()) {
    // let the writer drain the queue; it exits once empty + closing
    lk.unlock();
    writer_.join();
    lk.lock();
  }
  std::queue<Event>().swap(q_);  // drop events raced in after drain
  fputs("{}]\n", file_);
  fclose(file_);
  file_ = nullptr;
}

double Timeline::Now() {
  return std::chrono::duration<double, std::micro>(
             std::chrono::steady_clock::now() - t0_)
      .count();
}

void Timeline::Begin(const std::string& tid, const std::string& name) {
  if (!enabled()) return;
  std::lock_guard<std::mutex> lk(mu_);
  if (!file_) return;
  q_.push({'B', tid, name, Now()});
  cv_.notify_one();
}

void Timeline::End(const std::string& tid) {
  if (!enabled()) return;
  std::lock_guard<std::mutex> lk(mu_);
  if (!file_) return;
  q_.push({'E', tid, "", Now()});
  cv_.notify_one();
}

void Timeline::Instant(const std::string& name) {
  if (!enabled()) return;
  std::lock_guard<std::mutex> lk(mu_);
  if (!file_) return;
  q_.push({'i', "marker", name, Now()});
  cv_.notify_one();
}

void Timeline::WriterLoop() {
  for (;;) {
    Event ev;
    {
      std::unique_lock<std::mutex> lk(mu_);
      cv_.wait(lk, [&] { return !q_.empty() || closing_; });
      if (q_.empty()) return;  // closing and drained
      ev = q_.front();
      q_.pop();
    }
    fprintf(file_,
            "{\"ph\":\"%c\",\"name\":\"%s\",\"pid\":%d,\"tid\":\"%s\","
            "\"ts\":%.3f},\n",
            ev.ph, ev.name.c_str(), rank_, ev.tid.c_str(), ev.ts_us);
  }
}

}  // namespace hvd
