#include "timeline.h"

namespace hvd {

Timeline::Timeline(int rank, const std::string& path, bool mark_cycles)
    : rank_(rank) {
  t0_ = std::chrono::steady_clock::now();
  if (!path.empty()) Start(path, mark_cycles);
}

Timeline::~Timeline() { Stop(); }

bool Timeline::Start(const std::string& path, bool mark_cycles) {
  if (rank_ != 0 || path.empty()) return true;  // coordinator-only file
  std::lock_guard<std::mutex> lg(lifecycle_mu_);
  StopUnlocked();  // fully retires any previous writer + file first
  FILE* f = fopen(path.c_str(), "w");
  if (!f) return false;
  fputs("[\n", f);
  // SHARD_META: wall-clock anchor so the shard merger
  // (python -m horovod_tpu.diagnostics merge) can align this trace with
  // the per-rank host shards — epoch_us is the wall clock at an instant
  // whose shard-relative timestamp is this event's own ts.
  {
    double epoch_us =
        std::chrono::duration<double, std::micro>(
            std::chrono::system_clock::now().time_since_epoch())
            .count();
    fprintf(f,
            "{\"ph\":\"i\",\"name\":\"SHARD_META\",\"pid\":%d,"
            "\"tid\":\"meta\",\"ts\":%.3f,\"s\":\"g\",\"args\":"
            "{\"epoch_us\":%.3f,\"rank\":%d,\"source\":\"core\","
            "\"wall_offset_us\":0}},\n",
            rank_, Now(), epoch_us, rank_);
  }
  {
    std::lock_guard<std::mutex> lk(mu_);
    file_ = f;
    closing_ = false;
    std::queue<Event>().swap(q_);  // drop events raced in while stopped
  }
  // the writer owns its FILE* by value: a later Stop() can null file_
  // without pulling the file out from under an in-flight fprintf
  writer_ = std::thread([this, f] { WriterLoop(f); });
  mark_cycles_.store(mark_cycles, std::memory_order_relaxed);
  enabled_.store(true, std::memory_order_relaxed);
  return true;
}

void Timeline::Stop() {
  std::lock_guard<std::mutex> lg(lifecycle_mu_);
  StopUnlocked();
}

// caller holds lifecycle_mu_; idempotent — a second concurrent Stop (or
// the destructor racing a Python stop_timeline) sees file_ == nullptr
// under mu_ and returns without touching the writer or the FILE*.
void Timeline::StopUnlocked() {
  FILE* f;
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (!file_) return;
    enabled_.store(false, std::memory_order_relaxed);
    closing_ = true;
    f = file_;
    file_ = nullptr;  // Begin/End stop enqueueing from here on
    cv_.notify_all();
  }
  if (writer_.joinable()) writer_.join();  // drains the queue, then exits
  fputs("{}]\n", f);
  fclose(f);
}

double Timeline::Now() {
  return std::chrono::duration<double, std::micro>(
             std::chrono::steady_clock::now() - t0_)
      .count();
}

// caller holds mu_: current span id for a tensor name ("" before its
// first NoteEnqueue — e.g. another rank's process-set-only tensor)
std::string Timeline::SpanLocked(const std::string& name) {
  auto it = span_seq_.find(name);
  if (it == span_seq_.end() || it->second == 0) return "";
  return name + "#" + std::to_string(it->second);
}

void Timeline::NoteEnqueue(const std::string& name) {
  std::lock_guard<std::mutex> lk(mu_);
  // auto-named eager tensors mint a fresh name per call: cap the map so
  // a long run can't grow it unboundedly. Every rank enqueues the same
  // name sequence (negotiation requires it), so the reset happens at
  // the same enqueue on every rank and ids stay aligned (spans.py
  // applies the same bound).
  if (span_seq_.size() >= 65536) span_seq_.clear();
  ++span_seq_[name];
}

void Timeline::Begin(const std::string& tid, const std::string& name) {
  if (!enabled()) return;
  std::lock_guard<std::mutex> lk(mu_);
  if (!file_) return;
  q_.push({'B', tid, name, Now(), SpanLocked(tid)});
  cv_.notify_one();
}

void Timeline::End(const std::string& tid) {
  if (!enabled()) return;
  std::lock_guard<std::mutex> lk(mu_);
  if (!file_) return;
  q_.push({'E', tid, "", Now(), ""});
  cv_.notify_one();
}

void Timeline::Instant(const std::string& name) {
  if (!enabled()) return;
  std::lock_guard<std::mutex> lk(mu_);
  if (!file_) return;
  q_.push({'i', "marker", name, Now(), ""});
  cv_.notify_one();
}

void Timeline::MarkSpan(const std::string& name, const std::string& span) {
  if (!enabled()) return;
  std::lock_guard<std::mutex> lk(mu_);
  if (!file_) return;
  q_.push({'i', "marker", name, Now(), span});
  cv_.notify_one();
}

void Timeline::WriterLoop(FILE* file) {
  for (;;) {
    Event ev;
    {
      std::unique_lock<std::mutex> lk(mu_);
      cv_.wait(lk, [&] { return !q_.empty() || closing_; });
      if (q_.empty()) return;  // closing and drained
      ev = q_.front();
      q_.pop();
    }
    std::string name = JsonEscape(ev.name), tid = JsonEscape(ev.tid);
    if (ev.span.empty()) {
      fprintf(file,
              "{\"ph\":\"%c\",\"name\":\"%s\",\"pid\":%d,\"tid\":\"%s\","
              "\"ts\":%.3f},\n",
              ev.ph, name.c_str(), rank_, tid.c_str(), ev.ts_us);
    } else {
      fprintf(file,
              "{\"ph\":\"%c\",\"name\":\"%s\",\"pid\":%d,\"tid\":\"%s\","
              "\"ts\":%.3f,\"args\":{\"span\":\"%s\"}},\n",
              ev.ph, name.c_str(), rank_, tid.c_str(), ev.ts_us,
              JsonEscape(ev.span).c_str());
    }
    // flush on drain, not per event: batches syscalls under load while
    // an idle (or hung) trace still has a fresh tail for the autopsy
    bool drained;
    {
      std::lock_guard<std::mutex> lk(mu_);
      drained = q_.empty();
    }
    if (drained) fflush(file);
  }
}

}  // namespace hvd
