// Adasum allreduce — vector-halving distance-doubling (VHDD) on the host
// TCP data plane.
//
// Reference: horovod/common/ops/adasum/adasum.h:38-564 — the recursive
// algorithm: at level L, hypercube partners exchange vector halves, compute
// partial dot/norms on the kept half, sum the three scalars across the
// active subcube, and combine
//     a' = (1 - dot/(2*|a|^2)) a + (1 - dot/(2*|b|^2)) b ;
// after log2(p) levels each rank owns 1/p of the combined vector, which an
// allgather-doubling phase reassembles. Non-power-of-two groups fold the
// extra ranks onto partners first (reference: adasum.h:205-240).
#include <cmath>
#include <cstring>
#include <vector>

#include "collectives.h"

namespace hvd {

namespace {

template <typename T>
void DotAndNorms(const T* a, const T* b, int64_t n, double* out3) {
  double dot = 0, na = 0, nb = 0;
  for (int64_t i = 0; i < n; ++i) {
    double x = (double)a[i], y = (double)b[i];
    dot += x * y;
    na += x * x;
    nb += y * y;
  }
  out3[0] = dot;
  out3[1] = na;
  out3[2] = nb;
}

template <typename T>
void ScaledAdd(T* dst, double ca, const T* a, double cb, const T* b,
               int64_t n) {
  for (int64_t i = 0; i < n; ++i)
    dst[i] = (T)(ca * (double)a[i] + cb * (double)b[i]);
}

template <typename T>
void AddInto(T* dst, const T* src, int64_t n) {
  for (int64_t i = 0; i < n; ++i) dst[i] += src[i];
}

// Sum 3 doubles across the 2^(level+1)-rank subcube containing my_pos
// (recursive doubling on the hypercube).
template <typename T>
Status SubcubeScalarSum(Transport& t, const Group& g, int32_t tag,
                        const std::vector<int>& cube, int my_pos,
                        int levels, double* vals) {
  for (int l = 0; l < levels; ++l) {
    int partner_pos = my_pos ^ (1 << l);
    int peer = g.global(cube[partner_pos]);
    auto st = t.Send(peer, tag, vals, 3 * sizeof(double));
    if (!st.ok()) return st;
    std::vector<uint8_t> buf;
    st = t.Recv(peer, tag, &buf);
    if (!st.ok()) return st;
    const double* other = (const double*)buf.data();
    vals[0] += other[0];
    vals[1] += other[1];
    vals[2] += other[2];
  }
  return Status::OK();
}

template <typename T>
Status VhddTyped(Transport& t, const Group& g, int32_t tag, T* data,
                 int64_t nelem) {
  int p = g.size();
  int me = g.my_index;
  if (p == 1 || nelem == 0) return Status::OK();

  // largest power of two <= p
  int pow2 = 1;
  while (pow2 * 2 <= p) pow2 *= 2;
  int extras = p - pow2;

  // fold extras: rank pow2+i sends its vector to rank i, which combines
  // locally with the Adasum rule; at the end the partner sends the result
  // back (reference adasum.h:205-240 pairing)
  std::vector<T> fold;
  if (me >= pow2) {
    auto st = t.Send(g.global(me - pow2), tag, data, nelem * sizeof(T));
    if (!st.ok()) return st;
    std::vector<uint8_t> buf;
    st = t.Recv(g.global(me - pow2), tag + 1, &buf);
    if (!st.ok()) return st;
    memcpy(data, buf.data(), nelem * sizeof(T));
    return Status::OK();
  }
  if (me < extras) {
    std::vector<uint8_t> buf;
    auto st = t.Recv(g.global(me + pow2), tag, &buf);
    if (!st.ok()) return st;
    const T* other = (const T*)buf.data();
    double d3[3];
    DotAndNorms(data, other, nelem, d3);
    double ca = d3[1] > 0 ? 1.0 - d3[0] / (2.0 * d3[1]) : 1.0;
    double cb = d3[2] > 0 ? 1.0 - d3[0] / (2.0 * d3[2]) : 1.0;
    ScaledAdd(data, ca, data, cb, other, nelem);
  }

  // VHDD among the first pow2 ranks
  std::vector<int> cube(pow2);
  for (int i = 0; i < pow2; ++i) cube[i] = i;
  int64_t start = 0, len = nelem;
  int levels = 0;
  while ((1 << levels) < pow2) levels++;
  std::vector<int64_t> seg_starts(levels), seg_lens(levels);

  std::vector<uint8_t> buf;
  for (int l = 0; l < levels; ++l) {
    int partner = me ^ (1 << l);
    int peer = g.global(partner);
    int64_t half = len / 2;
    int64_t my_start, my_len, their_start, their_len;
    if (me < partner) {  // keep left half, send right
      my_start = start;
      my_len = half;
      their_start = start + half;
      their_len = len - half;
    } else {             // keep right half, send left
      my_start = start + half;
      my_len = len - half;
      their_start = start;
      their_len = half;
    }
    auto st = t.Send(peer, tag + 2, data + their_start,
                     their_len * sizeof(T));
    if (!st.ok()) return st;
    st = t.Recv(peer, tag + 2, &buf);
    if (!st.ok()) return st;
    const T* theirs = (const T*)buf.data();  // partner's copy of MY segment
    // role consistency across the pair: "a" is always the LOWER partner's
    // vector so the summed partial norms refer to the same operand on both
    // sides (reference fixes roles the same way)
    const T* va = (me < partner) ? data + my_start : theirs;
    const T* vb = (me < partner) ? theirs : data + my_start;
    double d3[3];
    DotAndNorms(va, vb, my_len, d3);
    // global coefficients: sum partials across the 2^(l+1) subcube
    auto st2 = SubcubeScalarSum<T>(t, g, tag + 3, cube, me, l + 1, d3);
    if (!st2.ok()) return st2;
    double ca = d3[1] > 0 ? 1.0 - d3[0] / (2.0 * d3[1]) : 1.0;
    double cb = d3[2] > 0 ? 1.0 - d3[0] / (2.0 * d3[2]) : 1.0;
    ScaledAdd(data + my_start, ca, va, cb, vb, my_len);
    seg_starts[l] = start;
    seg_lens[l] = len;
    start = my_start;
    len = my_len;
  }

  // allgather doubling back (reverse order): exchange my combined segment
  // with the level partner to rebuild its parent segment
  for (int l = levels - 1; l >= 0; --l) {
    int partner = me ^ (1 << l);
    int peer = g.global(partner);
    auto st = t.Send(peer, tag + 4, data + start, len * sizeof(T));
    if (!st.ok()) return st;
    st = t.Recv(peer, tag + 4, &buf);
    if (!st.ok()) return st;
    int64_t pstart = seg_starts[l], plen = seg_lens[l];
    // partner's segment is the complement of mine within the parent
    int64_t other_start = (start == pstart) ? pstart + len : pstart;
    int64_t other_len = plen - len;
    memcpy(data + other_start, buf.data(), other_len * sizeof(T));
    start = pstart;
    len = plen;
  }

  // return folded result to the extra ranks
  if (me < extras) {
    auto st = t.Send(g.global(me + pow2), tag + 1, data, nelem * sizeof(T));
    if (!st.ok()) return st;
  }
  return Status::OK();
}

}  // namespace

Status AdasumAllreduce(Transport& t, const Group& g, int32_t tag, void* data,
                       int64_t nelem, DataType dtype) {
  switch (dtype) {
    case DataType::kFloat32:
      return VhddTyped<float>(t, g, tag, (float*)data, nelem);
    case DataType::kFloat64:
      return VhddTyped<double>(t, g, tag, (double*)data, nelem);
    default:
      return Status::Error(
          "Adasum on the host path supports float32/float64 (cast 16-bit "
          "gradients up, or use the XLA Adasum path)");
  }
}

}  // namespace hvd
