// Chrome-tracing timeline for the native core (reference:
// horovod/common/timeline.{h,cc} — writer thread + activity events;
// coordinator-only file, operations.cc:459-475; dynamic start/stop via the
// C API, operations.cc:1011-1041; activity taxonomy common.h:73-105).
#pragma once

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <mutex>
#include <queue>
#include <string>
#include <thread>

namespace hvd {

class Timeline {
 public:
  // path empty or rank != 0 -> disabled until Start() is called
  Timeline(int rank, const std::string& path, bool mark_cycles = false);
  ~Timeline();
  bool enabled() const { return enabled_.load(std::memory_order_relaxed); }
  bool mark_cycles() const {
    return mark_cycles_.load(std::memory_order_relaxed);
  }
  // Dynamic control (reference: horovod_start_timeline/_stop_timeline).
  // Coordinator-only: non-zero ranks no-op and return OK. Start on an
  // already-running timeline reopens at the new path.
  bool Start(const std::string& path, bool mark_cycles);
  void Stop();
  void Begin(const std::string& tid, const std::string& name);
  void End(const std::string& tid);
  void Instant(const std::string& name);
  void Close() { Stop(); }

 private:
  struct Event {
    char ph;
    std::string tid, name;
    double ts_us;
  };
  void WriterLoop(FILE* file);
  void StopUnlocked();  // caller holds lifecycle_mu_
  double Now();
  int rank_;
  FILE* file_ = nullptr;
  std::atomic<bool> enabled_{false};
  std::atomic<bool> mark_cycles_{false};
  std::chrono::steady_clock::time_point t0_;
  // lifecycle_mu_ serializes whole Start()/Stop() operations (a concurrent
  // Stop/Start/destructor pair must never join the same writer thread
  // twice or double-close the FILE*); mu_ protects the event queue and is
  // the only lock the hot Begin/End path or the writer ever takes.
  std::mutex lifecycle_mu_;
  std::mutex mu_;  // queue (+ file_ presence check on the event path)
  std::condition_variable cv_;
  std::queue<Event> q_;
  bool closing_ = false;
  std::thread writer_;
};

}  // namespace hvd
