// Chrome-tracing timeline for the native core (reference:
// horovod/common/timeline.{h,cc} — writer thread + activity events;
// coordinator-only file, operations.cc:459-475; dynamic start/stop via the
// C API, operations.cc:1011-1041; activity taxonomy common.h:73-105).
#pragma once

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <unordered_map>

namespace hvd {

// Tensor names come from user code: escape them before embedding in
// hand-rolled JSON (timeline events, engine-state snapshots) or a name
// with a quote/backslash corrupts the whole document exactly when a
// post-mortem needs it.
inline std::string JsonEscape(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (unsigned char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          out += buf;
        } else {
          out += (char)c;
        }
    }
  }
  return out;
}

class Timeline {
 public:
  // path empty or rank != 0 -> disabled until Start() is called
  Timeline(int rank, const std::string& path, bool mark_cycles = false);
  ~Timeline();
  bool enabled() const { return enabled_.load(std::memory_order_relaxed); }
  bool mark_cycles() const {
    return mark_cycles_.load(std::memory_order_relaxed);
  }
  // Dynamic control (reference: horovod_start_timeline/_stop_timeline).
  // Coordinator-only: non-zero ranks no-op and return OK. Start on an
  // already-running timeline reopens at the new path.
  bool Start(const std::string& path, bool mark_cycles);
  void Stop();
  void Begin(const std::string& tid, const std::string& name);
  void End(const std::string& tid);
  void Instant(const std::string& name);
  // Per-collective span ids (diagnostics cross-rank trace): every rank
  // counts enqueues per tensor name, so "<name>#<count>" is the SAME id
  // the Python layer computes (horovod_tpu/diagnostics/spans.py) — no
  // wire traffic, correlation by construction. NoteEnqueue bumps the
  // counter; Begin/End attach the current span as event args.
  void NoteEnqueue(const std::string& name);
  // Explicit-span instant for the C API (hvd_timeline_mark): the Python
  // enqueue path stamps its span id straight into the engine trace.
  void MarkSpan(const std::string& name, const std::string& span);
  void Close() { Stop(); }

 private:
  struct Event {
    char ph;
    std::string tid, name;
    double ts_us;
    std::string span;  // "" = no args emitted
  };
  std::string SpanLocked(const std::string& name);  // caller holds mu_
  void WriterLoop(FILE* file);
  void StopUnlocked();  // caller holds lifecycle_mu_
  double Now();
  int rank_;
  FILE* file_ = nullptr;
  std::atomic<bool> enabled_{false};
  std::atomic<bool> mark_cycles_{false};
  std::chrono::steady_clock::time_point t0_;
  // lifecycle_mu_ serializes whole Start()/Stop() operations (a concurrent
  // Stop/Start/destructor pair must never join the same writer thread
  // twice or double-close the FILE*); mu_ protects the event queue and is
  // the only lock the hot Begin/End path or the writer ever takes.
  std::mutex lifecycle_mu_;
  std::mutex mu_;  // queue (+ file_ presence check on the event path)
  std::condition_variable cv_;
  std::queue<Event> q_;
  // per-name enqueue counts -> span ids; counted even while disabled so
  // a timeline started mid-run still agrees with the Python layer's
  // per-name counters (both count from process start)
  std::unordered_map<std::string, uint64_t> span_seq_;
  bool closing_ = false;
  std::thread writer_;
};

}  // namespace hvd
