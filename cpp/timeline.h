// Chrome-tracing timeline for the native core (reference:
// horovod/common/timeline.{h,cc} — writer thread + activity events;
// coordinator-only file, operations.cc:459-475).
#pragma once

#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <mutex>
#include <queue>
#include <string>
#include <thread>

namespace hvd {

class Timeline {
 public:
  Timeline(int rank, const std::string& path);
  ~Timeline();
  bool enabled() const { return file_ != nullptr; }
  void Begin(const std::string& tid, const std::string& name);
  void End(const std::string& tid);
  void Instant(const std::string& name);
  void Close();

 private:
  struct Event {
    char ph;
    std::string tid, name;
    double ts_us;
  };
  void WriterLoop();
  double Now();
  int rank_;
  FILE* file_ = nullptr;
  std::chrono::steady_clock::time_point t0_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::queue<Event> q_;
  bool closing_ = false;
  std::thread writer_;
};

}  // namespace hvd
