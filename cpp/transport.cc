#include "transport.h"
#include "logging.h"
#include "wire.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace hvd {

namespace {

// Parse the HVD_TPU_CHAOS_TRANSPORT spec (see TransportChaos in
// transport.h).  Malformed entries are skipped with a log line — a typo
// in a chaos spec must degrade to "fault not armed", never crash the job
// it was meant to test.
std::unique_ptr<TransportChaos> ParseChaosEnv(int size) {
  const char* env = getenv("HVD_TPU_CHAOS_TRANSPORT");
  if (env == nullptr || env[0] == '\0') return nullptr;
  auto chaos = std::unique_ptr<TransportChaos>(new TransportChaos(size));
  std::string spec(env);
  size_t pos = 0;
  while (pos <= spec.size()) {
    size_t end = spec.find(';', pos);
    if (end == std::string::npos) end = spec.size();
    std::string entry = spec.substr(pos, end - pos);
    pos = end + 1;
    if (entry.empty()) continue;
    TransportChaosRule rule;
    bool ok = !entry.empty(), have_dir = false, have_kind = false;
    size_t fpos = 0;
    while (fpos <= entry.size()) {
      size_t fend = entry.find(':', fpos);
      if (fend == std::string::npos) fend = entry.size();
      std::string field = entry.substr(fpos, fend - fpos);
      fpos = fend + 1;
      size_t eq = field.find('=');
      if (eq == std::string::npos) { ok = false; break; }
      std::string k = field.substr(0, eq), v = field.substr(eq + 1);
      if (k == "dir") {
        have_dir = true;
        if (v == "recv") rule.recv = true;
        else if (v == "send") rule.recv = false;
        else ok = false;
      } else if (k == "kind") {
        have_kind = true;
        if (v == "delay") rule.kind = 0;
        else if (v == "drop") rule.kind = 1;
        else if (v == "close") rule.kind = 2;
        else if (v == "bit_flip") rule.kind = 3;
        else ok = false;
      } else if (k == "peer") {
        rule.peer = (v == "*") ? -1 : atoi(v.c_str());
      } else if (k == "after") {
        rule.after = strtoull(v.c_str(), nullptr, 10);
      } else if (k == "count") {
        rule.count = strtoull(v.c_str(), nullptr, 10);
      } else if (k == "ms") {
        rule.ms = atof(v.c_str());
      } else if (k == "minb") {
        rule.min_bytes = strtoull(v.c_str(), nullptr, 10);
      } else if (k == "fires") {
        rule.fires = strtoull(v.c_str(), nullptr, 10);
      } else {
        ok = false;
      }
    }
    if (ok && have_dir && have_kind) {
      chaos->rules.push_back(rule);
    } else {
      HVD_LOG(Warning) << "chaos: ignoring malformed transport rule '"
                       << entry << "'";
    }
  }
  if (chaos->rules.empty()) return nullptr;
  chaos->rule_fired.reset(new std::atomic<uint64_t>[chaos->rules.size()]);
  for (size_t i = 0; i < chaos->rules.size(); ++i) {
    chaos->rule_fired[i] = 0;
    if (chaos->rules[i].kind == 3) chaos->has_bit_flip = true;
  }
  HVD_LOG(Warning) << "chaos: transport faults armed ("
                   << chaos->rules.size() << " rule(s): " << spec << ")";
  return chaos;
}

Status WriteAll(int fd, const void* data, size_t len) {
  const uint8_t* p = (const uint8_t*)data;
  while (len > 0) {
    ssize_t n = ::send(fd, p, len, MSG_NOSIGNAL);
    if (n <= 0) {
      if (n < 0 && (errno == EINTR)) continue;
      return Status::Error("socket send failed: " +
                           std::string(strerror(errno)));
    }
    p += n;
    len -= n;
  }
  return Status::OK();
}

int64_t NowNs() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// `activity` (optional) is stamped after every successful chunk, so a
// peer slowly streaming one large frame keeps registering as alive for
// the recv inactivity deadline.
Status ReadAll(int fd, void* data, size_t len,
               std::atomic<int64_t>* activity = nullptr) {
  uint8_t* p = (uint8_t*)data;
  while (len > 0) {
    ssize_t n = ::recv(fd, p, len, 0);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      return Status::Error("socket recv failed/closed");
    }
    if (activity) activity->store(NowNs());
    p += n;
    len -= n;
  }
  return Status::OK();
}

int MakeListenSocket(int port, int* actual_port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons(port);
  if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    close(fd);
    return -1;
  }
  if (listen(fd, 128) != 0) {
    close(fd);
    return -1;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, (sockaddr*)&addr, &alen);
  if (actual_port) *actual_port = ntohs(addr.sin_port);
  return fd;
}

void SetNoDelay(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

}  // namespace

Transport::Transport(int rank, int size, const std::string& coord_addr,
                     int coord_port, double connect_timeout_secs,
                     double recv_timeout_secs, bool wire_checksum)
    : rank_(rank), size_(size), coord_addr_(coord_addr),
      coord_port_(coord_port),
      connect_timeout_secs_(connect_timeout_secs),
      recv_timeout_secs_(recv_timeout_secs),
      checksum_enabled_(wire_checksum),
      chaos_(ParseChaosEnv(size)), last_rx_ns_(size) {
  for (int i = 0; i < size; ++i) last_rx_ns_[i] = 0;
  peer_fds_.assign(size, -1);
  inbox_.resize(size);
  dead_.assign(size, false);
  peer_error_.assign(size, std::string());
  for (int i = 0; i < size; ++i)
    send_mu_.emplace_back(new std::mutex());
}

bool Transport::ChaosOnFrame(bool recv, int peer, uint8_t* payload,
                             size_t len) {
  // chaos_ checked by the caller; frame indices count per peer per
  // direction so `after` means "the Nth frame exchanged with THAT peer"
  uint64_t seq = recv ? chaos_->recv_seen[peer].fetch_add(1)
                      : chaos_->send_seen[peer].fetch_add(1);
  bool drop = false;
  for (size_t ri = 0; ri < chaos_->rules.size(); ++ri) {
    const auto& r = chaos_->rules[ri];
    if (r.recv != recv) continue;
    if (r.peer != -1 && r.peer != peer) continue;
    if (seq < r.after) continue;
    if (r.count != 0 && seq >= r.after + r.count) continue;
    if (r.min_bytes != 0 && len < r.min_bytes) continue;
    if (r.fires != 0 &&
        chaos_->rule_fired[ri].fetch_add(1) >= r.fires) {
      continue;  // fire budget spent (fetch_add keeps it spent)
    }
    chaos_->injected.fetch_add(1);
    if (r.kind == 0) {  // delay
      HVD_LOG(Warning) << "chaos: delaying " << (recv ? "recv" : "send")
                       << " frame " << seq << " from/to peer " << peer
                       << " by " << r.ms << "ms";
      usleep((useconds_t)(r.ms * 1000.0));
    } else if (r.kind == 1) {  // drop
      HVD_LOG(Warning) << "chaos: dropping " << (recv ? "recv" : "send")
                       << " frame " << seq << " (peer " << peer << ")";
      drop = true;
    } else if (r.kind == 3) {  // bit_flip: corrupt one payload byte.
      // On the send side this runs AFTER the frame's CRC was computed
      // — the flip models corruption ON THE WIRE, which is exactly
      // what the checksum must catch (docs/CHAOS.md "Wire integrity").
      if (payload != nullptr && len > 0) {
        // bit 7 of the middle byte, not bit 0: for little-endian f32
        // payloads the lowest mantissa bit of a flipped addend can
        // ROUND AWAY in the reduction (1.0 + (1.0+2^-23) == 2.0f
        // exactly), which would make the undetected-corruption half of
        // the acceptance flaky — a higher-order bit always survives
        payload[len / 2] ^= 0x80;
        HVD_LOG(Warning) << "chaos: bit-flipping "
                         << (recv ? "recv" : "send") << " frame " << seq
                         << " (peer " << peer << ", " << len
                         << " bytes, offset " << (len / 2) << ")";
      }
    } else {  // close: reset the peer's socket mid-stream
      HVD_LOG(Warning) << "chaos: closing socket to peer " << peer
                       << " at frame " << seq;
      int fd = peer_fds_[peer];
      if (fd >= 0) ::shutdown(fd, SHUT_RDWR);
      drop = true;
    }
  }
  return drop;
}

Transport::~Transport() { Shutdown(); }

Status Transport::ConnectTo(const std::string& host, int port, int* fd_out) {
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  bool is_literal = inet_pton(AF_INET, host.c_str(), &addr.sin_addr) == 1;
  // retry loop: peers may not be listening yet — and at fleet startup a
  // hostname may not RESOLVE yet either (records published as VMs come
  // up), so name resolution retries under the same deadline. Deadline =
  // the HOROVOD_GLOO_TIMEOUT_SECONDS-equivalent knob.
  std::string last_err = "unresolved";
  int attempts = std::max(1, (int)(connect_timeout_secs_ * 10));
  for (int attempt = 0; attempt < attempts; ++attempt) {
    if (attempt > 0) usleep(100 * 1000);
    if (!is_literal) {
      // TPU-VM fleets (and the Ray/Spark integrations) hand out
      // hostnames; the reference resolves through Gloo's rendezvous
      addrinfo hints{};
      hints.ai_family = AF_INET;
      hints.ai_socktype = SOCK_STREAM;
      addrinfo* res = nullptr;
      int rc = getaddrinfo(host.c_str(), nullptr, &hints, &res);
      if (rc != 0 || res == nullptr) {
        last_err = std::string("bad address: ") + gai_strerror(rc);
        if (res) freeaddrinfo(res);
        continue;
      }
      addr.sin_addr = ((sockaddr_in*)res->ai_addr)->sin_addr;
      freeaddrinfo(res);
    }
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return Status::Error("socket() failed");
    if (connect(fd, (sockaddr*)&addr, sizeof(addr)) == 0) {
      SetNoDelay(fd);
      *fd_out = fd;
      return Status::OK();
    }
    last_err = strerror(errno);
    close(fd);
  }
  return Status::Error("could not connect to " + host + ":" +
                       std::to_string(port) + " within " +
                       std::to_string((int)connect_timeout_secs_) +
                       "s (" + last_err + ")");
}

Status Transport::Init() {
  if (size_ == 1) return Status::OK();
  // Every rank opens its own listen socket on an ephemeral port.
  int my_port = 0;
  listen_fd_ = MakeListenSocket(rank_ == 0 ? coord_port_ : 0, &my_port);
  if (listen_fd_ < 0) return Status::Error("listen socket failed");

  // Rendezvous: rank 0 accepts size-1 registrations (rank, port), replies
  // with the full table; like the reference's KV-store rendezvous
  // (gloo_context.cc:67-94) with rank 0 as the store.
  std::vector<std::string> hosts(size_);
  std::vector<int> ports(size_, 0);
  hosts[0] = coord_addr_;
  ports[0] = my_port;

  if (rank_ == 0) {
    std::vector<int> reg_fds(size_, -1);
    for (int i = 1; i < size_; ++i) {
      sockaddr_in peer{};
      socklen_t plen = sizeof(peer);
      int fd = accept(listen_fd_, (sockaddr*)&peer, &plen);
      if (fd < 0) return Status::Error("accept failed in rendezvous");
      SetNoDelay(fd);
      int32_t hdr[2];
      auto st = ReadAll(fd, hdr, sizeof(hdr));
      if (!st.ok()) return st;
      int r = hdr[0];
      char ip[64];
      inet_ntop(AF_INET, &peer.sin_addr, ip, sizeof(ip));
      hosts[r] = ip;
      ports[r] = hdr[1];
      reg_fds[r] = fd;
    }
    // broadcast table
    wire::Writer w;
    for (int i = 0; i < size_; ++i) {
      w.str(hosts[i]);
      w.i32(ports[i]);
    }
    for (int i = 1; i < size_; ++i) {
      int32_t len = (int32_t)w.buf.size();
      auto st = WriteAll(reg_fds[i], &len, 4);
      if (st.ok()) st = WriteAll(reg_fds[i], w.buf.data(), w.buf.size());
      if (!st.ok()) return st;
      close(reg_fds[i]);
    }
  } else {
    int fd;
    auto st = ConnectTo(coord_addr_, coord_port_, &fd);
    if (!st.ok()) return st;
    int32_t hdr[2] = {rank_, my_port};
    st = WriteAll(fd, hdr, sizeof(hdr));
    if (!st.ok()) return st;
    int32_t len;
    st = ReadAll(fd, &len, 4);
    if (!st.ok()) return st;
    std::vector<uint8_t> buf(len);
    st = ReadAll(fd, buf.data(), len);
    if (!st.ok()) return st;
    close(fd);
    wire::Reader rd(buf.data(), buf.size());
    for (int i = 0; i < size_; ++i) {
      hosts[i] = rd.str();
      ports[i] = rd.i32();
    }
  }

  // Full mesh: connect to lower ranks; accept from higher ranks.
  for (int peer = 0; peer < rank_; ++peer) {
    int fd;
    auto st = ConnectTo(hosts[peer], ports[peer], &fd);
    if (!st.ok()) return st;
    int32_t me = rank_;
    st = WriteAll(fd, &me, 4);
    if (!st.ok()) return st;
    peer_fds_[peer] = fd;
  }
  for (int i = rank_ + 1; i < size_; ++i) {
    int fd = accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) return Status::Error("accept failed in mesh setup");
    SetNoDelay(fd);
    int32_t who;
    auto st = ReadAll(fd, &who, 4);
    if (!st.ok()) return st;
    peer_fds_[who] = fd;
  }

  for (int peer = 0; peer < size_; ++peer) {
    if (peer == rank_) continue;
    readers_.emplace_back([this, peer] { ReaderLoop(peer); });
  }
  return Status::OK();
}

std::shared_ptr<Transport::TagQueue> Transport::GetQueue(int peer,
                                                         int32_t tag) {
  std::lock_guard<std::mutex> lk(inbox_mu_);
  auto& m = inbox_[peer];
  auto it = m.find(tag);
  if (it == m.end()) {
    auto q = std::make_shared<TagQueue>();
    if (dead_[peer]) q->closed = true;  // peer already gone
    m[tag] = q;
    return q;
  }
  return it->second;
}

void Transport::ReaderLoop(int peer) {
  int fd = peer_fds_[peer];
  for (;;) {
    // tag, len [, frame crc32c, header crc32c with the checksum on]
    int32_t hdr[4];
    size_t hdr_len = checksum_enabled_ ? sizeof(hdr) : 8;
    int64_t before = last_rx_ns_[peer].load();
    if (!ReadAll(fd, hdr, hdr_len, &last_rx_ns_[peer]).ok()) break;
    bool bad_header = hdr[1] < 0;  // a negative length is never real,
    // and would drive a garbage allocation below (the pre-checksum
    // hazard too, so it is checked in both modes)
    if (checksum_enabled_ && !bad_header) {
      // validate the HEADER'S OWN crc before trusting the length: a
      // flipped bit in the len field would otherwise block the reader
      // (or blow the allocation) before the frame CRC could fail —
      // exactly the corruption this layer must catch, not hang on
      uint32_t want_h;
      memcpy(&want_h, &hdr[3], 4);
      bad_header = wire::Crc32c(hdr, 8) != want_h;
    }
    if (bad_header) {
      if (checksum_enabled_) checksum_failures_.fetch_add(1);
      char buf[128];
      snprintf(buf, sizeof(buf),
               "wire corruption from peer %d: frame header failed "
               "verification (tag=%d, len=%d)", peer, hdr[0], hdr[1]);
      HVD_LOG(Error) << buf;
      {
        std::lock_guard<std::mutex> lk(inbox_mu_);
        peer_error_[peer] = buf;
      }
      ::shutdown(fd, SHUT_RDWR);
      break;
    }
    std::vector<uint8_t> payload(hdr[1]);
    if (hdr[1] > 0 &&
        !ReadAll(fd, payload.data(), hdr[1], &last_rx_ns_[peer]).ok())
      break;
    // chaos seam: zero-cost when off (one null test per frame)
    if (chaos_ && ChaosOnFrame(/*recv=*/true, peer, payload.data(),
                               payload.size())) {
      // an injected drop/close must look like SILENCE to the recv
      // deadline — that is the wedged-peer scenario it simulates
      last_rx_ns_[peer].store(before);
      continue;
    }
    if (checksum_enabled_) {
      // verify AFTER the chaos seam: a recv-side bit_flip models the
      // same on-the-wire corruption a send-side one does
      uint32_t want;
      memcpy(&want, &hdr[2], 4);
      uint32_t got = wire::Crc32c(hdr, 8);
      got = wire::Crc32c(payload.data(), payload.size(), got);
      if (got != want) {
        checksum_failures_.fetch_add(1);
        char buf[160];
        snprintf(buf, sizeof(buf),
                 "wire checksum mismatch on frame from peer %d (tag=%d,"
                 " len=%d, crc 0x%08x != expected 0x%08x): corrupted"
                 " data on the eager wire; closing the connection",
                 peer, hdr[0], hdr[1], got, want);
        HVD_LOG(Error) << buf
                       << " (HVD_TPU_WIRE_CHECKSUM; "
                       << "transport_checksum_failures counts these)";
        {
          std::lock_guard<std::mutex> lk(inbox_mu_);
          peer_error_[peer] = buf;
        }
        // a corrupt stream is unrecoverable (the length field itself
        // may be lying): reset the socket so the PEER also observes
        // the failure and both sides enter elastic recovery
        ::shutdown(fd, SHUT_RDWR);
        break;
      }
    }
    auto q = GetQueue(peer, hdr[0]);
    {
      std::lock_guard<std::mutex> lk(q->mu);
      q->q.push(std::move(payload));
    }
    q->cv.notify_all();
  }
  // close all queues for this peer so blocked recvs fail fast; mark the
  // peer dead so queues created later are born closed
  std::lock_guard<std::mutex> lk(inbox_mu_);
  dead_[peer] = true;
  for (auto& kv : inbox_[peer]) {
    std::lock_guard<std::mutex> qk(kv.second->mu);
    kv.second->closed = true;
    kv.second->cv.notify_all();
  }
}

Status Transport::Send(int peer, int32_t tag, const void* data, size_t len) {
  if (peer == rank_) {
    auto q = GetQueue(peer, tag);
    std::vector<uint8_t> payload((const uint8_t*)data,
                                 (const uint8_t*)data + len);
    {
      std::lock_guard<std::mutex> lk(q->mu);
      q->q.push(std::move(payload));
    }
    q->cv.notify_all();
    return Status::OK();
  }
  std::lock_guard<std::mutex> lk(*send_mu_[peer]);
  // header: {tag, len, frame_crc, hdr_crc}; the last two only when the
  // wire checksum is on.  hdr_crc covers (tag, len) ALONE so the
  // receiver can validate the length BEFORE allocating/reading the
  // payload — a flipped bit in the length field must be detected
  // immediately, not hang the reader waiting for bytes that never come
  int32_t hdr[4] = {tag, (int32_t)len, 0, 0};
  if (checksum_enabled_) {
    // frame CRC over header (tag+len) then payload, computed BEFORE
    // the chaos seam below may corrupt the bytes: a send-side bit_flip
    // models corruption on the wire, after checksumming — the case the
    // recv-side verification exists to catch
    uint32_t crc = wire::Crc32c(hdr, 8);
    crc = wire::Crc32c(data, len, crc);
    memcpy(&hdr[2], &crc, 4);
    uint32_t hcrc = wire::Crc32c(hdr, 8);
    memcpy(&hdr[3], &hcrc, 4);
  }
  // chaos seam: a dropped send is written NOWHERE — the peer starves,
  // which is exactly the wedged-peer scenario the recv deadline
  // catches; a bit_flip corrupts a COPY of the payload (the caller's
  // tensor bytes must stay intact — the fault is on the wire, not in
  // host memory)
  std::vector<uint8_t> corrupted;
  const void* out_data = data;
  if (chaos_) {
    uint8_t* mut = nullptr;
    if (chaos_->has_bit_flip && len > 0) {
      corrupted.assign((const uint8_t*)data, (const uint8_t*)data + len);
      mut = corrupted.data();
      out_data = mut;
    }
    if (ChaosOnFrame(/*recv=*/false, peer, mut, len))
      return Status::OK();
  }
  int fd = peer_fds_[peer];
  if (fd < 0) return Status::Error("no connection to peer");
  size_t hdr_len = checksum_enabled_ ? sizeof(hdr) : 8;
  auto st = WriteAll(fd, hdr, hdr_len);
  if (!st.ok()) return st;
  return WriteAll(fd, out_data, len);
}

Status Transport::Recv(int peer, int32_t tag, std::vector<uint8_t>* out) {
  auto q = GetQueue(peer, tag);
  std::unique_lock<std::mutex> lk(q->mu);
  if (recv_timeout_secs_ > 0) {
    // inactivity deadline: the engine's lockstep cycle keeps frames
    // flowing every few ms while peers are healthy, so a silent gap of
    // this length means a dead-but-connected peer (SIGSTOP, wedged
    // host, half-open TCP) — surface it instead of blocking forever.
    // The clock is per-peer DELIVERED-byte activity (stamped chunk-wise
    // by ReaderLoop), not this tag queue's emptiness: a healthy peer
    // slowly streaming one large fused frame keeps resetting it.
    const int64_t timeout_ns = (int64_t)(recv_timeout_secs_ * 1e9);
    const int64_t waited_from = NowNs();
    while (q->q.empty() && !q->closed) {
      q->cv.wait_for(lk, std::chrono::milliseconds(200));
      if (!q->q.empty() || q->closed) break;
      int64_t base = waited_from;
      if (peer != rank_) base = std::max(base, last_rx_ns_[peer].load());
      if (NowNs() - base > timeout_ns) {
        return Status::Error(
            "transport timeout: no data from peer " +
            std::to_string(peer) + " for " +
            std::to_string(recv_timeout_secs_) +
            "s (HVD_TPU_TRANSPORT_TIMEOUT_S); peer is wedged or "
            "unreachable");
      }
    }
  } else {
    q->cv.wait(lk, [&] { return !q->q.empty() || q->closed; });
  }
  if (q->q.empty()) {
    // integrity failures carry their own cause: the waiter's error must
    // NAME the corrupting peer, not read as a generic peer loss.
    // Release the queue lock first — the reader's close-out path locks
    // inbox_mu_ then each queue, so taking inbox_mu_ while holding
    // q->mu would invert the order and risk a deadlock.
    lk.unlock();
    {
      std::lock_guard<std::mutex> ik(inbox_mu_);
      if (peer >= 0 && peer < (int)peer_error_.size() &&
          !peer_error_[peer].empty())
        return Status::Error(peer_error_[peer]);
    }
    return Status::Aborted("connection closed");
  }
  *out = std::move(q->q.front());
  q->q.pop();
  return Status::OK();
}

void Transport::Shutdown() {
  if (shutting_down_.exchange(true)) return;
  {
    // unblock every pending and future Recv
    std::lock_guard<std::mutex> lk(inbox_mu_);
    for (size_t p = 0; p < inbox_.size(); ++p) {
      dead_[p] = true;
      for (auto& kv : inbox_[p]) {
        std::lock_guard<std::mutex> qk(kv.second->mu);
        kv.second->closed = true;
        kv.second->cv.notify_all();
      }
    }
  }
  for (auto& fd : peer_fds_) {
    if (fd >= 0) {
      ::shutdown(fd, SHUT_RDWR);
    }
  }
  if (listen_fd_ >= 0) close(listen_fd_);
  for (auto& t : readers_)
    if (t.joinable()) t.join();
  for (auto& fd : peer_fds_) {
    if (fd >= 0) close(fd);
    fd = -1;
  }
  listen_fd_ = -1;
}

}  // namespace hvd
