// Core types for the hvdcore native coordination engine.
//
// TPU-native re-design of the reference's common types
// (horovod/common/common.h:150-340: Status, DataType, TensorShape,
// TensorTableEntry). No framework tensor abstraction is needed: the Python
// layer hands us raw host buffers (numpy / jax device->host), the engine
// coordinates + moves bytes, and the TPU data plane stays in XLA.
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <functional>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

namespace hvd {

enum class DataType : int32_t {
  kUint8 = 0,
  kInt8 = 1,
  kInt32 = 4,
  kInt64 = 5,
  kFloat16 = 6,
  kFloat32 = 7,
  kFloat64 = 8,
  kBool = 9,
  kBFloat16 = 10,
};

inline size_t DataTypeSize(DataType t) {
  switch (t) {
    case DataType::kUint8:
    case DataType::kInt8:
    case DataType::kBool:
      return 1;
    case DataType::kFloat16:
    case DataType::kBFloat16:
      return 2;
    case DataType::kInt32:
    case DataType::kFloat32:
      return 4;
    case DataType::kInt64:
    case DataType::kFloat64:
      return 8;
  }
  return 1;
}

enum class ReduceOp : int32_t {
  kAverage = 0,
  kSum = 1,
  kAdasum = 2,
  kMin = 3,
  kMax = 4,
  kProduct = 5,
};

enum class StatusType : int32_t { kOk = 0, kAborted = 1, kInvalid = 2,
                                  kInProgress = 3 };

struct Status {
  StatusType type = StatusType::kOk;
  std::string reason;
  static Status OK() { return Status(); }
  static Status Error(const std::string& msg) {
    return Status{StatusType::kInvalid, msg};
  }
  static Status Aborted(const std::string& msg) {
    return Status{StatusType::kAborted, msg};
  }
  bool ok() const { return type == StatusType::kOk; }
};

// Request: one rank announcing a tensor is ready (reference:
// horovod/common/message.h:55-140).
struct Request {
  enum Type : int32_t { kAllreduce = 0, kAllgather = 1, kBroadcast = 2,
                        kAlltoall = 3, kJoin = 4, kBarrier = 5 };
  Type type = kAllreduce;
  int32_t rank = 0;
  std::string name;
  DataType dtype = DataType::kFloat32;
  std::vector<int64_t> shape;
  int32_t root_rank = 0;
  ReduceOp op = ReduceOp::kSum;
  double prescale = 1.0;
  double postscale = 1.0;
  int32_t group_id = -1;
  int32_t group_size = 0;
};

// Response: coordinator's instruction to execute a (possibly fused) op
// (reference: horovod/common/message.h:143-252).
struct Response {
  enum Type : int32_t { kAllreduce = 0, kAllgather = 1, kBroadcast = 2,
                        kAlltoall = 3, kJoin = 4, kBarrier = 5, kError = 6,
                        kShutdown = 7 };
  Type type = kAllreduce;
  std::vector<std::string> names;
  std::string error_message;
  // per-tensor metadata so non-submitting (joined) ranks can participate
  std::vector<DataType> dtypes;
  std::vector<std::vector<int64_t>> shapes;
  int32_t root_rank = 0;
  ReduceOp op = ReduceOp::kSum;
  double prescale = 1.0;
  double postscale = 1.0;
  int32_t last_joined_rank = -1;
  int32_t group_id = -1;
  int32_t group_size = 0;
  // true when served from the response cache (receivers must not re-insert)
  bool from_cache = false;
};

using StatusCallback = std::function<void(const Status&)>;

// One enqueued tensor awaiting coordination (reference:
// horovod/common/common.h:297-332 TensorTableEntry).
struct TensorTableEntry {
  std::string name;
  Request::Type type = Request::kAllreduce;
  const void* input = nullptr;   // caller-owned until callback fires
  void* output = nullptr;        // allreduce/broadcast: same-shape output
  DataType dtype = DataType::kFloat32;
  std::vector<int64_t> shape;
  int32_t root_rank = 0;
  ReduceOp op = ReduceOp::kSum;
  double prescale = 1.0;
  double postscale = 1.0;
  std::vector<int64_t> splits;          // alltoall send splits
  // results for variable-size ops (allgather/alltoall); shared with the
  // caller's handle so Execute's writes are visible through the handle
  std::shared_ptr<std::vector<uint8_t>> result;
  std::shared_ptr<std::vector<int64_t>> result_shape;
  std::shared_ptr<std::vector<int64_t>> recv_splits;
  StatusCallback callback;

  int64_t NumElements() const {
    int64_t n = 1;
    for (auto d : shape) n *= d;
    return n;
  }
  size_t ByteSize() const { return NumElements() * DataTypeSize(dtype); }
};

}  // namespace hvd
