#include "collectives.h"

#include <algorithm>
#include <cmath>
#include <cstring>

namespace hvd {

namespace {

// -- elementwise accumulate ------------------------------------------------

inline float Bf16ToF32(uint16_t h) {
  uint32_t u = ((uint32_t)h) << 16;
  float f;
  memcpy(&f, &u, 4);
  return f;
}

inline uint16_t F32ToBf16(float f) {
  uint32_t u;
  memcpy(&u, &f, 4);
  // round-to-nearest-even like the reference's fp16 path rounds properly
  uint32_t rounding = 0x7fff + ((u >> 16) & 1);
  return (uint16_t)((u + rounding) >> 16);
}

inline float F16ToF32(uint16_t h) {
  uint32_t sign = (h & 0x8000) << 16;
  uint32_t exp = (h >> 10) & 0x1f;
  uint32_t man = h & 0x3ff;
  uint32_t u;
  if (exp == 0) {
    if (man == 0) {
      u = sign;
    } else {
      exp = 127 - 15 + 1;
      while ((man & 0x400) == 0) {
        man <<= 1;
        exp--;
      }
      man &= 0x3ff;
      u = sign | (exp << 23) | (man << 13);
    }
  } else if (exp == 0x1f) {
    u = sign | 0x7f800000 | (man << 13);
  } else {
    u = sign | ((exp - 15 + 127) << 23) | (man << 13);
  }
  float f;
  memcpy(&f, &u, 4);
  return f;
}

inline uint16_t F32ToF16(float f) {
  uint32_t u;
  memcpy(&u, &f, 4);
  uint32_t sign = (u >> 16) & 0x8000;
  int32_t bexp = (u >> 23) & 0xff;
  uint32_t man = u & 0x7fffff;
  if (bexp == 0xff)  // preserve NaN (quiet) vs Inf
    return (uint16_t)(sign | 0x7c00 | (man ? 0x200 : 0));
  int32_t e = bexp - 127 + 15;
  if (e >= 0x1f) return (uint16_t)(sign | 0x7c00);  // overflow -> inf
  if (e <= 0) {
    if (e < -10) return (uint16_t)sign;  // underflow -> signed zero
    man |= 0x800000;                     // implicit leading 1
    uint32_t shift = 14 - e;
    uint16_t val = (uint16_t)(man >> shift);
    if ((man >> (shift - 1)) & 1) val++;  // round to nearest
    return (uint16_t)(sign | val);
  }
  uint16_t h = (uint16_t)(sign | (e << 10) | (man >> 13));
  if (man & 0x1000) h++;  // round to nearest; mantissa carry bumps exponent
  return h;
}

template <typename T>
void AccumTyped(T* dst, const T* src, int64_t n, ReduceOp op) {
  switch (op) {
    case ReduceOp::kSum:
    case ReduceOp::kAverage:
    case ReduceOp::kAdasum:
      for (int64_t i = 0; i < n; ++i) dst[i] += src[i];
      break;
    case ReduceOp::kMin:
      for (int64_t i = 0; i < n; ++i) dst[i] = std::min(dst[i], src[i]);
      break;
    case ReduceOp::kMax:
      for (int64_t i = 0; i < n; ++i) dst[i] = std::max(dst[i], src[i]);
      break;
    case ReduceOp::kProduct:
      for (int64_t i = 0; i < n; ++i) dst[i] *= src[i];
      break;
  }
}

template <typename T, typename CvtIn, typename CvtOut>
void Accum16(uint16_t* dst, const uint16_t* src, int64_t n, ReduceOp op,
             CvtIn in, CvtOut out) {
  for (int64_t i = 0; i < n; ++i) {
    float a = in(dst[i]), b = in(src[i]), r;
    switch (op) {
      case ReduceOp::kMin: r = std::min(a, b); break;
      case ReduceOp::kMax: r = std::max(a, b); break;
      case ReduceOp::kProduct: r = a * b; break;
      default: r = a + b; break;
    }
    dst[i] = out(r);
  }
}

void Accumulate(void* dst, const void* src, int64_t n, DataType dt,
                ReduceOp op) {
  switch (dt) {
    case DataType::kFloat32:
      AccumTyped((float*)dst, (const float*)src, n, op);
      break;
    case DataType::kFloat64:
      AccumTyped((double*)dst, (const double*)src, n, op);
      break;
    case DataType::kInt32:
      AccumTyped((int32_t*)dst, (const int32_t*)src, n, op);
      break;
    case DataType::kInt64:
      AccumTyped((int64_t*)dst, (const int64_t*)src, n, op);
      break;
    case DataType::kUint8:
      AccumTyped((uint8_t*)dst, (const uint8_t*)src, n, op);
      break;
    case DataType::kInt8:
      AccumTyped((int8_t*)dst, (const int8_t*)src, n, op);
      break;
    case DataType::kBFloat16:
      Accum16<uint16_t>((uint16_t*)dst, (const uint16_t*)src, n, op,
                        Bf16ToF32, F32ToBf16);
      break;
    case DataType::kFloat16:
      Accum16<uint16_t>((uint16_t*)dst, (const uint16_t*)src, n, op,
                        F16ToF32, F32ToF16);
      break;
    case DataType::kBool:
      AccumTyped((uint8_t*)dst, (const uint8_t*)src, n, op);
      break;
  }
}

void ScaleBuffer(void* data, int64_t n, DataType dt, double factor) {
  if (factor == 1.0) return;
  switch (dt) {
    case DataType::kFloat32: {
      auto* p = (float*)data;
      for (int64_t i = 0; i < n; ++i) p[i] = (float)(p[i] * factor);
      break;
    }
    case DataType::kFloat64: {
      auto* p = (double*)data;
      for (int64_t i = 0; i < n; ++i) p[i] *= factor;
      break;
    }
    case DataType::kBFloat16: {
      auto* p = (uint16_t*)data;
      for (int64_t i = 0; i < n; ++i)
        p[i] = F32ToBf16((float)(Bf16ToF32(p[i]) * factor));
      break;
    }
    case DataType::kFloat16: {
      auto* p = (uint16_t*)data;
      for (int64_t i = 0; i < n; ++i)
        p[i] = F32ToF16((float)(F16ToF32(p[i]) * factor));
      break;
    }
    case DataType::kInt32: {
      auto* p = (int32_t*)data;
      for (int64_t i = 0; i < n; ++i) p[i] = (int32_t)(p[i] * factor);
      break;
    }
    case DataType::kInt64: {
      auto* p = (int64_t*)data;
      for (int64_t i = 0; i < n; ++i) p[i] = (int64_t)(p[i] * factor);
      break;
    }
    default:
      break;
  }
}

}  // namespace

void ScaleBufferOp(void* data, int64_t n, DataType dt, double factor) {
  ScaleBuffer(data, n, dt, factor);
}

Status RingAllreduce(Transport& t, const Group& g, int32_t tag, void* data,
                     int64_t nelem, DataType dtype, ReduceOp op,
                     double prescale, double postscale) {
  int size = g.size();
  int me = g.my_index;
  ScaleBuffer(data, nelem, dtype, prescale);
  if (size > 1 && nelem > 0) {
    size_t esz = DataTypeSize(dtype);
    // chunk boundaries
    std::vector<int64_t> starts(size + 1);
    for (int i = 0; i <= size; ++i) starts[i] = nelem * i / size;
    auto chunk_ptr = [&](int c) {
      return (uint8_t*)data + starts[c] * esz;
    };
    auto chunk_n = [&](int c) { return starts[c + 1] - starts[c]; };
    int right = g.global((me + 1) % size);
    int left = g.global((me - 1 + size) % size);
    std::vector<uint8_t> recvbuf;
    // phase 1: reduce-scatter (size-1 steps)
    for (int step = 0; step < size - 1; ++step) {
      int send_c = (me - step + size) % size;
      int recv_c = (me - step - 1 + size) % size;
      auto st = t.Send(right, tag, chunk_ptr(send_c), chunk_n(send_c) * esz);
      if (!st.ok()) return st;
      st = t.Recv(left, tag, &recvbuf);
      if (!st.ok()) return st;
      Accumulate(chunk_ptr(recv_c), recvbuf.data(), chunk_n(recv_c), dtype,
                 op);
    }
    // phase 2: allgather (size-1 steps)
    for (int step = 0; step < size - 1; ++step) {
      int send_c = (me + 1 - step + size) % size;
      int recv_c = (me - step + size) % size;
      auto st = t.Send(right, tag, chunk_ptr(send_c), chunk_n(send_c) * esz);
      if (!st.ok()) return st;
      st = t.Recv(left, tag, &recvbuf);
      if (!st.ok()) return st;
      memcpy(chunk_ptr(recv_c), recvbuf.data(), chunk_n(recv_c) * esz);
    }
  }
  if (op == ReduceOp::kAverage)
    ScaleBuffer(data, nelem, dtype, 1.0 / size);
  ScaleBuffer(data, nelem, dtype, postscale);
  return Status::OK();
}

Status HierarchicalAllreduce(Transport& t, const Group& local,
                             const Group& cross, bool is_leader, int32_t tag,
                             void* data, int64_t nelem, DataType dtype,
                             ReduceOp op, double prescale, double postscale) {
  ScaleBuffer(data, nelem, dtype, prescale);
  size_t esz = DataTypeSize(dtype);
  // 1) intra-host reduce to the local leader (local index 0)
  if (local.size() > 1) {
    if (local.my_index == 0) {
      std::vector<uint8_t> buf;
      for (int i = 1; i < local.size(); ++i) {
        auto st = t.Recv(local.global(i), tag, &buf);
        if (!st.ok()) return st;
        Accumulate(data, buf.data(), nelem, dtype, op);
      }
    } else {
      auto st = t.Send(local.global(0), tag, data, nelem * esz);
      if (!st.ok()) return st;
    }
  }
  // 2) cross-host ring among leaders
  if (is_leader && cross.size() > 1) {
    auto st = RingAllreduce(t, cross, tag + 1, data, nelem, dtype,
                            op == ReduceOp::kAverage ? ReduceOp::kSum : op,
                            1.0, 1.0);
    if (!st.ok()) return st;
  }
  // 3) intra-host broadcast of the result
  if (local.size() > 1) {
    auto st = Broadcast(t, local, tag + 2, data, nelem * esz, 0);
    if (!st.ok()) return st;
  }
  if (op == ReduceOp::kAverage) {
    int total = local.size() * std::max(cross.size(), 1);
    ScaleBuffer(data, nelem, dtype, 1.0 / total);
  }
  ScaleBuffer(data, nelem, dtype, postscale);
  return Status::OK();
}

Status HierarchicalAllgatherV(Transport& t, const Group& local,
                              const Group& cross, bool is_leader,
                              int32_t tag, const void* send,
                              int64_t send_bytes,
                              std::vector<int64_t>* per_rank_bytes,
                              std::vector<uint8_t>* out) {
  int lsz = local.size(), csz = cross.size();
  int n_global = lsz * csz;
  if (!is_leader) {
    // 1) hand our block to the node leader (size prefix + data)...
    std::vector<uint8_t> pkt(sizeof(int64_t) + (size_t)send_bytes);
    memcpy(pkt.data(), &send_bytes, sizeof(int64_t));
    if (send_bytes > 0)
      memcpy(pkt.data() + sizeof(int64_t), send, send_bytes);
    auto st = t.Send(local.global(0), tag, pkt.data(), pkt.size());
    if (!st.ok()) return st;
    // ...then wait for the leader's fan-out: [n_global sizes][all data]
    std::vector<uint8_t> sizes_buf((size_t)n_global * sizeof(int64_t));
    st = Broadcast(t, local, tag + 3, sizes_buf.data(),
                   (int64_t)sizes_buf.size(), 0);
    if (!st.ok()) return st;
    per_rank_bytes->assign(n_global, 0);
    memcpy(per_rank_bytes->data(), sizes_buf.data(), sizes_buf.size());
    int64_t total = 0;
    for (auto b : *per_rank_bytes) total += b;
    out->resize((size_t)total);
    return Broadcast(t, local, tag + 4, out->data(), total, 0);
  }
  // leader: 1) gather local blocks in local-rank order
  std::vector<int64_t> local_sizes(lsz, 0);
  std::vector<std::vector<uint8_t>> local_blocks(lsz);
  local_sizes[0] = send_bytes;
  int64_t host_total = send_bytes;
  for (int i = 1; i < lsz; ++i) {
    std::vector<uint8_t> pkt;
    auto st = t.Recv(local.global(i), tag, &pkt);
    if (!st.ok()) return st;
    memcpy(&local_sizes[i], pkt.data(), sizeof(int64_t));
    local_blocks[i].assign(pkt.begin() + sizeof(int64_t), pkt.end());
    host_total += local_sizes[i];
  }
  // host concat: [sizes of my lsz ranks][their data in local-rank order]
  std::vector<uint8_t> host((size_t)lsz * sizeof(int64_t) +
                            (size_t)host_total);
  memcpy(host.data(), local_sizes.data(), (size_t)lsz * sizeof(int64_t));
  int64_t off = (int64_t)lsz * sizeof(int64_t);
  if (send_bytes > 0) memcpy(host.data() + off, send, send_bytes);
  off += send_bytes;
  for (int i = 1; i < lsz; ++i) {
    memcpy(host.data() + off, local_blocks[i].data(), local_sizes[i]);
    off += local_sizes[i];
  }
  // 2) leaders exchange host blocks; [cross][local] order IS global rank
  // order under the launcher's homogeneous topology contract
  std::vector<int64_t> per_host;
  std::vector<uint8_t> gathered;
  auto st = AllgatherV(t, cross, tag + 1, host.data(), (int64_t)host.size(),
                       &per_host, &gathered);
  if (!st.ok()) return st;
  per_rank_bytes->assign(n_global, 0);
  int64_t total = 0;
  {
    int64_t goff = 0;
    for (int c = 0; c < csz; ++c) {
      memcpy(per_rank_bytes->data() + (size_t)c * lsz,
             gathered.data() + goff, (size_t)lsz * sizeof(int64_t));
      goff += per_host[c];
    }
    for (auto b : *per_rank_bytes) total += b;
  }
  out->clear();
  out->reserve((size_t)total);
  {
    int64_t goff = 0;
    for (int c = 0; c < csz; ++c) {
      const uint8_t* data0 = gathered.data() + goff +
                             (int64_t)lsz * sizeof(int64_t);
      int64_t data_bytes = per_host[c] - (int64_t)lsz * sizeof(int64_t);
      out->insert(out->end(), data0, data0 + data_bytes);
      goff += per_host[c];
    }
  }
  // 3) fan the result out to local members
  if (lsz > 1) {
    std::vector<uint8_t> sizes_buf((size_t)n_global * sizeof(int64_t));
    memcpy(sizes_buf.data(), per_rank_bytes->data(), sizes_buf.size());
    st = Broadcast(t, local, tag + 3, sizes_buf.data(),
                   (int64_t)sizes_buf.size(), 0);
    if (!st.ok()) return st;
    st = Broadcast(t, local, tag + 4, out->data(), total, 0);
    if (!st.ok()) return st;
  }
  return Status::OK();
}

Status AllgatherV(Transport& t, const Group& g, int32_t tag,
                  const void* send, int64_t send_bytes,
                  std::vector<int64_t>* per_rank_bytes,
                  std::vector<uint8_t>* out) {
  int size = g.size();
  int me = g.my_index;
  per_rank_bytes->assign(size, 0);
  (*per_rank_bytes)[me] = send_bytes;
  // exchange sizes (pairwise)
  for (int i = 0; i < size; ++i) {
    if (i == me) continue;
    auto st = t.Send(g.global(i), tag, &send_bytes, sizeof(int64_t));
    if (!st.ok()) return st;
  }
  for (int i = 0; i < size; ++i) {
    if (i == me) continue;
    std::vector<uint8_t> buf;
    auto st = t.Recv(g.global(i), tag, &buf);
    if (!st.ok()) return st;
    memcpy(&(*per_rank_bytes)[i], buf.data(), sizeof(int64_t));
  }
  int64_t total = 0;
  std::vector<int64_t> offs(size);
  for (int i = 0; i < size; ++i) {
    offs[i] = total;
    total += (*per_rank_bytes)[i];
  }
  out->resize(total);
  memcpy(out->data() + offs[me], send, send_bytes);
  for (int i = 0; i < size; ++i) {
    if (i == me) continue;
    auto st = t.Send(g.global(i), tag + 1, send, send_bytes);
    if (!st.ok()) return st;
  }
  for (int i = 0; i < size; ++i) {
    if (i == me) continue;
    std::vector<uint8_t> buf;
    auto st = t.Recv(g.global(i), tag + 1, &buf);
    if (!st.ok()) return st;
    memcpy(out->data() + offs[i], buf.data(), buf.size());
  }
  return Status::OK();
}

Status Broadcast(Transport& t, const Group& g, int32_t tag, void* data,
                 int64_t nbytes, int root_index) {
  int size = g.size();
  int me = g.my_index;
  // binomial tree rooted at root_index (rotate indices)
  int vrank = (me - root_index + size) % size;
  int mask = 1;
  while (mask < size) {
    if (vrank < mask) {
      int vpeer = vrank + mask;
      if (vpeer < size) {
        int peer = g.global((vpeer + root_index) % size);
        auto st = t.Send(peer, tag, data, nbytes);
        if (!st.ok()) return st;
      }
    } else if (vrank < 2 * mask) {
      int vpeer = vrank - mask;
      int peer = g.global((vpeer + root_index) % size);
      std::vector<uint8_t> buf;
      auto st = t.Recv(peer, tag, &buf);
      if (!st.ok()) return st;
      memcpy(data, buf.data(), std::min((int64_t)buf.size(), nbytes));
    }
    mask <<= 1;
  }
  return Status::OK();
}

Status AlltoallV(Transport& t, const Group& g, int32_t tag, const void* send,
                 const std::vector<int64_t>& splits, int64_t row_bytes,
                 std::vector<int64_t>* recv_splits,
                 std::vector<uint8_t>* out) {
  int size = g.size();
  int me = g.my_index;
  if ((int)splits.size() != size)
    return Status::Error("alltoall splits must have one entry per rank");
  recv_splits->assign(size, 0);
  (*recv_splits)[me] = splits[me];
  for (int i = 0; i < size; ++i) {
    if (i == me) continue;
    auto st = t.Send(g.global(i), tag, &splits[i], sizeof(int64_t));
    if (!st.ok()) return st;
  }
  for (int i = 0; i < size; ++i) {
    if (i == me) continue;
    std::vector<uint8_t> buf;
    auto st = t.Recv(g.global(i), tag, &buf);
    if (!st.ok()) return st;
    memcpy(&(*recv_splits)[i], buf.data(), sizeof(int64_t));
  }
  std::vector<int64_t> send_offs(size), recv_offs(size);
  int64_t so = 0, ro = 0;
  for (int i = 0; i < size; ++i) {
    send_offs[i] = so;
    so += splits[i] * row_bytes;
    recv_offs[i] = ro;
    ro += (*recv_splits)[i] * row_bytes;
  }
  out->resize(ro);
  memcpy(out->data() + recv_offs[me], (const uint8_t*)send + send_offs[me],
         splits[me] * row_bytes);
  for (int i = 0; i < size; ++i) {
    if (i == me) continue;
    auto st = t.Send(g.global(i), tag + 1,
                     (const uint8_t*)send + send_offs[i],
                     splits[i] * row_bytes);
    if (!st.ok()) return st;
  }
  for (int i = 0; i < size; ++i) {
    if (i == me) continue;
    std::vector<uint8_t> buf;
    auto st = t.Recv(g.global(i), tag + 1, &buf);
    if (!st.ok()) return st;
    memcpy(out->data() + recv_offs[i], buf.data(), buf.size());
  }
  return Status::OK();
}

Status Barrier(Transport& t, const Group& g, int32_t tag) {
  uint8_t b = 1;
  std::vector<uint8_t> bits(1, 1);
  return BitvectorAnd(t, g, tag, &bits);
  (void)b;
}

static Status BitvectorOp(Transport& t, const Group& g, int32_t tag,
                          std::vector<uint8_t>* bits, bool is_and) {
  // gather to group root (index 0), combine, broadcast back
  int me = g.my_index;
  if (me == 0) {
    for (int i = 1; i < g.size(); ++i) {
      std::vector<uint8_t> buf;
      auto st = t.Recv(g.global(i), tag, &buf);
      if (!st.ok()) return st;
      for (size_t j = 0; j < bits->size() && j < buf.size(); ++j) {
        if (is_and)
          (*bits)[j] &= buf[j];
        else
          (*bits)[j] |= buf[j];
      }
    }
    for (int i = 1; i < g.size(); ++i) {
      auto st = t.Send(g.global(i), tag + 1, bits->data(), bits->size());
      if (!st.ok()) return st;
    }
  } else {
    auto st = t.Send(g.global(0), tag, bits->data(), bits->size());
    if (!st.ok()) return st;
    std::vector<uint8_t> buf;
    st = t.Recv(g.global(0), tag + 1, &buf);
    if (!st.ok()) return st;
    *bits = std::move(buf);
  }
  return Status::OK();
}

Status BitvectorAnd(Transport& t, const Group& g, int32_t tag,
                    std::vector<uint8_t>* bits) {
  return BitvectorOp(t, g, tag, bits, true);
}

Status BitvectorOr(Transport& t, const Group& g, int32_t tag,
                   std::vector<uint8_t>* bits) {
  return BitvectorOp(t, g, tag, bits, false);
}

}  // namespace hvd
