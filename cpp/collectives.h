// Host CPU collectives over the TCP transport — the framework's
// "Gloo-class" reference data plane (reference: horovod/common/ops/
// gloo_operations.cc + mpi_operations.cc). Ring allreduce (reduce-scatter +
// allgather phases, the same algorithm NCCL/Gloo rings implement),
// allgatherv, broadcast, alltoallv, barrier. On TPU pods the hot data plane
// is XLA collectives; this one serves CPU testing, host-side state sync and
// the control plane.
#pragma once

#include <cstdint>
#include <vector>

#include "transport.h"
#include "types.h"

namespace hvd {

// A communicator over a subset of global ranks (reference: sub-communicator
// per process set, horovod/common/process_set.h).
struct Group {
  std::vector<int> ranks;  // global ranks, sorted
  int my_index = 0;        // position of this process in `ranks`

  int size() const { return (int)ranks.size(); }
  int global(int idx) const { return ranks[idx]; }
};

Status RingAllreduce(Transport& t, const Group& g, int32_t tag, void* data,
                     int64_t nelem, DataType dtype, ReduceOp op,
                     double prescale, double postscale);

// Gather variable-size row blocks from every rank, concatenated in rank
// order. send_bytes must be a multiple of row_bytes.
Status AllgatherV(Transport& t, const Group& g, int32_t tag,
                  const void* send, int64_t send_bytes,
                  std::vector<int64_t>* per_rank_bytes,
                  std::vector<uint8_t>* out);

Status Broadcast(Transport& t, const Group& g, int32_t tag, void* data,
                 int64_t nbytes, int root_index);

// splits[i] = rows this rank sends to group index i. Returns received
// buffer (rank-order concat) and recv_splits.
Status AlltoallV(Transport& t, const Group& g, int32_t tag, const void* send,
                 const std::vector<int64_t>& splits, int64_t row_bytes,
                 std::vector<int64_t>* recv_splits,
                 std::vector<uint8_t>* out);

Status Barrier(Transport& t, const Group& g, int32_t tag);

// Bitwise AND/OR across ranks (for the response-cache coordinator,
// reference: response_cache.h CacheCoordinator bitvector sync).
Status BitvectorAnd(Transport& t, const Group& g, int32_t tag,
                    std::vector<uint8_t>* bits);
Status BitvectorOr(Transport& t, const Group& g, int32_t tag,
                   std::vector<uint8_t>* bits);

// Two-level hierarchical allreduce (reference:
// NCCLHierarchicalAllreduce, nccl_operations.cc:233-420: intra-node
// reduce to a leader, inter-node allreduce among leaders, intra-node
// broadcast). Groups are derived from launcher-injected local/cross
// topology. Uses tags [tag, tag+2].
Status HierarchicalAllreduce(Transport& t, const Group& local,
                             const Group& cross, bool is_leader, int32_t tag,
                             void* data, int64_t nelem, DataType dtype,
                             ReduceOp op, double prescale, double postscale);

// Two-level hierarchical allgatherv (reference: MPIHierarchicalAllgather,
// horovod/common/ops/mpi_operations.cc — node-leader gather + shared
// buffer fan-out; here the fan-out is a local binomial broadcast):
// (1) local members send their block to the node leader, (2) leaders
// allgatherv their hosts' concatenations cross-host (global rank order ==
// [cross][local] by the launcher's topology contract), (3) leaders
// broadcast sizes + data locally. Uses tags [tag, tag+4]. Requires the
// homogeneous topology the launcher injects (size == local*cross).
Status HierarchicalAllgatherV(Transport& t, const Group& local,
                              const Group& cross, bool is_leader,
                              int32_t tag, const void* send,
                              int64_t send_bytes,
                              std::vector<int64_t>* per_rank_bytes,
                              std::vector<uint8_t>* out);

// Adasum VHDD allreduce (cpp/adasum.cc; reference: adasum/adasum.h).
// Uses tags [tag, tag+4].
Status AdasumAllreduce(Transport& t, const Group& g, int32_t tag, void* data,
                       int64_t nelem, DataType dtype);

// Elementwise in-place scale (fp paths; exposed for the Adasum pre/post
// scaling in the engine).
void ScaleBufferOp(void* data, int64_t n, DataType dt, double factor);

}  // namespace hvd
