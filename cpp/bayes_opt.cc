#include "bayes_opt.h"

#include <algorithm>
#include <cmath>

namespace hvd {

double GaussianProcess::Kernel(const std::vector<double>& a,
                               const std::vector<double>& b) const {
  double d2 = 0;
  for (size_t i = 0; i < a.size(); ++i) {
    double d = a[i] - b[i];
    d2 += d * d;
  }
  return signal_var_ * std::exp(-d2 / (2 * length_scale_ * length_scale_));
}

void GaussianProcess::Fit(const std::vector<std::vector<double>>& x,
                          const std::vector<double>& y, double noise) {
  x_ = x;
  int n = (int)x.size();
  mean_ = 0;
  for (double v : y) mean_ += v;
  mean_ /= std::max(n, 1);
  // normalize signal variance to data variance
  double var = 0;
  for (double v : y) var += (v - mean_) * (v - mean_);
  signal_var_ = n > 1 ? std::max(var / (n - 1), 1e-12) : 1.0;

  // K + noise*I, Cholesky factorization (reference: gaussian_process.cc)
  std::vector<std::vector<double>> K(n, std::vector<double>(n));
  for (int i = 0; i < n; ++i)
    for (int j = 0; j < n; ++j)
      K[i][j] = Kernel(x[i], x[j]) + (i == j ? noise * signal_var_ : 0.0);
  chol_.assign(n, std::vector<double>(n, 0.0));
  for (int i = 0; i < n; ++i) {
    for (int j = 0; j <= i; ++j) {
      double s = K[i][j];
      for (int k = 0; k < j; ++k) s -= chol_[i][k] * chol_[j][k];
      if (i == j)
        chol_[i][i] = std::sqrt(std::max(s, 1e-12));
      else
        chol_[i][j] = s / chol_[j][j];
    }
  }
  // alpha = K^-1 (y - mean) via forward/back substitution
  std::vector<double> z(n);
  for (int i = 0; i < n; ++i) {
    double s = y[i] - mean_;
    for (int k = 0; k < i; ++k) s -= chol_[i][k] * z[k];
    z[i] = s / chol_[i][i];
  }
  alpha_.assign(n, 0.0);
  for (int i = n - 1; i >= 0; --i) {
    double s = z[i];
    for (int k = i + 1; k < n; ++k) s -= chol_[k][i] * alpha_[k];
    alpha_[i] = s / chol_[i][i];
  }
}

void GaussianProcess::Predict(const std::vector<double>& xs, double* mu,
                              double* var) const {
  int n = (int)x_.size();
  if (n == 0) {
    *mu = mean_;
    *var = signal_var_;
    return;
  }
  std::vector<double> k(n);
  for (int i = 0; i < n; ++i) k[i] = Kernel(xs, x_[i]);
  double m = mean_;
  for (int i = 0; i < n; ++i) m += k[i] * alpha_[i];
  // v = L^-1 k ; var = k(x,x) - v.v
  std::vector<double> v(n);
  for (int i = 0; i < n; ++i) {
    double s = k[i];
    for (int j = 0; j < i; ++j) s -= chol_[i][j] * v[j];
    v[i] = s / chol_[i][i];
  }
  double vv = 0;
  for (int i = 0; i < n; ++i) vv += v[i] * v[i];
  *mu = m;
  *var = std::max(Kernel(xs, xs) - vv, 1e-12);
}

BayesianOptimizer::BayesianOptimizer(int dims, uint64_t seed,
                                     double gp_noise)
    : dims_(dims), rng_(seed), gp_noise_(gp_noise) {}

void BayesianOptimizer::AddSample(const std::vector<double>& x, double y) {
  x_.push_back(x);
  y_.push_back(y);
}

std::vector<double> BayesianOptimizer::BestSample() const {
  if (y_.empty()) return std::vector<double>(dims_, 0.5);
  size_t best = 0;
  for (size_t i = 1; i < y_.size(); ++i)
    if (y_[i] > y_[best]) best = i;
  return x_[best];
}

std::vector<double> BayesianOptimizer::NextSample() {
  std::uniform_real_distribution<double> u(0.0, 1.0);
  if (y_.size() < 3) {  // pure exploration until the GP has something
    std::vector<double> x(dims_);
    for (auto& v : x) v = u(rng_);
    return x;
  }
  GaussianProcess gp;
  gp.Fit(x_, y_, gp_noise_);
  double best_y = *std::max_element(y_.begin(), y_.end());
  // expected improvement (reference: bayesian_optimization.cc EI), argmax
  // over random candidates
  std::vector<double> best_x(dims_, 0.5);
  double best_ei = -1;
  const double xi = 0.01;
  for (int c = 0; c < 256; ++c) {
    std::vector<double> x(dims_);
    for (auto& v : x) v = u(rng_);
    double mu, var;
    gp.Predict(x, &mu, &var);
    double sigma = std::sqrt(var);
    double imp = mu - best_y - xi;
    double zz = imp / sigma;
    // EI = imp*Phi(z) + sigma*phi(z)
    double Phi = 0.5 * std::erfc(-zz / std::sqrt(2.0));
    double phi = std::exp(-0.5 * zz * zz) / std::sqrt(2 * M_PI);
    double ei = imp * Phi + sigma * phi;
    if (ei > best_ei) {
      best_ei = ei;
      best_x = x;
    }
  }
  return best_x;
}

}  // namespace hvd
