// The hvdcore engine: background coordination thread, tensor queue,
// coordinator-worker negotiation, response cache, fusion, stall inspection,
// autotuning, timeline.
//
// TPU-native re-design of the reference core (horovod/common/operations.cc
// BackgroundThreadLoop/RunLoopOnce, controller.cc ComputeResponseList,
// tensor_queue.h, response_cache.h, fusion_buffer_manager.h,
// stall_inspector.h, parameter_manager.h). The data plane here is host TCP
// (cpp/collectives.h); on TPU pods the per-chip data plane stays in XLA and
// this engine provides ordering/negotiation for eager multi-process ops.
#pragma once

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <list>
#include <map>
#include <memory>
#include <set>
#include <thread>
#include <unordered_map>

#include "collectives.h"
#include "transport.h"
#include "types.h"

namespace hvd {

// Thread-safe queue of pending submissions (reference:
// horovod/common/tensor_queue.h:28-64).
class TensorQueue {
 public:
  // false if an entry with the same name is already in flight
  bool Push(TensorTableEntry entry, Request req);
  // Pop all pending requests this cycle.
  std::vector<Request> PopRequests();
  bool Take(const std::string& name, TensorTableEntry* out);
  void FinalizeAllWithError(const Status& s);
  size_t pending() const;

 private:
  mutable std::mutex mu_;
  std::deque<Request> requests_;
  std::unordered_map<std::string, TensorTableEntry> table_;
};

// LRU cache of Responses keyed by request signature (reference:
// horovod/common/response_cache.h:45-102). A hit means every rank already
// agreed on this exact op before — skip negotiation, just bitvector-AND
// the hit sets each cycle.
//
// LRU discipline: recency is updated ONLY at coordinated points (Insert
// and Touch while processing the broadcast response list), never from the
// rank-local Lookup — so the eviction sequence is identical on every rank
// and bit spaces stay aligned without explicit invalidation messages. When
// a full cache evicts, the freed bit is reused for the new entry; the
// coordinator migrates any pending bit announcements for the evicted
// entry back into full-request negotiation (see Core::RunOnce).
class ResponseCache {
 public:
  explicit ResponseCache(size_t capacity) : capacity_(capacity) {}
  static std::string Key(const Request& r);
  // returns bit position, or -1 if not cached
  int Lookup(const std::string& key) const;
  // Insert, evicting the least-recently-used entry when full (reference:
  // response_cache.cc put() eviction). Returns the bit used; if an
  // eviction happened, *evicted holds the displaced Response and
  // *did_evict is set so the coordinator can migrate pending bits.
  int Insert(const std::string& key, const Response& resp,
             Response* evicted = nullptr, bool* did_evict = nullptr);
  // move a bit to most-recently-used; call only at coordinated points
  void Touch(int bit);
  const Response& Get(int bit) const;
  size_t size() const { return entries_.size(); }
  uint64_t evictions() const { return evictions_; }

 private:
  size_t capacity_;
  std::vector<std::pair<std::string, Response>> entries_;  // bit -> entry
  std::unordered_map<std::string, int> index_;
  std::list<int> lru_;  // front = most recent
  std::unordered_map<int, std::list<int>::iterator> lru_pos_;
  uint64_t evictions_ = 0;
};

// Stall detection (reference: horovod/common/stall_inspector.h:30-99).
class StallInspector {
 public:
  void RecordPending(const std::string& name, const std::vector<int>& ranks,
                     int size);
  void RemoveReady(const std::string& name);
  // returns warning string if stalled tensors exist past the threshold;
  // newly_warned counts tensors first warned about this call (feeds the
  // hvd_stall_warnings_total counter), currently_stalled the tensors
  // past the threshold right now (feeds the stalled-tensor gauge)
  std::string Check(double warn_seconds, int* newly_warned = nullptr,
                    int* currently_stalled = nullptr);
  // snapshot of every pending (not-yet-ready-everywhere) tensor, for
  // the engine-state autopsy JSON (hvd_engine_state_json)
  struct PendingEntry {
    std::string name;
    double waited_s;
    std::vector<int> ready_ranks;
  };
  std::vector<PendingEntry> Pending() const;
  // names stalled past the (stricter) shutdown threshold; caller errors
  // them out (reference: STALL_SHUTDOWN_TIME aborts, stall_inspector.h)
  std::vector<std::string> FatallyStalled(double shutdown_seconds);

 private:
  struct Info {
    std::chrono::steady_clock::time_point first_seen;
    std::vector<int> ready_ranks;
    bool warned = false;
  };
  std::map<std::string, Info> pending_;
};

// Online autotune of cycle time & fusion threshold (reference:
// horovod/common/parameter_manager.h driving the GP/EI Bayesian optimizer
// in optim/bayesian_optimization.cc — same design in cpp/bayes_opt.{h,cc}).
// Coordinator-only; the chosen fusion threshold is broadcast with each
// response list so fusion grouping stays rank-identical.
class BayesianOptimizer;

class ParameterManager {
 public:
  ~ParameterManager() {
    if (log_) fclose(log_);
  }
  void Enable(int64_t init_fusion, double init_cycle,
              int warmup_samples = 3, int max_samples = 24,
              double gp_noise = 1e-6, const std::string& log_path = "",
              double window_secs = 2.0, bool allow_hier = false);
  bool enabled() const { return enabled_; }
  void Record(int64_t bytes);
  // maybe update params; returns true if changed. Categorical dims
  // (reference tunes these too — parameter_manager.h:42-105): the GP
  // searches a 4-D space (log fusion, log cycle, hierarchical on/off,
  // cache on/off); binary dims threshold at 0.5. hier candidates are
  // clamped off unless the topology supports the two-level path.
  bool Tune(int64_t* fusion_bytes, double* cycle_ms, bool* hierarchical,
            bool* cache_enabled);

 private:
  bool enabled_ = false;
  bool allow_hier_ = false;
  int64_t bytes_acc_ = 0;
  std::chrono::steady_clock::time_point window_start_;
  int samples_ = 0;
  int warmup_samples_ = 3;
  int max_samples_ = 24;
  double gp_noise_ = 1e-6;
  double window_secs_ = 2.0;
  FILE* log_ = nullptr;
  std::shared_ptr<BayesianOptimizer> bo_;
};

struct CoreConfig {
  int rank = 0;
  bool disable_group_fusion = false;
  bool hierarchical_allreduce = false;
  bool hierarchical_allgather = false;
  int local_rank = 0;
  int local_size = 1;
  int cross_rank = 0;
  int cross_size = 1;
  int size = 1;
  std::string coord_addr = "127.0.0.1";
  int coord_port = 37592;
  int64_t fusion_threshold = 64 * 1024 * 1024;
  double cycle_time_ms = 1.0;
  size_t cache_capacity = 1024;
  bool cache_enabled = true;
  double stall_warning_secs = 60.0;
  // > 0: fatally stalled tensors are errored out to their waiters
  // (reference: HOROVOD_STALL_SHUTDOWN_TIME_SECONDS shuts the job down;
  // here the error surfaces as HorovodInternalError so elastic can react)
  double stall_shutdown_secs = 0.0;
  bool autotune = false;
  // reference semantics: discard this many scoring samples before the
  // optimizer starts learning (AUTOTUNE_WARMUP_SAMPLES)
  int autotune_warmup_samples = 3;
  int autotune_max_samples = 24;       // BAYES_OPT_MAX_SAMPLES analog
  double autotune_gp_noise = 1e-6;     // GAUSSIAN_PROCESS_NOISE analog
  double autotune_window_secs = 2.0;   // scoring window per sample
  std::string autotune_log;            // AUTOTUNE_LOG sample trace file
  double rendezvous_timeout_secs = 30.0;  // GLOO_TIMEOUT_SECONDS analog
  // > 0: inactivity deadline on transport receives — a dead-but-connected
  // peer surfaces as a collective error (-> HorovodInternalError, feeding
  // elastic recovery) instead of an infinite recv
  // (HVD_TPU_TRANSPORT_TIMEOUT_S; docs/CHAOS.md)
  double transport_timeout_secs = 0.0;
  // CRC32C every eager-wire frame; a mismatch names the peer and fails
  // the affected collectives (HVD_TPU_WIRE_CHECKSUM, default on —
  // docs/CHAOS.md "Wire integrity"). Must be uniform across the world.
  bool wire_checksum = true;
  // > 0: the coordinator logs a rank-attributed negotiation-wait summary
  // every this many seconds (HVD_TPU_STRAGGLER_REPORT_SECONDS); the
  // snapshot is queryable via hvd_stragglers_json either way
  double straggler_report_secs = 0.0;
  int thread_affinity = -1;            // pin background loop to this CPU
  bool timeline_mark_cycles = false;
  std::string timeline_path;
};

class Timeline;

// One coordination domain (global or a process set); owns queue + group
// (reference: horovod/common/process_set.h:26-81).
struct CoordDomain {
  int id = 0;
  Group group;
  TensorQueue queue;
  std::unique_ptr<ResponseCache> cache;
  StallInspector stall;
  // Process-set lifecycle: added sets are INACTIVE (no lockstep traffic)
  // until the domain-0 coordinator confirms every rank registered them
  // (deadlock-free dynamic registration; reference operations.cc:587-623).
  bool active = true;
  bool retiring = false;
  bool inactive_warned = false;
  std::chrono::steady_clock::time_point registered_at;
  bool joined = false;             // this rank has submitted Join
  int join_count = 0;              // coordinator: ranks joined (cumulative)
  std::vector<bool> joined_ranks;
  // coordinator negotiation state: name -> set of ready ranks
  std::unordered_map<std::string, std::pair<Request, std::vector<int>>>
      ready_table_;
  // coordinator: cache-bit -> ranks that hit it this steady-state round
  std::unordered_map<int, std::vector<int>> bit_ready_;
  // coordinator: first-announcement stamps feeding straggler attribution
  // (wait = last announce - first announce, charged to the last rank)
  std::unordered_map<std::string, std::chrono::steady_clock::time_point>
      announce_time_;
  std::unordered_map<int, std::chrono::steady_clock::time_point> bit_time_;
  // coordinator: tensors whose ranks disagreed on dtype/shape/op
  std::unordered_map<std::string, std::string> error_table_;
  // coordinator: group id -> (expected member count, ready singles held
  // back until the whole group is ready) — reference: GroupTable,
  // horovod/common/group_table.h:30-60
  std::unordered_map<int, std::pair<int, std::vector<Response>>> groups_;
  // groups with an errored member: remaining members error out instead of
  // waiting forever
  std::set<int> poisoned_groups_;
};

class Core {
 public:
  static Core& Get();

  ~Core();
  Status Init(const CoreConfig& cfg);
  void Shutdown(bool force = false);
  bool initialized() const { return initialized_; }

  int rank() const { return cfg_.rank; }
  int size() const { return cfg_.size; }

  // async enqueue; handle is resolved when the op completes
  int EnqueueAllreduce(int domain, const std::string& name, const void* in,
                       void* out, DataType dt,
                       const std::vector<int64_t>& shape, ReduceOp op,
                       double prescale, double postscale,
                       int group_id = -1, int group_size = 0);
  int EnqueueAllgather(int domain, const std::string& name, const void* in,
                       DataType dt, const std::vector<int64_t>& shape);
  int EnqueueBroadcast(int domain, const std::string& name, const void* in,
                       void* out, int root, DataType dt,
                       const std::vector<int64_t>& shape);
  int EnqueueAlltoall(int domain, const std::string& name, const void* in,
                      const std::vector<int64_t>& splits, DataType dt,
                      const std::vector<int64_t>& shape);
  int EnqueueJoin(int domain);
  Status ExecBarrier(int domain);

  // handle API (reference: horovod/torch/handle_manager.h)
  bool Poll(int handle);
  Status WaitHandle(int handle, double timeout_s);
  // variable-size results
  std::vector<int64_t> ResultShape(int handle);
  std::vector<int64_t> RecvSplits(int handle);
  Status CopyResult(int handle, void* dst, int64_t max_bytes);
  void FreeHandle(int handle);

  int AddProcessSet(const std::vector<int>& ranks);
  void RemoveProcessSet(int id);
  int last_join_rank(int domain);

  // Dynamic timeline control (reference: horovod_start_timeline /
  // horovod_stop_timeline, operations.cc:1011-1041). Coordinator-only
  // file; non-zero ranks no-op.
  Status StartTimeline(const std::string& path, bool mark_cycles);
  Status StopTimeline();

  // Control-plane observability counters (steady-state health: cache-hit
  // rate, negotiation volume, fusion effectiveness). The reference exposes
  // this only through the timeline; first-class counters make the
  // fast-path measurable without tracing overhead.
  struct Counters {
    std::atomic<uint64_t> cycles{0};
    std::atomic<uint64_t> cache_hits{0};        // requests sent as bits
    std::atomic<uint64_t> cache_misses{0};      // requests fully negotiated
    std::atomic<uint64_t> cache_evictions{0};
    std::atomic<uint64_t> responses_executed{0};
    std::atomic<uint64_t> tensors_fused{0};     // tensors sharing a unit
    std::atomic<uint64_t> fused_units{0};       // multi-tensor units
    std::atomic<uint64_t> bytes_allreduced{0};
    std::atomic<uint64_t> bytes_allgathered{0};
    // two-level paths actually taken (proof the topology dispatch ran)
    std::atomic<uint64_t> hier_allreduces{0};
    std::atomic<uint64_t> hier_allgathers{0};
    // stall inspector surfaced as metrics (docs/OBSERVABILITY.md):
    // cumulative count of stall warnings issued, and the CURRENT number
    // of tensors past the warning threshold (a gauge, not a counter)
    std::atomic<uint64_t> stall_warnings{0};
    std::atomic<int64_t> stalled_tensors{0};
    // chaos-harness transport injections (docs/CHAOS.md), MIRRORED here
    // by the loop thread from the Transport's own counter: the metrics
    // scrape thread must never dereference transport_ (an elastic
    // re-init resets that pointer under it)
    std::atomic<uint64_t> transport_chaos_injected{0};
    // eager-wire CRC32C failures (HVD_TPU_WIRE_CHECKSUM), mirrored from
    // the Transport by the loop thread for the same reason as above
    std::atomic<uint64_t> transport_checksum_failures{0};
    // live values of the autotune-managed knobs (docs/OBSERVABILITY.md
    // "Autotune metrics"): mirrored every negotiation cycle by the loop
    // thread so /metrics shows WHAT the tuner picked, not just that it
    // is on. cycle time stored as microseconds to stay integral.
    std::atomic<int64_t> autotune_fusion_bytes{0};
    std::atomic<uint64_t> autotune_cycle_us{0};
    std::atomic<uint64_t> autotune_hierarchical{0};
    std::atomic<uint64_t> autotune_cache_enabled{0};
  };
  const Counters& counters() const { return counters_; }

  // Coordinator-side straggler attribution: per-rank totals of how long
  // the rest of the world waited on that rank being the LAST to announce
  // a tensor (the per-tensor negotiation wait the timeline shows as
  // NEGOTIATE_*/WAIT_FOR_OTHER_TENSOR_DATA spans, aggregated per rank).
  // Non-coordinator ranks have no data and serialize an empty report.
  std::string StragglersJson() const;

  // Engine-state snapshot for hang autopsies (hvd_engine_state_json):
  // per-domain pending tensors with who announced / who is missing,
  // queue depth, join state. The loop thread PUBLISHES the snapshot
  // (PublishEngineState, <=2 Hz) because domain internals are
  // loop-thread-only; readers get the latest published copy — mid-hang
  // the loop keeps cycling (peers keep sending empty request lists), so
  // the snapshot stays fresh exactly when it matters.
  std::string EngineStateJson() const;

  // Span plumbing for the Python layer (hvd_timeline_mark /
  // hvd_timeline_enabled): stamps eager-enqueue markers with the
  // caller's span id into the engine timeline.
  bool TimelineEnabled() const;
  void TimelineMark(const std::string& name, const std::string& span);

  Transport* transport() { return transport_.get(); }

 private:
  Core() = default;
  void Loop();
  bool RunOnce();
  // coordinator: integrate rank's requests into ready table, return
  // responses that became ready
  void HandleRequests(CoordDomain& d, int from_rank,
                      std::vector<Request>& reqs);
  void HandleCacheBits(CoordDomain& d, int from_rank,
                       const std::vector<int32_t>& bits);
  // coordinator: ready cached bits + negotiated tensors → SINGLE-tensor
  // responses in deterministic order
  std::vector<Response> CollectReady(CoordDomain& d);
  // merge compatible allreduce singles into fused units (reference:
  // controller.cc:793 FuseResponses); identical input → identical output on
  // every rank
  std::vector<Response> FuseResponses(const std::vector<Response>& singles);
  void Execute(CoordDomain& d, const Response& r);
  // activate / erase domains on domain-0 consensus (deadlock-free dynamic
  // process-set registration; see CoordDomain::active)
  void ApplyDomainLifecycle(const std::vector<int32_t>& activate,
                            const std::vector<int32_t>& retired);

  CoreConfig cfg_;
  Counters counters_;
  // last values mirrored from the CURRENT transport: the long-lived
  // counters_ accumulate DELTAS across transport lives, because every
  // checksum failure tears its transport down (elastic re-init builds
  // a fresh one at 0) and an absolute store would erase the very
  // evidence the counter exists to carry
  uint64_t seen_transport_chaos_ = 0;
  uint64_t seen_transport_checksum_ = 0;
  void MirrorTransportCounters();
  // straggler attribution state (coordinator-only writes, any-thread
  // reads through StragglersJson)
  struct StragglerStats {
    struct PerRank {
      double wait_seconds = 0.0;
      uint64_t held_count = 0;
    };
    std::map<int, PerRank> ranks;
    uint64_t tensors_timed = 0;
    double total_wait_seconds = 0.0;
  };
  mutable std::mutex straggler_mu_;
  StragglerStats stragglers_;
  std::chrono::steady_clock::time_point last_straggler_report_;
  // engine-state snapshot published by the loop thread (see
  // EngineStateJson); the mutex guards only the string swap
  mutable std::mutex engine_state_mu_;
  std::string engine_state_json_ = "{}";
  std::chrono::steady_clock::time_point last_state_pub_;
  void PublishEngineState();
  // charge `waited` seconds to `last_rank` (the rank everyone waited on)
  void ChargeStraggler(int last_rank, double waited);
  void MaybeReportStragglers();
  std::atomic<bool> initialized_{false};
  std::atomic<bool> shutdown_requested_{false};
  std::atomic<bool> loop_done_{false};
  // the transport error that killed the background loop (loop-thread
  // writes before exiting, loop thread reads in its own epilogue) —
  // finalized waiters then carry the REAL cause ("transport timeout: no
  // data from peer 1 ...") instead of a generic abort
  std::string loop_error_;
  // wake-on-enqueue: the loop sleeps cycle_time_ms between lockstep
  // rounds, but a freshly enqueued collective (or shutdown vote) kicks it
  // awake so single eager ops don't pay the idle-poll latency. SPMD ranks
  // enqueue together, so all enter the next round together.
  std::mutex cycle_mu_;
  std::condition_variable cycle_cv_;
  bool cycle_kick_ = false;
  void KickCycle();
  std::unique_ptr<Transport> transport_;
  std::thread loop_;
  std::unique_ptr<Timeline> timeline_;
  ParameterManager param_mgr_;
  // autotuned categorical knobs awaiting the atomic cross-rank flip: the
  // coordinator defers applying hier/cache to ITSELF until the domain-0
  // response send that hands them to the workers, so every rank switches
  // at the same cycle boundary (a skewed cache flip would split readiness
  // accounting between bit and name tables and deadlock negotiation)
  bool has_pending_knobs_ = false;
  uint8_t pending_knob_flags_ = 0;
  bool hier_topology_ok_ = false;
  // current effective knob flags (bit0 hier, bit1 cache) for the wire
  uint8_t KnobFlags() const;
  void ApplyKnobFlags(uint8_t flags);

  std::mutex domains_mu_;
  std::map<int, std::unique_ptr<CoordDomain>> domains_;
  int next_domain_ = 1;
  // domain-0 coordinator: registration/retire consensus per domain id
  struct Consensus {
    uint64_t ranks_hash = 0;
    std::set<int> ranks;
    bool mismatch_warned = false;
  };
  std::map<int, Consensus> announce_table_;
  std::map<int, std::set<int>> retire_table_;
  // hierarchical topology groups (valid when hier_topology_ok_)
  bool hier_enabled_ = false;
  bool hier_ag_enabled_ = false;
  Group local_group_;
  Group cross_group_;

  struct HandleState {
    std::mutex mu;
    std::condition_variable cv;
    bool done = false;
    Status status;
    TensorTableEntry entry;  // holds results for var-size ops
  };
  std::mutex handles_mu_;
  std::unordered_map<int, std::shared_ptr<HandleState>> handles_;
  std::atomic<int> next_handle_{1};
  int NewHandle(TensorTableEntry* entry_out_binding);
  std::shared_ptr<HandleState> GetHandle(int h);
  void PushToDomain(int domain, TensorTableEntry e, Request r);
};

}  // namespace hvd
