// Leveled, rank-tagged logging for the native core.
//
// Reference: horovod/common/logging.{h,cc} — LOG(level) stream macros
// honoring HOROVOD_LOG_LEVEL, with rank + timestamp prefixes. Format
// matches this package's Python logger ("[time] [tag] [rank N] LEVEL:
// msg") so interleaved host logs from both planes read uniformly.
// HOROVOD_LOG_HIDE_TIME drops the timestamp (reference knob).
#pragma once

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <sstream>
#include <string>

#include <sys/time.h>

namespace hvd {

enum class LogSeverity : int {
  kTrace = 0, kDebug = 1, kInfo = 2, kWarning = 3, kError = 4, kFatal = 5
};

inline const char* LogSeverityName(LogSeverity s) {
  switch (s) {
    case LogSeverity::kTrace: return "TRACE";
    case LogSeverity::kDebug: return "DEBUG";
    case LogSeverity::kInfo: return "INFO";
    case LogSeverity::kWarning: return "WARNING";
    case LogSeverity::kError: return "ERROR";
    case LogSeverity::kFatal: return "FATAL";
  }
  return "?";
}

inline LogSeverity ParseLogLevel(const char* v) {
  if (!v || !*v) return LogSeverity::kWarning;  // reference default
  std::string s(v);
  for (auto& c : s) c = (char)tolower(c);
  if (s == "trace") return LogSeverity::kTrace;
  if (s == "debug") return LogSeverity::kDebug;
  if (s == "info") return LogSeverity::kInfo;
  if (s == "warning" || s == "warn") return LogSeverity::kWarning;
  if (s == "error") return LogSeverity::kError;
  if (s == "fatal") return LogSeverity::kFatal;
  return LogSeverity::kWarning;
}

// threshold / rank / hide-time are process-wide; rank is stamped by the
// core once its config is parsed (env fallback covers pre-init messages)
inline LogSeverity& LogThreshold() {
  static LogSeverity lvl = ParseLogLevel(getenv("HOROVOD_LOG_LEVEL"));
  return lvl;
}

inline int& LogRank() {
  static int rank = [] {
    const char* e = getenv("HOROVOD_RANK");
    if (!e) e = getenv("HVD_TPU_RANK");
    return e ? atoi(e) : -1;
  }();
  return rank;
}

inline bool& LogHideTime() {
  static bool hide = [] {
    const char* e = getenv("HOROVOD_LOG_HIDE_TIME");
    return e && *e && strcmp(e, "0") != 0;
  }();
  return hide;
}

// Stream-style message; the destructor emits ONE fprintf so concurrent
// threads' lines never interleave mid-line. LOG(FATAL) aborts like the
// reference's.
class LogMessage {
 public:
  explicit LogMessage(LogSeverity severity) : severity_(severity) {}

  ~LogMessage() {
    char ts[64] = "";
    if (!LogHideTime()) {
      struct timeval tv;
      gettimeofday(&tv, nullptr);
      struct tm tm_buf;
      localtime_r(&tv.tv_sec, &tm_buf);
      size_t n = strftime(ts, sizeof(ts), "[%F %T", &tm_buf);
      snprintf(ts + n, sizeof(ts) - n, ".%03d] ", (int)(tv.tv_usec / 1000));
    }
    fprintf(stderr, "%s[hvdcore] [rank %d] %s: %s\n", ts, LogRank(),
            LogSeverityName(severity_), stream_.str().c_str());
    if (severity_ == LogSeverity::kFatal) abort();
  }

  std::ostringstream& stream() { return stream_; }

 private:
  LogSeverity severity_;
  std::ostringstream stream_;
};

}  // namespace hvd

// usage: HVD_LOG(Warning) << "stalled for " << secs << "s";
#define HVD_LOG(severity)                                                  \
  if (::hvd::LogSeverity::k##severity < ::hvd::LogThreshold())             \
    ;                                                                      \
  else                                                                     \
    ::hvd::LogMessage(::hvd::LogSeverity::k##severity).stream()
