// TCP full-mesh transport with coordinator-based rendezvous.
//
// Fills the role of the reference's Gloo context + HTTP-KV rendezvous
// (horovod/common/gloo/gloo_context.cc:67-131): workers learn each other's
// addresses through rank 0 (address from HOROVOD_GLOO_RENDEZVOUS_ADDR-style
// env) and build a full mesh of TCP connections; framed messages are
// demultiplexed by (peer, tag) with per-tag blocking queues.
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <map>
#include <memory>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <vector>

#include "types.h"

namespace hvd {

struct Frame {
  int32_t tag;
  std::vector<uint8_t> payload;
};

// Fault injection for the chaos harness (docs/CHAOS.md): parsed from
// HVD_TPU_CHAOS_TRANSPORT ("dir=recv:kind=delay:peer=1:after=10:count=5:
// ms=25;..." — compiled per rank by horovod_tpu/chaos from the JSON
// fault plan).  Rules key on the per-peer per-direction frame index:
// `delay` sleeps ms before handling the frame, `drop` discards it
// (recv: never delivered; send: never written — the peer starves),
// `close` shuts the peer socket down mid-stream.  When the env var is
// absent the transport holds no chaos object and the hot path pays one
// null-pointer test per frame.
struct TransportChaosRule {
  bool recv = true;       // direction this rule applies to
  int kind = 0;           // 0 delay, 1 drop, 2 close, 3 bit_flip
  int peer = -1;          // -1 = any peer
  uint64_t after = 0;     // first affected frame index (0-based)
  uint64_t count = 0;     // frames affected; 0 = unlimited
  double ms = 0.0;        // delay milliseconds
  // bit_flip extras (docs/CHAOS.md "Wire integrity"): only frames with
  // at least `min_bytes` of payload qualify (so a flip targets tensor
  // DATA frames, not the small lockstep negotiation frames whose index
  // is timing-dependent), and at most `fires` frames are ever corrupted
  // (0 = unlimited) — counted per fire, unlike the window `count`
  uint64_t min_bytes = 0;
  uint64_t fires = 0;
};

struct TransportChaos {
  std::vector<TransportChaosRule> rules;
  std::vector<std::atomic<uint64_t>> recv_seen, send_seen;  // per peer
  std::atomic<uint64_t> injected{0};
  // per-rule fire counts (the `fires` budget); sized to rules.size()
  // after parsing
  std::unique_ptr<std::atomic<uint64_t>[]> rule_fired;
  bool has_bit_flip = false;  // Send copies the payload only when true
  explicit TransportChaos(int size)
      : recv_seen(size), send_seen(size) {
    for (int i = 0; i < size; ++i) {
      recv_seen[i] = 0;
      send_seen[i] = 0;
    }
  }
};

class Transport {
 public:
  // rank/size/coordinator address resolved from env by the caller.
  // connect_timeout_secs: how long rendezvous/mesh connects retry before
  // giving up (reference knob: HOROVOD_GLOO_TIMEOUT_SECONDS, default 30).
  // recv_timeout_secs: inactivity deadline on Recv (0 = wait forever,
  // the pre-hardening behavior) — a dead-but-connected peer (SIGSTOP,
  // wedged host, chaos `drop`) then surfaces as a Status error instead
  // of an infinite block (knob: HVD_TPU_TRANSPORT_TIMEOUT_S).
  // wire_checksum: CRC32C every frame (header + payload) on the eager
  // wire (knob: HVD_TPU_WIRE_CHECKSUM, default ON; must be set
  // uniformly across the world — the frame header grows frame- and header-crc fields).
  // A mismatch names the corrupting peer, counts checksum_failures(),
  // and kills the connection so both sides surface
  // HorovodInternalError into the elastic recovery path.
  Transport(int rank, int size, const std::string& coord_addr,
            int coord_port, double connect_timeout_secs = 30.0,
            double recv_timeout_secs = 0.0,
            bool wire_checksum = true);
  ~Transport();

  Status Init();            // rendezvous + full mesh
  void Shutdown();

  int rank() const { return rank_; }
  int size() const { return size_; }

  // Framed point-to-point messaging. Send is thread-safe per peer; recv
  // blocks until a frame with `tag` arrives from `peer`.
  Status Send(int peer, int32_t tag, const void* data, size_t len);
  Status Recv(int peer, int32_t tag, std::vector<uint8_t>* out);

  // total chaos faults injected by this transport (0 when no spec armed)
  uint64_t chaos_injected() const {
    return chaos_ ? chaos_->injected.load() : 0;
  }

  // frames whose CRC32C failed verification (0 with the check off)
  uint64_t checksum_failures() const { return checksum_failures_.load(); }

 private:
  void ReaderLoop(int peer);
  Status ConnectTo(const std::string& host, int port, int* fd_out);
  // returns true when the frame must be dropped; may sleep, corrupt
  // `payload` in place (bit_flip), or shut the peer's socket down per
  // the armed rules
  bool ChaosOnFrame(bool recv, int peer, uint8_t* payload, size_t len);

  int rank_, size_;
  std::string coord_addr_;
  int coord_port_;
  double connect_timeout_secs_;
  double recv_timeout_secs_;
  bool checksum_enabled_;
  std::atomic<uint64_t> checksum_failures_{0};
  std::unique_ptr<TransportChaos> chaos_;  // null = chaos off
  // per-peer last-DELIVERED-byte stamp (steady ns), fed by ReaderLoop as
  // payload bytes stream in: the recv deadline measures true peer
  // inactivity, so a healthy peer slowly streaming one large fused frame
  // can never trip it (a chaos drop/close rewinds the stamp — a dropped
  // frame must look like silence, that is the scenario it simulates)
  std::vector<std::atomic<int64_t>> last_rx_ns_;
  int listen_fd_ = -1;
  std::vector<int> peer_fds_;                 // index = peer rank
  std::vector<std::unique_ptr<std::mutex>> send_mu_;
  std::vector<std::thread> readers_;

  struct TagQueue {
    std::mutex mu;
    std::condition_variable cv;
    std::queue<std::vector<uint8_t>> q;
    bool closed = false;
  };
  // inbox_[peer][tag]
  std::mutex inbox_mu_;
  std::vector<std::map<int32_t, std::shared_ptr<TagQueue>>> inbox_;
  std::vector<bool> dead_;  // peer's reader exited: new queues born closed
  // why a peer's reader died, when it was an integrity failure rather
  // than a plain close: Recv surfaces this instead of the generic
  // "connection closed" so the collective error NAMES the bad peer
  std::vector<std::string> peer_error_;  // guarded by inbox_mu_
  std::shared_ptr<TagQueue> GetQueue(int peer, int32_t tag);
  std::atomic<bool> shutting_down_{false};
};

}  // namespace hvd
