// TCP full-mesh transport with coordinator-based rendezvous.
//
// Fills the role of the reference's Gloo context + HTTP-KV rendezvous
// (horovod/common/gloo/gloo_context.cc:67-131): workers learn each other's
// addresses through rank 0 (address from HOROVOD_GLOO_RENDEZVOUS_ADDR-style
// env) and build a full mesh of TCP connections; framed messages are
// demultiplexed by (peer, tag) with per-tag blocking queues.
#pragma once

#include <condition_variable>
#include <cstdint>
#include <map>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <vector>

#include "types.h"

namespace hvd {

struct Frame {
  int32_t tag;
  std::vector<uint8_t> payload;
};

class Transport {
 public:
  // rank/size/coordinator address resolved from env by the caller.
  // connect_timeout_secs: how long rendezvous/mesh connects retry before
  // giving up (reference knob: HOROVOD_GLOO_TIMEOUT_SECONDS, default 30).
  Transport(int rank, int size, const std::string& coord_addr,
            int coord_port, double connect_timeout_secs = 30.0);
  ~Transport();

  Status Init();            // rendezvous + full mesh
  void Shutdown();

  int rank() const { return rank_; }
  int size() const { return size_; }

  // Framed point-to-point messaging. Send is thread-safe per peer; recv
  // blocks until a frame with `tag` arrives from `peer`.
  Status Send(int peer, int32_t tag, const void* data, size_t len);
  Status Recv(int peer, int32_t tag, std::vector<uint8_t>* out);

 private:
  void ReaderLoop(int peer);
  Status ConnectTo(const std::string& host, int port, int* fd_out);

  int rank_, size_;
  std::string coord_addr_;
  int coord_port_;
  double connect_timeout_secs_;
  int listen_fd_ = -1;
  std::vector<int> peer_fds_;                 // index = peer rank
  std::vector<std::unique_ptr<std::mutex>> send_mu_;
  std::vector<std::thread> readers_;

  struct TagQueue {
    std::mutex mu;
    std::condition_variable cv;
    std::queue<std::vector<uint8_t>> q;
    bool closed = false;
  };
  // inbox_[peer][tag]
  std::mutex inbox_mu_;
  std::vector<std::map<int32_t, std::shared_ptr<TagQueue>>> inbox_;
  std::vector<bool> dead_;  // peer's reader exited: new queues born closed
  std::shared_ptr<TagQueue> GetQueue(int peer, int32_t tag);
  std::atomic<bool> shutting_down_{false};
};

}  // namespace hvd
