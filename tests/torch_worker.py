"""Multi-process torch drop-in worker (reference analog: the torch cases
of test/parallel/test_torch.py under horovodrun): eager collectives,
sparse allreduce, and DistributedOptimizer equivalence to single-process
full-batch training."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import torch  # noqa: E402

import horovod_tpu.torch as hvd  # noqa: E402


def main():
    rank = int(os.environ["HOROVOD_RANK"])
    size = int(os.environ["HOROVOD_SIZE"])
    hvd.init()
    assert hvd.rank() == rank and hvd.size() == size

    # dense allreduce
    out = hvd.allreduce(torch.arange(6, dtype=torch.float32) + rank,
                        op=hvd.Sum, name="d")
    expect = sum(torch.arange(6, dtype=torch.float32) + r
                 for r in range(size))
    assert torch.allclose(out, expect), (out, expect)

    # sparse allreduce: overlapping + disjoint coordinates across ranks
    i = torch.tensor([[0, rank + 1], [0, 0]])
    v = torch.tensor([1.0, 2.0])
    sp = torch.sparse_coo_tensor(i, v, (size + 2, 2))
    handle = hvd.sparse_allreduce_async(sp, name="sp", op=hvd.Sum)
    dense = hvd.synchronize(handle).to_dense()
    expect = torch.zeros(size + 2, 2)
    expect[0, 0] = float(size)          # every rank contributed 1.0 there
    for r in range(size):
        expect[r + 1, 0] += 2.0         # each rank's private coordinate
    assert torch.allclose(dense, expect), (dense, expect)

    # allgather_object (reference: torch/functions.py:233-266)
    metas = hvd.allgather_object({"rank": rank, "loss": 0.5 * rank})
    assert [m["rank"] for m in metas] == list(range(size))

    # in-place async variants (reference: torch/mpi_ops.py allreduce_async_
    # / broadcast_async_ / grouped_allreduce family): the handle's
    # synchronize writes back into the argument tensors
    t = torch.full((3,), float(rank + 1))
    out = hvd.synchronize(hvd.allreduce_async_(t, op=hvd.Sum, name="ip"))
    assert out is t
    expect_sum = float(sum(r + 1 for r in range(size)))
    assert torch.allclose(t, torch.full((3,), expect_sum)), t

    b = torch.full((2,), float(rank))
    hvd.synchronize(hvd.broadcast_async_(b, root_rank=0, name="ipb"))
    assert torch.allclose(b, torch.zeros(2)), b

    g1, g2 = torch.full((2,), float(rank)), torch.full((4,), 2.0 * rank)
    outs = hvd.grouped_allreduce([g1, g2], op=hvd.Average, name="ga")
    mean_r = float(sum(range(size))) / size
    assert torch.allclose(outs[0], torch.full((2,), mean_r))
    assert torch.allclose(outs[1], torch.full((4,), 2 * mean_r))
    hvd.synchronize(hvd.grouped_allreduce_async_(
        [g1, g2], op=hvd.Average, name="ga_"))
    assert torch.allclose(g1, torch.full((2,), mean_r)), g1
    hvd.grouped_allreduce_([g2], op=hvd.Average, name="ga2_")
    # g2 was already reduced in place once, so averaging the averages is
    # idempotent across equal ranks' values
    assert torch.allclose(g2, torch.full((4,), 2 * mean_r)), g2

    # async alltoall returns (tensor, recv_splits) from wait
    a2a = torch.arange(size, dtype=torch.float32) + rank * 10
    at, asplits = hvd.synchronize(
        hvd.alltoall_async(a2a, splits=[1] * size, name="a2a"))
    assert at.shape[0] == size and list(asplits) == [1] * size
    assert float(at[0]) == float(rank)  # rank 0's slot r element

    # DistributedOptimizer: equal shards => identical to full-batch SGD
    torch.manual_seed(0)
    model = hvd.broadcast_object(torch.nn.Linear(4, 1), 0, name="m")
    ref = torch.nn.Linear(4, 1)
    ref.load_state_dict(model.state_dict())
    rng = np.random.RandomState(0)
    X = torch.from_numpy(rng.randn(8 * size, 4).astype(np.float32))
    Y = torch.from_numpy(rng.randn(8 * size, 1).astype(np.float32))
    mine = slice(rank * 8, (rank + 1) * 8)
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters())
    ref_opt = torch.optim.SGD(ref.parameters(), lr=0.1)
    for step in range(5):
        opt.zero_grad()
        torch.nn.functional.mse_loss(model(X[mine]), Y[mine]).backward()
        opt.step()
        ref_opt.zero_grad()
        torch.nn.functional.mse_loss(ref(X), Y).backward()
        ref_opt.step()
    for a, b in zip(model.parameters(), ref.parameters()):
        assert torch.allclose(a, b, atol=1e-5), (a, b)

    # hook mode: each param's allreduce is enqueued DURING .backward()
    # (post-accumulate-grad hook), so handles are already in flight when
    # backward returns; step() drains them (reference: grad-accumulator
    # hooks, torch/optimizer.py:128-171)
    hX = torch.from_numpy(rng.randn(8, 4).astype(np.float32))
    hY = torch.from_numpy(rng.randn(8, 1).astype(np.float32))
    hmodel = hvd.broadcast_object(torch.nn.Linear(4, 1), 0, name="hm")
    hopt = hvd.DistributedOptimizer(
        torch.optim.SGD(hmodel.parameters(), lr=0.1),
        named_parameters=hmodel.named_parameters())
    assert hopt._use_hooks
    hopt.zero_grad()
    torch.nn.functional.mse_loss(hmodel(hX), hY).backward()
    if size > 1:
        assert len(hopt._handles) == 2, hopt._handles  # weight + bias
    hopt.step()
    assert not hopt._handles

    # backward_passes_per_step=2 under hooks: the first backward only
    # counts down; the SECOND enqueues — and the result equals one
    # full-batch step on the summed gradient scaled by 1/2
    amodel = hvd.broadcast_object(torch.nn.Linear(4, 1), 0, name="am")
    aref = torch.nn.Linear(4, 1)
    aref.load_state_dict(amodel.state_dict())
    aopt = hvd.DistributedOptimizer(
        torch.optim.SGD(amodel.parameters(), lr=0.1),
        named_parameters=amodel.named_parameters(),
        backward_passes_per_step=2)
    aopt.zero_grad()
    torch.nn.functional.mse_loss(amodel(hX[:4]), hY[:4]).backward()
    assert not aopt._handles  # countdown, nothing in flight yet
    torch.nn.functional.mse_loss(amodel(hX[4:]), hY[4:]).backward()
    if size > 1:
        assert len(aopt._handles) == 2
    aopt.step()
    aref_opt = torch.optim.SGD(aref.parameters(), lr=0.1)
    torch.nn.functional.mse_loss(aref(hX[:4]), hY[:4]).backward()
    torch.nn.functional.mse_loss(aref(hX[4:]), hY[4:]).backward()
    for p in aref.parameters():
        p.grad.div_(2.0)  # same shard on every rank -> avg == local
    aref_opt.step()
    for a, b in zip(amodel.parameters(), aref.parameters()):
        assert torch.allclose(a, b, atol=1e-5), (a, b)

    # more backwards than backward_passes_per_step raises like the
    # reference (a re-enqueue would collide with the in-flight op)
    aopt.zero_grad()
    torch.nn.functional.mse_loss(amodel(hX[:4]), hY[:4]).backward()
    torch.nn.functional.mse_loss(amodel(hX[4:]), hY[4:]).backward()
    try:
        torch.nn.functional.mse_loss(amodel(hX[:4]), hY[:4]).backward()
        raise AssertionError("expected over-backward error")
    except (ValueError, RuntimeError) as e:
        assert "backward_passes_per_step" in str(e), e
    aopt.synchronize()  # drain the legal in-flight enqueues

    # fallback (HVD_TORCH_HOOKS=0): per-tensor sync in step(), same numerics
    os.environ["HVD_TORCH_HOOKS"] = "0"
    try:
        fmodel = hvd.broadcast_object(torch.nn.Linear(4, 1), 0, name="fm")
        fopt = hvd.DistributedOptimizer(
            torch.optim.SGD(fmodel.parameters(), lr=0.1),
            named_parameters=fmodel.named_parameters())
        assert not fopt._use_hooks
        fopt.zero_grad()
        torch.nn.functional.mse_loss(fmodel(hX), hY).backward()
        assert not fopt._handles  # nothing enqueued during backward
        fopt.step()
    finally:
        del os.environ["HVD_TORCH_HOOKS"]

    # SyncBatchNorm: sharded batch must match plain BN on the full batch
    # for output, input grad, affine grads (after averaging), and running
    # stats (reference: torch/sync_batch_norm.py numerics)
    torch.manual_seed(1)
    X = torch.from_numpy(rng.randn(4 * size, 3, 5, 5).astype(np.float32))
    mine = slice(rank * 4, (rank + 1) * 4)
    sbn = hvd.SyncBatchNorm(3, momentum=0.1)
    bn = torch.nn.BatchNorm2d(3, momentum=0.1)
    bn.load_state_dict({k: v.clone() for k, v in sbn.state_dict().items()})
    xs = X[mine].clone().requires_grad_(True)
    xf = X.clone().requires_grad_(True)
    out_s = sbn(xs)
    out_f = bn(xf)
    assert torch.allclose(out_s, out_f[mine], atol=1e-5)
    out_s.sum().backward()
    out_f.sum().backward()
    assert torch.allclose(xs.grad, xf.grad[mine], atol=1e-5)
    # affine grads are LOCAL sums; averaging across ranks then scaling by
    # size reproduces the full-batch sums (sum-over-shards contract)
    gw = hvd.allreduce(sbn.weight.grad, op=hvd.Sum, name="sbn.gw")
    gb = hvd.allreduce(sbn.bias.grad, op=hvd.Sum, name="sbn.gb")
    assert torch.allclose(gw, bn.weight.grad, atol=1e-4), (gw, bn.weight.grad)
    assert torch.allclose(gb, bn.bias.grad, atol=1e-4)
    assert torch.allclose(sbn.running_mean, bn.running_mean, atol=1e-5)
    assert torch.allclose(sbn.running_var, bn.running_var, atol=1e-5)

    # join with genuinely uneven batches (reference:
    # test/parallel/test_torch.py join tests; controller.cc:94-98,262-265):
    # rank r trains on r+1 batches, calling hvd.join() when it runs out —
    # later ranks keep allreducing gradients while joined ranks contribute
    # nothing, then everyone agrees on the last rank to join
    if size >= 2:
        jmodel = hvd.broadcast_object(torch.nn.Linear(4, 1), 0, name="jm")
        jopt = hvd.DistributedOptimizer(
            torch.optim.SGD(jmodel.parameters(), lr=0.05),
            named_parameters=jmodel.named_parameters())
        for b in range(rank + 1):  # uneven: rank r has r+1 batches
            jopt.zero_grad()
            xb = torch.from_numpy(
                rng.randn(4, 4).astype(np.float32))
            yb = torch.from_numpy(rng.randn(4, 1).astype(np.float32))
            torch.nn.functional.mse_loss(jmodel(xb), yb).backward()
            jopt.step()
        last = hvd.join()
        # every rank agrees on who joined last (it holds the most-trained
        # parameters), and the standard post-join broadcast from that rank
        # leaves the whole world with identical parameters
        lasts = hvd.allgather_object(last)
        assert len(set(lasts)) == 1, lasts
        hvd.broadcast_parameters(jmodel.state_dict(), root_rank=lasts[0])
        ws = hvd.allgather_object(
            [p.detach().numpy() for p in jmodel.parameters()])
        for other in ws[1:]:
            for a, b in zip(ws[0], other):
                assert np.allclose(a, b, atol=1e-6)

    hvd.barrier()
    hvd.shutdown()
    print(f"torch worker {rank}: OK", flush=True)


if __name__ == "__main__":
    main()
