"""Multi-process torch drop-in worker (reference analog: the torch cases
of test/parallel/test_torch.py under horovodrun): eager collectives,
sparse allreduce, and DistributedOptimizer equivalence to single-process
full-batch training."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import torch  # noqa: E402

import horovod_tpu.torch as hvd  # noqa: E402


def main():
    rank = int(os.environ["HOROVOD_RANK"])
    size = int(os.environ["HOROVOD_SIZE"])
    hvd.init()
    assert hvd.rank() == rank and hvd.size() == size

    # dense allreduce
    out = hvd.allreduce(torch.arange(6, dtype=torch.float32) + rank,
                        op=hvd.Sum, name="d")
    expect = sum(torch.arange(6, dtype=torch.float32) + r
                 for r in range(size))
    assert torch.allclose(out, expect), (out, expect)

    # sparse allreduce: overlapping + disjoint coordinates across ranks
    i = torch.tensor([[0, rank + 1], [0, 0]])
    v = torch.tensor([1.0, 2.0])
    sp = torch.sparse_coo_tensor(i, v, (size + 2, 2))
    handle = hvd.sparse_allreduce_async(sp, name="sp", op=hvd.Sum)
    dense = hvd.synchronize(handle).to_dense()
    expect = torch.zeros(size + 2, 2)
    expect[0, 0] = float(size)          # every rank contributed 1.0 there
    for r in range(size):
        expect[r + 1, 0] += 2.0         # each rank's private coordinate
    assert torch.allclose(dense, expect), (dense, expect)

    # DistributedOptimizer: equal shards => identical to full-batch SGD
    torch.manual_seed(0)
    model = hvd.broadcast_object(torch.nn.Linear(4, 1), 0, name="m")
    ref = torch.nn.Linear(4, 1)
    ref.load_state_dict(model.state_dict())
    rng = np.random.RandomState(0)
    X = torch.from_numpy(rng.randn(8 * size, 4).astype(np.float32))
    Y = torch.from_numpy(rng.randn(8 * size, 1).astype(np.float32))
    mine = slice(rank * 8, (rank + 1) * 8)
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters())
    ref_opt = torch.optim.SGD(ref.parameters(), lr=0.1)
    for step in range(5):
        opt.zero_grad()
        torch.nn.functional.mse_loss(model(X[mine]), Y[mine]).backward()
        opt.step()
        ref_opt.zero_grad()
        torch.nn.functional.mse_loss(ref(X), Y).backward()
        ref_opt.step()
    for a, b in zip(model.parameters(), ref.parameters()):
        assert torch.allclose(a, b, atol=1e-5), (a, b)

    hvd.barrier()
    hvd.shutdown()
    print(f"torch worker {rank}: OK", flush=True)


if __name__ == "__main__":
    main()
