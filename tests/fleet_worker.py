"""Live fleet-scrape worker (launched by test_core_multiprocess.py):
the ISSUE 7 acceptance — a 2-process job where ONLY rank 0's
``/metrics/fleet`` is scraped and it carries correctly merged samples
from EVERY rank (counter sums, gauge aggregation, per-rank step-time
breakdown), surviving one elastic ``shutdown -> init`` re-mesh (tree
re-registered, merged counters keep accumulating, same ports rebound).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"

import urllib.request  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu.common.basics import _state  # noqa: E402
from horovod_tpu.train.callbacks import TelemetryCallback  # noqa: E402

STEPS_GEN1 = 3
STEPS_GEN2 = 2


def scrape(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=15) as r:
        return r.status, r.read().decode()


def parse(text):
    out = {}
    for line in text.splitlines():
        if line and not line.startswith("#"):
            key, val = line.rsplit(" ", 1)
            out[key] = float(val)
    return out


def run_steps(n):
    telemetry = TelemetryCallback(units_per_step=32, unit="examples")
    # goodput mode (HVD_TEST_GOODPUT=1, window=2 via env): rank 1 slow
    # ON PURPOSE — an inter-step stall books as its input_wait, so the
    # merged view must name rank 1 the worst goodput rank while rank
    # 0's own blocking allreduce wait stays inside its step envelope
    import time
    gp_stall = 0.05 if (os.environ.get("HVD_TEST_GOODPUT")
                        and hvd.rank() == 1) else 0.0
    for _ in range(n):
        if gp_stall:
            time.sleep(gp_stall)
        telemetry.on_step_begin()
        hvd.allreduce(jnp.ones(8), op=hvd.Sum, name="fleet_grad")
        telemetry.on_step_end()


def push_and_settle():
    """Deterministic aggregation: every rank flushes its tree node
    (children push upstream synchronously), fenced by barriers so rank
    0 holds every rank's doc before the scrape."""
    agg = _state.metrics_exporter.fleet
    assert agg is not None, "fleet aggregator missing on the exporter"
    if hvd.rank() != 0:
        agg.flush()  # POSTs this subtree to the parent's exporter
    hvd.barrier()


def assert_fleet_view(base_port, expected_steps, generation_label):
    status, body = scrape(base_port, "/metrics/fleet")
    assert status == 200, (status, body)
    series = parse(body)
    size = hvd.size()
    # counter sums across EVERY rank, through the tree
    assert series["hvd_steps_total"] == expected_steps, \
        (generation_label, series["hvd_steps_total"], expected_steps)
    assert series['hvd_collective_calls_total{kind="allreduce"}'] >= \
        expected_steps, (generation_label, body)
    # tree health: every rank reporting
    assert series["hvd_fleet_size"] == size
    assert series["hvd_fleet_ranks_reporting"] == size, \
        (generation_label, body)
    # per-rank step-time breakdown for every rank
    for r in range(size):
        key = f'hvd_fleet_rank_step_time_seconds{{rank="{r}"}}'
        assert key in series and series[key] > 0, (generation_label, key)
    assert series["hvd_fleet_step_time_max"] >= \
        series["hvd_fleet_step_time_min"] > 0
    assert series["hvd_fleet_straggler_rank"] in set(range(size))
    # gauge aggregation: throughput declares agg=sum — the fleet value
    # must be >= any single rank's contribution (both ranks just ran)
    own = parse(scrape(base_port + hvd.local_rank(),
                       "/metrics")[1])["hvd_examples_per_second"]
    assert series["hvd_examples_per_second"] >= own * 0.999, \
        (generation_label, series["hvd_examples_per_second"], own)
    # histogram merge: bucket counts add across ranks
    assert series["hvd_step_time_seconds_count"] == expected_steps
    # goodput mode: every rank's ledger closed a window (window=2 via
    # env) and the merged view carries the per-rank productive fraction
    # plus the worst-offender pair (docs/OBSERVABILITY.md "Goodput
    # ledger") — and they AGREE with each other
    if os.environ.get("HVD_TEST_GOODPUT"):
        fr = {}
        for r in range(size):
            key = f'hvd_fleet_rank_goodput_fraction{{rank="{r}"}}'
            assert key in series, (generation_label, sorted(series))
            fr[r] = series[key]
            assert 0 < fr[r] <= 1, (generation_label, fr)
        worst = int(series["hvd_fleet_goodput_worst_rank"])
        assert abs(series["hvd_fleet_goodput_min"]
                   - min(fr.values())) < 1e-6, (generation_label, series)
        assert abs(fr[worst] - min(fr.values())) < 1e-6, \
            (generation_label, fr, worst)
        # rank 1 stalls between steps: it must be the worst offender
        assert worst == 1, (generation_label, fr)


def main():
    rank = int(os.environ["HOROVOD_RANK"])
    size = int(os.environ["HOROVOD_SIZE"])
    base_port = int(os.environ["HVD_TPU_METRICS_PORT"])

    # ---- generation 1 ----
    hvd.init()
    run_steps(STEPS_GEN1)
    push_and_settle()
    if rank == 0:
        assert_fleet_view(base_port, STEPS_GEN1 * size, "gen1")
    hvd.barrier()

    # ---- elastic re-mesh: shutdown -> init ----
    hvd.shutdown()
    hvd.init()
    assert _state.metrics_exporter is not None, \
        "exporter did not rebind after re-mesh"
    assert _state.metrics_exporter.fleet is not None, \
        "fleet tree not re-registered after re-mesh"

    run_steps(STEPS_GEN2)
    push_and_settle()
    if rank == 0:
        # the process-global registry accumulates across the re-mesh:
        # merged counters now carry BOTH generations from BOTH ranks
        assert_fleet_view(base_port, (STEPS_GEN1 + STEPS_GEN2) * size,
                          "gen2")
    hvd.barrier()
    hvd.shutdown()
    print(f"fleet worker {rank}: OK", flush=True)


if __name__ == "__main__":
    main()
