"""KV relay battery (ISSUE 10): tree addressing, parent-cache routing,
upstream forwarding, relay-death fallback, re-mesh client rebuild, and
the in-process fan-in proof — rank 0's root KV handling O(arity) world
traffic while the relay nodes carry the rest (virtual hosts: every node
is a server object in this process, exactly how the acceptance allows).
"""

import threading

import pytest

from horovod_tpu.runner import kv_relay
from horovod_tpu.runner.http_kv import KVStoreServer, kv_get, kv_put
from horovod_tpu.runner.kv_relay import (RelayClient, RelayKVServer,
                                         relay_parent)


@pytest.fixture(autouse=True)
def _clean_relay(monkeypatch):
    monkeypatch.delenv("HVD_TPU_KV_RELAY_ARITY", raising=False)
    monkeypatch.delenv("HVD_TPU_KV_RELAY_TTL_S", raising=False)
    kv_relay.reset()
    yield
    kv_relay.reset()


def _root():
    srv = KVStoreServer()
    srv.start()
    return srv


def _node(rank, root, arity, ttl=None):
    """A relay node for ``rank``: its upstream is the same parent-or-root
    client a real WorkerNotificationListener would build."""
    client = RelayClient(rank, "127.0.0.1", root.port, arity=arity)
    srv = RelayKVServer(lambda c=client: c)
    srv.start()
    return srv, client


# -- tree addressing ---------------------------------------------------------

def test_relay_parent_addressing():
    # complete arity-2 tree: parent(r) = (r-1)//2, rank 0 routes direct
    assert relay_parent(0, 2) is None
    assert [relay_parent(r, 2) for r in range(1, 8)] == \
        [0, 0, 1, 1, 2, 2, 3]
    # arity 4 (the fleet-metrics default shape)
    assert [relay_parent(r, 4) for r in (1, 4, 5, 20)] == [0, 0, 1, 4]
    # relay disabled: everyone routes direct
    assert relay_parent(5, 0) is None


def test_relay_arity_env(monkeypatch):
    assert kv_relay.relay_arity() == 0  # default: flat topology
    monkeypatch.setenv("HVD_TPU_KV_RELAY_ARITY", "4")
    assert kv_relay.relay_arity() == 4
    monkeypatch.setenv("HVD_TPU_KV_RELAY_ARITY", "-2")
    assert kv_relay.relay_arity() == 0


# -- routing through the parent ----------------------------------------------

def test_world_poll_served_from_parent_cache(monkeypatch):
    """Children's world polls land on the parent's relay node; the node
    refreshes from upstream at most once per TTL — N child polls cost
    ONE root fetch, which is the whole point."""
    monkeypatch.setenv("HVD_TPU_KV_RELAY_TTL_S", "30")
    root = _root()
    node1 = client1 = None
    try:
        root.put("world", "current", b"doc-gen-1")
        node1, client1 = _node(1, root, arity=2)
        # rank 1's listener registered with the driver; rank 3 resolves
        # its parent (rank 1) from that registration
        root.put("notify", "1", f"127.0.0.1:{node1.port}".encode())
        child = RelayClient(3, "127.0.0.1", root.port, arity=2)
        for _ in range(5):
            assert child.get("world", "current") == b"doc-gen-1"
        # the node carried all 5 polls; the root saw ONE refresh (rank
        # 1's own client goes root-direct: its parent rank 0 never
        # registered, so resolution falls through to the root)
        assert node1.requests_for("world", "GET") == 5
        assert root.requests_for("world", "GET") == 1
    finally:
        if node1 is not None:
            node1.stop()
        root.stop()


def test_driver_push_lands_fresh_in_node_cache(monkeypatch):
    """The driver's world push is a direct PUT at the listener (scope
    ``world`` is not forwarded): it must land locally and count as fresh
    truth — children polling right after see the pushed doc with zero
    upstream traffic."""
    monkeypatch.setenv("HVD_TPU_KV_RELAY_TTL_S", "30")
    root = _root()
    node1 = None
    try:
        node1, _ = _node(1, root, arity=2)
        root.put("notify", "1", f"127.0.0.1:{node1.port}".encode())
        kv_put("127.0.0.1", node1.port, "world", "current", b"pushed")
        child = RelayClient(3, "127.0.0.1", root.port, arity=2)
        assert child.get("world", "current") == b"pushed"
        assert root.requests_for("world", "GET") == 0
    finally:
        if node1 is not None:
            node1.stop()
        root.stop()


def test_registration_put_forwarded_to_root(monkeypatch):
    """Forward scopes (notify/drain) travel up the tree: the child PUTs
    at its parent, the parent forwards upstream, the value materializes
    at the ROOT (where the driver reads it) — not in the node's cache."""
    root = _root()
    node1 = None
    try:
        node1, _ = _node(1, root, arity=2)
        root.put("notify", "1", f"127.0.0.1:{node1.port}".encode())
        child = RelayClient(3, "127.0.0.1", root.port, arity=2)
        child.put("notify", "3", b"hostX:4242")
        child.put("drain", "3", b'{"rank": 3}')
        assert root.get("notify", "3") == b"hostX:4242"
        assert root.get("drain", "3") == b'{"rank": 3}'
        # the relay node forwarded, it did not adopt
        assert node1.get("notify", "3") is None
        assert node1.requests_for("notify", "PUT") == 1
        assert node1.requests_for("drain", "PUT") == 1
    finally:
        if node1 is not None:
            node1.stop()
        root.stop()


# -- failure handling ---------------------------------------------------------

def test_dead_relay_degrades_to_root_without_failing(monkeypatch):
    """A killed relay node costs latency, never a failed call: the child
    marks the parent dead and degrades to direct root requests for both
    reads and writes."""
    root = _root()
    try:
        node1, _ = _node(1, root, arity=2)
        root.put("notify", "1", f"127.0.0.1:{node1.port}".encode())
        root.put("world", "current", b"doc")
        node1.stop()  # the relay node dies
        child = RelayClient(3, "127.0.0.1", root.port, arity=2)
        assert child.get("world", "current", timeout=3.0) == b"doc"
        child.put("notify", "3", b"hostY:1", timeout=3.0)
        assert root.get("notify", "3") == b"hostY:1"
        # dead-listed: follow-up calls skip the corpse entirely
        assert child._parent_usable(1.0) is None
    finally:
        root.stop()


def test_unregistered_parent_falls_through_to_root():
    """Mid-registration (parent listener not in the driver KV yet): the
    lookup fails softly, the negative result is cached briefly, and the
    call proceeds root-direct."""
    root = _root()
    try:
        root.put("world", "current", b"doc")
        child = RelayClient(3, "127.0.0.1", root.port, arity=2)
        assert child.get("world", "current") == b"doc"
        assert child._resolve_failed_until > 0  # negative cache armed
    finally:
        root.stop()


def test_node_without_upstream_rejects_forward_scope():
    """A relay node whose upstream is unresolved must 503 forwarded
    scopes — the CHILD then falls back to the root — rather than
    swallowing a registration into a cache the driver never reads."""
    root = _root()
    node = None
    try:
        node = RelayKVServer(lambda: None)
        node.start()
        with pytest.raises(OSError):
            kv_put("127.0.0.1", node.port, "notify", "9", b"x",
                   timeout=2.0)
    finally:
        if node is not None:
            node.stop()
        root.stop()


# -- re-mesh client rebuild ---------------------------------------------------

def test_client_rebuilt_when_identity_or_root_moves(monkeypatch):
    monkeypatch.setenv("HVD_TPU_KV_RELAY_ARITY", "2")
    monkeypatch.setenv("HOROVOD_RANK", "3")
    monkeypatch.setenv("HVD_ELASTIC_GENERATION", "0")
    c1 = kv_relay.client("127.0.0.1", 19999)
    assert c1.rank == 3 and c1.parent_rank == 1
    assert kv_relay.client("127.0.0.1", 19999) is c1  # cached
    # an elastic re-mesh renumbers the worker: the route must follow
    monkeypatch.setenv("HOROVOD_RANK", "1")
    monkeypatch.setenv("HVD_ELASTIC_GENERATION", "1")
    c2 = kv_relay.client("127.0.0.1", 19999)
    assert c2 is not c1 and c2.rank == 1 and c2.parent_rank == 0
    # a moved root rebuilds too
    c3 = kv_relay.client("127.0.0.1", 19998)
    assert c3 is not c2 and c3.root_port == 19998


def test_listener_upgrades_to_relay_node(monkeypatch):
    """WorkerNotificationListener doubles as the relay node exactly when
    the relay is enabled and a driver address is known."""
    from horovod_tpu.elastic.notification import WorkerNotificationListener
    root = _root()
    lst = None
    try:
        monkeypatch.setenv("HVD_TPU_KV_RELAY_ARITY", "2")
        monkeypatch.setenv("HOROVOD_RANK", "1")
        monkeypatch.setenv("HOROVOD_HOSTNAME", "127.0.0.1")
        lst = WorkerNotificationListener("127.0.0.1", root.port)
        assert isinstance(lst.kv, RelayKVServer)
        lst.register("127.0.0.1", root.port)
        reg = root.scope("notify")
        assert "1" in reg and reg["1"].endswith(b":%d" % lst.port)
    finally:
        if lst is not None:
            lst.stop()
        root.stop()


def test_listener_stays_plain_without_relay(monkeypatch):
    from horovod_tpu.elastic.notification import WorkerNotificationListener
    root = _root()
    lst = None
    try:
        monkeypatch.setenv("HOROVOD_RANK", "1")
        lst = WorkerNotificationListener("127.0.0.1", root.port)
        assert not isinstance(lst.kv, RelayKVServer)
    finally:
        if lst is not None:
            lst.stop()
        root.stop()


# -- the fan-in proof ---------------------------------------------------------

def test_fanin_world8_root_sees_one_world_fetch(monkeypatch):
    """The acceptance shape (virtual world 8, arity 2): every worker
    runs a relay node, workers 1..7 poll the world 3 times each — 21
    polls — and the ROOT serves exactly ONE world fetch (rank 0's node
    refreshing its cache).  The per-node request counters prove where
    the load actually went."""
    monkeypatch.setenv("HVD_TPU_KV_RELAY_TTL_S", "30")
    arity, world, polls = 2, 8, 3
    root = _root()
    nodes, clients = {}, {}
    try:
        root.put("world", "current", b"doc-gen-0")
        for r in range(world):
            nodes[r], clients[r] = _node(r, root, arity=arity)
            root.put("notify", str(r),
                     f"127.0.0.1:{nodes[r].port}".encode())
        for r in range(1, world):
            for _ in range(polls):
                assert clients[r].get("world", "current") == b"doc-gen-0"
        root_world_gets = root.requests_for("world", "GET")
        node_world_gets = {r: n.requests_for("world", "GET")
                          for r, n in nodes.items()}
        # O(arity): the root saw one cache refresh, not 21 polls
        assert root_world_gets == 1, (root_world_gets, node_world_gets)
        # the tree carried the polls plus the internal refresh hops
        assert sum(node_world_gets.values()) == \
            (world - 1) * polls + 3, node_world_gets
        # no node carries more than its own children + refresh traffic
        assert max(node_world_gets.values()) <= arity * polls + arity, \
            node_world_gets
    finally:
        for n in nodes.values():
            n.stop()
        root.stop()


def test_fanin_counters_exported_to_metrics():
    """The per-node counters land on /metrics too
    (hvd_kv_server_requests_total) so the fan-in is observable in a
    real fleet, not only in tests."""
    from horovod_tpu.metrics.registry import default_registry
    key = 'hvd_kv_server_requests_total{method="GET",scope="fanin_t"}'
    before = default_registry().snapshot().get(key, {}).get("value", 0)
    root = _root()
    try:
        root.put("fanin_t", "k", b"v")
        assert kv_get("127.0.0.1", root.port, "fanin_t", "k") == b"v"
    finally:
        root.stop()
    snap = default_registry().snapshot()
    assert snap[key]["value"] == before + 1
