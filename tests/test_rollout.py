"""Canary weight rollout (ISSUE 18, docs/SERVING.md "Canary rollout").

Fast battery: the rollout actions/policies in the autopilot defaults,
the verdict gate routing one rollout_verdict finding to exactly one of
the two policies, finding trace continuation, replica version pinning
(API + /pin route + pin_version restore + the weight_swap audit), the
router's deterministic crc32 version split (same id -> same arm, empty
arm falls back loudly), the per-version SLO comparator and golden
probe, the controller state machine over an in-process fleet adapter,
the fully in-process governed transition (evaluate -> autopilot ->
hooks, one trace id printed by `diagnostics trace`), the rollout
status CLI, and the `check_bench --rollout` gate.

Slow (serving/chaos CI tiers; tier-1 budget rule — all multiprocess
tests are slow-marked): the churn acceptance (SIGKILL the canary
replica mid-rollout: zero drop, idempotent replay stays on its arm,
the healed replacement joins at the INCUMBENT) and the ISSUE 18 chaos
acceptance — a poisoned commit canaried at N% is caught by the
per-version comparator's golden probe and auto-rolled-back by the
autopilot with ZERO failed requests, then a clean commit promotes
fleet-wide, each transition resolving to a single trace id.
"""

import json
import os
import signal
import threading
import time
import urllib.error
import urllib.request
import zlib

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_singletons(monkeypatch):
    import horovod_tpu.autopilot as autopilot
    from horovod_tpu import chaos
    from horovod_tpu.diagnostics.flight_recorder import recorder
    from horovod_tpu.metrics import anomaly, timeseries
    monkeypatch.delenv("HVD_TPU_AUTOPILOT", raising=False)
    monkeypatch.delenv("HVD_TPU_AUTOPILOT_POLICY", raising=False)
    monkeypatch.delenv("HVD_TPU_OBS_DIR", raising=False)
    # manufactured findings must not arm real device-trace captures
    monkeypatch.setenv("HVD_TPU_PROFILE_ON_ANOMALY", "0")
    chaos.uninstall()
    autopilot.reset()
    anomaly.reset()
    timeseries.reset()
    recorder().clear()
    yield
    chaos.uninstall()
    autopilot.reset()
    anomaly.reset()
    timeseries.reset()


def _wait(cond, timeout=10.0, step=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(step)
    return cond()


def _post(port, doc, path="/infer", timeout=10.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(doc).encode(),
        method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class _VersionStub:
    """Minimal replica stand-in: /infer answers with a fixed weight
    version (y = [version] * len(x)), /readyz answers 200 — the router
    and golden probe only need the wire contract, not a real model."""

    def __init__(self, version):
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)
        stub = self

        class _H(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _send(self, code, doc):
                body = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                self._send(200, {"ready": True, "version": stub.version})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                doc = json.loads(self.rfile.read(n))
                stub.hits += 1
                x = doc.get("x") or [0.0]
                self._send(200, {"id": doc.get("id"),
                                 "y": [float(stub.version)] * len(x),
                                 "version": stub.version,
                                 "replica": f"stub-v{stub.version}"})

        self.version = version
        self.hits = 0
        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), _H)
        threading.Thread(target=self._srv.serve_forever,
                         daemon=True).start()

    @property
    def endpoint(self):
        return ("127.0.0.1", self._srv.server_address[1])

    def close(self):
        self._srv.shutdown()


class _FakeFleet:
    """The controller's fleet surface, in-process: records every pin
    call; version arms serve from a static endpoints-by-version map."""

    def __init__(self, slots, eps_by_version=None):
        self._slots = list(slots)
        self.eps = dict(eps_by_version or {})
        self.pin_calls = []
        self.pinned = {}

    def slots(self):
        return list(self._slots)

    def pin_slot(self, slot, version, reason="pin", heal_version=None):
        self.pin_calls.append({"slot": slot, "version": version,
                               "reason": reason, "heal": heal_version})
        if version is None:
            self.pinned.pop(slot, None)
        else:
            self.pinned[slot] = version
        return True

    def unpin_slot(self, slot):
        return self.pin_slot(slot, None, reason="unpin")

    def endpoints_at(self, version):
        return list(self.eps.get(version, []))


# -- autopilot wiring ---------------------------------------------------------
def test_rollout_policies_registered():
    from horovod_tpu.autopilot.policy import ACTIONS, default_policies
    assert "promote_rollout" in ACTIONS
    assert "rollback_rollout" in ACTIONS
    byname = {p.name: p for p in default_policies()}
    assert byname["rollout-promote"].finding == "rollout_verdict"
    assert byname["rollout-promote"].action == "promote_rollout"
    assert byname["rollout-rollback"].finding == "rollout_verdict"
    assert byname["rollout-rollback"].action == "rollback_rollout"


def test_verdict_gate_routes_to_exactly_one_policy(monkeypatch):
    """Both rollout policies subscribe to the SAME rollout_verdict
    finding; the verdict field routes it to exactly one — the other's
    decision is suppressed with the mismatched verdict recorded."""
    import horovod_tpu.autopilot as autopilot
    from horovod_tpu.autopilot import actions
    from horovod_tpu.metrics import anomaly
    for verdict, fired_policy, other_policy in (
            ("promote", "rollout-promote", "rollout-rollback"),
            ("rollback", "rollout-rollback", "rollout-promote")):
        monkeypatch.setenv("HVD_TPU_AUTOPILOT", "act")
        autopilot.reset()
        anomaly.reset()
        calls = []
        actions.register_promote_rollout_hook(
            lambda f: calls.append(("promote", f)))
        actions.register_rollback_rollout_hook(
            lambda f: calls.append(("rollback", f)))
        anomaly.report_finding("rollout_verdict", verdict=verdict,
                               reason="test", rollout_id="r-1")
        assert _wait(lambda: len(calls) == 1 and len(
            [d for d in autopilot.recent_decisions()
             if d["finding"] == "rollout_verdict"]) >= 2, timeout=5)
        ds = {d["policy"]: d for d in autopilot.recent_decisions()
              if d["finding"] == "rollout_verdict"}
        assert ds[fired_policy]["outcome"] == "fired"
        assert ds[other_policy]["outcome"] == "suppressed"
        assert ds[other_policy]["gate"]["verdict"] == verdict
        assert ds[other_policy]["gate"]["want"] != verdict
        # the hook received the FINDING (rollout_id routes staleness)
        assert calls == [(verdict, calls[0][1])]
        assert calls[0][1]["rollout_id"] == "r-1"
    autopilot.reset()
    anomaly.reset()


def test_finding_continues_supplied_traceparent():
    """A rollout_verdict carrying the controller's traceparent must
    CONTINUE that trace (child span), not root a fresh one — the whole
    governed transition is one causal tree."""
    from horovod_tpu import tracing
    from horovod_tpu.metrics import anomaly
    root = tracing.new_trace("rollout")
    f = anomaly.report_finding(
        "rollout_verdict", verdict="promote", rollout_id="r-t",
        **{tracing.TRACEPARENT: root.traceparent})
    assert f["trace"] == root.trace_id
    assert f[tracing.TRACEPARENT] != root.traceparent  # a child span
    # without a supplied traceparent the finding roots its own trace
    f2 = anomaly.report_finding("rollout_verdict", verdict="promote",
                                rollout_id="r-t2")
    assert f2["trace"] != root.trace_id


# -- replica version pinning --------------------------------------------------
def test_replica_pin_holds_against_newer_commits(tmp_path):
    """Satellite: a pinned replica never chases a newer commit; unpin
    resumes the chase; a rollback repin is a BACKWARD flip audited as
    a weight_swap event naming both endpoints and its reason."""
    from horovod_tpu.checkpoint import ShardedCheckpointer
    from horovod_tpu.diagnostics.flight_recorder import recorder
    from horovod_tpu.metrics.registry import default_registry
    from horovod_tpu.serving import ReplicaServer
    from horovod_tpu.serving.replica import demo_params
    store = ShardedCheckpointer(str(tmp_path), rank=0, world_size=1)
    store.save(1, {"params": demo_params(4, scale=1.0)}, wait=True)
    r = ReplicaServer(dim=4, store_dir=str(tmp_path), replica_id="pin0",
                      swap_poll_s=0.05).start()
    try:
        doc = r.pin(1)
        assert doc["pinned"] == 1 and doc["version"] == 1
        store.save(2, {"params": demo_params(4, scale=2.0)}, wait=True)
        time.sleep(0.3)  # several swap-poll intervals
        code, resp = _post(r.port, {"id": "p1", "x": [4.0, 0, 0, 0]})
        assert code == 200 and resp["version"] == 1  # never chased
        r.unpin()
        assert _wait(lambda: _post(
            r.port, {"id": f"p-{time.monotonic_ns()}",
                     "x": [4.0, 0, 0, 0]})[1]["version"] == 2)
        # rollback repin: 2 -> 1 while 2 is still latest in the store
        r.pin(1, reason="rollback")
        code, resp = _post(r.port, {"id": "p2", "x": [4.0, 0, 0, 0]})
        assert code == 200 and resp["version"] == 1
        assert abs(resp["y"][0] - 1.0) < 1e-5  # v1 math, not v2's
        swaps = [e for e in recorder().events()
                 if e.get("kind") == "weight_swap"
                 and e.get("replica") == "pin0"]
        assert any(e.get("reason") == "chase" for e in swaps)
        back = [e for e in swaps if e.get("reason") == "rollback"]
        assert back and back[-1]["from_version"] == 2
        assert back[-1]["to_version"] == 1
        c = default_registry().get("hvd_serving_weight_swaps_total",
                                   labels={"reason": "rollback"})
        assert c is not None and c.value >= 1
    finally:
        r.stop()
        store.close()


def test_pin_http_route(tmp_path):
    """The fleet manager's control seam: POST /pin pins/unpins; a
    malformed body is a 400, never a crashed replica."""
    from horovod_tpu.checkpoint import ShardedCheckpointer
    from horovod_tpu.serving import ReplicaServer
    from horovod_tpu.serving.replica import demo_params
    store = ShardedCheckpointer(str(tmp_path), rank=0, world_size=1)
    store.save(1, {"params": demo_params(4, scale=1.0)}, wait=True)
    store.save(2, {"params": demo_params(4, scale=2.0)}, wait=True)
    r = ReplicaServer(dim=4, store_dir=str(tmp_path),
                      replica_id="pinhttp").start()
    try:
        assert r._version == 2  # restored latest at start
        code, doc = _post(r.port, {"version": 1, "reason": "pin"},
                          path="/pin")
        assert code == 200 and doc["pinned"] == 1 and doc["version"] == 1
        # readyz carries the observed version + pin (the fleet's
        # membership view parses exactly this doc)
        ready = r.ready_doc()
        assert ready["version"] == 1 and ready["pinned"] == 1
        code, doc = _post(r.port, {}, path="/pin")  # null version unpins
        assert code == 200 and doc["pinned"] is None
        req = urllib.request.Request(
            f"http://127.0.0.1:{r.port}/pin", data=b"{nope",
            method="POST")
        try:
            urllib.request.urlopen(req, timeout=5)
            code = 200
        except urllib.error.HTTPError as e:
            code = e.code
        assert code == 400
    finally:
        r.stop()
        store.close()


def test_replica_restores_pin_version_at_start(tmp_path):
    """A healed replacement spawned with --pin-version restores the
    pinned step DIRECTLY — it never transits through latest."""
    from horovod_tpu.checkpoint import ShardedCheckpointer
    from horovod_tpu.serving import ReplicaServer
    from horovod_tpu.serving.replica import demo_params
    store = ShardedCheckpointer(str(tmp_path), rank=0, world_size=1)
    store.save(1, {"params": demo_params(4, scale=1.0)}, wait=True)
    store.save(2, {"params": demo_params(4, scale=3.0)}, wait=True)
    r = ReplicaServer(dim=4, store_dir=str(tmp_path), replica_id="heal",
                      swap_poll_s=0.05, pin_version=1).start()
    try:
        code, resp = _post(r.port, {"id": "h1", "x": [4.0, 0, 0, 0]})
        assert code == 200 and resp["version"] == 1
        time.sleep(0.3)  # the pin holds across swap polls too
        code, resp = _post(r.port, {"id": "h2", "x": [4.0, 0, 0, 0]})
        assert resp["version"] == 1 and abs(resp["y"][0] - 1.0) < 1e-5
    finally:
        r.stop()
        store.close()


def test_pin_to_missing_version_leaves_replica_unpinned(tmp_path):
    """Regression: a failed pin restore must not commit the pin — the
    replica keeps serving its old weights UNPINNED (and keeps chasing
    commits) instead of freezing on an unloadable version that the
    swap loop would retry forever."""
    from horovod_tpu.checkpoint import ShardedCheckpointer
    from horovod_tpu.serving import ReplicaServer
    from horovod_tpu.serving.replica import demo_params
    store = ShardedCheckpointer(str(tmp_path), rank=0, world_size=1)
    store.save(1, {"params": demo_params(4, scale=1.0)}, wait=True)
    r = ReplicaServer(dim=4, store_dir=str(tmp_path),
                      replica_id="nopin", swap_poll_s=0.05).start()
    try:
        code, doc = _post(r.port, {"version": 99}, path="/pin")
        assert code == 500
        assert r.pinned is None  # the failed pin was NOT committed
        code, resp = _post(r.port, {"id": "n1", "x": [4.0, 0, 0, 0]})
        assert code == 200 and resp["version"] == 1  # old weights serve
        # and the replica still chases the next commit — not frozen
        store.save(2, {"params": demo_params(4, scale=2.0)}, wait=True)
        assert _wait(lambda: _post(
            r.port, {"id": f"n-{time.monotonic_ns()}",
                     "x": [4.0, 0, 0, 0]})[1]["version"] == 2)
    finally:
        r.stop()
        store.close()


# -- router version split -----------------------------------------------------
def test_router_version_split_deterministic_by_request_id():
    """crc32(id) % 100 buckets the split: the assignment is exact and
    an idempotent replay of an id lands on the SAME arm — answered by
    the same version as the original."""
    from horovod_tpu.serving import Router
    canary, incumbent = _VersionStub(2), _VersionStub(1)
    router = Router(lambda: [canary.endpoint, incumbent.endpoint],
                    max_attempts=4)
    try:
        router.set_version_split(30, [canary.endpoint],
                                 [incumbent.endpoint],
                                 canary_version=2, incumbent_version=1)
        assert router.version_split() == {
            "pct": 30, "canary_version": 2, "incumbent_version": 1}
        expect, got = {}, {}
        for i in range(60):
            rid = f"s{i}"
            expect[rid] = 2 if zlib.crc32(rid.encode()) % 100 < 30 else 1
            got[rid] = router.submit([1.0, 2.0], req_id=rid)["version"]
        assert got == expect
        n_canary = sum(1 for v in expect.values() if v == 2)
        assert 0 < n_canary < 60  # both arms actually exercised
        acct = router.accounting()
        assert acct["by_version"][2] == n_canary
        assert acct["by_version"][1] == 60 - n_canary
        # replay: same id -> same arm -> same version
        assert router.submit([9.0, 9.0],
                             req_id="s0")["version"] == expect["s0"]
        router.clear_version_split()
        assert router.version_split() is None
    finally:
        router.close()
        canary.close()
        incumbent.close()


def test_router_empty_arm_falls_back_to_full_fleet():
    """Zero-drop outranks split fidelity: an empty arm (canary mid-
    heal) degrades to the full fleet, counted — never a failed
    request."""
    from horovod_tpu.metrics.registry import default_registry
    from horovod_tpu.serving import Router
    incumbent = _VersionStub(1)
    router = Router(lambda: [incumbent.endpoint], max_attempts=4)
    try:
        router.set_version_split(100, lambda: [], [incumbent.endpoint],
                                 canary_version=2, incumbent_version=1)
        before = 0.0
        c = default_registry().get(
            "hvd_serving_rollout_split_fallback_total",
            labels={"arm": "canary"})
        if c is not None:
            before = c.value
        doc = router.submit([1.0], req_id="fb-1")  # 100% canary, empty
        assert doc["version"] == 1  # answered by the incumbent instead
        c = default_registry().get(
            "hvd_serving_rollout_split_fallback_total",
            labels={"arm": "canary"})
        assert c is not None and c.value >= before + 1
    finally:
        router.close()
        incumbent.close()


def test_retry_attribution_names_arm_version_for_dead_canary():
    """Regression: a poisoned candidate that never answers 200 must
    still accrue canary errors — retried-line attribution is by
    CURRENT arm membership, not the last version observed answering
    the endpoint (which would be the incumbent's, or nothing at all,
    so the error-rate rollback could never fire)."""
    import socket
    from horovod_tpu.serving import Router
    from horovod_tpu.serving.rollout import version_windows
    incumbent = _VersionStub(1)
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead = ("127.0.0.1", s.getsockname()[1])
    s.close()  # connection refused from now on: a 200-less canary
    router = Router(lambda: [dead, incumbent.endpoint],
                    max_attempts=4, hedge_ms=0)
    try:
        router.set_version_split(100, [dead], [incumbent.endpoint],
                                 canary_version=2, incumbent_version=1)
        doc = router.submit([1.0], req_id="dead-1")
        assert doc["version"] == 1  # widened to the incumbent: no drop
        retried = [e for e in router.log.entries
                   if e["outcome"] == "retried"]
        assert retried and retried[0]["after_version"] == 2
        assert retried[0]["version"] == 1  # the retry target's version
        stats = version_windows(router.log.entries, [2, 1])
        assert stats[2]["errors"] >= 1  # the canary window accrues
        assert stats[1]["ok"] == 1
    finally:
        router.close()
        incumbent.close()


def test_request_log_seq_anchor_survives_memory_trim(monkeypatch):
    """The stage-window anchor is an absolute sequence number: after
    the in-memory cap trims head entries, ``since(anchor)`` still
    returns every SURVIVING post-anchor entry (an index anchor would
    over-skip by the trimmed count)."""
    from horovod_tpu.serving.router import RequestLog
    monkeypatch.setattr(RequestLog, "MAX_MEMORY", 100)
    log = RequestLog()
    for i in range(90):
        log.note(f"a{i}", "ok", version=1)
    anchor = log.seq_now()
    assert anchor == 90
    for i in range(120):  # crosses the cap repeatedly -> trims fire
        log.note(f"b{i}", "ok", version=2)
    assert log.trimmed > 0
    assert log.seq_now() == 210
    ids = {e["id"] for e in log.since(anchor)}
    # every surviving post-anchor entry is in the window...
    for e in log.entries:
        if e["id"].startswith("b"):
            assert e["id"] in ids
    # ...and nothing from before the anchor leaks in
    assert not any(i.startswith("a") for i in ids)


# -- comparator ---------------------------------------------------------------
def _ok(version, latency_s):
    return {"outcome": "ok", "version": version, "latency_s": latency_s}


def test_comparator_version_windows_and_verdicts():
    from horovod_tpu.serving.rollout import compare, version_windows
    entries = ([_ok(2, 0.01)] * 9 + [_ok(1, 0.01)] * 20
               + [{"outcome": "retried", "after_version": 2}]
               + [{"outcome": "accepted", "id": "x"}])  # ignored
    stats = version_windows(entries, [2, 1])
    assert stats[2]["ok"] == 9 and stats[2]["errors"] == 1
    assert stats[2]["requests"] == 10
    assert stats[2]["error_rate"] == pytest.approx(0.1)
    assert stats[1] == {"version": 1, "requests": 20, "ok": 20,
                        "errors": 0, "error_rate": 0.0,
                        "p50_s": 0.01, "p99_s": 0.01}
    # insufficient traffic outranks everything: no verdict on noise
    v, reason = compare(stats[2], stats[1], min_requests=50,
                        max_p99_ratio=2.0, max_error_rate=0.05)
    assert v is None and "insufficient" in reason
    # error rate over the cap AND over the incumbent's -> rollback
    v, reason = compare(stats[2], stats[1], min_requests=10,
                        max_p99_ratio=2.0, max_error_rate=0.05)
    assert v == "rollback" and "error rate" in reason
    # p99 beyond the allowed ratio -> rollback
    slow = version_windows([_ok(2, 0.5)] * 10 + [_ok(1, 0.01)] * 10,
                           [2, 1])
    v, reason = compare(slow[2], slow[1], min_requests=10,
                        max_p99_ratio=2.0, max_error_rate=0.05)
    assert v == "rollback" and "p99" in reason
    # healthy canary -> promote
    good = version_windows([_ok(2, 0.011)] * 10 + [_ok(1, 0.01)] * 10,
                           [2, 1])
    v, reason = compare(good[2], good[1], min_requests=10,
                        max_p99_ratio=2.0, max_error_rate=0.05)
    assert v == "promote"
    # the golden probe outranks latency: a FAST canary with wrong math
    # still rolls back
    v, reason = compare(good[2], good[1], min_requests=10,
                        max_p99_ratio=2.0, max_error_rate=0.05,
                        golden_divergence=49.0, golden_max=0.5)
    assert v == "rollback" and "golden" in reason


def test_comparator_percentiles_are_fractions_not_percents():
    """Regression: percentile() takes a fraction in [0,1] — passing
    50.0/99.0 clamps to max() and both p50 and p99 become the single
    worst sample, so one slow outlier on the canary could spuriously
    roll back a healthy candidate.  On a skewed list p50 != p99."""
    from horovod_tpu.serving.rollout import version_windows
    entries = [_ok(2, 0.01)] * 9 + [_ok(2, 1.0)]  # one slow outlier
    stats = version_windows(entries, [2])
    assert stats[2]["p50_s"] == pytest.approx(0.01)
    assert stats[2]["p99_s"] == pytest.approx(1.0)
    assert stats[2]["p50_s"] != stats[2]["p99_s"]


def test_golden_set_loader_and_divergence(tmp_path):
    from horovod_tpu.serving.rollout import (golden_divergence,
                                             load_golden_set)
    p = tmp_path / "golden.json"
    p.write_text(json.dumps({"requests": [{"x": [1.0, 2.0]}]}))
    assert load_golden_set(str(p)) == [{"x": [1.0, 2.0]}]
    bare = tmp_path / "bare.json"
    bare.write_text(json.dumps([{"x": [3.0]}]))
    assert load_golden_set(str(bare)) == [{"x": [3.0]}]
    # malformed sets fail LOUDLY — a quality gate whose probe set
    # silently failed to load is a gate that never fires
    empty = tmp_path / "empty.json"
    empty.write_text("[]")
    with pytest.raises(ValueError, match="no requests"):
        load_golden_set(str(empty))
    nox = tmp_path / "nox.json"
    nox.write_text(json.dumps([{"y": [1.0]}]))
    with pytest.raises(ValueError, match="no 'x'"):
        load_golden_set(str(nox))
    # divergence: max |y_canary - y_incumbent| over the fixed set
    a, b = _VersionStub(5), _VersionStub(2)
    try:
        d = golden_divergence(a.endpoint, b.endpoint,
                              [{"x": [1.0, 2.0]}, {"x": [0.0]}])
        assert d == pytest.approx(3.0)
        assert a.hits == 2 and b.hits == 2
    finally:
        a.close()
        b.close()


# -- controller state machine -------------------------------------------------
def test_controller_state_machine_and_persisted_status(tmp_path):
    from horovod_tpu.serving import Router
    from horovod_tpu.serving.rollout import (RolloutConfig,
                                             RolloutController,
                                             read_status)
    fleet = _FakeFleet([0, 1, 2])
    router = Router(lambda: [], max_attempts=2)
    cfg = RolloutConfig(canary_pct=34, expand_pct=50, window_s=60.0,
                        min_requests=5)
    ctl = RolloutController(fleet, router, cfg,
                            store_dir=str(tmp_path))
    try:
        assert ctl.state == "idle"
        assert ctl.evaluate(force=True) is None  # nothing to measure
        ctl.begin(candidate=7, incumbent=6)
        assert ctl.state == "canary"
        assert ctl.canary_slots == [0]  # 3 slots at 34% -> exactly one
        pins = {c["slot"]: c for c in fleet.pin_calls}
        # canary pinned to the candidate, HEALING at the incumbent
        assert pins[0]["version"] == 7 and pins[0]["heal"] == 6
        # the rest pinned to the incumbent (unpinned would chase the
        # candidate and silently widen the canary)
        assert pins[1]["version"] == 6 and pins[1]["heal"] is None
        assert pins[2]["version"] == 6
        assert router.version_split() == {
            "pct": 34, "canary_version": 7, "incumbent_version": 6}
        with pytest.raises(RuntimeError, match="already in progress"):
            ctl.begin(candidate=8, incumbent=7)
        # the stage window is still open -> no verdict; forcing with
        # zero traffic is still insufficient evidence
        assert ctl.evaluate() is None
        assert ctl.evaluate(force=True) is None
        # a stale finding from a previous rollout is ignored
        ctl._on_promote({"rollout_id": "rollout-999-v9"})
        assert ctl.state == "canary"
        ctl._on_promote({"rollout_id": ctl.rollout_id})
        assert ctl.state == "expanding"
        assert router.version_split()["pct"] == 50
        fleet.pin_calls.clear()
        ctl._on_promote({"rollout_id": ctl.rollout_id})
        assert ctl.state == "promoted"
        assert router.version_split() is None
        assert ctl.canary_slots == []
        # every slot flipped to the candidate, then released to chase
        for s in (0, 1, 2):
            calls = [c for c in fleet.pin_calls if c["slot"] == s]
            assert calls[0]["version"] == 7
            assert calls[-1]["version"] is None
        # durable status answers from OUTSIDE the controller process
        doc = read_status(str(tmp_path))
        assert doc["state"] == "promoted"
        assert doc["rollout_id"] == ctl.rollout_id
        assert doc["trace"] == ctl.trace.trace_id
        assert [h["to"] for h in doc["history"]] == [
            "canary", "expanding", "promoted"]
        # a fresh rollout from promoted; the rollback path
        ctl.begin(candidate=9, incumbent=7)
        fleet.pin_calls.clear()
        # the operator escape hatch takes the same path as the hook
        ctl.rollback("test")
        assert ctl.state == "rolled_back"
        assert router.version_split() is None
        # EVERY slot ends pinned to the incumbent — the poisoned
        # candidate is still the newest commit in the store
        for s in (0, 1, 2):
            last = [c for c in fleet.pin_calls if c["slot"] == s][-1]
            assert last["version"] == 7 and last["reason"] == "rollback"
        assert fleet.pinned == {0: 7, 1: 7, 2: 7}
        assert read_status(str(tmp_path))["state"] == "rolled_back"
        # rollback duplicates are idempotent no-ops
        ctl.rollback()
        assert ctl.state == "rolled_back"
    finally:
        router.close()


def test_rollout_refuses_single_slot_fleet():
    """The canary invariant is 'at least 1, never the whole fleet': a
    1-slot fleet has no incumbent arm to compare against, so begin()
    must refuse rather than pin 100% of traffic to the candidate."""
    from horovod_tpu.serving import Router
    from horovod_tpu.serving.rollout import (RolloutConfig,
                                             RolloutController)
    fleet = _FakeFleet([0])
    router = Router(lambda: [], max_attempts=2)
    try:
        ctl = RolloutController(fleet, router, RolloutConfig())
        with pytest.raises(RuntimeError, match="at least 2"):
            ctl.begin(candidate=2, incumbent=1)
        assert ctl.state == "idle"
        assert fleet.pin_calls == []  # nothing was pinned
        assert router.version_split() is None
    finally:
        router.close()


def test_controller_stage_window_survives_log_trim(monkeypatch,
                                                   tmp_path):
    """Regression: the stage window is anchored on the request log's
    absolute sequence number — when the in-memory cap trims head
    entries mid-stage, the verdict still sees every surviving
    current-stage line (an index anchor would have silently dropped
    the trimmed count from the window and starved the verdict)."""
    from horovod_tpu.serving import Router
    from horovod_tpu.serving.router import RequestLog
    from horovod_tpu.serving.rollout import (RolloutConfig,
                                             RolloutController)
    monkeypatch.setattr(RequestLog, "MAX_MEMORY", 200)
    fleet = _FakeFleet([0, 1])
    router = Router(lambda: [], max_attempts=2)
    cfg = RolloutConfig(canary_pct=50, window_s=0.0, min_requests=60)
    ctl = RolloutController(fleet, router, cfg,
                            store_dir=str(tmp_path))
    try:
        for i in range(150):  # pre-stage traffic advances the anchor
            router.log.note(f"pre-{i}", "ok", version=1,
                            latency_s=0.01)
        ctl.begin(candidate=2, incumbent=1)
        for i in range(100):  # stage traffic crosses the cap -> trims
            router.log.note(f"c2-{i}", "ok", version=2,
                            latency_s=0.01)
            router.log.note(f"c1-{i}", "ok", version=1,
                            latency_s=0.01)
        assert router.log.trimmed > 0  # trims actually fired
        f = ctl.evaluate(force=True)
        assert f is not None and f["verdict"] == "promote"
        # both arms kept (nearly) all their surviving stage evidence
        assert f["canary_stats"]["requests"] >= 60
        assert f["incumbent_stats"]["requests"] >= 60
    finally:
        router.close()


def test_governed_rollout_end_to_end_in_process(monkeypatch, tmp_path,
                                                capsys):
    """evaluate -> rollout_verdict finding -> autopilot decision ->
    registered hook, fully in process under act: a healthy candidate
    walks canary -> expanding -> promoted, a degraded one rolls back —
    and each rollout's finding, decision and transitions share ONE
    trace id whose tree `diagnostics trace <id>` prints."""
    import horovod_tpu.autopilot as autopilot
    from horovod_tpu.diagnostics.flight_recorder import recorder
    from horovod_tpu.metrics import anomaly
    from horovod_tpu.serving import Router
    from horovod_tpu.serving.rollout import (RolloutConfig,
                                             RolloutController)
    monkeypatch.setenv("HVD_TPU_AUTOPILOT", "act")
    autopilot.reset()
    anomaly.reset()
    fleet = _FakeFleet([0, 1])
    router = Router(lambda: [], max_attempts=2)
    cfg = RolloutConfig(canary_pct=50, window_s=0.01, min_requests=5)
    ctl = RolloutController(fleet, router, cfg, store_dir=str(tmp_path)
                            ).register_autopilot_hooks()

    def _feed(version, latency_s, n=8):
        for i in range(n):
            router.log.note(f"f{version}-{time.monotonic_ns()}-{i}",
                            "ok", version=version, latency_s=latency_s)

    try:
        ctl.begin(candidate=2, incumbent=1)
        trace_id = ctl.trace.trace_id
        _feed(2, 0.01)
        _feed(1, 0.01)
        time.sleep(0.05)  # past the stage window
        finding = ctl.evaluate()
        assert finding is not None and finding["verdict"] == "promote"
        assert finding["trace"] == trace_id  # continues the rollout
        assert _wait(lambda: ctl.state == "expanding", timeout=5)
        # the expanding stage measures a FRESH window
        assert ctl.evaluate(force=True) is None  # no evidence yet
        _feed(2, 0.01)
        _feed(1, 0.01)
        time.sleep(1.1)  # rollout-promote cooldown between fires
        assert ctl.evaluate(force=True)["verdict"] == "promote"
        assert _wait(lambda: ctl.state == "promoted", timeout=5)
        promoted = [d for d in autopilot.recent_decisions()
                    if d["policy"] == "rollout-promote"
                    and d["outcome"] == "fired"]
        assert len(promoted) == 2
        assert all(d["trace"] == trace_id for d in promoted)

        # a poisoned candidate: degraded p99 rolls back autonomously
        ctl.begin(candidate=3, incumbent=2)
        t2 = ctl.trace.trace_id
        assert t2 != trace_id  # each rollout is its own causal tree
        _feed(3, 0.5)
        _feed(2, 0.01)
        f2 = ctl.evaluate(force=True)
        assert f2["verdict"] == "rollback" and "p99" in f2["reason"]
        assert f2["trace"] == t2
        assert _wait(lambda: ctl.state == "rolled_back", timeout=5)
        assert fleet.pinned == {0: 2, 1: 2}
        rb = [d for d in autopilot.recent_decisions()
              if d["policy"] == "rollout-rollback"
              and d["outcome"] == "fired"]
        assert len(rb) == 1 and rb[0]["trace"] == t2
        # the CLI prints the rollback's causal tree from the flight dump
        dump = tmp_path / "flight_rank0.json"
        recorder().dump_to(str(dump))
        from horovod_tpu.diagnostics.__main__ import main as diag_main
        rc = diag_main(["trace", t2, "--flight", str(dump)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "rollout" in out and "rolled_back" in out
    finally:
        router.close()
        autopilot.reset()
        anomaly.reset()


# -- CLI ----------------------------------------------------------------------
def test_rollout_status_cli(tmp_path, capsys):
    from horovod_tpu.serving import Router
    from horovod_tpu.serving.__main__ import main as serving_main
    from horovod_tpu.serving.rollout import (RolloutConfig,
                                             RolloutController)
    rc = serving_main(["rollout", "status", "--store-dir",
                       str(tmp_path)])
    assert rc == 1
    assert "no status" in capsys.readouterr().out
    router = Router(lambda: [], max_attempts=2)
    try:
        ctl = RolloutController(_FakeFleet([0, 1]), router,
                                RolloutConfig(canary_pct=50),
                                store_dir=str(tmp_path))
        ctl.begin(candidate=2, incumbent=1)
    finally:
        router.close()
    rc = serving_main(["rollout", "status", "--store-dir",
                       str(tmp_path)])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["state"] == "canary" and doc["candidate"] == 2
    assert doc["split"]["pct"] == 50


# -- bench gate ---------------------------------------------------------------
def _rollout_doc(**over):
    doc = {"bench": "rollout", "replicas": 3, "clients": 4,
           "requests": 500, "failed": 0, "unanswered": 0,
           "answered_twice": 0, "by_version": {"1": 300, "2": 200},
           "promote_s": 0.03, "rollback_s": 0.02,
           "final_state": "promoted"}
    doc.update(over)
    return doc


def test_check_bench_rollout_gate(tmp_path):
    import sys as _sys
    _sys.path.insert(0, REPO)
    try:
        from ci.check_bench import (_load_rollout_doc, check_rollout,
                                    rollout_main)
    finally:
        _sys.path.remove(REPO)
    # extraction: raw JSON and captured BENCH_ROLLOUT line both load
    raw = tmp_path / "BENCH_ROLLOUT.json"
    raw.write_text(json.dumps(_rollout_doc()))
    assert _load_rollout_doc(str(raw))["requests"] == 500
    cap = tmp_path / "out.txt"
    cap.write_text("noise\nBENCH_ROLLOUT " + json.dumps(_rollout_doc())
                   + "\n")
    assert _load_rollout_doc(str(cap))["promote_s"] == 0.03
    # clean artifact passes standalone
    assert not check_rollout(_rollout_doc(), None, 0.5)
    # the zero-drop audit is the gate: any drop/dup refuses the number
    assert check_rollout(_rollout_doc(failed=1), None, 0.5)
    assert check_rollout(_rollout_doc(unanswered=2), None, 0.5)
    assert check_rollout(_rollout_doc(answered_twice=1), None, 0.5)
    assert check_rollout(_rollout_doc(requests=0), None, 0.5)
    # a null transition latency is a FAILURE artifact, not a skip
    assert check_rollout(_rollout_doc(promote_s=None), None, 0.5)
    assert check_rollout(_rollout_doc(rollback_s=None), None, 0.5)
    # regression band vs baseline: beyond tolerance fails, inside holds
    base = _rollout_doc(promote_s=0.02, rollback_s=0.02)
    assert check_rollout(_rollout_doc(promote_s=0.05), base, 0.5)
    assert check_rollout(_rollout_doc(rollback_s=0.05), base, 0.5)
    assert not check_rollout(_rollout_doc(promote_s=0.025,
                                          rollback_s=0.02), base, 0.5)
    # end to end rcs
    assert rollout_main(["--rollout", str(raw), "--baseline",
                         str(raw)]) == 0
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(_rollout_doc(failed=2)))
    assert rollout_main(["--rollout", str(bad)]) == 1


# -- slow: churn + chaos acceptance -------------------------------------------
def _closed_loop(router, clients, stop, errors, dim=4):
    threads = []

    def client(i):
        n = 0
        while not stop.is_set():
            n += 1
            try:
                router.submit([float(i)] + [1.0] * (dim - 1),
                              req_id=f"c{i}-{n}")
            except Exception as e:  # noqa: BLE001 - audit catches all
                errors.append(repr(e))
            time.sleep(0.002)  # pace: the audit, not the ring, is the
            #                    point — don't flood the flight ring

    for i in range(clients):
        t = threading.Thread(target=client, args=(i,), daemon=True)
        t.start()
        threads.append(t)
    return threads


@pytest.mark.slow  # tier-1 budget rule: multiprocess tests are
#                    slow-marked; the serving/chaos CI tiers run them
def test_version_split_survives_canary_churn(tmp_path):
    """Satellite: SIGKILL the canary replica mid-rollout under load —
    zero drop, an idempotent replay is answered by the same version as
    the original, and the healed replacement joins at the INCUMBENT
    version (a crash mid-canary shrinks the canary, never re-grows
    it)."""
    from horovod_tpu.checkpoint import ShardedCheckpointer
    from horovod_tpu.serving import ReplicaFleet, Router
    from horovod_tpu.serving.replica import demo_params
    from horovod_tpu.serving.rollout import (RolloutConfig,
                                             RolloutController)
    store = ShardedCheckpointer(str(tmp_path), rank=0, world_size=1)
    store.save(1, {"params": demo_params(4, scale=1.0)}, wait=True)
    fleet = ReplicaFleet(
        size=3, dim=4, store_dir=str(tmp_path),
        extra_env={"HVD_TPU_SERVING_SWAP_POLL_S": "0.05"}).start(
        ready_timeout_s=120)
    router = Router(fleet.endpoints, hedge_ms=200, max_attempts=8)
    # a controller that only SPLITS (windows effectively disabled):
    # this test is about the mechanics under churn, not verdicts
    cfg = RolloutConfig(canary_pct=34, window_s=3600.0,
                        min_requests=10 ** 9)
    ctl = RolloutController(fleet, router, cfg, store_dir=str(tmp_path))
    stop = threading.Event()
    errors = []
    threads = _closed_loop(router, 4, stop, errors)
    try:
        time.sleep(0.5)
        store.save(2, {"params": demo_params(4, scale=2.0)}, wait=True)
        ctl.begin(candidate=2, incumbent=1)
        [canary_slot] = ctl.canary_slots
        assert _wait(lambda: fleet.versions().get(canary_slot) == 2,
                     timeout=30)
        time.sleep(0.5)  # split traffic actually flows
        # idempotent replay: a canary-bucketed id answered twice gets
        # the same version (and, replica-side, the same cached answer)
        rid = next(f"dup-{i}" for i in range(1000)
                   if zlib.crc32(f"dup-{i}".encode()) % 100 < 34)
        a = router.submit([1.0, 1.0, 1.0, 1.0], req_id=rid)
        b = router.submit([9.0, 9.0, 9.0, 9.0], req_id=rid)
        assert a["version"] == b["version"] == 2
        assert a["y"] == b["y"]
        victim = fleet._replicas[canary_slot]
        os.kill(victim.proc.pid, signal.SIGKILL)
        assert _wait(lambda: fleet.live_count() == 3, timeout=90,
                     step=0.25), "fleet did not heal"
        # the replacement joined at the INCUMBENT (heal pin), not the
        # candidate the slot was canarying
        assert _wait(lambda: fleet.versions().get(canary_slot) == 1,
                     timeout=30)
        assert fleet.pins().get(canary_slot) == 1
        time.sleep(0.5)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=15)
        router.close()
    acct = router.accounting()
    exits = list(fleet.exits)
    fleet.stop()
    store.close()
    # the zero-drop audit across the kill + heal
    assert not errors, errors[:3]
    assert acct["accepted"] == acct["answered_ok"] > 0
    assert not acct["unanswered"] and not acct["answered_twice"]
    assert acct["outcomes"].get("failed", 0) == 0
    # both versions actually took traffic under the split
    assert acct["by_version"].get(2, 0) > 0
    assert acct["by_version"].get(1, 0) > 0
    kills = [e for e in exits if e["outcome"] == "failure"]
    assert len(kills) == 1 and kills[0]["rc"] == -9


@pytest.mark.slow
def test_chaos_poisoned_commit_rolls_back_clean_commit_promotes(
        tmp_path, monkeypatch, capsys):
    """ISSUE 18 acceptance: a poisoned commit (silently-wrong math,
    served FAST — only the golden probe can see it) is canaried at
    34%, caught by the per-version comparator, and auto-rolled-back by
    the autopilot with ZERO failed requests; a clean commit then
    promotes fleet-wide.  Both transitions each resolve to a single
    trace id whose causal tree `diagnostics trace <id>` prints."""
    import horovod_tpu.autopilot as autopilot
    from horovod_tpu.checkpoint import ShardedCheckpointer
    from horovod_tpu.diagnostics.flight_recorder import recorder
    from horovod_tpu.metrics import anomaly
    from horovod_tpu.serving import ReplicaFleet, Router
    from horovod_tpu.serving.replica import demo_params
    from horovod_tpu.serving.rollout import (RolloutConfig,
                                             RolloutController)
    monkeypatch.setenv("HVD_TPU_AUTOPILOT", "act")
    autopilot.reset()
    anomaly.reset()
    golden = tmp_path / "golden.json"
    golden.write_text(json.dumps(
        {"requests": [{"x": [4.0, 0.0, 0.0, 0.0]}]}))
    store_dir = tmp_path / "store"
    store = ShardedCheckpointer(str(store_dir), rank=0, world_size=1)
    store.save(1, {"params": demo_params(4, scale=1.0)}, wait=True)
    fleet = ReplicaFleet(
        size=3, dim=4, store_dir=str(store_dir),
        extra_env={"HVD_TPU_SERVING_SWAP_POLL_S": "0.05"}).start(
        ready_timeout_s=120)
    router = Router(fleet.endpoints, hedge_ms=200, max_attempts=8)
    cfg = RolloutConfig(canary_pct=34, expand_pct=50, window_s=0.3,
                        min_requests=10, golden_path=str(golden),
                        golden_max=0.5)
    ctl = RolloutController(fleet, router, cfg, store_dir=str(store_dir)
                            ).register_autopilot_hooks()
    stop = threading.Event()
    errors = []
    threads = _closed_loop(router, 4, stop, errors)
    dump_rollback = tmp_path / "flight_rollback_rank0.json"
    dump_promote = tmp_path / "flight_promote_rank0.json"
    try:
        time.sleep(0.5)
        # ---- the poisoned commit: y = 50*mean(x) instead of mean(x),
        # served exactly as fast as the incumbent
        store.save(2, {"params": demo_params(4, scale=50.0)}, wait=True)
        ctl.begin(candidate=2, incumbent=1)
        [canary_slot] = ctl.canary_slots
        poisoned_trace = ctl.trace.trace_id
        assert _wait(lambda: fleet.versions().get(canary_slot) == 2,
                     timeout=30)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline \
                and ctl.state != "rolled_back":
            ctl.evaluate()
            time.sleep(0.1)
        assert ctl.state == "rolled_back", ctl.status()
        # every replica repinned to the incumbent, although the
        # poisoned candidate is still the newest commit in the store
        assert _wait(lambda: all(
            v == 1 for v in fleet.versions().values()), timeout=30)
        assert all(v == 1 for v in fleet.pins().values())
        recorder().dump_to(str(dump_rollback))  # before ring wraps
        time.sleep(0.5)  # post-rollback traffic, all on the incumbent
        # ---- the clean commit promotes canary -> 50% -> fleet-wide
        store.save(3, {"params": demo_params(4, scale=1.0)}, wait=True)
        ctl.begin(candidate=3, incumbent=1)
        clean_trace = ctl.trace.trace_id
        assert clean_trace != poisoned_trace
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline and ctl.state != "promoted":
            ctl.evaluate()
            time.sleep(0.2)
        assert ctl.state == "promoted", ctl.status()
        assert _wait(lambda: all(
            v == 3 for v in fleet.versions().values()), timeout=30)
        recorder().dump_to(str(dump_promote))
        time.sleep(0.5)  # post-promotion traffic on the new version
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=15)
        router.close()
    acct = router.accounting()
    exits = list(fleet.exits)
    fleet.stop()
    store.close()
    # ZERO failed requests through BOTH transitions: the request-log
    # audit proves every accepted request was answered exactly once
    assert not errors, errors[:3]
    assert acct["accepted"] == acct["answered_ok"] > 0
    assert not acct["unanswered"] and not acct["answered_twice"]
    assert acct["outcomes"].get("failed", 0) == 0
    assert not [e for e in exits if e["outcome"] == "failure"], exits
    # the canary arm actually took candidate traffic before rollback
    assert acct["by_version"].get(2, 0) > 0
    assert acct["by_version"].get(3, 0) > 0
    # the AUTOPILOT (not the test) drove both transitions, and each
    # decision continues its rollout's trace
    rb = [d for d in autopilot.recent_decisions()
          if d["policy"] == "rollout-rollback"
          and d["outcome"] == "fired"]
    pr = [d for d in autopilot.recent_decisions()
          if d["policy"] == "rollout-promote"
          and d["outcome"] == "fired"]
    assert len(rb) == 1 and rb[0]["trace"] == poisoned_trace
    assert len(pr) == 2
    assert all(d["trace"] == clean_trace for d in pr)
    # each transition is ONE causal tree the CLI prints end to end
    from horovod_tpu.diagnostics.__main__ import main as diag_main
    for tid, dump, marker in (
            (poisoned_trace, dump_rollback, "rolled_back"),
            (clean_trace, dump_promote, "promoted")):
        rc = diag_main(["trace", tid, "--flight", str(dump)])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "rollout" in out and marker in out
    autopilot.reset()
    anomaly.reset()
