"""Data-plane integrity battery (ISSUE 13; docs/CHAOS.md "Wire
integrity", docs/TROUBLESHOOTING.md "My loss went NaN / my replicas
disagree"):

* CRC32C unit vectors against the exported C function, and the chaos
  ``bit_flip`` / ``grad`` plan schema;
* the numeric guardrail's skip-step EXACTNESS — a chaos-NaN'd step's
  trajectory is identical to a clean run with that one update removed,
  on both the overlap (pure-DP) and pipeline (dp x pp) factories —
  plus skip counting and the ``grad_nonfinite`` escalation;
* canary digest determinism across mesh layouts and the majority-vote
  attribution;
* ``restore_latest`` falling back past a corrupt newest checkpoint;
* the ``quarantine_rank`` / ``rollback_restore`` autopilot wiring;
* (slow) the 2-process wire bit_flip pair — detected + recovered with
  the checksum on, silently wrong with it off — and the 3-process
  acceptance pair: a chaos-divergent replica autonomously quarantined
  (drained, host blocklisted with digest evidence, world healed to
  full size) under ``HVD_TPU_AUTOPILOT=act``, the identical decision
  recorded and nothing acted under ``observe``.
"""

import ctypes
import json
import os
import socket
import sys
import textwrap
import time

import numpy as np
import pytest

from horovod_tpu import chaos
from horovod_tpu.chaos.plan import (FaultPlanError, compile_transport_spec,
                                    parse_plan)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
INTEGRITY_WORKER = os.path.join(os.path.dirname(__file__),
                                "integrity_worker.py")


@pytest.fixture(autouse=True)
def _fresh_state(monkeypatch):
    from horovod_tpu import autopilot
    from horovod_tpu.metrics import anomaly
    monkeypatch.delenv("HVD_TPU_FAULT_PLAN", raising=False)
    monkeypatch.delenv("HVD_TPU_GUARD", raising=False)
    monkeypatch.delenv("HVD_TPU_CANARY_EVERY", raising=False)
    monkeypatch.delenv("HVD_TPU_AUTOPILOT", raising=False)
    monkeypatch.setenv("HVD_TPU_PROFILE_ON_ANOMALY", "0")
    chaos.uninstall()
    anomaly.reset()
    autopilot.reset()
    yield
    chaos.uninstall()
    anomaly.reset()
    autopilot.reset()


def _arm(monkeypatch, plan: dict):
    monkeypatch.setenv("HVD_TPU_FAULT_PLAN", json.dumps(plan))
    return chaos.install(rank=0)


# -- CRC32C -------------------------------------------------------------------

def _crc_fn():
    from horovod_tpu.core import _lib_path, core_available
    if not core_available():
        pytest.skip("libhvdcore.so not built")
    lib = ctypes.CDLL(_lib_path())
    lib.hvd_crc32c.restype = ctypes.c_uint32
    lib.hvd_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_longlong]
    return lambda b: lib.hvd_crc32c(b, len(b))


def test_crc32c_published_vectors():
    """The wire check runs THIS function per frame (cpp/wire.h): hold
    it to the published Castagnoli vectors."""
    crc = _crc_fn()
    assert crc(b"123456789") == 0xE3069283  # the canonical check value
    assert crc(b"") == 0x00000000
    assert crc(b"\x00" * 32) == 0x8A9136AA  # iSCSI 32-zeros vector


def test_crc32c_flip_roundtrip():
    """A single-bit flip anywhere must change the digest — the mismatch
    the recv-side verification keys on."""
    crc = _crc_fn()
    payload = bytes(range(256)) * 8
    base = crc(payload)
    for off in (0, len(payload) // 2, len(payload) - 1):
        flipped = bytearray(payload)
        flipped[off] ^= 0x01
        assert crc(bytes(flipped)) != base, off


# -- chaos plan schema: bit_flip + grad ---------------------------------------

def test_bit_flip_rule_parses_and_compiles():
    plan = parse_plan(json.dumps({"faults": [
        {"seam": "transport.send", "kind": "bit_flip", "rank": 1,
         "peer": 0, "count": 1, "min_bytes": 1024}]}))
    spec = compile_transport_spec(plan, rank=1)
    assert "kind=bit_flip" in spec and "minb=1024" in spec \
        and "fires=1" in spec, spec
    # the rule is rank-scoped: rank 0 compiles an empty spec
    assert compile_transport_spec(plan, rank=0) == ""


def test_min_bytes_only_for_bit_flip():
    with pytest.raises(FaultPlanError, match="min_bytes"):
        parse_plan(json.dumps({"faults": [
            {"seam": "transport.send", "kind": "drop",
             "min_bytes": 64}]}))


def test_grad_seam_validation():
    # nan/inf need no parameters
    parse_plan(json.dumps({"faults": [
        {"seam": "grad", "kind": "nan", "rank": 0, "start": 3}]}))
    # scale requires a meaningful factor
    with pytest.raises(FaultPlanError, match="factor"):
        parse_plan(json.dumps({"faults": [
            {"seam": "grad", "kind": "scale", "rank": 0}]}))
    with pytest.raises(FaultPlanError, match="factor"):
        parse_plan(json.dumps({"faults": [
            {"seam": "grad", "kind": "scale", "factor": 1.0}]}))
    # factor is meaningless elsewhere
    with pytest.raises(FaultPlanError, match="factor"):
        parse_plan(json.dumps({"faults": [
            {"seam": "step", "kind": "stall", "stall_s": 1,
             "factor": 2.0}]}))
    # unknown kind still rejected
    with pytest.raises(FaultPlanError, match="kind"):
        parse_plan(json.dumps({"faults": [
            {"seam": "grad", "kind": "flip"}]}))


def test_grad_injection_codes(monkeypatch):
    _arm(monkeypatch, {"faults": [
        {"seam": "grad", "kind": "scale", "rank": 0, "start": 2,
         "stop": 4, "factor": 8.0},
        {"seam": "grad", "kind": "nan", "rank": 0, "start": 7,
         "stop": 8}]})
    assert chaos.grad_rules_armed()
    assert chaos.grad_injection(0) == (0, 0.0)
    assert chaos.grad_injection(2) == (3, 8.0)
    assert chaos.grad_injection(3) == (3, 8.0)
    assert chaos.grad_injection(4) == (0, 0.0)
    assert chaos.grad_injection(7) == (1, 0.0)
    chaos.uninstall()
    assert not chaos.grad_rules_armed()
    assert chaos.grad_injection(2) == (0, 0.0)


# -- guard: skip-step exactness ----------------------------------------------

def _toy_overlap():
    import jax
    import jax.numpy as jnp
    import optax

    mesh = jax.make_mesh((8,), ("dp",))

    def loss_fn(p, b):
        x, y = b
        return jnp.mean((x @ p["w"] - y) ** 2)

    tx = optax.adam(1e-2)
    rng = np.random.RandomState(0)
    w0 = rng.randn(4, 2).astype(np.float32)
    batches = [(jnp.asarray(rng.randn(16, 4).astype(np.float32)),
                jnp.asarray(rng.randn(16, 2).astype(np.float32)))
               for _ in range(6)]

    def fresh():
        p = {"w": jnp.asarray(w0)}
        return p, tx.init(p)

    return mesh, loss_fn, tx, batches, fresh


def _run_overlap(mesh, loss_fn, tx, batches, fresh, skip_at=None,
                 **kwargs):
    from horovod_tpu.train.overlap import make_overlap_train_step
    step = make_overlap_train_step(loss_fn, tx, mesh, "dp", **kwargs)
    p, o = fresh()
    for i, b in enumerate(batches):
        if i == skip_at:
            continue
        p, o, _loss = step(p, o, b)
    if hasattr(step, "flush"):
        step.flush()
    return np.asarray(p["w"]), step


def test_guard_skip_step_exactness(monkeypatch):
    """The acceptance exactness bar: a chaos grad-NaN at step 3 yields
    a SKIPPED step whose trajectory matches the clean run everywhere
    else — final params equal a clean run over the same batches with
    batch 3's update removed, bit for bit."""
    from horovod_tpu.diagnostics.flight_recorder import recorder
    from horovod_tpu.metrics import anomaly
    mesh, loss_fn, tx, batches, fresh = _toy_overlap()
    _arm(monkeypatch, {"faults": [
        {"seam": "grad", "kind": "nan", "rank": 0, "start": 3,
         "stop": 4}]})
    faulted, fstep = _run_overlap(mesh, loss_fn, tx, batches, fresh)
    assert fstep.observer.skipped == 1
    assert np.all(np.isfinite(faulted))
    chaos.uninstall()
    ref, rstep = _run_overlap(mesh, loss_fn, tx, batches, fresh,
                              skip_at=3)
    assert rstep.observer.skipped == 0
    np.testing.assert_array_equal(faulted, ref)
    # the skip is observable: flight event + NO escalation at one skip
    assert any(e["kind"] == "guard_skip"
               for e in recorder().events())
    assert not [f for f in anomaly.recent_findings()
                if f["kind"] == "grad_nonfinite"]


def test_guard_escalates_consecutive_skips(monkeypatch):
    """HVD_TPU_GUARD_ESCALATE consecutive skips become a
    ``grad_nonfinite`` anomaly finding — the rollback policy's
    subscription."""
    from horovod_tpu.metrics import anomaly
    mesh, loss_fn, tx, batches, fresh = _toy_overlap()
    monkeypatch.setenv("HVD_TPU_GUARD_ESCALATE", "3")
    _arm(monkeypatch, {"faults": [
        {"seam": "grad", "kind": "inf", "rank": 0, "start": 1,
         "stop": 4}]})
    _w, step = _run_overlap(mesh, loss_fn, tx, batches, fresh)
    assert step.observer.skipped == 3
    found = [f for f in anomaly.recent_findings()
             if f["kind"] == "grad_nonfinite"]
    assert found and found[0]["consecutive"] == 3, found


def test_guard_norm_cap_skips_finite_spike(monkeypatch):
    """A finite scale-spike sails past the finiteness check but not the
    norm cap."""
    mesh, loss_fn, tx, batches, fresh = _toy_overlap()
    monkeypatch.setenv("HVD_TPU_GUARD_MAX_NORM", "10.0")
    _arm(monkeypatch, {"faults": [
        {"seam": "grad", "kind": "scale", "rank": 0, "start": 2,
         "stop": 3, "factor": 1e6}]})
    _w, step = _run_overlap(mesh, loss_fn, tx, batches, fresh)
    assert step.observer.skipped == 1
    assert np.all(np.isfinite(_w))


def test_guard_off_restores_prepipeline_step():
    """HVD_TPU_GUARD=0 / guard=False compiles the exact pre-guard step:
    a plain jitted callable, three outputs, no wrapper."""
    from horovod_tpu.train import guard as guard_mod
    mesh, loss_fn, tx, batches, fresh = _toy_overlap()
    w_off, step_off = _run_overlap(mesh, loss_fn, tx, batches, fresh,
                                   guard=False)
    assert not isinstance(step_off, guard_mod.GuardedStep)
    # and a clean guarded run lands on the identical trajectory
    w_on, _ = _run_overlap(mesh, loss_fn, tx, batches, fresh)
    np.testing.assert_array_equal(w_off, w_on)


def test_pipeline_guard_skip_exactness(monkeypatch):
    """Same exactness bar on the composed dp x pp factory: the verdict
    scalar is psum'd over pp, so every stage skips (or applies) the
    same step."""
    import jax.numpy as jnp
    import optax

    from horovod_tpu.train.pipeline import make_pipeline_train_step

    def layer_fn(lp, x):
        return jnp.tanh(x @ lp["w"])

    def loss_fn(y, t):
        return jnp.mean((y - t) ** 2)

    tx = optax.sgd(1e-2)
    rng = np.random.RandomState(1)
    L, D = 4, 4
    ws = rng.randn(L, D, D).astype(np.float32) * 0.3
    batches = [(jnp.asarray(rng.randn(16, D).astype(np.float32)),
                jnp.asarray(rng.randn(16, D).astype(np.float32)))
               for _ in range(5)]

    def run(skip_at=None):
        step = make_pipeline_train_step(
            layer_fn, loss_fn, tx, n_layers=L, pp=2, schedule="1f1b",
            n_micro=2)
        p = step.prepare_params({"w": jnp.asarray(ws)})
        o = step.prepare_params(tx.init({"w": jnp.asarray(ws)}))
        for i, b in enumerate(batches):
            if i == skip_at:
                continue
            p, o, _l = step(p, o, b)
        step.flush()
        return np.asarray(step.restore_params(p)["w"]), step

    _arm(monkeypatch, {"faults": [
        {"seam": "grad", "kind": "nan", "rank": 0, "start": 2,
         "stop": 3}]})
    faulted, fstep = run()
    assert fstep.observer.skipped == 1
    assert np.all(np.isfinite(faulted))
    chaos.uninstall()
    ref, _ = run(skip_at=2)
    np.testing.assert_array_equal(faulted, ref)


def test_pipeline_pp1_degenerate_exposes_guard_surface(monkeypatch):
    """The pp==1 degenerate path nests the guard-wrapped overlap step
    INSIDE the PipelineTrainStep shell — flush()/observer must stay
    reachable through it (review regression), and the final step's
    deferred verdict must be drainable."""
    import jax.numpy as jnp
    import optax

    from horovod_tpu.train.pipeline import make_pipeline_train_step

    def layer_fn(lp, x):
        return jnp.tanh(x @ lp["w"])

    def loss_fn(y, t):
        return jnp.mean((y - t) ** 2)

    tx = optax.sgd(1e-2)
    rng = np.random.RandomState(2)
    ws = rng.randn(2, 4, 4).astype(np.float32) * 0.3
    _arm(monkeypatch, {"faults": [
        {"seam": "grad", "kind": "nan", "rank": 0, "start": 1,
         "stop": 2}]})
    step = make_pipeline_train_step(layer_fn, loss_fn, tx, n_layers=2,
                                    pp=1, n_micro=2)
    # the guard surface is reachable BEFORE the first call too
    assert step.observer.skipped == 0
    p = step.prepare_params({"w": jnp.asarray(ws)})
    o = step.prepare_params(tx.init({"w": jnp.asarray(ws)}))
    for i in range(2):
        b = (jnp.asarray(rng.randn(16, 4).astype(np.float32)),
             jnp.asarray(rng.randn(16, 4).astype(np.float32)))
        p, o, _l = step(p, o, b)
    step.flush()  # drains the LAST step's deferred verdict
    assert step.observer.skipped == 1
    assert np.all(np.isfinite(np.asarray(p["w"])))


# -- canary -------------------------------------------------------------------

def test_canary_digest_deterministic_across_mesh_layouts():
    """The digest is a function of the logical values, not the
    placement: the same parameters sharded over dp8 and over
    dp2 x sp2 x tp2 digest identically; perturbing one element
    changes it."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.train.guard import param_digest

    rng = np.random.RandomState(3)
    tree_np = {"w": rng.randn(8, 16).astype(np.float32),
               "b": rng.randn(8).astype(np.float32)}
    base = param_digest(tree_np)

    mesh1 = jax.make_mesh((8,), ("dp",))
    mesh2 = jax.make_mesh((2, 2, 2), ("dp", "sp", "tp"))
    t1 = {k: jax.device_put(v, NamedSharding(mesh1, P("dp")))
          for k, v in tree_np.items()}
    t2 = {"w": jax.device_put(tree_np["w"],
                              NamedSharding(mesh2, P("sp", "tp"))),
          "b": jax.device_put(tree_np["b"],
                              NamedSharding(mesh2, P("dp")))}
    assert param_digest(t1) == base
    assert param_digest(t2) == base

    perturbed = {"w": tree_np["w"].copy(), "b": tree_np["b"]}
    perturbed["w"][0, 0] += 1e-6
    assert param_digest(perturbed) != base
    # determinism across calls (no hidden state)
    assert param_digest(tree_np) == base


def test_canary_majority_attribution():
    from horovod_tpu.train.guard import divergent_ranks
    assert divergent_ranks([7, 7, 9]) == [2]
    assert divergent_ranks([9, 7, 7, 7]) == [0]
    assert divergent_ranks([7, 7, 9, 9, 7]) == [2, 3]
    assert divergent_ranks([7, 7]) == []          # agreement
    assert divergent_ranks([7, 9]) == []          # tie: no attribution
    assert divergent_ranks([7, 7, 9, 9]) == []    # 50/50: no majority
    assert divergent_ranks([1, 2, 3]) == []       # everyone different
    assert divergent_ranks([5]) == []             # nobody to compare


def test_canary_unattributable_mismatch_still_counted(monkeypatch):
    """World-2 coverage (review regression): digests that disagree with
    no strict majority convict nobody — but the mismatch itself must be
    counted and flight-recorded, not read as a green canary."""
    from horovod_tpu.diagnostics.flight_recorder import recorder
    from horovod_tpu.metrics.registry import default_registry
    from horovod_tpu.train.guard import ReplicaCanary
    import horovod_tpu.common.basics as basics
    import horovod_tpu.ops.collectives as coll

    monkeypatch.setattr(basics, "is_initialized", lambda: True)
    monkeypatch.setattr(basics, "size", lambda: 2)
    monkeypatch.setattr(basics, "rank", lambda: 0)
    monkeypatch.setattr(
        coll, "allgather",
        lambda v, name=None: np.array([[7], [9]], np.int64))
    before = default_registry().get("hvd_canary_divergence_total")
    before = before.value if before is not None else 0.0
    findings = ReplicaCanary(every=1).check(4, {"w": np.ones(4)})
    assert findings == []  # nobody convicted...
    after = default_registry().get("hvd_canary_divergence_total").value
    assert after == before + 1  # ...but the mismatch is on the record
    assert any(e["kind"] == "canary_mismatch" and e["step"] == 4
               for e in recorder().events())


def test_canary_noop_without_world():
    """In a single process the canary compares nothing (and runs no
    collective)."""
    from horovod_tpu.train.guard import ReplicaCanary
    c = ReplicaCanary(every=2)
    assert c.maybe_check(4, {"w": np.ones(4)}) == []


# -- checkpoint restore fallback ---------------------------------------------

def _corrupt(path):
    b = bytearray(open(path, "rb").read())
    b[len(b) // 2] ^= 0xFF
    open(path, "wb").write(bytes(b))


def test_restore_latest_falls_back_past_corrupt_newest(tmp_path):
    from horovod_tpu.checkpoint.store import ShardedCheckpointer
    from horovod_tpu.diagnostics.flight_recorder import recorder
    from horovod_tpu.metrics.registry import default_registry
    ck = ShardedCheckpointer(str(tmp_path), rank=0, world_size=1)
    ck.save(1, {"w": np.arange(8.0)}, wait=True)
    ck.save(2, {"w": np.arange(8.0) * 2}, wait=True)
    before = default_registry().get(
        "hvd_checkpoint_restore_fallback_total")
    before = before.value if before is not None else 0.0
    _corrupt(str(tmp_path / "step_2" / "shard_0.npz"))
    out = ck.restore_latest()
    np.testing.assert_array_equal(out["w"], np.arange(8.0))
    after = default_registry().get(
        "hvd_checkpoint_restore_fallback_total").value
    assert after == before + 1
    assert any(e["kind"] == "ckpt_restore_fallback" and e["step"] == 2
               and e["fallback_step"] == 1
               for e in recorder().events())


def test_restore_latest_raises_when_every_commit_is_corrupt(tmp_path):
    from horovod_tpu.checkpoint.format import CheckpointError
    from horovod_tpu.checkpoint.store import ShardedCheckpointer
    ck = ShardedCheckpointer(str(tmp_path), rank=0, world_size=1)
    ck.save(1, {"w": np.arange(4.0)}, wait=True)
    ck.save(2, {"w": np.arange(4.0)}, wait=True)
    _corrupt(str(tmp_path / "step_1" / "shard_0.npz"))
    _corrupt(str(tmp_path / "step_2" / "shard_0.npz"))
    with pytest.raises(CheckpointError):
        ck.restore_latest()


# -- autopilot wiring: quarantine + rollback ---------------------------------

def test_quarantine_request_carries_evidence(monkeypatch):
    from horovod_tpu.autopilot import actions as ap_actions
    from horovod_tpu.autopilot.policy import Policy
    from horovod_tpu.runner import kv_relay
    from horovod_tpu.runner.http_kv import KVStoreServer
    srv = KVStoreServer()
    srv.start()
    try:
        monkeypatch.setenv("HVD_ELASTIC_KV", f"127.0.0.1:{srv.port}")
        monkeypatch.setenv("HVD_ELASTIC_GENERATION", "2")
        kv_relay.reset()
        pol = Policy(name="replica-quarantine",
                     finding="replica_divergence",
                     action="quarantine_rank")
        assert ap_actions._request_driver_action(
            "quarantine", 2, pol, {"finding": "replica_divergence"},
            evidence={"step": 12, "digest": 7, "majority": 9})
        entries = srv.scope("action")
        assert len(entries) == 1
        req = json.loads(next(iter(entries.values())))
        assert req["action"] == "quarantine" and req["rank"] == 2
        assert req["evidence"] == {"step": 12, "digest": 7,
                                   "majority": 9}
    finally:
        srv.stop()
        kv_relay.reset()


def test_driver_scans_quarantine_requests_with_evidence():
    from horovod_tpu.runner.elastic.discovery import FixedHosts
    from horovod_tpu.runner.elastic.driver import ElasticDriver, \
        _GenRuntime
    from horovod_tpu.runner.hosts import HostInfo

    class _Alive:
        def is_alive(self):
            return True

    class _Slot:
        def __init__(self, hostname):
            self.hostname = hostname

    driver = ElasticDriver(FixedHosts([HostInfo("localhost", 3)]),
                           ["true"], min_np=1)
    try:
        g = _GenRuntime([], 0, "127.0.0.1", 0)
        for r in (0, 1, 2):
            key = (0, r)
            g.essential_keys.append(key)
            g.current_rank[key] = r
            g.slot_by_key[key] = _Slot("localhost")
            g.threads[key] = _Alive()
        driver._kv.put("action", "1-1", json.dumps(
            {"action": "quarantine", "rank": 2, "generation": 0,
             "policy": "replica-quarantine",
             "evidence": {"digest": 7, "majority": 9}}).encode())
        groups = driver._scan_action_requests(g)
        doomed, meta, tokens = groups["quarantine"]
        assert {g.current_rank[k] for k in doomed} == {2}
        assert meta[0]["policy"] == "replica-quarantine"
        assert meta[0]["evidence"] == {"digest": 7, "majority": 9}
        # without notify registrations nothing is planned (deferred)
        assert not driver._poll_action_requests(g)
        assert not driver._hosts.is_blacklisted("localhost")
    finally:
        driver._kv.stop()


def test_rollback_restore_runs_hooks_under_act_only():
    import threading

    from horovod_tpu.autopilot import actions as ap_actions
    from horovod_tpu.autopilot.engine import PolicyEngine
    from horovod_tpu.autopilot.policy import Policy
    from horovod_tpu.metrics.registry import Registry

    ran = threading.Event()
    ap_actions.register_rollback_hook(ran.set)
    pol = [Policy(name="nonfinite-rollback", finding="grad_nonfinite",
                  action="rollback_restore", cooldown_s=0.0)]
    finding = {"kind": "grad_nonfinite", "step": 9, "consecutive": 3}

    obs = PolicyEngine(policies=pol, registry=Registry(),
                       mode="observe", rank=0)
    d = obs.on_finding(dict(finding))[0]
    assert d["outcome"] == "dry_run"
    assert not ran.wait(0.3), "observe must not act"

    act = PolicyEngine(policies=pol, registry=Registry(), mode="act",
                       rank=0)
    d = act.on_finding(dict(finding))[0]
    assert d["outcome"] == "fired"
    assert ran.wait(5.0), "act must run the rollback hooks"
    from horovod_tpu.diagnostics.flight_recorder import recorder
    deadline = time.time() + 5.0
    while time.time() < deadline:
        if any(e["kind"] == "autopilot_rollback" and e.get("ran") == 1
               for e in recorder().events()):
            break
        time.sleep(0.02)
    assert any(e["kind"] == "autopilot_rollback" and e.get("ran") == 1
               for e in recorder().events())


# -- slow: the 2-process wire bit_flip pair -----------------------------------

def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _launch_pair(extra_env, timeout=180):
    import subprocess
    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.update({
            "HOROVOD_RANK": str(rank), "HOROVOD_SIZE": "2",
            "HVD_TPU_COORD_ADDR": "127.0.0.1",
            "HVD_TPU_COORD_PORT": str(port),
            "HOROVOD_LOCAL_RANK": str(rank),
            "HOROVOD_LOCAL_SIZE": "2",
        })
        env.update(extra_env)
        procs.append(subprocess.Popen(
            [sys.executable, INTEGRITY_WORKER], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    outs, ok = [], True
    for rank, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(f"--- rank {rank} (rc={p.returncode}) ---\n"
                    + out.decode())
        ok = ok and p.returncode == 0
    assert ok, "\n".join(outs)
    return "\n".join(outs)


_BIT_FLIP_PLAN = json.dumps({"faults": [
    {"seam": "transport.send", "kind": "bit_flip", "rank": 1,
     "peer": 0, "count": 1, "min_bytes": 1024}]})


@pytest.mark.slow  # tier-1 budget rule: multiprocess tests are
#                    slow-marked; the chaos/parallel CI tiers run them
def test_wire_bit_flip_detected_named_and_recovered():
    """ISSUE 13 acceptance, detect half: a chaos bit_flip on the eager
    wire is caught by the CRC (peer NAMED in the HorovodInternalError,
    ``transport_checksum_failures`` counted) and the job recovers
    through the elastic path's disarm→re-init→retry mechanics.
    Worker-side assertions in integrity_worker.py."""
    from horovod_tpu.core import core_available
    if not core_available():
        pytest.skip("libhvdcore.so not built")
    out = _launch_pair({"HVD_TPU_FAULT_PLAN": _BIT_FLIP_PLAN,
                        "HVD_TEST_INTEGRITY_MODE": "detect"})
    assert "OK (detect)" in out


@pytest.mark.slow
def test_wire_bit_flip_undetected_without_checksum():
    """The load-bearing proof: the IDENTICAL flip with
    HVD_TPU_WIRE_CHECKSUM=0 completes without any error while the
    allreduce result is silently wrong."""
    from horovod_tpu.core import core_available
    if not core_available():
        pytest.skip("libhvdcore.so not built")
    out = _launch_pair({"HVD_TPU_FAULT_PLAN": _BIT_FLIP_PLAN,
                        "HVD_TEST_INTEGRITY_MODE": "undetect",
                        "HVD_TPU_WIRE_CHECKSUM": "0"})
    assert "OK (undetect)" in out


# -- slow: the quarantine acceptance pair -------------------------------------

def _quarantine_worker_prog(log, flights, metrics_out, finish_step,
                            min_generation):
    """3-process elastic worker: every rank applies the IDENTICAL
    deterministic update per step (replicated-by-construction state);
    the chaos ``grad`` scale rule makes rank 2's math silently wrong
    for three steps — finite, so only the canary can see it."""
    return textwrap.dedent(f"""
        import os, sys, time
        sys.path.insert(0, {REPO!r})
        import numpy as np
        import horovod_tpu as hvd
        from horovod_tpu import chaos, elastic
        from horovod_tpu.diagnostics.flight_recorder import recorder
        from horovod_tpu.train.guard import ReplicaCanary

        orig_rank = int(os.environ["HOROVOD_RANK"])
        hvd.init()
        with open({str(log)!r}, "a") as f:
            f.write(f"BOOT rank={{orig_rank}} pid={{os.getpid()}}\\n")

        state = elastic.ObjectState(
            name="qrun", step=0,
            params=np.zeros(64, np.float64), durable=True)
        canary = ReplicaCanary(every=3)

        @elastic.run
        def train(state):
            while True:
                g = np.full(64, 0.01)
                code, factor = chaos.grad_injection(state.step)
                if code == 3:
                    g = g * factor   # this rank's silently-wrong math
                state.params = state.params + g
                canary.maybe_check(state.step, {{"p": state.params}})
                time.sleep(0.05)
                state.step += 1
                state.commit()
                gen = int(os.environ.get("HVD_ELASTIC_GENERATION", "0"))
                if state.step >= {finish_step} and hvd.size() == 3 \\
                        and gen >= {min_generation}:
                    return True

        train(state)
        state.flush()
        if hvd.rank() == 0:
            from horovod_tpu.metrics.registry import (default_registry,
                                                      render_prometheus)
            with open({str(metrics_out)!r}, "w") as f:
                f.write(render_prometheus(default_registry().snapshot()))
        recorder().dump_to(os.path.join(
            {str(flights)!r}, f"rank{{hvd.rank()}}_pid{{os.getpid()}}.json"))
        with open({str(log)!r}, "a") as f:
            f.write(f"DONE rank={{hvd.rank()}} pid={{os.getpid()}} "
                    f"size={{hvd.size()}} step={{state.step}}\\n")
        hvd.shutdown()
    """)


def _run_quarantine_scenario(tmp_path, monkeypatch, name, mode,
                             min_generation):
    from horovod_tpu.runner.elastic.discovery import FixedHosts
    from horovod_tpu.runner.elastic.driver import ElasticDriver
    from horovod_tpu.runner.hosts import HostInfo
    base = tmp_path / name
    base.mkdir()
    log = base / "events.log"
    flights = base / "flights"
    flights.mkdir()
    obs = base / "obs"
    metrics_out = base / "metrics_rank0.prom"
    plan_file = base / "plan.json"
    # rank 2's gradients are scaled x1.5 at steps 4-6: finite (the
    # guard stays quiet) but divergent — only the canary (every 3
    # steps) can convict it.  The window is closed well before any
    # re-mesh resumes (renumbered ranks must not re-diverge).
    plan_file.write_text(json.dumps({"faults": [
        {"seam": "grad", "kind": "scale", "rank": 2,
         "start": 4, "stop": 7, "factor": 1.5}]}))
    prog = base / "train.py"
    prog.write_text(_quarantine_worker_prog(
        log, flights, metrics_out, finish_step=40,
        min_generation=min_generation))
    env = dict(os.environ)
    env.update({
        "HVD_TPU_FAULT_PLAN": str(plan_file),
        "HVD_TPU_AUTOPILOT": mode,
        "HVD_TPU_OBS_DIR": str(obs),
        "HVD_TPU_CHECKPOINT_DIR": str(base / "ckpt"),
        "HVD_TPU_CHECKPOINT_COMMIT_TIMEOUT_S": "5",
        "HVD_TPU_AUTOPSY_DIR": str(base / "autopsy"),
        "HVD_TPU_METADATA_ENDPOINT": "http://127.0.0.1:1",
        "HVD_TPU_PREEMPTION_POLL_S": "0.5",
        "HVD_TPU_TRANSPORT_TIMEOUT_S": "20",
        # the canary findings are the scenario; device-trace captures
        # on top of them are dead weight here
        "HVD_TPU_PROFILE_ON_ANOMALY": "0",
    })
    env.pop("HVD_TPU_AUTOPILOT_POLICY", None)  # the shipped policy set
    monkeypatch.setenv("HVD_TPU_DRAIN_COOLDOWN_S", "2")
    # the divergent rank sits ALONE on its "host" (ranks 0/1 share
    # localhost), with a spare single-slot host for the replacement —
    # quarantine blocklists the convicted host, so the replacement must
    # have somewhere else to land.  All three names resolve locally.
    hosts = [HostInfo("localhost", 2), HostInfo("127.0.0.1", 1),
             HostInfo(socket.gethostname(), 1)]
    driver = ElasticDriver(
        FixedHosts(hosts),
        [sys.executable, str(prog)],
        min_np=2, max_np=3, target_np=3, reset_limit=4,
        ckpt_dir=str(base), env=env)
    rc = driver.run()
    lines = log.read_text().strip().splitlines() if log.exists() else []
    decisions = []
    for f in sorted(obs.glob("actions_rank*.jsonl")) \
            if obs.exists() else []:
        decisions += [json.loads(l)
                      for l in f.read_text().splitlines()]
    return rc, lines, decisions, metrics_out, flights, driver


@pytest.mark.slow
def test_quarantine_divergent_rank_act(tmp_path, monkeypatch):
    """The ISSUE 13 acceptance, act half: a chaos-divergent replica is
    canary-convicted and autonomously QUARANTINED — drained through the
    planned re-mesh path, its host blocklisted with the digest
    evidence, the world healed back to full size — with zero human
    input under HVD_TPU_AUTOPILOT=act."""
    from horovod_tpu.core import core_available
    if not core_available():
        pytest.skip("libhvdcore.so not built")
    # exactly ONE re-mesh heals the world (generation 0 -> 1): unlike a
    # preemption drain there is no later re-admission growth publish —
    # the quarantined host stays blocklisted
    rc, lines, decisions, metrics_out, flights, driver = \
        _run_quarantine_scenario(tmp_path, monkeypatch, "act", "act",
                                 min_generation=1)
    assert rc == 0, lines
    boots = [l for l in lines if l.startswith("BOOT")]
    dones = [l for l in lines if l.startswith("DONE")]
    assert len(boots) == 4, lines   # 3 originals + 1 replacement
    assert len(dones) == 3, lines
    for d in dones:
        assert "size=3" in d, lines  # healed back to full size
    # the divergent rank's host is BLOCKLISTED (unlike a drain), the
    # innocent shared host is not
    assert driver._hosts.is_blacklisted("127.0.0.1")
    assert not driver._hosts.is_blacklisted("localhost")
    # driver-side evidence: handled as a quarantine, with the digests
    from horovod_tpu.diagnostics.flight_recorder import recorder
    events = recorder().events()
    handled = [e for e in events
               if e["kind"] == "autopilot_action_handled"]
    assert any(e.get("drained_ranks") == [2]
               and e.get("notices", [{}])[0].get("action") == "quarantine"
               for e in handled), handled
    blocked = [e for e in events
               if e["kind"] == "quarantine_blocklisted"]
    assert blocked and blocked[0]["host"] == "127.0.0.1", blocked
    assert blocked[0]["policy"] == "replica-quarantine"
    assert "digest" in (blocked[0].get("evidence") or {}), blocked
    # the decision audit trail: fired quarantine naming rank 2
    fired = [d for d in decisions
             if d["policy"] == "replica-quarantine"
             and d["outcome"] == "fired"]
    assert fired, decisions
    assert fired[0]["action"] == "quarantine_rank"
    assert fired[0]["target_rank"] == 2
    # /metrics: canary conviction + the act-mode decision counters
    prom = metrics_out.read_text()
    assert "hvd_canary_divergence_total" in prom, prom
    assert 'hvd_autopilot_actions_total{action="quarantine_rank"}' \
        in prom, prom
    assert "hvd_autopilot_mode 2" in prom


@pytest.mark.slow
def test_quarantine_observe_records_without_acting(tmp_path,
                                                   monkeypatch):
    """The observe half: the IDENTICAL fault plan records the same
    quarantine decision (same policy, action, target) as a dry run and
    acts on nothing — no re-mesh, no blocklist, the original three
    processes finish."""
    from horovod_tpu.core import core_available
    if not core_available():
        pytest.skip("libhvdcore.so not built")
    rc, lines, decisions, metrics_out, flights, driver = \
        _run_quarantine_scenario(tmp_path, monkeypatch, "observe",
                                 "observe", min_generation=0)
    assert rc == 0, lines
    boots = [l for l in lines if l.startswith("BOOT")]
    dones = [l for l in lines if l.startswith("DONE")]
    assert len(boots) == 3, lines   # nobody was replaced
    assert len(dones) == 3, lines
    assert not driver._hosts.is_blacklisted("127.0.0.1")
    dry = [d for d in decisions
           if d["policy"] == "replica-quarantine"]
    assert dry and dry[0]["outcome"] == "dry_run", decisions
    assert dry[0]["action"] == "quarantine_rank"
    assert dry[0]["target_rank"] == 2
    # nothing re-meshed anywhere
    for f in flights.glob("*.json"):
        events = json.load(open(f)).get("events", [])
        assert not [e for e in events
                    if e["kind"] == "remesh_complete"], f
    prom = metrics_out.read_text()
    assert "hvd_autopilot_mode 1" in prom
