"""Pallas flash-attention kernel vs the XLA oracle (interpret mode on the
CPU mesh; the real-TPU path is exercised by bench/examples)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from horovod_tpu.ops.pallas_attention import attend, flash_attention_tpu
from horovod_tpu.parallel.ring_attention import _plain_attention


def _qkv(B=2, S=256, H=2, D=128, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, S, H, D), jnp.float32) * 0.3
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
def test_flash_kernel_matches_oracle(causal):
    q, k, v = _qkv()
    out = flash_attention_tpu(q, k, v, causal=causal, interpret=True)
    ref = _plain_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_attend_fallback_on_cpu():
    # CPU backend → must take the XLA fallback (no pallas compile) and agree
    q, k, v = _qkv(S=16, D=8)
    out = attend(q, k, v, causal=True)
    ref = _plain_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


def test_flash_kernel_rect(causal=True):
    # Sq != Sk (cross-block boundary conditions)
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 128, 2, 128), jnp.float32) * 0.3
    k = jnp.asarray(rng.randn(1, 256, 2, 128), jnp.float32) * 0.3
    v = jnp.asarray(rng.randn(1, 256, 2, 128), jnp.float32) * 0.3
    out = flash_attention_tpu(q, k, v, causal=False, interpret=True)
    ref = _plain_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
