"""Pallas flash-attention kernel vs the XLA oracle (interpret mode on the
CPU mesh; the real-TPU path is exercised by bench/examples)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from horovod_tpu.ops.pallas_attention import attend, flash_attention_tpu
from horovod_tpu.parallel.ring_attention import _plain_attention


def _qkv(B=2, S=256, H=2, D=128, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, S, H, D), jnp.float32) * 0.3
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
def test_flash_kernel_matches_oracle(causal):
    q, k, v = _qkv()
    out = flash_attention_tpu(q, k, v, causal=causal, interpret=True)
    ref = _plain_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_attend_fallback_on_cpu():
    # CPU backend → must take the XLA fallback (no pallas compile) and agree
    q, k, v = _qkv(S=16, D=8)
    out = attend(q, k, v, causal=True)
    ref = _plain_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_kernel_grads_match_oracle(causal):
    """The custom-VJP backward (blockwise recompute from lse) must agree
    with autodiff through the XLA oracle — the kernel is used in training
    forwards, so its gradient is load-bearing."""
    q, k, v = _qkv(B=1, S=256, H=2, D=128)

    def loss_flash(q, k, v):
        o = flash_attention_tpu(q, k, v, causal=causal, interpret=True)
        return jnp.sum(o * jnp.cos(o))   # non-trivial cotangent

    def loss_ref(q, k, v):
        o = _plain_attention(q, k, v, causal=causal)
        return jnp.sum(o * jnp.cos(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=2e-4, atol=2e-4, err_msg=name)


def test_flash_grads_rect():
    """Sq != Sk backward (cross-attention shape)."""
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(1, 128, 2, 128), jnp.float32) * 0.3
    k = jnp.asarray(rng.randn(1, 256, 2, 128), jnp.float32) * 0.3
    v = jnp.asarray(rng.randn(1, 256, 2, 128), jnp.float32) * 0.3

    f = lambda q, k, v: jnp.sum(flash_attention_tpu(
        q, k, v, causal=False, interpret=True) ** 2)
    r = lambda q, k, v: jnp.sum(_plain_attention(q, k, v, causal=False) ** 2)
    gf = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(r, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4, err_msg=name)


def test_flash_kernel_rect(causal=True):
    # Sq != Sk (cross-block boundary conditions)
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 128, 2, 128), jnp.float32) * 0.3
    k = jnp.asarray(rng.randn(1, 256, 2, 128), jnp.float32) * 0.3
    v = jnp.asarray(rng.randn(1, 256, 2, 128), jnp.float32) * 0.3
    out = flash_attention_tpu(q, k, v, causal=False, interpret=True)
    ref = _plain_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
