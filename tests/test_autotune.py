"""Mesh-path communication autotuner battery (ISSUE 8): plan space,
successive-halving controller, fingerprinting, persistent plan cache
hygiene (corrupt/stale entries retune, never crash), the
DistributedOptimizer warm-start seam, and the acceptance gates — the
online search converges within its step budget to a plan no worse than
the best hand-set config in benchmarks/overlap_bench.py's sweep
(tolerance band), and a second run with a warm plan cache performs ZERO
search trials.

CPU note: these trials run under tests/conftest.py, which keeps the
persistent XLA compile cache DISABLED by default — required on the
8-device CPU mesh (known warm-cache heap-corruption signature)."""

import json
import os
import sys

import numpy as np
import pytest

from horovod_tpu.train.autotune import (AutotuneController,
                                        AutotuneOptions, Plan, PlanCache,
                                        candidate_plans,
                                        plan_fingerprint)
from horovod_tpu.common.topology import MeshTopology, flat_topology

BENCH_DIR = os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "benchmarks")


# -- Plan -------------------------------------------------------------------

def test_plan_roundtrip_and_key():
    p = Plan(1 << 20, "hier", "int8", 4096)
    assert Plan.from_dict(p.to_dict()) == p
    assert "hier/int8" in p.key


@pytest.mark.parametrize("kw", [
    dict(bucket_bytes=0),
    dict(bucket_bytes=1, algorithm="tree"),
    dict(bucket_bytes=1, codec="int4"),
    dict(bucket_bytes=1, algorithm="ring", codec="int8"),
    dict(bucket_bytes=1, small_floor=-1),
])
def test_plan_validation_rejects(kw):
    with pytest.raises(ValueError):
        Plan(**kw)


def test_candidate_plans_shape():
    flat = candidate_plans(flat_topology(8))
    assert all(p.algorithm != "hier" for p in flat)
    hier = candidate_plans(MeshTopology(2, 4))
    assert any(p.algorithm == "hier" for p in hier)
    assert len(set(hier)) == len(hier)  # deduplicated
    # floor variants never duplicate the dense flat path
    assert not any(p.algorithm == "psum" and p.codec == "none"
                   and p.small_floor > 0 for p in hier)
    base = Plan(123456, "ring", "none")
    assert candidate_plans(flat_topology(8), baseline=base)[0] == base


# -- controller -------------------------------------------------------------

def _drive(ctl, times):
    """Run the controller to lock against a fixed per-plan step time."""
    guard = 0
    while not ctl.done and guard < 10_000:
        plan = ctl.begin_step()
        ctl.end_step(times[plan])
        guard += 1
    assert ctl.done, "controller never locked"


def test_controller_picks_fastest_plan():
    a, b, c = (Plan(1, "psum", "none"), Plan(2, "psum", "none"),
               Plan(3, "psum", "none"))
    ctl = AutotuneController([a, b, c], budget_steps=100,
                             steps_per_trial=2)
    _drive(ctl, {a: 0.010, b: 0.004, c: 0.020})
    assert ctl.locked_plan == b
    assert ctl.best_seconds == pytest.approx(0.004)
    assert ctl.trials > 0 and ctl.steps_used <= 100
    assert not ctl.from_cache


def test_controller_warmup_steps_not_scored():
    a = Plan(1, "psum", "none")
    ctl = AutotuneController([a], budget_steps=10, steps_per_trial=2)
    ctl.begin_step()
    ctl.end_step(99.0)  # warmup (compile) — must not poison the score
    while not ctl.done:
        ctl.begin_step()
        ctl.end_step(0.005)
    assert ctl.best_seconds == pytest.approx(0.005)


def test_controller_budget_exhaustion_locks_best_scored():
    plans = [Plan(i + 1, "psum", "none") for i in range(10)]
    times = {p: 0.010 - 0.0005 * i for i, p in enumerate(plans)}
    # budget fits only 2 plans at 3 steps each (1 warmup + 2 scored)
    ctl = AutotuneController(plans, budget_steps=6, steps_per_trial=2)
    _drive(ctl, times)
    assert ctl.locked_plan in plans[:2]  # trimmed tail never ran
    assert ctl.steps_used <= 6


def test_controller_trims_to_budget_with_warning(caplog):
    plans = [Plan(i + 1, "psum", "none") for i in range(8)]
    import logging
    with caplog.at_level(logging.WARNING):
        ctl = AutotuneController(plans, budget_steps=9,
                                 steps_per_trial=2)
    assert len(ctl._survivors) == 3
    assert any("dropping" in r.message for r in caplog.records)


def test_controller_csv_trace(tmp_path):
    a, b = Plan(1, "psum", "none"), Plan(2, "ring", "none")
    log_path = str(tmp_path / "trace.csv")
    ctl = AutotuneController([a, b], budget_steps=50,
                             steps_per_trial=2, log_path=log_path)
    _drive(ctl, {a: 0.002, b: 0.009})
    lines = open(log_path).read().strip().splitlines()
    assert lines[0].startswith("round,bucket_bytes,algorithm")
    assert lines[-1].endswith(",1")  # final-choice row
    assert any(",ring," in ln for ln in lines)


# -- fingerprint ------------------------------------------------------------

def test_fingerprint_sensitivity():
    import jax.numpy as jnp
    tree = {"w": jnp.zeros((4, 4)), "b": jnp.zeros((4,))}
    fp = plan_fingerprint(tree, {"dp": 8}, 8)
    assert fp == plan_fingerprint(tree, {"dp": 8}, 8)  # stable
    assert fp != plan_fingerprint(tree, {"dp": 4}, 4)  # world
    assert fp != plan_fingerprint(tree, {"dp": 4, "tp": 2}, 4)  # mesh
    other = {"w": jnp.zeros((4, 5)), "b": jnp.zeros((4,))}
    assert fp != plan_fingerprint(other, {"dp": 8}, 8)  # structure
    cast = {"w": jnp.zeros((4, 4), jnp.bfloat16), "b": jnp.zeros((4,))}
    assert fp != plan_fingerprint(cast, {"dp": 8}, 8)  # dtype


# -- plan cache hygiene (satellite: never crash init) -----------------------

def test_cache_store_load_roundtrip(tmp_path):
    cache = PlanCache(str(tmp_path))
    plan = Plan(1 << 20, "hier", "int8", 4096)
    path = cache.store("f" * 64, plan, meta={"trials": 7})
    assert path and os.path.exists(path)
    assert cache.load("f" * 64) == plan
    assert cache.load("0" * 64) is None  # unknown fingerprint


def test_cache_truncated_json_retunes(tmp_path, caplog):
    import logging
    cache = PlanCache(str(tmp_path))
    cache.store("a" * 64, Plan(1, "psum", "none"))
    with open(cache.path("a" * 64), "w") as f:
        f.write('{"version": 1, "plan": {"bucket')  # torn mid-write
    with caplog.at_level(logging.WARNING):
        assert cache.load("a" * 64) is None
    assert any("retuning" in r.message for r in caplog.records)


def test_cache_fingerprint_mismatch_retunes(tmp_path, caplog):
    import logging
    cache = PlanCache(str(tmp_path))
    cache.store("b" * 64, Plan(1, "psum", "none"))
    # a stale rename: file for one fingerprint served under another
    os.replace(cache.path("b" * 64), cache.path("c" * 64))
    with caplog.at_level(logging.WARNING):
        assert cache.load("c" * 64) is None
    assert any("mismatch" in r.message for r in caplog.records)


def test_cache_wrong_version_retunes(tmp_path):
    cache = PlanCache(str(tmp_path))
    with open(cache.path("d" * 64), "w") as f:
        json.dump({"version": 999, "fingerprint": "d" * 64,
                   "plan": {"bucket_bytes": 1}}, f)
    assert cache.load("d" * 64) is None


def test_cache_invalid_plan_retunes(tmp_path):
    cache = PlanCache(str(tmp_path))
    with open(cache.path("e" * 64), "w") as f:
        json.dump({"version": 1, "fingerprint": "e" * 64,
                   "plan": {"bucket_bytes": 1, "algorithm": "warp"}}, f)
    assert cache.load("e" * 64) is None


def test_cache_unwritable_dir_degrades(tmp_path):
    target = tmp_path / "blocked"
    target.write_text("a file where the cache dir should be")
    cache = PlanCache(str(target))  # makedirs will fail
    assert cache.store("f" * 64, Plan(1, "psum", "none")) is None


def test_controller_try_cache_locks_with_zero_trials(tmp_path):
    cache = PlanCache(str(tmp_path))
    plan = Plan(7, "ring", "none")
    cache.store("9" * 64, plan)
    ctl = AutotuneController([Plan(1, "psum", "none")], budget_steps=10,
                             cache=cache, fingerprint="9" * 64)
    assert ctl.try_cache()
    assert ctl.locked_plan == plan
    assert ctl.from_cache and ctl.trials == 0
    # begin/end are no-ops once locked
    assert ctl.begin_step() == plan
    ctl.end_step(1.0)
    assert ctl.trials == 0


# -- DistributedOptimizer warm-start seam -----------------------------------

def test_distributed_optimizer_autotune_warm_start(hvd, tmp_path,
                                                   monkeypatch):
    import jax.numpy as jnp
    import optax
    from horovod_tpu.common.config import reset_config

    from horovod_tpu.train.autotune import topology_key

    params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
    # the seam reconstructs the fingerprint from the CANONICAL topology
    # key (axis-name-free), so a plan the mesh search stored for this
    # model at this world size is found regardless of axis naming
    topo = flat_topology(hvd.size())
    fp = plan_fingerprint(params, topology_key(topo), hvd.size())
    PlanCache(str(tmp_path)).store(fp, Plan(4096, "psum", "int8"))
    monkeypatch.setenv("HVD_TPU_AUTOTUNE_CACHE_DIR", str(tmp_path))
    reset_config()
    try:
        from horovod_tpu.metrics.registry import default_registry
        hits = default_registry().counter(
            "hvd_autotune_cache_hits_total",
            help="runs that started from a cached tuned plan with zero "
                 "search trials")
        before = hits.value
        opt = hvd.DistributedOptimizer(optax.sgd(0.1), autotune=True)
        state = opt.init(params)
        assert hits.value == before + 1
        grads = {"w": jnp.full((4, 4), 0.5), "b": jnp.ones((4,))}
        updates, state = opt.update(grads, state, params)
        # the cached int8 codec is applied under error feedback: the
        # update is the (lossily quantized) gradient scaled by -lr
        w = np.asarray(updates["w"])
        assert np.abs(w + 0.05).max() < 0.01
    finally:
        reset_config()


def test_distributed_optimizer_autotune_miss_keeps_settings(
        hvd, tmp_path, monkeypatch):
    import jax.numpy as jnp
    import optax
    from horovod_tpu.common.config import reset_config

    monkeypatch.setenv("HVD_TPU_AUTOTUNE_CACHE_DIR", str(tmp_path))
    reset_config()
    try:
        params = {"w": jnp.ones((3, 3))}
        opt = hvd.DistributedOptimizer(optax.sgd(0.1), autotune=True)
        state = opt.init(params)
        grads = {"w": jnp.full((3, 3), 0.5)}
        updates, state = opt.update(grads, state, params)
        np.testing.assert_allclose(np.asarray(updates["w"]), -0.05,
                                   rtol=1e-6)
    finally:
        reset_config()


def test_distributed_optimizer_autotune_rejects_adasum(hvd):
    import optax
    with pytest.raises(ValueError, match="standard sync path"):
        hvd.DistributedOptimizer(optax.sgd(0.1),
                                 op=hvd.ReduceOp.ADASUM, autotune=True)


def test_autotune_mesh_env_enables_search_by_default(hvd, monkeypatch):
    """HVD_TPU_AUTOTUNE_MESH=1 flips every make_overlap_train_step to
    the searching wrapper without touching call sites; Adasum under the
    fleet-wide env default is skipped, not an init crash."""
    import optax
    from horovod_tpu.common.config import reset_config
    from horovod_tpu.train.autotune import AutotunedStep
    from horovod_tpu.train.overlap import make_overlap_train_step

    import jax.numpy as jnp

    monkeypatch.setenv("HVD_TPU_AUTOTUNE_MESH", "1")
    reset_config()
    try:
        mesh = hvd.build_mesh(dp=-1)

        def loss_fn(p, b):
            return jnp.mean((b @ p["w"]) ** 2)

        step = make_overlap_train_step(loss_fn, optax.sgd(0.1), mesh)
        assert isinstance(step, AutotunedStep)
        # the candidate builder must pin autotune OFF — under the env
        # default it would otherwise recurse into the searcher forever
        params = {"w": jnp.ones((4, 4))}
        tx_state = optax.sgd(0.1).init(params)
        batch = jnp.ones((8, 4))
        step(params, tx_state, batch)  # must not RecursionError
        assert step.autotune is not None
        # explicit opt-out still wins
        plain = make_overlap_train_step(lambda p, b: 0.0, optax.sgd(0.1),
                                        mesh, autotune=False)
        assert not isinstance(plain, AutotunedStep)
        # env-driven default skips incompatible paths instead of raising
        hvd.DistributedOptimizer(optax.sgd(0.1), op=hvd.ReduceOp.ADASUM)
    finally:
        reset_config()


# -- acceptance: convergence vs the hand-set sweep + warm zero-trial --------

def test_autotune_converges_and_warm_cache_skips_search(
        hvd, tmp_path, monkeypatch):
    """ISSUE 8 acceptance. On the 8-device CPU mesh the online search
    must (a) lock, within its step budget, a plan whose step time — as
    measured by benchmarks/overlap_bench.py's hand-set sweep over the
    SAME candidates — is within the tolerance band of the sweep's best
    row, and (b) a second run against the warm plan cache must lock the
    same plan with zero search trials. The band is wide (3x) because
    the shared-CPU box is noisy; the gate catches a search that scored
    garbage (locking a plan several times slower than the best), not
    scheduler jitter."""
    import jax.numpy as jnp
    import optax
    from horovod_tpu.train.overlap import make_overlap_train_step

    monkeypatch.setenv("HVD_TPU_VIRTUAL_HOSTS", "2")  # enable hier
    mesh = hvd.build_mesh(dp=-1)
    from horovod_tpu.common.topology import detect_topology
    topo = detect_topology(mesh, "dp")
    assert topo.is_hierarchical

    plans = [
        Plan(1 << 20, "psum", "none"),
        Plan(4096, "psum", "int8"),
        Plan(1 << 20, "ring", "none"),
        Plan(1 << 20, "hier", "none"),
    ]

    rng = np.random.RandomState(0)
    params = {f"w{i}": jnp.asarray(rng.randn(64, 64).astype(np.float32)
                                   / 8.0) for i in range(4)}

    def loss_fn(p, xy):
        x, y = xy
        h = x
        for i in range(4):
            h = jnp.tanh(h @ p[f"w{i}"])
        return jnp.mean((h - y) ** 2)

    tx = optax.sgd(1e-3)
    x = jnp.asarray(rng.randn(64, 64).astype(np.float32))
    y = jnp.asarray(rng.randn(64, 64).astype(np.float32))
    opts = AutotuneOptions(plans=plans, budget_steps=40,
                           steps_per_trial=3,
                           cache_dir=str(tmp_path))

    step = make_overlap_train_step(loss_fn, tx, mesh, "dp", n_micro=2,
                                   autotune=opts, donate=False)
    p, s = params, tx.init(params)
    for _ in range(60):
        p, s, loss = step(p, s, (x, y))
        if step.autotune is not None and step.autotune.done:
            break
    ctl = step.autotune
    assert ctl.done, "search must converge within its budget"
    assert ctl.steps_used <= opts.budget_steps
    assert ctl.trials > 0 and not ctl.from_cache

    # the hand-set baseline: overlap_bench's sweep over the SAME
    # candidates, measured AFTER the search in the same (now warm)
    # process with interleaved repeats, so box-load drift hits every
    # plan equally rather than skewing the comparison
    sys.path.insert(0, BENCH_DIR)
    try:
        from overlap_bench import run_plan_sweep
    finally:
        sys.path.remove(BENCH_DIR)
    sweep = run_plan_sweep(mesh, plans=plans, d_model=64, n_layers=4,
                           n_micro=2, iters=4, repeats=3)
    assert set(sweep["plans"]) == {p.key for p in plans}

    locked_key = ctl.locked_plan.key
    band = 3.0  # tolerance band (CPU noise), see docstring
    assert sweep["plans"][locked_key] <= sweep["best_s"] * band, (
        f"autotune locked {locked_key} "
        f"({sweep['plans'][locked_key]:.6f}s by the sweep) vs best "
        f"hand-set {sweep['best_plan']} ({sweep['best_s']:.6f}s)")

    # the winner is in the persistent cache; a fresh step warm-starts
    # with ZERO trials and the same plan
    warm = make_overlap_train_step(loss_fn, tx, mesh, "dp", n_micro=2,
                                   autotune=opts, donate=False)
    p2, s2 = params, tx.init(params)
    for _ in range(2):
        p2, s2, _ = warm(p2, s2, (x, y))
    ctl2 = warm.autotune
    assert ctl2.from_cache and ctl2.trials == 0
    assert ctl2.locked_plan == ctl.locked_plan
