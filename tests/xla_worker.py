"""Worker for the XLA eager backend (HVD_TPU_OPERATIONS=XLA_EAGER):
collectives ride jitted XLA programs over the jax.distributed global mesh."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["HOROVOD_TPU_OPERATIONS"] = "XLA_EAGER"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import horovod_tpu as hvd  # noqa: E402


def main():
    rank = int(os.environ["HOROVOD_RANK"])
    size = int(os.environ["HOROVOD_SIZE"])
    hvd.init()
    assert hvd.rank() == rank and hvd.size() == size
    from horovod_tpu.ops.xla_backend import XlaBackend
    from horovod_tpu.common.basics import _require_init
    assert isinstance(_require_init().backend, XlaBackend)

    # allreduce sum / average
    out = hvd.allreduce(jnp.arange(8.0) + rank, op=hvd.Sum, name="s")
    np.testing.assert_allclose(
        np.asarray(out), sum(np.arange(8.0) + r for r in range(size)))
    out = hvd.allreduce(jnp.ones(4) * (rank + 1), name="a")
    np.testing.assert_allclose(np.asarray(out),
                               np.mean([r + 1 for r in range(size)]))
    # min/max
    mn = hvd.allreduce(jnp.asarray([float(rank)]), op=hvd.Min, name="mn")
    mx = hvd.allreduce(jnp.asarray([float(rank)]), op=hvd.Max, name="mx")
    assert float(np.asarray(mn)[0]) == 0 and \
        float(np.asarray(mx)[0]) == size - 1

    # broadcast from nonzero root
    b = hvd.broadcast(jnp.full(3, float(rank)), root_rank=size - 1, name="b")
    np.testing.assert_allclose(np.asarray(b), float(size - 1))

    # ragged allgather
    g = hvd.allgather(jnp.ones((rank + 1, 2)) * rank, name="g")
    assert np.asarray(g).shape == (sum(r + 1 for r in range(size)), 2)

    # uniform alltoall
    t, rs = hvd.alltoall(jnp.arange(float(size * 2)).reshape(size * 2, 1),
                         name="t")
    assert list(np.asarray(rs)) == [2] * size

    # uneven alltoall: rank r sends (i+1) rows of value r*10+i to rank i
    splits = [i + 1 for i in range(size)]
    sendbuf = np.concatenate([
        np.full((i + 1, 2), rank * 10 + i, np.float32)
        for i in range(size)])
    out, recv = hvd.alltoall(jnp.asarray(sendbuf), splits=splits, name="u")
    expect = np.concatenate([
        np.full((rank + 1, 2), r * 10 + rank, np.float32)
        for r in range(size)])
    np.testing.assert_allclose(np.asarray(out), expect)

    hvd.barrier()
    hvd.shutdown()
    print(f"xla worker {rank}: OK", flush=True)


if __name__ == "__main__":
    main()
