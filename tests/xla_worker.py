"""Worker for the XLA eager backend (HVD_TPU_OPERATIONS=XLA_EAGER):
collectives ride jitted XLA programs over the jax.distributed global mesh."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["HOROVOD_TPU_OPERATIONS"] = "XLA_EAGER"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import horovod_tpu as hvd  # noqa: E402


def main():
    rank = int(os.environ["HOROVOD_RANK"])
    size = int(os.environ["HOROVOD_SIZE"])
    hvd.init()
    assert hvd.rank() == rank and hvd.size() == size
    from horovod_tpu.ops.xla_backend import XlaBackend
    from horovod_tpu.common.basics import _require_init
    assert isinstance(_require_init().backend, XlaBackend)

    # allreduce sum / average
    out = hvd.allreduce(jnp.arange(8.0) + rank, op=hvd.Sum, name="s")
    np.testing.assert_allclose(
        np.asarray(out), sum(np.arange(8.0) + r for r in range(size)))
    out = hvd.allreduce(jnp.ones(4) * (rank + 1), name="a")
    np.testing.assert_allclose(np.asarray(out),
                               np.mean([r + 1 for r in range(size)]))
    # min/max
    mn = hvd.allreduce(jnp.asarray([float(rank)]), op=hvd.Min, name="mn")
    mx = hvd.allreduce(jnp.asarray([float(rank)]), op=hvd.Max, name="mx")
    assert float(np.asarray(mn)[0]) == 0 and \
        float(np.asarray(mx)[0]) == size - 1

    # broadcast from nonzero root
    b = hvd.broadcast(jnp.full(3, float(rank)), root_rank=size - 1, name="b")
    np.testing.assert_allclose(np.asarray(b), float(size - 1))

    # ragged allgather
    g = hvd.allgather(jnp.ones((rank + 1, 2)) * rank, name="g")
    assert np.asarray(g).shape == (sum(r + 1 for r in range(size)), 2)

    # uniform alltoall
    t, rs = hvd.alltoall(jnp.arange(float(size * 2)).reshape(size * 2, 1),
                         name="t")
    assert list(np.asarray(rs)) == [2] * size

    # uneven alltoall: rank r sends (i+1) rows of value r*10+i to rank i
    splits = [i + 1 for i in range(size)]
    sendbuf = np.concatenate([
        np.full((i + 1, 2), rank * 10 + i, np.float32)
        for i in range(size)])
    out, recv = hvd.alltoall(jnp.asarray(sendbuf), splits=splits, name="u")
    expect = np.concatenate([
        np.full((rank + 1, 2), r * 10 + rank, np.float32)
        for r in range(size)])
    np.testing.assert_allclose(np.asarray(out), expect)

    # grouped allreduce: ONE fused program — check numerics here and that
    # the compiled program has a single all-reduce per dtype group
    vals = [jnp.full((16,), float(rank + 1)),
            jnp.ones((4, 4)) * rank,
            jnp.asarray(np.arange(6, dtype=np.int32))]
    outs = hvd.grouped_allreduce(vals, op=hvd.Sum, name="grp")
    np.testing.assert_allclose(
        np.asarray(outs[0]), sum(r + 1.0 for r in range(size)))
    np.testing.assert_allclose(
        np.asarray(outs[1]), np.ones((4, 4)) * sum(range(size)))
    np.testing.assert_allclose(np.asarray(outs[2]),
                               np.arange(6) * size)
    be = _require_init().backend
    grouped_keys = [k for k in be._group._fn_cache if k[0] == "grouped"]
    assert len(grouped_keys) == 1, grouped_keys
    fused = be._group._fn_cache[grouped_keys[0]]
    arrs = [np.asarray(v) for v in vals]
    garrs = [be._group.to_global(a) for a in arrs]
    hlo = fused.lower(*garrs).compile().as_text()
    n_ar = hlo.count("all-reduce(") + hlo.count("all-reduce-start(")
    # one per dtype group (f32, i32); XLA's combiner may merge further —
    # the claim is it is NOT one collective per tensor (= 3)
    assert 1 <= n_ar <= 2, \
        f"expected <=2 fused all-reduces for 3 tensors, got {n_ar}"

    # async overlap: enqueue returns before completion (a fresh-shape
    # collective must still be compiling when the handle comes back)
    h = hvd.allreduce_async(jnp.ones((257, 129)), op=hvd.Sum, name="ov")
    assert not h.poll(), "handle completed synchronously - no overlap"
    np.testing.assert_allclose(np.asarray(h.wait(120)),
                               np.ones((257, 129)) * size)

    # Adasum must apply the VHDD combine, not a plain sum (ADVICE r1)
    from horovod_tpu.ops.adasum import adasum_tree_reduce
    xs = [np.full((8,), float(r + 1), np.float32) for r in range(size)]
    ad = hvd.allreduce(jnp.asarray(xs[rank]), op=hvd.Adasum, name="ad")
    expect = np.asarray(adasum_tree_reduce(jnp.asarray(np.stack(xs))))
    np.testing.assert_allclose(np.asarray(ad), expect, rtol=1e-5)

    # grouped Adasum: fused transfer but PER-TENSOR combine coefficients
    # (one big + one small tensor would pollute each other if the combine
    # ran over the concatenated buffer)
    a_r = np.full((6,), float(rank + 1), np.float32)
    b_r = np.full((3,), float(10 * (rank + 1)), np.float32)
    ga, gb = hvd.grouped_allreduce(
        [jnp.asarray(a_r), jnp.asarray(b_r)], op=hvd.Adasum, name="gad")
    ea = np.asarray(adasum_tree_reduce(jnp.asarray(np.stack(
        [np.full((6,), float(r + 1), np.float32) for r in range(size)]))))
    eb = np.asarray(adasum_tree_reduce(jnp.asarray(np.stack(
        [np.full((3,), float(10 * (r + 1)), np.float32)
         for r in range(size)]))))
    np.testing.assert_allclose(np.asarray(ga), ea, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gb), eb, rtol=1e-5)

    # reducescatter over dim 0
    rs = hvd.reducescatter(jnp.ones((size * 2, 3)) * (rank + 1),
                           op=hvd.Sum, name="rs")
    np.testing.assert_allclose(np.asarray(rs),
                               np.ones((2, 3)) * sum(r + 1 for r in range(size)))

    # join needs negotiation: must raise with a pointer to the core, not
    # silently pretend to work
    try:
        hvd.join()
        raise AssertionError("join must raise on the XLA eager backend")
    except NotImplementedError:
        pass

    hvd.barrier()
    hvd.shutdown()
    print(f"xla worker {rank}: OK", flush=True)


if __name__ == "__main__":
    main()
