"""Bucketed backprop/collective overlap (ISSUE 6 tentpole): numerics
parity of the software-pipelined accumulation against the unbucketed
reduce-after-backward path on the traced mesh regime, the chunked ring
collective, loss-trajectory parity under int8 compression, and the
exposed-communication acceptance gate (overlap strictly below the
serialized schedule on the 8-device CPU mesh)."""

import functools
import os
import sys

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd_mod
from horovod_tpu._compat import shard_map
from horovod_tpu.ops.mesh_collectives import pring_allreduce
from horovod_tpu.ops.reduce_op import ReduceOp
from horovod_tpu.train.overlap import (bucketed_grad_sync,
                                       make_overlap_train_step,
                                       pipelined_accumulate)

BENCH_DIR = os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "benchmarks")


@pytest.fixture
def dp_mesh(hvd):
    return hvd.build_mesh(dp=-1)  # all 8 virtual devices on one axis


def _grad_tree(rng):
    return {"w": jnp.asarray(rng.randn(8, 16, 3).astype(np.float32)),
            "b": jnp.asarray(rng.randn(8, 5).astype(np.float32))}


def _run_sync(mesh, g, **kw):
    @functools.partial(shard_map, mesh=mesh, in_specs=(P("dp"),),
                       out_specs=P("dp"), check_vma=False)
    def body(gs):
        loc = jax.tree_util.tree_map(lambda x: x[0], gs)
        out = bucketed_grad_sync(loc, "dp", **kw)
        return jax.tree_util.tree_map(lambda x: x[None], out)

    return jax.jit(body)(g)


@pytest.mark.parametrize("kw", [
    {},                                  # single psum bucket
    {"bucket_bytes": 64},                # many buckets
    {"ring": True},                      # chunked ppermute ring
    {"op": ReduceOp.SUM, "bucket_bytes": 128},
], ids=["one-bucket", "many-buckets", "ring", "sum"])
def test_bucketed_sync_matches_dense_reduction(hvd, dp_mesh, kw):
    rng = np.random.RandomState(0)
    g = _grad_tree(rng)
    out = _run_sync(dp_mesh, g, **kw)
    red = np.sum if kw.get("op") == ReduceOp.SUM else np.mean
    for got, want in zip(jax.tree_util.tree_leaves(out),
                         jax.tree_util.tree_leaves(g)):
        ref = red(np.asarray(want), axis=0, keepdims=True).repeat(8, 0)
        np.testing.assert_allclose(np.asarray(got), ref, atol=1e-5)


def test_bucketed_sync_quantized_within_codec_bound(hvd, dp_mesh):
    rng = np.random.RandomState(1)
    g = _grad_tree(rng)
    out = _run_sync(dp_mesh, g, compression=hvd.Compression.int8,
                    bucket_bytes=256)
    for got, want in zip(jax.tree_util.tree_leaves(out),
                         jax.tree_util.tree_leaves(g)):
        ref = np.mean(np.asarray(want), axis=0, keepdims=True).repeat(8, 0)
        # one quantization step of error on the gathered phase
        bound = np.abs(ref).max() / 254 + 1e-6
        assert np.abs(np.asarray(got) - ref).max() <= bound


def test_ring_allreduce_matches_psum_any_shape(hvd, dp_mesh):
    rng = np.random.RandomState(2)
    for shape in [(8, 13), (8, 4, 5), (8, 1)]:
        x = jnp.asarray(rng.randn(*shape).astype(np.float32))

        @functools.partial(shard_map, mesh=dp_mesh, in_specs=(P("dp"),),
                           out_specs=P("dp"), check_vma=False)
        def body(xs):
            return pring_allreduce(xs[0], "dp")[None]

        out = jax.jit(body)(x)
        ref = np.sum(np.asarray(x), axis=0, keepdims=True).repeat(8, 0)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5,
                                   atol=1e-5)


# -- pipelined accumulation parity -----------------------------------------

def _linear_problem(rng, n=64, din=6, dout=4):
    params = {"w": jnp.asarray(rng.randn(din, dout).astype(np.float32)),
              "b": jnp.zeros((dout,), jnp.float32)}
    X = jnp.asarray(rng.randn(n, din).astype(np.float32))
    Y = jnp.asarray(rng.randn(n, dout).astype(np.float32))

    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((x @ p["w"] + p["b"] - y) ** 2)

    return params, (X, Y), loss_fn


def _accumulate(mesh, params, batch, loss_fn, n_micro, **kw):
    gf = jax.value_and_grad(loss_fn)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(), P("dp"), P("dp")),
                       out_specs=(P(), P()), check_vma=False)
    def body(p, x, y):
        mb = jax.tree_util.tree_map(
            lambda a: a.reshape((n_micro, a.shape[0] // n_micro)
                                + a.shape[1:]), (x, y))
        loss, g = pipelined_accumulate(gf, p, mb, axis_name="dp", **kw)
        return jax.lax.pmean(loss, "dp"), g

    return jax.jit(body)(params, *batch)


@pytest.mark.parametrize("kw", [
    {"n_micro": 1},                          # exact fallback, no pipeline
    {"n_micro": 4},                          # pipelined
    {"n_micro": 4, "overlap": False},        # serialized comparator
    {"n_micro": 4, "bucket_bytes": 32},      # many buckets
    {"n_micro": 2, "ring": True},            # ring collective
], ids=["fallback", "pipelined", "serialized", "buckets", "ring"])
def test_pipelined_accumulate_matches_full_batch(hvd, dp_mesh, kw):
    """Bucketed/pipelined == unbucketed single-shot to fp32 tolerance:
    reduction is linear, so reducing each microbatch one iteration late
    and summing must equal reducing the full-batch gradient."""
    kw = dict(kw)
    n_micro = kw.pop("n_micro")
    rng = np.random.RandomState(0)
    params, batch, loss_fn = _linear_problem(rng)
    ref_loss, ref_g = jax.value_and_grad(loss_fn)(params, batch)
    loss, g = _accumulate(dp_mesh, params, batch, loss_fn, n_micro, **kw)
    assert abs(float(loss) - float(ref_loss)) < 1e-5
    for got, want in zip(jax.tree_util.tree_leaves(g),
                         jax.tree_util.tree_leaves(ref_g)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)


def test_pipelined_rejects_mismatched_microbatch_axes(hvd, dp_mesh):
    rng = np.random.RandomState(0)
    params, (X, Y), loss_fn = _linear_problem(rng)
    gf = jax.value_and_grad(loss_fn)
    with pytest.raises(ValueError, match="leading axis"):
        pipelined_accumulate(
            gf, params, (X.reshape(4, 16, 6), Y.reshape(2, 32, 4)),
            axis_name="dp")


def test_loss_trajectory_parity_bucketed_vs_unbucketed(hvd, dp_mesh):
    """Acceptance: bucketed (pipelined, quantized) training matches
    unbucketed loss trajectories within tolerance — exact under plain
    psum, codec-bounded under int8."""
    rng = np.random.RandomState(3)
    params, batch, loss_fn = _linear_problem(rng, n=64)
    tx = optax.sgd(0.05)

    def train(**kw):
        step = make_overlap_train_step(loss_fn, tx, dp_mesh, "dp",
                                       donate=False, **kw)
        p, o = dict(params), tx.init(params)
        losses = []
        for _ in range(6):
            p, o, loss = step(p, o, batch)
            losses.append(float(loss))
        return np.asarray(losses)

    base = train(n_micro=1)                       # unbucketed, serialized
    pipelined = train(n_micro=4, bucket_bytes=64)  # bucketed + pipelined
    quantized = train(n_micro=4, bucket_bytes=64,
                      compression=hvd_mod.Compression.int8)
    np.testing.assert_allclose(pipelined, base, rtol=2e-2)
    np.testing.assert_allclose(quantized, base, rtol=5e-2)
    assert quantized[-1] < quantized[0]  # it actually trains


def test_exposed_comm_overlap_beats_serialized(hvd):
    """ISSUE 6 acceptance: on the 8-device CPU mesh the pipelined
    schedule's exposed-communication seconds per step are strictly
    below the serialized (bucket-count-1) configuration, and the result
    lands on the metrics registry.

    The schedules differ by tens of milliseconds per step, so an
    external process saturating this 1-core box can invert a single
    measurement — the claim under test is the schedule's capability,
    not one sample: up to 3 measurement rounds, pass on the first win
    (healthy margins observed are 25-55%)."""
    sys.path.insert(0, BENCH_DIR)
    try:
        from overlap_bench import run_overlap_bench
    finally:
        sys.path.remove(BENCH_DIR)

    doc = None
    for _ in range(3):
        doc = run_overlap_bench(d_model=192, n_layers=8, n_micro=4,
                                batch_per_device=4,
                                bucket_bytes=64 * 1024,
                                iters=6, repeats=3)
        if doc["overlap_beats_serialized"]:
            break
    assert doc["overlap_beats_serialized"], doc
    assert doc["exposed_comm_s"]["overlap"] < \
        doc["exposed_comm_s"]["serialized"], doc
    snap = hvd_mod.metrics_snapshot()["registry"]
    for config in ("overlap", "serialized"):
        key = f'hvd_overlap_exposed_comm_seconds{{config="{config}"}}'
        assert key in snap, sorted(k for k in snap if "overlap" in k)
