"""End-to-end test of ``horovod_tpu.ray.RayExecutor`` over a fake actor
runtime (reference analog: ``test/integration/test_ray.py``
``test_horovod_train`` against a local Ray cluster).

ray is not in this image, so ``tests/fake_ray`` provides the exact actor
surface the executor touches, with every actor a REAL subprocess and all
calls shipped via cloudpickle. The distributed part is genuine: both
actors call ``hvd.init()`` and the collectives run over the native TCP
core between the actor processes.
"""

import os
import sys

import pytest

from horovod_tpu.core import core_available

FAKE_RAY = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fake_ray")

needs_core = pytest.mark.skipif(not core_available(),
                                reason="libhvdcore.so not built")


@pytest.fixture
def fake_ray(monkeypatch):
    monkeypatch.syspath_prepend(FAKE_RAY)
    for mod in [m for m in sys.modules if m.split(".")[0] == "ray"]:
        monkeypatch.delitem(sys.modules, mod, raising=False)
    yield
    for mod in [m for m in sys.modules if m.split(".")[0] == "ray"]:
        sys.modules.pop(mod, None)


@needs_core
def test_ray_executor_end_to_end(fake_ray):
    from horovod_tpu.ray import RayExecutor

    ex = RayExecutor(num_workers=2, env={"HVD_RAY_TEST_KNOB": "7"})
    ex.start()
    try:
        def fn(offset):
            import os
            import jax.numpy as jnp
            import numpy as np
            import horovod_tpu as hvd

            out = hvd.allreduce(jnp.ones(3) * (hvd.rank() + offset),
                                op=hvd.Sum, name="ray_x")
            return {"rank": hvd.rank(), "size": hvd.size(),
                    "sum": np.asarray(out).tolist(),
                    "knob": os.environ.get("HVD_RAY_TEST_KNOB")}

        results = ex.run(fn, args=(1.0,))
        assert len(results) == 2
        for rank, res in enumerate(results):
            assert res["rank"] == rank
            assert res["size"] == 2
            # sum over ranks of (rank+1) = 1 + 2 = 3 per element
            assert res["sum"] == [3.0, 3.0, 3.0]
            assert res["knob"] == "7"

        # a second run on the SAME started executor (actors persist,
        # like the reference's run/execute reuse)
        results = ex.run(lambda: "alive")
        assert results == ["alive", "alive"]
    finally:
        ex.shutdown()


def test_ray_host_discovery(fake_ray):
    import ray as fake_ray_mod
    from horovod_tpu.ray import RayHostDiscovery

    fake_ray_mod._FAKE_NODES[:] = [
        {"Alive": True, "NodeManagerAddress": "10.0.0.1",
         "Resources": {"CPU": 8.0}},
        {"Alive": True, "NodeManagerAddress": "10.0.0.2",
         "Resources": {"CPU": 3.0}},
        {"Alive": False, "NodeManagerAddress": "10.0.0.3",
         "Resources": {"CPU": 8.0}},
        {"Alive": True, "NodeManagerAddress": "10.0.0.4",
         "Resources": {}},
    ]
    try:
        disc = RayHostDiscovery(cpus_per_slot=2)
        assert disc.find_available_hosts_and_slots() == {
            "10.0.0.1": 4, "10.0.0.2": 1}
    finally:
        fake_ray_mod._FAKE_NODES[:] = []


def test_ray_executor_requires_ray():
    for mod in [m for m in sys.modules if m.split(".")[0] == "ray"]:
        sys.modules.pop(mod, None)
    if any(os.path.isdir(os.path.join(p, "ray")) for p in sys.path):
        pytest.skip("real or fake ray importable in this environment")
    from horovod_tpu.ray import RayExecutor
    with pytest.raises(ImportError, match="ray"):
        RayExecutor(num_workers=1)


@needs_core
def test_elastic_ray_executor_fn_recovers_from_crash(fake_ray, tmp_path):
    """ElasticRayExecutor.run(fn): Ray actors host the agent transport,
    a rank-1 crash in generation 0 triggers a generation restart on the
    same actors, and the retry completes (reference:
    ``ElasticRayExecutor``, ``ray/elastic.py:149+``)."""
    from horovod_tpu.ray import ElasticRayExecutor

    marker = str(tmp_path / "crashed_once")

    def train():
        import os
        import numpy as np
        import horovod_tpu as hvd

        hvd.init()
        if hvd.rank() == 1 and not os.path.exists(marker):
            open(marker, "w").close()
            os._exit(17)
        out = hvd.allreduce(np.ones(2) * (hvd.rank() + 1), op=hvd.Sum,
                            name="rayel")
        hvd.shutdown()
        return float(np.asarray(out)[0])

    ex = ElasticRayExecutor(min_np=2, max_np=2)
    results = ex.run(train)
    assert os.path.exists(marker)
    assert results == [3.0, 3.0]


@needs_core
def test_ray_executor_executable_surface(fake_ray):
    """start(executable_cls=...) + execute/execute_single/run_remote
    (reference: ray/runner.py:250-345): the user class instantiates once
    per worker with hvd live, fn(executable) applies to that instance,
    and run_remote returns per-worker futures."""
    import ray
    from horovod_tpu.ray import RayExecutor

    class Trainer:
        def __init__(self, base):
            import horovod_tpu as hvd
            self.base = base
            self.rank = hvd.rank()
            self.steps = 0

        def step(self):
            import numpy as np
            import horovod_tpu as hvd
            self.steps += 1
            out = hvd.allreduce(np.ones(1) * (self.rank + self.base),
                                op=hvd.Sum, name=f"ex.{self.steps}")
            return float(np.asarray(out)[0])

    ex = RayExecutor(num_workers=2)
    ex.start(executable_cls=Trainer, executable_args=(10.0,))
    try:
        # execute: fn(executable) on every worker
        outs = ex.execute(lambda t: t.step())
        assert outs == [21.0, 21.0]  # (10+0) + (10+1)
        # state persists on the workers between execute calls
        outs = ex.execute(lambda t: (t.steps, t.rank))
        assert outs == [(1, 0), (1, 1)]
        # execute_single: rank 0 only (no collectives inside)
        assert ex.execute_single(lambda t: t.base) == 10.0
        # run_remote: futures resolve straight to the return values
        futs = ex.run_remote(lambda: "async")
        assert ray.get(futs) == ["async", "async"]
    finally:
        ex.shutdown()

    # lifecycle guards: clear errors instead of opaque remote failures
    fresh = RayExecutor(num_workers=1)
    with pytest.raises(ValueError, match="start"):
        fresh.run(lambda: 1)
    with pytest.raises(ValueError, match="executable_cls"):
        fresh._workers = ["sentinel"]
        fresh.execute(lambda t: t)
