"""Unit tests for the metrics & telemetry subsystem (docs/OBSERVABILITY.md):
registry semantics (counter/gauge/histogram, snapshot merge), Prometheus
text rendering, the per-worker HTTP exporter round-trip, the engine-counter
derived view, and the train-loop StepTimer. Pure-host — the multi-process
live-scrape and straggler-attribution paths are covered by
test_core_multiprocess.py."""

import json
import math
import threading
import urllib.request

import pytest

from horovod_tpu.metrics.engine import EngineCollector, derived_ratios
from horovod_tpu.metrics.exporter import MetricsExporter
from horovod_tpu.metrics.registry import (DEFAULT_BUCKETS, Registry,
                                          render_prometheus)


# -- registry ---------------------------------------------------------------

def test_counter_semantics():
    reg = Registry()
    c = reg.counter("requests", help="total requests")
    c.inc()
    c.inc(4.5)
    assert c.value == 5.5
    with pytest.raises(ValueError):
        c.inc(-1)
    assert reg.counter("requests") is c  # get-or-create returns same obj


def test_gauge_semantics():
    reg = Registry()
    g = reg.gauge("queue_depth")
    g.set(7)
    g.inc(3)
    assert g.value == 10.0
    with pytest.raises(ValueError):
        reg.gauge("bad", agg="median")


def test_type_conflict_raises():
    reg = Registry()
    reg.counter("x")
    with pytest.raises(ValueError):
        reg.gauge("x")


def test_option_conflict_raises_omitted_matches():
    reg = Registry()
    g = reg.gauge("thr", agg="sum")
    assert reg.gauge("thr") is g  # omitted agg = don't-care re-get
    with pytest.raises(ValueError):
        reg.gauge("thr", agg="last")
    h = reg.histogram("lat")
    assert reg.histogram("lat") is h
    with pytest.raises(ValueError):
        reg.histogram("lat", buckets=[1.0, 2.0])


def test_labels_key_canonical_order():
    reg = Registry()
    a = reg.counter("c", labels={"b": "2", "a": "1"})
    b = reg.counter("c", labels={"a": "1", "b": "2"})
    assert a is b
    assert 'c{a="1",b="2"}' in reg.snapshot()


def test_histogram_buckets_and_moments():
    reg = Registry()
    h = reg.histogram("lat", buckets=[0.1, 1.0, 10.0])
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    s = h.snapshot()
    assert s["counts"] == [1, 2, 1, 1]  # last slot = +Inf overflow
    assert s["count"] == 5
    assert abs(s["sum"] - 56.05) < 1e-9


def test_histogram_default_buckets_log_scale():
    assert DEFAULT_BUCKETS[0] == pytest.approx(1e-3)
    ratios = {DEFAULT_BUCKETS[i + 1] / DEFAULT_BUCKETS[i]
              for i in range(len(DEFAULT_BUCKETS) - 1)}
    assert ratios == {2.0}


def test_histogram_boundary_value_lands_in_le_bucket():
    """A value exactly on a bound counts toward that bound's bucket
    (Prometheus le = less-or-equal semantics)."""
    reg = Registry()
    h = reg.histogram("b", buckets=[1.0, 2.0])
    h.observe(1.0)
    assert h.snapshot()["counts"] == [1, 0, 0]


def test_histogram_rejects_bad_buckets():
    reg = Registry()
    with pytest.raises(ValueError):
        reg.histogram("h", buckets=[0.0, 1.0])
    with pytest.raises(ValueError):
        reg.histogram("h2", buckets=[1.0, math.inf])


def test_snapshot_merge_counters_histograms_add():
    def snap(n):
        reg = Registry()
        reg.counter("steps").inc(n)
        h = reg.histogram("t", buckets=[1.0, 2.0])
        h.observe(0.5 * n)
        return reg.snapshot()

    merged = Registry.merge([snap(1), snap(2), snap(4)])
    assert merged["steps"]["value"] == 7
    assert merged["t"]["count"] == 3
    assert merged["t"]["counts"] == [2, 1, 0]  # 0.5, 1.0 <= 1.0 < 2.0


def test_snapshot_merge_gauge_aggs():
    def snap(v):
        reg = Registry()
        reg.gauge("thr", agg="sum").set(v)
        reg.gauge("mfu", agg="mean").set(v / 10.0)
        reg.gauge("peak", agg="max").set(v)
        reg.gauge("last").set(v)
        return reg.snapshot()

    merged = Registry.merge([snap(1.0), snap(2.0), snap(3.0)])
    assert merged["thr"]["value"] == 6.0
    assert merged["mfu"]["value"] == pytest.approx(0.2)
    assert merged["peak"]["value"] == 3.0
    assert merged["last"]["value"] == 3.0


def test_snapshot_merge_mismatches_raise():
    ra, rb = Registry(), Registry()
    ra.counter("m")
    rb.gauge("m")
    with pytest.raises(ValueError):
        Registry.merge([ra.snapshot(), rb.snapshot()])
    rc, rd = Registry(), Registry()
    rc.histogram("h", buckets=[1.0])
    rd.histogram("h", buckets=[2.0])
    with pytest.raises(ValueError):
        Registry.merge([rc.snapshot(), rd.snapshot()])


def test_concurrent_increments_are_lossless():
    reg = Registry()
    c = reg.counter("n")
    threads = [threading.Thread(target=lambda: [c.inc() for _ in range(1000)])
               for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000


# -- prometheus rendering ---------------------------------------------------

def _parse_prometheus(text):
    """Minimal text-format v0.0.4 parser: {series_key: value}."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        key, val = line.rsplit(" ", 1)
        out[key] = float(val)
    return out


def test_render_prometheus_counter_gauge():
    reg = Registry()
    reg.counter("hvd_steps_total", help="steps").inc(3)
    reg.gauge("hvd_mfu").set(0.42)
    text = render_prometheus(reg.snapshot())
    assert "# HELP hvd_steps_total steps" in text
    assert "# TYPE hvd_steps_total counter" in text
    assert "# TYPE hvd_mfu gauge" in text
    series = _parse_prometheus(text)
    assert series["hvd_steps_total"] == 3
    assert series["hvd_mfu"] == 0.42


def test_render_prometheus_histogram_cumulative():
    reg = Registry()
    h = reg.histogram("hvd_step_time_seconds", buckets=[0.1, 1.0])
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    series = _parse_prometheus(render_prometheus(reg.snapshot()))
    assert series['hvd_step_time_seconds_bucket{le="0.1"}'] == 1
    assert series['hvd_step_time_seconds_bucket{le="1"}'] == 2
    assert series['hvd_step_time_seconds_bucket{le="+Inf"}'] == 3
    assert series["hvd_step_time_seconds_count"] == 3
    assert series["hvd_step_time_seconds_sum"] == pytest.approx(5.55)


def test_render_prometheus_labeled_histogram_and_escaping():
    reg = Registry()
    reg.histogram("h", labels={"rank": "0"}, buckets=[1.0]).observe(0.5)
    reg.gauge("g", labels={"path": 'a"b\nc'}).set(1)
    text = render_prometheus(reg.snapshot())
    assert 'h_bucket{rank="0",le="1"}' in text
    assert 'path="a\\"b\\nc"' in text


# -- engine derived view ----------------------------------------------------

def test_derived_ratios():
    c = {"cache_hits": 30, "cache_misses": 10, "responses_executed": 20,
         "fused_units": 5, "tensors_fused": 40}
    d = derived_ratios(c)
    assert d["cache_hit_rate"] == pytest.approx(0.75)
    assert d["fusion_ratio"] == pytest.approx(0.25)
    assert d["tensors_per_fused_unit"] == pytest.approx(8.0)
    assert derived_ratios({}) == {}  # no division by zero on empty engine


def test_engine_collector_mirrors_counters_and_rates():
    reg = Registry()
    counters = {"cache_hits": 8, "cache_misses": 2, "bytes_allreduced": 0}
    collector = EngineCollector(lambda: dict(counters), registry=reg)
    collector.collect()
    snap = reg.snapshot()
    assert snap["hvd_engine_cache_hits"]["value"] == 8
    assert snap["hvd_engine_cache_hit_rate"]["value"] == pytest.approx(0.8)
    # second scrape computes a bytes/s rate from the delta
    collector._prev_t -= 2.0  # pretend the first scrape was 2s ago
    counters["bytes_allreduced"] = 1 << 20
    collector.collect()
    rate = reg.snapshot()["hvd_engine_bytes_allreduced_per_second"]["value"]
    assert 0 < rate <= (1 << 20)


def test_engine_collector_autotune_decision_gauges():
    """The C++ autotuner's live decisions (ISSUE 8 satellite): counter
    keys with the autotune_ prefix surface as first-class
    hvd_autotune_* gauges — what the tuner PICKED — instead of being
    mirrored as cumulative hvd_engine_* counters."""
    reg = Registry()
    counters = {"cycles": 5,
                "autotune_fusion_bytes": 32 * 1024 * 1024,
                "autotune_cycle_ms": 2.5,
                "autotune_hierarchical": 1,
                "autotune_cache_enabled": 0}
    EngineCollector(lambda: dict(counters), registry=reg).collect()
    snap = reg.snapshot()
    assert snap["hvd_autotune_fusion_bytes"]["value"] == 32 * 1024 * 1024
    assert snap["hvd_autotune_cycle_ms"]["value"] == pytest.approx(2.5)
    assert snap["hvd_autotune_hierarchical"]["value"] == 1
    assert snap["hvd_autotune_cache_enabled"]["value"] == 0
    assert "hvd_engine_autotune_fusion_bytes" not in snap
    assert snap["hvd_engine_cycles"]["value"] == 5


def test_engine_collector_straggler_gauges():
    reg = Registry()
    report = {"tensors_timed": 2, "total_wait_seconds": 3.5,
              "ranks": {"1": {"wait_seconds": 3.0, "held_count": 2}}}
    EngineCollector(lambda: {}, registry=reg,
                    stragglers_fn=lambda: report).collect()
    snap = reg.snapshot()
    assert snap['hvd_straggler_wait_seconds{rank="1"}']["value"] == 3.0
    assert snap['hvd_straggler_held_count{rank="1"}']["value"] == 2


def test_engine_collector_survives_failing_source():
    reg = Registry()
    def boom():
        raise RuntimeError("engine gone")
    EngineCollector(boom, registry=reg).collect()  # must not raise
    assert reg.snapshot() == {}


# -- exporter round-trip ----------------------------------------------------

def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.status, r.headers.get("Content-Type"), r.read().decode()


def test_exporter_scrape_roundtrip():
    reg = Registry()
    reg.counter("hvd_steps_total", help="steps").inc(2)
    reg.histogram("hvd_step_time_seconds", buckets=[0.1, 1.0]).observe(0.5)
    exp = MetricsExporter(registry=reg, port=0)
    exp.start()
    try:
        status, ctype, body = _get(exp.port, "/metrics")
        assert status == 200 and "0.0.4" in ctype
        series = _parse_prometheus(body)
        assert series["hvd_steps_total"] == 2
        assert series['hvd_step_time_seconds_bucket{le="+Inf"}'] == 1
        status, ctype, body = _get(exp.port, "/healthz")
        assert status == 200 and json.loads(body) == {"status": "ok"}
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(exp.port, "/nope")
        assert e.value.code == 404
    finally:
        exp.stop()


def test_exporter_collectors_run_per_scrape_and_failures_skipped():
    reg = Registry()
    calls = []

    def refresh():
        calls.append(1)
        reg.gauge("live").set(len(calls))

    def broken():
        raise RuntimeError("collector bug")

    exp = MetricsExporter(registry=reg, port=0,
                          collectors=[refresh, broken])
    exp.start()
    try:
        _get(exp.port, "/metrics")
        _, _, body = _get(exp.port, "/metrics")
        assert _parse_prometheus(body)["live"] == 2  # ran once per scrape
    finally:
        exp.stop()


def test_exporter_unhealthy_health_fn_returns_503():
    exp = MetricsExporter(registry=Registry(), port=0,
                          health_fn=lambda: {"status": "shutdown"})
    exp.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(exp.port, "/healthz")
        assert e.value.code == 503
    finally:
        exp.stop()


def test_exporter_stop_without_start_returns():
    exp = MetricsExporter(registry=Registry(), port=0)
    t = threading.Thread(target=exp.stop, daemon=True)
    t.start()
    t.join(timeout=5)
    assert not t.is_alive()  # shutdown() must not wait on serve_forever()


# -- step timer -------------------------------------------------------------

def test_step_timer_records_histogram_and_throughput():
    from horovod_tpu.train.callbacks import StepTimer
    reg = Registry()
    timer = StepTimer(unit="images", registry=reg)
    with timer.step(units=32):
        pass
    timer.start_step()
    dt = timer.end_step(units=32)
    assert dt is not None and dt >= 0
    snap = reg.snapshot()
    assert snap["hvd_steps_total"]["value"] == 2
    assert snap["hvd_images_total"]["value"] == 64
    assert snap["hvd_step_time_seconds"]["count"] == 2
    assert snap["hvd_images_per_second"]["value"] > 0


def test_step_timer_failed_step_not_recorded():
    from horovod_tpu.train.callbacks import StepTimer
    reg = Registry()
    timer = StepTimer(registry=reg)
    with pytest.raises(RuntimeError):
        with timer.step(units=8):
            raise RuntimeError("oom")
    assert reg.snapshot()["hvd_steps_total"]["value"] == 0
    assert timer.end_step() is None  # the aborted step left no open timer


def test_step_timer_mfu_unknown_peak_stays_none():
    from horovod_tpu.train.callbacks import StepTimer
    reg = Registry()
    timer = StepTimer(flops_per_step=1e12, registry=reg)
    timer._peak = None  # device peak unknown (e.g. CPU host)
    timer.start_step()
    timer.end_step(units=1)
    assert timer.last_mfu is None  # never report the gauge's 0.0 default
    timer._peak = 2e12
    timer.start_step()
    timer.end_step(units=1)
    assert timer.last_mfu is not None and timer.last_mfu > 0
    assert reg.snapshot()["hvd_mfu"]["value"] == pytest.approx(
        timer.last_mfu)


def test_telemetry_callback_hooks():
    from horovod_tpu.train.callbacks import TelemetryCallback
    reg = Registry()
    cb = TelemetryCallback(units_per_step=16, unit="tokens", registry=reg)
    for _ in range(3):
        cb.on_step_begin()
        cb.on_step_end()
    snap = reg.snapshot()
    assert snap["hvd_steps_total"]["value"] == 3
    assert snap["hvd_tokens_total"]["value"] == 48
    assert cb.on_epoch_end({"loss": 1.0}) == {"loss": 1.0}


# -- registry get/drop_prefix (fleet + re-mesh hygiene) ---------------------

def test_registry_get_never_creates():
    reg = Registry()
    assert reg.get("absent") is None
    assert "absent" not in reg.snapshot()
    c = reg.counter("present", labels={"a": "1"})
    assert reg.get("present", labels={"a": "1"}) is c
    assert reg.get("present") is None  # label set is part of identity


def test_registry_drop_prefix():
    reg = Registry()
    reg.gauge("hvd_engine_cycles").set(1)
    reg.gauge("hvd_engine_cache_hits").set(2)
    reg.counter("hvd_stall_warnings_total").inc(3)
    assert reg.drop_prefix("hvd_engine_") == 2
    snap = reg.snapshot()
    assert "hvd_engine_cycles" not in snap
    # cumulative counters under other prefixes survive the re-mesh
    assert snap["hvd_stall_warnings_total"]["value"] == 3


# -- /healthz liveness (ISSUE 7 satellite) ----------------------------------

def test_watchdog_liveness_doc():
    from horovod_tpu.diagnostics import watchdog as wd
    wd.reset()
    try:
        live = wd.liveness()
        assert live["last_step"] is None
        assert live["last_step_age_s"] is None  # still compiling != stalled
        wd.notify_progress(7)
        live = wd.liveness()
        assert live["last_step"] == 7
        assert 0 <= live["last_step_age_s"] < 5
    finally:
        wd.reset()


def _health_doc_like_worker(state_initialized, age_s, timeout_s,
                            last_step):
    """The exporter's health rule, distilled: stalled only when steps
    HAVE flowed and then stopped past the watchdog threshold."""
    status = "ok" if state_initialized else "shutdown"
    if status == "ok" and timeout_s and timeout_s > 0 \
            and age_s is not None and age_s > timeout_s:
        status = "stalled"
    return status


def test_healthz_statuses():
    assert _health_doc_like_worker(True, None, 600, None) == "ok"
    assert _health_doc_like_worker(True, 10, 600, 5) == "ok"
    assert _health_doc_like_worker(True, 700, 600, 5) == "stalled"
    assert _health_doc_like_worker(True, 700, 0, 5) == "ok"  # disarmed
    assert _health_doc_like_worker(False, 1, 600, 5) == "shutdown"


def test_healthz_liveness_served_end_to_end(monkeypatch):
    """A live exporter built the way hvd.init builds it (same health
    closure semantics): reports last-step age, flips to 503 once the
    age crosses the threshold."""
    from horovod_tpu.diagnostics import watchdog as wd

    class _State:
        initialized = True
        rank, size, hostname = 0, 1, "test-host"
        backend = None

    state = _State()
    wd.reset()

    def health():
        doc = {"status": "ok" if state.initialized else "shutdown",
               "rank": state.rank, "size": state.size}
        live = wd.liveness()
        doc["last_step"] = live["last_step"]
        doc["last_step_age_s"] = live["last_step_age_s"]
        doc["watchdog"] = {"armed": live["armed"],
                           "timeout_s": live["timeout_s"]}
        age = live["last_step_age_s"]
        if doc["status"] == "ok" and live["timeout_s"] > 0 \
                and age is not None and age > live["timeout_s"]:
            doc["status"] = "stalled"
        return doc

    exp = MetricsExporter(registry=Registry(), port=0, health_fn=health)
    exp.start()
    try:
        # no steps yet: ok (compiling is not a stall)
        status, _, body = _get(exp.port, "/healthz")
        doc = json.loads(body)
        assert status == 200 and doc["last_step"] is None

        wd.notify_progress(41)
        status, _, body = _get(exp.port, "/healthz")
        doc = json.loads(body)
        assert status == 200 and doc["last_step"] == 41
        assert doc["last_step_age_s"] < 5
        assert doc["watchdog"]["timeout_s"] == 600.0

        # age the last step past the threshold: 503 + "stalled"
        monkeypatch.setenv("HVD_TPU_WATCHDOG_SECONDS", "0.01")
        import time
        time.sleep(0.05)
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(exp.port, "/healthz")
        assert e.value.code == 503
        assert json.loads(e.value.read())["status"] == "stalled"

        # disarmed watchdog (0) never reports stalled
        monkeypatch.setenv("HVD_TPU_WATCHDOG_SECONDS", "0")
        status, _, body = _get(exp.port, "/healthz")
        assert status == 200
    finally:
        exp.stop()
        wd.reset()
