"""Bucket planner unit battery (ISSUE 6): byte budgets, reverse
registration order, tiny-tensor coalescing, oversized leaves,
pack/unpack roundtrips, budget resolution against the fusion
threshold."""

import numpy as np
import jax
import jax.numpy as jnp

from horovod_tpu.common.config import reset_config
from horovod_tpu.train.buckets import (pack, plan_buckets,
                                       resolve_bucket_bytes, unpack)


def _tree(*shapes, dtype=jnp.float32):
    return [jnp.zeros(s, dtype) for s in shapes]


def test_single_bucket_when_budget_covers_all():
    plan = plan_buckets(_tree((4,), (8,), (2,)), bucket_bytes=1 << 20)
    assert plan.num_buckets == 1
    assert plan.buckets[0].indices == (0, 1, 2)
    assert plan.total_bytes == (4 + 8 + 2) * 4


def test_budget_splits_and_reverse_order():
    # leaves: 400B, 48B, 8B; reverse walk packs (c, b) then a
    plan = plan_buckets(_tree((100,), (3, 4), (2,)), bucket_bytes=400)
    assert plan.num_buckets == 2
    # bucket 0 holds the LAST-registered leaves (first grads produced)
    assert plan.buckets[0].indices == (1, 2)
    assert plan.buckets[0].nbytes == 56
    assert plan.buckets[1].indices == (0,)


def test_forward_order_flag():
    plan = plan_buckets(_tree((100,), (3, 4), (2,)), bucket_bytes=400,
                        reverse=False)
    assert plan.buckets[0].indices == (0,)


def test_tiny_tensors_coalesce():
    # 64 tiny leaves coalesce into few buckets, never one-per-leaf
    plan = plan_buckets(_tree(*[(4,)] * 64), bucket_bytes=128)
    assert plan.num_buckets == 8
    assert all(len(b.indices) == 8 for b in plan.buckets)


def test_oversized_leaf_gets_own_bucket():
    plan = plan_buckets(_tree((1000,), (2,), (1000,)), bucket_bytes=512)
    sizes = [b.nbytes for b in plan.buckets]
    assert plan.num_buckets == 3
    assert sorted(sizes)[-1] == 4000  # oversized leaves ride alone
    # and the tiny leaf shares no bucket with either giant
    tiny = [b for b in plan.buckets if 1 in b.indices]
    assert tiny[0].indices == (1,)


def test_plan_on_shape_dtype_structs():
    tree = {"w": jax.ShapeDtypeStruct((16, 16), jnp.bfloat16),
            "b": jax.ShapeDtypeStruct((16,), jnp.float32)}
    plan = plan_buckets(tree, bucket_bytes=1 << 20)
    assert plan.total_bytes == 16 * 16 * 2 + 16 * 4


def test_budget_resolution_prefers_env_then_fusion(monkeypatch):
    monkeypatch.delenv("HVD_TPU_BUCKET_BYTES", raising=False)
    monkeypatch.delenv("HOROVOD_BUCKET_BYTES", raising=False)
    monkeypatch.delenv("HVD_TPU_FUSION_THRESHOLD", raising=False)
    monkeypatch.delenv("HOROVOD_FUSION_THRESHOLD", raising=False)
    reset_config()
    # reconciled default: 64 MiB, the reference's own fusion default
    assert resolve_bucket_bytes() == 64 * 1024 * 1024
    monkeypatch.setenv("HVD_TPU_BUCKET_BYTES", "4096")
    reset_config()
    assert resolve_bucket_bytes() == 4096
    assert resolve_bucket_bytes(128) == 128  # explicit argument wins
    reset_config()


def test_pack_unpack_roundtrip():
    rng = np.random.RandomState(0)
    leaves = [jnp.asarray(rng.randn(3, 5).astype(np.float32)),
              jnp.asarray(rng.randn(7).astype(np.float32)),
              jnp.asarray(rng.randn(2, 2).astype(np.float32))]
    plan = plan_buckets(leaves, bucket_bytes=1 << 20)
    vec = pack(leaves, plan.buckets[0], pad_to=8)
    assert vec.size % 8 == 0
    out = unpack(vec, plan.buckets[0], leaves)
    for got, want in zip(out, [leaves[i]
                               for i in plan.buckets[0].indices]):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_pack_keeps_native_bf16_wire_dtype():
    """An all-bf16 bucket must move bf16 (the bandwidth the subsystem
    exists to save), promoting only for mixed buckets."""
    leaves = [jnp.zeros((8,), jnp.bfloat16), jnp.zeros((4,), jnp.bfloat16)]
    plan = plan_buckets(leaves, bucket_bytes=1 << 20)
    assert pack(leaves, plan.buckets[0]).dtype == jnp.bfloat16
    mixed = [jnp.zeros((8,), jnp.bfloat16), jnp.zeros((4,), jnp.float32)]
    plan = plan_buckets(mixed, bucket_bytes=1 << 20)
    assert pack(mixed, plan.buckets[0]).dtype == jnp.float32


def test_plan_records_metrics():
    from horovod_tpu.metrics.registry import default_registry
    plan_buckets(_tree((64,), (64,)), bucket_bytes=256)
    snap = default_registry().snapshot()
    assert snap["hvd_overlap_bucket_count"]["value"] == 2
    assert snap["hvd_overlap_bucket_bytes"]["value"] == 512
