"""Unified parallelism plan battery (ISSUE 11): ParallelPlan
validation / fingerprint / cache roundtrip, the compile seam's
pjit-vs-shard_map dispatch, interleaved == 1f1b == jax.grad parity
across the (pp, dp, M, v) grid, composed DP x PP loss-trajectory parity
with pure DP (incl. the int8 wire codec), the schedule-sweep timing
acceptance, and the extended autotune search locking a full parallelism
plan (warm cache => zero trials).

CPU note: everything runs on the 8-device virtual mesh under
tests/conftest.py with the persistent XLA compile cache at its default
of DISABLED (the known warm-cache heap-corruption constraint)."""

import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.parallel import build_mesh, dp_pp_mesh
from horovod_tpu.parallel.pipeline import (bubble_fraction,
                                           interleaved_tables,
                                           pipeline_1f1b_apply,
                                           pipeline_interleaved_apply,
                                           replicate_from_stage,
                                           schedule_ticks, stage_stacked)
from horovod_tpu.parallel.plan import (ParallelPlan, compile_step_with_plan,
                                       plan_from_dict)
from horovod_tpu.train.autotune import (AutotuneOptions, Plan, PlanCache,
                                        make_parallel_train_step,
                                        parallel_candidate_plans,
                                        plan_fingerprint, topology_key)
from horovod_tpu.train.pipeline import (make_pipeline_train_step,
                                        stage_layout_permutation)
from horovod_tpu.common.topology import flat_topology


# -- ParallelPlan validation / identity -------------------------------------

def test_parallel_plan_roundtrip_and_key():
    p = ParallelPlan(dp=2, pp=4, schedule="interleaved", n_microbatches=8,
                     virtual_stages=2, comms=Plan(1 << 20, "psum", "int8"))
    assert ParallelPlan.from_dict(p.to_dict()) == p
    assert "dp2xpp4" in p.key and "interleavedv2" in p.key
    assert p.world == 8 and p.total_stages == 8
    # the comm facade the shared controller/CSV/gauges read
    assert p.codec == "int8" and p.algorithm == "psum"
    bare = ParallelPlan(dp=8, pp=1)
    assert bare.codec == "none" and bare.bucket_bytes == 0


@pytest.mark.parametrize("kw", [
    dict(dp=0),
    dict(pp=0),
    dict(schedule="pipedream"),
    dict(pp=2, n_microbatches=1),                       # pure bubble
    dict(virtual_stages=2, schedule="1f1b"),            # v needs interleaved
    dict(n_microbatches=0),
    dict(comms="int8"),                                 # not a Plan
])
def test_parallel_plan_validation_rejects(kw):
    base = dict(dp=2, pp=2, n_microbatches=4)
    base.update(kw)
    with pytest.raises(ValueError):
        ParallelPlan(**base)


def test_plan_from_dict_dispatch():
    comm = Plan(4096, "ring", "none")
    par = ParallelPlan(dp=4, pp=2, n_microbatches=4, comms=comm)
    assert plan_from_dict(comm.to_dict()) == comm
    revived = plan_from_dict(par.to_dict())
    assert isinstance(revived, ParallelPlan) and revived == par
    assert revived.comms == comm


def test_bubble_fraction_analytics():
    # plain 1F1B pays the combined fill+drain bubble; interleaving with
    # v chunks strictly shrinks it at the same M (the tentpole claim,
    # deterministic tick counts)
    for S, M, v in [(4, 8, 2), (4, 8, 4), (8, 8, 2), (2, 8, 2)]:
        plain = bubble_fraction("1f1b", S, M)
        inter = bubble_fraction("interleaved", S, M, v)
        t_plain = v * schedule_ticks("1f1b", S, M)[0]  # sub-tick equiv
        t_inter = schedule_ticks("interleaved", S, M, v)[0]
        assert t_inter <= t_plain, (S, M, v)
        if S > 2:
            assert inter < plain, (S, M, v)
    assert bubble_fraction("gpipe", 1, 4) == 0.0
    assert ParallelPlan(dp=2, pp=4, n_microbatches=8).bubble_fraction() \
        == bubble_fraction("1f1b", 4, 8)


def test_interleaved_tables_are_a_valid_schedule():
    """Replay the static tables and assert every dependency: forwards
    in stage order with one-tick transfer delay, backwards after the
    successor's backward, the last stage seeding same-tick, and at most
    one unit per device per phase per tick (the scheduler's contract —
    the numerics tests would catch corruption, this catches an invalid
    schedule that happens to mask itself)."""
    for S, v, M in [(2, 2, 4), (4, 2, 8), (2, 4, 8), (4, 3, 5)]:
        sched = interleaved_tables(S, v, M)
        tb = sched["tables"]
        V = S * v
        ef, eb = {}, {}
        for t in range(sched["ticks"]):
            for d in range(S):
                if tb["fv"][t][d]:
                    q = tb["fj"][t][d] * S + d
                    m = tb["fm"][t][d]
                    assert (q, m) not in ef
                    if q > 0:
                        assert ef[(q - 1, m)] < t, (S, v, M, q, m, t)
                    ef[(q, m)] = t
            for d in range(S):
                if tb["bv"][t][d]:
                    q = tb["bj"][t][d] * S + d
                    m = tb["bm"][t][d]
                    assert (q, m) not in eb
                    assert ef[(q, m)] <= t
                    if q < V - 1:
                        assert eb[(q + 1, m)] < t
                    eb[(q, m)] = t
        assert len(ef) == V * M and len(eb) == V * M
        assert 0.0 < sched["bubble_fraction"] < 1.0


def test_stage_layout_permutation_roundtrip():
    perm = stage_layout_permutation(8, pp=2, virtual_stages=2)
    # device 0: chunk0 = stages 0 (layers 0,1), chunk1 = stage 2
    # (layers 4,5); device 1: stage 1 (2,3) then stage 3 (6,7)
    assert perm.tolist() == [0, 1, 4, 5, 2, 3, 6, 7]
    assert stage_layout_permutation(8, pp=4).tolist() == list(range(8))
    with pytest.raises(ValueError):
        stage_layout_permutation(8, pp=3)


# -- fingerprint / cache ----------------------------------------------------

def test_topology_key_pp_dimension():
    topo = flat_topology(8)
    tree = {"w": jnp.zeros((4, 4))}
    comm_fp = plan_fingerprint(tree, topology_key(topo), 8)
    pipe_fp = plan_fingerprint(tree, topology_key(topo, pp=0), 8)
    under_pp = plan_fingerprint(tree, topology_key(topo, pp=4), 8)
    # a comm plan tuned under one pp split can never shadow the
    # parallel-plan entry (pp=0 sentinel) or another split's entry
    assert len({comm_fp, pipe_fp, under_pp}) == 3


def test_cache_roundtrips_parallel_plan(tmp_path):
    cache = PlanCache(str(tmp_path))
    plan = ParallelPlan(dp=2, pp=4, schedule="interleaved",
                        n_microbatches=8, virtual_stages=2,
                        comms=Plan(1 << 20, "psum", "int8"))
    assert cache.store("a" * 64, plan)
    got = cache.load("a" * 64)
    assert isinstance(got, ParallelPlan) and got == plan
    # comm plans still roundtrip as comm plans
    cache.store("b" * 64, Plan(4096, "ring", "none"))
    assert cache.load("b" * 64) == Plan(4096, "ring", "none")


# -- compile seam -----------------------------------------------------------

def test_compile_seam_pjit_path():
    mesh = build_mesh(dp=8)
    sh = NamedSharding(mesh, P("dp"))
    rep = NamedSharding(mesh, P())

    def step(x):
        return x * 2.0, jnp.sum(x)

    fn = compile_step_with_plan(step, mesh, in_shardings=(sh,),
                                out_shardings=(sh, rep))
    x = jnp.arange(16.0)
    y, s = fn(x)
    np.testing.assert_allclose(np.asarray(y), np.arange(16.0) * 2)
    assert float(s) == np.arange(16.0).sum()
    assert y.sharding.is_equivalent_to(sh, y.ndim)


def test_compile_seam_shard_map_path():
    mesh = build_mesh(dp=8)

    def body(x):     # map-style SPMD: a named-axis collective
        return lax.psum(jnp.sum(x), "dp")

    fn = compile_step_with_plan(body, mesh, in_specs=(P("dp"),),
                                out_specs=P())
    assert float(fn(jnp.ones(16))) == 16.0


def test_compile_seam_single_device_fallback():
    mesh = build_mesh(dp=1, devices=jax.devices()[:1])
    fn = compile_step_with_plan(lambda x: x + 1, mesh)
    assert float(fn(jnp.asarray(1.0))) == 2.0


def test_compile_seam_rejects_mixed_and_half_args():
    mesh = build_mesh(dp=8)
    sh = NamedSharding(mesh, P("dp"))
    with pytest.raises(ValueError, match="BOTH in_shardings"):
        compile_step_with_plan(lambda x: x, mesh, in_shardings=(sh,))
    with pytest.raises(ValueError, match="BOTH in_specs"):
        compile_step_with_plan(lambda x: x, mesh, out_specs=P())
    with pytest.raises(ValueError, match="not both"):
        compile_step_with_plan(lambda x: x, mesh, in_shardings=(sh,),
                               out_shardings=(sh,), in_specs=(P("dp"),),
                               out_specs=P())


def test_replicate_from_stage_grads_inside_shard_map():
    """Differentiating a replicated consumer INSIDE shard_map: the
    masked-psum idiom over-counts by the axis size (every shard seeds
    its replicated loss); replicate_from_stage must not — this is the
    GPipe-by-autodiff / transformer-pp gradient-scale regression test."""
    import functools
    from horovod_tpu._compat import shard_map
    mesh = build_mesh(dp=1, pp=4, devices=jax.devices()[:4])
    w = jnp.asarray(np.random.RandomState(0).randn(4).astype(np.float32))

    @functools.partial(shard_map, mesh=mesh, in_specs=(P("pp"),),
                       out_specs=P("pp"), check_vma=False)
    def grads(wl):
        def loss(wl):
            stage = lax.axis_index("pp")
            val = jnp.where(stage == 3, wl[0] * 2.0, wl[0])
            y = replicate_from_stage(val, "pp", 3)
            return y ** 2
        return jax.grad(loss)(wl)

    g = np.asarray(grads(w))
    # only stage 3 feeds the replicated output; its gradient must be
    # d/dw (2w)^2 = 8w — once, not 4x
    np.testing.assert_allclose(g[3], 8.0 * w[3], rtol=1e-6)
    np.testing.assert_allclose(g[:3], 0.0, atol=1e-7)


# -- schedule numerics: interleaved == 1f1b == jax.grad ---------------------

def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _mse(y, t):
    return jnp.mean((y - t) ** 2)


def _grid_case(pp, dp, M, v, H=8):
    V = pp * v
    T = M * 4
    rng = np.random.RandomState(7)
    stages = [{"w": jnp.asarray(rng.randn(H, H), jnp.float32) * 0.4,
               "b": jnp.asarray(rng.randn(H), jnp.float32) * 0.1}
              for _ in range(V)]
    x = jnp.asarray(rng.randn(T, H), jnp.float32)
    tgt = jnp.asarray(rng.randn(T, H), jnp.float32)
    stacked = stage_stacked(stages)

    def oracle(pl):
        xm = x.reshape(M, T // M, H)
        tm = tgt.reshape(M, T // M, H)

        def one_mb(xb, tb):
            h = xb
            for s in range(V):
                h = _stage_fn(jax.tree_util.tree_map(
                    lambda p, s=s: p[s], pl), h)
            return _mse(h, tb)
        return jax.vmap(one_mb)(xm, tm).mean()

    ref_loss, ref_g = jax.value_and_grad(oracle)(stacked)
    mesh = build_mesh(dp=dp, pp=pp, devices=jax.devices()[:dp * pp])
    loss, g = pipeline_interleaved_apply(
        _stage_fn, _mse, stacked, x, tgt, mesh, n_microbatches=M,
        virtual_stages=v)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(g),
                    jax.tree_util.tree_leaves(ref_g)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
    if v == 1:
        # at v=1 the interleaved machinery must agree with the plain
        # 1F1B implementation too (same schedule, different codepath)
        l2, g2 = pipeline_1f1b_apply(_stage_fn, _mse, stacked, x, tgt,
                                     mesh, n_microbatches=M)
        np.testing.assert_allclose(float(l2), float(loss), rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(g2),
                        jax.tree_util.tree_leaves(g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("pp,dp,M,v", [(2, 2, 4, 2), (4, 2, 8, 1)])
def test_interleaved_matches_jax_grad(pp, dp, M, v):
    _grid_case(pp, dp, M, v)


@pytest.mark.slow
@pytest.mark.parametrize("pp,dp,M,v", [
    (4, 2, 8, 2),      # the acceptance 2x4 layout, v=2
    (2, 4, 8, 4),      # deep virtual interleave
    (4, 1, 3, 2),      # M < 2S-1: ragged fill/drain
    (2, 2, 5, 3),      # M coprime with S and v
])
def test_interleaved_matches_jax_grad_heavy(pp, dp, M, v):
    _grid_case(pp, dp, M, v)


def test_dp_reducer_seam_matches_dense_pmean():
    """Satellite 1: the dp reduction seam. Routed through the bucketed
    sync, gradients must equal the exact dense-pmean fallback (Average
    psum per bucket == pmean per leaf, fp32)."""
    from horovod_tpu.train.overlap import bucketed_grad_sync
    pp, dp, M = 2, 4, 4
    rng = np.random.RandomState(3)
    stages = [{"w": jnp.asarray(rng.randn(8, 8), jnp.float32) * 0.4,
               "b": jnp.asarray(rng.randn(8), jnp.float32) * 0.1}
              for _ in range(pp)]
    stacked = stage_stacked(stages)
    x = jnp.asarray(rng.randn(16, 8), jnp.float32)
    tgt = jnp.asarray(rng.randn(16, 8), jnp.float32)
    mesh = build_mesh(dp=dp, pp=pp)
    dense_loss, dense_g = pipeline_1f1b_apply(
        _stage_fn, _mse, stacked, x, tgt, mesh, n_microbatches=M)

    def reducer(grads):
        return bucketed_grad_sync(grads, "dp", bucket_bytes=64)

    loss, g = pipeline_1f1b_apply(
        _stage_fn, _mse, stacked, x, tgt, mesh, n_microbatches=M,
        dp_reducer=reducer)
    np.testing.assert_allclose(float(loss), float(dense_loss), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g),
                    jax.tree_util.tree_leaves(dense_g)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


# -- composed DP x PP vs pure DP (the factory) ------------------------------

_L, _D = 8, 16


def _layer_model():
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(_L, _D, _D), jnp.float32) * 0.4,
              "b": jnp.asarray(rng.randn(_L, _D), jnp.float32) * 0.1}

    def layer_fn(lp, x):
        return jnp.tanh(x @ lp["w"] + lp["b"])

    x = jnp.asarray(rng.randn(64, _D), jnp.float32)
    tgt = jnp.asarray(rng.randn(64, _D), jnp.float32)
    return params, layer_fn, (x, tgt)


def _trajectory(schedule, pp, M, v=1, steps=6, compression=None,
                params=None, batch=None, layer_fn=None, tx=None):
    step = make_pipeline_train_step(
        layer_fn, _mse, tx, n_layers=_L, schedule=schedule, pp=pp,
        n_micro=M, virtual_stages=v, compression=compression,
        donate=False, autotune=False)
    p = step.prepare_params(params)
    s = step.prepare_params(tx.init(params))
    losses = []
    for _ in range(steps):
        p, s, loss = step(p, s, batch)
        losses.append(float(loss))
    return losses, step.restore_params(p)


@pytest.mark.parametrize("schedule,pp,M,v", [
    ("1f1b", 4, 8, 1),            # acceptance layout dp2 x pp4
    ("interleaved", 2, 8, 2),     # acceptance layout dp4 x pp2
])
def test_composed_dp_pp_matches_pure_dp_trajectory(schedule, pp, M, v):
    """ISSUE 11 acceptance: on the 8-device mesh the composed DP x PP
    step (stage grads through bucketed_grad_sync over dp) must match
    the pure-DP (pp=1, overlap-engine) loss trajectory to fp32
    tolerance, parameters included."""
    params, layer_fn, batch = _layer_model()
    tx = optax.adam(1e-2)
    kw = dict(params=params, batch=batch, layer_fn=layer_fn, tx=tx)
    ref_losses, ref_p = _trajectory("1f1b", 1, M, **kw)
    losses, p = _trajectory(schedule, pp, M, v, **kw)
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4, atol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p),
                    jax.tree_util.tree_leaves(ref_p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)


@pytest.mark.slow
def test_composed_dp_pp_gpipe_and_int8_trajectories():
    """The gpipe schedule and the int8 wire codec through the composed
    step: gpipe matches pure DP exactly (same fp32 math); with the int8
    codec on the dp hop, both layouts quantize (different bucket
    boundaries), so the gate is a converging trajectory that tracks the
    exact one within a loose band — the codec's documented contract,
    not bit parity."""
    from horovod_tpu.compression.quantizers import resolve_compressor
    params, layer_fn, batch = _layer_model()
    tx = optax.adam(1e-2)
    kw = dict(params=params, batch=batch, layer_fn=layer_fn, tx=tx)
    ref_losses, _ = _trajectory("1f1b", 1, 8, **kw)
    g_losses, _ = _trajectory("gpipe", 4, 8, **kw)
    np.testing.assert_allclose(g_losses, ref_losses, rtol=1e-4, atol=1e-5)
    q = resolve_compressor("int8")
    q_losses, _ = _trajectory("1f1b", 4, 8, steps=8, compression=q, **kw)
    assert q_losses[-1] < q_losses[0] * 0.8, q_losses
    exact, _ = _trajectory("1f1b", 1, 8, steps=8, **kw)
    assert abs(q_losses[-1] - exact[-1]) < 0.1 * abs(exact[0]), (
        q_losses, exact)


def test_factory_rejects_bad_layouts():
    params, layer_fn, batch = _layer_model()
    tx = optax.sgd(1e-2)
    with pytest.raises(ValueError, match="does not divide"):
        make_pipeline_train_step(layer_fn, _mse, tx, n_layers=_L,
                                 schedule="1f1b", pp=3, n_micro=4)
    with pytest.raises(ValueError, match="not divisible"):
        make_pipeline_train_step(layer_fn, _mse, tx, n_layers=6,
                                 schedule="1f1b", pp=4, n_micro=4)
    step = make_pipeline_train_step(layer_fn, _mse, tx, n_layers=_L,
                                    schedule="1f1b", pp=2, n_micro=4,
                                    donate=False, autotune=False)
    p = step.prepare_params(params)
    s = tx.init(p)
    bad = (jnp.ones((30, _D)), jnp.ones((30, _D)))   # 30 % (dp*M) != 0
    with pytest.raises((ValueError, TypeError)):
        step(p, s, bad)


# -- the schedule-sweep timing acceptance -----------------------------------

@pytest.mark.slow
def test_schedule_sweep_interleaved_beats_plain_1f1b():
    """ISSUE 11 acceptance, PR-8 sweep design (interleaved repeats,
    best-of): at fixed M on the 8-dev mesh, measured interleaved step
    time must not exceed plain 1F1B's (the ~1/v bubble), and no
    schedule may fall outside a 3x band of the fastest (the PR-8
    tolerance-band form of `interleaved <= 1f1b <= gpipe` — on an SPMD
    mesh the 1F1B family pays remat + the combined-tick bubble against
    GPipe-by-autodiff, so the raw middle inequality is a band, not a
    strict order; docs/PERF.md "Pipeline parallelism" has the cost
    model and measured numbers)."""
    import sys
    bench_dir = os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "benchmarks")
    sys.path.insert(0, bench_dir)
    try:
        from pipeline_bench import run_schedule_sweep
    finally:
        sys.path.remove(bench_dir)
    doc = run_schedule_sweep(pp=4, virtual_stages=2, n_micro=8,
                             d_model=384, n_layers=8,
                             rows_per_microbatch=16, iters=4, repeats=3)
    t = doc["schedules"]
    assert t["interleaved"] <= t["1f1b"] * 1.02, doc
    fastest = min(t.values())
    assert max(t.values()) <= 3.0 * fastest, doc
    assert doc["bubble"]["interleaved"] < doc["bubble"]["1f1b"]


# -- the extended autotune search -------------------------------------------

def _tune_model():
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(_L, 32, 32), jnp.float32) * 0.4}

    def layer_fn(lp, x):
        return jnp.tanh(x @ lp["w"])

    x = jnp.asarray(rng.randn(64, 32), jnp.float32)
    tgt = jnp.asarray(rng.randn(64, 32), jnp.float32)
    return params, layer_fn, (x, tgt)


def test_parallel_candidate_plans_shape():
    plans = parallel_candidate_plans(8, 8)
    assert plans[0] == ParallelPlan(dp=8, pp=1)    # baseline first
    keys = {p.key for p in plans}
    assert len(keys) == len(plans)                 # deduplicated
    assert any(p.pp == 4 and p.schedule == "interleaved" for p in plans)
    assert any(p.comms is not None and p.comms.codec == "int8"
               for p in plans)
    # pp must divide both the world and the layer count
    assert all(8 % p.pp == 0 and 8 % p.total_stages == 0 for p in plans)
    assert all(p.pp <= 4 for p in parallel_candidate_plans(8, 4))


def test_parallel_autotune_warm_cache_zero_trials(tmp_path):
    """A cached ParallelPlan must lock on the FIRST call with zero
    search trials (fast path of the acceptance; the full search is the
    slow test below)."""
    params, layer_fn, batch = _tune_model()
    tx = optax.sgd(1e-2)
    topo = flat_topology(8)
    fp = plan_fingerprint(params, topology_key(topo, pp=0), 8)
    want = ParallelPlan(dp=2, pp=4, schedule="interleaved",
                        n_microbatches=8, virtual_stages=2)
    PlanCache(str(tmp_path)).store(fp, want)
    opts = AutotuneOptions(budget_steps=40, cache_dir=str(tmp_path))
    step = make_parallel_train_step(layer_fn, _mse, tx, n_layers=_L,
                                    autotune=opts, donate=False)
    p, s = params, tx.init(params)
    p, s, loss = step(p, s, batch)
    ctl = step.autotune
    assert ctl.from_cache and ctl.trials == 0
    assert ctl.locked_plan == want
    assert step.pin() is not None
    assert np.isfinite(float(loss))


def test_parallel_autotune_stale_cached_plan_retunes(tmp_path):
    """The fingerprint covers tree+world but NOT the batch: a cached
    plan tuned at another global batch must be rejected with a warning
    and a fresh search, never crash the first step (the documented
    cache contract)."""
    params, layer_fn, batch = _tune_model()   # global batch 64
    tx = optax.sgd(1e-2)
    topo = flat_topology(8)
    fp = plan_fingerprint(params, topology_key(topo, pp=0), 8)
    # m=48 cannot tile 64/2=32 rows per replica
    stale = ParallelPlan(dp=2, pp=4, schedule="1f1b", n_microbatches=48)
    PlanCache(str(tmp_path)).store(fp, stale)
    opts = AutotuneOptions(
        plans=[ParallelPlan(dp=8, pp=1),
               ParallelPlan(dp=2, pp=4, schedule="1f1b",
                            n_microbatches=8)],
        budget_steps=20, steps_per_trial=1, cache_dir=str(tmp_path))
    step = make_parallel_train_step(layer_fn, _mse, tx, n_layers=_L,
                                    autotune=opts, donate=False)
    p, s = params, tx.init(params)
    for _ in range(30):
        p, s, loss = step(p, s, batch)
        if step.autotune is not None and step.autotune.done:
            break
    ctl = step.autotune
    assert ctl.done and not ctl.from_cache and ctl.trials > 0
    assert ctl.locked_plan != stale
    # the retune overwrote the stale entry with a plan that DOES tile
    assert PlanCache(str(tmp_path)).load(fp) == ctl.locked_plan


def test_csv_trace_rotates_old_schema(tmp_path):
    from horovod_tpu.train.autotune import AutotuneController
    log_path = str(tmp_path / "trace.csv")
    with open(log_path, "w") as f:
        f.write("round,bucket_bytes,algorithm,codec,small_floor,"
                "step_s,final\n0,1,psum,none,0,0.001000,1\n")
    a, b = Plan(1, "psum", "none"), Plan(2, "psum", "none")
    ctl = AutotuneController([a, b], budget_steps=50, steps_per_trial=1,
                             log_path=log_path)
    while not ctl.done:
        ctl.end_step({a: 0.002, b: 0.009}[ctl.begin_step()])
    lines = open(log_path).read().strip().splitlines()
    assert lines[0] == ("round,bucket_bytes,algorithm,codec,"
                        "small_floor,plan,step_s,final")
    assert all(ln.count(",") == 7 for ln in lines)
    old = open(log_path + ".v1").read()
    assert "0.001000" in old   # the old audit trail survives, apart


@pytest.mark.slow
def test_parallel_autotune_converges_and_warm_cache_skips_search(
        tmp_path):
    """ISSUE 11 acceptance: the extended search — (pp, n_microbatches,
    schedule) joining bucket x algorithm x codec — locks a full
    parallelism plan within its step budget, and a second run against
    the warm cache locks the SAME plan with zero trials."""
    params, layer_fn, batch = _tune_model()
    tx = optax.sgd(1e-2)
    plans = parallel_candidate_plans(8, _L)[:8]
    opts = AutotuneOptions(plans=plans, budget_steps=60,
                           steps_per_trial=1, cache_dir=str(tmp_path))
    step = make_parallel_train_step(layer_fn, _mse, tx, n_layers=_L,
                                    autotune=opts, donate=False)
    p, s = params, tx.init(params)
    for _ in range(80):
        p, s, loss = step(p, s, batch)
        if step.autotune is not None and step.autotune.done:
            break
    ctl = step.autotune
    assert ctl.done and ctl.steps_used <= opts.budget_steps
    assert ctl.trials > 0 and not ctl.from_cache
    assert ctl.locked_plan in plans
    # training continued through the search on one state
    assert np.isfinite(float(loss))

    warm = make_parallel_train_step(layer_fn, _mse, tx, n_layers=_L,
                                    autotune=opts, donate=False)
    wp, ws = params, tx.init(params)
    warm(wp, ws, batch)
    assert warm.autotune.from_cache and warm.autotune.trials == 0
    assert warm.autotune.locked_plan == ctl.locked_plan


def test_factory_env_autotune_default(monkeypatch):
    """HVD_TPU_AUTOTUNE_MESH=1 flips the pipeline factory to the
    parallel searcher without touching call sites; explicit plan= or
    autotune=False still wins."""
    from horovod_tpu.common.config import reset_config
    from horovod_tpu.train.autotune import ParallelAutotunedStep
    params, layer_fn, batch = _tune_model()
    tx = optax.sgd(1e-2)
    monkeypatch.setenv("HVD_TPU_AUTOTUNE_MESH", "1")
    reset_config()
    try:
        step = make_pipeline_train_step(layer_fn, _mse, tx, n_layers=_L)
        assert isinstance(step, ParallelAutotunedStep)
        pinned = make_pipeline_train_step(
            layer_fn, _mse, tx, n_layers=_L,
            plan=ParallelPlan(dp=4, pp=2, n_microbatches=4))
        assert not isinstance(pinned, ParallelAutotunedStep)
        plain = make_pipeline_train_step(layer_fn, _mse, tx, n_layers=_L,
                                         autotune=False, pp=2, n_micro=4)
        assert not isinstance(plain, ParallelAutotunedStep)
    finally:
        reset_config()


def test_dp_pp_mesh_helper():
    mesh = dp_pp_mesh(pp=4)
    assert mesh.shape["pp"] == 4 and mesh.shape["dp"] == 2
    mesh2 = dp_pp_mesh(dp=2, pp=2, devices=jax.devices()[:4])
    assert mesh2.shape["dp"] == 2 and mesh2.shape["pp"] == 2
