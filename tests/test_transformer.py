"""Flagship transformer: forward/backward under every parallelism layout on
the 8-device virtual mesh, checked for finiteness, cross-layout loss
agreement, and training progress."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax

from horovod_tpu.models import (TransformerConfig, init_params, shard_params,
                                make_train_step, make_forward, init_opt_state,
                                shard_batch)
from horovod_tpu.parallel import build_mesh

CFG = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=4,
                        d_ff=64, max_seq=32, dtype=jnp.float32,
                        n_microbatches=2, remat=False)
MOE_CFG = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=4,
                            d_ff=64, max_seq=32, n_experts=4,
                            dtype=jnp.float32, n_microbatches=2, remat=False)


def _batch(B=8, S=16, vocab=64, seed=0):
    rng = np.random.RandomState(seed)
    tokens = rng.randint(0, vocab, (B, S)).astype(np.int32)
    targets = np.roll(tokens, -1, axis=1).astype(np.int32)
    return jnp.asarray(tokens), jnp.asarray(targets)


MESHES = {
    "dp8": dict(dp=8),
    "dp2_tp4": dict(dp=2, tp=4),
    "dp2_sp2_tp2": dict(dp=2, sp=2, tp=2),
    "dp2_pp2_tp2": dict(dp=2, pp=2, tp=2),
    "dp2_pp2_sp2": dict(dp=2, pp=2, sp=2),
}


@pytest.mark.parametrize("name", list(MESHES))
def test_forward_loss_agrees_across_layouts(name):
    """Same params + data must give (nearly) the same loss on every layout —
    the cross-layout analog of the reference's multi-rank numeric equality
    tests."""
    mesh_ref = build_mesh(dp=8)
    fwd_ref = make_forward(CFG, mesh_ref)
    rngp = np.random.RandomState(42)
    params_host = init_params(rngp, CFG, n_stages=1)
    tokens, targets = _batch()

    p_ref = shard_params(params_host, CFG, mesh_ref)
    t_ref, y_ref = shard_batch(tokens, targets, mesh_ref)
    ref = float(fwd_ref(p_ref, t_ref, y_ref))

    mesh = build_mesh(**MESHES[name])
    n_stages = MESHES[name].get("pp", 1)
    params_host_s = init_params(np.random.RandomState(42), CFG,
                                n_stages=n_stages)
    p = shard_params(params_host_s, CFG, mesh)
    t, y = shard_batch(tokens, targets, mesh)
    fwd = make_forward(CFG, mesh)
    out = float(fwd(p, t, y))
    assert np.isfinite(out)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_moe_forward_all_axes():
    """MoE config on a mesh using dp, ep and tp simultaneously."""
    mesh = build_mesh(dp=2, ep=2, tp=2)
    params_host = init_params(np.random.RandomState(1), MOE_CFG, n_stages=1)
    p = shard_params(params_host, MOE_CFG, mesh)
    tokens, targets = _batch()
    t, y = shard_batch(tokens, targets, mesh)
    out = float(make_forward(MOE_CFG, mesh)(p, t, y))
    assert np.isfinite(out)


def test_train_step_reduces_loss():
    mesh = build_mesh(dp=2, sp=2, tp=2)
    params_host = init_params(np.random.RandomState(3), CFG, n_stages=1)
    p = shard_params(params_host, CFG, mesh)
    tokens, targets = _batch()
    t, y = shard_batch(tokens, targets, mesh)
    tx = optax.adam(1e-2)
    step = make_train_step(CFG, mesh, tx)
    opt_state = init_opt_state(tx, p, mesh, CFG)
    losses = []
    for i in range(10):
        p, opt_state, loss, aux = step(p, opt_state, t, y)
        jax.block_until_ready(loss)  # 1-core CPU: avoid rendezvous pile-up
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses


def test_train_step_pipeline_moe():
    """The everything-at-once layout: dp, pp, and ep+tp shared... (8 devices:
    dp2 × pp2 × ep... ) — use dp2/pp2/tp2 with MoE (ep=1 degenerates to
    replicated experts, still exercising the MoE code path in the pipeline)."""
    mesh = build_mesh(dp=2, pp=2, tp=2)
    cfg = MOE_CFG
    params_host = init_params(np.random.RandomState(4), cfg, n_stages=2)
    p = shard_params(params_host, cfg, mesh)
    tokens, targets = _batch()
    t, y = shard_batch(tokens, targets, mesh)
    tx = optax.sgd(1e-2)
    step = make_train_step(cfg, mesh, tx)
    opt_state = init_opt_state(tx, p, mesh, cfg)
    p, opt_state, loss, aux = step(p, opt_state, t, y)
    jax.block_until_ready(loss)
    assert np.isfinite(float(loss))
    assert np.isfinite(float(aux))


def test_train_step_pipeline_matches_pure_dp_trajectory():
    """Pipeline-parallel training must be a pure LAYOUT change (ISSUE
    11): at the same data-parallel width, dp4 alone (4 devices) and
    dp4 x pp2 (8 devices) run identical math, so their loss
    trajectories must agree to fp tolerance. This is the regression
    test for the pipeline gradient-scale bug — differentiating the
    replicated loss inside shard_map over-counted every STAGE gradient
    by pp while the embed/head gradients stayed x1, silently skewing
    stage-vs-embedding training balance on every pp>1 mesh
    (parallel/pipeline.py `replicate_from_stage`)."""
    tokens, targets = _batch()

    def run(mesh_kw, n_stages, n_dev, steps=4):
        mesh = build_mesh(**mesh_kw, devices=jax.devices()[:n_dev])
        params_host = init_params(np.random.RandomState(42), CFG,
                                  n_stages=n_stages)
        p = shard_params(params_host, CFG, mesh)
        t, y = shard_batch(tokens, targets, mesh)
        tx = optax.sgd(5e-2)
        step = make_train_step(CFG, mesh, tx)
        s = init_opt_state(tx, p, mesh, CFG)
        out = []
        for _ in range(steps):
            p, s, loss, aux = step(p, s, t, y)
            jax.block_until_ready(loss)
            out.append(float(loss))
        return out

    ref = run(dict(dp=4), 1, 4)
    pp2 = run(dict(dp=4, pp=2), 2, 8)
    np.testing.assert_allclose(pp2, ref, rtol=1e-5, atol=1e-5)
