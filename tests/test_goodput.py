"""Goodput ledger tests (docs/OBSERVABILITY.md "Goodput ledger",
ISSUE 16): closed-books wall-clock attribution — every second between
window open and close lands in exactly one category and the categories
sum back to wall time within tolerance — plus the roofline MFU
decomposition, the ``goodput_regression`` detector wiring, the CLI
views, the fleet merge, and the end-to-end acceptance: a run on the
8-device CPU mesh paying a real compile, a checkpoint save, an elastic
re-mesh and a chaos stall closes its books with each event in its
category, the stall is flagged as ``goodput_regression`` naming
``input_wait`` and arms an autonomous profile capture; an identical
clean run reports no goodput finding."""

import argparse
import json
import os
import time

import pytest

from horovod_tpu.metrics import goodput
from horovod_tpu.metrics.goodput import CATEGORIES, GoodputLedger
from horovod_tpu.metrics.registry import Registry, default_registry
from horovod_tpu.profiling import attribution


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    """Drop every singleton the ledger feeds or reads so each test
    re-reads its knobs; unit findings must not arm real device traces
    (the e2e test below opts back in explicitly)."""
    import horovod_tpu.profiling as profiling
    from horovod_tpu.elastic import remesh
    from horovod_tpu.metrics import anomaly, timeseries
    monkeypatch.setenv("HVD_TPU_PROFILE_ON_ANOMALY", "0")
    # a stale per-step exposed-comm gauge from another test file would
    # silently siphon in-step time out of `compute` in every window
    g = default_registry().get("hvd_overlap_exposed_comm_seconds")
    if g is not None:
        g.set(0.0)
    for mod in (goodput, anomaly, timeseries, profiling, remesh):
        mod.reset()
    yield
    g = default_registry().get("hvd_overlap_exposed_comm_seconds")
    if g is not None:
        g.set(0.0)
    for mod in (goodput, anomaly, timeseries, profiling, remesh):
        mod.reset()


def _run_steps(led, n, step_s=0.01, gap_s=0.0):
    for _ in range(n):
        led.note_step_begin()
        time.sleep(step_s)
        led.note_step_end(step_s)
        if gap_s:
            time.sleep(gap_s)


# -- the ledger: closed books by construction -------------------------------

def test_books_close_and_every_category_lands():
    led = GoodputLedger(window_steps=4, tolerance=0.05)
    led.note_step_begin()
    time.sleep(0.01)
    led.note_step_end(0.01)
    # out-of-step events between envelopes: a checkpoint stall and a
    # completed re-mesh recovery claim their slice of the gap
    time.sleep(0.012)
    led.note_checkpoint_stall(0.004)
    led.note_remesh(0.003)
    _run_steps(led, 3, step_s=0.01, gap_s=0.004)
    assert led.windows_closed == 1
    rec = led.last_window()
    assert rec["steps"] == 4
    # the closed-books invariant: categories sum to wall time exactly
    # (sequential clamping), the residual is float noise only
    assert sum(rec["seconds"].values()) == pytest.approx(
        rec["wall_s"], abs=1e-6)
    assert rec["closed"], rec
    assert set(rec["seconds"]) == set(CATEGORIES)
    s = rec["seconds"]
    assert s["compute"] == pytest.approx(0.04, rel=0.4)
    assert s["checkpoint_stall"] == pytest.approx(0.004, abs=0.002)
    assert s["remesh_recovery"] == pytest.approx(0.003, abs=0.002)
    assert s["input_wait"] > 0  # the un-attributed slice of the gaps
    assert all(v >= 0 for v in s.values()), s
    snap = led.snapshot()
    assert snap["windows"] == 1 and snap["steps"] == 4
    assert snap["books_violations"] == 0 and snap["closed"]
    assert 0 < snap["fraction"] < 1


def test_overclaimed_events_are_clamped_never_negative():
    """Absurd claimed costs (dt longer than the wall itself, hours of
    checkpoint stall) must clamp — books still close, nothing negative,
    nothing double-counted."""
    led = GoodputLedger(window_steps=1, tolerance=0.05)
    led.note_step_begin()
    time.sleep(0.005)
    led.note_checkpoint_stall(999.0)
    led.note_remesh(999.0)
    led.note_step_end(999.0)  # claimed in-step time >> wall
    rec = led.last_window()
    assert rec is not None
    s = rec["seconds"]
    assert all(v >= 0 for v in s.values()), s
    assert sum(s.values()) == pytest.approx(rec["wall_s"], abs=1e-6)
    # in-step claimed the whole wall, so the out-of-step claims got 0
    assert s["checkpoint_stall"] == 0.0 and s["remesh_recovery"] == 0.0


def test_exposed_comm_and_guard_skip_claims():
    reg = default_registry()
    g = reg.get("hvd_overlap_exposed_comm_seconds") or reg.gauge(
        "hvd_overlap_exposed_comm_seconds",
        help="per-step exposed collective seconds")
    c = reg.get("hvd_guard_skipped_steps_total") or reg.counter(
        "hvd_guard_skipped_steps_total", help="guard-zeroed updates")
    led = GoodputLedger(window_steps=3, tolerance=0.1)
    # step 1: 4ms of the 10ms step was exposed collective time
    g.set(0.004)
    led.note_step_begin()
    time.sleep(0.01)
    led.note_step_end(0.01)
    g.set(0.0)
    # step 2: the guard zeroed this update — the whole step was wasted
    led.note_step_begin()
    time.sleep(0.01)
    c.inc()
    led.note_step_end(0.01)
    # step 3: clean
    led.note_step_begin()
    time.sleep(0.01)
    led.note_step_end(0.01)
    s = led.last_window()["seconds"]
    assert s["exposed_comm"] == pytest.approx(0.004, abs=1e-4)
    assert s["guard_skipped"] == pytest.approx(0.01, abs=1e-4)
    assert s["compute"] == pytest.approx(0.016, abs=0.002)


def test_dominating_is_the_largest_non_compute_category():
    rec = {"seconds": {"compute": 50.0, "exposed_comm": 3.0,
                       "input_wait": 7.0, "idle_other": 1.0}}
    assert GoodputLedger.dominating(rec) == "input_wait"
    assert GoodputLedger.dominating({"seconds": {}}) is None


def test_window_cadence_flush_and_reopen(monkeypatch):
    monkeypatch.setenv("HVD_TPU_GOODPUT_WINDOW", "2")
    goodput.reset()
    for _ in range(5):
        goodput.note_step_begin()
        time.sleep(0.002)
        goodput.note_step_end(0.002)
    led = goodput.ledger(create=False)
    assert led is not None and led.windows_closed == 2
    # the 5th step sits in an open window; flush_open folds it in
    snap = goodput.snapshot()
    assert snap["windows"] == 2 and snap["steps"] == 4
    snap = goodput.snapshot(flush_open=True)
    assert snap["windows"] == 3 and snap["steps"] == 5
    fs = goodput.fleet_summary()
    assert fs is not None and 0 <= fs["fraction"] <= 1
    assert "dominating" in fs and fs["wall_s"] > 0


def test_module_seams_are_inert_until_a_step_lands():
    assert goodput.snapshot() is None
    assert goodput.flush() is None
    assert goodput.fleet_summary() is None
    # out-of-band events before any step must not conjure a ledger
    goodput.note_checkpoint_stall(1.0)
    goodput.note_remesh(1.0)
    assert goodput.ledger(create=False) is None


def test_disabled_knob_keeps_the_plane_dark(monkeypatch):
    monkeypatch.setenv("HVD_TPU_GOODPUT", "0")
    goodput.note_step_begin()
    goodput.note_step_end(0.01)
    assert goodput.ledger(create=False) is None
    assert goodput.snapshot() is None


def test_emit_writes_counters_gauge_and_timeseries(monkeypatch, tmp_path):
    monkeypatch.setenv("HVD_TPU_OBS_DIR", str(tmp_path))
    monkeypatch.setenv("HVD_TPU_GOODPUT_WINDOW", "2")
    from horovod_tpu.metrics import timeseries
    timeseries.reset()
    goodput.reset()
    reg = default_registry()
    c0 = reg.get("hvd_goodput_seconds_total", labels={"category": "compute"})
    before = c0.value if c0 is not None else 0.0
    for _ in range(2):
        goodput.note_step_begin()
        time.sleep(0.004)
        goodput.note_step_end(0.004)
    c = reg.get("hvd_goodput_seconds_total", labels={"category": "compute"})
    assert c is not None and c.value > before
    frac = reg.get("hvd_goodput_fraction")
    assert frac is not None and 0 < frac.value <= 1
    pts = [p for p in timeseries.read_series(str(tmp_path))
           if isinstance(p.get("goodput"), dict)]
    assert pts and pts[-1]["goodput_steps"] == 2
    assert pts[-1]["goodput_closed"] is True
    timeseries.reset()


def test_autopsy_summary_embeds_flushed_ledger(monkeypatch, tmp_path):
    """The autopsy bundle ships the final ledger account with the open
    window flushed (docs/OBSERVABILITY.md "Goodput ledger") — the
    in-process leg of the 2-proc hang-autopsy demo, whose stall_worker
    asserts the same contract."""
    from horovod_tpu.diagnostics import autopsy
    monkeypatch.setenv("HVD_TPU_GOODPUT_WINDOW", "50")
    goodput.reset()
    # 3 steps land; window 50 never closes on its own — the autopsy
    # flush must fold the open window in
    for _ in range(3):
        goodput.note_step_begin()
        time.sleep(0.004)
        goodput.note_step_end(0.004)
    bundle = autopsy.write_autopsy(str(tmp_path / "bundle"),
                                   reason="test", fetch_peers=False)
    summaries = [p for p in os.listdir(bundle)
                 if p.startswith("summary_rank")]
    assert summaries, bundle
    doc = json.load(open(f"{bundle}/{summaries[0]}"))
    gp = doc["goodput"]
    assert gp is not None and gp["windows"] >= 1 and gp["steps"] == 3
    assert gp["closed"] and not gp["books_violations"], gp
    assert abs(sum(gp["seconds"].values()) - gp["wall_s"]) <= \
        gp["tolerance"] * gp["wall_s"] + 0.01, gp
    # no ledger at all -> the summary says None, never a crash
    goodput.reset()
    bundle2 = autopsy.write_autopsy(str(tmp_path / "bundle2"),
                                    reason="test", fetch_peers=False)
    s2 = [p for p in os.listdir(bundle2)
          if p.startswith("summary_rank")]
    assert json.load(open(f"{bundle2}/{s2[0]}"))["goodput"] is None


# -- roofline MFU attribution ------------------------------------------------

def _snapshot_doc(wall=100.0, compute=80.0, exposed=10.0, compile_s=5.0,
                  idle=5.0, steps=50):
    secs = {c: 0.0 for c in CATEGORIES}
    secs.update({"compute": compute, "exposed_comm": exposed,
                 "compile": compile_s, "idle_other": idle})
    return {"wall_s": wall, "seconds": secs, "steps": steps}


def test_attribution_identity_decomposes_one_minus_mfu():
    att = attribution.attribute(_snapshot_doc(), mfu=0.5)
    assert att["mfu"] == 0.5 and att["one_minus_mfu"] == 0.5
    assert sum(att["shares"].values()) == pytest.approx(1.0)
    # the roofline identity: 1 − MFU = non-compute share + the kernel
    # inefficiency hiding INSIDE the compute share
    assert att["kernel_inefficiency"] == pytest.approx(0.8 - 0.5)
    assert att["non_compute_share"] == pytest.approx(0.2)
    assert att["one_minus_mfu"] == pytest.approx(
        att["kernel_inefficiency"] + att["non_compute_share"])
    assert att["dominating"] == "exposed_comm"


def test_attribution_cpu_path_mfu_none():
    """CPU/bench children have no roofline: shares still attribute, the
    MFU-derived fields are None (never fabricated)."""
    att = attribution.attribute(_snapshot_doc())
    assert att["mfu"] is None and att["one_minus_mfu"] is None
    assert att["kernel_inefficiency"] is None
    assert att["shares"]["compute"] == pytest.approx(0.8)


def test_attribution_derives_mfu_from_flops():
    att = attribution.attribute(_snapshot_doc(), flops_per_step=1e9,
                                peak_flops=1e9)
    # 1e9 FLOPs x 50 steps / (100 s x 1e9 FLOP/s) = 0.5
    assert att["mfu"] == pytest.approx(0.5)
    # measured MFU above the attributed compute share clamps to 0
    att2 = attribution.attribute(_snapshot_doc(), mfu=0.95)
    assert att2["kernel_inefficiency"] == 0.0


def test_attribution_absent_ledger_is_none():
    assert attribution.attribute(None) is None
    assert attribution.attribute({"wall_s": 0.0, "seconds": {}}) is None
    assert attribution.from_ledger() is None  # plane never ran
    assert "no ledger data" in attribution.render_lines(None)
    text = attribution.render_lines(
        attribution.attribute(_snapshot_doc(), mfu=0.5))
    assert "mfu=0.500" in text and "kernel_inefficiency" in text


# -- goodput_regression detector --------------------------------------------

def _tuned_engine(monkeypatch, consecutive=2):
    from horovod_tpu.metrics.anomaly import AnomalyEngine
    monkeypatch.setenv("HVD_TPU_ANOMALY_WARMUP", "3")
    monkeypatch.setenv("HVD_TPU_ANOMALY_CONSECUTIVE", str(consecutive))
    monkeypatch.setenv("HVD_TPU_ANOMALY_K", "3")
    monkeypatch.setenv("HVD_TPU_ANOMALY_MIN_RATIO", "1.15")
    return AnomalyEngine(registry=Registry())


def test_goodput_regression_fires_and_names_the_category(monkeypatch):
    eng = _tuned_engine(monkeypatch)
    for _ in range(10):
        assert eng.observe_goodput(0.9, dominating="idle_other") == []
    # a sustained productive-fraction collapse: consecutive=2, so the
    # first bad window is a streak, the second flags
    assert eng.observe_goodput(0.4, dominating="input_wait") == []
    out = eng.observe_goodput(0.4, dominating="input_wait")
    assert len(out) == 1
    f = out[0]
    assert f["kind"] == "goodput_regression"
    assert f["category"] == "input_wait"
    assert f["value"] == pytest.approx(0.4)
    # hysteresis: the episode already flagged — no refire while low
    assert eng.observe_goodput(0.35, dominating="input_wait") == []
    # recovery re-arms: a NEW collapse is a new episode
    for _ in range(3):
        assert eng.observe_goodput(0.9) == []
    assert eng.observe_goodput(0.4, dominating="checkpoint_stall") == []
    out = eng.observe_goodput(0.4, dominating="checkpoint_stall")
    assert len(out) == 1 and out[0]["category"] == "checkpoint_stall"


def test_goodput_detector_ignores_healthy_jitter(monkeypatch):
    import random
    eng = _tuned_engine(monkeypatch)
    rng = random.Random(16)
    for _ in range(200):
        assert eng.observe_goodput(0.88 + rng.uniform(-0.03, 0.03)) == []


def test_default_knobs_catch_a_real_regression_after_compile_ramp():
    """DEFAULT thresholds must catch an 83% sustained goodput drop even
    when the first window was skewed by compile (a real out-of-repo
    drive missed this before EwmaMad's bias-corrected warmup: the slow
    EWMA lagged the compile->steady ramp and the MAD learned that lag
    as noise, inflating k*dev past the whole [0,1] range)."""
    from horovod_tpu.metrics.anomaly import AnomalyEngine
    eng = AnomalyEngine(registry=Registry())  # default env knobs
    windows = ([0.62] + [0.99] * 10          # compile ramp + steady
               + [0.15, 0.15, 0.15]          # sustained regression
               + [0.99, 0.99])               # recovery
    finds = []
    for v in windows:
        finds += eng.observe_goodput(v, dominating="input_wait")
    assert len(finds) == 1, finds
    assert finds[0]["kind"] == "goodput_regression"
    assert finds[0]["category"] == "input_wait"


# -- CLI views ---------------------------------------------------------------

def test_render_top_goodput_line():
    from horovod_tpu.metrics.__main__ import render_top
    series = {
        'hvd_goodput_seconds_total{category="compute"}': 80.0,
        'hvd_goodput_seconds_total{category="input_wait"}': 15.0,
        'hvd_goodput_seconds_total{category="compile"}': 5.0,
        "hvd_fleet_goodput_min": 0.6,
        "hvd_fleet_goodput_worst_rank": 2.0,
    }
    out = render_top(series, "test")
    line = next(ln for ln in out.splitlines() if ln.startswith("GOODPUT"))
    assert "80.0% productive" in line
    # loss categories sorted largest first
    assert line.index("input_wait") < line.index("compile")
    assert "worst rank 2 @ 60.0%" in line
    # no goodput series -> no GOODPUT line (don't render zeros)
    assert "GOODPUT" not in render_top({"hvd_steps_total": 3.0}, "test")


def _history_args(tmp_path, **kw):
    defaults = dict(dir=str(tmp_path), rank=None, last=0, json=False,
                    goodput=True, serving=False, remesh=False,
                    actions=False)
    defaults.update(kw)
    return argparse.Namespace(**defaults)


def test_history_goodput_table_and_json(monkeypatch, tmp_path, capsys):
    from horovod_tpu.metrics import timeseries
    from horovod_tpu.metrics.__main__ import cmd_history
    monkeypatch.setenv("HVD_TPU_OBS_DIR", str(tmp_path))
    timeseries.reset()
    for frac, closed in ((0.91, True), (0.42, False)):
        timeseries.record_point({
            "goodput": {"compute": frac, "input_wait": 1 - frac},
            "goodput_wall_s": 1.0, "goodput_fraction": frac,
            "goodput_steps": 5, "goodput_closed": closed})
        timeseries.record_point({"step": 1, "step_time_s": 0.01})
    timeseries.reset()  # flush the writer
    assert cmd_history(_history_args(tmp_path)) == 0
    out = capsys.readouterr().out
    assert "2 goodput window(s)" in out
    assert "91.0%" in out and "42.0%" in out
    assert "ok" in out and "OPEN!" in out  # the unclosed window shouts
    assert cmd_history(_history_args(tmp_path, json=True)) == 0
    lines = [json.loads(ln) for ln in
             capsys.readouterr().out.strip().splitlines()]
    assert len(lines) == 2 and all("goodput" in p for p in lines)
    # the step view must NOT show goodput points
    assert cmd_history(_history_args(tmp_path, goodput=False)) == 0
    assert "goodput" not in capsys.readouterr().out
    # empty store: loud failure, nonzero rc
    empty = tmp_path / "empty"
    empty.mkdir()
    assert cmd_history(_history_args(empty)) == 1
    assert "no goodput windows" in capsys.readouterr().err


# -- fleet merge -------------------------------------------------------------

def test_fleet_merges_per_rank_goodput_and_names_worst(monkeypatch):
    from horovod_tpu.metrics.fleet import FleetAggregator
    regs = {r: Registry() for r in range(3)}
    aggs = {r: FleetAggregator(rank=r, size=3, base_port=9090,
                               registry=regs[r], push_interval=60.0)
            for r in range(3)}
    # the ledger is process-global; impersonate each rank's summary
    # around its push so the merged view carries real diversity
    fracs = {0: 0.9, 1: 0.55, 2: 0.8}
    root = aggs[0]
    for r in (1, 2):
        monkeypatch.setattr(
            goodput, "fleet_summary",
            lambda r=r: {"fraction": fracs[r], "dominating": "input_wait",
                         "wall_s": 10.0})
        assert root.ingest(aggs[r].subtree_doc())
    monkeypatch.setattr(
        goodput, "fleet_summary",
        lambda: {"fraction": fracs[0], "dominating": "idle_other",
                 "wall_s": 10.0})
    snap = root.fleet_snapshot()["snapshot"]
    for r, f in fracs.items():
        key = f'hvd_fleet_rank_goodput_fraction{{rank="{r}"}}'
        assert snap[key]["value"] == pytest.approx(f), sorted(snap)
    assert snap["hvd_fleet_goodput_min"]["value"] == pytest.approx(0.55)
    assert snap["hvd_fleet_goodput_worst_rank"]["value"] == 1
    # view-only: synthesized gauges must not leak into the local
    # registry (they would ride the next upstream push)
    assert "hvd_fleet_goodput_min" not in regs[0].snapshot()


def test_fleet_merge_survives_ranks_without_a_ledger(monkeypatch):
    from horovod_tpu.metrics.fleet import FleetAggregator
    regs = {r: Registry() for r in range(2)}
    aggs = {r: FleetAggregator(rank=r, size=2, base_port=9090,
                               registry=regs[r], push_interval=60.0)
            for r in range(2)}
    monkeypatch.setattr(goodput, "fleet_summary", lambda: None)
    assert aggs[0].ingest(aggs[1].subtree_doc())
    snap = aggs[0].fleet_snapshot()["snapshot"]
    assert "hvd_fleet_goodput_min" not in snap


# -- end-to-end acceptance (8-device CPU mesh) -------------------------------

def _e2e_env(monkeypatch, tmp_path, profile_on):
    monkeypatch.setenv("HVD_TPU_GOODPUT_WINDOW", "5")
    monkeypatch.setenv("HVD_TPU_GOODPUT_TOLERANCE", "0.05")
    monkeypatch.setenv("HVD_TPU_ANOMALY_ALPHA", "0.5")
    monkeypatch.setenv("HVD_TPU_ANOMALY_WARMUP", "2")
    monkeypatch.setenv("HVD_TPU_ANOMALY_CONSECUTIVE", "1")
    monkeypatch.setenv("HVD_TPU_ANOMALY_K", "3")
    monkeypatch.setenv("HVD_TPU_ANOMALY_MIN_RATIO", "1.15")
    monkeypatch.setenv("HVD_TPU_PROFILE_ON_ANOMALY",
                       "1" if profile_on else "0")
    monkeypatch.setenv("HVD_TPU_PROFILE_COOLDOWN_S", "0")
    monkeypatch.setenv("HVD_TPU_PROFILE_STEPS", "2")
    monkeypatch.setenv("HVD_TPU_PROFILE_DIR", str(tmp_path / "profiles"))


def _e2e_loop(ckpt, stall_steps=()):
    """The acceptance loop: 6 ledger windows of 5 steps driven through
    the real StepTimer seam — window 1 pays a REAL jit compile, window
    4 a waited checkpoint save, window 5 a completed re-mesh episode,
    and ``stall_steps`` get an inter-step chaos stall (the input
    pipeline going away BETWEEN envelopes, not inside one — in-step
    time is the step's own claim).  The clean run differs only in the
    stall."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from horovod_tpu import chaos
    from horovod_tpu.elastic import remesh
    from horovod_tpu.profiling import compile_watch
    from horovod_tpu.train.callbacks import StepTimer

    compile_watch.ensure_installed()
    timer = StepTimer(registry=Registry())
    fn = jax.jit(lambda x: jnp.tanh(x) * 2.0 + x)
    x = np.arange(17.0, dtype=np.float32)  # odd shape: forces a compile
    # 33 steps: 6 full 5-step windows + 3 trailing healthy steps so a
    # capture armed at the LAST window close still gets steps to trace
    for i in range(33):
        if i in stall_steps:
            # the chaos `step` seam fired OUTSIDE the envelope: the
            # stall is wall time no step claimed -> input_wait
            chaos.step_tick(i)
        if i == 17:
            ckpt.save(1, {"w": np.zeros(64, np.float32)}, wait=True)
        if i == 22:
            remesh.begin("test", old_size=8, generation=0)
            with remesh.phase("rebuild"):
                time.sleep(0.012)
            remesh.mark_recovered(new_size=8, generation=0)
        timer.start_step()
        if i == 0:
            fn(x).block_until_ready()  # the first step pays the compile
        time.sleep(0.02)
        timer.end_step(32)
    return timer


def test_goodput_e2e_regression_flagged_and_profiled(
        monkeypatch, tmp_path):
    import horovod_tpu.profiling as profiling
    from horovod_tpu import chaos
    from horovod_tpu.checkpoint.store import ShardedCheckpointer
    from horovod_tpu.metrics import anomaly

    _e2e_env(monkeypatch, tmp_path, profile_on=True)
    plan = {"faults": [{"seam": "step", "kind": "stall",
                        "start": 25, "stop": 28, "stall_s": 0.08}]}
    monkeypatch.setenv("HVD_TPU_FAULT_PLAN", json.dumps(plan))
    anomaly.reset()
    profiling.reset()
    goodput.reset()
    chaos.install(rank=0)
    try:
        _e2e_loop(ShardedCheckpointer(str(tmp_path / "ckpt"), rank=0,
                                      world_size=1),
                  stall_steps=(25, 26, 27))
    finally:
        chaos.uninstall()

    # books close over the WHOLE run, compile/checkpoint/re-mesh each
    # landed in its category
    snap = goodput.snapshot(flush_open=True)
    assert snap is not None and snap["windows"] >= 6, snap
    assert snap["closed"] and snap["books_violations"] == 0, snap
    assert abs(snap["residual_s"]) <= \
        snap["tolerance"] * snap["wall_s"] + 1e-3, snap
    s = snap["seconds"]
    assert s["compute"] > 0.3, s
    assert s["compile"] > 0, s
    assert s["checkpoint_stall"] > 0, s
    assert s["remesh_recovery"] > 0.01, s
    assert s["input_wait"] > 0.15, s  # the three 80 ms stalls

    # the stall window was flagged as a goodput regression naming the
    # category that ate the time, and armed an autonomous capture
    findings = [f for f in anomaly.recent_findings()
                if f["kind"] == "goodput_regression"]
    assert findings, anomaly.recent_findings()
    f = findings[-1]
    assert f["category"] == "input_wait", f
    assert "profile" in f, f  # the planned trace path, stamped early
    caps = profiling.recent_captures()
    assert caps, "the armed capture never ran"
    trig = caps[-1]["trigger"]
    assert trig["kind"] == "goodput_regression"
    assert trig["category"] == "input_wait"

    # the MFU decomposition over the same account (CPU: mfu is None,
    # the shares still name the dominating loss)
    att = attribution.from_ledger()
    assert att is not None and att["mfu"] is None
    assert att["shares"]["compute"] == pytest.approx(
        snap["fractions"]["compute"], abs=0.01)


def test_goodput_e2e_clean_run_reports_nothing(monkeypatch, tmp_path):
    import horovod_tpu.profiling as profiling
    from horovod_tpu.checkpoint.store import ShardedCheckpointer
    from horovod_tpu.metrics import anomaly

    _e2e_env(monkeypatch, tmp_path, profile_on=False)
    anomaly.reset()
    profiling.reset()
    goodput.reset()
    _e2e_loop(ShardedCheckpointer(str(tmp_path / "ckpt"), rank=0,
                                  world_size=1),
              stall_steps=())
    snap = goodput.snapshot(flush_open=True)
    assert snap is not None and snap["closed"], snap
    assert snap["books_violations"] == 0
    assert not [f for f in anomaly.recent_findings()
                if f["kind"] == "goodput_regression"], \
        anomaly.recent_findings()
