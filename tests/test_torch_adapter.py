"""Torch adapter tests (reference analog: test/parallel/test_torch.py, run
single-process here; the multi-process path shares the core backend already
covered by test_core_multiprocess)."""

import os

import numpy as np
import pytest

torch = pytest.importorskip("torch")


@pytest.fixture
def thvd(hvd):
    import horovod_tpu.torch as thvd
    return thvd


def test_torch_allreduce(thvd):
    x = torch.arange(6, dtype=torch.float32)
    out = thvd.allreduce(x, op=thvd.Sum)
    assert torch.allclose(out, x)
    # in-place
    y = x.clone()
    thvd.allreduce_(y, op=thvd.Average)
    assert torch.allclose(y, x)


def test_torch_grouped_and_gather(thvd):
    outs = thvd.grouped_allreduce([torch.ones(3), torch.zeros(2)],
                                  op=thvd.Sum)
    assert torch.allclose(outs[0], torch.ones(3))
    g = thvd.allgather(torch.eye(2))
    assert g.shape == (2, 2)


def test_torch_broadcast_parameters(thvd):
    model = torch.nn.Linear(4, 2)
    thvd.broadcast_parameters(model.state_dict(), root_rank=0)
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    thvd.broadcast_optimizer_state(opt, root_rank=0)


def test_torch_distributed_optimizer_trains(thvd):
    torch.manual_seed(0)
    model = torch.nn.Linear(8, 1)
    opt = thvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.05),
        named_parameters=model.named_parameters())
    x = torch.randn(64, 8)
    w = torch.randn(8, 1)
    y = x @ w
    losses = []
    for i in range(50):
        opt.zero_grad()
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()
        opt.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.1


def test_torch_backward_passes_per_step(thvd):
    """Reference contract (torch/optimizer.py _allreduce_delay): the user
    runs k backwards (grads accumulate locally), then ONE step() ends the
    accumulation cycle — sync + always apply. The old behavior (count
    step() calls, return None until the k-th) silently no-opped for users
    following the reference pattern (ADVICE r1)."""
    torch.manual_seed(0)
    model = torch.nn.Linear(2, 1)
    ref = torch.nn.Linear(2, 1)
    ref.load_state_dict(model.state_dict())
    opt = thvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        backward_passes_per_step=2)
    ref_opt = torch.optim.SGD(ref.parameters(), lr=0.1)
    x1, x2 = torch.ones(1, 2), torch.full((1, 2), 2.0)
    # k = 2 backwards, then one step — must apply an update
    model(x1).sum().backward()
    model(x2).sum().backward()
    out = opt.step()
    # accumulated grads are scaled by 1/k at EVERY world size (consistent
    # 1-process vs N-process dynamics; the reference's TF aggregation
    # helper divides the same way)
    ref(x1).sum().backward()
    ref(x2).sum().backward()
    for p in ref.parameters():
        p.grad.div_(2)
    ref_opt.step()
    assert torch.allclose(model.weight, ref.weight)
    assert not torch.allclose(model.weight, torch.zeros_like(model.weight))


def test_torch_join_barrier(thvd):
    assert thvd.join() == 0
    thvd.barrier()


def test_torch_sparse_allreduce(thvd):
    """Allgather-based sparse allreduce (reference: torch/mpi_ops.py:515):
    duplicate coordinates sum on coalesce; Average divides by size."""
    i = torch.tensor([[0, 2, 2], [1, 0, 0]])
    v = torch.tensor([3.0, 4.0, 5.0])
    sp = torch.sparse_coo_tensor(i, v, (4, 3))
    handle = thvd.sparse_allreduce_async(sp, name="sp", op=thvd.Sum)
    out = thvd.synchronize(handle).to_dense()
    expect = sp.coalesce().to_dense()  # size 1: reduction == input
    assert torch.allclose(out, expect)
    # Average at size 1 is also identity
    h2 = thvd.sparse_allreduce_async(sp, name="sp2", op=thvd.Average)
    assert torch.allclose(thvd.synchronize(h2).to_dense(), expect)


def test_elastic_sampler_partition_and_resume(thvd):
    """ElasticSampler (reference: torch/elastic/sampler.py): partitions the
    dataset, excludes processed indices after reset, round-trips state."""
    from horovod_tpu.torch.elastic import ElasticSampler
    data = list(range(10))
    s = ElasticSampler(data, shuffle=False)
    idx = list(iter(s))
    assert idx == data  # size 1: everything on this rank
    assert len(s) == 10
    # record the first two batches of 3, then simulate an elastic reset
    s.record_batch(0, 3)
    s.record_batch(1, 3)
    st = s.state_dict()
    s2 = ElasticSampler(data, shuffle=False)
    s2.load_state_dict(st)
    remaining = list(iter(s2))
    assert sorted(remaining) == list(range(6, 10))
    # end of epoch clears progress
    s2.set_epoch(1)
    assert len(list(iter(s2))) == 10


def test_elastic_sampler_tail_smaller_than_world(thvd, monkeypatch):
    """Late-epoch elastic resume: fewer remaining indices than ranks must
    pad by repetition, not crash (the reference sampler's single self-copy
    pad asserts here)."""
    from horovod_tpu.torch import elastic as el
    monkeypatch.setattr(el, "size", lambda: 4)
    monkeypatch.setattr(el, "rank", lambda: 0)
    s = el.ElasticSampler(list(range(10)), shuffle=False)
    s.record_indices(range(9))  # one index left, 4 ranks
    s.reset()
    out = list(iter(s))
    assert out == [9] and len(s) == 1


def test_torch_synchronize_then_step_applies_once(thvd):
    """Manual synchronize() for gradient clipping followed by step() must
    not sync (and 1/k-scale) twice (reference guards with _synchronized +
    skip_synchronize)."""
    model = torch.nn.Linear(2, 1)
    opt = thvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        backward_passes_per_step=2)
    ref = torch.nn.Linear(2, 1)
    ref.load_state_dict(model.state_dict())
    ref_opt = torch.optim.SGD(ref.parameters(), lr=0.1)
    x = torch.ones(1, 2)
    model(x).sum().backward()
    model(x).sum().backward()
    opt.synchronize()          # user syncs manually (e.g. to clip)
    grad_after_sync = model.weight.grad.clone()
    opt.step()                 # must NOT divide by k again
    assert torch.allclose(model.weight.grad, grad_after_sync)
    ref(x).sum().backward()
    ref(x).sum().backward()
    for p in ref.parameters():
        p.grad.div_(2)
    ref_opt.step()
    assert torch.allclose(model.weight, ref.weight)
    # skip_synchronize parity surface exists
    with opt.skip_synchronize():
        pass


def test_torch_state_commit_restore(thvd, tmp_path, monkeypatch):
    """TorchState snapshots/restores model+optimizer+sampler together
    (reference: torch/elastic/state.py)."""
    monkeypatch.setenv("HVD_ELASTIC_CKPT", str(tmp_path))
    from horovod_tpu.torch.elastic import ElasticSampler, TorchState
    model = torch.nn.Linear(3, 1)
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    sampler = ElasticSampler(list(range(8)), shuffle=False)
    state = TorchState(model=model, optimizer=opt, sampler=sampler, epoch=0)
    state.save()
    before = {k: v.clone() for k, v in model.state_dict().items()}
    # mutate everything
    with torch.no_grad():
        model.weight.add_(1.0)
    sampler.record_batch(0, 4)
    state.epoch = 3
    state.restore()
    for k, v in model.state_dict().items():
        assert torch.allclose(v, before[k])
    assert state.epoch == 0
    assert list(iter(sampler)) == list(range(8))  # progress rolled back
    state.sync()  # size 1: broadcast is a no-op but must not fail


def test_torch_state_generation_restart_resume(thvd, tmp_path, monkeypatch):
    """Under the elastic driver (HVD_ELASTIC_CKPT set), a NEW process's
    TorchState resumes model + optimizer + scalars from the last commit —
    the snapshots persist WITH the scalars, not memory-only (r2 review)."""
    monkeypatch.setenv("HVD_ELASTIC_CKPT", str(tmp_path))
    from horovod_tpu.torch.elastic import TorchState
    torch.manual_seed(0)
    model = torch.nn.Linear(3, 1)
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    state = TorchState(model=model, optimizer=opt, epoch=0,
                       name="gen_restart")
    with torch.no_grad():
        model.weight.fill_(7.0)
    state.epoch = 5
    state.save()
    # simulate the restarted generation: fresh objects, same ckpt dir
    torch.manual_seed(1)
    model2 = torch.nn.Linear(3, 1)
    opt2 = torch.optim.SGD(model2.parameters(), lr=0.1)
    state2 = TorchState(model=model2, optimizer=opt2, epoch=0,
                        name="gen_restart")
    assert state2.epoch == 5
    assert torch.allclose(model2.weight, torch.full_like(model2.weight, 7.0))


def test_object_state_no_persistence_without_driver(thvd, monkeypatch):
    """Without HVD_ELASTIC_CKPT (no elastic driver) ObjectState is
    host-memory only — no shared-tempdir pickles for unrelated later jobs
    to adopt (r2 review)."""
    monkeypatch.delenv("HVD_ELASTIC_CKPT", raising=False)
    import glob
    import tempfile
    from horovod_tpu.elastic import ObjectState
    st = ObjectState(name="no_persist_check", epoch=1)
    st.save()
    leaked = glob.glob(os.path.join(tempfile.gettempdir(),
                                    "hvd_state_no_persist_check*"))
    assert leaked == []
    st2 = ObjectState(name="no_persist_check", epoch=0)
    assert st2.epoch == 0  # nothing adopted


def test_torch_sync_batch_norm_single_process(thvd):
    """Size-1 SyncBatchNorm == plain BatchNorm (training + eval), and the
    module round-trips through train->eval with running stats
    (reference: torch/sync_batch_norm.py SyncBatchNorm._run_bn path)."""
    import torch
    torch.manual_seed(0)
    sbn = thvd.SyncBatchNorm(3, momentum=0.1)
    bn = torch.nn.BatchNorm2d(3, momentum=0.1)
    bn.load_state_dict({k: v.clone() for k, v in sbn.state_dict().items()})
    x = torch.randn(4, 3, 5, 5)
    out_s = sbn(x)
    out_b = bn(x)
    assert torch.allclose(out_s, out_b, atol=1e-6)
    assert torch.allclose(sbn.running_mean, bn.running_mean, atol=1e-6)
    assert torch.allclose(sbn.running_var, bn.running_var, atol=1e-6)
    sbn.eval(), bn.eval()
    y = torch.randn(2, 3, 5, 5)
    assert torch.allclose(sbn(y), bn(y), atol=1e-6)

    # momentum=None = cumulative moving average, same as _BatchNorm
    sbn2 = thvd.SyncBatchNorm(3, momentum=None)
    bn2 = torch.nn.BatchNorm2d(3, momentum=None)
    bn2.load_state_dict({k: v.clone() for k, v in sbn2.state_dict().items()})
    for _ in range(3):
        z = torch.randn(4, 3, 5, 5)
        sbn2(z), bn2(z)
    assert torch.allclose(sbn2.running_mean, bn2.running_mean, atol=1e-6)
    assert torch.allclose(sbn2.running_var, bn2.running_var, atol=1e-6)
