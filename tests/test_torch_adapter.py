"""Torch adapter tests (reference analog: test/parallel/test_torch.py, run
single-process here; the multi-process path shares the core backend already
covered by test_core_multiprocess)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")


@pytest.fixture
def thvd(hvd):
    import horovod_tpu.torch as thvd
    return thvd


def test_torch_allreduce(thvd):
    x = torch.arange(6, dtype=torch.float32)
    out = thvd.allreduce(x, op=thvd.Sum)
    assert torch.allclose(out, x)
    # in-place
    y = x.clone()
    thvd.allreduce_(y, op=thvd.Average)
    assert torch.allclose(y, x)


def test_torch_grouped_and_gather(thvd):
    outs = thvd.grouped_allreduce([torch.ones(3), torch.zeros(2)],
                                  op=thvd.Sum)
    assert torch.allclose(outs[0], torch.ones(3))
    g = thvd.allgather(torch.eye(2))
    assert g.shape == (2, 2)


def test_torch_broadcast_parameters(thvd):
    model = torch.nn.Linear(4, 2)
    thvd.broadcast_parameters(model.state_dict(), root_rank=0)
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    thvd.broadcast_optimizer_state(opt, root_rank=0)


def test_torch_distributed_optimizer_trains(thvd):
    torch.manual_seed(0)
    model = torch.nn.Linear(8, 1)
    opt = thvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.05),
        named_parameters=model.named_parameters())
    x = torch.randn(64, 8)
    w = torch.randn(8, 1)
    y = x @ w
    losses = []
    for i in range(50):
        opt.zero_grad()
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()
        opt.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.1


def test_torch_backward_passes_per_step(thvd):
    model = torch.nn.Linear(2, 1)
    opt = thvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        backward_passes_per_step=2)
    before = model.weight.detach().clone()
    loss = model(torch.ones(1, 2)).sum()
    loss.backward()
    assert opt.step() is None           # accumulating, no update
    assert torch.allclose(model.weight, before)
    loss = model(torch.ones(1, 2)).sum()
    loss.backward()
    opt.step()                          # second pass applies
    assert not torch.allclose(model.weight, before)


def test_torch_join_barrier(thvd):
    assert thvd.join() == 0
    thvd.barrier()
