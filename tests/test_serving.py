"""Zero-drop serving plane (ISSUE 14, docs/SERVING.md).

Fast battery: dynamic batcher (batch formation, explicit sheds,
deadlines, drain), hardened HTTP server (bounded handler pool +
per-request timeouts), /readyz-vs-/healthz split, in-process replica
(roundtrip, idempotency, chaos seam, hot weight swap, drain), router
(retry to a survivor, hedging a slow replica, admission shed,
exactly-once accounting), the SLO window -> slo_breach ->
autopilot-scale_out chain, `metrics top`/`history --serving`
rendering, and the `check_bench --serving` gate.

Slow (serving/chaos CI tiers; tier-1 budget rule — all multiprocess
tests are slow-marked): the chaos acceptance pair — (a) SIGKILL one
replica of a 2-replica fleet under sustained closed-loop load: every
accepted request answered exactly once, fleet heals; (b) a chaos
preemption notice drains a replica (DRAINED exit, no failure
evidence) while a fresh durable commit hot-swaps — zero failed
requests, new version served.
"""

import json
import os
import signal
import socket
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _chaos_clean():
    from horovod_tpu import chaos
    chaos.uninstall()
    yield
    chaos.uninstall()


# -- batcher ------------------------------------------------------------------
def test_batcher_forms_full_batch():
    from horovod_tpu.serving.batcher import DynamicBatcher
    b = DynamicBatcher(max_batch_size=4, max_wait_s=5.0, max_queue=16)
    reqs = [b.submit(f"r{i}", i) for i in range(4)]
    batch = b.next_batch(timeout_s=1.0)
    assert [r.id for r in batch] == ["r0", "r1", "r2", "r3"]
    for r in batch:
        r.set_result(r.payload * 10)
    b.batch_done()
    assert reqs[2].wait(timeout=1.0) == 20


def test_batcher_max_wait_bounds_latency():
    """A lone request must not wait for a full batch: the window is
    max_wait_s from the OLDEST member's enqueue."""
    from horovod_tpu.serving.batcher import DynamicBatcher
    b = DynamicBatcher(max_batch_size=64, max_wait_s=0.05, max_queue=16)
    t0 = time.monotonic()
    b.submit("solo", 1)
    batch = b.next_batch(timeout_s=1.0)
    took = time.monotonic() - t0
    assert len(batch) == 1 and took < 0.5


def test_batcher_sheds_explicitly_on_full_queue():
    from horovod_tpu.serving.batcher import DynamicBatcher, SheddedError
    b = DynamicBatcher(max_batch_size=4, max_queue=2)
    b.submit("a", 1)
    b.submit("b", 2)
    with pytest.raises(SheddedError):
        b.submit("c", 3)


def test_batcher_expired_deadline_fails_at_formation():
    from horovod_tpu.serving.batcher import DeadlineError, DynamicBatcher
    b = DynamicBatcher(max_batch_size=4, max_wait_s=0.01, max_queue=16)
    doomed = b.submit("late", 1, deadline_s=0.01)
    live = b.submit("fine", 2, deadline_s=30.0)
    time.sleep(0.05)
    batch = b.next_batch(timeout_s=1.0)
    assert [r.id for r in batch] == ["fine"]
    with pytest.raises(DeadlineError):
        doomed.wait(timeout=0.1)
    live.set_result(None)
    b.batch_done()


def test_batcher_drain_refuses_new_and_flushes_admitted():
    from horovod_tpu.serving.batcher import DrainingError, DynamicBatcher
    b = DynamicBatcher(max_batch_size=4, max_wait_s=0.01, max_queue=16)
    r1 = b.submit("pre", 1)
    b.drain()
    with pytest.raises(DrainingError):
        b.submit("post", 2)
    assert not b.drained()  # "pre" is still owed an answer
    batch = b.next_batch(timeout_s=1.0)
    assert [r.id for r in batch] == ["pre"]
    r1.set_result(None)
    b.batch_done()
    assert b.drained()
    assert b.wait_drained(timeout_s=1.0)


# -- hardened HTTP server -----------------------------------------------------
def test_http_bounded_pool_rejects_busy_and_times_out_wedged():
    """Satellite: HVD_TPU_HTTP_MAX_HANDLERS handler slots; wedged
    clients get per-request timeouts, the overflow connection gets an
    immediate 503 — and after the timeout frees the slots, the server
    answers again (one slow client can no longer pin a thread
    forever)."""
    from horovod_tpu.runner.http_kv import ThreadedHTTPServer, _KVHandler
    srv = ThreadedHTTPServer(("127.0.0.1", 0), _KVHandler,
                             max_handlers=2, handler_timeout_s=1.0)
    srv.kv, srv.kv_lock = {}, threading.Lock()
    srv.note_request = lambda *a: None
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    port = srv.server_address[1]
    try:
        wedged = []
        for _ in range(2):  # hold both slots with half-sent requests
            c = socket.create_connection(("127.0.0.1", port))
            c.sendall(b"GET /a/b HTTP/1.1\r\n")
            wedged.append(c)
        time.sleep(0.2)
        c3 = socket.create_connection(("127.0.0.1", port))
        c3.sendall(b"GET /a/b HTTP/1.0\r\n\r\n")
        assert b"503" in c3.recv(1000)
        c3.close()
        time.sleep(1.3)  # wedged clients hit the 1s request timeout
        c4 = socket.create_connection(("127.0.0.1", port))
        c4.sendall(b"GET /a/b HTTP/1.0\r\n\r\n")
        resp = c4.recv(1000)
        assert b"404" in resp  # served again (empty KV -> 404)
        c4.close()
        for c in wedged:
            c.close()
    finally:
        srv.shutdown()
        srv.server_close()


def test_kv_retry_shield_retries_busy_503_not_404():
    """Review regression: the hardened pool's inline 503 busy-reject
    must be RETRYABLE for the repo's own KV clients (it means 'again
    in a moment'), while semantic HTTP statuses (404) stay terminal."""
    from urllib.error import HTTPError
    from horovod_tpu.runner.http_kv import _with_retries
    calls = {"n": 0}

    def busy_twice():
        calls["n"] += 1
        if calls["n"] < 3:
            raise HTTPError("http://x/", 503, "busy", {}, None)
        return b"ok"

    assert _with_retries(busy_twice, attempts=4) == b"ok"
    assert calls["n"] == 3

    def not_found():
        calls["n"] += 1
        raise HTTPError("http://x/", 404, "nope", {}, None)

    calls["n"] = 0
    with pytest.raises(HTTPError):
        _with_retries(not_found, attempts=4)
    assert calls["n"] == 1  # terminal on the first answer


def test_exporter_readyz_split_from_healthz():
    """Satellite: /healthz liveness vs /readyz readiness; a ready_fn
    flip is visible to orchestrators without touching /healthz."""
    import urllib.error
    import urllib.request
    from horovod_tpu.metrics.exporter import MetricsExporter
    state = {"ready": True}
    exp = MetricsExporter(
        port=0, health_fn=lambda: {"status": "ok"},
        ready_fn=lambda: {"ready": state["ready"], "why": "test"})
    exp.start()
    try:
        base = f"http://127.0.0.1:{exp.port}"
        with urllib.request.urlopen(base + "/readyz", timeout=5) as r:
            assert json.loads(r.read())["ready"] is True
        state["ready"] = False
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/readyz", timeout=5)
        assert ei.value.code == 503
        # liveness unaffected by readiness
        with urllib.request.urlopen(base + "/healthz", timeout=5) as r:
            assert json.loads(r.read())["status"] == "ok"
        # default derivation: no ready_fn -> ready iff healthy
        exp.set_ready_fn(None)
        with urllib.request.urlopen(base + "/readyz", timeout=5) as r:
            assert json.loads(r.read())["ready"] is True
    finally:
        exp.stop()


# -- replica ------------------------------------------------------------------
def _post(port, doc, path="/infer", timeout=10.0):
    import urllib.error
    import urllib.request
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(doc).encode(),
        method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture
def replica():
    from horovod_tpu.serving import ReplicaServer
    r = ReplicaServer(dim=4, replica_id="t0").start()
    yield r
    r.stop()


def test_replica_infer_roundtrip(replica):
    code, resp = _post(replica.port, {"id": "q1", "x": [4.0, 0, 0, 0]})
    assert code == 200 and resp["version"] == 0
    # demo model: w = 1/dim everywhere, b = 0 -> y_j = mean(x)
    assert np.allclose(resp["y"], [1.0] * 4)
    # a wrong-width payload is rejected at admission (400), never
    # co-batched where it would fail the whole batch
    code, resp = _post(replica.port, {"id": "q2", "x": [1.0, 2.0]})
    assert code == 400 and "shape" in resp["error"]


def test_replica_idempotent_duplicate_returns_same_answer(replica):
    """A hedged/retried duplicate (same id, even different payload)
    must return the SAME response, not recompute."""
    _, a = _post(replica.port, {"id": "dup", "x": [1.0, 0, 0, 0]})
    _, b = _post(replica.port, {"id": "dup", "x": [9.0, 9, 9, 9]})
    assert a["y"] == b["y"]
    from horovod_tpu.metrics.registry import default_registry
    c = default_registry().get("hvd_serving_duplicate_hits_total")
    assert c is not None


def test_replica_readiness_gates_on_queue_and_drain(monkeypatch):
    from horovod_tpu.serving import ReplicaServer
    # queue budget -1: any depth (incl. 0) is over budget -> not ready
    monkeypatch.setenv("HVD_TPU_SERVING_READY_QUEUE", "-1")
    r = ReplicaServer(dim=4, replica_id="t1").start()
    try:
        assert r.ready_doc()["ready"] is False
    finally:
        r.stop()
    monkeypatch.delenv("HVD_TPU_SERVING_READY_QUEUE")
    r2 = ReplicaServer(dim=4, replica_id="t2").start()
    try:
        assert r2.ready_doc()["ready"] is True
        r2.drain(source="test")
        assert r2.ready_doc()["ready"] is False
        assert r2.ready_doc()["draining"] is True
        assert r2.wait_drained(5.0)
        # draining replica refuses new work with an explicit 503
        code, resp = _post(r2.port, {"id": "late", "x": [1, 1, 1, 1]})
        assert code == 503 and "draining" in resp["error"]
    finally:
        r2.stop()


def test_replica_chaos_serving_request_seam(monkeypatch, replica):
    """The serving.request seam: shed -> explicit 429, error -> 500
    (what the router retries around), both counted as injections."""
    from horovod_tpu import chaos
    plan = json.dumps({"faults": [
        {"seam": "serving.request", "kind": "shed", "start": 0,
         "stop": 1},
        {"seam": "serving.request", "kind": "error", "start": 1,
         "stop": 2}]})
    monkeypatch.setenv("HVD_TPU_FAULT_PLAN", plan)
    chaos.install(rank=0)
    try:
        code, resp = _post(replica.port, {"id": "s1", "x": [1, 0, 0, 0]})
        assert code == 429 and "chaos" in resp["error"]
        code, _resp = _post(replica.port, {"id": "s2", "x": [1, 0, 0, 0]})
        assert code == 500
        code, _resp = _post(replica.port, {"id": "s3", "x": [1, 0, 0, 0]})
        assert code == 200
    finally:
        chaos.uninstall()


def test_replica_hot_swap_from_durable_store(tmp_path):
    """Tentpole: restore_latest reshards a fresh commit onto the
    serving mesh while the old weights keep serving; the flip is
    atomic between batches and responses name the version that
    computed them."""
    from horovod_tpu.checkpoint import ShardedCheckpointer
    from horovod_tpu.serving import ReplicaServer
    from horovod_tpu.serving.replica import demo_params
    store = ShardedCheckpointer(str(tmp_path), rank=0, world_size=1)
    store.save(1, {"params": demo_params(4, scale=1.0)}, wait=True)
    r = ReplicaServer(dim=4, store_dir=str(tmp_path),
                      replica_id="swap", swap_poll_s=0.05).start()
    try:
        code, resp = _post(r.port, {"id": "v1", "x": [4.0, 0, 0, 0]})
        assert code == 200 and resp["version"] == 1
        assert abs(resp["y"][0] - 1.0) < 1e-5
        store.save(2, {"params": demo_params(4, scale=3.0)}, wait=True)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            code, resp = _post(
                r.port, {"id": f"v2-{time.monotonic_ns()}",
                         "x": [4.0, 0, 0, 0]})
            assert code == 200  # zero failed requests THROUGH the swap
            if resp["version"] == 2:
                break
            time.sleep(0.05)
        assert resp["version"] == 2
        assert abs(resp["y"][0] - 3.0) < 1e-5
    finally:
        r.stop()
        store.close()


def _corrupt(path):
    b = bytearray(open(path, "rb").read())
    b[len(b) // 2] ^= 0xFF
    open(path, "wb").write(bytes(b))


def test_replica_swap_fallback_names_the_restored_version(tmp_path):
    """Review regression: a corrupt NEWEST commit falls back to the
    older one — the serving version must name the weights ACTUALLY
    restored (not latest_step()), the non-swap must not count as a
    swap, and a later intact commit must still go live."""
    from horovod_tpu.checkpoint import ShardedCheckpointer
    from horovod_tpu.serving import ReplicaServer
    from horovod_tpu.serving.replica import demo_params
    store = ShardedCheckpointer(str(tmp_path), rank=0, world_size=1)
    store.save(1, {"params": demo_params(4, scale=1.0)}, wait=True)
    store.save(2, {"params": demo_params(4, scale=2.0)}, wait=True)
    _corrupt(str(tmp_path / "step_2" / "shard_0.npz"))
    r = ReplicaServer(dim=4, store_dir=str(tmp_path),
                      replica_id="fb", swap_poll_s=0.05).start()
    try:
        # initial load fell back to step 1 and SAYS so
        code, resp = _post(r.port, {"id": "fb1", "x": [4.0, 0, 0, 0]})
        assert code == 200 and resp["version"] == 1
        assert abs(resp["y"][0] - 1.0) < 1e-5
        time.sleep(0.3)  # swap polls see the corrupt step 2, skip it
        assert r._version == 1
        # an intact NEWER commit still goes live
        store.save(3, {"params": demo_params(4, scale=3.0)}, wait=True)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and r._version != 3:
            time.sleep(0.05)
        code, resp = _post(r.port,
                           {"id": "fb3", "x": [4.0, 0, 0, 0]})
        assert resp["version"] == 3 and abs(resp["y"][0] - 3.0) < 1e-5
    finally:
        r.stop()
        store.close()


# -- router -------------------------------------------------------------------
class _StubServer:
    """Minimal /infer stub with a configurable delay (the slow-replica
    stand-in for hedge tests)."""

    def __init__(self, delay_s=0.0, name="stub"):
        from http.server import BaseHTTPRequestHandler
        from horovod_tpu.runner.http_kv import ThreadedHTTPServer
        stub = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                doc = json.loads(self.rfile.read(n))
                time.sleep(stub.delay_s)
                body = json.dumps(
                    {"id": doc["id"], "y": [0.0], "version": 0,
                     "replica": stub.name}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.delay_s = delay_s
        self.name = name
        self.httpd = ThreadedHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    @property
    def port(self):
        return self.httpd.server_address[1]

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_router_retries_past_dead_replica(replica):
    """A dead endpoint (connection refused) costs a retry, never the
    request: the survivor answers and the accounting stays
    exactly-once."""
    from horovod_tpu.serving import Router
    dead = ("127.0.0.1", _free_port())
    router = Router([dead, ("127.0.0.1", replica.port)], hedge_ms=0)
    doc = router.submit([1.0, 0, 0, 0], req_id="retry-1")
    assert doc["replica"] == "t0"
    acct = router.accounting()
    assert acct["outcomes"].get("retried", 0) >= 1
    assert acct["accepted"] == acct["answered_ok"] == 1
    assert not acct["unanswered"] and not acct["answered_twice"]
    router.close()


def test_router_hedges_slow_replica(replica):
    """A replica that has gone silent past hedge_ms gets the request
    duplicated to a second replica; the first success wins."""
    from horovod_tpu.serving import Router
    slow = _StubServer(delay_s=2.0, name="slow")
    try:
        router = Router([("127.0.0.1", slow.port),
                         ("127.0.0.1", replica.port)],
                        hedge_ms=100)
        t0 = time.monotonic()
        doc = router.submit([1.0, 0, 0, 0], req_id="hedge-1")
        took = time.monotonic() - t0
        assert doc["replica"] == "t0"  # the fast replica won
        assert took < 1.5  # did NOT wait out the slow replica
        acct = router.accounting()
        assert acct["outcomes"].get("hedged", 0) >= 1
        router.close()
    finally:
        slow.stop()


def test_router_client_error_is_terminal_not_retried(replica):
    """Review regression: a definitive 4xx (wrong-width payload) must
    be terminal — answered with the replica's verdict, logged
    ``rejected``, never re-dispatched across the fleet, and never a
    zero-drop audit violation."""
    from horovod_tpu.serving import Router
    from horovod_tpu.serving.router import RequestRejected
    router = Router([("127.0.0.1", replica.port)], hedge_ms=0)
    with pytest.raises(RequestRejected) as ei:
        router.submit([1.0, 2.0], req_id="badwidth")  # replica dim=4
    assert ei.value.code == 400
    acct = router.accounting()
    assert acct["outcomes"].get("rejected") == 1
    assert acct["outcomes"].get("retried", 0) == 0
    assert not acct["unanswered"]  # rejected IS a terminal answer
    router.close()


def test_router_admission_shed_is_explicit():
    from horovod_tpu.serving import Router
    from horovod_tpu.serving.batcher import SheddedError
    slow = _StubServer(delay_s=1.0)
    try:
        router = Router([("127.0.0.1", slow.port)], max_inflight=1,
                        hedge_ms=0)
        results = []

        def first():
            results.append(router.submit([1.0], req_id="occupant"))

        t = threading.Thread(target=first, daemon=True)
        t.start()
        time.sleep(0.2)  # occupant holds the one admission slot
        with pytest.raises(SheddedError):
            router.submit([2.0], req_id="shed-me")
        t.join(timeout=10)
        assert results  # the occupant itself completed
        acct = router.accounting()
        assert acct["outcomes"].get("shed") == 1
        entries = [e for e in router.log.entries
                   if e["outcome"] == "shed"]
        assert entries and entries[0]["where"] == "admission"
        router.close()
    finally:
        slow.stop()


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# -- SLO window -> finding -> autopilot scale-out -----------------------------
def test_latency_window_publishes_percentiles_and_history_point(
        tmp_path, monkeypatch):
    from horovod_tpu.metrics import timeseries
    from horovod_tpu.serving.metrics import LatencyWindow
    monkeypatch.setenv("HVD_TPU_OBS_DIR", str(tmp_path))
    timeseries.reset()
    try:
        w = LatencyWindow(window_s=60.0)
        for ms in (1, 2, 3, 4, 100):
            w.observe(ms / 1000.0)
        doc = w.maybe_roll(force=True)
        assert doc["requests"] == 5
        assert doc["p50_s"] == pytest.approx(0.003, abs=1e-6)
        assert doc["p99_s"] == pytest.approx(0.1, abs=1e-6)
        from horovod_tpu.metrics.registry import default_registry
        snap = default_registry().snapshot()
        assert snap["hvd_serving_p99_seconds"]["value"] == \
            pytest.approx(0.1, abs=1e-6)
        points = timeseries.read_series(str(tmp_path))
        assert any(isinstance(p.get("serving"), dict) for p in points)
    finally:
        timeseries.reset()


def test_slo_breach_finding_scales_out_fleet_under_act(monkeypatch):
    """The detection->remediation chain end to end, in-process: a
    sustained windowed p99 over SLO reports ONE slo_breach finding;
    the default serving-slo-scaleout policy under act runs the
    registered scale-out hook.  Under observe the identical decision
    is recorded and nothing runs."""
    import horovod_tpu.autopilot as autopilot
    from horovod_tpu.metrics import anomaly
    from horovod_tpu.serving.metrics import LatencyWindow

    monkeypatch.setenv("HVD_TPU_SERVING_SLO_P99_MS", "10")
    monkeypatch.setenv("HVD_TPU_SERVING_SLO_WINDOWS", "2")

    for mode, expect_calls in (("act", 1), ("observe", 0)):
        monkeypatch.setenv("HVD_TPU_AUTOPILOT", mode)
        autopilot.reset()
        anomaly.reset()
        calls = []
        autopilot.actions.register_scale_out_hook(
            lambda: calls.append(1))
        w = LatencyWindow(window_s=0.01)
        for _ in range(2):  # two consecutive breaching windows
            w.observe(0.5)
            w.maybe_roll(force=True)
        # hysteresis: ONE finding per episode, not one per window
        w.observe(0.5)
        w.maybe_roll(force=True)
        deadline = time.monotonic() + 5
        decisions = []
        while time.monotonic() < deadline:
            decisions = [d for d in autopilot.recent_decisions()
                         if d["policy"] == "serving-slo-scaleout"]
            if decisions and (len(calls) >= expect_calls):
                if mode == "observe" or calls:
                    break
            time.sleep(0.05)
        assert len(decisions) == 1, decisions
        assert decisions[0]["outcome"] == \
            ("fired" if mode == "act" else "dry_run")
        if mode == "act":
            assert len(calls) == 1
        else:
            assert not calls
    autopilot.reset()
    anomaly.reset()


# -- CLI rendering ------------------------------------------------------------
def test_top_renders_serving_lines():
    from horovod_tpu.metrics.__main__ import render_top
    series = {
        "hvd_serving_qps": 123.4, "hvd_serving_queue_depth": 3.0,
        "hvd_serving_p50_seconds": 0.0012,
        "hvd_serving_p99_seconds": 0.0045,
        'hvd_serving_shed_total{where="queue"}': 2.0,
        "hvd_serving_hedged_total": 5.0,
        "hvd_serving_retried_total": 1.0,
        "hvd_serving_replicas_live": 1.0,
        "hvd_serving_replicas_target": 2.0,
        "hvd_serving_weight_version": 7.0,
        "hvd_serving_swaps_total": 2.0,
        "hvd_serving_replica_respawns_total": 1.0,
    }
    frame = render_top(series, "test")
    assert "SERVING" in frame and "123.4 qps" in frame
    assert "p99 4.5ms" in frame and "shed 2" in frame
    assert "hedged 5" in frame and "retried 1" in frame
    assert "replicas        : 1/2" in frame
    assert "FLEET BELOW TARGET" in frame
    # no serving series -> no serving line
    assert "SERVING" not in render_top({"hvd_steps_total": 5.0}, "t")


def test_history_serving_table(tmp_path, monkeypatch, capsys):
    from horovod_tpu.metrics import timeseries
    from horovod_tpu.metrics.__main__ import main as metrics_main
    monkeypatch.setenv("HVD_TPU_OBS_DIR", str(tmp_path))
    timeseries.reset()
    try:
        timeseries.record_point({"serving": {
            "window_s": 5.0, "requests": 100, "qps": 20.0,
            "p50_s": 0.002, "p99_s": 0.009, "shed": 1}})
    finally:
        timeseries.reset()
    rc = metrics_main(["history", "--dir", str(tmp_path), "--serving"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "p99" in out and "20.0" in out and "serving window" in out
    # the step view must not show serving points
    rc = metrics_main(["history", "--dir", str(tmp_path)])
    assert rc == 1  # nothing but serving windows in the store


# -- bench gate ---------------------------------------------------------------
def _serving_doc(**over):
    doc = {"bench": "serving", "replicas": 2, "clients": 4,
           "duration_s": 5.0, "requests": 1000, "qps": 200.0,
           "p50_s": 0.002, "p99_s": 0.01, "shed_fraction": 0.0,
           "failed": 0, "unanswered": 0, "answered_twice": 0,
           # the request ledger's closed books (ISSUE 19): the gate
           # refuses an artifact without them
           "stage_seconds": {"forward": 1.6, "queue": 0.3,
                             "dispatch": 0.05, "unattributed": 0.05},
           "stage_unattributed_frac": 0.025,
           "dominant_stage": "forward"}
    doc.update(over)
    return doc


def test_check_bench_serving_gate(tmp_path):
    import sys as _sys
    _sys.path.insert(0, REPO)
    try:
        from ci.check_bench import (_load_serving_doc, check_serving,
                                    serving_main)
    finally:
        _sys.path.remove(REPO)
    # extraction: raw JSON and captured BENCH_SERVE line both load
    raw = tmp_path / "BENCH_SERVE.json"
    raw.write_text(json.dumps(_serving_doc()))
    assert _load_serving_doc(str(raw))["qps"] == 200.0
    cap = tmp_path / "out.txt"
    cap.write_text("noise\nBENCH_SERVE " + json.dumps(_serving_doc())
                   + "\n")
    assert _load_serving_doc(str(cap))["qps"] == 200.0
    # clean + no baseline: OK
    assert serving_main(["--serving", str(raw)]) == 0
    # a "clean" number that shed requests is refused
    assert check_serving(_serving_doc(shed_fraction=0.1), None, 0.5)
    # failed / zero-drop-audit violations are refused
    assert check_serving(_serving_doc(failed=3), None, 0.5)
    assert check_serving(_serving_doc(answered_twice=1), None, 0.5)
    # p99 regression beyond tolerance fails, inside tolerance passes
    base = _serving_doc(p99_s=0.005)
    assert check_serving(_serving_doc(p99_s=0.02), base, 0.5)
    assert not check_serving(_serving_doc(p99_s=0.007), base, 0.5)
    # the ledger's books-close gate (ISSUE 19): missing breakdown and
    # open books both fail; a closed artifact passes (above)
    assert check_serving(_serving_doc(stage_seconds=None), None, 0.5)
    assert check_serving(
        _serving_doc(stage_unattributed_frac=0.25), None, 0.5)
    assert check_serving(
        _serving_doc(stage_unattributed_frac=None), None, 0.5)
    # percentile replay: a sample that agrees passes, one that says the
    # artifact's p99 math diverged fails
    good = _serving_doc(latency_sample=[0.002] * 50 + [0.01] * 5)
    assert not check_serving(good, None, 0.5)
    bad = _serving_doc(latency_sample=[0.002] * 55, p99_s=0.2)
    assert any("replay" in p for p in check_serving(bad, None, 0.5))
    # end to end with a baseline file
    shed = tmp_path / "shed.json"
    shed.write_text(json.dumps(_serving_doc(shed_fraction=0.2)))
    assert serving_main(["--serving", str(shed)]) == 1
    assert serving_main(["--serving", str(raw), "--baseline",
                         str(raw)]) == 0


def test_chaos_plan_validates_serving_seam():
    from horovod_tpu.chaos import FaultPlanError, parse_plan
    plan = parse_plan(json.dumps({"faults": [
        {"seam": "serving.request", "kind": "shed", "count": 1},
        {"seam": "serving.request", "kind": "delay", "delay_ms": 5,
         "rank": 1},
        {"seam": "serving.request", "kind": "error", "start": 3,
         "stop": 9}]}))
    assert len(plan.rules) == 3
    with pytest.raises(FaultPlanError, match="not valid for seam"):
        parse_plan(json.dumps({"faults": [
            {"seam": "serving.request", "kind": "kill"}]}))
    with pytest.raises(FaultPlanError, match="not valid for seam"):
        parse_plan(json.dumps({"faults": [
            {"seam": "step", "kind": "shed"}]}))


@pytest.mark.slow  # spins real traffic for ~3s; serving/chaos tiers
def test_serving_bench_end_to_end_through_gate(tmp_path):
    """benchmarks/serving_bench.py (in-process mode) emits a clean
    BENCH_SERVE artifact that passes the check_bench --serving gate."""
    import sys as _sys
    bench_dir = os.path.join(REPO, "benchmarks")
    _sys.path.insert(0, bench_dir)
    _sys.path.insert(0, REPO)
    try:
        from serving_bench import run_bench
        from ci.check_bench import check_serving
    finally:
        _sys.path.remove(bench_dir)
        _sys.path.remove(REPO)
    doc = run_bench(replicas=2, clients=3, duration_s=2.0,
                    in_process=True, warmup_s=0.5)
    assert doc["requests"] > 0 and doc["qps"] > 0
    assert doc["p50_s"] <= doc["p99_s"]
    problems = check_serving(doc, None, 0.5)
    assert not problems, problems
    # and vs itself as baseline (regression band trivially holds)
    assert not check_serving(doc, doc, 0.5)


# -- slow: the chaos acceptance pair ------------------------------------------
def _closed_loop(router, clients, stop, errors):
    threads = []

    def client(i):
        n = 0
        while not stop.is_set():
            n += 1
            try:
                router.submit([float(i), 1.0, 2.0, 3.0],
                              req_id=f"c{i}-{n}")
            except Exception as e:  # noqa: BLE001 - audit catches all
                errors.append(repr(e))

    for i in range(clients):
        t = threading.Thread(target=client, args=(i,), daemon=True)
        t.start()
        threads.append(t)
    return threads


@pytest.mark.slow  # tier-1 budget rule: multiprocess tests are
#                    slow-marked; the serving/chaos CI tiers run them
def test_serving_kill_replica_zero_drop_and_heal():
    """ISSUE 14 acceptance (a): SIGKILL one replica of a 2-replica
    fleet under sustained closed-loop load — every accepted request
    gets exactly one successful response (hedged/retried to the
    survivor), zero drops, and the fleet heals to full size with the
    exit classified FAILURE."""
    from horovod_tpu.serving import ReplicaFleet, Router
    fleet = ReplicaFleet(size=2, dim=4).start(ready_timeout_s=120)
    router = Router(fleet.endpoints, hedge_ms=200, max_attempts=8)
    stop = threading.Event()
    errors = []
    threads = _closed_loop(router, 4, stop, errors)
    try:
        time.sleep(1.5)
        victim = fleet._replicas[1]
        os.kill(victim.proc.pid, signal.SIGKILL)
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline and fleet.live_count() < 2:
            time.sleep(0.25)
        assert fleet.live_count() == 2, "fleet did not heal"
        time.sleep(1.0)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=15)
        router.close()
    acct = router.accounting()
    fleet.stop()
    assert not errors, errors[:3]
    # the zero-drop audit, from request-log accounting
    assert acct["accepted"] == acct["answered_ok"] > 0
    assert not acct["unanswered"] and not acct["answered_twice"]
    assert acct["outcomes"].get("failed", 0) == 0
    # the kill was absorbed by hedge/retry, visibly
    assert acct["outcomes"].get("retried", 0) \
        + acct["outcomes"].get("hedged", 0) > 0
    # exit classified FAILURE (not drained), exactly one kill
    kills = [e for e in fleet.exits if e["outcome"] == "failure"]
    assert len(kills) == 1 and kills[0]["rc"] == -9
    from horovod_tpu.metrics.registry import default_registry
    snap = default_registry().snapshot()
    assert snap["hvd_serving_accepted_total"]["value"] >= \
        acct["accepted"]


@pytest.mark.slow
def test_serving_drain_plus_hot_swap_zero_failures(tmp_path):
    """ISSUE 14 acceptance (b): a chaos preemption notice drains one
    replica — it finishes all in-flight requests and exits DRAINED
    (exit 0, never failure evidence) — while a concurrent hot weight
    swap from a fresh durable commit serves the new version, with
    zero failed requests; proven from request-log accounting plus the
    hvd_serving_* counters and the fleet's exit classification."""
    from horovod_tpu.checkpoint import ShardedCheckpointer
    from horovod_tpu.serving import ReplicaFleet, Router
    from horovod_tpu.serving.replica import demo_params
    store_dir = tmp_path / "store"
    store = ShardedCheckpointer(str(store_dir), rank=0, world_size=1)
    store.save(1, {"params": demo_params(4, scale=1.0)}, wait=True)
    # the preemption notice targets SLOT 1 only, ~1s into the run
    # (poll every 0.2s -> invocation index 5), with a marker so the
    # RESPAWNED replacement in the slot does not re-drain forever
    plan = json.dumps({"faults": [
        {"seam": "preemption", "kind": "notice", "rank": 1,
         "start": 5, "count": 1,
         "marker": str(tmp_path / "preempt_once")}]})
    fleet = ReplicaFleet(
        size=2, dim=4, store_dir=str(store_dir),
        extra_env={"HVD_TPU_FAULT_PLAN": plan,
                   "HVD_TPU_SERVING_SWAP_POLL_S": "0.1"}).start(
        ready_timeout_s=120)
    router = Router(fleet.endpoints, hedge_ms=200, max_attempts=8)
    stop = threading.Event()
    errors = []
    threads = _closed_loop(router, 4, stop, errors)
    versions = set()
    try:
        time.sleep(0.5)
        # concurrent hot swap: a fresh durable commit lands mid-drain
        store.save(2, {"params": demo_params(4, scale=3.0)}, wait=True)
        # wait for the drained exit + heal + the new version serving
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            drained = [e for e in fleet.exits
                       if e["outcome"] == "drained"]
            doc = router.submit([4.0, 0, 0, 0])
            versions.add(doc["version"])
            if drained and fleet.live_count() == 2 and 2 in versions:
                break
            time.sleep(0.2)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=15)
        router.close()
    acct = router.accounting()
    exits = list(fleet.exits)
    fleet.stop()
    store.close()
    # the doomed replica finished its in-flight work and exited
    # DRAINED; nothing was held against it and the fleet healed
    drained = [e for e in exits if e["outcome"] == "drained"]
    assert len(drained) == 1, exits
    assert drained[0]["rc"] == 0 and drained[0]["slot"] == 1
    assert "DRAINED" in drained[0]["tail"]
    assert "preemption" in drained[0]["tail"]
    assert not [e for e in exits if e["outcome"] == "failure"], exits
    # zero failed requests through drain + swap, exactly-once audit
    assert not errors, errors[:3]
    assert acct["accepted"] == acct["answered_ok"] > 0
    assert not acct["unanswered"] and not acct["answered_twice"]
    assert acct["outcomes"].get("failed", 0) == 0
    # the new version went live with zero downtime
    assert 2 in versions
