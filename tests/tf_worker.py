"""Multi-process TF drop-in worker: DistributedGradientTape inside a
``tf.function`` (reference analog: the tf.function cases of
test/parallel/test_tensorflow.py — their tape allreduces are TF ops and
trace transparently; ours hosts the TCP-core grouped allreduce via
py_function at graph execution time)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import tensorflow as tf  # noqa: E402

import horovod_tpu.tensorflow as hvd  # noqa: E402


def rank_grads(data_rank, w):
    """The (deterministic) local gradient each rank produces, computable
    on any rank so the expected cross-rank average needs no extra comms."""
    x = np.full((4, 3), float(data_rank + 1), np.float32)
    with tf.GradientTape() as tape:
        y = tf.linalg.matmul(tf.constant(x), w)
        loss = tf.reduce_sum(y * y)
    return tape.gradient(loss, [w])[0].numpy()


def main():
    rank = int(os.environ["HOROVOD_RANK"])
    size = int(os.environ["HOROVOD_SIZE"])
    hvd.init()
    assert hvd.rank() == rank and hvd.size() == size

    w = tf.Variable(np.arange(6, dtype=np.float32).reshape(3, 2) / 10.0)
    unused = tf.Variable(1.0)  # tape.gradient yields None for it

    @tf.function
    def step(x):
        with tf.GradientTape() as tape:
            y = tf.linalg.matmul(x, w)
            loss = tf.reduce_sum(y * y)
        dtape = hvd.DistributedGradientTape(tape)
        return dtape.gradient(loss, [w, unused])

    x = tf.constant(np.full((4, 3), float(rank + 1), np.float32))
    gw, gu = step(x)
    assert gu is None, "None gradient must pass through the graph tape"

    expect = np.mean([rank_grads(r, w) for r in range(size)], axis=0)
    np.testing.assert_allclose(gw.numpy(), expect, rtol=1e-5)

    # eager path stays equivalent to the traced path
    with tf.GradientTape() as tape:
        y = tf.linalg.matmul(x, w)
        loss = tf.reduce_sum(y * y)
    eg = hvd.DistributedGradientTape(tape).gradient(loss, [w])[0]
    np.testing.assert_allclose(eg.numpy(), expect, rtol=1e-5)

    # sparse embedding grads (IndexedSlices) stay sparse inside the
    # tf.function: every rank's (indices, values) allgather and the
    # values average, so densifying reproduces the cross-rank mean
    emb = tf.Variable(np.zeros((5, 2), np.float32))

    @tf.function
    def emb_step(ids):
        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(tf.nn.embedding_lookup(emb, ids))
        return hvd.DistributedGradientTape(tape).gradient(loss, [emb])[0]

    g = emb_step(tf.constant([rank, rank]))  # rank r touches row r twice
    assert isinstance(g, tf.IndexedSlices), type(g)
    assert int(tf.shape(g.indices)[0]) == 2 * size  # gathered, not densified
    exp = np.zeros((5, 2), np.float32)
    for r in range(size):
        exp[r] += 2.0
    exp /= size
    np.testing.assert_allclose(
        np.asarray(tf.convert_to_tensor(g)), exp, rtol=1e-6)

    # two tapes over the SAME variables in one traced step (WGAN-GP
    # style): identical gradient structure, so only the trace-time
    # graph-unique name suffix keeps their allreduces apart
    def local_pair(data_rank):
        x_r = tf.constant(np.full((4, 3), float(data_rank + 1), np.float32))
        with tf.GradientTape() as t1:
            l1 = tf.reduce_sum(tf.linalg.matmul(x_r, w))
        with tf.GradientTape() as t2:
            l2 = tf.reduce_sum(tf.linalg.matmul(x_r, w) ** 2)
        return (t1.gradient(l1, [w])[0].numpy(),
                t2.gradient(l2, [w])[0].numpy())

    @tf.function
    def double_step(xx):
        with tf.GradientTape() as t1:
            l1 = tf.reduce_sum(tf.linalg.matmul(xx, w))
        with tf.GradientTape() as t2:
            l2 = tf.reduce_sum(tf.linalg.matmul(xx, w) ** 2)
        # distinct name_scopes: the uniquifier must keep the scope path
        # ('gen/tfgrad' vs 'disc/tfgrad'), not just the leaf name
        with tf.name_scope("gen"):
            g1 = hvd.DistributedGradientTape(t1).gradient(l1, [w])[0]
        with tf.name_scope("disc"):
            g2 = hvd.DistributedGradientTape(t2).gradient(l2, [w])[0]
        return g1, g2

    g1, g2 = double_step(x)
    pairs = [local_pair(r) for r in range(size)]
    np.testing.assert_allclose(
        g1.numpy(), np.mean([p[0] for p in pairs], axis=0), rtol=1e-5)
    np.testing.assert_allclose(
        g2.numpy(), np.mean([p[1] for p in pairs], axis=0), rtol=1e-5)

    # a lone Variable source keeps its structure at size > 1 too
    with tf.GradientTape() as tape:
        y = tf.linalg.matmul(x, w)
        loss = tf.reduce_sum(y * y)
    sg = hvd.DistributedGradientTape(tape).gradient(loss, w)
    assert not isinstance(sg, (list, tuple))
    np.testing.assert_allclose(sg.numpy(), expect, rtol=1e-5)

    # fp16 compression through the traced optimizer path: compressed
    # wire dtype, original dtype after decompress, ranks agree
    wc = tf.Variable(np.ones((3,), np.float32))
    copt = hvd.DistributedOptimizer(
        tf.keras.optimizers.SGD(1.0), compression=hvd.Compression.fp16)

    @tf.function
    def cstep(g):
        copt.apply_gradients([(g, wc)])

    cstep(tf.constant(np.full(3, float(rank + 1), np.float32)))
    np.testing.assert_allclose(wc.numpy(),
                               1.0 - (sum(range(size)) + size) / size,
                               rtol=1e-3)

    # keras model.fit at size 2: the wrapped optimizer's graph-mode sync
    # (keras compiles train_step into a tf.function) plus the broadcast
    # callback must leave every rank with IDENTICAL weights
    import horovod_tpu.keras as khvd
    tf.random.set_seed(rank)  # deliberately different init per rank
    model = tf.keras.Sequential([tf.keras.layers.Dense(1, input_shape=(4,))])
    model.compile(
        optimizer=khvd.DistributedOptimizer(tf.keras.optimizers.SGD(0.05)),
        loss="mse")
    rng = np.random.RandomState(0)
    fx = rng.randn(32, 4).astype(np.float32)
    fy = (fx @ np.asarray([[1.0], [2.0], [3.0], [4.0]], np.float32))
    mine = slice(rank * 16, (rank + 1) * 16)
    model.fit(fx[mine], fy[mine], epochs=2, batch_size=8, verbose=0,
              callbacks=[khvd.callbacks.BroadcastGlobalVariablesCallback(0)])
    final = np.concatenate([w.reshape(-1) for w in model.get_weights()])
    gathered = hvd.allgather(tf.constant(final[None, :]))
    np.testing.assert_allclose(np.asarray(gathered)[0],
                               np.asarray(gathered)[1], rtol=1e-6)

    hvd.shutdown()
    print("tf_worker ok")


if __name__ == "__main__":
    main()
