"""Driver-contract tests: dryrun_multichip must compile+run at every device
count the driver may choose, and entry() must produce a jittable forward."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cpu_snippet(n_devices: int, tail: str) -> str:
    """Shared env bootstrap for subprocess tests (kept in one place so a
    future env requirement can't drift between snippets)."""
    return f"""
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count={n_devices}"
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
import jax
jax.config.update("jax_platforms", "cpu")
import sys
sys.path.insert(0, {REPO!r})
""" + tail


def test_driver_call_path(capsys, monkeypatch):
    """EXACTLY what the driver does: import the module and call
    dryrun_multichip(8) — no env bootstrap, no subprocess wrapper. The
    function must self-bootstrap a forced-CPU child regardless of this
    process's JAX state. The scaling-curve phase must emit its
    ``[scaling] {json}`` artifact line (one world here keeps the test
    inside the tier-1 budget; the driver's real run measures 1,2,4,8)."""
    monkeypatch.setenv("HVD_DRYRUN_SCALING_WORLDS", "2")
    sys.path.insert(0, REPO)
    try:
        import __graft_entry__
        __graft_entry__.dryrun_multichip(8)
    finally:
        sys.path.remove(REPO)
    out = capsys.readouterr().out
    assert "[dryrun] OK" in out
    sys.path.insert(0, REPO)
    try:
        from ci.check_bench import extract_scaling_curve
    finally:
        sys.path.remove(REPO)
    curve = extract_scaling_curve(out)
    assert curve and curve["scaling_curve"][0]["world"] == 2
    assert curve["scaling_curve"][0]["samples_per_sec"] > 0
    assert curve["scaling_curve"][0]["samples_per_sec_int8"] > 0


@pytest.mark.parametrize("n", [2, 4, 16])
def test_dryrun_device_counts(n, monkeypatch):
    # the function self-bootstraps; call it directly at every
    # driver-plausible device count (scaling is the driver-artifact
    # phase, covered by test_driver_call_path — skip it here)
    monkeypatch.setenv("HVD_DRYRUN_SCALING", "0")
    sys.path.insert(0, REPO)
    try:
        import __graft_entry__
        __graft_entry__.dryrun_multichip(n)
    finally:
        sys.path.remove(REPO)


def test_entry_compiles_on_cpu():
    code = _cpu_snippet(1, """
import jax
from __graft_entry__ import entry
fn, args = entry()
out = jax.jit(fn)(*args)
print("entry loss:", float(out))
assert float(out) > 0
""")
    rc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                        timeout=900, cwd=REPO)
    assert rc.returncode == 0, rc.stdout.decode() + rc.stderr.decode()
