"""Lifecycle & identity tests (reference analog: test/single/ init tests and
process-set tests in test/parallel/test_process_sets_*)."""

import pytest


def test_init_idempotent(hvd):
    assert hvd.is_initialized()
    hvd.init()  # second init is a no-op
    assert hvd.is_initialized()


def test_identity_single_process(hvd):
    assert hvd.rank() == 0
    assert hvd.size() == 1
    assert hvd.local_rank() == 0
    assert hvd.local_size() == 1
    assert hvd.cross_rank() == 0
    assert hvd.cross_size() == 1
    assert hvd.is_homogeneous()


def test_built_queries(hvd):
    assert hvd.xla_built()
    assert not hvd.mpi_built()
    assert not hvd.nccl_built()
    assert not hvd.cuda_built()
    assert not hvd.ddl_built()
    assert not hvd.sycl_built()
    assert not hvd.mpi_enabled()
    # the TCP core stands in for gloo; enabled tracks the built .so
    assert hvd.gloo_enabled() == hvd.gloo_built()


def test_num_devices(hvd):
    assert hvd.num_devices() == 8  # virtual CPU mesh from conftest
    assert hvd.global_device_count() == 8


def test_requires_init():
    import horovod_tpu as hvd
    hvd.shutdown()
    with pytest.raises(RuntimeError):
        hvd.rank()


def test_process_sets(hvd):
    # At size 1, any ranks list equals the global set → dedup to id 0
    # (reference: ProcessSetTable dedup of identical rank lists).
    ps = hvd.add_process_set([0])
    assert ps.process_set_id == 0
    assert ps.included()
    assert ps.rank() == 0
    assert ps.size() == 1
    assert hvd.process_set_ids() == [0]
    with pytest.raises(ValueError):
        hvd.remove_process_set(hvd.global_process_set)


def test_shutdown_and_reinit(hvd):
    hvd.shutdown()
    assert not hvd.is_initialized()
    hvd.init()
    assert hvd.rank() == 0
