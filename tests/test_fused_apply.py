"""Pallas fused dequantize+optimizer kernels (ISSUE 6): interpret-mode
kernel parity against the reference optax math, the quantize-with-
residual kernel, the fused DistributedOptimizer transform across
regimes, and its argument validation."""

import functools

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd_mod
from horovod_tpu._compat import shard_map
from horovod_tpu.compression.error_feedback import (ErrorFeedback,
                                                    error_feedback_transform)
from horovod_tpu.compression.quantizers import BlockInt8Quantizer
from horovod_tpu.ops.pallas_quantize import (block_dequantize,
                                             block_quantize,
                                             block_quantize_ef,
                                             fused_adam_apply,
                                             fused_sgd_apply)


def _blocks(rng, n=5, block=256):
    return jnp.asarray(rng.randn(n, block).astype(np.float32))


def test_quantize_ef_kernel_matches_plain_quantize_plus_residual():
    rng = np.random.RandomState(0)
    x = _blocks(rng)
    v1, s1 = block_quantize(x, interpret=True)
    v2, s2, res = block_quantize_ef(x, interpret=True)
    np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
    dq = block_dequantize(v1, s1, interpret=True)
    np.testing.assert_allclose(np.asarray(res), np.asarray(x - dq),
                               atol=1e-6)


def test_quantize_ef_xla_fallback_same_semantics():
    rng = np.random.RandomState(1)
    x = _blocks(rng, block=100)  # non-128-multiple -> XLA path
    v, s, res = block_quantize_ef(x)
    dq = block_dequantize(v, s)
    np.testing.assert_allclose(np.asarray(res), np.asarray(x - dq),
                               atol=1e-6)


@pytest.mark.parametrize("momentum", [0.0, 0.9])
def test_fused_sgd_kernel_matches_optax(momentum):
    rng = np.random.RandomState(2)
    x = _blocks(rng)
    vals, scales = block_quantize(x, interpret=True)
    g = block_dequantize(vals, scales, interpret=True)
    mom = _blocks(rng) if momentum else None
    delta, nm = fused_sgd_apply(vals, scales, mom, 0.1, momentum,
                                interpret=True)
    ref_m = g if not momentum else g + momentum * mom
    np.testing.assert_allclose(np.asarray(delta),
                               np.asarray(-0.1 * ref_m), atol=1e-6)
    if momentum:
        np.testing.assert_allclose(np.asarray(nm), np.asarray(ref_m),
                                   atol=1e-6)
    else:
        assert nm is None


def test_fused_adam_kernel_matches_optax_step():
    rng = np.random.RandomState(3)
    x = _blocks(rng)
    vals, scales = block_quantize(x, interpret=True)
    g = block_dequantize(vals, scales, interpret=True)
    tx = optax.adam(1e-3)
    st = tx.init(x)
    ref_updates, _ = tx.update(g, st, x)
    delta, nm, nv = fused_adam_apply(
        vals, scales, jnp.zeros_like(x), jnp.zeros_like(x),
        1e-3, 0.9, 0.999, 1e-8, 1 - 0.9, 1 - 0.999, interpret=True)
    np.testing.assert_allclose(np.asarray(delta),
                               np.asarray(ref_updates), atol=1e-6)


def test_fused_kernels_pad_ragged_rows():
    # 33 rows crosses the 32-row int8 tile: padding must round-trip
    rng = np.random.RandomState(4)
    x = _blocks(rng, n=33, block=128)
    vals, scales, res = block_quantize_ef(x, interpret=True)
    assert vals.shape == (33, 128) and res.shape == (33, 128)
    delta, nm, nv = fused_adam_apply(
        vals, scales, jnp.zeros_like(x), jnp.zeros_like(x),
        1e-3, 0.9, 0.999, 1e-8, 0.1, 0.001, interpret=True)
    assert delta.shape == (33, 128)
    assert np.all(np.isfinite(np.asarray(delta)))


# -- the fused transform through DistributedOptimizer ----------------------

def _param_tree(rng):
    return {"w": jnp.asarray(rng.randn(10, 30).astype(np.float32)),
            "b": jnp.asarray(rng.randn(7).astype(np.float32))}


@pytest.mark.parametrize("spec_ref", [
    ("sgd", lambda h: (h.fused_sgd(0.1), optax.sgd(0.1))),
    ("sgd_mom", lambda h: (h.fused_sgd(0.1, momentum=0.9),
                           optax.sgd(0.1, momentum=0.9))),
    ("adam", lambda h: (h.fused_adam(1e-3), optax.adam(1e-3))),
], ids=lambda p: p[0] if isinstance(p, tuple) else None)
def test_fused_transform_matches_ef_reference_chain(hvd, spec_ref):
    """fused path == error_feedback_transform(int8) ∘ optax reference
    over multiple steps (single-process regime: identity sync)."""
    spec, ref_tx = spec_ref[1](hvd)
    codec = BlockInt8Quantizer(256, interpret=True)
    tx = hvd.DistributedOptimizer(spec,
                                  compression=ErrorFeedback(codec))
    ref = optax.chain(error_feedback_transform(codec), ref_tx)
    rng = np.random.RandomState(5)
    params = _param_tree(rng)
    st, rst = tx.init(params), ref.init(params)
    p1, p2 = dict(params), dict(params)
    for _ in range(5):
        g = _param_tree(rng)
        u1, st = tx.update(g, st, p1)
        u2, rst = ref.update(g, rst, p2)
        p1 = optax.apply_updates(p1, u1)
        p2 = optax.apply_updates(p2, u2)
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5)


def test_fused_transform_under_jit_and_multisteps(hvd):
    tx = hvd.DistributedOptimizer(
        hvd.fused_adam(1e-3), compression=hvd.Compression.int8,
        backward_passes_per_step=2)
    rng = np.random.RandomState(6)
    params = _param_tree(rng)
    st = tx.init(params)

    @jax.jit
    def step(p, st, g):
        u, st = tx.update(g, st, p)
        return optax.apply_updates(p, u), st

    p = params
    for _ in range(3):
        p, st = step(p, st, _param_tree(rng))
    assert all(bool(jnp.all(jnp.isfinite(x)))
               for x in jax.tree_util.tree_leaves(p))


def test_fused_transform_axis_regime_reduces(hvd):
    """shard_map regime: codes dequantize into an in-graph pmean, every
    shard lands on the identical update."""
    mesh = hvd_mod.build_mesh(dp=-1)
    codec = BlockInt8Quantizer(256, interpret=True)
    tx = hvd_mod.DistributedOptimizer(
        hvd_mod.fused_sgd(1.0), compression=codec, axis_name="dp")
    rng = np.random.RandomState(7)
    params = {"w": jnp.zeros((64,), jnp.float32)}
    g = jnp.asarray(rng.randn(8, 64).astype(np.float32))

    @functools.partial(shard_map, mesh=mesh, in_specs=(P(), P("dp")),
                       out_specs=P("dp"), check_vma=False)
    def body(p, gs):
        st = tx.init(p)
        u, _ = tx.update({"w": gs[0]}, st, p)
        return u["w"][None]

    out = np.asarray(jax.jit(body)(params, g))
    expect = -np.mean([np.asarray(codec.qdq(g[r])) for r in range(8)],
                      axis=0)
    for r in range(8):
        np.testing.assert_allclose(out[r], expect, atol=2e-5)


def test_fused_requires_int8_codec(hvd):
    with pytest.raises(ValueError, match="block-int8"):
        hvd.DistributedOptimizer(hvd.fused_sgd(0.1))
    with pytest.raises(ValueError, match="block-int8"):
        hvd.DistributedOptimizer(hvd.fused_sgd(0.1),
                                 compression=hvd.Compression.fp16)


def test_fused_rejects_unsupported_combinations(hvd):
    ok = hvd.Compression.int8
    with pytest.raises(ValueError, match="Adasum"):
        hvd.DistributedOptimizer(hvd.fused_sgd(0.1), op=hvd_mod.Adasum,
                                 compression=ok)
    with pytest.raises(ValueError, match="scale"):
        hvd.DistributedOptimizer(hvd.fused_sgd(0.1), compression=ok,
                                 prescale_factor=2.0)
    with pytest.raises(ValueError, match="host_sync_in_jit"):
        hvd.DistributedOptimizer(hvd.fused_sgd(0.1), compression=ok,
                                 host_sync_in_jit=True)


def test_fused_trains_a_model(hvd):
    """End-to-end: the fused optimizer reduces the loss on a small
    regression problem (EF carries the int8 error, so convergence must
    track plain SGD closely)."""
    rng = np.random.RandomState(8)
    X = jnp.asarray(rng.randn(128, 10).astype(np.float32))
    true_w = jnp.asarray(rng.randn(10).astype(np.float32))
    Y = X @ true_w
    codec = BlockInt8Quantizer(256, interpret=True)
    tx = hvd.DistributedOptimizer(hvd.fused_sgd(0.05),
                                  compression=ErrorFeedback(codec))
    params = {"w": jnp.zeros((10,), jnp.float32)}
    st = tx.init(params)

    def loss_fn(p):
        return jnp.mean((X @ p["w"] - Y) ** 2)

    losses = []
    for _ in range(40):
        loss, g = jax.value_and_grad(loss_fn)(params)
        u, st = tx.update(g, st, params)
        params = optax.apply_updates(params, u)
        losses.append(float(loss))
    assert losses[-1] < 0.05 * losses[0], losses[::8]
