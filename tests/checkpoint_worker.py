"""Sharded-checkpoint worker (launched by test_core_multiprocess.py).

Exercises the REAL multi-process two-phase commit — no collectives, no
core: the commit barrier is the shared filesystem, exactly as on a TPU
pod with an NFS/GCS-fuse checkpoint dir.  Modes (``CKPT_MODE``):

* ``save``     — every rank writes only its shards for steps 10 and 11;
  rank 0 commits, the others poll until the commit is visible.
* ``crash``    — like ``save``, but ``CKPT_CRASH_RANK`` kill -9's
  ITSELF mid-write of step 11 (partial npz on disk, no marker): rank 0's
  commit must time out, step 10 must stay restorable, and GC must
  reclaim the wreckage (ISSUE 3 acceptance).
* ``restore``  — restore the latest checkpoint at the CURRENT world
  size (1 or 3, saved at 2) and verify the global arrays bit-for-bit;
  optionally re-save at ``CKPT_RESAVE_STEP`` from the new world.
"""

import os
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from horovod_tpu.checkpoint import CheckpointError, ShardedCheckpointer  # noqa: E402
from horovod_tpu.checkpoint import format as fmt  # noqa: E402


def make_state(step):
    """Deterministic, rank-independent state (the replication contract):
    every leaf kind the store supports."""
    return {
        "params": {
            "w": jnp.arange(48.0).reshape(12, 4) + step,
            "b": jnp.linspace(0.0, 1.0, 7) * (step + 1),
            "h": jnp.full((5,), step, jnp.bfloat16),
        },
        "step": int(step),
        "name": f"run-{step}",
        "hist": [1, (2.0, step)],
    }


def check_state(out, step):
    expect = make_state(step)
    np.testing.assert_array_equal(out["params"]["w"],
                                  np.asarray(expect["params"]["w"]))
    np.testing.assert_array_equal(out["params"]["b"],
                                  np.asarray(expect["params"]["b"]))
    assert out["params"]["h"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        out["params"]["h"].astype(np.float32),
        np.asarray(expect["params"]["h"], np.float32))
    assert out["step"] == step and type(out["step"]) is int
    assert out["name"] == f"run-{step}"
    assert isinstance(out["hist"][1], tuple) and out["hist"][1][1] == step


def poll_step(store, step, timeout=60):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if store.latest_step() == step:
            return
        time.sleep(0.1)
    raise AssertionError(f"step {step} never committed; "
                         f"steps={store.all_steps()}")


def arm_crash(crash_step):
    """kill -9 OURSELVES mid-shard-write of ``crash_step``: a partial
    ``.npz.part`` lands on disk, the completion marker never does."""
    real = fmt.write_shard

    def sabotaged(dirpath, rank, arrays, entries, **kw):
        if dirpath.endswith(f"step_{crash_step}.tmp"):
            os.makedirs(dirpath, exist_ok=True)
            part = os.path.join(dirpath, fmt.shard_npz(rank) + ".part")
            with open(part, "wb") as f:
                f.write(b"\x93NUMPY partial garbage")
                f.flush()
                os.fsync(f.fileno())
            os.kill(os.getpid(), signal.SIGKILL)
        return real(dirpath, rank, arrays, entries, **kw)

    fmt.write_shard = sabotaged


def main():
    rank = int(os.environ["HOROVOD_RANK"])
    size = int(os.environ["HOROVOD_SIZE"])
    mode = os.environ["CKPT_MODE"]
    store = ShardedCheckpointer(os.environ["CKPT_DIR"])

    if mode in ("save", "crash"):
        crash_rank = int(os.environ.get("CKPT_CRASH_RANK", "-1"))
        store.save(10, make_state(10), wait=True)
        poll_step(store, 10)  # everyone sees the commit before step 11
        if mode == "crash" and rank == crash_rank:
            arm_crash(11)
        if mode == "crash" and rank == 0:
            # the peer dies mid-write: commit must fail loudly...
            try:
                store.save(11, make_state(11), wait=True)
            except CheckpointError as e:
                assert "timed out" in str(e), e
            else:
                raise AssertionError("commit succeeded without the peer")
            # ...the previous checkpoint is untouched and restorable...
            assert store.latest_step() == 10
            check_state(store.restore_latest(), 10)
            # ...and GC reclaims the wreckage once it goes idle
            time.sleep(1.0)
            store.gc(tmp_ttl=0.5)
            assert fmt.list_tmp_steps(os.environ["CKPT_DIR"]) == []
            assert store.latest_step() == 10
        else:
            store.save(11, make_state(11), wait=True)  # crash rank dies here
            poll_step(store, 11)
    elif mode == "restore":
        expect = int(os.environ["CKPT_EXPECT_STEP"])
        assert store.latest_step() == expect
        check_state(store.restore_latest(), expect)
        # the manifest remembers the world that WROTE it, not ours
        saved_world = fmt.read_manifest(os.environ["CKPT_DIR"],
                                        expect)["world_size"]
        assert saved_world == int(os.environ["CKPT_SAVED_WORLD"]), saved_world
        resave = os.environ.get("CKPT_RESAVE_STEP")
        if resave:
            store.save(int(resave), make_state(int(resave)), wait=True)
            poll_step(store, int(resave))
    else:
        raise SystemExit(f"unknown CKPT_MODE {mode!r}")

    store.close()
    print(f"checkpoint worker {rank}/{size} mode={mode}: OK", flush=True)


if __name__ == "__main__":
    main()
