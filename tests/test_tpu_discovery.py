"""TPU pod-slice discovery against a faked GCE metadata server — the
TPU-native analog of the reference's LSF/MPI environment-detection tests
(reference: ``horovod/runner/launch.py:677-709``, ``runner/util/lsf.py``).
No -H/--hostfile anywhere: hosts come from the metadata surface."""

import http.server
import threading

import pytest

from horovod_tpu.runner import launch
from horovod_tpu.runner.elastic.discovery import HostManager
from horovod_tpu.runner.tpu_discovery import (
    TpuPodDiscovery, metadata_get, running_on_tpu_vm, tpu_accelerator_type,
    tpu_pod_hosts, tpu_worker_index)

WORKERS4 = ("9f3a:w-0:10.164.0.10,9f3a:w-1:10.164.0.11,"
            "9f3a:w-2:10.164.0.12,9f3a:w-3:10.164.0.13")


class _FakeMetadata:
    """Tiny metadata server: serves instance attributes from a mutable
    dict, enforcing the Metadata-Flavor header like the real one."""

    def __init__(self):
        self.attrs = {
            "worker-network-endpoints": WORKERS4,
            "agent-worker-number": "2",
            "accelerator-type": "v5litepod-16",
        }
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                if self.headers.get("Metadata-Flavor") != "Google":
                    self.send_error(403, "Missing Metadata-Flavor header")
                    return
                # instance attributes plus the top-level instance/
                # surface (maintenance-event lives there, not under
                # attributes/ — mirroring the real server's layout)
                base = "/computeMetadata/v1/instance/"
                if not self.path.startswith(base):
                    self.send_error(404)
                    return
                name = self.path[len(base):]
                if name.startswith("attributes/"):
                    name = name[len("attributes/"):]
                elif name != "maintenance-event":
                    self.send_error(404)
                    return
                val = outer.attrs.get(name)
                if val is None:
                    self.send_error(404)
                    return
                body = val.encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.server = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                                      Handler)
        self.thread = threading.Thread(target=self.server.serve_forever,
                                       daemon=True)
        self.thread.start()
        self.endpoint = f"http://127.0.0.1:{self.server.server_address[1]}"

    def close(self):
        self.server.shutdown()
        self.server.server_close()


@pytest.fixture()
def metadata(monkeypatch):
    fake = _FakeMetadata()
    monkeypatch.setenv("HVD_TPU_METADATA_ENDPOINT", fake.endpoint)
    yield fake
    fake.close()


def test_pod_hosts_parsed_in_worker_order(metadata):
    hosts = tpu_pod_hosts()
    assert [h.hostname for h in hosts] == [
        "10.164.0.10", "10.164.0.11", "10.164.0.12", "10.164.0.13"]
    assert all(h.slots == 1 for h in hosts)


def test_worker_index_and_accelerator_type(metadata):
    assert tpu_worker_index() == 2
    assert tpu_accelerator_type() == "v5litepod-16"


def test_missing_attribute_raises_oserror(metadata):
    with pytest.raises(OSError):
        metadata_get("no-such-attribute")


def test_running_on_tpu_vm_probe(metadata):
    assert running_on_tpu_vm()
    assert not running_on_tpu_vm(endpoint="http://127.0.0.1:1",
                                 timeout=0.5)


def test_cli_resolves_pod_hosts_without_dash_h(metadata):
    args = launch.parse_args(["--tpu", "--", "echo", "hi"])
    hosts = launch.resolve_hosts(args)
    assert len(hosts) == 4 and hosts[0].hostname == "10.164.0.10"


def test_cli_tpu_excludes_explicit_hosts(metadata):
    # conflicting host sources are rejected at parse time, for the elastic
    # path too (parse_args errors via SystemExit)
    for argv in (["--tpu", "-H", "a:1", "--", "echo"],
                 ["--tpu", "--host-discovery-script", "./d.sh",
                  "--min-np", "2", "--", "echo"]):
        with pytest.raises(SystemExit):
            launch.parse_args(argv)


def test_launch_static_receives_metadata_hosts(metadata, monkeypatch):
    """hvdrun --tpu end to end through run_commandline: the static
    launcher gets the 4 pod workers, np defaults to the slot sum."""
    captured = {}

    def fake_launch(hosts, np, command, **kw):
        captured.update(hosts=hosts, np=np, command=command)
        return 0

    monkeypatch.setattr(launch, "launch_static", fake_launch)
    rc = launch.run_commandline(["--tpu", "--no-nic-probe", "--",
                                 "echo", "hi"])
    assert rc == 0
    assert [h.hostname for h in captured["hosts"]] == [
        "10.164.0.10", "10.164.0.11", "10.164.0.12", "10.164.0.13"]
    assert captured["np"] == 4
    assert captured["command"] == ["echo", "hi"]


def test_elastic_discovery_tracks_slice_changes(metadata):
    """TpuPodDiscovery re-reads the slice each refresh: a repaired 4th
    worker VM appears without a user discovery script; blacklisted hosts
    stay excluded (driver semantics unchanged)."""
    metadata.attrs["worker-network-endpoints"] = \
        "9f3a:w-0:10.164.0.10,9f3a:w-1:10.164.0.11,9f3a:w-2:10.164.0.12"
    mgr = HostManager(TpuPodDiscovery())
    assert mgr.update_available_hosts() is True
    assert mgr.slot_count() == 3

    metadata.attrs["worker-network-endpoints"] = WORKERS4
    assert mgr.update_available_hosts() is True  # growth observed
    assert mgr.slot_count() == 4

    mgr.blacklist("10.164.0.12")
    assert mgr.update_available_hosts() is True
    assert mgr.slot_count() == 3
    assert "10.164.0.12" not in [h.hostname for h in mgr.current_hosts()]


def test_maintenance_event_surface(metadata):
    """The advance-notice surface (ISSUE 10): ``instance/maintenance-
    event`` reads through the same metadata client, with NONE meaning
    "nothing scheduled" and anything else meaning the host is doomed."""
    from horovod_tpu.runner.tpu_discovery import (MAINTENANCE_NONE,
                                                  tpu_maintenance_event)
    metadata.attrs["maintenance-event"] = "NONE"
    assert tpu_maintenance_event() == MAINTENANCE_NONE
    metadata.attrs["maintenance-event"] = "TERMINATE_ON_HOST_MAINTENANCE"
    assert tpu_maintenance_event() == "TERMINATE_ON_HOST_MAINTENANCE"


def test_preemption_watcher_reads_metadata_notice(metadata):
    """PreemptionWatcher's metadata source: NONE is quiet, a scheduled
    maintenance event reads as a notice."""
    from horovod_tpu.elastic.preemption import PreemptionWatcher
    metadata.attrs["maintenance-event"] = "NONE"
    w = PreemptionWatcher()
    assert w.check_once() is None
    metadata.attrs["maintenance-event"] = "MIGRATE_ON_HOST_MAINTENANCE"
    assert w.check_once() == "metadata"


def test_preemption_watcher_latches_metadata_off(monkeypatch):
    """Off-TPU there is no metadata server: after 3 consecutive probe
    failures the watcher stops paying the connect timeout forever."""
    from horovod_tpu.elastic.preemption import PreemptionWatcher
    monkeypatch.setenv("HVD_TPU_METADATA_ENDPOINT", "http://127.0.0.1:1")
    w = PreemptionWatcher()
    for _ in range(3):
        assert w.check_once() is None
    assert w._metadata_dead is True
