"""Inception V3 model tests (reference benchmark table parity:
docs/benchmarks.rst:13-14 — Inception V3 / ResNet-101 / VGG-16)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax

from horovod_tpu.models.inception import (InceptionV3,
                                          create_inception_state,
                                          make_inception_train_step)
from horovod_tpu.models.resnet import batch_sharding


@pytest.mark.slow  # ~30s XLA:CPU compile; tier-1 budget (models tier
#                    runs it unfiltered)
def test_inception_v3_trains(hvd):
    """Geometry + one GSPMD-auto train step (small input keeps the CPU
    test fast; 95 is the smallest size the VALID-padded stem and the two
    reduction stages all accept)."""
    mesh = hvd.build_mesh(dp=-1)
    model = InceptionV3(num_classes=8, dtype=jnp.float32, dropout=0.0)
    params, batch_stats = create_inception_state(
        model, jax.random.PRNGKey(0), image_size=95, mesh=mesh)
    tx = optax.sgd(0.01, momentum=0.9)
    opt_state = jax.jit(tx.init)(params)
    step = make_inception_train_step(model, tx, mesh)
    rng = np.random.RandomState(0)
    images = jax.device_put(
        jnp.asarray(rng.rand(8, 95, 95, 3), jnp.float32),
        batch_sharding(mesh))
    labels = jax.device_put(
        jnp.asarray(rng.randint(0, 8, (8,)), jnp.int32),
        batch_sharding(mesh))
    params, batch_stats, opt_state, loss = step(
        params, batch_stats, opt_state, images, labels)
    assert np.isfinite(float(loss))


def test_inception_v3_channel_geometry():
    """Stage output channels match the canonical architecture:
    35x35 stages end at 288, 17x17 at 768, 8x8 at 2048."""
    from horovod_tpu.models.inception import (InceptionA, ReductionA,
                                              InceptionB, ReductionB,
                                              InceptionC)
    x = jnp.zeros((1, 35, 35, 192), jnp.float32)
    for pf, want in ((32, 256), (64, 288), (64, 288)):
        m = InceptionA(pf, jnp.float32)
        v = m.init(jax.random.PRNGKey(0), x, train=False)
        x = m.apply(v, x, train=False)
        assert x.shape[-1] == want
    m = ReductionA(jnp.float32)
    v = m.init(jax.random.PRNGKey(0), x, train=False)
    x = m.apply(v, x, train=False)
    assert x.shape == (1, 17, 17, 768)
    m = InceptionB(128, jnp.float32)
    v = m.init(jax.random.PRNGKey(0), x, train=False)
    x = m.apply(v, x, train=False)
    assert x.shape[-1] == 768
    m = ReductionB(jnp.float32)
    v = m.init(jax.random.PRNGKey(0), x, train=False)
    x = m.apply(v, x, train=False)
    assert x.shape == (1, 8, 8, 1280)
    m = InceptionC(jnp.float32)
    v = m.init(jax.random.PRNGKey(0), x, train=False)
    x = m.apply(v, x, train=False)
    assert x.shape[-1] == 2048
