"""Stall-shutdown worker: rank 0 submits a tensor rank 1 never does.
With HOROVOD_STALL_SHUTDOWN_TIME_SECONDS set, rank 0's wait must fail with
a clear stall error instead of hanging (reference:
stall_inspector.h shutdown path; here surfaced per-tensor as
HorovodInternalError). Afterwards the domain keeps working.

Straggler mode (HVD_TEST_STRAGGLER_SECS set): instead of the stall
scenario, rank 1 deliberately sleeps before each submission and the
coordinator's rank-attributed negotiation-wait report
(``CoreBackend.stragglers`` → ``hvd_stragglers_json``) must name rank 1
as the rank everyone waited up on (docs/OBSERVABILITY.md).

Autopsy mode (HVD_TEST_AUTOPSY=1): the end-to-end hang-autopsy demo
(docs/OBSERVABILITY.md "Flight recorder & hang autopsy") — both ranks
run a telemetry-instrumented loop (arming the watchdog), rank 1 then
silently stops submitting; with NO operator action rank 0's watchdog
must write an autopsy bundle containing per-rank stacks, engine state
naming the missing rank/tensor, a flight-recorder dump, peer evidence
fetched over /debug/*, and a merged multi-rank Perfetto trace with
correlated collective spans.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import time  # noqa: E402

import numpy as np  # noqa: E402

from horovod_tpu.core.core_backend import CoreBackend  # noqa: E402
from horovod_tpu.ops.reduce_op import ReduceOp  # noqa: E402


def straggle(be, rank):
    delay = float(os.environ["HVD_TEST_STRAGGLER_SECS"])
    rounds = 3
    # warm-up: both ranks roughly in sync, clean slate for attribution
    be.allreduce_async("warm", np.ones(2, np.float32),
                       ReduceOp.SUM).wait(60)
    for i in range(rounds):
        if rank == 1:
            time.sleep(delay)  # rank 1 is deliberately the last announcer
        be.allreduce_async(f"slow_{i}", np.ones(4, np.float32),
                           ReduceOp.SUM).wait(60)
    be.barrier()
    s = be.stragglers()
    if rank == 0:
        # the coordinator saw every announcement: rank 1 must be charged
        # ~rounds * delay of peer wait, strictly more than rank 0
        r1 = s["ranks"].get("1")
        assert r1 is not None, s
        assert r1["held_count"] >= rounds, s
        min_wait = rounds * delay * 0.5
        assert r1["wait_seconds"] > min_wait, s
        r0 = s["ranks"].get("0", {"wait_seconds": 0.0})
        assert r1["wait_seconds"] > r0["wait_seconds"], s
        assert s["tensors_timed"] >= rounds, s
        assert s["total_wait_seconds"] >= r1["wait_seconds"], s
    else:
        # attribution is coordinator-only state
        assert s.get("ranks", {}) == {}, s
    be.barrier()
    be.shutdown()
    print(f"straggler worker {rank}: OK", flush=True)


def autopsy():
    """One stalled rank → rank 0 produces a self-contained autopsy."""
    import json

    import horovod_tpu as hvd
    from horovod_tpu.train.callbacks import TelemetryCallback

    hvd.init()
    rank = hvd.rank()
    tele = TelemetryCallback()  # arms the watchdog (env: 3s)
    assert tele.watchdog is not None and tele.watchdog.armed

    for i in range(3):  # healthy steps on every rank
        tele.on_step_begin()
        hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum,
                      name=f"step.{i}")
        tele.on_step_end()

    bundle = os.environ["HVD_TPU_AUTOPSY_DIR"]
    if rank == 1:
        # silently stop submitting; stay alive so /debug/* answers and
        # close the timeline shard so the merger sees a complete file
        from horovod_tpu.common.basics import _state
        _state.timeline.stop()
        time.sleep(25)
        print("autopsy worker 1: OK", flush=True)
        os._exit(0)

    # rank 0 enqueues a collective rank 1 never joins -> silent hang
    tele.on_step_begin()
    h = hvd.allreduce_async(np.ones(4, np.float32), op=hvd.Sum,
                            name="step.hang")
    try:
        h.wait(20)
        raise AssertionError("expected the collective to hang")
    except TimeoutError:
        pass

    # the watchdog (3s) must have fired DURING the hang, no operator
    # action — verify the bundle answers "which rank is stuck in what"
    assert tele.watchdog.trigger_count >= 1, "watchdog never fired"
    summary = json.load(open(os.path.join(bundle, "summary_rank0.json")))
    suspects = summary["suspects"]
    assert suspects, summary
    assert suspects[0]["tensor"] == "step.hang", suspects
    assert suspects[0]["missing_ranks"] == [1], suspects

    # goodput ledger rides the bundle (docs/OBSERVABILITY.md "Goodput
    # ledger"): the final snapshot is present (the telemetry loop's 3
    # healthy steps opened a window; the autopsy flushed it) and its
    # books CLOSE — categories sum to wall time within tolerance
    gp = summary["goodput"]
    assert gp is not None and gp["windows"] >= 1, summary
    assert gp["closed"] and not gp["books_violations"], gp
    assert abs(sum(gp["seconds"].values()) - gp["wall_s"]) <= \
        gp["tolerance"] * gp["wall_s"] + 0.01, gp

    stacks = open(os.path.join(bundle, "stacks_rank0.txt")).read()
    assert "Thread" in stacks or "File" in stacks, stacks[:200]

    flight = json.load(open(os.path.join(bundle, "flight_rank0.json")))
    kinds = {(e["kind"], e.get("name")) for e in flight["events"]}
    assert ("enqueue", "step.hang") in kinds, sorted(kinds)
    assert ("watchdog_trigger", None) in kinds, sorted(kinds)

    engine = json.load(open(os.path.join(bundle, "engine_rank0.json")))
    pend = [p for d in engine["engine_state"]["domains"]
            for p in d["pending"]]
    assert any(p["name"] == "step.hang" and p["missing_ranks"] == [1]
               for p in pend), pend
    # satellite: the stall inspector surfaced as counters (warn time 1s)
    assert engine["counters"]["stall_warnings"] >= 1, engine["counters"]
    assert engine["counters"]["stalled_tensors"] >= 1, engine["counters"]

    # peer evidence fetched from rank 1's /debug endpoints
    peer = open(os.path.join(bundle, "peer_rank1_stacks.txt")).read()
    assert "Thread" in peer or "File" in peer, peer[:200]
    assert os.path.exists(os.path.join(bundle, "peer_rank1_flight.json"))
    assert os.path.exists(os.path.join(bundle, "peer_rank1_engine.json"))

    # merged multi-rank trace: valid chrome JSON, >=2 process tracks,
    # the same collective span correlated across rank tracks
    trace = json.load(open(os.path.join(bundle, "merged_trace.json")))
    events = trace["traceEvents"]
    span_pids = {}
    for ev in events:
        span = (ev.get("args") or {}).get("span")
        if ev.get("ph") == "B" and span:
            span_pids.setdefault(span, set()).add(ev["pid"])
    pids = {ev["pid"] for ev in events if ev.get("ph") != "M"}
    assert len(pids) >= 2, pids
    correlated = [s for s, p in span_pids.items() if len(p) >= 2]
    assert any(s.startswith("step.") for s in correlated), \
        (sorted(span_pids), pids)

    print("autopsy worker 0: OK", flush=True)
    os._exit(0)  # skip atexit shutdown: rank 1 is gone, consensus can't


def main():
    if os.environ.get("HVD_TEST_AUTOPSY"):
        autopsy()
        return
    be = CoreBackend()
    rank = be.rank
    if os.environ.get("HVD_TEST_STRAGGLER_SECS"):
        straggle(be, rank)
        return
    if rank == 0:
        h = be.allreduce_async("lonely", np.ones(4, np.float32),
                               ReduceOp.SUM)
        try:
            h.wait(60)
            raise AssertionError("expected a stall-shutdown error")
        except RuntimeError as e:
            assert "stalled beyond" in str(e), e
    else:
        # submit the recovery tensor before rank 0's stall error fires
        # (shutdown is 4s; rank 0 joins at ~4s, well inside the window)
        time.sleep(3)
    # the domain must still be usable after the stall error
    out = be.allreduce_async("after", np.full(3, float(rank + 1),
                                              np.float32),
                             ReduceOp.SUM).wait(60)
    np.testing.assert_allclose(out, 3.0)
    be.barrier()
    be.shutdown()
    print(f"stall worker {rank}: OK", flush=True)


if __name__ == "__main__":
    main()
