"""Stall-shutdown worker: rank 0 submits a tensor rank 1 never does.
With HOROVOD_STALL_SHUTDOWN_TIME_SECONDS set, rank 0's wait must fail with
a clear stall error instead of hanging (reference:
stall_inspector.h shutdown path; here surfaced per-tensor as
HorovodInternalError). Afterwards the domain keeps working.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import time  # noqa: E402

import numpy as np  # noqa: E402

from horovod_tpu.core.core_backend import CoreBackend  # noqa: E402
from horovod_tpu.ops.reduce_op import ReduceOp  # noqa: E402


def main():
    be = CoreBackend()
    rank = be.rank
    if rank == 0:
        h = be.allreduce_async("lonely", np.ones(4, np.float32),
                               ReduceOp.SUM)
        try:
            h.wait(60)
            raise AssertionError("expected a stall-shutdown error")
        except RuntimeError as e:
            assert "stalled beyond" in str(e), e
    else:
        # submit the recovery tensor before rank 0's stall error fires
        # (shutdown is 4s; rank 0 joins at ~4s, well inside the window)
        time.sleep(3)
    # the domain must still be usable after the stall error
    out = be.allreduce_async("after", np.full(3, float(rank + 1),
                                              np.float32),
                             ReduceOp.SUM).wait(60)
    np.testing.assert_allclose(out, 3.0)
    be.barrier()
    be.shutdown()
    print(f"stall worker {rank}: OK", flush=True)


if __name__ == "__main__":
    main()
