"""Stall-shutdown worker: rank 0 submits a tensor rank 1 never does.
With HOROVOD_STALL_SHUTDOWN_TIME_SECONDS set, rank 0's wait must fail with
a clear stall error instead of hanging (reference:
stall_inspector.h shutdown path; here surfaced per-tensor as
HorovodInternalError). Afterwards the domain keeps working.

Straggler mode (HVD_TEST_STRAGGLER_SECS set): instead of the stall
scenario, rank 1 deliberately sleeps before each submission and the
coordinator's rank-attributed negotiation-wait report
(``CoreBackend.stragglers`` → ``hvd_stragglers_json``) must name rank 1
as the rank everyone waited on (docs/OBSERVABILITY.md).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import time  # noqa: E402

import numpy as np  # noqa: E402

from horovod_tpu.core.core_backend import CoreBackend  # noqa: E402
from horovod_tpu.ops.reduce_op import ReduceOp  # noqa: E402


def straggle(be, rank):
    delay = float(os.environ["HVD_TEST_STRAGGLER_SECS"])
    rounds = 3
    # warm-up: both ranks roughly in sync, clean slate for attribution
    be.allreduce_async("warm", np.ones(2, np.float32),
                       ReduceOp.SUM).wait(60)
    for i in range(rounds):
        if rank == 1:
            time.sleep(delay)  # rank 1 is deliberately the last announcer
        be.allreduce_async(f"slow_{i}", np.ones(4, np.float32),
                           ReduceOp.SUM).wait(60)
    be.barrier()
    s = be.stragglers()
    if rank == 0:
        # the coordinator saw every announcement: rank 1 must be charged
        # ~rounds * delay of peer wait, strictly more than rank 0
        r1 = s["ranks"].get("1")
        assert r1 is not None, s
        assert r1["held_count"] >= rounds, s
        min_wait = rounds * delay * 0.5
        assert r1["wait_seconds"] > min_wait, s
        r0 = s["ranks"].get("0", {"wait_seconds": 0.0})
        assert r1["wait_seconds"] > r0["wait_seconds"], s
        assert s["tensors_timed"] >= rounds, s
        assert s["total_wait_seconds"] >= r1["wait_seconds"], s
    else:
        # attribution is coordinator-only state
        assert s.get("ranks", {}) == {}, s
    be.barrier()
    be.shutdown()
    print(f"straggler worker {rank}: OK", flush=True)


def main():
    be = CoreBackend()
    rank = be.rank
    if os.environ.get("HVD_TEST_STRAGGLER_SECS"):
        straggle(be, rank)
        return
    if rank == 0:
        h = be.allreduce_async("lonely", np.ones(4, np.float32),
                               ReduceOp.SUM)
        try:
            h.wait(60)
            raise AssertionError("expected a stall-shutdown error")
        except RuntimeError as e:
            assert "stalled beyond" in str(e), e
    else:
        # submit the recovery tensor before rank 0's stall error fires
        # (shutdown is 4s; rank 0 joins at ~4s, well inside the window)
        time.sleep(3)
    # the domain must still be usable after the stall error
    out = be.allreduce_async("after", np.full(3, float(rank + 1),
                                              np.float32),
                             ReduceOp.SUM).wait(60)
    np.testing.assert_allclose(out, 3.0)
    be.barrier()
    be.shutdown()
    print(f"stall worker {rank}: OK", flush=True)


if __name__ == "__main__":
    main()
