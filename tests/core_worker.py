"""Worker script for multi-process core tests (launched by
test_core_multiprocess.py with HOROVOD_RANK/SIZE env). The numpy-only analog
of the reference's test/parallel suite bodies."""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from horovod_tpu.core.core_backend import CoreBackend  # noqa: E402
from horovod_tpu.ops.reduce_op import ReduceOp  # noqa: E402


def main():
    rank = int(os.environ["HOROVOD_RANK"])
    size = int(os.environ["HOROVOD_SIZE"])
    be = CoreBackend()
    assert be.rank == rank and be.size == size, (be.rank, be.size)

    # -- allreduce sum across dtypes -----------------------------------------
    for dtype in (np.float32, np.float64, np.int32, np.int64):
        x = (np.arange(17, dtype=dtype) + rank)
        out = be.allreduce_async(f"ar.{np.dtype(dtype).name}", x,
                                 ReduceOp.SUM).wait()
        expect = sum((np.arange(17, dtype=dtype) + r) for r in range(size))
        np.testing.assert_allclose(out, expect, rtol=1e-6)

    # -- average + prescale/postscale ----------------------------------------
    x = np.full((8,), float(rank + 1), np.float32)
    out = be.allreduce_async("ar.avg", x, ReduceOp.AVERAGE,
                             prescale=2.0, postscale=0.5).wait()
    expect = np.full((8,), np.mean([(r + 1) * 2.0 for r in range(size)]) * 0.5,
                     np.float32)
    np.testing.assert_allclose(out, expect, rtol=1e-6)

    # -- min / max -------------------------------------------------------------
    x = np.asarray([rank, -rank, 10 + rank], np.float32)
    mn = be.allreduce_async("ar.min", x, ReduceOp.MIN).wait()
    mx = be.allreduce_async("ar.max", x, ReduceOp.MAX).wait()
    np.testing.assert_allclose(mn, [0, -(size - 1), 10])
    np.testing.assert_allclose(mx, [size - 1, 0, 10 + size - 1])

    # -- grouped (fused) allreduce --------------------------------------------
    vals = [np.full((5,), float(rank), np.float32),
            np.full((1000,), 1.0, np.float32),
            np.full((3, 3), float(rank * 2), np.float32)]
    outs = be.grouped_allreduce_async(
        [f"g.{i}" for i in range(3)], vals, ReduceOp.SUM).wait()
    np.testing.assert_allclose(outs[0], np.full((5,), sum(range(size))))
    np.testing.assert_allclose(outs[1], np.full((1000,), float(size)))
    np.testing.assert_allclose(outs[2],
                               np.full((3, 3), 2.0 * sum(range(size))))

    # -- bfloat16 via raw uint16 view is exercised through jax in other tests;
    # float16 here
    x = np.full((64,), 0.5, np.float16) * (rank + 1)
    out = be.allreduce_async("ar.f16", x, ReduceOp.SUM).wait()
    np.testing.assert_allclose(out.astype(np.float32),
                               np.full((64,), 0.5 * sum(r + 1 for r in
                                                        range(size))),
                               rtol=1e-2)

    # -- allgather with ragged first dims --------------------------------------
    rows = rank + 1
    x = np.arange(rows * 2, dtype=np.float32).reshape(rows, 2) + 100 * rank
    out = be.allgather_async("ag", x).wait()
    expect = np.concatenate([
        np.arange((r + 1) * 2, dtype=np.float32).reshape(r + 1, 2) + 100 * r
        for r in range(size)])
    np.testing.assert_allclose(out, expect)

    # -- broadcast -------------------------------------------------------------
    for root in range(size):
        x = (np.arange(6, dtype=np.float64) * (rank + 1))
        out = be.broadcast_async(f"bc.{root}", x, root).wait()
        np.testing.assert_allclose(out, np.arange(6, dtype=np.float64) *
                                   (root + 1))

    # -- alltoall with uneven splits -------------------------------------------
    # rank r sends (i+1) rows of value r*10+i to rank i
    splits = [i + 1 for i in range(size)]
    total = sum(splits)
    sendbuf = np.concatenate([
        np.full((i + 1, 2), rank * 10 + i, np.float32) for i in range(size)])
    assert sendbuf.shape[0] == total
    out, recv_splits = be.alltoall_async("a2a", sendbuf, splits).wait()
    assert list(recv_splits) == [rank + 1] * size
    expect = np.concatenate([
        np.full((rank + 1, 2), r * 10 + rank, np.float32)
        for r in range(size)])
    np.testing.assert_allclose(out, expect)

    # -- barrier ----------------------------------------------------------------
    be.barrier()

    # -- process set (first two ranks) -------------------------------------------
    if size >= 2:
        sub = be.make_subset([0, 1])
        if rank in (0, 1):
            x = np.full((4,), float(rank + 5), np.float32)
            out = sub.allreduce_async("ps.ar", x, ReduceOp.SUM).wait()
            np.testing.assert_allclose(out, np.full((4,), 5.0 + 6.0))
        be.barrier()

    # -- join: odd ranks join early; even ranks allreduce once more -------------
    if size >= 2:
        if rank % 2 == 1:
            last = be.join()
        else:
            x = np.full((4,), 1.0, np.float32)
            out = be.allreduce_async("post_join", x, ReduceOp.SUM).wait()
            # joined ranks contribute zeros
            n_even = (size + 1) // 2
            np.testing.assert_allclose(out, np.full((4,), float(n_even)))
            last = be.join()
        assert isinstance(last, int)

    # sustained traffic window (autotune tests need enough seconds of
    # scored collectives for samples to land). When an autotune log is
    # expected, keep the traffic flowing until rank 0 sees a recorded
    # sample (bounded) — a fixed window is flaky under CI load on a
    # 1-core box; the stop flag rides the collective itself.
    extra = float(os.environ.get("HVD_TEST_TRAFFIC_SECONDS", "0"))
    if extra > 0:
        import time
        log_path = os.environ.get(
            "HVD_TPU_AUTOTUNE_LOG",
            os.environ.get("HOROVOD_AUTOTUNE_LOG", ""))
        # rows to wait for: header + N samples (categorical-dim tests need
        # several tuned samples so the GP explores the binary knobs)
        want_rows = 1 + int(os.environ.get("HVD_TEST_AUTOTUNE_MIN_SAMPLES",
                                           "1"))
        limit = max(extra, 60.0) if log_path else extra
        deadline = time.monotonic() + limit
        i = 0
        while time.monotonic() < deadline:
            stop = 0.0
            if rank == 0 and log_path and os.path.exists(log_path):
                with open(log_path) as f:
                    stop = 1.0 if len(f.readlines()) >= want_rows else 0.0
            out = be.allreduce_async(f"traffic.{i}",
                                     np.full(4096, stop, np.float32),
                                     ReduceOp.MAX).wait()
            i += 1
            if log_path and float(np.asarray(out)[0]) >= 1.0:
                break  # a sample is on disk; the assertion is satisfied

    if os.environ.get("HVD_TEST_EXPECT_HIER_AG"):
        c = be.counters()
        assert c["hier_allgathers"] > 0, c  # two-level path actually ran
    be.shutdown()
    print(f"worker {rank}: OK")


if __name__ == "__main__":
    main()
