"""Worker validating the C++ VHDD Adasum against the Python tree oracle
(reference analog: test/parallel/test_adasum_*.py numeric checks)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""

import numpy as np  # noqa: E402

from horovod_tpu.core.core_backend import CoreBackend  # noqa: E402
from horovod_tpu.ops.reduce_op import ReduceOp  # noqa: E402


def main():
    be = CoreBackend()
    rank, size = be.rank, be.size

    # 1) identical inputs: Adasum(a, a, ...) == a (idempotent)
    a = np.linspace(1, 2, 32).astype(np.float32)
    out = be.allreduce_async("ad.same", a.copy(), ReduceOp.ADASUM).wait(60)
    np.testing.assert_allclose(out, a, rtol=1e-5)

    # 2) orthogonal inputs: Adasum == plain sum
    x = np.zeros(size * 4, np.float32)
    x[rank * 4:(rank + 1) * 4] = rank + 1.0
    out = be.allreduce_async("ad.orth", x, ReduceOp.ADASUM).wait(60)
    expect = np.concatenate([np.full(4, r + 1.0) for r in range(size)])
    np.testing.assert_allclose(out, expect, rtol=1e-5)

    # 3) random inputs: match the Python binary-tree oracle
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from horovod_tpu.ops.adasum import adasum_combine, adasum_tree_reduce

    def vhdd_oracle(contribs):
        """Mirror the C++ structure: fold extras onto partners, then the
        power-of-two binary tree (for pow2 sizes this IS the plain tree)."""
        p = len(contribs)
        pow2 = 1
        while pow2 * 2 <= p:
            pow2 *= 2
        folded = []
        for i in range(pow2):
            c = jnp.asarray(contribs[i])
            if i < p - pow2:
                c = adasum_combine(c, jnp.asarray(contribs[i + pow2]))
            folded.append(c)
        return np.asarray(adasum_tree_reduce(jnp.stack(folded)))

    rng = np.random.RandomState(7)
    all_contribs = rng.randn(size, 64).astype(np.float32)
    mine = all_contribs[rank].copy()
    out = be.allreduce_async("ad.rand", mine, ReduceOp.ADASUM).wait(60)
    oracle = vhdd_oracle(all_contribs)
    np.testing.assert_allclose(out, oracle, rtol=1e-4, atol=1e-5)

    # 4) float64 path
    out = be.allreduce_async("ad.f64", all_contribs[rank].astype(np.float64),
                             ReduceOp.ADASUM).wait(60)
    np.testing.assert_allclose(out, oracle, rtol=1e-4, atol=1e-5)

    be.shutdown()
    print(f"adasum worker {rank}: OK", flush=True)


if __name__ == "__main__":
    main()
