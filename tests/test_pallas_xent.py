"""Pallas fused softmax-xent kernel vs the XLA/optax oracle (interpret
mode on the CPU mesh; the real-TPU path is exercised by bench/models)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax

from horovod_tpu.ops.pallas_xent import fused_softmax_xent


def _case(n=256, v=1024, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    logits = jnp.asarray(rng.randn(n, v), dtype) * 2.0
    labels = jnp.asarray(rng.randint(0, v, (n,)), jnp.int32)
    return logits, labels


def _oracle(logits, labels):
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), labels)


def test_fused_xent_matches_oracle():
    logits, labels = _case()
    out = fused_softmax_xent(logits, labels, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_oracle(
        logits, labels)), rtol=1e-5, atol=1e-5)


def test_fused_xent_pads_odd_vocab():
    # 30522-style vocab: not a BLOCK_V multiple -> NEG_INF padding path
    logits, labels = _case(n=128, v=700)
    out = fused_softmax_xent(logits, labels, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_oracle(
        logits, labels)), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("v", [1024, 700])  # 700: NEG_INF-padded path
def test_fused_xent_grads_match_oracle(v):
    logits, labels = _case(n=128, v=v)

    def f_fused(lg):
        return jnp.mean(fused_softmax_xent(lg, labels, interpret=True))

    def f_ref(lg):
        return jnp.mean(_oracle(lg, labels))

    g_fused = jax.grad(f_fused)(logits)
    g_ref = jax.grad(f_ref)(logits)
    assert np.isfinite(np.asarray(g_fused)).all()
    np.testing.assert_allclose(np.asarray(g_fused), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-6)


def test_fused_xent_leading_shape_and_bf16():
    # [B, S, V] logits with bf16 storage: per-token losses keep shape
    logits, labels = _case(n=256, v=512, dtype=jnp.bfloat16)
    logits3 = logits.reshape(2, 128, 512)
    labels3 = labels.reshape(2, 128)
    out = fused_softmax_xent(logits3, labels3, interpret=True)
    assert out.shape == (2, 128)
    ref = _oracle(logits3, labels3)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)  # bf16 inputs


def test_fused_xent_cpu_fallback_without_interpret():
    # CPU backend without interpret -> XLA fallback, identical numbers
    logits, labels = _case(n=64, v=256)
    out = fused_softmax_xent(logits, labels)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_oracle(
        logits, labels)), rtol=1e-5, atol=1e-5)


def test_fused_xent_out_of_range_label_consistent():
    """Out-of-range labels (ignore-id style) give loss = lse on BOTH the
    kernel and the fallback — a CPU debug run reproduces the TPU loss."""
    logits, labels = _case(n=128, v=512)
    bad = labels.at[0].set(99999).at[1].set(-7)
    out_kernel = fused_softmax_xent(logits, bad, interpret=True)
    out_fb = fused_softmax_xent(logits[:100], bad[:100])  # untiled -> fb
    lse = jax.scipy.special.logsumexp(logits.astype(jnp.float32), -1)
    np.testing.assert_allclose(np.asarray(out_kernel[0]), float(lse[0]),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out_kernel[1]), float(lse[1]),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out_fb[:2]),
                               np.asarray(out_kernel[:2]), rtol=1e-5)


def test_fused_xent_untiled_rows_fall_back():
    # n not a BLOCK_N multiple -> fallback still correct
    logits, labels = _case(n=37, v=512)
    out = fused_softmax_xent(logits, labels, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_oracle(
        logits, labels)), rtol=1e-5, atol=1e-5)
