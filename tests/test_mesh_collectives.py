"""Collective numerics over the virtual 8-device mesh — the TPU analog of the
reference's test/parallel suite (multi-rank numeric equality of collectives,
e.g. test/parallel/test_torch.py)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from horovod_tpu._compat import shard_map
from horovod_tpu.ops import mesh_collectives as mc
from horovod_tpu.ops.reduce_op import ReduceOp
from horovod_tpu.parallel import build_mesh


@pytest.fixture(scope="module")
def mesh():
    return build_mesh(dp=4, tp=2)


DTYPES = [jnp.float32, jnp.bfloat16, jnp.int32]


@pytest.mark.parametrize("dtype", DTYPES)
def test_device_allreduce_sum(mesh, dtype):
    x = jnp.arange(4 * 6, dtype=dtype).reshape(4, 6)
    out = mc.device_allreduce(x, mesh, "dp", ReduceOp.SUM)
    np.testing.assert_allclose(np.asarray(out, np.float64),
                               np.asarray(x, np.float64).sum(0))


def test_device_allreduce_ops(mesh):
    x = jnp.asarray(np.random.RandomState(0).randn(4, 8), jnp.float32)
    np_x = np.asarray(x)
    np.testing.assert_allclose(
        np.asarray(mc.device_allreduce(x, mesh, "dp", ReduceOp.AVERAGE)),
        np_x.mean(0), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(mc.device_allreduce(x, mesh, "dp", ReduceOp.MIN)),
        np_x.min(0))
    np.testing.assert_allclose(
        np.asarray(mc.device_allreduce(x, mesh, "dp", ReduceOp.MAX)),
        np_x.max(0))
    np.testing.assert_allclose(
        np.asarray(mc.device_allreduce(x, mesh, "dp", ReduceOp.PRODUCT)),
        np_x.prod(0), rtol=1e-5)


def test_device_allreduce_adasum_is_not_sum(mesh):
    """ADASUM over a mesh axis must apply the VHDD scaled-add combine, not
    silently psum (ADVICE r1)."""
    from horovod_tpu.ops.adasum import adasum_tree_reduce
    x = jnp.asarray(np.random.RandomState(1).randn(4, 8), jnp.float32)
    out = mc.device_allreduce(x, mesh, "dp", ReduceOp.ADASUM)
    expect = np.asarray(adasum_tree_reduce(x))
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5)
    assert not np.allclose(np.asarray(out), np.asarray(x).sum(0))


def test_device_allgather(mesh):
    x = jnp.arange(8.0).reshape(4, 2)
    out = mc.device_allgather(x, mesh, "dp")
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


@pytest.mark.parametrize("root", [0, 2, 3])
def test_device_broadcast(mesh, root):
    x = jnp.arange(4 * 3.0).reshape(4, 3)
    out = mc.device_broadcast(x, mesh, root=root, axis_name="dp")
    np.testing.assert_allclose(np.asarray(out), np.asarray(x)[root])


def test_device_alltoall(mesh):
    n = 4
    x = jnp.arange(n * n, dtype=jnp.float32).reshape(n * n, 1)
    out = mc.device_alltoall(x, mesh, "dp")
    expect = (np.arange(n * n).reshape(n, n).T.reshape(n * n, 1))
    np.testing.assert_allclose(np.asarray(out), expect)


def test_device_reduce_scatter(mesh):
    x = jnp.asarray(np.random.RandomState(1).randn(4, 8), jnp.float32)
    out = mc.device_reduce_scatter(x, mesh, "dp")
    # Each shard i holds sum over contributors of rows [2i:2i+2]; the global
    # result is the full row-sum (tiled scatter then re-concat).
    np.testing.assert_allclose(np.asarray(out).reshape(-1),
                               np.asarray(x).sum(0), rtol=1e-6)


def test_ring_shift_spmd(mesh):
    from functools import partial
    from jax.sharding import PartitionSpec as P

    @partial(shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    def shift(x):
        return mc.pring_shift(x, "dp", 1)

    x = jnp.arange(4.0)
    out = shift(x)
    np.testing.assert_allclose(np.asarray(out), np.roll(np.arange(4.0), 1))


def test_multiaxis_mesh_axes_sizes(mesh):
    assert mesh.shape["dp"] == 4
    assert mesh.shape["tp"] == 2
