"""ResNet model family tests (reference analog: the synthetic benchmark
models in examples/; here unit-level so the bench harness model is
covered off-TPU), including the MLPerf-style space-to-depth stem."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax

from horovod_tpu.models.resnet import (ResNet, ResNet50, batch_sharding,
                                       create_resnet_state,
                                       make_resnet_train_step,
                                       space_to_depth)


def test_space_to_depth_layout():
    x = jnp.arange(2 * 4 * 4 * 3, dtype=jnp.float32).reshape(2, 4, 4, 3)
    y = space_to_depth(x, 2)
    assert y.shape == (2, 2, 2, 12)
    # block (0,0) of image 0: pixels (0,0),(0,1),(1,0),(1,1) channel-major
    np.testing.assert_array_equal(
        np.asarray(y)[0, 0, 0],
        np.concatenate([np.asarray(x)[0, 0, 0], np.asarray(x)[0, 0, 1],
                        np.asarray(x)[0, 1, 0], np.asarray(x)[0, 1, 1]]))


@pytest.mark.parametrize("stem", ["conv", "s2d"])
def test_resnet_stems_same_geometry(stem):
    """Both stems produce the identical downstream geometry (112x112x64
    after the stem at 224 input; logits shape equal)."""
    model = ResNet([1, 1, 1, 1], num_classes=10, dtype=jnp.float32,
                   stem=stem)
    x = jnp.zeros((2, 64, 64, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    logits, _ = model.apply(variables, x, train=True,
                            mutable=["batch_stats"])
    assert logits.shape == (2, 10)


def test_resnet101_deeper_than_50():
    """ResNet-101 shares the implementation; only stage depths differ
    (reference benchmark trio: docs/benchmarks.rst:13-14)."""
    from horovod_tpu.models.resnet import ResNet101
    assert ResNet101().stage_sizes == [3, 4, 23, 3]
    assert ResNet50().stage_sizes == [3, 4, 6, 3]


def test_vgg16_trains(hvd):
    """VGG-16 (the reference's gradient-bandwidth stress model) trains
    under the same GSPMD-auto contract as the ResNet family."""
    from horovod_tpu.models.vgg import VGG, create_vgg_state, \
        make_vgg_train_step
    mesh = hvd.build_mesh(dp=-1)
    # thin VGG (same topology, fewer channels) keeps the CPU test fast
    model = VGG(stages=((1, 8), (1, 16), (1, 16), (1, 32), (1, 32)),
                num_classes=8, dtype=jnp.float32, dropout=0.0)
    params = create_vgg_state(model, jax.random.PRNGKey(0), image_size=64,
                              mesh=mesh)
    tx = optax.sgd(0.05, momentum=0.9)
    opt_state = jax.jit(tx.init)(params)
    step = make_vgg_train_step(model, tx, mesh)
    rng = np.random.RandomState(0)
    images = jax.device_put(
        jnp.asarray(rng.rand(16, 64, 64, 3), jnp.float32),
        batch_sharding(mesh))
    labels = jax.device_put(jnp.asarray(rng.randint(0, 8, (16,)), jnp.int32),
                            batch_sharding(mesh))
    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, images, labels)
        loss.block_until_ready()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


@pytest.mark.slow  # ~12s double compile; tier-1 budget (models tier
#                    runs it unfiltered)
def test_vgg_scan_steps_matches_sequential_dropout_indices(hvd):
    """The INDEXED scan variant (dropout models): scanned step i must use
    dropout index step_idx * scan_steps + i, so a scan_steps=2 dispatch
    with step_idx=0 equals sequential calls with step_idx=0 then 1."""
    from horovod_tpu.models.vgg import VGG, create_vgg_state, \
        make_vgg_train_step
    mesh = hvd.build_mesh(dp=-1)
    # real dropout so identical masks would be detectable
    model = VGG(stages=((1, 8), (1, 16), (1, 16), (1, 32), (1, 32)),
                num_classes=8, dtype=jnp.float32, dropout=0.5)
    tx = optax.sgd(0.05, momentum=0.9)
    rng = np.random.RandomState(0)
    images = jax.device_put(
        jnp.asarray(rng.rand(16, 64, 64, 3), jnp.float32),
        batch_sharding(mesh))
    labels = jax.device_put(jnp.asarray(rng.randint(0, 8, (16,)), jnp.int32),
                            batch_sharding(mesh))

    def init():
        params = create_vgg_state(model, jax.random.PRNGKey(0),
                                  image_size=64, mesh=mesh)
        return params, jax.jit(tx.init)(params)

    step1 = make_vgg_train_step(model, tx, mesh)
    p, o = init()
    for i in range(2):
        p, o, loss_seq = step1(p, o, images, labels, step_idx=i)
        loss_seq.block_until_ready()

    step2 = make_vgg_train_step(model, tx, mesh, scan_steps=2)
    p2, o2 = init()
    p2, o2, loss_scan = step2(p2, o2, images, labels, step_idx=0)
    loss_scan.block_until_ready()

    np.testing.assert_allclose(float(loss_scan), float(loss_seq), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


@pytest.mark.slow  # ~19s double compile; tier-1 budget (models tier
#                    runs it unfiltered)
def test_scan_steps_matches_sequential(hvd):
    """scan_steps=2 (one dispatch, two in-graph optimizer steps) must
    produce the same params/loss as two sequential scan_steps=1 calls —
    the bench's multi-step chain changes dispatch count, not training."""
    mesh = hvd.build_mesh(dp=-1)
    model = ResNet([1, 1, 1, 1], num_classes=8, dtype=jnp.float32)
    tx = optax.sgd(0.05, momentum=0.9)
    rng = np.random.RandomState(0)
    images = jax.device_put(
        jnp.asarray(rng.rand(16, 64, 64, 3), jnp.float32),
        batch_sharding(mesh))
    labels = jax.device_put(jnp.asarray(rng.randint(0, 8, (16,)), jnp.int32),
                            batch_sharding(mesh))

    def init():
        params, batch_stats = create_resnet_state(
            model, jax.random.PRNGKey(0), image_size=64, mesh=mesh)
        return params, batch_stats, jax.jit(tx.init)(params)

    step1 = make_resnet_train_step(model, tx, mesh)
    p, bs, o = init()
    for _ in range(2):
        p, bs, o, loss_seq = step1(p, bs, o, images, labels)
        loss_seq.block_until_ready()

    step2 = make_resnet_train_step(model, tx, mesh, scan_steps=2)
    p2, bs2, o2 = init()
    p2, bs2, o2, loss_scan = step2(p2, bs2, o2, images, labels)
    loss_scan.block_until_ready()

    np.testing.assert_allclose(float(loss_scan), float(loss_seq),
                               rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


@pytest.mark.slow  # ~12s double compile; tier-1 budget (models tier
#                    runs it unfiltered)
def test_resnet_remat_matches_plain(hvd):
    """remat=True (jax.checkpoint per block) changes memory, not math:
    one train step produces the same loss and params as the plain model."""
    mesh = hvd.build_mesh(dp=-1)
    tx = optax.sgd(0.05, momentum=0.9)
    rng = np.random.RandomState(0)
    images = jax.device_put(
        jnp.asarray(rng.rand(8, 64, 64, 3), jnp.float32),
        batch_sharding(mesh))
    labels = jax.device_put(jnp.asarray(rng.randint(0, 8, (8,)), jnp.int32),
                            batch_sharding(mesh))

    outs = []
    for remat in (False, True):
        model = ResNet([1, 1, 1, 1], num_classes=8, dtype=jnp.float32,
                       remat=remat)
        params, batch_stats = create_resnet_state(
            model, jax.random.PRNGKey(0), image_size=64, mesh=mesh)
        step = make_resnet_train_step(model, tx, mesh)
        p, bs, _, loss = step(params, batch_stats,
                              jax.jit(tx.init)(params), images, labels)
        loss.block_until_ready()
        outs.append((p, bs, float(loss)))
    (p0, bs0, l0), (p1, bs1, l1) = outs
    np.testing.assert_allclose(l0, l1, rtol=1e-6)
    # params AND the mutable batch_stats (running mean/var updated inside
    # the checkpointed blocks) must agree
    for tree0, tree1 in ((p0, p1), (bs0, bs1)):
        for a, b in zip(jax.tree_util.tree_leaves(tree0),
                        jax.tree_util.tree_leaves(tree1)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-7)


def test_resnet_s2d_trains(hvd):
    mesh = hvd.build_mesh(dp=-1)
    model = ResNet([1, 1, 1, 1], num_classes=8, dtype=jnp.float32,
                   stem="s2d")
    params, batch_stats = create_resnet_state(
        model, jax.random.PRNGKey(0), image_size=64, mesh=mesh)
    tx = optax.sgd(0.05, momentum=0.9)
    opt_state = jax.jit(tx.init)(params)
    step = make_resnet_train_step(model, tx, mesh)
    rng = np.random.RandomState(0)
    images = jax.device_put(
        jnp.asarray(rng.rand(16, 64, 64, 3), jnp.float32),
        batch_sharding(mesh))
    labels = jax.device_put(jnp.asarray(rng.randint(0, 8, (16,)), jnp.int32),
                            batch_sharding(mesh))
    losses = []
    for _ in range(5):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, images, labels)
        loss.block_until_ready()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
