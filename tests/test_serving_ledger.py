"""Serving request ledger (ISSUE 19, docs/OBSERVABILITY.md "Serving
request ledger").

Fast battery: the shared nearest-rank quantile (one implementation for
the SLO plane, the rollout comparator and ``check_bench --serving`` —
p50/p99 semantics pinned here), close_books/residual/dominant-stage
units, the bounded tail-exemplar ring + ``/debug/exemplars`` +
autopsy dump, WindowBooks window accounting, burn-rate SLO hysteresis
(one finding per episode, re-arm under 1x fast burn), the stale-gauge
idle-roll rule (stage-share gauges ZERO on an idle window, never
frozen), batch-size buckets widening past 128 with the slot count,
the ttft_drift / queue_growth detectors, books closing end-to-end
through a real router+replica pair (aggregate residual < 10%, exemplar
trace ids resolving to spans), generate-plane stage coverage including
the swap_pause bracket and the slot_wait-vs-page_wait discrimination,
and the chaos acceptance pair: injected ``serving.kv`` starvation must
surface as a ``kv_thrash`` finding naming ``page_wait``, and a clean
control run of the same length must produce none.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean():
    from horovod_tpu import chaos
    from horovod_tpu.serving import ledger
    chaos.uninstall()
    ledger.reset()
    yield
    chaos.uninstall()
    ledger.reset()


# -- the one quantile ---------------------------------------------------------
def test_quantile_nearest_rank_semantics_pinned():
    """THE shared quantile: nearest-rank over a sorted sequence,
    fraction in [0, 1].  p50 of 1..100 is 51 (index round(.5*99)=50),
    p99 is 99 (index 98) — pinned so the SLO plane, the comparator and
    the bench gate can never drift apart on what "p99" means."""
    from horovod_tpu.serving.ledger import quantile
    assert quantile([], 0.99) == 0.0
    assert quantile([5.0], 0.5) == 5.0
    vals = [float(i) for i in range(1, 101)]
    assert quantile(vals, 0.0) == 1.0
    assert quantile(vals, 0.50) == 51.0
    assert quantile(vals, 0.99) == 99.0
    assert quantile(vals, 1.0) == 100.0
    # two points: p99 is the max, p50 the second (round half up)
    assert quantile([1.0, 9.0], 0.99) == 9.0


def test_quantile_is_shared_across_all_three_call_sites():
    """serving.metrics.percentile and the rollout comparator's
    percentile must BE ledger.quantile (not copies), and check_bench's
    replay gate must import the same function."""
    from horovod_tpu.serving import ledger
    from horovod_tpu.serving import metrics as smetrics
    from horovod_tpu.serving.rollout import comparator
    assert smetrics.percentile is ledger.quantile
    assert comparator.percentile is ledger.quantile
    src = open(os.path.join(REPO, "ci", "check_bench.py")).read()
    assert "from horovod_tpu.serving.ledger import quantile" in src


# -- close_books units --------------------------------------------------------
def test_close_books_names_the_residual():
    from horovod_tpu.serving.ledger import (close_books, dominant_stage,
                                            residual_fraction)
    stages = close_books(1.0, {"queue": 0.2, "forward": 0.5})
    assert stages["unattributed"] == pytest.approx(0.3)
    assert sum(stages.values()) == pytest.approx(1.0)
    # a clock race (negative stage) is clamped, never negative time;
    # over-attribution clamps the residual at zero
    stages = close_books(0.4, {"forward": 0.5, "queue": -0.1})
    assert stages["queue"] == 0.0 and stages["unattributed"] == 0.0
    # a caller-supplied residual is recomputed, not trusted
    stages = close_books(1.0, {"forward": 0.9, "unattributed": 9.0})
    assert stages["unattributed"] == pytest.approx(0.1)
    assert residual_fraction(1.0, {"forward": 0.9}) == pytest.approx(0.1)
    assert residual_fraction(0.0, {}) == 0.0
    assert dominant_stage({"queue": 0.2, "forward": 0.5}) == "forward"
    # the residual can never be "dominant" — it is the absence of an
    # answer, not an answer
    assert dominant_stage({"unattributed": 9.0}) is None
    assert dominant_stage({}) is None


def test_stage_catalog_is_closed_and_ordered():
    from horovod_tpu.serving import ledger
    assert ledger.STAGES[-1] == ledger.RESIDUAL
    assert set(ledger.STAGES) == (set(ledger.ROUTER_STAGES)
                                  | set(ledger.REPLICA_STAGES)
                                  | set(ledger.GENERATE_STAGES)
                                  | {ledger.RESIDUAL})
    assert len(set(ledger.STAGES)) == len(ledger.STAGES)


# -- exemplar ring ------------------------------------------------------------
def test_exemplar_ring_is_bounded_oldest_evicted():
    from horovod_tpu.serving.ledger import ExemplarRing
    ring = ExemplarRing(capacity=4)
    for i in range(10):
        ring.add({"e2e_s": float(i), "req_id": f"r{i}"})
    assert len(ring) == 4
    held = {e["req_id"] for e in ring.snapshot()}
    assert held == {"r6", "r7", "r8", "r9"}  # oldest evicted first
    assert [e["req_id"] for e in ring.worst(2)] == ["r9", "r8"]
    ring.clear()
    assert len(ring) == 0


def test_exemplars_reach_debug_endpoint_and_autopsy(tmp_path, monkeypatch):
    """The process-wide ring is what ``/debug/exemplars`` serves and
    what the autopsy bundle dumps as ``exemplars_rank<r>.json``."""
    import urllib.request
    from horovod_tpu.diagnostics.autopsy import write_autopsy
    from horovod_tpu.metrics.exporter import MetricsExporter
    from horovod_tpu.serving.ledger import default_ring
    default_ring().add({"e2e_s": 0.5, "trace": "t-123",
                        "stages": {"forward": 0.4, "unattributed": 0.1},
                        "dominant_stage": "forward"})
    exp = MetricsExporter(port=0)
    exp.start()
    try:
        url = f"http://127.0.0.1:{exp.port}/debug/exemplars"
        with urllib.request.urlopen(url, timeout=5) as r:
            doc = json.loads(r.read())
        assert doc["exemplars"][0]["trace"] == "t-123"
        assert doc["exemplars"][0]["dominant_stage"] == "forward"
    finally:
        exp.stop()
    bundle = write_autopsy(out_dir=str(tmp_path), reason="test")
    dumped = json.load(open(os.path.join(bundle, "exemplars_rank0.json")))
    assert dumped["exemplars"][0]["trace"] == "t-123"
    summary = json.load(open(os.path.join(bundle, "summary_rank0.json")))
    assert summary["exemplars"] == 1


# -- window books -------------------------------------------------------------
def test_window_books_sums_shares_ttft_and_worst():
    from horovod_tpu.serving.ledger import WindowBooks
    b = WindowBooks(exemplars_per_window=2)
    b.add(1.0, {"queue": 0.3, "forward": 0.6}, trace="fast",
          req_id="a", ttft_s=0.01)
    b.add(3.0, {"queue": 2.4, "forward": 0.3}, trace="slow",
          req_id="b", version=7, ttft_s=0.09)
    b.add(0.5, {"forward": 0.5}, trace="tiny", req_id="c", ttft_s=0.01)
    doc, worst = b.close()
    assert doc["stages"]["queue"] == pytest.approx(2.7)
    assert doc["stages"]["forward"] == pytest.approx(1.4)
    assert doc["unattributed_s"] == pytest.approx(0.4)
    assert doc["unattributed_frac"] == pytest.approx(0.4 / 4.5, abs=1e-3)
    assert sum(doc["stage_shares"].values()) == pytest.approx(1.0,
                                                              abs=1e-3)
    assert doc["dominant_stage"] == "queue"
    assert doc["ttft_p50_s"] == pytest.approx(0.01)
    assert doc["ttft_p99_s"] == pytest.approx(0.09)
    # exemplars: bounded per window, slowest first, full breakdown
    assert [e["req_id"] for e in worst] == ["b", "a"]
    assert worst[0]["trace"] == "slow" and worst[0]["version"] == 7
    assert worst[0]["dominant_stage"] == "queue"
    assert doc["worst_trace"] == "slow"
    # close() resets: an idle window reads zero, not stale
    doc2, worst2 = b.close()
    assert doc2["stages"] == {} and doc2["stage_shares"] == {}
    assert doc2["unattributed_frac"] == 0.0
    assert doc2["dominant_stage"] is None and worst2 == []


def test_stage_share_gauges_zero_on_idle_roll():
    """Stale-gauge regression (satellite): after a busy window the
    share gauges carry the breakdown; an IDLE window must publish 0.0
    for every canonical stage — a frozen share gauge would keep
    blaming a stage that stopped existing."""
    from horovod_tpu.metrics.registry import default_registry
    from horovod_tpu.serving import ledger
    from horovod_tpu.serving.metrics import LatencyWindow
    w = LatencyWindow(window_s=3600.0)
    w.observe(1.0, stages={"queue": 0.7, "forward": 0.29})
    doc = w.maybe_roll(force=True)
    assert doc["requests"] == 1 and doc["dominant_stage"] == "queue"
    reg = default_registry()
    g = reg.get("hvd_serving_stage_share", labels={"stage": "queue"})
    assert g is not None and g.value == pytest.approx(0.7, abs=1e-3)
    idle = w.maybe_roll(force=True)
    assert idle["requests"] == 0
    for stage in ledger.STAGES:
        g = reg.get("hvd_serving_stage_share", labels={"stage": stage})
        assert g is not None and g.value == 0.0, stage


# -- burn-rate SLO ------------------------------------------------------------
def test_burn_rate_one_finding_per_episode_and_rearm(monkeypatch):
    """Hysteresis: the episode opens once (fast AND slow spans over
    threshold, window itself over budget), stays silent while active,
    and re-arms only after the fast span burns under 1.0."""
    monkeypatch.setenv("HVD_TPU_ANOMALY", "0")  # unit-test the class
    from horovod_tpu.serving.ledger import BurnRateSlo
    slo = BurnRateSlo(slo_p99_s=0.01, budget=0.01, fast_windows=2,
                      slow_windows=4, threshold=10.0)
    assert slo.enabled and slo.is_bad(0.02) and not slo.is_bad(0.005)
    bad, good = (10, 10), (10, 0)
    # one breaching window is not an episode: fast span not yet filled
    assert slo.observe_window(*bad) is None
    f = slo.observe_window(*bad)
    assert f is not None and f["burn_fast"] == pytest.approx(100.0)
    # still breaching: same episode, NO second finding
    assert slo.observe_window(*bad) is None
    # one good window: fast burn 50 >= 1.0, still armed-off
    assert slo.observe_window(*good) is None and slo.active
    # second good window: fast burn 0 < 1.0 -> re-arm
    assert slo.observe_window(*good) is None and not slo.active
    # fresh breach after recovery opens a NEW episode (slow span still
    # carries the old badness: 20/40 bad = burn 50 >= threshold)
    f2 = slo.observe_window(*bad)
    assert f2 is not None
    # a recovered window can never OPEN an episode, whatever the spans
    assert slo.observe_window(*good) is None


def test_burn_rate_finding_names_the_dominant_stage(monkeypatch):
    monkeypatch.setenv("HVD_TPU_ANOMALY", "0")
    from horovod_tpu.serving.ledger import BurnRateSlo
    slo = BurnRateSlo(slo_p99_s=0.01, budget=0.01, fast_windows=1,
                      slow_windows=2, threshold=2.0)
    doc = {"p99_s": 0.5, "qps": 10.0, "dominant_stage": "page_wait",
           "stage_shares": {"page_wait": 0.8, "decode": 0.2},
           "worst_trace": "t-9"}
    f = slo.observe_window(10, 5, doc)
    assert f["dominant_stage"] == "page_wait"
    assert f["dominant_share"] == pytest.approx(0.8)
    assert f["worst_trace"] == "t-9"
    # disabled SLO (no HVD_TPU_SERVING_SLO_P99_MS) never fires
    off = BurnRateSlo(slo_p99_s=0.0)
    assert not off.enabled and off.observe_window(10, 10) is None


# -- batch-size buckets -------------------------------------------------------
def test_batch_size_buckets_widen_with_slot_count(monkeypatch):
    """Satellite: the old fixed top of 128 dumped every big decode
    batch into +Inf; buckets now derive from the configured slot
    count, power-of-two, and never shrink below the old top."""
    from horovod_tpu.serving.metrics import batch_size_buckets
    b = batch_size_buckets(top=512)
    assert b[-1] >= 512 and b[0] == 1
    assert all(b[i + 1] == 2 * b[i] for i in range(len(b) - 1))
    # back-compat floor: a tiny config still covers the old 128 top
    assert batch_size_buckets(top=8)[-1] >= 128
    monkeypatch.setenv("HVD_TPU_GEN_SLOTS", "300")
    assert batch_size_buckets()[-1] >= 300


# -- serving-window anomaly detectors -----------------------------------------
def _mk_engine(monkeypatch, **env):
    from horovod_tpu.metrics.anomaly import AnomalyEngine
    for k, v in env.items():
        monkeypatch.setenv(f"HVD_TPU_{k}", str(v))
    return AnomalyEngine()


def test_ttft_drift_detector_flags_sustained_drift(monkeypatch):
    eng = _mk_engine(monkeypatch, ANOMALY_WARMUP=2,
                     ANOMALY_CONSECUTIVE=1)
    base = {"requests": 5, "ttft_p50_s": 0.01}
    for _ in range(4):
        assert eng.observe_serving(dict(base)) == []
    out = eng.observe_serving({"requests": 5, "ttft_p50_s": 0.08,
                               "worst_trace": "t-slow"})
    assert len(out) == 1 and out[0]["kind"] == "ttft_drift"
    assert out[0]["worst_trace"] == "t-slow"
    # an idle window carries no ttft signal and no false positive
    assert eng.observe_serving({"requests": 0}) == []


def test_queue_growth_detector_streak_and_idle_reset(monkeypatch):
    eng = _mk_engine(monkeypatch, SERVING_STAGE_WINDOWS=2)
    hot = {"requests": 10,
           "stage_shares": {"queue": 0.4, "batch_wait": 0.3}}
    assert eng.observe_serving(dict(hot)) == []  # streak 1 of 2
    out = eng.observe_serving(dict(hot))
    assert len(out) == 1 and out[0]["kind"] == "queue_growth"
    assert out[0]["dominant_stage"] == "queue"
    # hysteresis: still hot -> same episode, silent
    assert eng.observe_serving(dict(hot)) == []
    # an idle window resets the episode AND the streak: the condition
    # did not survive the traffic that caused it
    assert eng.observe_serving({"requests": 0}) == []
    assert eng.observe_serving(dict(hot)) == []  # streak back to 1
    assert len(eng.observe_serving(dict(hot))) == 1


# -- books close end to end through router + replica --------------------------
def test_books_close_through_router_and_replica():
    """Acceptance: real traffic through a real router+replica pair —
    every response doc carries a closed stage ledger, the aggregate
    residual stays under the 10% gate, and the window's tail exemplars
    carry trace ids that resolve to the request's spans."""
    from horovod_tpu.diagnostics.flight_recorder import recorder
    from horovod_tpu.serving import ReplicaServer, Router, ledger
    from horovod_tpu.tracing.reader import spans_from_events
    replica = ReplicaServer(dim=4, replica_id="lg0").start()
    router = Router([("127.0.0.1", replica.port)], hedge_ms=0)
    try:
        docs = [router.submit([float(i), 0, 0, 0],
                              req_id=f"books-{i}") for i in range(12)]
        # roll BEFORE close (close force-rolls the window as a flush)
        win = router.window.maybe_roll(force=True)
    finally:
        router.close()
        replica.stop()
    total = unattr = 0.0
    for doc in docs:
        stages = doc["stages"]
        assert "unattributed" in stages
        assert stages.get("forward", 0) > 0  # replica plane attributed
        assert stages.get("dispatch", 0) > 0  # router plane attributed
        assert set(stages) <= set(ledger.STAGES)
        assert all(v >= 0 for v in stages.values())
        total += sum(stages.values())
        unattr += stages["unattributed"]
    assert total > 0 and unattr / total < 0.10, (unattr, total)
    assert win["requests"] == 12
    assert win["unattributed_frac"] < 0.10
    assert win["dominant_stage"] in ledger.STAGES[:-1]
    assert sum(win["stage_shares"].values()) == pytest.approx(1.0,
                                                              abs=0.01)
    # the worst requests landed in the ring with resolvable traces
    worst = ledger.default_ring().worst(1)
    assert worst and worst[0].get("trace")
    spans, _ = spans_from_events(recorder().events(),
                                 trace_id=worst[0]["trace"])
    names = [s["name"] for s in spans]
    assert "request" in names and "serve" in names
    req_span = [s for s in spans if s["name"] == "request"][0]
    assert any(k.startswith("stage_") for k in req_span["attrs"])


# -- generate plane: stage coverage -------------------------------------------
def _gen_engine(**over):
    from horovod_tpu.serving.generate import (GenerateEngine,
                                              demo_gen_setup)
    params, cfg = demo_gen_setup()
    kw = dict(n_slots=2, page_bytes=4096, prefill_chunk=8)
    kw.update(over)
    return GenerateEngine(params, cfg, **kw)


def test_generate_stages_cover_swap_pause():
    """A hot weight swap mid-generation: the pause the swap bracket
    imposes on live sequences lands in the ``swap_pause`` stage, next
    to real prefill/decode time — never in the residual."""
    from horovod_tpu.serving.generate.scheduler import DONE
    eng = _gen_engine()
    req = eng.submit("swap-1", [3, 5, 7], max_new=6)
    n = 0
    while req.decode_steps < 1:  # run prefill + first decode step
        eng.step_once()
        n += 1
        assert n < 10_000, "engine failed to reach decode"
    eng.begin_swap()
    t = threading.Timer(0.08, eng.end_swap)
    t.start()
    eng.step_once()  # blocks at the swap gate; pause is charged
    t.join()
    while req.state != DONE:
        eng.step_once()
        n += 1
        assert n < 10_000, "engine failed to converge"
    result = req.pending.wait(timeout=10.0)
    stages = result["stages"]
    assert stages["swap_pause"] >= 0.05
    assert stages["prefill"] > 0 and stages["decode"] > 0
    assert set(stages) == {"slot_wait", "page_wait", "prefill",
                           "decode", "swap_pause"}


def _sched(n_slots=2, pool_pages=4, page_tokens=4):
    from horovod_tpu.serving.generate.pages import (PagePool,
                                                    plan_kv_pages)
    from horovod_tpu.serving.generate.scheduler import SlotScheduler
    plan = plan_kv_pages(1, 8, np.float32, slots=pool_pages,
                         max_ctx=page_tokens,
                         page_bytes=64 * page_tokens)
    pool = PagePool(plan)
    return SlotScheduler(n_slots, pool, 4,
                         max_ctx=pool_pages * page_tokens), pool


def test_scheduler_discriminates_slot_wait_from_page_wait():
    """The ledger must answer "waiting for WHAT": a full slot array
    charges slot_wait, an exhausted page pool charges page_wait — the
    exact discrimination kv_thrash runs on."""
    from horovod_tpu.serving.generate.scheduler import GenRequest
    # slots are the bottleneck: 1 slot, plenty of pages
    sched, _pool = _sched(n_slots=1, pool_pages=4)
    first = GenRequest("first", [1] * 4, 4)   # admits into the slot
    queued = GenRequest("queued", [1] * 4, 4)
    sched.add_waiting(first)
    sched.add_waiting(queued)
    assert [r.id for r in sched.admit()] == ["first"]
    time.sleep(0.02)
    sched.admit()
    assert queued.slot_wait_s > 0 and queued.page_wait_s == 0.0
    # pages are the bottleneck: free slots, pool too small for the head
    sched2, _pool2 = _sched(n_slots=2, pool_pages=1)
    big = GenRequest("big", [1] * 4, 4)  # worst case 8 tokens, 2 pages
    sched2.add_waiting(big)
    assert sched2.admit() == []
    time.sleep(0.02)
    sched2.admit()
    # queue transit BEFORE the first classification charges slot_wait
    # (microseconds); the real wait after it is all page_wait
    assert big.page_wait_s > 0.015 and big.slot_wait_s < 0.001


# -- chaos acceptance: KV starvation -> kv_thrash -----------------------------
def _starved_stage_docs(monkeypatch, starve: bool):
    """Run real admissions through the real serving.kv seam (chaos
    starving the first page grants when ``starve``); returns the
    per-request closed stage dicts."""
    from horovod_tpu import chaos
    from horovod_tpu.serving.generate.scheduler import GenRequest
    if starve:
        plan = {"faults": [{"seam": "serving.kv", "kind": "starve",
                            "start": 0, "stop": 3}]}
        monkeypatch.setenv("HVD_TPU_FAULT_PLAN", json.dumps(plan))
        chaos.install(rank=0)
    sched, _pool = _sched(n_slots=2, pool_pages=4)
    reqs = [GenRequest(f"g{i}", [1] * 4, 4) for i in range(2)]
    for r in reqs:
        sched.add_waiting(r)
    deadline = time.monotonic() + 10.0
    while sched.waiting_count():
        sched.admit()
        time.sleep(0.01)
        assert time.monotonic() < deadline, "admission never unblocked"
    chaos.uninstall()
    return [r.stages() for r in reqs]


def test_chaos_kv_starvation_flags_kv_thrash(monkeypatch):
    """Acceptance pair: injected KV starvation (the serving.kv seam
    refusing page grants) piles request time into page_wait; after the
    detector's window streak the anomaly engine reports ``kv_thrash``
    naming ``page_wait`` as the dominant stage.  A clean control run of
    the same length produces ZERO serving findings."""
    from horovod_tpu.metrics import anomaly
    from horovod_tpu.serving.metrics import LatencyWindow

    def run(starve: bool):
        monkeypatch.setenv("HVD_TPU_SERVING_STAGE_WINDOWS", "2")
        anomaly.reset()
        stage_docs = _starved_stage_docs(monkeypatch, starve)
        w = LatencyWindow(window_s=3600.0)
        findings = []
        for _ in range(2):  # the detector needs 2 consecutive windows
            for stages in stage_docs:
                w.observe(sum(stages.values()), stages=stages)
            w.maybe_roll(force=True)
            findings = [f for f in anomaly.recent_findings()
                        if f["kind"] in ("kv_thrash", "queue_growth",
                                         "ttft_drift")]
        anomaly.reset()
        return stage_docs, findings

    stage_docs, findings = run(starve=True)
    # the seam starved 3 grants -> the head piled up real page_wait
    assert all(s["page_wait"] > 0 for s in stage_docs)
    assert len(findings) == 1, findings
    assert findings[0]["kind"] == "kv_thrash"
    assert findings[0]["dominant_stage"] == "page_wait"
    assert findings[0]["stage_share"] > 0.25
    # clean control, same traffic shape: no starvation, no finding
    stage_docs, findings = run(starve=False)
    assert findings == [], findings
