"""Bench harness contract tests (no TPU): the single-JSON-line artifact
contract under failure, model selection, and failure-identity naming.
The success path is covered on hardware by ci/check_bench.py."""

import io
import contextlib
import json
import os
import subprocess
import sys

import bench


def test_failure_json_parses_and_carries_last_measured(monkeypatch):
    """Persistent failure still yields ONE parseable JSON line with the
    right metric name and the latest committed real-hardware result as
    provenance (value stays null, error stays set)."""
    monkeypatch.setattr(
        bench, "_run_attempt",
        lambda deadline_s=None: (None, None,
                                 "child rc=1: backend 'axon' down"))
    monkeypatch.setattr(bench, "BACKOFF_S", 0)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.main()
    lines = [l for l in buf.getvalue().strip().splitlines() if l.strip()]
    assert len(lines) == 1
    doc = json.loads(lines[0])
    assert doc["metric"] == "resnet50_images_per_sec_per_chip"
    assert doc["value"] is None and doc["error"]
    lm = doc["last_measured"]
    assert lm and lm["result"]["metric"] == doc["metric"]
    assert lm["result"]["value"] and lm["result"]["mfu"]


def test_config_error_fails_fast(monkeypatch):
    """A deterministic config error (unknown model) must not retry and
    must not mint a real benchmark's metric name."""
    monkeypatch.setenv("HVD_BENCH_MODEL", "resent50")  # typo
    calls = []

    def counting(deadline_s=None):
        calls.append(1)
        return (None, None, "config error (no retry): child rc=2: unknown")
    monkeypatch.setattr(bench, "_run_attempt", counting)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.main()
    assert len(calls) == 1  # no retries
    doc = json.loads(buf.getvalue().strip())
    assert doc["metric"] == "unknown_model_resent50"
    assert doc["unit"] == "n/a" and doc["last_measured"] is None


def test_unknown_model_child_exits_rc2():
    env = dict(os.environ)
    env.update({"HVD_BENCH_MODEL": "nope", "JAX_PLATFORMS": "cpu"})
    r = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(bench.__file__),
                                      "bench.py"), "--child"],
        env=env, capture_output=True, text=True, timeout=120)
    assert r.returncode == 2
    assert "unknown HVD_BENCH_MODEL" in r.stderr


def test_gpt_child_runs_on_cpu_mesh():
    """The gpt bench child is wired end-to-end: tiny shapes on the
    8-device CPU mesh must produce the one-JSON-line contract."""
    env = dict(os.environ)
    env.update({
        "HVD_BENCH_MODEL": "gpt", "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "HVD_BENCH_GPT_DMODEL": "64", "HVD_BENCH_GPT_HEADS": "4",
        "HVD_BENCH_GPT_LAYERS": "2", "HVD_BENCH_GPT_DFF": "128",
        "HVD_BENCH_BATCH": "2", "HVD_BENCH_SEQ": "64",
        # the contract under test is the artifact schema, not timing
        # precision: a short final window keeps this inside the tier-1
        # budget (~2s/step on the 1-core CPU mesh)
        "HVD_BENCH_ITERS": "3",
    })
    r = subprocess.run(
        [sys.executable, "-c",
         "import os\n"
         "import jax\n"
         "jax.config.update('jax_platforms', 'cpu')\n"
         "import bench\n"
         "bench._child()\n"],
        env=env, capture_output=True, text=True, timeout=300,
        cwd=os.path.dirname(os.path.abspath(bench.__file__)))
    assert r.returncode == 0, r.stderr[-1500:]
    lines = []
    for l in r.stdout.strip().splitlines():  # tolerate stray banner lines
        try:
            parsed = json.loads(l)
        except ValueError:
            continue
        if isinstance(parsed, dict) and "metric" in parsed:
            lines.append(parsed)
    # warmup window emits a provisional line BEFORE the final one, so a
    # deadline-killed run still carries a measured value
    assert len(lines) == 2, r.stdout
    assert lines[0]["provisional"] is True and lines[0]["value"] > 0
    doc = lines[-1]
    assert "provisional" not in doc
    assert doc["metric"] == "gpt_tokens_per_sec_per_chip"
    assert doc["value"] > 0
    assert doc["n_chips"] == 8
    assert doc["compile_s"] > 0


def test_child_exits_cleanly_before_deadline():
    """With the attempt deadline imminent, the child must emit the
    provisional line and exit 0 WITHOUT running the final window — a
    child the parent has to kill tears the TPU chip claim down dirty and
    wedges the relay lease for the next run."""
    env = dict(os.environ)
    env.update({
        "HVD_BENCH_MODEL": "gpt", "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "HVD_BENCH_GPT_DMODEL": "64", "HVD_BENCH_GPT_HEADS": "4",
        "HVD_BENCH_GPT_LAYERS": "2", "HVD_BENCH_GPT_DFF": "128",
        "HVD_BENCH_BATCH": "2", "HVD_BENCH_SEQ": "64",
        "HVD_BENCH_CHILD_DEADLINE": "1",  # long past: skip final window
    })
    r = subprocess.run(
        [sys.executable, "-c",
         "import jax\n"
         "jax.config.update('jax_platforms', 'cpu')\n"
         "import bench\n"
         "bench._child()\n"],
        env=env, capture_output=True, text=True, timeout=300,
        cwd=os.path.dirname(os.path.abspath(bench.__file__)))
    assert r.returncode == 0, r.stderr[-1500:]
    lines = [json.loads(l) for l in r.stdout.strip().splitlines()
             if l.strip().startswith("{")]
    assert len(lines) == 1  # provisional only, no final window
    assert lines[0]["provisional"] is True and lines[0]["value"] > 0
    assert "exiting cleanly" in r.stderr


def test_provisional_salvaged_when_final_window_never_lands(monkeypatch):
    """If every attempt times out but a warmup-window provisional line was
    streamed out, main() must print that REAL measured number (with the
    failure context in "note") instead of a value:null artifact."""
    prov = json.dumps({
        "metric": "resnet50_images_per_sec_per_chip", "value": 2500.0,
        "unit": "img/s/chip", "vs_baseline": 24.1, "mfu": 0.31,
        "provisional": True})
    monkeypatch.setattr(
        bench, "_run_attempt",
        lambda deadline_s=None: (None, prov, "attempt exceeded 900s "
                                 "deadline"))
    monkeypatch.setattr(bench, "BACKOFF_S", 0)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.main()
    lines = [l for l in buf.getvalue().strip().splitlines() if l.strip()]
    assert len(lines) == 1
    doc = json.loads(lines[0])
    assert doc["value"] == 2500.0
    assert doc["provisional"] is True
    assert "deadline" in doc["note"]


def test_failure_identity_names():
    for model, metric, unit in [
            ("resnet50", "resnet50_images_per_sec_per_chip", "img/s/chip"),
            ("resnet50_bare", "resnet50_bare_images_per_sec_per_chip",
             "img/s/chip"),
            ("resnet101", "resnet101_images_per_sec_per_chip", "img/s/chip"),
            ("vgg16", "vgg16_images_per_sec_per_chip", "img/s/chip"),
            ("inception3", "inception3_images_per_sec_per_chip",
             "img/s/chip"),
            ("bert", "bert_large_seqs_per_sec_per_chip", "seq/s/chip"),
            ("bert_large", "bert_large_seqs_per_sec_per_chip",
             "seq/s/chip"),
            ("gpt", "gpt_tokens_per_sec_per_chip", "tokens/s/chip"),
            ("transformer", "gpt_tokens_per_sec_per_chip",
             "tokens/s/chip")]:
        os.environ["HVD_BENCH_MODEL"] = model
        try:
            assert bench._failure_identity() == (metric, unit)
        finally:
            del os.environ["HVD_BENCH_MODEL"]
