"""Bench harness contract tests (no TPU): the single-JSON-line artifact
contract under failure, model selection, and failure-identity naming.
The success path is covered on hardware by ci/check_bench.py."""

import io
import contextlib
import json
import os
import subprocess
import sys

import pytest

import bench

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_failure_json_parses_and_carries_last_measured(monkeypatch):
    """Persistent failure still yields ONE parseable JSON line with the
    right metric name and the latest committed real-hardware result as
    provenance (value stays null, error stays set)."""
    monkeypatch.setattr(
        bench, "_run_attempt",
        lambda deadline_s=None: (None, None,
                                 "child rc=1: backend 'axon' down"))
    monkeypatch.setattr(bench, "BACKOFF_S", 0)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.main()
    lines = [l for l in buf.getvalue().strip().splitlines() if l.strip()]
    assert len(lines) == 1
    doc = json.loads(lines[0])
    assert doc["metric"] == "resnet50_images_per_sec_per_chip"
    assert doc["value"] is None and doc["error"]
    lm = doc["last_measured"]
    assert lm and lm["result"]["metric"] == doc["metric"]
    assert lm["result"]["value"] and lm["result"]["mfu"]


def test_config_error_fails_fast(monkeypatch):
    """A deterministic config error (unknown model) must not retry and
    must not mint a real benchmark's metric name."""
    monkeypatch.setenv("HVD_BENCH_MODEL", "resent50")  # typo
    calls = []

    def counting(deadline_s=None):
        calls.append(1)
        return (None, None, "config error (no retry): child rc=2: unknown")
    monkeypatch.setattr(bench, "_run_attempt", counting)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.main()
    assert len(calls) == 1  # no retries
    doc = json.loads(buf.getvalue().strip())
    assert doc["metric"] == "unknown_model_resent50"
    assert doc["unit"] == "n/a" and doc["last_measured"] is None


def test_unknown_model_child_exits_rc2():
    env = dict(os.environ)
    env.update({"HVD_BENCH_MODEL": "nope", "JAX_PLATFORMS": "cpu"})
    r = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(bench.__file__),
                                      "bench.py"), "--child"],
        env=env, capture_output=True, text=True, timeout=120)
    assert r.returncode == 2
    assert "unknown HVD_BENCH_MODEL" in r.stderr


@pytest.mark.slow  # ~26s gpt-child compile; tier-1 budget (single
#                    tier runs the whole file unfiltered)
def test_gpt_child_runs_on_cpu_mesh():
    """The gpt bench child is wired end-to-end: tiny shapes on the
    8-device CPU mesh must produce the one-JSON-line contract."""
    env = dict(os.environ)
    env.update({
        "HVD_BENCH_MODEL": "gpt", "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "HVD_BENCH_GPT_DMODEL": "64", "HVD_BENCH_GPT_HEADS": "4",
        "HVD_BENCH_GPT_LAYERS": "2", "HVD_BENCH_GPT_DFF": "128",
        "HVD_BENCH_BATCH": "2", "HVD_BENCH_SEQ": "64",
        # the contract under test is the artifact schema, not timing
        # precision: a short final window keeps this inside the tier-1
        # budget (~2s/step on the 1-core CPU mesh)
        "HVD_BENCH_ITERS": "3",
    })
    r = subprocess.run(
        [sys.executable, "-c",
         "import os\n"
         "import jax\n"
         "jax.config.update('jax_platforms', 'cpu')\n"
         "import bench\n"
         "bench._child()\n"],
        env=env, capture_output=True, text=True, timeout=300,
        cwd=os.path.dirname(os.path.abspath(bench.__file__)))
    assert r.returncode == 0, r.stderr[-1500:]
    lines = []
    for l in r.stdout.strip().splitlines():  # tolerate stray banner lines
        try:
            parsed = json.loads(l)
        except ValueError:
            continue
        if isinstance(parsed, dict) and "metric" in parsed:
            lines.append(parsed)
    # the warmup window emits TWO provisional lines before the final one
    # (first post-compile step immediately, refined after full warmup),
    # so a deadline kill anywhere past compile still carries a value
    assert len(lines) == 3, r.stdout
    assert all(l["provisional"] is True and l["value"] > 0
               for l in lines[:2])
    doc = lines[-1]
    assert "provisional" not in doc
    assert doc["metric"] == "gpt_tokens_per_sec_per_chip"
    assert doc["value"] > 0
    assert doc["n_chips"] == 8
    assert doc["compile_s"] > 0
    # ISSUE 9: hook-measured compile time (counts EVERY backend
    # compile, not just the first-step wall clock) + HBM peak (None on
    # CPU: the backend reports no memory_stats)
    assert doc["compile_seconds"] > 0
    assert "hbm_peak_bytes" in doc and doc["hbm_peak_bytes"] is None


def test_child_exits_cleanly_before_deadline(tmp_path):
    """With the attempt deadline imminent, the child must emit the
    provisional line and exit 0 WITHOUT running the final window — a
    child the parent has to kill tears the TPU chip claim down dirty and
    wedges the relay lease for the next run. The same child also proves
    the ISSUE-6 side channel: the provisional result doc must be
    mirrored into HVD_BENCH_PHASE_FILE (the parent's salvage source
    when a SIGKILL loses the stdout pipe)."""
    phase_file = str(tmp_path / "phases.json")
    env = dict(os.environ)
    env.update({
        "HVD_BENCH_MODEL": "gpt", "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "HVD_BENCH_GPT_DMODEL": "64", "HVD_BENCH_GPT_HEADS": "4",
        "HVD_BENCH_GPT_LAYERS": "2", "HVD_BENCH_GPT_DFF": "128",
        "HVD_BENCH_BATCH": "2", "HVD_BENCH_SEQ": "64",
        "HVD_BENCH_PHASE_FILE": phase_file,
        "HVD_BENCH_CHILD_DEADLINE": "1",  # long past: skip final window
    })
    r = subprocess.run(
        [sys.executable, "-c",
         "import jax\n"
         "jax.config.update('jax_platforms', 'cpu')\n"
         "import bench\n"
         "bench._child()\n"],
        env=env, capture_output=True, text=True, timeout=300,
        cwd=os.path.dirname(os.path.abspath(bench.__file__)))
    assert r.returncode == 0, r.stderr[-1500:]
    lines = [json.loads(l) for l in r.stdout.strip().splitlines()
             if l.strip().startswith("{")]
    # provisionals only (first-step + refined), no final window
    assert len(lines) == 2, r.stdout
    assert all(l["provisional"] is True and l["value"] > 0 for l in lines)
    assert "exiting cleanly" in r.stderr
    # the phase-file side channel carries the provisional (salvage source)
    with open(phase_file) as f:
        doc = json.load(f)
    prov = doc["provisional_result"]
    assert prov and prov["provisional"] is True and prov["value"] > 0
    assert "warmup" in doc["phases"]


def test_provisional_salvaged_when_final_window_never_lands(monkeypatch):
    """If every attempt times out but a warmup-window provisional line was
    streamed out, main() must print that REAL measured number (with the
    failure context in "note") instead of a value:null artifact."""
    prov = json.dumps({
        "metric": "resnet50_images_per_sec_per_chip", "value": 2500.0,
        "unit": "img/s/chip", "vs_baseline": 24.1, "mfu": 0.31,
        "provisional": True})
    monkeypatch.setattr(
        bench, "_run_attempt",
        lambda deadline_s=None: (None, prov, "attempt exceeded 900s "
                                 "deadline"))
    monkeypatch.setattr(bench, "BACKOFF_S", 0)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.main()
    lines = [l for l in buf.getvalue().strip().splitlines() if l.strip()]
    assert len(lines) == 1
    doc = json.loads(lines[0])
    assert doc["value"] == 2500.0
    assert doc["provisional"] is True
    assert "deadline" in doc["note"]


def test_provisional_salvaged_from_phase_file(monkeypatch, tmp_path):
    """A SIGKILLed child can lose its stdout lines entirely; the
    provisional mirrored into the HVD_BENCH_PHASE_FILE side channel must
    still be salvaged by main() instead of shipping value:null."""
    prov = {"metric": "resnet50_images_per_sec_per_chip", "value": 2400.0,
            "unit": "img/s/chip", "vs_baseline": 23.2, "mfu": 0.30,
            "provisional": True}
    phase_doc = {"phases": {"compile": 100.0}, "in_progress": "measure",
                 "provisional_result": prov}

    def fake_attempt(deadline_s=None):
        monkeypatch.setattr(bench, "_LAST_PHASES", phase_doc)
        return None, None, "attempt exceeded 900s deadline"

    monkeypatch.setattr(bench, "_run_attempt", fake_attempt)
    monkeypatch.setattr(bench, "BACKOFF_S", 0)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.main()
    lines = [l for l in buf.getvalue().strip().splitlines() if l.strip()]
    assert len(lines) == 1
    doc = json.loads(lines[0])
    assert doc["value"] == 2400.0
    assert doc["provisional"] is True
    assert "deadline" in doc["note"]
    assert doc["phases"] == {"compile": 100.0}




def test_scaling_gate_extract_and_regression(tmp_path):
    """ci/check_bench.py --scaling: curve extraction from raw output and
    from MULTICHIP artifacts, and the tolerance-band regression check."""
    sys.path.insert(0, REPO)
    try:
        from ci.check_bench import (check_scaling_regression,
                                    extract_scaling_curve, scaling_main)
    finally:
        sys.path.remove(REPO)
    curve = {"scaling_curve": [
        {"world": 1, "samples_per_sec": 10.0, "samples_per_sec_int8": 8.0},
        {"world": 2, "samples_per_sec": 18.0,
         "samples_per_sec_int8": 15.0}]}
    tail = ("[dryrun] OK: 2 layouts on 8 devices\n"
            "[scaling] world=1 plain=10.0/s int8=8.0/s\n"
            "[scaling] " + json.dumps(curve) + "\n")
    # raw text and MULTICHIP-artifact forms both extract
    assert extract_scaling_curve(tail) == curve
    new_path = tmp_path / "MULTICHIP_new.json"
    new_path.write_text(json.dumps({"n_devices": 8, "tail": tail}))

    # within band: passes
    base_ok = {"scaling_curve": [
        {"world": 1, "samples_per_sec": 11.0,
         "samples_per_sec_int8": 9.0}]}
    assert check_scaling_regression(curve, base_ok, 0.25) == []
    # collapse beyond band: fails and names the series
    base_bad = {"scaling_curve": [
        {"world": 2, "samples_per_sec": 40.0,
         "samples_per_sec_int8": 15.0}]}
    bad = check_scaling_regression(curve, base_bad, 0.25)
    assert bad == [(2, "samples_per_sec", 18.0, 40.0)]

    # CLI: regression -> rc 1; within band -> rc 0; no baseline curve
    # (old artifact) -> rc 0 with a note; new without curve -> rc 1
    base_path = tmp_path / "MULTICHIP_base.json"
    base_path.write_text(json.dumps(
        {"tail": "[scaling] " + json.dumps(base_bad)}))
    argv = ["--scaling", str(new_path), "--baseline", str(base_path)]
    assert scaling_main(argv) == 1
    assert scaling_main(argv + ["--tolerance", "0.9"]) == 0
    old_style = tmp_path / "MULTICHIP_old.json"
    old_style.write_text(json.dumps({"tail": "[dryrun] OK\n"}))
    assert scaling_main(["--scaling", str(new_path), "--baseline",
                         str(old_style)]) == 0
    assert scaling_main(["--scaling", str(old_style), "--baseline",
                         str(base_path)]) == 1

    # a baseline world the new run COULD have measured but didn't is a
    # regression (evidence erased), and a truncated curve fails loudly
    short = dict(curve)
    short["n_devices"] = 8
    base_full = {"scaling_curve": curve["scaling_curve"] + [
        {"world": 8, "samples_per_sec": 60.0,
         "samples_per_sec_int8": 40.0}]}
    missing = check_scaling_regression(short, base_full, 0.25)
    assert (8, "missing", None, 60.0) in missing
    trunc_path = tmp_path / "MULTICHIP_trunc.json"
    trunc_path.write_text(json.dumps({"tail": "[scaling] " + json.dumps(
        dict(curve, truncated=True))}))
    full_base_path = tmp_path / "MULTICHIP_fullbase.json"
    full_base_path.write_text(json.dumps(
        {"tail": "[scaling] " + json.dumps(base_full)}))
    assert scaling_main(["--scaling", str(trunc_path), "--baseline",
                         str(base_path), "--tolerance", "0.9"]) == 1


def test_compile_budget_gate(tmp_path):
    """ci/check_bench.py --compile-budget (ISSUE 9): hook-measured
    compile_seconds gated against the baseline with a tolerance band;
    wall-clock compile_s is the fallback for pre-contract artifacts."""
    sys.path.insert(0, REPO)
    try:
        from ci.check_bench import (check_compile_budget,
                                    compile_budget_main,
                                    doc_compile_seconds)
    finally:
        sys.path.remove(REPO)
    new = {"metric": "resnet50", "value": 100.0, "compile_seconds": 30.0,
           "compile_s": 99.0}
    assert doc_compile_seconds(new) == (30.0, "hooks")  # hooks beat wall
    old = {"metric": "resnet50", "value": 90.0, "compile_s": 25.0}
    assert doc_compile_seconds(old) == (25.0, "wall")
    # within band / beyond band / no baseline / broken contract
    assert check_compile_budget(new, old, tolerance=0.5) is None
    assert "regression" in check_compile_budget(
        {"value": 1.0, "compile_seconds": 60.0}, old, tolerance=0.5)
    assert check_compile_budget(new, None, tolerance=0.5) is None
    assert "contract" in check_compile_budget(
        {"value": 1.0}, old, tolerance=0.5)
    # a failure doc (value null) has no compile to judge
    assert check_compile_budget(
        {"value": None, "error": "x"}, old, tolerance=0.5) is None

    # CLI roundtrip incl. the BENCH_r* "parsed" wrapper form
    new_path = tmp_path / "new.json"
    new_path.write_text(json.dumps(new))
    base_path = tmp_path / "BENCH_base.json"
    base_path.write_text(json.dumps({"n": 1, "parsed": old}))
    rc = compile_budget_main(["--compile-budget", str(new_path),
                              "--baseline", str(base_path)])
    assert rc == 0
    bad_path = tmp_path / "bad.json"
    bad_path.write_text(json.dumps(
        {"value": 1.0, "compile_seconds": 60.0}))
    rc = compile_budget_main(["--compile-budget", str(bad_path),
                              "--baseline", str(base_path)])
    assert rc == 1
    rc = compile_budget_main(["--compile-budget", str(new_path),
                              "--baseline", str(base_path),
                              "--tolerance", "0.1"])
    assert rc == 1
    # a failure doc (value null, no compile time) against a real
    # baseline passes without crashing on the success-path print
    fail_path = tmp_path / "failed.json"
    fail_path.write_text(json.dumps({"value": None, "error": "boom"}))
    rc = compile_budget_main(["--compile-budget", str(fail_path),
                              "--baseline", str(base_path)])
    assert rc == 0


def test_tuned_vs_default_gate(tmp_path):
    """ci/check_bench.py --tuned TUNED --default DEFAULT (ISSUE 8):
    the autotuned run must not lose to the static default beyond the
    band — including the missing-world evidence rule — and degraded
    inputs (no curve on either side) fail rather than pass silently."""
    sys.path.insert(0, REPO)
    try:
        from ci.check_bench import tuned_main
    finally:
        sys.path.remove(REPO)

    def artifact(path, curve, n_devices=8):
        doc = {"n_devices": n_devices,
               "tail": "[scaling] " + json.dumps(curve)}
        path.write_text(json.dumps(doc))
        return str(path)

    default = {"scaling_curve": [
        {"world": 1, "samples_per_sec": 10.0,
         "samples_per_sec_int8": 8.0},
        {"world": 8, "samples_per_sec": 60.0,
         "samples_per_sec_int8": 45.0}]}
    default_path = artifact(tmp_path / "default.json", default)

    # tuned at least as good everywhere: passes
    good = {"scaling_curve": [
        {"world": 1, "samples_per_sec": 11.0,
         "samples_per_sec_int8": 8.5},
        {"world": 8, "samples_per_sec": 66.0,
         "samples_per_sec_int8": 50.0}]}
    good_path = artifact(tmp_path / "tuned_good.json", good)
    assert tuned_main(["--tuned", good_path,
                       "--default", default_path]) == 0

    # tuned loses a world beyond the band: fails
    bad = {"scaling_curve": [
        {"world": 1, "samples_per_sec": 11.0,
         "samples_per_sec_int8": 8.5},
        {"world": 8, "samples_per_sec": 30.0,
         "samples_per_sec_int8": 50.0}]}
    bad_path = artifact(tmp_path / "tuned_bad.json", bad)
    assert tuned_main(["--tuned", bad_path,
                       "--default", default_path]) == 1
    # ... but a wide-enough band accepts it
    assert tuned_main(["--tuned", bad_path, "--default", default_path,
                       "--tolerance", "0.6"]) == 0

    # a world the default measured but the tuned run erased: fails
    short = {"n_devices": 8, "scaling_curve": good["scaling_curve"][:1]}
    short_path = artifact(tmp_path / "tuned_short.json", short)
    assert tuned_main(["--tuned", short_path,
                       "--default", default_path]) == 1

    # degraded inputs fail loudly instead of passing by default
    empty_path = tmp_path / "empty.json"
    empty_path.write_text(json.dumps({"tail": "[dryrun] OK\n"}))
    assert tuned_main(["--tuned", str(empty_path),
                       "--default", default_path]) == 1
    assert tuned_main(["--tuned", good_path,
                       "--default", str(empty_path)]) == 1
    assert tuned_main(["--tuned", good_path]) == 2  # --default missing


def test_failure_identity_names():
    for model, metric, unit in [
            ("resnet50", "resnet50_images_per_sec_per_chip", "img/s/chip"),
            ("resnet50_bare", "resnet50_bare_images_per_sec_per_chip",
             "img/s/chip"),
            ("resnet101", "resnet101_images_per_sec_per_chip", "img/s/chip"),
            ("vgg16", "vgg16_images_per_sec_per_chip", "img/s/chip"),
            ("inception3", "inception3_images_per_sec_per_chip",
             "img/s/chip"),
            ("bert", "bert_large_seqs_per_sec_per_chip", "seq/s/chip"),
            ("bert_large", "bert_large_seqs_per_sec_per_chip",
             "seq/s/chip"),
            ("gpt", "gpt_tokens_per_sec_per_chip", "tokens/s/chip"),
            ("transformer", "gpt_tokens_per_sec_per_chip",
             "tokens/s/chip")]:
        os.environ["HVD_BENCH_MODEL"] = model
        try:
            assert bench._failure_identity() == (metric, unit)
        finally:
            del os.environ["HVD_BENCH_MODEL"]


def test_pipeline_plan_gate(tmp_path):
    """ci/check_bench.py --pipeline (ISSUE 11): the parallel_plan /
    bubble_fraction pair must be coherent with the analytic tick-count
    model; a doc without a plan passes with nothing to judge."""
    sys.path.insert(0, REPO)
    try:
        from ci.check_bench import check_pipeline_plan, pipeline_main
    finally:
        sys.path.remove(REPO)
    good = {"metric": "gpt_tokens_per_sec_per_chip", "value": 1.0,
            "n_chips": 8,
            "parallel_plan": {"dp": 4, "pp": 2, "schedule": "gpipe",
                              "n_microbatches": 4, "virtual_stages": 1},
            "bubble_fraction": 0.2}    # 2(M+S-1)=10 vs 2M=8 -> 0.2
    assert check_pipeline_plan(good) is None
    assert check_pipeline_plan({"value": 1.0}) is None  # pp=1 run
    wrong_bubble = dict(good, bubble_fraction=0.4286)
    assert "disagrees" in check_pipeline_plan(wrong_bubble)
    bad_tile = dict(good, n_chips=6)
    assert "does not tile" in check_pipeline_plan(bad_tile)
    missing = dict(good)
    del missing["bubble_fraction"]
    assert "without bubble_fraction" in check_pipeline_plan(missing)
    # measured bubble (ISSUE 12 satellite): range-checked when present,
    # drift vs analytic is printed, never gated
    measured = dict(good, bubble_measured=0.31)
    assert check_pipeline_plan(measured) is None
    assert "outside" in check_pipeline_plan(
        dict(good, bubble_measured=1.2))
    assert "not a number" in check_pipeline_plan(
        dict(good, bubble_measured="fast"))
    # the CLI form
    path = tmp_path / "doc.json"
    path.write_text(json.dumps(good))
    assert pipeline_main(["--pipeline", str(path)]) == 0
    path.write_text(json.dumps(measured))
    assert pipeline_main(["--pipeline", str(path)]) == 0
    path.write_text(json.dumps(wrong_bubble))
    assert pipeline_main(["--pipeline", str(path)]) == 1


def _gp_section(fractions, closed=True, violations=0, wall=100.0):
    """A synthetic ledger snapshot shaped like goodput.snapshot()."""
    secs = {c: round(f * wall, 4) for c, f in fractions.items()}
    return {"windows": 2, "steps": 100, "wall_s": wall,
            "seconds": secs, "fractions": fractions,
            "fraction": fractions.get("compute", 0.0),
            "residual_s": 0.0, "closed": closed,
            "books_violations": violations, "tolerance": 0.01}


def test_goodput_gate(tmp_path):
    """ci/check_bench.py --goodput (ISSUE 16): real-valued artifacts
    must carry a CLOSED ledger, and the exposed_comm/compile shares are
    gated against the baseline — both directions (pass + synthesized
    regression)."""
    sys.path.insert(0, REPO)
    try:
        from ci.check_bench import check_goodput, goodput_main
    finally:
        sys.path.remove(REPO)
    good = {"metric": "m", "value": 10.0,
            "goodput": _gp_section({"compute": 0.8, "exposed_comm": 0.1,
                                    "compile": 0.05, "idle_other": 0.05}),
            "mfu_attribution": {"mfu": 0.3, "dominating": "exposed_comm",
                                "kernel_inefficiency": 0.5}}
    base = {"metric": "m", "value": 11.0,
            "goodput": _gp_section({"compute": 0.88, "exposed_comm": 0.05,
                                    "compile": 0.05, "idle_other": 0.02})}
    # within band: passes
    assert check_goodput(good, base, tolerance=0.1) == []
    # synthesized regression: exposed_comm share triples past the band
    bad = {"metric": "m", "value": 6.0,
           "goodput": _gp_section({"compute": 0.6, "exposed_comm": 0.3,
                                   "compile": 0.05, "idle_other": 0.05})}
    problems = check_goodput(bad, base, tolerance=0.1)
    assert len(problems) == 1 and "exposed_comm" in problems[0] \
        and "REGRESSION" in problems[0], problems
    # ... a wide-enough band accepts it
    assert check_goodput(bad, base, tolerance=0.5) == []
    # real value without the ledger: the recording contract broke
    problems = check_goodput({"value": 1.0}, base, tolerance=0.1)
    assert problems and "contract" in problems[0]
    # a failure doc (value null) has nothing to account
    assert check_goodput({"value": None, "error": "x"}, base, 0.1) == []
    # books that did not close fail even with no baseline
    open_books = {"value": 1.0,
                  "goodput": _gp_section({"compute": 0.7,
                                          "idle_other": 0.1},
                                         closed=False, violations=1)}
    problems = check_goodput(open_books, None, tolerance=0.1)
    assert problems and "did NOT close" in problems[0]

    # CLI both ways, incl. the BENCH_r* "parsed" wrapper form
    new_path = tmp_path / "new.json"
    new_path.write_text(json.dumps(good))
    base_path = tmp_path / "BENCH_base.json"
    base_path.write_text(json.dumps({"n": 1, "parsed": base}))
    assert goodput_main(["--goodput", str(new_path),
                         "--baseline", str(base_path)]) == 0
    bad_path = tmp_path / "bad.json"
    bad_path.write_text(json.dumps(bad))
    assert goodput_main(["--goodput", str(bad_path),
                         "--baseline", str(base_path)]) == 1
    assert goodput_main(["--goodput", str(bad_path),
                         "--baseline", str(base_path),
                         "--tolerance", "0.5"]) == 0
    # a pre-contract baseline is judged standalone, not crashed on
    old_path = tmp_path / "old.json"
    old_path.write_text(json.dumps({"value": 5.0}))
    assert goodput_main(["--goodput", str(new_path),
                         "--baseline", str(old_path)]) == 0


def test_baseline_discovery_skips_null_artifacts_loudly(tmp_path,
                                                        monkeypatch,
                                                        capsys):
    """Baseline auto-discovery (--goodput / --compile-budget): a
    null-valued BENCH_r* round is skipped with an explicit message —
    never silently — and the gate compares against the newest REAL
    artifact behind it."""
    sys.path.insert(0, REPO)
    try:
        import ci.check_bench as cb
    finally:
        sys.path.remove(REPO)
    monkeypatch.setattr(cb, "REPO", str(tmp_path))
    # newest round failed (value null); the round before it is real
    (tmp_path / "BENCH_r9.json").write_text(json.dumps(
        {"parsed": {"value": None, "error": "relay down", "mfu": None}}))
    (tmp_path / "BENCH_r8.json").write_text("{not json")
    real = {"value": 10.0, "compile_seconds": 5.0,
            "goodput": _gp_section({"compute": 0.9, "idle_other": 0.1})}
    (tmp_path / "BENCH_r7.json").write_text(json.dumps({"parsed": real}))
    path, doc = cb.discover_baseline(
        "BENCH_r*.json", str(tmp_path / "new.json"),
        lambda d: cb.doc_goodput(d) is not None, what="goodput section")
    assert path.endswith("BENCH_r7.json") and doc["value"] == 10.0
    out = capsys.readouterr().out
    assert "BENCH_r9.json" in out and "null-valued" in out, out
    assert "BENCH_r8.json" in out, out
    # nothing real at all -> (None, None), every skip still reported
    (tmp_path / "BENCH_r7.json").unlink()
    path, doc = cb.discover_baseline(
        "BENCH_r*.json", str(tmp_path / "new.json"),
        lambda d: cb.doc_goodput(d) is not None, what="goodput section")
    assert path is None and doc is None
    assert "null-valued" in capsys.readouterr().out
    # the compile-budget gate's auto-discovery goes through the same
    # loud helper: its messages surface there too
    (tmp_path / "BENCH_r7.json").write_text(json.dumps({"parsed": real}))
    new_path = tmp_path / "candidate.json"
    new_path.write_text(json.dumps({"value": 9.0, "compile_seconds": 6.0}))
    assert cb.compile_budget_main(
        ["--compile-budget", str(new_path)]) == 0
    out = capsys.readouterr().out
    assert "null-valued" in out and "BENCH_r7.json" in out, out


def test_pipeline_plan_gate_never_raises_on_corrupt_docs():
    """Corrupt artifacts must FAIL the gate with a message, not kill it
    with a traceback (review hardening)."""
    sys.path.insert(0, REPO)
    try:
        from ci.check_bench import check_pipeline_plan
    finally:
        sys.path.remove(REPO)
    base = {"n_chips": 8,
            "parallel_plan": {"dp": 4, "pp": 2, "schedule": "gpipe",
                              "n_microbatches": 4, "virtual_stages": 1},
            "bubble_fraction": 0.2}
    for mutate in (
            lambda d: d["parallel_plan"].update(schedule="xyz"),
            lambda d: d["parallel_plan"].update(n_microbatches="many"),
            lambda d: d["parallel_plan"].update(
                schedule="interleaved", n_microbatches=10**9),
            lambda d: d.update(bubble_fraction="0.2x"),
            lambda d: d.update(parallel_plan=["dp", 4]),
            lambda d: d["parallel_plan"].update(pp=0),
    ):
        doc = json.loads(json.dumps(base))
        mutate(doc)
        problem = check_pipeline_plan(doc)
        assert isinstance(problem, str) and problem, doc
