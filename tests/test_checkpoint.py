"""Checkpoint/resume tests: save sharded training state, restore onto the
same and onto a DIFFERENT mesh layout (the elastic re-meshing contract).
The default ``Checkpointer`` is the native sharded store; the orbax
wrapper survives as an optional back-compat path (gated test at the
bottom)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from horovod_tpu.parallel import build_mesh
from horovod_tpu.train.checkpoint import Checkpointer, OrbaxCheckpointer


def _state(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P
    params = {
        "w": jax.device_put(jnp.arange(64.0).reshape(8, 8),
                            NamedSharding(mesh, P("dp"))),
        "b": jax.device_put(jnp.ones(8), NamedSharding(mesh, P())),
    }
    return params


def test_save_restore_roundtrip(tmp_path):
    mesh = build_mesh(dp=8)
    params = _state(mesh)
    ckpt = Checkpointer(str(tmp_path / "run"))
    ckpt.save(0, {"params": params, "step": 0}, wait=True)
    assert ckpt.latest_step() == 0
    out = ckpt.restore_latest(like={"params": params, "step": 0})
    np.testing.assert_allclose(np.asarray(out["params"]["w"]),
                               np.arange(64.0).reshape(8, 8))
    assert int(out["step"]) == 0
    ckpt.close()


def test_restore_onto_different_mesh(tmp_path):
    """Save sharded over dp=8, restore sharded over dp=2/tp=4 — what an
    elastic world-size change requires."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh_a = build_mesh(dp=8)
    params = _state(mesh_a)
    ckpt = Checkpointer(str(tmp_path / "run"))
    ckpt.save(3, {"params": params}, wait=True)

    mesh_b = build_mesh(dp=2, tp=4)
    like = {"params": {
        "w": jax.ShapeDtypeStruct((8, 8), jnp.float32,
                                  sharding=NamedSharding(mesh_b,
                                                         P("dp", "tp"))),
        "b": jax.ShapeDtypeStruct((8,), jnp.float32,
                                  sharding=NamedSharding(mesh_b, P())),
    }}
    out = ckpt.restore(3, like)
    w = out["params"]["w"]
    np.testing.assert_allclose(np.asarray(w), np.arange(64.0).reshape(8, 8))
    assert w.sharding.spec == P("dp", "tp")
    ckpt.close()


def test_max_to_keep(tmp_path):
    mesh = build_mesh(dp=8)
    params = _state(mesh)
    ckpt = Checkpointer(str(tmp_path / "run"), max_to_keep=2)
    for step in range(4):
        ckpt.save(step, {"params": params}, wait=True)
    assert ckpt.latest_step() == 3
    assert len(ckpt.all_steps()) <= 2
    ckpt.close()


def test_orbax_wrapper_roundtrip(tmp_path):
    """The optional orbax path keeps working when orbax is installed
    (without it, OrbaxCheckpointer raises an ImportError that names the
    native store as the default)."""
    pytest.importorskip("orbax.checkpoint")
    mesh = build_mesh(dp=8)
    params = _state(mesh)
    ckpt = OrbaxCheckpointer(str(tmp_path / "run"))
    ckpt.save(1, {"params": params}, wait=True)
    assert ckpt.latest_step() == 1
    out = ckpt.restore_latest(like={"params": params})
    np.testing.assert_allclose(np.asarray(out["params"]["w"]),
                               np.arange(64.0).reshape(8, 8))
    ckpt.close()
