"""Launcher unit tests (reference analog: test/single/test_run.py — arg
parsing, host parsing, slot assignment) plus real localhost integration runs
(reference analog: test/integration/test_static_run.py)."""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from horovod_tpu.core import core_available
from horovod_tpu.runner.hosts import (HostInfo, get_host_assignments,
                                      parse_hostfile, parse_hosts)
from horovod_tpu.runner.launch import knobs_to_env, parse_args, resolve_hosts

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- parsing ----------------------------------------------------------------

def test_parse_hosts():
    hosts = parse_hosts("h1:4,h2:2,h3")
    assert [(h.hostname, h.slots) for h in hosts] == [
        ("h1", 4), ("h2", 2), ("h3", 1)]


def test_parse_hostfile(tmp_path):
    p = tmp_path / "hostfile"
    p.write_text("# comment\nh1 slots=4\nh2 slots=2\nh3\n")
    hosts = parse_hostfile(str(p))
    assert [(h.hostname, h.slots) for h in hosts] == [
        ("h1", 4), ("h2", 2), ("h3", 1)]


def test_slot_assignment():
    slots = get_host_assignments(parse_hosts("a:2,b:2"), 4)
    assert [s.rank for s in slots] == [0, 1, 2, 3]
    assert [s.hostname for s in slots] == ["a", "a", "b", "b"]
    assert [s.local_rank for s in slots] == [0, 1, 0, 1]
    assert [s.cross_rank for s in slots] == [0, 0, 1, 1]
    assert all(s.size == 4 for s in slots)
    assert all(s.local_size == 2 for s in slots)
    env = slots[2].to_env()
    assert env["HOROVOD_RANK"] == "2"
    assert env["HOROVOD_CROSS_RANK"] == "1"


def test_slot_assignment_too_few():
    with pytest.raises(ValueError):
        get_host_assignments(parse_hosts("a:1"), 2)


def test_parse_args_and_knobs():
    args = parse_args(["-np", "4", "-H", "localhost:4", "--autotune",
                       "--fusion-threshold-mb", "32",
                       "--cycle-time-ms", "2.5",
                       "python", "train.py", "--lr", "0.1"])
    assert args.num_proc == 4
    assert args.command == ["python", "train.py", "--lr", "0.1"]
    env = knobs_to_env(args)
    assert env["HOROVOD_FUSION_THRESHOLD"] == str(32 * 1024 * 1024)
    assert env["HOROVOD_CYCLE_TIME"] == "2.5"
    assert env["HOROVOD_AUTOTUNE"] == "1"
    assert [(h.hostname, h.slots) for h in resolve_hosts(args)] == [
        ("localhost", 4)]


def test_config_file_yaml_to_env_to_cpp_parser(tmp_path):
    """Full round trip: YAML --config-file -> parsed args (CLI flags
    override) -> worker env -> the REAL C++ env parser (hvd_cfg_dump,
    capi.cc) reports the same values (reference: config_parser.py YAML
    schema + set_env_from_args)."""
    cfg = tmp_path / "hvd.yaml"
    cfg.write_text(textwrap.dedent("""\
        verbose: true
        start-timeout: 120
        elastic-timeout: 300
        slots: 4
        params:
          fusion-threshold-mb: 32
          cycle-time-ms: 2.5
          cache-capacity: 512
          hierarchical-allreduce: true
        autotune:
          enabled: true
          warmup_samples: 7
          gaussian-process-noise: 0.5
        timeline:
          filename: /tmp/tl.json
          mark-cycles: true
        stall_check:
          enabled: true
          warning_time_seconds: 33
        library_options:
          thread-affinity: 1
          gloo-timeout-seconds: 77
        logging:
          level: DEBUG
          hide-timestamp: true
        """))
    # CLI gives cycle-time 9.0 explicitly: it must beat the config's 2.5
    args = parse_args(["-np", "2", "--config-file", str(cfg),
                       "--cycle-time-ms", "9.0", "python", "t.py"])
    assert args.verbose is True
    assert args.start_timeout == 120
    assert args.elastic_timeout == 300
    assert args.slots_per_host == 4
    assert args.cycle_time_ms == 9.0          # CLI wins
    assert args.fusion_threshold_mb == 32     # config fills the rest
    assert args.no_stall_check is False       # enabled: true
    assert args.autotune is True
    env = knobs_to_env(args)
    assert env["HOROVOD_CYCLE_TIME"] == "9.0"
    assert env["HOROVOD_STALL_CHECK_TIME_SECONDS"] == "33.0"
    assert env["HOROVOD_LOG_LEVEL"] == "DEBUG"
    if not core_available():
        pytest.skip("libhvdcore.so not built: C++ leg skipped")
    # the C++ parser leg: a fresh process with exactly this env
    code = textwrap.dedent("""\
        from horovod_tpu.core.core_backend import _load_lib
        lib = _load_lib()
        print(lib.hvd_cfg_dump().decode())
        """)
    r = subprocess.run(
        [sys.executable, "-c", code],
        env={**os.environ, **env, "PYTHONPATH": REPO},
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr[-800:]
    dump = dict(line.split("=", 1)
                for line in r.stdout.strip().splitlines() if "=" in line)
    assert dump["fusion_threshold"] == str(32 * 1024 * 1024)
    assert float(dump["cycle_time_ms"]) == 9.0
    assert dump["cache_capacity"] == "512"
    assert dump["hierarchical_allreduce"] == "1"
    assert dump["autotune"] == "1"
    assert dump["autotune_warmup_samples"] == "7"
    assert float(dump["autotune_gp_noise"]) == 0.5
    assert float(dump["stall_warning_secs"]) == 33.0
    assert dump["timeline"] == "/tmp/tl.json"
    assert dump["timeline_mark_cycles"] == "1"
    assert dump["thread_affinity"] == "1"
    assert float(dump["rendezvous_timeout_secs"]) == 77.0


def test_config_file_validation_rejects_negative(tmp_path):
    cfg = tmp_path / "bad.yaml"
    cfg.write_text("params:\n  cache-capacity: -5\n")
    with pytest.raises(ValueError, match="cache_capacity"):
        parse_args(["--config-file", str(cfg), "python", "t.py"])


def test_config_file_ignores_command_flags_and_honors_abbrev(tmp_path):
    """The explicit-flag probe stops at the command boundary (the train
    script's own flags are not launcher overrides and must not crash the
    probe) and treats abbreviated launcher flags as explicit."""
    cfg = tmp_path / "c.yaml"
    cfg.write_text("verbose: true\nparams:\n  cycle-time-ms: 2.5\n")
    # the command's own --timeline-filename (valueless, last token) and
    # --verbose belong to the script, not the launcher
    args = parse_args(["--config-file", str(cfg),
                       "python", "t.py", "--timeline-filename",
                       "--verbose"])
    assert args.verbose is True                # config applies
    assert args.cycle_time_ms == 2.5
    assert args.command == ["python", "t.py", "--timeline-filename",
                            "--verbose"]
    # an ABBREVIATED launcher flag still beats the config
    args2 = parse_args(["--config-file", str(cfg), "--cycle-time", "9.0",
                        "python", "t.py"])
    assert args2.cycle_time_ms == 9.0


def test_config_file_coerces_quoted_numbers(tmp_path):
    cfg = tmp_path / "c.yaml"
    cfg.write_text("start-timeout: '120'\nslots: '4'\n")
    args = parse_args(["--config-file", str(cfg), "python", "t.py"])
    assert args.start_timeout == 120.0
    assert args.slots_per_host == 4
    bad = tmp_path / "bad.yaml"
    bad.write_text("start-timeout: abc\n")
    with pytest.raises(ValueError, match="start_timeout|start-timeout"):
        parse_args(["--config-file", str(bad), "python", "t.py"])


def test_config_file_stall_check_disable_inverts(tmp_path):
    """stall_check.enabled: false becomes no_stall_check=True (reference
    inverts the same way); an explicit CLI --no-stall-check wins."""
    cfg = tmp_path / "s.yaml"
    cfg.write_text("stall_check:\n  enabled: false\n")
    args = parse_args(["--config-file", str(cfg), "python", "t.py"])
    assert args.no_stall_check is True
    assert knobs_to_env(args)["HOROVOD_STALL_CHECK_DISABLE"] == "1"


def test_start_timeout_maps_to_mesh_deadline():
    """--start-timeout bounds the static mesh connect unless the user set
    --gloo-timeout-seconds explicitly."""
    args = parse_args(["--start-timeout", "45", "python", "t.py"])
    env = knobs_to_env(args)
    assert "HOROVOD_GLOO_TIMEOUT_SECONDS" not in env  # mapped at launch
    args2 = parse_args(["--start-timeout", "45",
                        "--gloo-timeout-seconds", "60", "python", "t.py"])
    assert knobs_to_env(args2)["HOROVOD_GLOO_TIMEOUT_SECONDS"] == "60.0"


def test_slots_per_host_defaults_discovery_lines(tmp_path):
    """Bare hostnames from a discovery script get --slots-per-host slots
    (reference: --slots)."""
    from horovod_tpu.runner.elastic.discovery import HostDiscoveryScript
    script = tmp_path / "disc.sh"
    script.write_text("#!/bin/sh\necho hostA\necho hostB:8\n")
    script.chmod(0o755)
    d = HostDiscoveryScript(str(script), default_slots=4)
    assert d.find_available_hosts_and_slots() == {"hostA": 4, "hostB": 8}


def test_full_knob_set_mirrors_to_env():
    """Every reference config_parser knob reaches the workers' env
    (docs/KNOBS.md table; reference: config_parser.set_env_from_args)."""
    args = parse_args([
        "-np", "2",
        "--fusion-threshold-mb", "8", "--cycle-time-ms", "0.5",
        "--cache-capacity", "2048",
        "--hierarchical-allreduce", "--hierarchical-allgather",
        "--autotune", "--autotune-log-file", "/tmp/at.log",
        "--autotune-warmup-samples", "5",
        "--autotune-steps-per-sample", "20",
        "--autotune-bayes-opt-max-samples", "40",
        "--autotune-gaussian-process-noise", "1e-5",
        "--timeline-filename", "/tmp/tl.json", "--timeline-mark-cycles",
        "--no-stall-check",
        "--stall-warning-timeout-seconds", "30",
        "--stall-shutdown-timeout-seconds", "120",
        "--gloo-timeout-seconds", "45",
        "--thread-affinity", "0",
        "--log-level", "DEBUG", "--log-hide-timestamp",
        "python", "t.py"])
    env = knobs_to_env(args)
    assert env == {
        "HOROVOD_FUSION_THRESHOLD": str(8 * 1024 * 1024),
        "HOROVOD_CYCLE_TIME": "0.5",
        "HOROVOD_CACHE_CAPACITY": "2048",
        "HOROVOD_HIERARCHICAL_ALLREDUCE": "1",
        "HOROVOD_HIERARCHICAL_ALLGATHER": "1",
        "HOROVOD_AUTOTUNE": "1",
        "HOROVOD_AUTOTUNE_LOG": "/tmp/at.log",
        "HOROVOD_AUTOTUNE_WARMUP_SAMPLES": "5",
        "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE": "20",
        "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES": "40",
        "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE": "1e-05",
        "HOROVOD_TIMELINE": "/tmp/tl.json",
        "HOROVOD_TIMELINE_MARK_CYCLES": "1",
        "HOROVOD_STALL_CHECK_DISABLE": "1",
        "HOROVOD_STALL_CHECK_TIME_SECONDS": "30.0",
        "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS": "120.0",
        "HOROVOD_GLOO_TIMEOUT_SECONDS": "45.0",
        "HOROVOD_THREAD_AFFINITY": "0",
        "HOROVOD_LOG_LEVEL": "DEBUG",
        "HOROVOD_LOG_HIDE_TIME": "1",
    }


def test_env_round_trips_into_core(monkeypatch):
    """Env knobs must reach the C++ engine's parsed config (KNOBS.md
    'Consumed by: C++ core' rows)."""
    pytest.importorskip("horovod_tpu.core.core_backend")
    from horovod_tpu.core import core_available
    if not core_available():
        pytest.skip("libhvdcore.so not built")
    from horovod_tpu.core.bindings import core_config_dump
    monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", "123456")
    monkeypatch.setenv("HVD_TPU_CYCLE_TIME", "7.5")   # alias wins
    monkeypatch.setenv("HOROVOD_CYCLE_TIME", "9.9")
    monkeypatch.setenv("HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", "90")
    monkeypatch.setenv("HOROVOD_GLOO_TIMEOUT_SECONDS", "12")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", "33")
    monkeypatch.setenv("HOROVOD_THREAD_AFFINITY", "2")
    monkeypatch.setenv("HOROVOD_TIMELINE_MARK_CYCLES", "1")
    cfg = core_config_dump()
    assert cfg["fusion_threshold"] == "123456"
    assert cfg["cycle_time_ms"] == "7.5"
    assert cfg["stall_shutdown_secs"] == "90"
    assert cfg["rendezvous_timeout_secs"] == "12"
    assert cfg["autotune_max_samples"] == "33"
    assert cfg["thread_affinity"] == "2"
    assert cfg["timeline_mark_cycles"] == "1"


def test_parse_args_requires_command(capsys):
    with pytest.raises(SystemExit):
        parse_args(["-np", "2"])


def test_check_build_report():
    """--check-build prints the capability table without needing a command
    (reference: horovodrun --check-build, launch.py:110-155)."""
    from horovod_tpu.runner.launch import check_build, run_commandline
    report = check_build()
    assert "[X] JAX" in report
    assert "TCP core" in report
    # no command required with -cb, and it exits cleanly
    assert run_commandline(["--check-build"]) == 0


# -- integration: real hvdrun on localhost ----------------------------------

needs_core = pytest.mark.skipif(not core_available(),
                                reason="libhvdcore.so not built")

WORKER_PROG = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, %r)
    import numpy as np
    from horovod_tpu.core.core_backend import CoreBackend
    from horovod_tpu.ops.reduce_op import ReduceOp
    be = CoreBackend()
    out = be.allreduce_async("t", np.ones(4, np.float32),
                             ReduceOp.SUM).wait(30)
    assert float(out[0]) == be.size, out
    print(f"rank {be.rank}/{be.size} ok")
    be.shutdown()
""" % REPO)


@needs_core
def test_hvdrun_static_localhost(tmp_path):
    prog = tmp_path / "worker.py"
    prog.write_text(WORKER_PROG)
    rc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         "-H", "localhost:2", sys.executable, str(prog)],
        cwd=REPO, capture_output=True, timeout=120)
    assert rc.returncode == 0, rc.stdout.decode() + rc.stderr.decode()


@needs_core
def test_hvdrun_propagates_failure(tmp_path):
    prog = tmp_path / "worker.py"
    prog.write_text("import sys; sys.exit(3)")
    rc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         sys.executable, str(prog)],
        cwd=REPO, capture_output=True, timeout=60)
    assert rc.returncode != 0


def test_interactive_run():
    if not core_available():
        pytest.skip("libhvdcore.so not built")
    code = textwrap.dedent("""
        import sys
        sys.path.insert(0, %r)
        from horovod_tpu.runner import run

        def work(x):
            import horovod_tpu as hvd
            return hvd.rank() * 10 + x

        print(run(work, args=(7,), np=2))
    """ % REPO)
    rc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                        timeout=120, cwd=REPO)
    assert rc.returncode == 0, rc.stdout.decode() + rc.stderr.decode()
    assert "[7, 17]" in rc.stdout.decode()


@needs_core
def test_hvdrun_output_filename(tmp_path):
    """--output-filename collects per-worker output under
    <dir>/rank.N/{stdout,stderr} (reference: horovodrun
    --output-filename)."""
    prog = tmp_path / "worker.py"
    prog.write_text(
        "import os, sys\n"
        "print('hello from rank', os.environ['HOROVOD_RANK'])\n"
        "print('warn', os.environ['HOROVOD_RANK'], file=sys.stderr)\n")
    out_dir = tmp_path / "logs"
    rc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         "--output-filename", str(out_dir),
         sys.executable, str(prog)],
        cwd=REPO, capture_output=True, timeout=120)
    assert rc.returncode == 0, rc.stdout.decode() + rc.stderr.decode()
    for r in (0, 1):
        out = (out_dir / f"rank.{r}" / "stdout").read_text()
        assert f"hello from rank {r}" in out, out
        err = (out_dir / f"rank.{r}" / "stderr").read_text()
        assert f"warn {r}" in err, err


@needs_core
def test_hvdrun_timestamped_output(tmp_path):
    """--prefix-output-with-timestamp stamps every pumped line
    (reference flag of the same name)."""
    prog = tmp_path / "worker.py"
    prog.write_text("print('stamped line')\n")
    out_dir = tmp_path / "logs"
    rc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "1",
         "--output-filename", str(out_dir),
         "--prefix-output-with-timestamp",
         sys.executable, str(prog)],
        cwd=REPO, capture_output=True, timeout=120)
    assert rc.returncode == 0, rc.stdout.decode() + rc.stderr.decode()
    line = (out_dir / "rank.0" / "stdout").read_text().strip()
    # "YYYY-MM-DD HH:MM:SS stamped line"
    assert line.endswith("stamped line") and line[:4].isdigit(), line
