"""Worker proving distributed == serial: 2-process DP training must produce
bit-comparable weights to single-process full-batch training (reference
analog: the convergence guarantees its allreduce semantics imply)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

import horovod_tpu as hvd  # noqa: E402


def serial_reference(X, Y, steps, lr):
    params = {"w": jnp.zeros((8, 2))}
    tx = optax.sgd(lr)
    st = tx.init(params)
    gf = jax.jit(jax.value_and_grad(
        lambda p, x, y: jnp.mean((x @ p["w"] - y) ** 2)))
    for _ in range(steps):
        _, g = gf(params, X, Y)
        u, st = tx.update(g, st, params)
        params = optax.apply_updates(params, u)
    return params


def main():
    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    rng = np.random.RandomState(0)
    W_true = rng.randn(8, 2).astype(np.float32)
    X = rng.randn(32, 8).astype(np.float32)
    Y = X @ W_true

    # distributed: each rank holds an equal contiguous shard; grads averaged
    shard = 32 // size
    Xs = jnp.asarray(X[rank * shard:(rank + 1) * shard])
    Ys = jnp.asarray(Y[rank * shard:(rank + 1) * shard])
    params = {"w": jnp.zeros((8, 2))}
    tx = hvd.DistributedOptimizer(optax.sgd(0.1))
    st = tx.init(params)
    gf = jax.jit(jax.value_and_grad(
        lambda p, x, y: jnp.mean((x @ p["w"] - y) ** 2)))
    for _ in range(40):
        _, g = gf(params, Xs, Ys)
        u, st = tx.update(g, st, params)  # eager allreduce(mean) via core
        params = optax.apply_updates(params, u)

    ref = serial_reference(jnp.asarray(X), jnp.asarray(Y), 40, 0.1)
    # mean of shard-mean grads == full-batch mean grad (equal shards), so
    # the trajectories must agree to float tolerance
    np.testing.assert_allclose(np.asarray(params["w"]),
                               np.asarray(ref["w"]), rtol=1e-5, atol=1e-6)
    print(f"rank {rank}: distributed == serial ✓", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
