"""Full-stack worker: the public hvd API over the native core with jax-cpu
arrays (launched by test_core_multiprocess.py)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

import horovod_tpu as hvd  # noqa: E402


def main():
    rank = int(os.environ["HOROVOD_RANK"])
    size = int(os.environ["HOROVOD_SIZE"])
    hvd.init()
    assert hvd.rank() == rank and hvd.size() == size

    # eager allreduce on jax arrays
    x = jnp.arange(8.0) + rank
    out = hvd.allreduce(x, op=hvd.Sum, name="x")
    np.testing.assert_allclose(
        np.asarray(out), sum(np.arange(8.0) + r for r in range(size)))

    # average (the default)
    out = hvd.allreduce(jnp.ones(4) * (rank + 1), name="avg")
    np.testing.assert_allclose(np.asarray(out),
                               np.mean([r + 1 for r in range(size)]))

    # broadcast_parameters + broadcast_object
    params = {"w": jnp.full((3,), float(rank)), "b": {"c": jnp.ones(2) * rank}}
    params = hvd.broadcast_parameters(params, root_rank=0)
    np.testing.assert_allclose(np.asarray(params["w"]), 0.0)
    obj = hvd.broadcast_object({"val": rank * 7}, root_rank=1)
    assert obj == {"val": 7}

    # allgather_object: arbitrary (differently-sized) python objects,
    # rank-ordered (reference: torch/functions.py:233-266)
    gathered = hvd.allgather_object({"rank": rank, "pad": "x" * (rank * 13)})
    assert [g["rank"] for g in gathered] == list(range(size))
    assert all(len(g["pad"]) == 13 * g["rank"] for g in gathered)

    # DistributedOptimizer: eager grads differ per rank, must sync to mean
    tx = hvd.DistributedOptimizer(optax.sgd(1.0))
    p = {"w": jnp.zeros(4)}
    st = tx.init(p)
    grads = {"w": jnp.full(4, float(rank + 1))}
    updates, st = tx.update(grads, st, p)
    mean_grad = np.mean([r + 1 for r in range(size)])
    np.testing.assert_allclose(np.asarray(updates["w"]), -mean_grad)

    # allgather (ragged)
    rows = rank + 1
    g = hvd.allgather(jnp.ones((rows, 2)) * rank, name="ag")
    assert np.asarray(g).shape == (sum(r + 1 for r in range(size)), 2)

    # alltoall even splits
    t, rs = hvd.alltoall(jnp.arange(float(size * 2)).reshape(size * 2, 1))
    assert np.asarray(t).shape == (size * 2, 1)

    # process set on ranks [0, 1]
    if size >= 2:
        ps = hvd.add_process_set([0, 1])
        if rank < 2:
            out = hvd.allreduce(jnp.ones(2), op=hvd.Sum, name="ps",
                                process_set=ps)
            np.testing.assert_allclose(np.asarray(out), 2.0)
        hvd.barrier()

    # reducescatter (default backend path: allreduce + slice)
    rsc = hvd.reducescatter(jnp.ones((size * 2, 3)), op=hvd.Sum, name="rs")
    np.testing.assert_allclose(np.asarray(rsc), float(size))
    assert np.asarray(rsc).shape == (2, 3)

    # join
    last = hvd.join()
    assert isinstance(last, int)

    hvd.shutdown()
    print(f"hvd worker {rank}: OK", flush=True)


if __name__ == "__main__":
    main()
