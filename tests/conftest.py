"""Test configuration: force an 8-device virtual CPU mesh.

The TPU analog of the reference's test strategy (SURVEY.md §4): parallel
collective numerics are validated on a multi-device host platform the way the
reference runs Gloo/MPI on localhost.

Note: this environment's sitecustomize may pre-register a TPU plugin and force
``jax_platforms``; we override back to CPU before any backend client exists.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# Anomaly findings arm real device-trace captures by default (ISSUE 9,
# docs/OBSERVABILITY.md "Deep profiling"); any test that provokes one
# must not drop trace directories into the repo checkout — default the
# retention dir to a per-run tmp location (tests that assert capture
# behavior point it at their own tmp_path)
import tempfile  # noqa: E402

os.environ.setdefault(
    "HVD_TPU_PROFILE_DIR",
    os.path.join(tempfile.gettempdir(), f"hvd_profile_test_{os.getpid()}"))

# Same treatment for autopsy bundles (ISSUE 10 satellite): chaos kills /
# hang autopsies flush flight rings to HVD_TPU_AUTOPSY_DIR, which
# defaults to ./hvd_autopsy — debris in the checkout. Tests that assert
# on bundle contents point it at their own tmp_path.
os.environ.setdefault(
    "HVD_TPU_AUTOPSY_DIR",
    os.path.join(tempfile.gettempdir(), f"hvd_autopsy_test_{os.getpid()}"))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# OPT-IN persistent compilation cache (HVD_TEST_COMPILE_CACHE=1): cuts
# the hot suite's XLA:CPU compile time ~35% (InceptionV3 70s -> 46s),
# but on this 1-core box the faster warm-cache dispatch can pile up
# multi-device executions and trip the known XLA:CPU co-scheduling
# SIGABRT (see .claude/skills/verify gotchas) — observed twice at ~90%
# of the full suite with the cache on, never with it off. Default off:
# suite determinism outranks wall clock.
if os.environ.get("HVD_TEST_COMPILE_CACHE") == "1":
    try:
        # honor a user-chosen cache dir; otherwise use repo-local (same
        # value in-process and via env so subprocess workers share it)
        _cache = os.environ.get("JAX_COMPILATION_CACHE_DIR") or \
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), ".jax_cache")
        os.makedirs(_cache, exist_ok=True)
        os.environ["JAX_COMPILATION_CACHE_DIR"] = _cache
        jax.config.update("jax_compilation_cache_dir", _cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
    except Exception:
        pass  # an optimization, never a failure

import pytest  # noqa: E402

assert jax.device_count() == 8, (
    f"tests require the 8-device virtual CPU mesh, got {jax.devices()}")

# Build the native core if it isn't present (kept out of git; ~20 s once).
import subprocess  # noqa: E402

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SO = os.path.join(_REPO, "horovod_tpu", "core", "libhvdcore.so")
if not os.path.exists(_SO):
    try:
        subprocess.run(["make", "-j4"], cwd=os.path.join(_REPO, "cpp"),
                       check=False, capture_output=True, timeout=300)
    except Exception:
        pass  # core tests skip cleanly when the .so is absent


@pytest.fixture(autouse=True, scope="session")
def _no_artifact_debris_in_checkout():
    """Regression guard for the PR 9/10 cleanup (ISSUE 13 satellite): no
    test may leave autopsy bundles, flight dumps, or profiler trace
    dirs in the repo checkout.  The env defaults above route everything
    to tmp; a test overriding them must use its own tmp_path.  Runs at
    session teardown so one stray writer fails the run visibly instead
    of silently re-accumulating debris."""
    import glob

    def debris():
        out = []
        for pat in ("hvd_autopsy", "hvd_profile*",
                    "hvd_flight_rank*.json", "autopsy_rank*",
                    "summary_rank*.json"):
            out += glob.glob(os.path.join(_REPO, pat))
        return sorted(out)

    before = debris()
    yield
    leaked = [p for p in debris() if p not in before]
    assert not leaked, (
        f"test run left autopsy/flight artifacts in the checkout: "
        f"{leaked}; point HVD_TPU_AUTOPSY_DIR / HVD_TPU_PROFILE_DIR / "
        f"flight dumps at tmp_path instead")


@pytest.fixture
def hvd():
    import horovod_tpu as hvd
    hvd.shutdown()
    hvd.init()
    yield hvd
    hvd.shutdown()


@pytest.fixture
def mesh8():
    import horovod_tpu as hvd
    return hvd.build_mesh(dp=2, pp=1, ep=1, sp=2, tp=2)
