"""Test configuration: force an 8-device virtual CPU mesh.

The TPU analog of the reference's test strategy (SURVEY.md §4): parallel
collective numerics are validated on a multi-device host platform the way the
reference runs Gloo/MPI on localhost.

Note: this environment's sitecustomize may pre-register a TPU plugin and force
``jax_platforms``; we override back to CPU before any backend client exists.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

assert jax.device_count() == 8, (
    f"tests require the 8-device virtual CPU mesh, got {jax.devices()}")

# Build the native core if it isn't present (kept out of git; ~20 s once).
import subprocess  # noqa: E402

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SO = os.path.join(_REPO, "horovod_tpu", "core", "libhvdcore.so")
if not os.path.exists(_SO):
    try:
        subprocess.run(["make", "-j4"], cwd=os.path.join(_REPO, "cpp"),
                       check=False, capture_output=True, timeout=300)
    except Exception:
        pass  # core tests skip cleanly when the .so is absent


@pytest.fixture
def hvd():
    import horovod_tpu as hvd
    hvd.shutdown()
    hvd.init()
    yield hvd
    hvd.shutdown()


@pytest.fixture
def mesh8():
    import horovod_tpu as hvd
    return hvd.build_mesh(dp=2, pp=1, ep=1, sp=2, tp=2)
