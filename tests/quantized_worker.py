"""Quantized eager allreduce over the native core (launched by
test_core_multiprocess.py): int8 payloads move over the TCP wire
(allgather-of-codes + local dequantize/reduce), numerics match the
locally recomputed expectation exactly, the EF-wrapped
DistributedOptimizer syncs in the eager regime, and the compression
metrics report > 3.5x for the int8 path (ISSUE 2 acceptance)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu.compression import Compression, ErrorFeedback  # noqa: E402
from horovod_tpu.compression.metrics import compression_ratio  # noqa: E402


def _rank_tensor(r, n=4096, seed=0):
    return jnp.asarray(np.random.RandomState(seed + r).randn(n), jnp.float32)


def main():
    rank = int(os.environ["HOROVOD_RANK"])
    size = int(os.environ["HOROVOD_SIZE"])
    hvd.init()
    assert hvd.rank() == rank and hvd.size() == size
    q = Compression.int8

    # quantized allreduce: every rank can recompute the EXACT expectation
    # locally — sum over ranks of each contribution's quantize∘dequantize
    x = _rank_tensor(rank)
    out = hvd.quantized_allreduce(x, q, op=hvd.Sum, name="qsum")
    expect = sum(np.asarray(q.qdq(_rank_tensor(r))) for r in range(size))
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5,
                               atol=1e-5)
    # ...and the quantized sum tracks the true fp32 sum within the codec
    # bound (one quantization step per contribution)
    true = sum(np.asarray(_rank_tensor(r)) for r in range(size))
    assert np.abs(np.asarray(out) - true).max() <= \
        size * np.abs(true).max() / 254 + 1e-5

    # grouped: both leaves fuse into one negotiation cycle
    y = _rank_tensor(rank, seed=100)
    outs = hvd.quantized_grouped_allreduce([x, y], q, op=hvd.Average,
                                           name="qgrp")
    expect_y = sum(np.asarray(q.qdq(_rank_tensor(r, seed=100)))
                   for r in range(size)) / size
    np.testing.assert_allclose(np.asarray(outs[1]), expect_y, rtol=1e-5,
                               atol=1e-5)

    # EF-wrapped DistributedOptimizer in the EAGER regime: the wire moves
    # int8, every rank lands on the identical averaged update
    tx = hvd.DistributedOptimizer(optax.sgd(1.0),
                                  compression=ErrorFeedback(q))
    params = {"w": jnp.zeros(2048)}
    st = tx.init(params)
    g = {"w": _rank_tensor(rank, n=2048, seed=7)}
    updates, st = tx.update(g, st, params)
    expect_u = -sum(np.asarray(q.qdq(_rank_tensor(r, n=2048, seed=7)))
                    for r in range(size)) / size
    np.testing.assert_allclose(np.asarray(updates["w"]), expect_u,
                               rtol=1e-5, atol=1e-5)

    # ISSUE 6: bucketed eager path parity under int8+EF — the same tree
    # synced through many per-bucket async groups and through the single
    # grouped call must land on IDENTICAL values (per-leaf codec math is
    # order-independent), and the overlap metrics must be recorded.
    from horovod_tpu.common.config import reset_config

    def _ef_update(bucket_env):
        os.environ.update(bucket_env)
        reset_config()
        tx2 = hvd.DistributedOptimizer(optax.sgd(1.0),
                                       compression=ErrorFeedback(q))
        params2 = {f"l{i}": jnp.zeros(512) for i in range(6)}
        st2 = tx2.init(params2)
        g2 = {f"l{i}": _rank_tensor(rank, n=512, seed=20 + i)
              for i in range(6)}
        u2, _ = tx2.update(g2, st2, params2)
        return u2

    # 512 floats = 2 KiB/leaf, 4 KiB budget -> 3 buckets of 2 leaves
    u_bucketed = _ef_update({"HVD_TPU_BUCKET_BYTES": "4096"})
    reg = hvd.metrics_snapshot()["registry"]
    assert reg["hvd_overlap_bucket_count"]["value"] == 3, \
        reg.get("hvd_overlap_bucket_count")
    assert "hvd_overlap_exposed_comm_seconds" in reg, sorted(
        k for k in reg if "overlap" in k)
    u_single = _ef_update({"HVD_TPU_OVERLAP_BUCKETS": "0"})
    for k in u_single:
        np.testing.assert_array_equal(np.asarray(u_bucketed[k]),
                                      np.asarray(u_single[k]))
    os.environ.pop("HVD_TPU_BUCKET_BYTES")
    os.environ.pop("HVD_TPU_OVERLAP_BUCKETS")
    reset_config()

    # acceptance: the int8 path's cumulative pre/wire ratio on the
    # metrics registry (scraped by /metrics) exceeds 3.5x
    ratio = compression_ratio("int8")
    assert ratio > 3.5, ratio
    reg = hvd.metrics_snapshot()["registry"]
    key = 'hvd_compression_ratio{codec="int8"}'
    assert key in reg and reg[key]["value"] > 3.5, sorted(reg)

    hvd.shutdown()
    print(f"quantized worker {rank}: OK ratio={ratio:.2f}", flush=True)


if __name__ == "__main__":
    main()
