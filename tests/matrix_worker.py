"""Collective numerics matrix worker — the depth analog of the reference's
test/parallel suite (test_torch.py / test_tensorflow.py): every supported
dtype x shape class (scalar / empty / odd / fusion-threshold-crossing) x
op x process set, asserting EXACT numerics and dtype preservation.

Backend-agnostic: run under the TCP core (default) or the XLA data plane
(HOROVOD_TPU_OPERATIONS=XLA_EAGER). Launched by test_core_multiprocess.py.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)  # int64/f64 must round-trip

import numpy as np  # noqa: E402
import ml_dtypes  # noqa: E402

import horovod_tpu as hvd  # noqa: E402

BF16 = np.dtype(ml_dtypes.bfloat16)

INT_DTYPES = [np.dtype(np.uint8), np.dtype(np.int8), np.dtype(np.int32),
              np.dtype(np.int64)]
FLOAT_DTYPES = [np.dtype(np.float16), BF16, np.dtype(np.float32),
                np.dtype(np.float64)]
ALL_NUMERIC = INT_DTYPES + FLOAT_DTYPES

# shape classes: scalar, empty, single-element, odd, >512B (fusion-crossing
# for f32 when HVD_TPU_FUSION_THRESHOLD=512)
SHAPES = [(), (0,), (1,), (7, 3), (256,)]


def gen(dtype, shape, rank, base=1, mod=5):
    """Small exact values: <= mod+size, exactly representable everywhere."""
    n = int(np.prod(shape, dtype=np.int64))
    v = (np.arange(n, dtype=np.int64) % mod) + rank + base
    return v.reshape(shape).astype(dtype)


def stack_all(dtype, shape, size, **kw):
    return np.stack([gen(dtype, shape, r, **kw).astype(np.float64)
                     for r in range(size)])


def check(out, expect, dtype, msg):
    out = np.asarray(out)
    assert out.dtype == dtype, f"{msg}: dtype {out.dtype} != {dtype}"
    expect = np.asarray(expect)
    # shape must match EXACTLY (assert_array_equal would broadcast a
    # (1,) result against a () expectation — the r2 scalar-shape bug)
    assert out.shape == expect.shape, \
        f"{msg}: shape {out.shape} != {expect.shape}"
    np.testing.assert_array_equal(
        out.astype(np.float64), expect.astype(np.float64), err_msg=msg)


def main():
    rank = int(os.environ["HOROVOD_RANK"])
    size = int(os.environ["HOROVOD_SIZE"])
    hvd.init()
    assert hvd.rank() == rank and hvd.size() == size

    # 1) SUM allreduce: every numeric dtype x every shape class
    for dt in ALL_NUMERIC:
        for shape in SHAPES:
            x = gen(dt, shape, rank)
            out = hvd.allreduce(x, op=hvd.Sum, name=f"s.{dt}.{shape}")
            expect = stack_all(dt, shape, size).sum(0).astype(dt)
            check(out, expect, dt, f"sum {dt} {shape}")

    # 2) AVERAGE: float dtypes; sum of (rank+1) -> mean (size+1)/2, exact
    #    in every binary float format for size <= 4
    for dt in FLOAT_DTYPES:
        x = np.full((6,), float(rank + 1), dt)
        out = hvd.allreduce(x, op=hvd.Average, name=f"a.{dt}")
        check(out, np.full((6,), (size + 1) / 2.0), dt, f"avg {dt}")

    # 3) MIN / MAX: all numeric dtypes, odd shape
    for dt in ALL_NUMERIC:
        x = gen(dt, (7, 3), rank)
        st = stack_all(dt, (7, 3), size)
        mn = hvd.allreduce(x, op=hvd.Min, name=f"mn.{dt}")
        mx = hvd.allreduce(x, op=hvd.Max, name=f"mx.{dt}")
        check(mn, st.min(0).astype(dt), dt, f"min {dt}")
        check(mx, st.max(0).astype(dt), dt, f"max {dt}")

    # 4) PRODUCT: values in {1, 2} keep everything exact
    for dt in (np.dtype(np.int32), np.dtype(np.float32),
               np.dtype(np.float64)):
        x = gen(dt, (9,), rank, base=1, mod=2).astype(np.float64)
        x = np.where(x > 1.5, 2.0, 1.0).astype(dt)
        st = np.stack([np.where(
            gen(dt, (9,), r, base=1, mod=2).astype(np.float64) > 1.5,
            2.0, 1.0) for r in range(size)])
        out = hvd.allreduce(x, op=hvd.Product, name=f"p.{dt}")
        check(out, st.prod(0).astype(dt), dt, f"prod {dt}")

    # 5) bool: MIN == logical AND, MAX == logical OR
    xb = ((np.arange(8) + rank) % 2).astype(np.bool_)
    stb = np.stack([((np.arange(8) + r) % 2).astype(np.bool_)
                    for r in range(size)])
    check(hvd.allreduce(xb, op=hvd.Min, name="b.min"),
          stb.min(0), np.dtype(np.bool_), "bool min")
    check(hvd.allreduce(xb, op=hvd.Max, name="b.max"),
          stb.max(0), np.dtype(np.bool_), "bool max")

    # 6) pre/postscale: integral factors on ints OK, fractional must raise
    xf = gen(np.float32, (5,), rank)
    out = hvd.allreduce(xf, op=hvd.Sum, name="sc.f",
                        prescale_factor=2.0, postscale_factor=0.5)
    check(out, stack_all(np.float32, (5,), size).sum(0), np.dtype(np.float32),
          "scaled f32")
    xi = gen(np.int32, (5,), rank)
    out = hvd.allreduce(xi, op=hvd.Sum, name="sc.i", prescale_factor=2.0)
    check(out, stack_all(np.int32, (5,), size).sum(0) * 2,
          np.dtype(np.int32), "prescaled i32")
    for call in (lambda: hvd.allreduce(xi, op=hvd.Sum, name="sc.bad",
                                       prescale_factor=0.5),
                 lambda: hvd.grouped_allreduce([xi], op=hvd.Sum,
                                               name="sc.badg",
                                               prescale_factor=0.5)):
        try:
            call()
            raise AssertionError("fractional int scale must raise")
        except ValueError:
            pass

    # 7) grouped mixed dtypes incl. scalar and empty members
    vals = [gen(np.float32, (7,), rank), gen(np.int32, (3, 2), rank),
            gen(BF16, (5,), rank), gen(np.float32, (), rank),
            gen(np.float32, (0,), rank)]
    outs = hvd.grouped_allreduce(vals, op=hvd.Sum, name="grp")
    for v, o, dt, shape in zip(
            vals, outs,
            [np.dtype(np.float32), np.dtype(np.int32), BF16,
             np.dtype(np.float32), np.dtype(np.float32)],
            [(7,), (3, 2), (5,), (), (0,)]):
        check(o, stack_all(dt, shape, size).sum(0).astype(dt), dt,
              f"grouped {dt} {shape}")

    # 8) many-tensor group crossing the fusion threshold several times
    many = [gen(np.float32, (64,), rank, base=i) for i in range(8)]
    outs = hvd.grouped_allreduce(many, op=hvd.Sum, name="grp.many")
    for i, o in enumerate(outs):
        expect = np.stack([gen(np.float32, (64,), r, base=i).astype(
            np.float64) for r in range(size)]).sum(0)
        check(o, expect, np.dtype(np.float32), f"grp.many[{i}]")

    # 9) ragged allgather: rank r contributes r rows (rank 0: zero rows)
    for dt in (np.dtype(np.float32), np.dtype(np.int64)):
        mine = np.full((rank, 2), rank + 1, dt)
        out = hvd.allgather(mine, name=f"ag.{dt}")
        expect = np.concatenate([np.full((r, 2), r + 1, np.float64)
                                 for r in range(size)], axis=0)
        check(out, expect, dt, f"allgather {dt}")
    # bool allgather with equal rows
    out = hvd.allgather(((np.arange(4) + rank) % 2).astype(np.bool_),
                        name="ag.bool")
    expect = np.concatenate([((np.arange(4) + r) % 2).astype(np.bool_)
                             for r in range(size)])
    check(out, expect, np.dtype(np.bool_), "allgather bool")

    # 10) broadcast: first/last roots, several dtypes, incl. scalar
    for root in (0, size - 1):
        for dt, shape in ((np.dtype(np.float16), (5,)),
                          (np.dtype(np.int64), ()),
                          (np.dtype(np.bool_), (4,))):
            x = gen(dt, shape, rank) if dt != np.bool_ else \
                ((np.arange(4) + rank) % 2).astype(np.bool_)
            out = hvd.broadcast(x, root_rank=root,
                                name=f"bc.{root}.{dt}.{len(shape)}")
            expect = (gen(dt, shape, root) if dt != np.bool_ else
                      ((np.arange(4) + root) % 2).astype(np.bool_))
            check(out, expect.astype(np.float64), dt, f"bcast {root} {dt}")

    # 11) alltoall with zero splits: rank r sends i rows (value r*100+i)
    #     to rank i; rank r receives r rows from every peer
    splits = list(range(size))
    send = np.concatenate(
        [np.full((i, 2), rank * 100 + i, np.float32) for i in range(size)]
    ) if sum(splits) else np.zeros((0, 2), np.float32)
    out, recv = hvd.alltoall(send, splits=splits, name="a2a.zero")
    expect = np.concatenate(
        [np.full((rank, 2), r * 100 + rank, np.float32)
         for r in range(size)]) if rank else np.zeros((0, 2), np.float32)
    check(out, expect, np.dtype(np.float32), "alltoall zero-splits")
    assert list(np.asarray(recv)) == [rank] * size

    # 12) reducescatter over dim 0
    for dt in (np.dtype(np.float32), np.dtype(np.int32)):
        x = gen(dt, (size * 2, 3), rank)
        out = hvd.reducescatter(x, op=hvd.Sum, name=f"rs.{dt}")
        full = stack_all(dt, (size * 2, 3), size).sum(0)
        check(out, full[rank * 2:(rank + 1) * 2], dt, f"rs {dt}")

    # 13) the same core ops inside a process set
    if size >= 2:
        ps = hvd.add_process_set([0, 1])
        if rank < 2:
            x = gen(np.float32, (6,), rank)
            out = hvd.allreduce(x, op=hvd.Sum, name="ps.sum", process_set=ps)
            expect = stack_all(np.float32, (6,), 2).sum(0)
            check(out, expect, np.dtype(np.float32), "ps sum")
            g = hvd.allgather(np.full((rank + 1, 2), rank, np.int32),
                              name="ps.ag", process_set=ps)
            expect = np.concatenate([np.full((r + 1, 2), r, np.int64)
                                     for r in range(2)])
            check(g, expect, np.dtype(np.int32), "ps allgather")

    # 14) join: per-backend visibility (VERDICT r3 weak #3). TCP core:
    #     uneven rank participation drains correctly and every rank agrees
    #     on the last-joined rank. XLA eager: join must raise the
    #     documented NotImplementedError on EVERY rank — the drop-in
    #     surface's backend asymmetry stays visible in the matrix.
    if os.environ.get("HOROVOD_TPU_OPERATIONS", "").upper() == "XLA_EAGER":
        try:
            hvd.join()
            raise AssertionError("XLA eager join() must raise")
        except NotImplementedError as e:
            assert "TCP core" in str(e), e  # actionable routing message
    elif size >= 2:
        if rank % 2 == 1:
            last = hvd.join()
        else:
            out = hvd.allreduce(np.full((4,), 1.0, np.float32),
                                op=hvd.Sum, name="join.post")
            n_even = (size + 1) // 2  # joined ranks contribute zeros
            check(out, np.full((4,), float(n_even)),
                  np.dtype(np.float32), "post-join sum")
            last = hvd.join()
        assert isinstance(last, int), last

    hvd.barrier()
    hvd.shutdown()
    print(f"matrix worker {rank}: OK", flush=True)


if __name__ == "__main__":
    main()
