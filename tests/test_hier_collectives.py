"""phier_allreduce parity battery (ISSUE 8 satellite): the hierarchical
intra-host reduce_scatter → inter-host allreduce → intra-host allgather
must match flat psum within fp tolerance on every tested virtual
topology of the 8-device CPU mesh — Sum and Average, with and without
the int8 codec on the inter-host hop (EQuARX error bound), and the
small-bucket latency floor path must match the dense reduction."""

import functools

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from horovod_tpu._compat import shard_map
from horovod_tpu.common.topology import MeshTopology
from horovod_tpu.compression.quantizers import BlockInt8Quantizer
from horovod_tpu.ops import mesh_collectives as mc
from horovod_tpu.ops.reduce_op import ReduceOp
from horovod_tpu.parallel import build_mesh
from horovod_tpu.train.overlap import bucketed_grad_sync

TOPOLOGIES = [MeshTopology(2, 4), MeshTopology(4, 2), MeshTopology(8, 1)]


@pytest.fixture(scope="module")
def mesh():
    return build_mesh(dp=-1)


def _run_hier(mesh, x, topo, op, codec=None, floor=None):
    @functools.partial(shard_map, mesh=mesh, in_specs=(P("dp"),),
                       out_specs=P("dp"), check_vma=False)
    def body(s):
        out = mc.phier_allreduce(s[0], "dp", topo, op,
                                 inter_codec=codec, small_floor=floor)
        return out[None]

    return np.asarray(jax.jit(body)(jnp.asarray(x)))


def _flat_ref(x, op):
    red = np.sum if op == ReduceOp.SUM else np.mean
    return red(np.asarray(x, np.float64), axis=0,
               keepdims=True).repeat(x.shape[0], 0)


@pytest.mark.parametrize("topo", TOPOLOGIES,
                         ids=["2x4", "4x2", "8x1"])
@pytest.mark.parametrize("op", [ReduceOp.SUM, ReduceOp.AVERAGE],
                         ids=["sum", "avg"])
def test_hier_matches_flat_psum(mesh, topo, op):
    # 37 elements: not divisible by local/world — exercises the padding
    x = np.random.RandomState(0).randn(8, 37).astype(np.float32)
    out = _run_hier(mesh, x, topo, op)
    np.testing.assert_allclose(out, _flat_ref(x, op), atol=1e-4)


@pytest.mark.parametrize("topo", TOPOLOGIES,
                         ids=["2x4", "4x2", "8x1"])
@pytest.mark.parametrize("op", [ReduceOp.SUM, ReduceOp.AVERAGE],
                         ids=["sum", "avg"])
def test_hier_quantized_inter_hop_within_codec_bound(mesh, topo, op):
    x = np.random.RandomState(1).randn(8, 64).astype(np.float32)
    out = _run_hier(mesh, x, topo, op, codec=BlockInt8Quantizer())
    ref = _flat_ref(x, op)
    # one quantization step on the already-reduced inter-host payload:
    # |err| <= absmax/254 per block (docs/PERF.md "Gradient
    # compression") — absmax bounded by the reduced tensor's max
    bound = np.abs(ref).max() / 254 + 1e-6
    assert np.abs(out - ref).max() <= bound


def test_hier_2d_tensor_and_dtype_preserved(mesh):
    x = np.random.RandomState(2).randn(8, 6, 10).astype(np.float32)
    topo = MeshTopology(2, 4)
    out = _run_hier(mesh, x, topo, ReduceOp.AVERAGE)
    assert out.shape == x.shape and out.dtype == np.float32
    np.testing.assert_allclose(out, _flat_ref(x, ReduceOp.AVERAGE),
                               atol=1e-4)


def test_small_floor_takes_dense_path_exactly(mesh):
    """Below the byte floor the hierarchical (and quantized) machinery
    is skipped entirely: the result must be BIT-comparable to flat psum
    — same collective, not merely within codec tolerance."""
    x = np.random.RandomState(3).randn(8, 16).astype(np.float32)
    topo = MeshTopology(2, 4)
    dense = _run_hier(mesh, x, topo, ReduceOp.SUM, floor=None)

    @functools.partial(shard_map, mesh=mesh, in_specs=(P("dp"),),
                       out_specs=P("dp"), check_vma=False)
    def flat(s):
        return mc.preduce(s[0], "dp", ReduceOp.SUM)[None]

    floored = _run_hier(mesh, x, topo, ReduceOp.SUM,
                        codec=BlockInt8Quantizer(), floor=1 << 30)
    ref = np.asarray(jax.jit(flat)(jnp.asarray(x)))
    np.testing.assert_array_equal(floored, ref)
    # and the unfloored hierarchy still agrees within fp tolerance
    np.testing.assert_allclose(dense, ref, atol=1e-4)


def test_topology_mismatch_raises(mesh):
    x = jnp.zeros((8, 4))
    with pytest.raises(Exception, match="does not cover"):
        _run_hier(mesh, np.asarray(x), MeshTopology(2, 2), ReduceOp.SUM)


def test_unsupported_op_raises(mesh):
    with pytest.raises(Exception, match="Sum/Average"):
        _run_hier(mesh, np.zeros((8, 4), np.float32), MeshTopology(2, 4),
                  ReduceOp.MIN)


# -- bucketed_grad_sync wiring (the PR-6 planner seam) ----------------------

def _sync(mesh, g, **kw):
    @functools.partial(shard_map, mesh=mesh, in_specs=(P("dp"),),
                       out_specs=P("dp"), check_vma=False)
    def body(gs):
        loc = jax.tree_util.tree_map(lambda x: x[0], gs)
        out = bucketed_grad_sync(loc, "dp", **kw)
        return jax.tree_util.tree_map(lambda x: x[None], out)

    return jax.jit(body)(g)


def _tree(rng):
    return {"w": jnp.asarray(rng.randn(8, 16, 3).astype(np.float32)),
            "b": jnp.asarray(rng.randn(8, 5).astype(np.float32))}


@pytest.mark.parametrize("topo", TOPOLOGIES[:2], ids=["2x4", "4x2"])
def test_bucketed_sync_hier_matches_dense(mesh, topo):
    g = _tree(np.random.RandomState(4))
    out = _sync(mesh, g, algorithm="hier", topology=topo,
                bucket_bytes=128)
    for got, want in zip(jax.tree_util.tree_leaves(out),
                         jax.tree_util.tree_leaves(g)):
        ref = np.mean(np.asarray(want), axis=0, keepdims=True).repeat(8, 0)
        np.testing.assert_allclose(np.asarray(got), ref, atol=1e-5)


def test_bucketed_sync_hier_quantized_inter_hop(mesh):
    g = _tree(np.random.RandomState(5))
    out = _sync(mesh, g, algorithm="hier", topology=MeshTopology(2, 4),
                compression=BlockInt8Quantizer())
    # the bucket packs all leaves into one vector, so a quantizer block
    # can span leaves: the codec bound is governed by the PACKED
    # vector's absmax, not each leaf's own
    packed_max = max(np.abs(np.mean(np.asarray(l), axis=0)).max()
                     for l in jax.tree_util.tree_leaves(g))
    bound = packed_max / 254 + 1e-6
    for got, want in zip(jax.tree_util.tree_leaves(out),
                         jax.tree_util.tree_leaves(g)):
        ref = np.mean(np.asarray(want), axis=0, keepdims=True).repeat(8, 0)
        assert np.abs(np.asarray(got) - ref).max() <= bound


def test_bucketed_sync_small_floor_skips_codec(mesh):
    """Buckets under the floor move dense even when a codec is set:
    result equals the exact mean, not merely within the codec bound."""
    g = _tree(np.random.RandomState(6))
    out = _sync(mesh, g, compression=BlockInt8Quantizer(),
                small_floor=1 << 30)
    for got, want in zip(jax.tree_util.tree_leaves(out),
                         jax.tree_util.tree_leaves(g)):
        ref = np.mean(np.asarray(want), axis=0, keepdims=True).repeat(8, 0)
        np.testing.assert_allclose(np.asarray(got), ref, atol=1e-6,
                                   rtol=1e-6)


def test_bucketed_sync_ring_with_codec_raises(mesh):
    g = _tree(np.random.RandomState(7))
    with pytest.raises(ValueError, match="no compression seam"):
        _sync(mesh, g, algorithm="ring",
              compression=BlockInt8Quantizer())


def test_bucketed_sync_flat_topology_degrades_to_psum(mesh):
    g = _tree(np.random.RandomState(8))
    out = _sync(mesh, g, algorithm="hier")  # detect: 1x8 on one process
    for got, want in zip(jax.tree_util.tree_leaves(out),
                         jax.tree_util.tree_leaves(g)):
        ref = np.mean(np.asarray(want), axis=0, keepdims=True).repeat(8, 0)
        np.testing.assert_allclose(np.asarray(got), ref, atol=1e-5)
