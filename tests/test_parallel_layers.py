"""Numerical tests for ring attention, Ulysses SP, MoE-EP and pipeline-PP
against single-device oracles (the TPU analog of the reference's
test/parallel numeric-equality suite)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from horovod_tpu.parallel import build_mesh
from horovod_tpu.parallel.ring_attention import (ring_attention,
                                                 _plain_attention)
from horovod_tpu.parallel.ulysses import ulysses_attention
from horovod_tpu.parallel.moe import moe_layer, top_k_gating
from horovod_tpu.parallel.pipeline import (pipeline_apply, stage_stacked)


def _qkv(B=2, S=16, H=4, D=8, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, S, H, D), jnp.float32) * 0.5
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_full(causal):
    mesh = build_mesh(dp=2, sp=4)
    q, k, v = _qkv()
    ref = _plain_attention(q, k, v, causal=causal)
    out = ring_attention(q, k, v, mesh, axis_name="sp", causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_flash_path_matches_full(causal):
    """The Pallas-kernel ring path (per-step flash + logaddexp merge of
    normalized (o, lse) partials) must agree with the full oracle —
    interpret mode stands in for the TPU kernel on the CPU mesh (2-device
    sub-mesh: flash blocks need S/sp >= 256, too big for an 8-way ring on
    the tiny test shapes)."""
    mesh = build_mesh(dp=1, sp=2, devices=jax.devices()[:2])
    rng = np.random.RandomState(3)
    mk = lambda: jnp.asarray(rng.randn(1, 512, 2, 128), jnp.float32) * 0.3
    q, k, v = mk(), mk(), mk()
    ref = _plain_attention(q, k, v, causal=causal)
    out = ring_attention(q, k, v, mesh, axis_name="sp", causal=causal,
                         use_flash=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_attention_flash_path_grads():
    """Training goes through the ring: the flash ring path's gradients
    (custom-VJP kernel + lse merge + ppermute loop) must match autodiff
    through the oracle."""
    mesh = build_mesh(dp=1, sp=2, devices=jax.devices()[:2])
    rng = np.random.RandomState(4)
    mk = lambda: jnp.asarray(rng.randn(1, 256, 2, 128), jnp.float32) * 0.3
    q, k, v = mk(), mk(), mk()

    def loss_ring(q, k, v):
        o = ring_attention(q, k, v, mesh, axis_name="sp", causal=True,
                           use_flash=True, interpret=True)
        return jnp.sum(o ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_plain_attention(q, k, v, causal=True) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gr, gf, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4, err_msg=name)


def test_ring_attention_sp1_fast_path():
    mesh = build_mesh(dp=8)
    q, k, v = _qkv()
    out = ring_attention(q, k, v, mesh, axis_name="sp")
    ref = _plain_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_full(causal):
    mesh = build_mesh(dp=2, sp=4)
    q, k, v = _qkv()
    ref = _plain_attention(q, k, v, causal=causal)
    out = ulysses_attention(q, k, v, mesh, axis_name="sp", causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_top_k_gating_shapes_and_capacity():
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(16, 4), jnp.float32)
    dispatch, combine, metrics = top_k_gating(logits, k=2, capacity=8)
    d = np.asarray(dispatch)
    assert d.shape == (16, 4, 8)
    # each token dispatched at most k times, each slot at most one token
    assert d.sum() <= 16 * 2
    assert np.all(d.sum(axis=0) <= 1.0 + 1e-6)
    assert float(metrics.aux_loss) > 0


def _ffn_expert(p, x):
    return jnp.tanh(x @ p["w1"]) @ p["w2"]


def _expert_params(E, M, Hdim, seed=1):
    rng = np.random.RandomState(seed)
    return {"w1": jnp.asarray(rng.randn(E, M, Hdim), jnp.float32) * 0.1,
            "w2": jnp.asarray(rng.randn(E, Hdim, M), jnp.float32) * 0.1}


def test_moe_ep_matches_single_device():
    E, M, Hd, T = 4, 8, 16, 64
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(T, M), jnp.float32)
    rw = jnp.asarray(rng.randn(M, E), jnp.float32) * 0.1
    ep_params = _expert_params(E, M, Hd)

    mesh1 = build_mesh(dp=8)   # no expert sharding
    y1, m1 = moe_layer(x, rw, _ffn_expert, ep_params, mesh1, token_axes=())
    mesh2 = build_mesh(dp=2, ep=4)  # 4-way expert parallel
    y2, m2 = moe_layer(x, rw, _ffn_expert, ep_params, mesh2, token_axes=())
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(m1.aux_loss), float(m2.aux_loss),
                               rtol=1e-5)


def test_moe_with_token_sharding():
    E, M, Hd, T = 4, 8, 16, 64
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(T, M), jnp.float32)
    rw = jnp.asarray(rng.randn(M, E), jnp.float32) * 0.1
    ep_params = _expert_params(E, M, Hd)
    mesh = build_mesh(dp=2, ep=4)
    y, m = moe_layer(x, rw, _ffn_expert, ep_params, mesh, token_axes=("dp",))
    assert y.shape == (T, M)
    assert np.all(np.isfinite(np.asarray(y)))


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def test_pipeline_matches_sequential():
    S, T, M = 4, 16, 8
    rng = np.random.RandomState(4)
    stages = [{"w": jnp.asarray(rng.randn(M, M), jnp.float32) * 0.5,
               "b": jnp.asarray(rng.randn(M), jnp.float32) * 0.1}
              for _ in range(S)]
    x = jnp.asarray(rng.randn(T, M), jnp.float32)

    ref = x
    for p in stages:
        ref = _stage_fn(p, ref)

    mesh = build_mesh(dp=2, pp=4)
    stacked = stage_stacked(stages)
    out = pipeline_apply(_stage_fn, stacked, x, mesh, n_microbatches=4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_pp1_fast_path():
    rng = np.random.RandomState(5)
    p = [{"w": jnp.asarray(rng.randn(8, 8), jnp.float32),
          "b": jnp.zeros(8, jnp.float32)}]
    x = jnp.asarray(rng.randn(6, 8), jnp.float32)
    mesh = build_mesh(dp=8)
    out = pipeline_apply(_stage_fn, stage_stacked(p), x, mesh,
                         n_microbatches=2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_stage_fn(p[0], x)),
                               rtol=1e-6)


def test_pipeline_bad_microbatch_count():
    mesh = build_mesh(dp=2, pp=4)
    p = stage_stacked([{"w": jnp.eye(4), "b": jnp.zeros(4)}] * 4)
    with pytest.raises(ValueError):
        pipeline_apply(_stage_fn, p, jnp.ones((10, 4)), mesh,
                       n_microbatches=3)


def _mse_loss(y, t):
    return jnp.mean((y - t) ** 2)


@pytest.mark.parametrize("pp,dp,n_mb", [(4, 2, 8), (2, 4, 3), (8, 1, 8)])
def test_pipeline_1f1b_matches_jax_grad(pp, dp, n_mb):
    """The 1F1B schedule's loss AND gradients must equal jax.grad of the
    sequentially applied stages (incl. M not a multiple of S, and a
    sharded batch axis)."""
    from horovod_tpu.parallel.pipeline import pipeline_1f1b_apply
    H = 8
    T = n_mb * 4
    rng = np.random.RandomState(7)
    stages = [{"w": jnp.asarray(rng.randn(H, H), jnp.float32) * 0.4,
               "b": jnp.asarray(rng.randn(H), jnp.float32) * 0.1}
              for _ in range(pp)]
    x = jnp.asarray(rng.randn(T, H), jnp.float32)
    tgt = jnp.asarray(rng.randn(T, H), jnp.float32)

    def oracle(stacked):
        xm = x.reshape(n_mb, T // n_mb, H)
        tm = tgt.reshape(n_mb, T // n_mb, H)

        def one_mb(xb, tb):
            h = xb
            for s in range(pp):
                h = _stage_fn(jax.tree_util.tree_map(
                    lambda p: p[s], stacked), h)
            return _mse_loss(h, tb)
        return jax.vmap(one_mb)(xm, tm).mean()

    stacked = stage_stacked(stages)
    ref_loss, ref_grads = jax.value_and_grad(oracle)(stacked)

    mesh = build_mesh(dp=dp, pp=pp)
    loss, grads = pipeline_1f1b_apply(
        _stage_fn, _mse_loss, stacked, x, tgt, mesh, n_microbatches=n_mb)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for g, rg in zip(jax.tree_util.tree_leaves(grads),
                     jax.tree_util.tree_leaves(ref_grads)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(rg),
                                   rtol=1e-4, atol=1e-5)


def test_pipeline_1f1b_pp1_fast_path():
    from horovod_tpu.parallel.pipeline import pipeline_1f1b_apply
    rng = np.random.RandomState(9)
    p = stage_stacked([{"w": jnp.asarray(rng.randn(6, 6), jnp.float32),
                        "b": jnp.zeros(6, jnp.float32)}])
    x = jnp.asarray(rng.randn(8, 6), jnp.float32)
    tgt = jnp.asarray(rng.randn(8, 6), jnp.float32)
    mesh = build_mesh(dp=8)
    loss, grads = pipeline_1f1b_apply(_stage_fn, _mse_loss, p, x, tgt,
                                      mesh, n_microbatches=2)
    assert np.isfinite(float(loss))
    assert jax.tree_util.tree_leaves(grads)[0].shape[0] == 1
