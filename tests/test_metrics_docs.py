"""The metrics <-> docs lint (ci/check_metrics_docs.py, ISSUE 7
satellite): the real tree must be in sync with docs/OBSERVABILITY.md,
and the matcher semantics that keep the lint honest are pinned here."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint():
    sys.path.insert(0, os.path.join(REPO, "ci"))
    try:
        import check_metrics_docs
        return check_metrics_docs
    finally:
        sys.path.pop(0)


def test_tree_and_docs_in_sync():
    """THE gate: every registered metric documented, no stale docs."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "ci",
                                      "check_metrics_docs.py")],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "lint OK" in out.stdout


def test_extraction_finds_known_registrations():
    lint = _lint()
    code = lint.code_metrics()
    # plain literal, f-string pattern, multi-line call, fleet g() helper
    assert "hvd_steps_total" in code
    assert "hvd_*_total" in code            # f"hvd_{metric_unit}_total"
    assert "hvd_anomaly_total" in code      # multi-line .counter(
    assert "hvd_fleet_straggler_rank" in code   # fleet's g(...)
    assert "hvd_engine_*" in code
    # registration sites are reported for the failure message
    assert any("callbacks.py" in s for s in code["hvd_steps_total"])


def test_generic_doc_pattern_does_not_blanket_document():
    lint = _lint()
    # hvd_engine_* documents any engine mirror...
    assert lint._doc_covers_code("hvd_engine_cache_hits", "hvd_engine_*")
    # ...but the fully generic per-unit convention must not swallow
    # arbitrary counters (the lint would never fire again)
    assert not lint._doc_covers_code("hvd_anomaly_total", "hvd_*_total")
    assert lint._doc_covers_code("hvd_*_total", "hvd_*_total")


def test_histogram_subseries_not_stale():
    lint = _lint()
    undocumented, stale, _code = lint.check()
    assert undocumented == []
    assert stale == []
    # docs show hvd_step_time_seconds_bucket{...} in examples; the
    # suffix-stripping keeps that from reading as a stale mention —
    # verified implicitly by stale == [] while the docs contain it
    docs = lint.doc_metrics()
    assert any(d.startswith("hvd_step_time_seconds_bucket")
               for d in docs)
