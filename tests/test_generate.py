"""Token-level continuous batching (ISSUE 17, docs/SERVING.md
"Continuous batching & KV paging").

Fast battery: the KV page plan (byte-budget precedence, geometry) and
page pool (all-or-nothing allocation, low-first ids, high-water /
fragmentation accounting), the slot scheduler (FIFO page-gated
admission with head-of-line blocking, prefill chunk math, eviction
returning pages at the step boundary, drop_waiting), engine admission
validation, the TOKEN-EXACT parity contract (staggered continuous
decode bit-identical to sequential decode and to the dense
full-recompute oracle), the one-compile-under-churn guard, the
continuous-vs-gang decode-step win, deadline/drain semantics, the
replica ``/generate`` path (roundtrip, duplicate replay, concurrent
duplicates joining one in-flight decode, 400 on oversized prompts),
router ``submit_generate`` exactly-once accounting with
``tokens_emitted`` on the audit line, lifecycle trace-span coverage,
the per-phase metrics, and the ``check_bench --serving-gen`` gate.

Everything here runs in-process on the 8-virtual-device CPU mesh; the
demo model is a tiny fp32 dense transformer so parity is exact.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _chaos_clean():
    from horovod_tpu import chaos
    chaos.uninstall()
    yield
    chaos.uninstall()


def _demo():
    from horovod_tpu.serving.generate import demo_gen_setup
    return demo_gen_setup()


def _engine(**over):
    from horovod_tpu.serving.generate import GenerateEngine
    params, cfg = _demo()
    kw = dict(n_slots=4, page_bytes=4096, prefill_chunk=8)
    kw.update(over)
    return GenerateEngine(params, cfg, **kw)


def _run_to_done(engine, reqs, guard=50_000):
    from horovod_tpu.serving.generate.scheduler import DONE
    n = 0
    while any(r.state != DONE for r in reqs):
        engine.step_once()
        n += 1
        assert n < guard, "engine failed to converge"


# -- page plan + pool ---------------------------------------------------------
def test_page_plan_geometry_and_budget_precedence(monkeypatch):
    from horovod_tpu.serving.generate.pages import (plan_kv_pages,
                                                    resolve_page_bytes)
    # explicit argument wins over everything
    assert resolve_page_bytes(1234) == 1234
    monkeypatch.setenv("HVD_TPU_KV_PAGE_BYTES", "2048")
    assert resolve_page_bytes(None) == 2048
    monkeypatch.delenv("HVD_TPU_KV_PAGE_BYTES")
    # env unset: the bucket-planner fallback, capped to page scale
    from horovod_tpu.serving.generate.pages import DEFAULT_PAGE_BYTES_CAP
    assert 1 <= resolve_page_bytes(None) <= DEFAULT_PAGE_BYTES_CAP
    # geometry: 1 layer x width 8 x fp32 x (K AND V) = 64 B/token;
    # a 256 B budget holds 4 tokens/page, 16-token ctx needs 4 pages
    plan = plan_kv_pages(1, 8, np.float32, slots=3, max_ctx=16,
                         page_bytes=256)
    assert plan.page_tokens == 4
    assert plan.pages_per_slot == 4
    assert plan.total_pages == 12
    assert plan.slot_tokens == 16
    assert plan.token_bytes == 64
    assert plan.page_bytes == 256
    assert plan.pages_for(1) == 1
    assert plan.pages_for(4) == 1
    assert plan.pages_for(5) == 2
    # the plan is cached per fingerprint (pure metadata)
    assert plan_kv_pages(1, 8, np.float32, slots=3, max_ctx=16,
                         page_bytes=256) is plan


def test_page_pool_all_or_nothing_and_accounting():
    from horovod_tpu.serving.generate.pages import PagePool, plan_kv_pages
    plan = plan_kv_pages(1, 8, np.float32, slots=2, max_ctx=16,
                         page_bytes=256)  # 8 pages total
    pool = PagePool(plan)
    a = pool.alloc(3)
    assert a == [0, 1, 2]           # low-first, contiguous when fresh
    b = pool.alloc(4)
    assert pool.in_use == 7
    # all-or-nothing: 2 > 1 free -> None, and NOTHING was taken
    assert pool.alloc(2) is None
    assert pool.in_use == 7
    assert pool.alloc(1) == [7]
    assert pool.high_water == 8
    pool.free(a)
    assert pool.in_use == 5
    # freeing re-sorts so hand-out stays low-first after churn
    assert pool.alloc(1) == [0]
    pool.free(b + [7, 0])
    assert pool.in_use == 0
    assert pool.fragmentation() == 0.0  # one contiguous free run
    assert pool.high_water == 8         # sticky across frees
    stats = pool.stats()
    assert stats["capacity"] == 8 and stats["page_tokens"] == 4


def test_page_pool_fragmentation_reports_shredded_free_set():
    from horovod_tpu.serving.generate.pages import PagePool, plan_kv_pages
    plan = plan_kv_pages(1, 8, np.float32, slots=2, max_ctx=32,
                         page_bytes=64)  # 64 B/token -> 1 tok/page
    pool = PagePool(plan)
    pages = pool.alloc(plan.total_pages)
    # free every OTHER page: the free set is all 1-page runs
    pool.free(pages[::2])
    assert pool.fragmentation() > 0.4


# -- slot scheduler -----------------------------------------------------------
def _sched(n_slots=2, pool_pages=4, page_tokens=4, prefill_chunk=4):
    from horovod_tpu.serving.generate.pages import PagePool, plan_kv_pages
    from horovod_tpu.serving.generate.scheduler import SlotScheduler
    plan = plan_kv_pages(1, 8, np.float32, slots=pool_pages,
                         max_ctx=page_tokens,
                         page_bytes=64 * page_tokens)
    assert plan.total_pages == pool_pages \
        and plan.page_tokens == page_tokens
    pool = PagePool(plan)
    return SlotScheduler(n_slots, pool, prefill_chunk,
                         max_ctx=pool_pages * page_tokens), pool


def test_scheduler_fifo_admission_is_page_gated_head_of_line():
    from horovod_tpu.serving.generate.scheduler import (PREFILL, WAITING,
                                                        GenRequest)
    sched, pool = _sched(n_slots=3, pool_pages=5, page_tokens=4)
    big = GenRequest("big", [1] * 8, 8)       # worst case 16 -> 4 pages
    small = GenRequest("small", [1], 1)       # worst case 2 -> 1 page
    held = pool.alloc(2)                      # only 3 pages remain
    sched.add_waiting(big)
    sched.add_waiting(small)
    # the head can't be covered: the LINE blocks — small is NOT
    # admitted around it (that would starve big forever under load)
    assert sched.admit() == []
    assert big.state == WAITING and small.state == WAITING
    pool.free(held)
    admitted = sched.admit()                  # FIFO order, both fit now
    assert [r.id for r in admitted] == ["big", "small"]
    assert big.state == PREFILL and big.slot == 0 and len(big.pages) == 4
    assert small.slot == 1 and len(small.pages) == 1
    assert sched.occupied() == 2 and sched.busy()


def test_scheduler_slots_gate_admission_too():
    from horovod_tpu.serving.generate.scheduler import GenRequest
    sched, _pool = _sched(n_slots=1, pool_pages=4, page_tokens=4)
    first = GenRequest("first", [1], 1)
    second = GenRequest("second", [1], 1)
    sched.add_waiting(first)
    sched.add_waiting(second)
    assert [r.id for r in sched.admit()] == ["first"]
    assert sched.admit() == []                # no free slot
    sched.evict(first, "length")
    assert [r.id for r in sched.admit()] == ["second"]


def test_scheduler_prefill_chunking_and_eviction_returns_pages():
    from horovod_tpu.serving.generate.scheduler import (DONE,
                                                        GenRequest)
    sched, pool = _sched(n_slots=2, pool_pages=4, page_tokens=4,
                         prefill_chunk=4)
    req = GenRequest("r", list(range(10)), 2)  # 10-token prompt
    sched.add_waiting(req)
    assert sched.admit() == [req]
    assert sched.chunks_for(req.prompt_len) == 3
    chunks = []
    while True:
        c = sched.next_prefill_chunk(req)
        if c is None:
            break
        chunks.append(c)
        req.prefill_pos += c[1]
    assert chunks == [(0, 4), (4, 4), (8, 2)]
    in_use = pool.in_use
    assert in_use == 3                         # ceil(12 / 4)
    sched.evict(req, "length")
    assert req.state == DONE and req.finish_reason == "length"
    assert req.pages == [] and pool.in_use == 0
    assert not sched.busy()


def test_scheduler_drop_waiting_only_removes_queued():
    from horovod_tpu.serving.generate.scheduler import GenRequest
    sched, _pool = _sched()
    req = GenRequest("w", [1], 1)
    sched.add_waiting(req)
    assert sched.waiting_count() == 1
    assert sched.drop_waiting(req) is True
    assert sched.drop_waiting(req) is False    # already gone
    assert sched.waiting_count() == 0


# -- engine: admission validation --------------------------------------------
def test_engine_rejects_what_cannot_fit_a_slot():
    eng = _engine()
    cap = eng.max_request_tokens
    assert cap >= 8
    with pytest.raises(ValueError):            # prompt+max_new too big
        eng.submit("big", [1] * cap, max_new=1)
    with pytest.raises(ValueError):
        eng.submit("empty", [], max_new=4)
    with pytest.raises(ValueError):
        eng.submit("zero", [1, 2], max_new=0)
    # the boundary case fits
    req = eng.submit("edge", [1] * (cap - 1), max_new=1)
    _run_to_done(eng, [req])
    assert len(req.tokens) == 1


def test_engine_max_new_one_finishes_at_prefill():
    """TTFT happens at prefill end: the last chunk's logits ARE the
    first token, so max_new=1 never enters the decode batch — and the
    prefill-emitted token still lands in gen_tokens_total (it is a
    real emission; skipping it under-counts by one per request)."""
    from horovod_tpu.metrics import default_registry
    eng = _engine()
    req = eng.submit("one", [3, 1, 4, 1, 5], max_new=1)
    before = eng.decode_steps_total
    ctr = default_registry().get("hvd_serving_gen_tokens_total")
    tok_before = ctr.value if ctr is not None else 0.0
    _run_to_done(eng, [req])
    assert req.finish_reason == "length"
    assert len(req.tokens) == 1
    assert eng.decode_steps_total == before    # zero decode steps
    ctr = default_registry().get("hvd_serving_gen_tokens_total")
    assert ctr is not None and ctr.value == tok_before + 1.0


# -- the parity contract ------------------------------------------------------
def _reqset(rng, n, max_prompt=20, max_new_hi=8):
    """Mixed-length prompts/budgets off one seeded stream."""
    out = []
    for _ in range(n):
        plen = int(rng.randint(1, max_prompt + 1))
        out.append(([int(t) for t in rng.randint(0, 64, size=plen)],
                    int(rng.randint(1, max_new_hi + 1))))
    return out


def test_token_parity_continuous_vs_sequential_vs_oracle():
    """THE acceptance contract: a staggered continuous run emits
    BIT-IDENTICAL tokens to a one-at-a-time sequential run of the same
    engine, and both match the dense full-recompute oracle — paging,
    slot churn, prefill chunking and co-batching must be numerically
    invisible."""
    from horovod_tpu.models.transformer import reference_greedy_decode
    params, cfg = _demo()
    reqset = _reqset(np.random.RandomState(7), 5)

    # continuous: stagger submissions mid-flight
    eng = _engine()
    reqs = []
    for i, (prompt, max_new) in enumerate(reqset[:2]):
        reqs.append(eng.submit(f"c{i}", prompt, max_new))
    for _ in range(3):
        eng.step_once()                        # first two are mid-decode
    for i, (prompt, max_new) in enumerate(reqset[2:], start=2):
        reqs.append(eng.submit(f"c{i}", prompt, max_new))
    _run_to_done(eng, reqs)

    # sequential: same engine geometry, one sequence at a time
    seq_eng = _engine()
    for i, ((prompt, max_new), creq) in enumerate(zip(reqset, reqs)):
        sreq = seq_eng.submit(f"s{i}", prompt, max_new)
        _run_to_done(seq_eng, [sreq])
        assert sreq.tokens == creq.tokens, \
            f"request {i}: continuous diverged from sequential"
        assert creq.finish_reason == "length"
        # The dense oracle recompiles per unique sequence length, so
        # anchor against it on a sample rather than every request.
        if i < 2:
            oracle = reference_greedy_decode(params, cfg, prompt, max_new)
            assert creq.tokens == oracle, \
                f"request {i}: paged decode diverged from the dense oracle"


# -- compile stability --------------------------------------------------------
def test_decode_step_compiles_exactly_once_under_churn():
    """The static-slot contract: sequences joining/leaving every few
    steps is host bookkeeping — the jit'd step functions compile
    EXACTLY once each across heavy churn."""
    from horovod_tpu.profiling import compile_watch
    compile_watch.ensure_installed()
    compile_watch.reset_counts()
    eng = _engine(n_slots=3)
    reqs = [eng.submit(f"n{i}", [1 + i] * (1 + (i * 5) % 17),
                       max_new=1 + i % 6)
            for i in range(12)]
    _run_to_done(eng, reqs)
    counts = compile_watch.per_function_compiles()
    assert counts.get("gen_decode_step", 0) == 1, counts
    assert counts.get("gen_prefill_chunk", 0) == 1, counts


# -- continuous vs request-level gang ----------------------------------------
def test_continuous_needs_strictly_fewer_decode_steps_than_gang():
    """The throughput claim in its deterministic form: over a mixed
    request set, continuous slot reuse spends strictly fewer compiled
    decode steps than the request-level gang discipline (early
    finishers stranding their slot), at identical per-step cost — and
    emits the identical tokens."""
    from horovod_tpu.serving.generate import request_level_generate
    reqset = _reqset(np.random.RandomState(11), 12, max_new_hi=10)

    eng = _engine()
    reqs = [eng.submit(f"c{i}", p, m) for i, (p, m) in enumerate(reqset)]
    _run_to_done(eng, reqs)
    continuous_steps = eng.decode_steps_total

    base = request_level_generate(eng, reqset)
    gang_steps = eng.decode_steps_total - continuous_steps
    assert continuous_steps < gang_steps, \
        (continuous_steps, gang_steps)
    for creq, breq in zip(reqs, base):
        assert creq.tokens == breq.tokens


# -- deadline / drain ---------------------------------------------------------
def test_engine_deadline_expires_mid_generation():
    from horovod_tpu.serving.batcher import DeadlineError
    eng = _engine()
    req = eng.submit("late", [1, 2, 3], max_new=50, deadline_s=0.05)
    eng.step_once()                            # admit + prefill
    time.sleep(0.1)
    eng.step_once()                            # sweep fires
    assert req.finish_reason == "deadline"
    with pytest.raises(DeadlineError):
        req.pending.wait(timeout=1.0)
    # the slot and pages came back
    assert eng.scheduler.occupied() == 0
    assert eng.pool.in_use == 0


def test_engine_drain_refuses_new_and_finishes_admitted():
    from horovod_tpu.serving.batcher import DrainingError
    eng = _engine()
    req = eng.submit("inflight", [5, 6], max_new=3)
    eng.step_once()
    eng.drain()
    with pytest.raises(DrainingError):
        eng.submit("refused", [1], max_new=1)
    assert not eng.drained()                   # still decoding
    _run_to_done(eng, [req])
    assert req.finish_reason == "length"
    assert eng.drained()


# -- metrics ------------------------------------------------------------------
def test_generate_metrics_register_all_documented_names():
    from horovod_tpu.metrics.registry import default_registry
    eng = _engine()
    req = eng.submit("m0", [1] * 10, max_new=3)
    _run_to_done(eng, [req])
    reg = default_registry()
    for name in ("hvd_serving_prefill_seconds_total",
                 "hvd_serving_prefill_chunks_total",
                 "hvd_serving_decode_seconds_total",
                 "hvd_serving_decode_steps_total",
                 "hvd_serving_gen_tokens_total",
                 "hvd_serving_slot_occupancy",
                 "hvd_serving_gen_waiting",
                 "hvd_serving_kv_pages_in_use",
                 "hvd_serving_kv_pages_total",
                 "hvd_serving_kv_page_bytes",
                 "hvd_serving_ttft_seconds",
                 "hvd_serving_itl_seconds"):
        assert reg.get(name) is not None, f"{name} never registered"
    finished = reg.get("hvd_serving_gen_finished_total",
                       labels={"reason": "length"})
    assert finished is not None and finished.value >= 1


# -- replica /generate --------------------------------------------------------
def _post(port, doc, path="/generate", timeout=30.0):
    import urllib.error
    import urllib.request
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(doc).encode(),
        method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture
def gen_replica():
    from horovod_tpu.serving import ReplicaServer
    r = ReplicaServer(replica_id="g0", mode="generate").start()
    yield r
    r.stop()


def test_replica_generate_roundtrip_and_duplicate_replay(gen_replica):
    code, resp = _post(gen_replica.port,
                       {"id": "g1", "prompt": [1, 2, 3], "max_new": 4})
    assert code == 200, resp
    assert resp["tokens_emitted"] == 4 and len(resp["tokens"]) == 4
    assert resp["finish_reason"] == "length"
    assert resp["prompt_tokens"] == 3
    # a duplicate (retry after timeout) replays the CACHED stream —
    # one id never decodes twice, even with a different payload
    code2, resp2 = _post(gen_replica.port,
                         {"id": "g1", "prompt": [9, 9], "max_new": 2})
    assert code2 == 200 and resp2["tokens"] == resp["tokens"]
    # an oversized prompt is a definitive 400, not a retryable fault
    cap = gen_replica.engine.max_request_tokens
    code3, resp3 = _post(gen_replica.port,
                         {"id": "g2", "prompt": [1] * cap,
                          "max_new": 8})
    assert code3 == 400 and "capacity" in resp3["error"]


def test_replica_concurrent_duplicates_join_one_decode(gen_replica):
    """The hedge-dedupe bugfix: duplicates of one id arriving WHILE it
    decodes join the live in-flight request before any second decode
    could start — every copy returns the identical token stream."""
    results = []
    lock = threading.Lock()

    def fire():
        code, resp = _post(gen_replica.port,
                           {"id": "dup", "prompt": [4, 2], "max_new": 6})
        with lock:
            results.append((code, resp))

    threads = [threading.Thread(target=fire) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
    assert len(results) == 3
    tokens = {tuple(resp["tokens"]) for code, resp in results}
    assert all(code == 200 for code, _ in results)
    assert len(tokens) == 1, "duplicates decoded divergent streams"
    # every copy reports the SAME single decode's accounting
    assert {resp["decode_steps"] for _c, resp in results} == {5}
    assert all(resp["tokens_emitted"] == 6 for _c, resp in results)


def test_infer_mode_replica_404s_generate():
    from horovod_tpu.serving import ReplicaServer
    r = ReplicaServer(dim=4, replica_id="i0").start()
    try:
        code, resp = _post(r.port, {"id": "x", "prompt": [1],
                                    "max_new": 1})
        assert code == 404 and "mode=infer" in resp["error"]
    finally:
        r.stop()


# -- router + tracing ---------------------------------------------------------
def test_router_generate_exactly_once_audit_and_trace_spans(gen_replica):
    """One request through router -> replica -> engine: the ``ok``
    audit line carries ``tokens_emitted``, the books close
    exactly-once, and ONE trace id covers the whole lifecycle —
    submit (request/dispatch), admission (gen_admit), every prefill
    chunk (gen_prefill), every decode step (gen_decode_step), and the
    finish (gen_finish)."""
    from horovod_tpu.diagnostics.flight_recorder import recorder
    from horovod_tpu.serving.router import Router
    from horovod_tpu.tracing.reader import spans_from_events
    router = Router([("127.0.0.1", gen_replica.port)], max_inflight=8)
    try:
        resp = router.submit_generate([7, 7, 7], max_new=5,
                                      req_id="traced-1")
    finally:
        router.close()
    assert resp["tokens_emitted"] == 5
    ok = [e for e in router.log.entries if e["outcome"] == "ok"]
    assert len(ok) == 1 and ok[0]["tokens_emitted"] == 5
    acct = router.log.accounting()
    assert acct["unanswered"] == [] and acct["answered_twice"] == []
    trace = ok[0]["trace"]
    assert trace and resp.get("trace") == trace
    spans, _points = spans_from_events(recorder().events(),
                                       trace_id=trace)
    names = [s["name"] for s in spans]
    for expected in ("request", "dispatch", "serve", "gen_admit",
                     "gen_prefill", "gen_finish"):
        assert expected in names, (expected, names)
    assert names.count("gen_decode_step") == 4  # token 1 is prefill's
    finish = [s for s in spans if s["name"] == "gen_finish"][0]
    assert finish["attrs"]["tokens_emitted"] == 5


# -- the check_bench --serving-gen gate ---------------------------------------
def _gen_doc(**over):
    doc = {"bench": "serving_generate", "requests": 16, "failed": 0,
           "n_slots": 4, "prefill_chunk": 8, "total_tokens": 150,
           "duration_s": 0.1, "tokens_per_s": 1500.0,
           "ttft_p50_s": 0.03, "ttft_p99_s": 0.06,
           "itl_p50_s": 0.001, "itl_p99_s": 0.003,
           "slot_occupancy_mean": 0.85, "decode_steps": 40,
           "decode_compiles": 1, "speedup": 1.2,
           "baseline_tokens_per_s": 1250.0}
    doc.update(over)
    return doc


def _gate():
    import sys as _sys
    _sys.path.insert(0, REPO)
    try:
        import ci.check_bench as cb
    finally:
        _sys.path.remove(REPO)
    return cb


def test_check_bench_serving_gen_gate(tmp_path):
    cb = _gate()
    # extraction: raw JSON and a captured BENCH_SERVE_GEN line both
    # load; a BENCH_SERVE (request-level) line does NOT
    raw = tmp_path / "BENCH_SERVE_GEN.json"
    raw.write_text(json.dumps(_gen_doc()))
    assert cb._load_serving_gen_doc(str(raw))["speedup"] == 1.2
    cap = tmp_path / "out.txt"
    cap.write_text("noise\nBENCH_SERVE {\"bench\": \"serving\"}\n"
                   "BENCH_SERVE_GEN " + json.dumps(_gen_doc()) + "\n")
    assert cb._load_serving_gen_doc(str(cap))["requests"] == 16
    other = tmp_path / "serve_only.txt"
    other.write_text("BENCH_SERVE " + json.dumps({"p99_s": 1}) + "\n")
    assert cb._load_serving_gen_doc(str(other)) is None
    # clean + explicit baseline: OK
    assert cb.serving_gen_main(["--serving-gen", str(raw),
                                "--baseline", str(raw)]) == 0
    # failed requests / compile churn / no speedup all refuse
    assert cb.check_serving_gen(_gen_doc(failed=2), None, 0.5)
    assert cb.check_serving_gen(_gen_doc(decode_compiles=2), None, 0.5)
    assert cb.check_serving_gen(_gen_doc(decode_compiles=0), None, 0.5)
    assert cb.check_serving_gen(_gen_doc(speedup=0.97), None, 0.5)
    assert cb.check_serving_gen(_gen_doc(speedup=None), None, 0.5)
    # tokens/s regression beyond tolerance fails, inside passes
    base = _gen_doc(tokens_per_s=2000.0)
    assert cb.check_serving_gen(_gen_doc(tokens_per_s=900.0), base, 0.5)
    assert not cb.check_serving_gen(_gen_doc(tokens_per_s=1500.0),
                                    base, 0.5)
    # end to end: a dirty artifact fails through main
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(_gen_doc(decode_compiles=3)))
    assert cb.serving_gen_main(["--serving-gen", str(bad),
                                "--baseline", str(raw)]) == 1


def test_serving_gen_gate_skips_null_baselines_loudly(tmp_path,
                                                      monkeypatch,
                                                      capsys):
    """The --goodput loud-skip contract: auto-discovery must SAY which
    committed artifacts it skipped and why — a silent skip reads as
    "compared against the last round" when it wasn't."""
    cb = _gate()
    (tmp_path / "BENCH_SERVE_GEN_r2.json").write_text(
        json.dumps(_gen_doc(tokens_per_s=None)))   # failure artifact
    (tmp_path / "BENCH_SERVE_GEN_r1.json").write_text(
        json.dumps(_gen_doc(tokens_per_s=1400.0)))
    new = tmp_path / "new.json"
    new.write_text(json.dumps(_gen_doc()))
    monkeypatch.setattr(cb, "REPO", str(tmp_path))
    assert cb.serving_gen_main(["--serving-gen", str(new)]) == 0
    out = capsys.readouterr().out
    assert "skipping BENCH_SERVE_GEN_r2.json" in out
    assert "null tokens/s" in out
    assert "BENCH_SERVE_GEN_r1.json" in out        # the one it used
