"""Unit battery for the native sharded checkpoint subsystem
(``horovod_tpu/checkpoint/``): format roundtrips across leaf kinds,
two-phase-commit crash artifacts, GC, integrity checking, async error
propagation, multi-rank save + different-world restore simulated
in-process, the elastic durable-commit backend, CheckpointCallback, and
ShardedDataset data-position checkpointing."""

import collections
import json
import os
import time

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from horovod_tpu.checkpoint import CheckpointError, ShardedCheckpointer
from horovod_tpu.checkpoint import format as fmt
from horovod_tpu.parallel import build_mesh


def _store(path, **kw):
    kw.setdefault("rank", 0)
    kw.setdefault("world_size", 1)
    return ShardedCheckpointer(str(path), **kw)


def _rich_state():
    return {
        "params": {"w": jnp.arange(64.0).reshape(8, 8),
                   "b": jnp.ones(8, jnp.bfloat16)},
        "step": 7, "lr": 0.5, "name": "run1", "flag": True,
        # np.float64 subclasses python float — must stay a np scalar
        "hist": [1, 2, (3.5, np.float32(2.0)), np.float64(4.0)],
        "blob": collections.deque([1, 2]),  # pickle-fallback leaf
    }


# ---------------------------------------------------------------- format


def test_roundtrip_all_leaf_kinds(tmp_path):
    """Python scalars stay python, np scalars stay np, tuples stay
    tuples (treedef path), bf16 survives the uint-view storage, and
    arbitrary picklable leaves ride along."""
    ck = _store(tmp_path)
    state = _rich_state()
    ck.save(3, state, wait=True)
    out = ck.restore_latest()
    np.testing.assert_allclose(out["params"]["w"],
                               np.arange(64.0).reshape(8, 8))
    assert out["params"]["b"].dtype == jnp.bfloat16
    assert out["step"] == 7 and type(out["step"]) is int
    assert out["lr"] == 0.5 and out["flag"] is True
    assert out["name"] == "run1"
    assert isinstance(out["hist"][2], tuple)
    assert type(out["hist"][2][1]) is np.float32
    assert type(out["hist"][3]) is np.float64 and out["hist"][3] == 4.0
    assert isinstance(out["blob"], collections.deque)
    ck.close()


def test_restore_with_like_places_on_mesh(tmp_path):
    """``like`` shardings re-slice the global arrays onto the CURRENT
    mesh — the elastic re-meshing contract."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    ck = _store(tmp_path)
    state = _rich_state()
    ck.save(0, state, wait=True)
    mesh = build_mesh(dp=2, tp=4)
    like = dict(state)
    like["params"] = {
        "w": jax.ShapeDtypeStruct((8, 8), jnp.float32,
                                  sharding=NamedSharding(mesh,
                                                         P("dp", "tp"))),
        "b": jax.ShapeDtypeStruct((8,), jnp.bfloat16,
                                  sharding=NamedSharding(mesh, P())),
    }
    out = ck.restore(0, like=like)
    assert out["params"]["w"].sharding.spec == P("dp", "tp")
    np.testing.assert_allclose(np.asarray(out["params"]["w"]),
                               np.arange(64.0).reshape(8, 8))
    ck.close()


def test_restore_missing_leaf_raises(tmp_path):
    ck = _store(tmp_path)
    ck.save(0, {"a": jnp.ones(4)}, wait=True)
    with pytest.raises(CheckpointError, match="has no value"):
        ck.restore(0, like={"a": jnp.ones(4), "extra": jnp.ones(2)})
    ck.close()


def test_manifest_contract(tmp_path):
    """The on-disk manifest carries what an external reader (or a future
    spec version) needs: world size, per-file sha256, global
    shapes/dtypes, shard→rank map."""
    ck = _store(tmp_path)
    ck.save(5, {"w": jnp.arange(16.0)}, wait=True)
    man = fmt.read_manifest(str(tmp_path), 5)
    assert man["version"] == fmt.SPEC_VERSION
    assert man["world_size"] == 1 and man["step"] == 5
    assert set(man["files"]) == {"shard_0.npz"}
    sha = fmt.file_sha256(os.path.join(fmt.step_dir(str(tmp_path), 5),
                                       "shard_0.npz"))
    assert man["files"]["shard_0.npz"] == sha
    (leaf,) = man["leaves"]
    assert leaf["kind"] == "array" and leaf["shape"] == [16]
    assert leaf["dtype"] == "float32"
    assert leaf["shards"][0]["rank"] == 0
    ck.close()


# --------------------------------------------- multi-rank save / reshard


def test_two_rank_save_restores_at_other_world_sizes(tmp_path):
    """Both ranks of a world-2 save write only their axis-0 slices; the
    committed checkpoint reassembles identically under stores configured
    for world sizes 1 and 3 (restore reads the manifest's world, not the
    current one)."""
    state = {"w": np.arange(24.0).reshape(6, 4), "b": np.ones(5), "k": 3}
    s1 = _store(tmp_path, rank=1, world_size=2, commit_timeout_s=30)
    s1.save(10, state)  # queued: rank 1 waits for rank 0's attempt token
    assert s1.latest_step() is None
    s0 = _store(tmp_path, rank=0, world_size=2, commit_timeout_s=30)
    s0.save(10, state, wait=True)  # opens the attempt, then commits
    s1.wait()
    assert s0.latest_step() == 10

    # each rank really wrote a strict subset of the bytes
    man = fmt.read_manifest(str(tmp_path), 10)
    w_leaf = [rec for rec in man["leaves"] if rec["path"] == "['w']"][0]
    by_rank = {s["rank"]: s["index"] for s in w_leaf["shards"]}
    assert by_rank[0][0] == [0, 3] and by_rank[1][0] == [3, 6]

    for world in (1, 3):
        r = _store(tmp_path, rank=0, world_size=world)
        out = r.restore_latest()
        np.testing.assert_allclose(out["w"], state["w"])
        np.testing.assert_allclose(out["b"], state["b"])
        assert out["k"] == 3
    s0.close()
    s1.close()


def test_commit_times_out_without_peer_marker(tmp_path):
    """Rank 0 of a world-2 save whose peer never writes: the commit
    times out with an error, the tmp dir stays (a peer might be slow,
    not dead), and no checkpoint appears."""
    s0 = _store(tmp_path, rank=0, world_size=2, commit_timeout_s=0.5)
    with pytest.raises(CheckpointError, match="timed out"):
        s0.save(4, {"w": np.ones(4)}, wait=True)
    assert s0.latest_step() is None
    assert fmt.list_tmp_steps(str(tmp_path)) != []
    # once idle past the ttl, GC reclaims it
    time.sleep(0.05)
    s0.gc(tmp_ttl=0.01)
    assert fmt.list_tmp_steps(str(tmp_path)) == []


def test_stale_attempt_marker_cannot_satisfy_commit(tmp_path):
    """A crashed generation's shard marker sitting in ``step_N.tmp``
    must never satisfy a NEW attempt's commit barrier: rank 0 clears
    the stale attempt, so alone it times out loudly instead of
    committing a checkpoint that mixes two generations."""
    stale = np.zeros(8)
    fmt.write_shard(fmt.tmp_dir(str(tmp_path), 7), 1,
                    {"L0S0": stale[4:]},
                    [{"key": "L0S0", "leaf": 0, "index": [[4, 8]]}])
    s0 = _store(tmp_path, rank=0, world_size=2, commit_timeout_s=0.5)
    with pytest.raises(CheckpointError, match="timed out"):
        s0.save(7, {"w": np.arange(8.0)}, wait=True)
    assert s0.latest_step() is None
    s0.close()


def test_fresh_peer_marker_after_stale_cleanup_commits(tmp_path):
    """Same wreckage, but the peer writes its FRESH shard after rank 0
    cleared the stale attempt: the commit succeeds and restores the new
    state, not the dead generation's."""
    stale = np.zeros(8)
    fmt.write_shard(fmt.tmp_dir(str(tmp_path), 7), 1,
                    {"L0S0": stale[4:]},
                    [{"key": "L0S0", "leaf": 0, "index": [[4, 8]]}])
    fresh = {"w": np.arange(8.0)}
    s0 = _store(tmp_path, rank=0, world_size=2, commit_timeout_s=20)
    s1 = _store(tmp_path, rank=1, world_size=2)
    s0.save(7, fresh)          # clears the stale tmp, commit pending
    s1.save(7, fresh, wait=True)
    s0.wait()
    out = s0.restore(7)
    np.testing.assert_array_equal(out["w"], np.arange(8.0))
    s0.close()
    s1.close()


# ------------------------------------------------- crash artifacts + GC


def test_crash_artifacts_ignored_and_gced(tmp_path):
    """A leftover ``step_N.tmp`` and a manifest-less step dir are
    invisible to ``latest_step``/``restore_latest`` and reclaimed by
    GC; the committed checkpoint stays restorable."""
    ck = _store(tmp_path)
    ck.save(1, {"w": jnp.ones(4)}, wait=True)
    # crash wreckage: a half-written tmp and a manifest-less dir
    os.makedirs(str(tmp_path / "step_2.tmp"))
    with open(str(tmp_path / "step_2.tmp" / "shard_0.npz"), "wb") as f:
        f.write(b"partial")
    os.makedirs(str(tmp_path / "step_3"))
    with open(str(tmp_path / "step_3" / "shard_0.npz"), "wb") as f:
        f.write(b"no manifest")

    assert ck.latest_step() == 1
    out = ck.restore_latest()
    np.testing.assert_allclose(out["w"], np.ones(4))
    time.sleep(0.05)
    ck.gc(tmp_ttl=0.01)
    assert not os.path.exists(str(tmp_path / "step_2.tmp"))
    assert not os.path.exists(str(tmp_path / "step_3"))
    assert os.path.isdir(str(tmp_path / "step_1"))
    ck.close()


def test_restore_latest_warns_on_foreign_layout(tmp_path, caplog):
    """A directory full of old-default orbax checkpoints (plain numeric
    step dirs) must not silently restart training from scratch."""
    import logging
    os.makedirs(str(tmp_path / "12"))
    ck = _store(tmp_path)
    with caplog.at_level(logging.WARNING):
        assert ck.restore_latest() is None
    assert any("another layout" in r.message for r in caplog.records)
    ck.close()


def test_gc_keeps_max_to_keep(tmp_path):
    ck = _store(tmp_path, max_to_keep=2)
    for step in range(5):
        ck.save(step, {"w": jnp.ones(4)}, wait=True)
    assert ck.all_steps() == [3, 4]
    assert ck.latest_step() == 4
    ck.close()


def test_gc_never_touches_active_tmp(tmp_path):
    """A tmp dir with recent writes is live (a slow peer), not
    wreckage."""
    ck = _store(tmp_path)
    os.makedirs(str(tmp_path / "step_9.tmp"))
    with open(str(tmp_path / "step_9.tmp" / "shard_1.npz"), "wb") as f:
        f.write(b"still coming")
    ck.gc(tmp_ttl=60.0)
    assert os.path.isdir(str(tmp_path / "step_9.tmp"))
    ck.close()


# ---------------------------------------------------- integrity + errors


def test_corrupt_shard_detected(tmp_path):
    ck = _store(tmp_path)
    ck.save(0, {"w": jnp.arange(32.0)}, wait=True)
    npz = os.path.join(fmt.step_dir(str(tmp_path), 0), "shard_0.npz")
    data = bytearray(open(npz, "rb").read())
    data[len(data) // 2] ^= 0xFF
    with open(npz, "wb") as f:
        f.write(data)
    with pytest.raises(CheckpointError, match="sha256 mismatch"):
        ck.restore(0)
    ck.close()


def test_unknown_spec_version_refused(tmp_path):
    ck = _store(tmp_path)
    ck.save(0, {"w": jnp.ones(2)}, wait=True)
    path = os.path.join(fmt.step_dir(str(tmp_path), 0), fmt.MANIFEST)
    man = json.loads(open(path, "rb").read())
    man["version"] = 999
    with open(path, "w") as f:
        json.dump(man, f)
    with pytest.raises(CheckpointError, match="spec version"):
        ck.restore(0)
    ck.close()


def test_async_error_surfaces_on_wait(tmp_path, monkeypatch):
    """A background write failure is re-raised at the next wait/save,
    never swallowed."""
    ck = _store(tmp_path)
    def boom(*a, **k):
        raise OSError("disk gone")
    monkeypatch.setattr(fmt, "write_shard", boom)
    ck.save(0, {"w": jnp.ones(2)})
    with pytest.raises(OSError, match="disk gone"):
        ck.wait()
    monkeypatch.undo()
    ck.save(1, {"w": jnp.ones(2)}, wait=True)  # store still usable
    assert ck.latest_step() == 1
    ck.close()


def test_double_save_same_step_rejected(tmp_path):
    ck = _store(tmp_path)
    ck.save(0, {"w": jnp.ones(2)}, wait=True)
    with pytest.raises(CheckpointError, match="already committed"):
        ck.save(0, {"w": jnp.ones(2)})
    ck.close()


def test_checkpoint_metrics_recorded(tmp_path):
    from horovod_tpu.metrics.registry import default_registry
    ck = _store(tmp_path)
    ck.save(2, {"w": jnp.ones(128)}, wait=True)
    ck.restore(2)
    snap = default_registry().snapshot()
    assert snap["hvd_checkpoint_save_bytes_total"]["value"] >= 128 * 4
    assert snap["hvd_checkpoint_restore_bytes_total"]["value"] > 0
    assert snap["hvd_checkpoint_save_seconds"]["count"] >= 1
    assert snap["hvd_checkpoint_restore_seconds"]["count"] >= 1
    assert snap["hvd_checkpoint_last_step"]["value"] >= 2
    ck.close()


# ------------------------------------------------ elastic durable commit


def test_objectstate_durable_commit_survives_pickle_loss(tmp_path,
                                                         monkeypatch):
    """The per-host pickle evaporates with its host; the durable sharded
    backend restores the last commit anyway (ISSUE 3 motivation)."""
    import horovod_tpu.elastic as elastic
    monkeypatch.setenv("HVD_ELASTIC_CKPT", str(tmp_path))
    monkeypatch.setenv("HVD_TPU_ELASTIC_DURABLE", "1")
    state = elastic.ObjectState(name="t", params={"w": np.arange(4.0)},
                                epoch=0)
    state.epoch = 3
    state.params = {"w": np.arange(4.0) * 2}
    state.commit()
    state._durable().wait()  # drain the async writer before "crashing"
    os.remove(str(tmp_path / "hvd_state_t.pkl"))  # the host died

    fresh = elastic.ObjectState(name="t", params={"w": np.zeros(4)},
                                epoch=0)
    assert fresh.epoch == 3
    np.testing.assert_allclose(fresh.params["w"], np.arange(4.0) * 2)


def test_objectstate_durable_steps_resume_monotonic(tmp_path, monkeypatch):
    """A restarted process keeps committing AFTER the stored steps —
    no collision with the previous generation's checkpoints."""
    import horovod_tpu.elastic as elastic
    monkeypatch.setenv("HVD_ELASTIC_CKPT", str(tmp_path))
    monkeypatch.setenv("HVD_TPU_ELASTIC_DURABLE", "1")
    s1 = elastic.ObjectState(name="m", count=1)
    s1.commit()
    s1.commit()
    s1._durable().wait()
    assert s1._durable().latest_step() == 2

    s2 = elastic.ObjectState(name="m", count=0)
    assert s2.count == 1  # restored from the durable store
    s2.commit()
    s2._durable().wait()
    assert s2._durable().latest_step() == 3


def test_objectstate_durable_step_self_heals(tmp_path, monkeypatch):
    """A desynced durable step counter (raced commit, NFS attr-cache
    lag) collides with an existing step — the save warns and the
    counter jumps past everything on disk instead of failing forever."""
    import horovod_tpu.elastic as elastic
    monkeypatch.setenv("HVD_ELASTIC_CKPT", str(tmp_path))
    monkeypatch.setenv("HVD_TPU_ELASTIC_DURABLE", "1")
    s = elastic.ObjectState(name="h", v=1)
    s.commit()
    s.commit()
    s._durable().wait()
    assert s._durable().latest_step() == 2
    s._durable_step = 0  # simulate the desync
    s.commit()           # targets step 1 (committed) → warns + heals
    s._durable().wait()
    s.commit()
    s._durable().wait()
    assert s._durable().latest_step() == 3


def test_objectstate_durable_recovers_after_background_failure(
        tmp_path, monkeypatch, caplog):
    """One transient background IO failure costs ONE durable commit
    (the failed one), not two: the next commit drains the pending
    error, attributes it to the earlier save, and still lands."""
    import logging
    import time as _time
    import horovod_tpu.elastic as elastic
    monkeypatch.setenv("HVD_ELASTIC_CKPT", str(tmp_path))
    monkeypatch.setenv("HVD_TPU_ELASTIC_DURABLE", "1")
    s = elastic.ObjectState(name="flaky", v=1)
    orig = fmt.write_shard
    failed = []

    def once(*a, **k):
        if not failed:
            failed.append(1)
            raise OSError("transient")
        return orig(*a, **k)

    monkeypatch.setattr(fmt, "write_shard", once)
    s.v = 2
    s.commit()  # background write fails
    _time.sleep(0.5)  # let the writer hit the error
    s.v = 3
    with caplog.at_level(logging.WARNING):
        s.commit()  # drains the pending error, still commits
    s._durable().wait()
    assert any("earlier durable commit" in r.message for r in caplog.records)
    fresh = elastic.ObjectState(name="flaky", v=0)
    os.remove(str(tmp_path / "hvd_state_flaky.pkl"))
    fresh2 = elastic.ObjectState(name="flaky", v=0)
    assert fresh.v == 3 and fresh2.v == 3


def test_objectstate_durable_without_dir_warns(monkeypatch, caplog):
    """The env knob promising durability with no directory configured
    must say so, not silently downgrade to pickle-only."""
    import logging
    import horovod_tpu.elastic as elastic
    monkeypatch.delenv("HVD_ELASTIC_CKPT", raising=False)
    monkeypatch.delenv("HVD_TPU_CHECKPOINT_DIR", raising=False)
    monkeypatch.delenv("HOROVOD_CHECKPOINT_DIR", raising=False)
    monkeypatch.setenv("HVD_TPU_ELASTIC_DURABLE", "1")
    state = elastic.ObjectState(name="nodirs", v=1)
    with caplog.at_level(logging.WARNING):
        assert state._durable() is None
        state.commit()
    assert any("NOT durable" in r.message for r in caplog.records)


def test_objectstate_durable_off_by_default(tmp_path, monkeypatch):
    import horovod_tpu.elastic as elastic
    monkeypatch.setenv("HVD_ELASTIC_CKPT", str(tmp_path))
    monkeypatch.delenv("HVD_TPU_ELASTIC_DURABLE", raising=False)
    monkeypatch.delenv("HOROVOD_ELASTIC_DURABLE", raising=False)
    state = elastic.ObjectState(name="off", v=1)
    state.commit()
    assert state._durable() is None
    assert not os.path.isdir(str(tmp_path / "hvd_state_off.sharded"))


# --------------------------------------------------- CheckpointCallback


def test_checkpoint_callback_roundtrip(tmp_path):
    from horovod_tpu.train.callbacks import CheckpointCallback
    cb = CheckpointCallback(str(tmp_path / "cb"), every_n_steps=2)
    state = {"w": jnp.zeros(4), "step": 0}
    state = cb.on_train_begin(state)  # nothing to restore
    assert cb.restored_step is None
    for step in range(5):
        state = {"w": state["w"] + 1, "step": step}
        cb.on_step_end(step, state)
    cb.on_train_end(4, state)
    assert cb.store.latest_step() == 4
    cb.close()

    cb2 = CheckpointCallback(str(tmp_path / "cb"), every_n_steps=2)
    out = cb2.on_train_begin({"w": jnp.zeros(4), "step": 0})
    assert cb2.restored_step == 4
    np.testing.assert_allclose(np.asarray(out["w"]), np.full(4, 5.0))
    assert int(out["step"]) == 4
    # the next periodic step (6) saves; already-stored steps don't re-save
    cb2.on_step_end(6, {"w": out["w"], "step": 6})
    cb2.store.wait()
    assert cb2.store.latest_step() == 6
    cb2.close()


def test_checkpoint_callback_needs_directory(monkeypatch):
    from horovod_tpu.train.callbacks import CheckpointCallback
    monkeypatch.delenv("HVD_TPU_CHECKPOINT_DIR", raising=False)
    monkeypatch.delenv("HOROVOD_CHECKPOINT_DIR", raising=False)
    with pytest.raises(ValueError, match="CHECKPOINT_DIR"):
        CheckpointCallback()


# ------------------------------------- ShardedDataset data position


def test_sharded_dataset_state_dict_resume():
    from horovod_tpu.data import ShardedDataset
    data = list(range(32))
    ds = ShardedDataset(data, rank=1, size=4, shuffle=True, seed=7)
    ds.set_epoch(2)
    full = list(ds)
    assert len(full) == 8

    ds2 = ShardedDataset(data, rank=1, size=4, shuffle=True, seed=7)
    ds2.set_epoch(2)
    it = iter(ds2)
    consumed = [next(it) for _ in range(3)]
    sd = ds2.state_dict()
    assert sd == {"epoch": 2, "cursor": 3}

    ds3 = ShardedDataset(data, rank=1, size=4, shuffle=True, seed=7)
    ds3.load_state_dict(sd)
    rest = list(ds3)
    assert consumed + rest == full

    # the STANDARD resume loop re-announces the current epoch before
    # iterating — that must keep the restored cursor, not replay
    ds4 = ShardedDataset(data, rank=1, size=4, shuffle=True, seed=7)
    ds4.load_state_dict(sd)
    ds4.set_epoch(sd["epoch"])
    assert list(ds4) == rest
    # a NEW epoch does reset the position
    ds4.set_epoch(sd["epoch"] + 1)
    assert len(list(ds4)) == 8


def test_sharded_dataset_cursor_resets_after_full_epoch():
    from horovod_tpu.data import ShardedDataset
    ds = ShardedDataset(list(range(16)), rank=0, size=2, shuffle=False)
    first = list(ds)
    assert ds.state_dict() == {"epoch": 0, "cursor": 0}
    assert list(ds) == first  # a second full pass is identical
