"""SparkSession stand-in (see package docstring)."""

from __future__ import annotations

from pyspark import Row, _FakeDataFrame, _FakeSparkContext

__all__ = ["Row", "SparkSession"]


class _Session:
    sparkContext = _FakeSparkContext()

    def createDataFrame(self, pdf, n_partitions: int = 2):
        return _FakeDataFrame(pdf, n_partitions)


class _Builder:
    def getOrCreate(self):
        return _Session()


class SparkSession:
    builder = _Builder()
