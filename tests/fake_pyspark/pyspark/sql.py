"""SparkSession stand-in (see package docstring)."""

from __future__ import annotations

from pyspark import _FakeSparkContext


class _Session:
    sparkContext = _FakeSparkContext()


class _Builder:
    def getOrCreate(self):
        return _Session()


class SparkSession:
    builder = _Builder()
