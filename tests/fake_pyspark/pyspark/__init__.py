"""Minimal pyspark stand-in for exercising ``horovod_tpu.spark.run``
without a Spark installation (reference analog: the Spark integration
tests in ``test/integration/test_spark.py`` run against a local-mode
SparkContext; this image has no pyspark, so the barrier-scheduling
surface that ``spark.run`` actually touches is reimplemented here over
subprocesses + a filesystem rendezvous).

Surface implemented (exactly what ``horovod_tpu/spark/__init__.py`` uses):

- ``pyspark.sql.SparkSession.builder.getOrCreate()``
- ``session.sparkContext.defaultParallelism``
- ``sc.parallelize(range(n), n).barrier().mapPartitions(fn).collect()``
- inside each task (a real subprocess, like a Spark executor):
  ``pyspark.BarrierTaskContext.get()`` with ``partitionId()``,
  ``getTaskInfos()`` (``.address``), ``allGather(str)``, ``barrier()``.

The task function is shipped to the worker subprocess with cloudpickle —
the same serialization Spark uses — so closure capture is exercised for
real, and every task runs ``hvd.init()`` in its own process over the
real TCP core, as on a genuine cluster.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
import tempfile
import time


class TaskInfo:
    def __init__(self, address: str):
        self.address = address


class BarrierTaskContext:
    """File-rendezvous barrier context; one instance per worker process.

    Rounds are numbered per process; ``allGather`` writes
    ``<sync>/<round>_<rank>`` and polls until all ``size`` files exist.
    Deterministic and dependency-free, which is all a test needs.
    """

    _instance = None

    def __init__(self):
        self._rank = int(os.environ["FAKE_SPARK_RANK"])
        self._size = int(os.environ["FAKE_SPARK_SIZE"])
        self._sync = os.environ["FAKE_SPARK_SYNC_DIR"]
        self._round = 0

    @classmethod
    def get(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def partitionId(self) -> int:
        return self._rank

    def getTaskInfos(self):
        return [TaskInfo("127.0.0.1:0") for _ in range(self._size)]

    def allGather(self, message: str = ""):
        rnd = self._round
        self._round += 1
        my = os.path.join(self._sync, f"{rnd}_{self._rank}")
        with open(my + ".tmp", "w") as f:
            f.write(message)
        os.rename(my + ".tmp", my)  # atomic publish
        deadline = time.time() + 120
        paths = [os.path.join(self._sync, f"{rnd}_{r}")
                 for r in range(self._size)]
        while not all(os.path.exists(p) for p in paths):
            if time.time() > deadline:
                raise RuntimeError(f"fake barrier round {rnd} timed out")
            time.sleep(0.01)
        out = []
        for p in paths:
            with open(p) as f:
                out.append(f.read())
        return out

    def barrier(self) -> None:
        self.allGather("")


class _FakeBarrierRDD:
    def __init__(self, n: int):
        self._n = n
        self._fn = None

    def mapPartitions(self, fn):
        self._fn = fn
        return self

    def collect(self):
        import cloudpickle

        tmp = tempfile.mkdtemp(prefix="fake_spark_")
        sync = os.path.join(tmp, "sync")
        os.makedirs(sync)
        fn_path = os.path.join(tmp, "task_fn.pkl")
        with open(fn_path, "wb") as f:
            cloudpickle.dump(self._fn, f)

        procs = []
        for rank in range(self._n):
            env = dict(os.environ)
            env.update({
                "FAKE_SPARK_RANK": str(rank),
                "FAKE_SPARK_SIZE": str(self._n),
                "FAKE_SPARK_SYNC_DIR": sync,
                # worker processes must resolve THIS fake pyspark first
                "PYTHONPATH": os.pathsep.join(
                    [os.path.dirname(os.path.dirname(
                        os.path.abspath(__file__)))] +
                    [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                     if p]),
            })
            out_path = os.path.join(tmp, f"out_{rank}.pkl")
            # the worker bootstrap forces the CPU JAX platform the same
            # way every worker script in tests/ does (hvd_worker.py:9-14):
            # this box's sitecustomize re-registers the real TPU platform
            # from inside jax, so the inherited env var alone is not
            # enough — without the config override, unit-test workers
            # would contend for the one real chip
            procs.append((rank, out_path, subprocess.Popen(
                [sys.executable, "-c",
                 "import os, sys\n"
                 "os.environ.setdefault(\n"
                 "    'XLA_FLAGS', '--xla_force_host_platform_device_count=1')\n"
                 "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
                 "import jax\n"
                 "jax.config.update('jax_platforms', 'cpu')\n"
                 "import cloudpickle\n"
                 "fn_path, out_path, rank = sys.argv[1:4]\n"
                 "with open(fn_path, 'rb') as f:\n"
                 "    fn = cloudpickle.load(f)\n"
                 "result = list(fn(iter([int(rank)])))\n"
                 "with open(out_path, 'wb') as f:\n"
                 "    cloudpickle.dump(result, f)\n",
                 fn_path, out_path, str(rank)],
                env=env)))

        results = []
        failed = []
        try:
            for rank, out_path, p in procs:
                rc = p.wait(timeout=180)
                if rc != 0:
                    failed.append((rank, rc))
                    continue
                with open(out_path, "rb") as f:
                    results.extend(cloudpickle.load(f))
        finally:
            # never leak workers: a task wedged in the barrier poll would
            # otherwise outlive the test session
            for _, _, p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait(timeout=30)
            shutil.rmtree(tmp, ignore_errors=True)
        if failed:
            raise RuntimeError(f"fake spark tasks failed: {failed}")
        return results


class Row:
    """Minimal pyspark.sql.Row: attribute access + ``asDict()``."""

    def __init__(self, **kwargs):
        self.__dict__["_fields"] = dict(kwargs)

    def asDict(self):
        return dict(self._fields)

    def __getattr__(self, item):
        try:
            return self.__dict__["_fields"][item]
        except KeyError:
            raise AttributeError(item) from None

    def __repr__(self):
        inner = ", ".join(f"{k}={v!r}" for k, v in self._fields.items())
        return f"Row({inner})"


class _FakeDataFrame:
    """Partitioned DataFrame stand-in: rows live in ``n`` contiguous
    partitions; ``rdd.mapPartitionsWithIndex`` ships the function to one
    SUBPROCESS PER PARTITION (cloudpickle, like a Spark executor) and
    ``collect`` returns only what the function yields — so estimator
    code that materializes data through executors is exercised for real,
    and a ``toPandas()`` regression (driver collect) is observable via
    ``toPandas_calls``."""

    def __init__(self, pdf, n_partitions: int = 2):
        self._pdf = pdf.reset_index(drop=True)
        self._n = n_partitions
        self.toPandas_calls = 0

    def repartition(self, n: int) -> "_FakeDataFrame":
        return _FakeDataFrame(self._pdf, n)

    @property
    def rdd(self):
        return _FakeDataFrameRDD(self._pdf, self._n)

    def toPandas(self):
        self.toPandas_calls += 1
        return self._pdf.copy()


class _FakeDataFrameRDD:
    def __init__(self, pdf, n: int):
        self._pdf, self._n = pdf, n

    def getNumPartitions(self) -> int:
        return self._n

    def mapPartitionsWithIndex(self, fn):
        return _FakeDataFrameJob(self._pdf, self._n, fn)


class _FakeDataFrameJob:
    def collect(self):
        import cloudpickle
        import numpy as np

        tmp = tempfile.mkdtemp(prefix="fake_spark_df_")
        try:
            bounds = np.array_split(np.arange(len(self._pdf)), self._n)
            payloads = []
            for idx, rows_idx in enumerate(bounds):
                rows = [Row(**rec) for rec in self._pdf.iloc[rows_idx]
                        .to_dict(orient="records")]
                path = os.path.join(tmp, f"task_{idx}.pkl")
                with open(path, "wb") as f:
                    cloudpickle.dump((self._fn, idx, rows), f)
                payloads.append((idx, path))

            procs = []
            for idx, path in payloads:
                env = dict(os.environ)
                env["PYTHONPATH"] = os.pathsep.join(
                    [os.path.dirname(os.path.dirname(
                        os.path.abspath(__file__)))] +
                    [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                     if p])
                out_path = os.path.join(tmp, f"out_{idx}.pkl")
                procs.append((idx, out_path, subprocess.Popen(
                    [sys.executable, "-c",
                     "import sys\n"
                     "import cloudpickle\n"
                     "task_path, out_path = sys.argv[1:3]\n"
                     "with open(task_path, 'rb') as f:\n"
                     "    fn, idx, rows = cloudpickle.load(f)\n"
                     "result = list(fn(idx, iter(rows)))\n"
                     "with open(out_path, 'wb') as f:\n"
                     "    cloudpickle.dump(result, f)\n",
                     path, out_path],
                    env=env)))
            results = []
            failed = []
            for idx, out_path, p in procs:
                rc = p.wait(timeout=120)
                if rc != 0:
                    failed.append((idx, rc))
                    continue
                with open(out_path, "rb") as f:
                    results.extend(cloudpickle.load(f))
            if failed:
                raise RuntimeError(f"fake spark df tasks failed: {failed}")
            return results
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    def __init__(self, pdf, n: int, fn):
        self._pdf, self._n, self._fn = pdf, n, fn


class _FakeSparkContext:
    defaultParallelism = 2

    def parallelize(self, data, n):
        return _FakeParallelized(n)


class _FakePlainRDD:
    """Non-barrier mapPartitions: each partition is a subprocess fed its
    partition's data, no sync-dir rendezvous (used by run_elastic's agent
    tasks, which coordinate through the driver KV instead)."""

    def __init__(self, n: int, fn):
        self._n = n
        self._fn = fn

    def collect(self):
        import cloudpickle

        tmp = tempfile.mkdtemp(prefix="fake_spark_plain_")
        fn_path = os.path.join(tmp, "task_fn.pkl")
        with open(fn_path, "wb") as f:
            cloudpickle.dump(self._fn, f)
        procs = []
        for rank in range(self._n):
            env = dict(os.environ)
            env["PYTHONPATH"] = os.pathsep.join(
                [os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__)))] +
                [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                 if p])
            out_path = os.path.join(tmp, f"out_{rank}.pkl")
            procs.append((rank, out_path, subprocess.Popen(
                [sys.executable, "-c",
                 "import os, sys\n"
                 "os.environ.setdefault(\n"
                 "    'XLA_FLAGS', '--xla_force_host_platform_device_count=1')\n"
                 "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
                 "import jax\n"
                 "jax.config.update('jax_platforms', 'cpu')\n"
                 "import cloudpickle\n"
                 "fn_path, out_path, rank = sys.argv[1:4]\n"
                 "with open(fn_path, 'rb') as f:\n"
                 "    fn = cloudpickle.load(f)\n"
                 "result = list(fn(iter([int(rank)])))\n"
                 "with open(out_path, 'wb') as f:\n"
                 "    cloudpickle.dump(result, f)\n",
                 fn_path, out_path, str(rank)],
                env=env)))
        results = []
        failed = []
        try:
            for rank, out_path, p in procs:
                rc = p.wait(timeout=300)
                if rc != 0:
                    failed.append((rank, rc))
                    continue
                with open(out_path, "rb") as f:
                    results.extend(cloudpickle.load(f))
        finally:
            for _, _, p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait(timeout=30)
            shutil.rmtree(tmp, ignore_errors=True)
        if failed:
            raise RuntimeError(f"fake spark tasks failed: {failed}")
        return results


class _FakeParallelized:
    def __init__(self, n: int):
        self._n = n

    def barrier(self):
        return _FakeBarrierRDD(self._n)

    def mapPartitions(self, fn):
        return _FakePlainRDD(self._n, fn)
