"""Compression subsystem tests: quantizer round-trip error bounds, the
Pallas kernel vs the XLA fallback, jit/shard_map compatibility, the
quantized mesh collective, error-feedback residual carry, and the EF
convergence smoke (tiny MLP vs fp32 within 5%).

Reference analog: the reference only ever tested its fp16 cast
(test_torch.py compression cases); the quantized paths are new
(EQuARX, arxiv 2506.17615)."""

import functools

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax

import horovod_tpu as hvd_mod
from horovod_tpu._compat import shard_map
from horovod_tpu.compression import (BlockInt8Quantizer, Compression,
                                     ErrorFeedback, OneBitQuantizer,
                                     Quantized, ef_apply,
                                     error_feedback_transform, fp8_supported,
                                     init_residual, resolve_compressor)
from horovod_tpu.ops.mesh_collectives import (device_allreduce,
                                              preduce_quantized)
from horovod_tpu.ops.reduce_op import ReduceOp


def _rand(shape, seed=0, dtype=jnp.float32):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape), dtype)


# -- quantizer round trips ---------------------------------------------------

def test_int8_roundtrip_error_bound():
    """Documented bound: |x - qdq(x)| <= absmax_block / 254 elementwise."""
    q = BlockInt8Quantizer(block_size=128)
    x = _rand((1000,))
    qt, spec = q.quantize(x)
    out = q.dequantize(qt, spec)
    assert out.shape == x.shape and out.dtype == x.dtype
    blocks = np.pad(np.asarray(x), (0, (-x.size) % 128)).reshape(-1, 128)
    bound = np.abs(blocks).max(axis=1) / 254 + 1e-7
    err = np.abs(np.pad(np.asarray(out - x), (0, (-x.size) % 128))
                 ).reshape(-1, 128)
    assert (err <= bound[:, None]).all()


@pytest.mark.parametrize("shape", [(7,), (1,), (3, 5), (4, 256),
                                   (2, 3, 17)])
def test_int8_shapes_and_padding(shape):
    """Non-block-multiple sizes pad internally and restore exactly."""
    q = BlockInt8Quantizer(block_size=64)
    x = _rand(shape, seed=3)
    qt, spec = q.quantize(x)
    out = q.dequantize(qt, spec)
    assert out.shape == x.shape
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=0.05)


def test_int8_bf16_input_keeps_dtype():
    q = BlockInt8Quantizer(block_size=64)
    x = _rand((128,), dtype=jnp.bfloat16)
    qt, spec = q.quantize(x)
    assert q.dequantize(qt, spec).dtype == jnp.bfloat16


def test_int8_wire_ratio():
    """fp32 -> int8 + per-block fp32 scale: > 3.5x at block 256."""
    q = BlockInt8Quantizer(block_size=256)
    x = _rand((4096,))
    qt, _ = q.quantize(x)
    assert x.nbytes / qt.wire_bytes > 3.5


def test_int8_pallas_interpret_matches_xla():
    """The Pallas kernel (interpret mode on CPU) agrees with the XLA
    fallback: payload codes within +-1, scales within 1 ULP."""
    x = _rand((2048,), seed=7)
    qk, _ = BlockInt8Quantizer(256, interpret=True).quantize(x)
    qx, _ = BlockInt8Quantizer(256).quantize(x)
    assert np.abs(np.asarray(qk.values, np.int32)
                  - np.asarray(qx.values, np.int32)).max() <= 1
    np.testing.assert_allclose(np.asarray(qk.scales),
                               np.asarray(qx.scales), rtol=1e-6)
    # full round trip through the kernel honors the error bound too
    qi = BlockInt8Quantizer(256, interpret=True)
    qt, spec = qi.quantize(x)
    err = np.abs(np.asarray(qi.dequantize(qt, spec)) - np.asarray(x))
    assert err.max() <= np.abs(np.asarray(x)).max() / 254 + 1e-6


def test_pallas_kernel_row_padding():
    """n_blocks not a multiple of the 32-row int8 tile pads and strips."""
    from horovod_tpu.ops.pallas_quantize import (block_dequantize,
                                                 block_quantize)
    blocks = _rand((5, 128), seed=9)
    vals, scales = block_quantize(blocks, interpret=True)
    assert vals.shape == (5, 128) and scales.shape == (5, 1)
    out = block_dequantize(vals, scales, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(blocks),
                               atol=0.05)


@pytest.mark.skipif(not fp8_supported(), reason="no jnp.float8_* dtypes")
@pytest.mark.parametrize("flavor", ["e4m3", "e5m2"])
def test_fp8_roundtrip(flavor):
    from horovod_tpu.compression import FP8Quantizer
    q = FP8Quantizer(flavor)
    x = _rand((512,), seed=1)
    qt, spec = q.quantize(x)
    assert qt.values.dtype.itemsize == 1
    out = q.dequantize(qt, spec)
    assert out.shape == x.shape and out.dtype == x.dtype
    # e4m3 has a ~2^-3 relative step near the top of a binade; scaled by
    # the per-tensor absmax that stays a loose but meaningful bound
    tol = 0.07 if flavor == "e4m3" else 0.3
    np.testing.assert_allclose(np.asarray(out), np.asarray(x),
                               atol=tol * float(jnp.abs(x).max()))


def test_onebit_roundtrip_structure():
    q = OneBitQuantizer()
    x = jnp.asarray([1.5, -0.5, 2.0, -3.0, 0.25, 1.0, -1.0, 0.75])
    qt, spec = q.quantize(x)
    assert qt.values.dtype == jnp.uint8 and qt.values.size == 1  # 8 bits
    mean = float(jnp.mean(jnp.abs(x)))
    out = np.asarray(q.dequantize(qt, spec))
    np.testing.assert_allclose(out, np.sign(np.asarray(x)) * mean,
                               rtol=1e-6)
    # ~32x for fp32 payloads
    big = _rand((8192,))
    qt, _ = q.quantize(big)
    assert big.nbytes / qt.wire_bytes > 25


def test_quantizers_jit_and_vmap():
    q = BlockInt8Quantizer(block_size=128)
    x = _rand((8, 256), seed=4)
    jitted = jax.jit(q.qdq)
    np.testing.assert_allclose(np.asarray(jitted(x)), np.asarray(q.qdq(x)),
                               rtol=1e-6)
    # vmap over a leading axis (the gathered-payload decode pattern)
    qt, spec = q.quantize(x[0])
    stacked = Quantized(jnp.stack([qt.values] * 4),
                        jnp.stack([qt.scales] * 4))
    outs = jax.vmap(lambda v, s: q.dequantize(Quantized(v, s), spec))(
        stacked.values, stacked.scales)
    assert outs.shape == (4,) + x[0].shape


def test_quantizer_hashable_config():
    assert BlockInt8Quantizer(128) == BlockInt8Quantizer(128)
    assert BlockInt8Quantizer(128) != BlockInt8Quantizer(256)
    assert hash(BlockInt8Quantizer(128)) == hash(BlockInt8Quantizer(128))


def test_resolve_compressor():
    assert isinstance(resolve_compressor("int8"), BlockInt8Quantizer)
    assert isinstance(resolve_compressor("onebit"), OneBitQuantizer)
    assert resolve_compressor("none") is Compression.none
    assert resolve_compressor("bf16") is Compression.bf16
    with pytest.raises(ValueError):
        resolve_compressor("zstd")


def test_train_compression_backcompat_shim():
    """The old import surface must keep working (train/compression.py)."""
    from horovod_tpu.train.compression import (Compression as C2,
                                               Compressor, FP16Compressor)
    assert C2.fp16 is FP16Compressor
    assert isinstance(C2.int8, BlockInt8Quantizer)
    assert issubclass(FP16Compressor, Compressor)


# -- quantized mesh collectives ----------------------------------------------

def test_preduce_quantized_shard_map(mesh8):
    """reduce_scatter -> quantize -> allgather -> dequantize inside
    shard_map matches the exact psum within the codec's error bound."""
    from jax.sharding import PartitionSpec as P

    q = BlockInt8Quantizer(block_size=64)
    x = _rand((2, 64, 16), seed=5)  # dp=2 shards of [64, 16]

    @functools.partial(shard_map, mesh=mesh8, in_specs=P("dp"),
                       out_specs=P(), check_vma=False)
    def qsum(s):
        return preduce_quantized(s[0], "dp", q, ReduceOp.SUM)

    exact = np.asarray(x[0] + x[1])
    out = np.asarray(qsum(x))
    assert out.shape == exact.shape
    # one quantization step of error on the REDUCED values (the scatter
    # phase is exact): bound by absmax/254 per 64-block of the sum
    assert np.abs(out - exact).max() <= np.abs(exact).max() / 254 * 1.01


def test_preduce_quantized_rejects(mesh8):
    from jax.sharding import PartitionSpec as P
    q = BlockInt8Quantizer(64)
    x = _rand((2, 63, 4))  # 63 not divisible by dp=2

    @functools.partial(shard_map, mesh=mesh8, in_specs=P("dp"),
                       out_specs=P(), check_vma=False)
    def bad(s):
        return preduce_quantized(s[0], "dp", q, ReduceOp.SUM)

    with pytest.raises(ValueError, match="divisible"):
        bad(x)


def test_device_allreduce_compressed_parity(mesh8):
    """Array-level quantized allreduce: parity with the exact path within
    the documented bound, Sum and Average, and the compression-ratio
    metric lands above 3.5x for int8."""
    from horovod_tpu.compression.metrics import compression_ratio

    x = _rand((2, 128, 8), seed=6)
    exact = np.asarray(device_allreduce(x, mesh8, "dp", ReduceOp.SUM))
    q = BlockInt8Quantizer(block_size=256)
    out = np.asarray(device_allreduce(x, mesh8, "dp", ReduceOp.SUM,
                                      compression=q))
    assert out.shape == exact.shape
    assert np.abs(out - exact).max() <= np.abs(exact).max() / 254 * 1.01

    avg = np.asarray(device_allreduce(x, mesh8, "dp", ReduceOp.AVERAGE,
                                      compression=q))
    np.testing.assert_allclose(avg, out / 2, atol=np.abs(exact).max() / 200)

    assert compression_ratio("int8") > 3.5


def test_device_allreduce_compressed_rejects(mesh8):
    x = _rand((2, 128, 8))
    with pytest.raises(TypeError, match="Quantizer"):
        device_allreduce(x, mesh8, "dp", ReduceOp.SUM,
                         compression=Compression.fp16)
    with pytest.raises(ValueError, match="Sum/Average"):
        device_allreduce(x, mesh8, "dp", ReduceOp.MAX,
                         compression=BlockInt8Quantizer(64))


# -- error feedback ----------------------------------------------------------

def test_ef_residual_carry_exact():
    """One-bit EF on a known vector: residual is exactly acc - C(acc) and
    is re-injected next step."""
    q = OneBitQuantizer()
    g = {"w": jnp.asarray([0.5, -0.25])}
    residual = init_residual(g)
    c1, r1 = ef_apply(q, g, residual)
    # mean|g| = 0.375 -> compressed [0.375, -0.375]
    np.testing.assert_allclose(np.asarray(c1["w"]), [0.375, -0.375],
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(r1["w"]), [0.125, 0.125],
                               rtol=1e-6)
    # second step compresses g + r1 = [0.625, -0.125]: mean = 0.375
    c2, r2 = ef_apply(q, g, r1)
    np.testing.assert_allclose(np.asarray(c2["w"]), [0.375, -0.375],
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(r2["w"]), [0.25, 0.25],
                               rtol=1e-6)


def test_ef_telescopes_to_true_sum():
    """Over K steps of a CONSTANT gradient, sum(compressed) + residual ==
    K * g exactly — EF loses nothing in the long run."""
    q = BlockInt8Quantizer(block_size=64)
    g = {"w": _rand((96,), seed=8)}
    residual = init_residual(g)
    total = jnp.zeros_like(g["w"])
    K = 10
    for _ in range(K):
        c, residual = ef_apply(q, g, residual)
        total = total + c["w"]
    np.testing.assert_allclose(np.asarray(total + residual["w"]),
                               np.asarray(g["w"] * K), rtol=1e-4,
                               atol=1e-5)


def test_ef_non_float_leaves_pass_through():
    g = {"w": jnp.ones(4), "step": jnp.asarray(3, jnp.int32)}
    residual = init_residual(g)
    assert residual["step"] is None
    c, r = ef_apply(BlockInt8Quantizer(64), g, residual)
    assert int(c["step"]) == 3 and r["step"] is None


def test_ef_transform_in_optax_chain():
    tx = optax.chain(error_feedback_transform(BlockInt8Quantizer(64)),
                     optax.sgd(0.1))
    params = {"w": jnp.ones(8)}
    state = tx.init(params)
    u, state = tx.update({"w": jnp.full(8, 0.5)}, state, params)
    assert np.allclose(np.asarray(u["w"]), -0.05, atol=1e-3)


def test_distributed_optimizer_ef_jit(hvd):
    """EF-int8 through the DistributedOptimizer seam, inside jit (the
    global-SPMD regime): state carries the residual pytree."""
    from horovod_tpu.compression import EFState

    tx = hvd.DistributedOptimizer(
        optax.sgd(0.1), compression=ErrorFeedback(Compression.int8))
    params = {"w": jnp.ones((16,))}
    state = tx.init(params)
    sync_state = state[0] if isinstance(state, tuple) else state
    assert isinstance(sync_state, EFState)

    @jax.jit
    def step(p, s):
        u, s = tx.update({"w": jnp.full((16,), 0.25)}, s, p)
        return optax.apply_updates(p, u), s

    p, state = step(params, state)
    assert np.all(np.isfinite(np.asarray(p["w"])))


def test_distributed_grad_rejects_ef(hvd):
    with pytest.raises(ValueError, match="stateless"):
        hvd_mod.distributed_grad(lambda w: jnp.sum(w ** 2),
                                 compression=ErrorFeedback(Compression.int8))


def test_adasum_rejects_compression(hvd):
    with pytest.raises(ValueError, match="Adasum"):
        hvd.DistributedOptimizer(optax.sgd(0.1), op=hvd_mod.Adasum,
                                 compression=ErrorFeedback(Compression.int8))


def test_quantized_allreduce_single_process(hvd):
    """size-1 quantized allreduce degenerates to qdq; metrics record."""
    from horovod_tpu.compression.metrics import compression_ratio

    x = _rand((1024,), seed=11)  # block-multiple: no padding waste
    out = hvd.quantized_allreduce(x, Compression.int8, op=hvd_mod.Sum,
                                  name="t")
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(Compression.int8.qdq(x)),
                               rtol=1e-6)
    outs = hvd.quantized_grouped_allreduce([x, x * 2], Compression.int8,
                                           op=hvd_mod.Average, name="tg")
    assert len(outs) == 2
    assert compression_ratio("int8") > 3.5
    with pytest.raises(ValueError, match="Sum/Average"):
        hvd.quantized_allreduce(x, Compression.int8, op=hvd_mod.Max)


# -- convergence smoke -------------------------------------------------------

def _train_tiny_mlp(tx, steps=150, seed=0):
    """Tiny 2-layer MLP regression; returns the final loss."""
    rng = np.random.RandomState(seed)
    X = jnp.asarray(rng.randn(64, 8), jnp.float32)
    w_true = jnp.asarray(rng.randn(8, 1), jnp.float32)
    Y = jnp.tanh(X @ w_true) + 0.01 * jnp.asarray(
        rng.randn(64, 1), jnp.float32)
    params = {
        "w1": jnp.asarray(rng.randn(8, 16) * 0.3, jnp.float32),
        "b1": jnp.zeros(16),
        "w2": jnp.asarray(rng.randn(16, 1) * 0.3, jnp.float32),
        "b2": jnp.zeros(1),
    }

    def loss_fn(p):
        h = jnp.tanh(X @ p["w1"] + p["b1"])
        return jnp.mean((h @ p["w2"] + p["b2"] - Y) ** 2)

    state = tx.init(params)

    @jax.jit
    def step(p, s):
        loss, g = jax.value_and_grad(loss_fn)(p)
        u, s = tx.update(g, s, p)
        return optax.apply_updates(p, u), s, loss

    for _ in range(steps):
        params, state, loss = step(params, state)
    return float(loss_fn(params))


def test_ef_convergence_smoke_vs_fp32(hvd):
    """Acceptance: EF-wrapped int8 training reaches the fp32 loss within
    5% on the tiny MLP (the EF residual recovers what quantization
    rounds away each step)."""
    base = _train_tiny_mlp(hvd.DistributedOptimizer(optax.sgd(0.05)))
    ef = _train_tiny_mlp(hvd.DistributedOptimizer(
        optax.sgd(0.05), compression=ErrorFeedback(Compression.int8)))
    assert ef <= base * 1.05 + 1e-5, (base, ef)


def test_onebit_needs_ef_smoke(hvd):
    """The 1-bit codec converges under EF where its bias would otherwise
    stall training — the reason ErrorFeedback exists."""
    ef = _train_tiny_mlp(hvd.DistributedOptimizer(
        optax.sgd(0.05), compression=ErrorFeedback(Compression.onebit)),
        steps=300)
    base = _train_tiny_mlp(hvd.DistributedOptimizer(optax.sgd(0.05)),
                           steps=300)
    # loose factor: onebit trades precision for 32x wire savings, but EF
    # must keep it in the same basin (not diverged / stuck at init)
    assert ef <= base * 3 + 0.05, (base, ef)
