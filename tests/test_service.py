"""Driver/task bootstrap RPC + NIC probing tests (reference analog:
test/single/test_service.py + test_task_service.py — fake interfaces,
secret auth, routability selection)."""

import socket
import threading

import pytest

from horovod_tpu.runner.service import (TaskClient, TaskService,
                                        find_routable_interfaces,
                                        get_local_addresses,
                                        pick_rendezvous_address)

SECRET = b"0123456789abcdef"


@pytest.fixture
def two_services():
    a = TaskService(0, SECRET, addresses_override={
        "lo": "127.0.0.1", "deadnet": "203.0.113.7"}).start()
    b = TaskService(1, SECRET, addresses_override={
        "lo": "127.0.0.1"}).start()
    try:
        yield (a, TaskClient("127.0.0.1", a.port, SECRET),
               b, TaskClient("127.0.0.1", b.port, SECRET))
    finally:
        a.stop()
        b.stop()


def test_local_addresses_enumerates_loopback():
    addrs = get_local_addresses()
    assert "127.0.0.1" in addrs.values()


def test_addresses_and_probe_rpc(two_services):
    a, ca, b, cb = two_services
    assert ca.addresses() == {"lo": "127.0.0.1", "deadnet": "203.0.113.7"}
    # b can reach a's service port on loopback...
    assert cb.probe("127.0.0.1", a.port)
    # ...but not a closed port
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    closed = s.getsockname()[1]
    s.close()
    assert not cb.probe("127.0.0.1", closed, timeout=0.5)


def test_bad_secret_rejected(two_services):
    a, ca, _, _ = two_services
    evil = TaskClient("127.0.0.1", a.port, b"wrong-secret-....")
    import urllib.error
    with pytest.raises(urllib.error.HTTPError):
        evil.addresses()


def test_routability_filters_dead_interfaces(two_services, monkeypatch):
    """Interfaces the probing peer cannot connect to are dropped. The
    probe itself is faked (this sandbox NATs every TCP connect to
    success, so real unreachability cannot be produced here); the live
    connect path is covered by test_addresses_and_probe_rpc."""
    a, ca, b, cb = two_services
    monkeypatch.setattr(
        TaskClient, "probe",
        lambda self, addr, port, timeout=2.0: addr == "127.0.0.1")
    routable = find_routable_interfaces([ca, cb])
    # the fake routing says only loopback is reachable for task 0
    assert routable[0] == (0, {"lo": "127.0.0.1"})
    assert routable[1] == (1, {"lo": "127.0.0.1"})
    assert pick_rendezvous_address(routable) == "127.0.0.1"


def test_restrict_list(two_services, monkeypatch):
    a, ca, b, cb = two_services
    monkeypatch.setattr(
        TaskClient, "probe",
        lambda self, addr, port, timeout=2.0: addr == "127.0.0.1")
    routable = find_routable_interfaces([ca, cb], restrict=["lo"])
    assert routable[0][1] == {"lo": "127.0.0.1"}
    with pytest.raises(RuntimeError, match="no mutually-routable"):
        find_routable_interfaces([ca, cb], restrict=["deadnet"])


def test_pick_rendezvous_prefers_non_loopback():
    routable = [(0, {"lo": "127.0.0.1", "eth0": "10.0.0.5"})]
    assert pick_rendezvous_address(routable) == "10.0.0.5"


def test_single_task_skips_peer_probe():
    svc = TaskService(0, SECRET,
                      addresses_override={"eth0": "10.1.2.3"}).start()
    try:
        c = TaskClient("127.0.0.1", svc.port, SECRET)
        routable = find_routable_interfaces([c])
        assert routable == [(0, {"eth0": "10.1.2.3"})]
    finally:
        svc.stop()


def test_task_server_entry_point(monkeypatch):
    """The ssh-launched module prints its port and serves until shutdown."""
    import subprocess
    import sys
    import os
    env = dict(os.environ)
    env.pop("HVD_TPU_SERVICE_SECRET", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner.task_server",
         "--index", "3", "--ttl", "30"],
        env=env, stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    try:
        # the secret travels over stdin (the ssh channel in production),
        # never argv/env where a remote process table would leak it
        proc.stdin.write(SECRET.hex() + "\n")
        proc.stdin.flush()
        line = proc.stdout.readline()
        assert line.startswith("HVD_TASK_PORT=")
        port = int(line.strip().split("=")[1])
        c = TaskClient("127.0.0.1", port, SECRET)
        assert c.addresses()  # live RPC
        c.shutdown()
        assert proc.wait(timeout=10) == 0
    finally:
        if proc.poll() is None:
            proc.terminate()