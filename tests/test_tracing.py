"""Causal fleet tracing (ISSUE 15, docs/OBSERVABILITY.md "Causal
tracing").

Fast battery: the trace-context unit battery (encode/decode/
propagate/malformed-header-ignored), hedged-duplicate sibling-span
semantics through a real router, the replica serve-span childing from
the HTTP header, the KV-doc roundtrip through the relay tree, the
finding→decision trace chain, re-mesh episode stamping, the request-
log/actions-JSONL rotation satellites, and the merged-timeline /
``trace <id>`` readers joining ≥2 planes.

Slow (tier-1 budget rule — multiprocess): the ISSUE acceptance (a): a
chaos-delayed replica of a 2-replica SUBPROCESS fleet under load —
``diagnostics trace <id>`` shows the hedged request's spans covering
the router and BOTH replicas with correct parentage and the delay
attributed to the slow hop.  (Acceptance (b) — the straggler→autopilot
→re-mesh chain under ``act`` — rides the existing scenario in
tests/test_autopilot.py, which asserts the single trace id end to
end.)
"""

import io
import json
import os
import time
import urllib.request
from contextlib import redirect_stdout

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from horovod_tpu import tracing  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    monkeypatch.delenv("HVD_TPU_TRACE", raising=False)
    monkeypatch.delenv("HVD_TPU_TRACE_SAMPLE", raising=False)
    monkeypatch.delenv("HVD_TPU_CLOCK_OFFSET_S", raising=False)
    tracing.set_current(None)
    yield
    tracing.set_current(None)


# -- the context unit battery -------------------------------------------------
def test_traceparent_roundtrip():
    ctx = tracing.new_trace("generic")
    assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
    assert ctx.parent_id is None
    header = tracing.encode(ctx)
    assert header == f"00-{ctx.trace_id}-{ctx.span_id}-01"
    back = tracing.decode(header)
    assert back.trace_id == ctx.trace_id
    assert back.span_id == ctx.span_id
    assert back.parent_id is None  # the wire carries trace+span only


def test_malformed_headers_ignored_and_counted():
    from horovod_tpu.metrics.registry import default_registry
    before = getattr(default_registry().get("hvd_trace_dropped_total"),
                     "value", 0)
    for bad in ("junk", "00-zz-xx-01", "00-1234-5678-01",
                "01-" + "a" * 32 + "-" + "b" * 16 + "-01",
                "00-" + "0" * 32 + "-" + "b" * 16 + "-01"):
        assert tracing.decode(bad) is None, bad
    # absent is untraced, NOT a drop
    assert tracing.decode(None) is None
    assert tracing.decode("") is None
    after = default_registry().get("hvd_trace_dropped_total").value
    assert after - before == 5


def test_child_and_sibling_parentage():
    root = tracing.new_trace()
    c1 = tracing.child(root)
    c2 = tracing.child(root)
    assert c1.trace_id == root.trace_id
    assert c1.parent_id == root.span_id == c2.parent_id
    assert c1.span_id != c2.span_id
    # a hedged duplicate: same trace, same PARENT, fresh span
    dup = tracing.sibling(c1)
    assert dup.trace_id == c1.trace_id
    assert dup.parent_id == c1.parent_id
    assert dup.span_id != c1.span_id
    # None-safety end to end
    assert tracing.child(None) is None
    assert tracing.sibling(None) is None
    assert tracing.encode(None) is None
    assert tracing.fields(None) == {}


def test_disabled_env_kills_every_source(monkeypatch):
    monkeypatch.setenv("HVD_TPU_TRACE", "0")
    assert tracing.new_trace() is None
    assert tracing.decode("00-" + "a" * 32 + "-" + "b" * 16 + "-01") \
        is None
    live = tracing.TraceContext("a" * 32, "b" * 16)
    assert tracing.child(live) is None


def test_sampling_is_a_property_of_the_id(monkeypatch):
    monkeypatch.setenv("HVD_TPU_TRACE_SAMPLE", "0")
    assert tracing.new_trace() is None
    monkeypatch.setenv("HVD_TPU_TRACE_SAMPLE", "1.0")
    assert tracing.new_trace() is not None
    monkeypatch.setenv("HVD_TPU_TRACE_SAMPLE", "not-a-float")
    assert tracing.new_trace() is not None  # bad knob degrades to keep


def test_activation_stamps_flight_events():
    from horovod_tpu.diagnostics.flight_recorder import (record_event,
                                                         recorder)
    ctx = tracing.new_trace()
    inner = tracing.child(ctx)
    with tracing.activate(ctx):
        record_event("outer_ev")
        with tracing.activate(inner):
            record_event("inner_ev")
        record_event("outer_again")
    record_event("outside")
    evs = {e["kind"]: e for e in recorder().events()[-4:]}
    assert evs["outer_ev"]["span"] == ctx.span_id
    assert evs["inner_ev"]["span"] == inner.span_id
    assert evs["inner_ev"]["parent"] == ctx.span_id
    assert evs["outer_again"]["span"] == ctx.span_id  # restored
    assert "trace" not in evs["outside"]
    # explicit fields always win over the ambient context
    with tracing.activate(ctx):
        record_event("explicit", **inner.fields())
    assert recorder().events()[-1]["span"] == inner.span_id


def test_flight_dump_carries_wall_offset(monkeypatch):
    from horovod_tpu.diagnostics import flight_recorder as fr
    old = fr.wall_offset()
    try:
        fr.set_wall_offset(2.5)
        assert fr.recorder().dump()["wall_offset_s"] == 2.5
        monkeypatch.setenv("HVD_TPU_CLOCK_OFFSET_S", "7.25")
        assert fr.recorder().dump()["wall_offset_s"] == 7.25
    finally:
        fr.set_wall_offset(old)


# -- hedged duplicates through a real router ---------------------------------
@pytest.fixture
def replica_pair():
    from horovod_tpu.serving.replica import ReplicaServer
    slow = ReplicaServer(dim=4, replica_id="slowr").start()
    fast = ReplicaServer(dim=4, replica_id="fastr").start()
    orig = slow.handle_infer

    def delayed(doc, trace=None):
        time.sleep(0.5)
        return orig(doc, trace=trace)

    slow.handle_infer = delayed
    yield slow, fast
    slow.stop()
    fast.stop()


def test_hedged_attempts_are_sibling_spans(replica_pair):
    from horovod_tpu.diagnostics.flight_recorder import recorder
    from horovod_tpu.serving.router import Router
    slow, fast = replica_pair
    router = Router([("127.0.0.1", slow.port),
                     ("127.0.0.1", fast.port)],
                    hedge_ms=80, max_inflight=8)
    try:
        doc = router.submit([1.0, 2.0, 3.0, 4.0], req_id="h1")
        assert doc["replica"] == "fastr"  # the hedge won
        time.sleep(0.8)  # let the slow primary's span record too
        entries = [e for e in router.log.entries if e["id"] == "h1"]
        by_outcome = {e["outcome"]: e for e in entries}
        assert "hedged" in by_outcome, entries
        trace_id = by_outcome["accepted"]["trace"]
        assert by_outcome["ok"]["trace"] == trace_id
        assert by_outcome["hedged"]["trace"] == trace_id
        root_span = by_outcome["accepted"]["span"]
        spans = [e for e in recorder().events()
                 if e.get("kind") == "trace_span"
                 and e.get("trace") == trace_id]
        dispatch = [s for s in spans if s["name"] == "dispatch"]
        assert len(dispatch) == 2
        # SIBLINGS: both attempts child the request's root span
        assert {d["parent"] for d in dispatch} == {root_span}
        assert len({d["span"] for d in dispatch}) == 2
        # the replicas' serve spans child their own attempt
        serve = {s["replica"]: s for s in spans
                 if s["name"] == "serve"}
        assert set(serve) == {"slowr", "fastr"}
        attempt_ids = {d["span"] for d in dispatch}
        assert serve["slowr"]["parent"] in attempt_ids
        assert serve["fastr"]["parent"] in attempt_ids
        assert serve["slowr"]["parent"] != serve["fastr"]["parent"]
        # the response names its trace (bench/client join key)
        assert doc["trace"] == trace_id
    finally:
        router.close()


def test_replica_serve_span_childs_from_header(replica_pair):
    from horovod_tpu.diagnostics.flight_recorder import recorder
    _slow, fast = replica_pair
    ctx = tracing.new_trace("serving")
    body = json.dumps({"id": "hdr1", "x": [1, 0, 0, 0]}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{fast.port}/infer", data=body,
        method="POST", headers={"Content-Type": "application/json",
                                tracing.TRACEPARENT: ctx.traceparent})
    with urllib.request.urlopen(req, timeout=10) as r:
        doc = json.loads(r.read())
    assert doc["trace"] == ctx.trace_id
    spans = [e for e in recorder().events()
             if e.get("kind") == "trace_span"
             and e.get("trace") == ctx.trace_id]
    serve = [s for s in spans if s["name"] == "serve"]
    assert serve and serve[0]["parent"] == ctx.span_id
    # queue + padded forward are the serve span's children, version on
    # the forward (the request is traceable through the batcher)
    kids = {s["name"]: s for s in spans
            if s.get("parent") == serve[0]["span"]}
    assert set(kids) == {"batcher_queue", "padded_forward"}
    assert kids["padded_forward"]["version"] == doc["version"]


# -- KV-doc roundtrip through the relay ---------------------------------------
def test_kv_doc_roundtrip_through_relay(monkeypatch):
    from horovod_tpu.diagnostics.flight_recorder import recorder
    from horovod_tpu.runner import kv_relay
    from horovod_tpu.runner.http_kv import KVStoreServer
    root = KVStoreServer()
    root.start()
    try:
        monkeypatch.setenv("HVD_TPU_KV_RELAY_ARITY", "2")
        relay = kv_relay.RelayKVServer(
            lambda: kv_relay.RelayClient(1, "127.0.0.1", root.port,
                                         arity=2))
        relay.start()
        try:
            ctx = tracing.new_trace("autopilot")
            doc = json.dumps({"action": "drain", "rank": 1,
                              "traceparent": ctx.traceparent}).encode()
            # publish THROUGH the relay node, as a worker would
            with tracing.activate(ctx):
                from horovod_tpu.runner.http_kv import kv_put
                kv_put("127.0.0.1", relay.port, "action", "1-1", doc)
            stored = root.get("action", "1-1")
            assert stored is not None
            got = json.loads(stored)
            # the doc's embedded context survives the hop unchanged —
            # the driver childs from exactly what the worker stamped
            assert tracing.from_doc(got).trace_id == ctx.trace_id
            assert tracing.from_doc(got).span_id == ctx.span_id
            # and the relay recorded its forward hop as a child span
            fwd = [e for e in recorder().events()
                   if e.get("kind") == "trace_span"
                   and e.get("name") == "relay_forward"
                   and e.get("trace") == ctx.trace_id]
            assert fwd and fwd[0]["parent"] == ctx.span_id
        finally:
            relay.stop()
    finally:
        root.stop()
        kv_relay.reset()


# -- the finding → decision → action chain ------------------------------------
def test_decision_chain_carries_finding_trace(monkeypatch):
    from horovod_tpu.autopilot.engine import PolicyEngine
    from horovod_tpu.autopilot.policy import Policy
    policy = Policy(name="t-freeze", finding="recompile_storm",
                    action="freeze_alert", hysteresis=1)
    eng = PolicyEngine(policies=[policy], mode="observe", rank=0)
    ctx = tracing.new_trace("anomaly")
    finding = {"kind": "recompile_storm", "function": "f",
               tracing.TRACEPARENT: ctx.traceparent, **ctx.fields()}
    decisions = eng.on_finding(finding)
    assert len(decisions) == 1
    d = decisions[0]
    assert d["trace"] == ctx.trace_id
    assert d["parent"] == ctx.span_id  # decision childs the finding
    assert d["span"] != ctx.span_id
    assert tracing.decode(d["traceparent"]).span_id == d["span"]


def test_anomaly_finding_roots_a_trace():
    from horovod_tpu.metrics.anomaly import AnomalyEngine
    eng = AnomalyEngine()
    finding = eng.report("recompile_storm", function="g", compiles=5)
    assert len(finding["trace"]) == 32 and len(finding["span"]) == 16
    assert tracing.decode(finding["traceparent"]).trace_id \
        == finding["trace"]
    from horovod_tpu.diagnostics.flight_recorder import recorder
    flight = [e for e in recorder().events()
              if e.get("kind") == "anomaly"
              and e.get("trace") == finding["trace"]]
    assert flight and flight[0]["span"] == finding["span"]
    assert "traceparent" not in flight[0]


def test_remesh_episode_stamps_trace():
    from horovod_tpu.diagnostics.flight_recorder import recorder
    from horovod_tpu.elastic import remesh
    remesh.reset()
    try:
        drain_stamp = {"ranks": [2]}
        parent = tracing.new_trace("elastic")
        drain_stamp["traceparent"] = parent.traceparent
        ep = remesh.begin("preemption_drain", old_size=3)
        ep.set_trace(tracing.child(
            tracing.from_doc(drain_stamp), "remesh"))
        with remesh.phase("drain"):
            time.sleep(0.01)
        remesh.mark_recovered(new_size=3, generation=7)
        remesh.note_step_end()
        evs = [e for e in recorder().events()
               if e.get("trace") == parent.trace_id]
        kinds = {e["kind"] for e in evs}
        assert {"remesh_phase", "remesh_complete",
                "trace_span"} <= kinds
        phases = [e for e in evs if e["kind"] == "trace_span"
                  and e["plane"] == "remesh"]
        # episode span + per-phase children
        names = {e["name"] for e in phases}
        assert "remesh_preemption_drain" in names
        assert "drain" in names and "first_step" in names
        # the episode childs from the drain stamp's span
        episode = next(e for e in phases
                       if e["name"] == "remesh_preemption_drain")
        assert episode["parent"] == parent.span_id
    finally:
        remesh.reset()


# -- rotation satellites ------------------------------------------------------
def test_reqlog_rotation_and_torn_tail_reader(tmp_path):
    from horovod_tpu.serving.router import RequestLog, read_request_log
    path = str(tmp_path / "reqlog.jsonl")
    log = RequestLog(path, max_bytes=600)
    n = 40
    for i in range(n):
        log.note(f"r{i}", "accepted", seq=i)
    log.close()
    assert os.path.exists(path + ".1")  # rotated exactly one gen back
    assert os.path.getsize(path) <= 600
    # torn tail: a crash mid-append leaves half a line
    with open(path, "a") as f:
        f.write('{"ts": 1, "id": "torn')
    entries = read_request_log(path)
    ids = [e["id"] for e in entries]
    assert ids == sorted(ids, key=lambda s: int(s[1:]))  # in order
    assert ids[-1] == f"r{n - 1}" and "torn" not in ids
    # the in-memory exactly-once audit is untouched by rotation
    acct = log.accounting()
    assert acct["accepted"] == n and not acct["answered_twice"]
    # close() is FINAL: a late hedge completion noting after close
    # stays in memory but must not resurrect the file handle
    size_before = os.path.getsize(path)
    log.note("late", "ok", seq=n)
    assert os.path.getsize(path) == size_before
    assert log.entries[-1]["id"] == "late"
    # and a bad path fails loudly at construction, not silently
    with pytest.raises(OSError):
        RequestLog(str(tmp_path / "no-such-dir" / "log.jsonl"))


def test_actions_jsonl_rotation_reads_across_boundary(tmp_path,
                                                      monkeypatch):
    from horovod_tpu.autopilot.engine import PolicyEngine
    from horovod_tpu.autopilot.policy import Policy
    from horovod_tpu.metrics.timeseries import read_series
    monkeypatch.setenv("HVD_TPU_OBS_DIR", str(tmp_path))
    monkeypatch.setenv("HVD_TPU_ACTIONS_MAX_BYTES", "900")
    policy = Policy(name="t-rot", finding="recompile_storm",
                    action="freeze_alert", hysteresis=1, cooldown_s=0.0,
                    max_actions=1000, window_s=60.0,
                    key_field="function")
    eng = PolicyEngine(policies=[policy], mode="observe", rank=0)
    n = 30
    for i in range(n):
        eng.on_finding({"kind": "recompile_storm", "function": f"f{i}"})
    path = tmp_path / "actions_rank0.jsonl"
    prev = tmp_path / "actions_rank0.jsonl.1"
    assert path.exists() and prev.exists()  # rotated, one gen kept
    assert path.stat().st_size <= 900
    decisions = read_series(str(tmp_path), basename="actions")
    current = path.read_text().splitlines()
    # the reader crossed the rotation boundary: strictly more than the
    # live file holds, in recording order, ending at the newest
    assert len(decisions) == len(current) + \
        len(prev.read_text().splitlines())
    assert len(decisions) > len(current)
    keys = [d["key"] for d in decisions]
    assert keys[-1] == f"f{n - 1}"
    assert keys == sorted(keys, key=lambda k: int(k[1:]))


# -- the unified readers ------------------------------------------------------
def _fake_planes(tmp_path, offset_s=0.0):
    """A two-plane fixture: a flight dump (request spans, offset
    clock) + an OBS store (a traced re-mesh point and a decision)."""
    ctx = tracing.TraceContext("ab" * 16, "12" * 8)
    child = tracing.TraceContext(ctx.trace_id, "34" * 8, ctx.span_id)
    now = time.time()
    flight = {
        "rank": 1, "wall_offset_s": offset_s,
        "events": [
            {"ts": now + offset_s, "kind": "trace_span",
             "plane": "serving", "name": "request",
             "start": now + offset_s - 0.2, "dur_s": 0.2,
             "trace": ctx.trace_id, "span": ctx.span_id},
            {"ts": now + offset_s, "kind": "trace_span",
             "plane": "serving", "name": "dispatch",
             "start": now + offset_s - 0.19, "dur_s": 0.18,
             "trace": ctx.trace_id, "span": child.span_id,
             "parent": ctx.span_id, "target": "h:1"},
            {"ts": now + offset_s, "kind": "serving_swap",
             "version": 3},
        ],
    }
    with open(tmp_path / "hvd_flight_rank1.json", "w") as f:
        json.dump(flight, f)
    obs = tmp_path / "obs"
    obs.mkdir()
    with open(obs / "obs_rank0.jsonl", "w") as f:
        f.write(json.dumps({
            "ts": now, "remesh": {"drain": 0.1},
            "remesh_total_s": 0.5, "trigger": "preemption_drain",
            "trace": ctx.trace_id, "span": "56" * 8,
            "parent": ctx.span_id}) + "\n")
    with open(obs / "actions_rank0.jsonl", "w") as f:
        f.write(json.dumps({
            "ts": now, "policy": "p", "outcome": "fired",
            "trace": ctx.trace_id, "span": "78" * 8,
            "parent": ctx.span_id}) + "\n")
    reqlog = tmp_path / "reqlog.jsonl"
    with open(reqlog, "w") as f:
        f.write(json.dumps({
            "ts": now, "id": "r1", "outcome": "ok",
            "latency_s": 0.2, "trace": ctx.trace_id,
            "span": ctx.span_id}) + "\n")
    return ctx, obs, reqlog


def test_merged_timeline_joins_planes_and_corrects_skew(tmp_path):
    from horovod_tpu.diagnostics.__main__ import main as diag_main
    ctx, obs, reqlog = _fake_planes(tmp_path, offset_s=100.0)
    out = tmp_path / "merged.json"
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = diag_main(["timeline", "--dir", str(tmp_path),
                        "--obs-dir", str(obs),
                        "--reqlog", str(reqlog), "-o", str(out)])
    assert rc == 0, buf.getvalue()
    doc = json.load(open(out))
    evs = [e for e in doc["traceEvents"] if e.get("ph") != "M"]
    tracks = {e["pid"] for e in evs}
    assert len(tracks) >= 3  # flight + reqlog + obs planes
    spans = [e for e in evs if e.get("ph") == "X"]
    assert spans
    # skew correction: the flight dump's 100s offset was subtracted,
    # so its request span and the reqlog's (offset-free) ok span —
    # the same 0.2s window — land together after rebasing (µs scale)
    req = [e for e in spans if e["name"] == "serving:request"][0]
    ok = [e for e in spans if e["name"] == "ok"][0]
    assert abs(req["ts"] - ok["ts"]) < 0.05e6, (req["ts"], ok["ts"])


def test_trace_cli_renders_causal_tree_across_planes(tmp_path):
    from horovod_tpu.diagnostics.__main__ import main as diag_main
    ctx, obs, reqlog = _fake_planes(tmp_path)
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = diag_main(["trace", ctx.trace_id[:8],  # prefix resolves
                        "--dir", str(tmp_path), "--obs-dir", str(obs),
                        "--reqlog", str(reqlog)])
    assert rc == 0
    out = buf.getvalue()
    assert ctx.trace_id in out
    assert "serving:request" in out
    assert "dispatch" in out and "<< slow hop" in out
    # the OBS planes joined the same tree: the re-mesh point and the
    # decision hang off the request's root span
    assert "preemption_drain" in out and "fired" in out
    # unknown id fails loudly
    buf2 = io.StringIO()
    with redirect_stdout(buf2):
        assert diag_main(["trace", "ffff0000", "--dir",
                          str(tmp_path)]) == 1


# -- acceptance (a): hedge across a chaos-delayed SUBPROCESS fleet -----------
@pytest.mark.slow
def test_hedged_trace_covers_router_and_both_replicas(tmp_path):
    """ISSUE 15 acceptance (a): one replica of a 2-replica subprocess
    fleet is chaos-delayed; under load, a hedged request's
    ``diagnostics trace <id>`` output shows spans from the router and
    BOTH replica processes with correct parentage, and the injected
    delay attributed to the slow hop."""
    from horovod_tpu.diagnostics.flight_recorder import recorder
    from horovod_tpu.serving.fleet import ReplicaFleet
    from horovod_tpu.serving.router import Router
    dumps = tmp_path / "dumps"
    dumps.mkdir()
    plan = json.dumps({"faults": [
        {"seam": "serving.request", "kind": "delay", "rank": 0,
         "start": 0, "stop": 1_000_000, "delay_ms": 400}]})
    fleet = ReplicaFleet(size=2, dim=4, extra_env={
        "HVD_TPU_FAULT_PLAN": plan,
        "HVD_TPU_FLIGHT_DUMP_ON_EXIT": "1",
        "HVD_TPU_AUTOPSY_DIR": str(dumps),
        "HVD_TPU_TRACE": "1",
    }).start(ready_timeout_s=120.0)
    router = Router(fleet.endpoints, hedge_ms=80, max_inflight=16)
    hedged_trace = None
    try:
        for i in range(12):
            try:
                router.submit([1.0, 0.0, 0.0, 0.0], req_id=f"acc-{i}")
            except Exception:
                pass
            hedged = [e for e in router.log.entries
                      if e["outcome"] == "hedged" and e.get("trace")]
            if hedged:
                hedged_trace = hedged[0]["trace"]
                break
        assert hedged_trace, router.log.entries
        time.sleep(1.0)  # the delayed primary's spans must land too
        # graceful drain so each replica's atexit flight dump lands
        fleet._stop.set()  # no heal-respawns during the drain
        for slot in (0, 1):
            fleet.drain(slot)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and any(
                r.proc.poll() is None
                for r in fleet._replicas.values()):
            time.sleep(0.2)
    finally:
        router_dump = str(dumps / "hvd_flight_rank9.json")
        recorder().dump_to(router_dump)
        router.close()
        fleet.stop()
    dump_files = sorted(os.listdir(dumps))
    assert len([n for n in dump_files if "flight" in n]) >= 3, dump_files

    from horovod_tpu.tracing.reader import collect
    data = collect(
        flight_paths=[str(dumps / n) for n in dump_files
                      if "flight" in n],
        trace_id=hedged_trace)
    spans = {s["span"]: s for s in data["spans"]}
    root = [s for s in spans.values() if s["name"] == "request"]
    assert len(root) == 1
    dispatch = [s for s in spans.values() if s["name"] == "dispatch"]
    assert len(dispatch) == 2
    assert all(d["parent"] == root[0]["span"] for d in dispatch)
    serve = [s for s in spans.values() if s["name"] == "serve"]
    # BOTH replica processes contributed their spans, each childing
    # the router attempt that reached it
    assert {s["attrs"]["replica"].split(".")[0] for s in serve} \
        == {"slot0", "slot1"}
    for s in serve:
        assert s["parent"] in {d["span"] for d in dispatch}
    # the injected 400ms lives on the slow dispatch hop
    slowest = max(d["dur_s"] for d in dispatch)
    assert slowest >= 0.35, dispatch

    from horovod_tpu.diagnostics.__main__ import main as diag_main
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = diag_main(["trace", hedged_trace, "--dir", str(dumps)])
    assert rc == 0
    out = buf.getvalue()
    assert "serving:request" in out
    assert out.count("serving:dispatch") == 2
    assert "slot0" in out and "slot1" in out
    assert "<< slow hop" in out
