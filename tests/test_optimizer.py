"""DistributedOptimizer / grad-transform / broadcast tests (reference analog:
optimizer wrapper tests inside test/parallel/test_torch.py and
test/parallel/test_tensorflow.py)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax

import horovod_tpu as hvd_mod
from horovod_tpu._compat import shard_map
from horovod_tpu.ops.adasum import adasum_combine, adasum_tree_reduce


def test_distributed_optimizer_single_process(hvd):
    tx = hvd.DistributedOptimizer(optax.sgd(0.1))
    params = {"w": jnp.ones(4), "b": jnp.zeros(2)}
    grads = {"w": jnp.full(4, 2.0), "b": jnp.ones(2)}
    state = tx.init(params)
    updates, state = tx.update(grads, state, params)
    new_params = optax.apply_updates(params, updates)
    np.testing.assert_allclose(np.asarray(new_params["w"]),
                               np.ones(4) - 0.1 * 2.0, rtol=1e-6)


def test_distributed_optimizer_inside_jit(hvd):
    """Under jit the transform must stay traceable (identity collective)."""
    tx = hvd.DistributedOptimizer(optax.adam(1e-2))
    params = {"w": jnp.ones((3, 3))}
    state = tx.init(params)

    @jax.jit
    def step(params, state):
        grads = {"w": jnp.ones((3, 3))}
        updates, state = tx.update(grads, state, params)
        return optax.apply_updates(params, updates), state

    p1, state = step(params, state)
    assert np.all(np.isfinite(np.asarray(p1["w"])))


def test_traced_identity_warns_at_multi_process(hvd, monkeypatch):
    """Traced sync with size()>1, no axis_name, no host sync is an identity
    that silently diverges per-process jits — it must warn once (ADVICE r1)."""
    import warnings
    from horovod_tpu.train import optimizer as opt_mod
    monkeypatch.setattr(opt_mod, "size", lambda: 2)
    monkeypatch.setattr(opt_mod, "_warned_traced_identity", False)
    tx = hvd_mod.DistributedGradTransform()
    params = {"w": jnp.ones(3)}
    state = tx.init(params)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        jax.jit(lambda g, s: tx.update(g, s))(params, state)
        jax.jit(lambda g, s: tx.update(g, s))({"w": jnp.zeros(3)}, state)
    msgs = [w for w in caught if "silently diverge" in str(w.message)]
    assert len(msgs) == 1  # once, not per trace


def test_grad_transform_shard_map_axis(hvd, mesh8):
    """Per-device grads synced with an explicit axis name inside shard_map —
    the chip-level DP path."""
    from functools import partial
    from jax.sharding import PartitionSpec as P

    tx = hvd_mod.DistributedGradTransform(op=hvd_mod.Average, axis_name="dp")

    @partial(shard_map, mesh=mesh8, in_specs=P("dp"), out_specs=P())
    def sync(g):
        upd, _ = tx.update({"g": g}, optax.EmptyState())
        return upd["g"]

    g = jnp.arange(2.0)  # dp=2 shards: [0], [1] → mean 0.5
    out = sync(g)
    np.testing.assert_allclose(np.asarray(out), [0.5])


def test_backward_passes_per_step(hvd):
    tx = hvd.DistributedOptimizer(optax.sgd(1.0), backward_passes_per_step=2)
    params = {"w": jnp.zeros(2)}
    state = tx.init(params)
    g = {"w": jnp.ones(2)}
    u1, state = tx.update(g, state, params)
    np.testing.assert_allclose(np.asarray(u1["w"]), 0.0)  # accumulating
    u2, state = tx.update(g, state, params)
    # emits after 2 passes: mean grad = 1 → sgd(1.0) update = -1
    np.testing.assert_allclose(np.asarray(u2["w"]), -1.0)


def test_distributed_grad(hvd):
    f = lambda w, x: jnp.sum((w * x) ** 2)
    dg = hvd_mod.distributed_grad(f)
    w = jnp.ones(3)
    x = jnp.arange(3.0)
    val, g = dg(w, x)
    np.testing.assert_allclose(np.asarray(g), 2 * w * x * x, rtol=1e-6)


def test_broadcast_parameters_and_object(hvd):
    params = {"a": jnp.ones(2), "b": {"c": jnp.zeros(3)}}
    out = hvd.broadcast_parameters(params, root_rank=0)
    np.testing.assert_allclose(np.asarray(out["b"]["c"]), np.zeros(3))
    obj = hvd.broadcast_object({"x": [1, 2, 3]}, root_rank=0)
    assert obj == {"x": [1, 2, 3]}


def test_compression_roundtrip(hvd):
    x = jnp.asarray(np.random.RandomState(2).randn(16), jnp.float32)
    for comp in (hvd.Compression.none, hvd.Compression.fp16,
                 hvd.Compression.bf16):
        c, ctx = comp.compress(x)
        out = comp.decompress(c, ctx)
        assert out.dtype == x.dtype
        np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=1e-1)


def test_adasum_combine_math():
    a = jnp.asarray([1.0, 0.0])
    b = jnp.asarray([0.0, 1.0])
    # Orthogonal: dot=0 → plain sum (reference adasum.h property)
    np.testing.assert_allclose(np.asarray(adasum_combine(a, b)), [1.0, 1.0])
    # Identical: a'=(1-1/2)a+(1-1/2)a = a (idempotent on duplicates)
    np.testing.assert_allclose(np.asarray(adasum_combine(a, a)), np.asarray(a))


def test_adasum_tree_reduce():
    rng = np.random.RandomState(3)
    stacked = jnp.asarray(rng.randn(4, 8), jnp.float32)
    out = adasum_tree_reduce(stacked)
    assert out.shape == (8,)
    assert np.all(np.isfinite(np.asarray(out)))


def test_adasum_optimizer(hvd):
    tx = hvd.DistributedOptimizer(optax.sgd(0.1), op=hvd_mod.Adasum)
    params = {"w": jnp.ones(4)}
    state = tx.init(params)
    updates, _ = tx.update({"w": jnp.full(4, 2.0)}, state, params)
    np.testing.assert_allclose(np.asarray(updates["w"]), -0.2, rtol=1e-6)
