"""Unit tests for the fleet observability plane (docs/OBSERVABILITY.md
"Fleet view"): tree topology math, subtree merge semantics, push/ingest
over the exporter HTTP plane, staleness and world-mismatch rejection,
rank-0 breakdown gauges, and — the ISSUE 7 satellite — exporter +
EngineCollector behavior across an elastic ``shutdown -> init`` re-mesh
(no stale collector serving the dead engine's counters, sane port
rebinding, fleet tree re-registered).  The live 2-process scrape is
covered by test_core_multiprocess.py::test_fleet_scrape_survives_remesh.
"""

import json
import urllib.error
import urllib.request

import pytest

from horovod_tpu.metrics.engine import EngineCollector
from horovod_tpu.metrics.exporter import MetricsExporter
from horovod_tpu.metrics.fleet import (FleetAggregator, children_of,
                                       parent_of, rank_endpoint,
                                       tree_depth)
from horovod_tpu.metrics.registry import Registry


# -- topology ---------------------------------------------------------------

def test_tree_topology_complete_and_consistent():
    """Every rank except 0 has exactly one parent, every rank is its
    parent's child, and the tree covers the whole world."""
    for size in (1, 2, 3, 5, 16, 100):
        for arity in (1, 2, 4, 8):
            seen = {0}
            for r in range(1, size):
                p = parent_of(r, arity)
                assert 0 <= p < r  # parents precede children: no cycles
                assert r in children_of(p, size, arity)
                seen.add(r)
            assert seen == set(range(size))
            for r in range(size):
                assert len(children_of(r, size, arity)) <= arity


def test_tree_depth_logarithmic():
    assert tree_depth(1, 4) == 0
    assert tree_depth(5, 4) == 1
    assert tree_depth(21, 4) == 2
    assert tree_depth(1000, 4) <= 5  # O(log_4 W), not O(W)
    assert tree_depth(8, 1) == 7     # degenerate chain still terminates


def test_rank_endpoint_peer_hosts(monkeypatch):
    monkeypatch.setenv("HVD_TPU_PEER_HOSTS", "hostA,hostA,hostB,hostB")
    assert rank_endpoint(0, 9090) == ("hostA", 9090)
    assert rank_endpoint(1, 9090) == ("hostA", 9091)  # 2nd worker on A
    assert rank_endpoint(2, 9090) == ("hostB", 9090)  # 1st worker on B
    monkeypatch.delenv("HVD_TPU_PEER_HOSTS")
    assert rank_endpoint(3, 9090) == ("127.0.0.1", 9093)


def test_rank_endpoint_short_host_map_degrades(monkeypatch):
    """A PEER_HOSTS list shorter than the world must fall back to the
    no-map convention for the uncovered ranks, not raise and silently
    kill the push loop."""
    monkeypatch.setenv("HVD_TPU_PEER_HOSTS", "h0,h0")
    assert rank_endpoint(3, 9090) == ("127.0.0.1", 9093)
    monkeypatch.setenv("HVD_TPU_PEER_HOSTS", "h0,,h1")  # blank entry
    assert rank_endpoint(1, 9090) == ("127.0.0.1", 9091)
    # and the autopsy uses the SAME implementation
    from horovod_tpu.metrics.exporter import peer_endpoint
    assert peer_endpoint(7, 9090, ["h0", "h0"]) == ("127.0.0.1", 9097)


def test_cross_host_without_peer_hosts_disables_push(monkeypatch):
    """Multi-host without a rank->host map: upstream addresses cannot
    be derived — refuse to guess loopback (pushes off, subtree serving
    stays up); PEER_HOSTS re-enables the tree."""
    monkeypatch.delenv("HVD_TPU_PEER_HOSTS", raising=False)
    blind = FleetAggregator(rank=1, size=4, base_port=1,
                            registry=Registry(), push_interval=60.0,
                            cross_size=2)
    assert not blind.routable
    blind.flush()  # no connection attempt: failures stay 0
    assert blind._push_failures == 0 and blind.pushes_sent == 0
    assert blind.subtree_doc()["covers"] == [1]  # local view still works
    monkeypatch.setenv("HVD_TPU_PEER_HOSTS", "h0,h0,h1,h1")
    routed = FleetAggregator(rank=1, size=4, base_port=1,
                             registry=Registry(), push_interval=60.0,
                             cross_size=2)
    assert routed.routable
    assert FleetAggregator(rank=0, size=4, base_port=1,
                           registry=Registry(), push_interval=60.0,
                           cross_size=2).routable  # root never pushes


# -- merge / ingest ---------------------------------------------------------

def _agg(rank, size, reg=None, **kw):
    kw.setdefault("push_interval", 60.0)  # no background push in tests
    return FleetAggregator(rank=rank, size=size, base_port=9090,
                           registry=reg or Registry(), **kw)


def _child_doc(agg_child):
    return agg_child.subtree_doc()


def test_subtree_merges_counters_and_covers():
    regs = [Registry() for _ in range(3)]
    for i, reg in enumerate(regs):
        reg.counter("hvd_steps_total").inc(10 * (i + 1))
    root = _agg(0, 3, regs[0], arity=4)
    for r in (1, 2):
        assert _agg(r, 3, regs[r], arity=4).parent == 0
        assert root.ingest(_child_doc(_agg(r, 3, regs[r], arity=4)))
    doc = root.subtree_doc()
    assert doc["covers"] == [0, 1, 2]
    assert doc["snapshot"]["hvd_steps_total"]["value"] == 60
    assert set(doc["per_rank"]) == {"0", "1", "2"}


def test_ingest_rejects_other_world_and_generation():
    root = _agg(0, 2, generation=1)
    child = _agg(1, 2, generation=1)
    ok_doc = _child_doc(child)
    assert root.ingest(ok_doc)
    wrong_size = dict(ok_doc, size=3)
    wrong_gen = dict(ok_doc, generation=0)
    not_my_child = dict(ok_doc, from_rank=5)
    garbage = {"hello": "world"}
    for doc in (wrong_size, wrong_gen, not_my_child, garbage):
        assert not root.ingest(doc)
    assert root.rejected == 4


def test_stale_children_drop_out_of_the_merge():
    root = _agg(0, 2, push_interval=0.05)  # stale_after = 0.15s
    child = _agg(1, 2)
    assert root.ingest(_child_doc(child))
    assert 1 in root.subtree_doc()["covers"]
    import time
    time.sleep(0.2)
    doc = root.subtree_doc()
    assert doc["covers"] == [0]  # silence != stale data served as live
    assert doc["stale"] == [1]


def test_mismatched_histogram_bounds_degrade_to_local_view():
    """A mid-rollout worker with different bucket bounds must not take
    the whole fleet view down."""
    ra, rb = Registry(), Registry()
    ra.histogram("h", buckets=[1.0]).observe(0.5)
    rb.histogram("h", buckets=[2.0]).observe(0.5)
    root = _agg(0, 2, ra)
    assert root.ingest(_child_doc(_agg(1, 2, rb)))
    doc = root.subtree_doc()  # must not raise
    assert doc["covers"] == [0]


def test_fleet_breakdown_gauges_and_straggler():
    regs = {r: Registry() for r in range(3)}
    # per-rank windowed step time = the step-time histogram's delta
    # since the previous push (first push: everything so far)
    aggs = {r: _agg(r, 3, regs[r]) for r in range(3)}
    for r, mean in ((0, 0.01), (1, 0.01), (2, 0.05)):
        for _ in range(4):
            regs[r].histogram("hvd_step_time_seconds").observe(mean)
    root = aggs[0]
    for r in (1, 2):
        assert root.ingest(aggs[r].subtree_doc())
    snap = root.fleet_snapshot()["snapshot"]
    assert snap["hvd_fleet_size"]["value"] == 3
    assert snap["hvd_fleet_ranks_reporting"]["value"] == 3
    assert snap["hvd_fleet_straggler_rank"]["value"] == 2
    assert snap["hvd_fleet_step_time_max"]["value"] == pytest.approx(
        0.05, rel=0.01)
    assert snap["hvd_fleet_step_time_min"]["value"] == pytest.approx(
        0.01, rel=0.01)
    assert snap['hvd_fleet_rank_step_time_seconds{rank="2"}'][
        "value"] == pytest.approx(0.05, rel=0.01)
    # the synthesized gauges are view-only: they must NOT leak back
    # into the local registry (they would ride the next upstream push)
    assert "hvd_fleet_size" not in regs[0].snapshot()


def test_scrape_does_not_consume_the_push_window():
    """A dashboard polling /metrics/fleet faster than the push cadence
    must not starve the window the next push reports; and a rank with
    no new steps since its last push keeps its last window mean instead
    of vanishing from the breakdown."""
    reg = Registry()
    agg = _agg(0, 1, reg)
    for _ in range(4):
        reg.histogram("hvd_step_time_seconds").observe(0.02)
    for _ in range(5):  # scrape storm between pushes
        snap = agg.fleet_snapshot()["snapshot"]
        assert snap["hvd_fleet_step_time_mean"]["value"] == \
            pytest.approx(0.02, rel=0.01)
    # the push still sees the whole 4-step window
    doc = agg.subtree_doc(consume_window=True)
    assert doc["per_rank"]["0"]["win_step_time"] == pytest.approx(
        0.02, rel=0.01)
    # idle since that push: the breakdown carries the last closed window
    doc = agg.subtree_doc(consume_window=True)
    assert doc["per_rank"]["0"]["win_step_time"] == pytest.approx(
        0.02, rel=0.01)


# -- push over the exporter HTTP plane --------------------------------------

def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.status, r.read().decode()


def test_push_and_fleet_scrape_over_http():
    reg0, reg1 = Registry(), Registry()
    reg0.counter("hvd_steps_total").inc(3)
    reg1.counter("hvd_steps_total").inc(4)
    exp = MetricsExporter(registry=reg0, port=0)
    exp.fleet = _agg(0, 2, reg0)
    exp.start()
    try:
        child_doc = _child_doc(_agg(1, 2, reg1))
        req = urllib.request.Request(
            f"http://127.0.0.1:{exp.port}/metrics/push",
            data=json.dumps(child_doc).encode(), method="POST")
        assert urllib.request.urlopen(req, timeout=10).status == 200
        status, body = _get(exp.port, "/metrics/fleet")
        assert status == 200
        assert "hvd_steps_total 7" in body  # 3 + 4: merged, not local
        assert "hvd_fleet_ranks_reporting 2" in body
    finally:
        exp.stop()


def test_push_rejected_with_409_and_no_fleet_404():
    exp = MetricsExporter(registry=Registry(), port=0)
    exp.fleet = _agg(0, 2, generation=7)
    exp.start()
    try:
        stale = dict(_child_doc(_agg(1, 2, generation=6)))
        req = urllib.request.Request(
            f"http://127.0.0.1:{exp.port}/metrics/push",
            data=json.dumps(stale).encode(), method="POST")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=10)
        assert e.value.code == 409
    finally:
        exp.stop()
    exp2 = MetricsExporter(registry=Registry(), port=0)
    exp2.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(exp2.port, "/metrics/fleet")
        assert e.value.code == 404  # fleet disabled: explicit, not 500
    finally:
        exp2.stop()


def test_child_pushes_upstream_for_real():
    """End-to-end over loopback: a child aggregator's flush() POSTs to
    the parent exporter's real port and the parent's fleet view then
    covers both ranks."""
    reg0, reg1 = Registry(), Registry()
    reg0.counter("hvd_steps_total").inc(1)
    reg1.counter("hvd_steps_total").inc(2)
    exp = MetricsExporter(registry=reg0, port=0)
    exp.fleet = _agg(0, 2, reg0)
    exp.start()
    try:
        child = FleetAggregator(rank=1, size=2, base_port=exp.port,
                                registry=reg1, push_interval=60.0)
        # rank_endpoint(0, base) = base + rank 0 = the exporter's port
        child.flush()
        doc = exp.fleet.subtree_doc()
        assert doc["covers"] == [0, 1]
        assert doc["snapshot"]["hvd_steps_total"]["value"] == 3
        assert child.pushes_sent == 1
    finally:
        exp.stop()


def test_dead_parent_degrades_gracefully():
    child = FleetAggregator(rank=1, size=2, base_port=1,  # nothing there
                            registry=Registry(), push_interval=60.0)
    child.flush()  # must not raise
    child.flush()
    assert child.pushes_sent == 0
    assert child._push_failures == 2


# -- elastic re-mesh coverage (ISSUE 7 satellite) ---------------------------

def test_remesh_drops_stale_engine_gauges_and_rebinds():
    """Generation 1's exporter mirrors engine counters; after a
    shutdown -> init re-mesh the NEW collector must serve the NEW
    engine's counters on the SAME port, with no gauge left from the
    dead engine."""
    reg = Registry()
    gen1_counters = {"cache_hits": 8, "cycles": 100, "legacy_only": 5,
                     "autotune_fusion_bytes": 1 << 25}
    col1 = EngineCollector(lambda: dict(gen1_counters), registry=reg)
    exp1 = MetricsExporter(registry=reg, port=0,
                           collectors=[col1.collect])
    exp1.start()
    port = exp1.port
    _get(port, "/metrics")
    assert reg.snapshot()["hvd_engine_legacy_only"]["value"] == 5
    exp1.stop()

    # re-mesh: the new engine has different counters (no legacy_only);
    # init-time hygiene drops the hvd_engine_*/hvd_straggler_* mirrors
    # exactly like start_worker_exporter does
    for prefix in ("hvd_engine_", "hvd_straggler_"):
        reg.drop_prefix(prefix)
    for name in ("hvd_autotune_fusion_bytes", "hvd_autotune_cycle_ms",
                 "hvd_autotune_hierarchical",
                 "hvd_autotune_cache_enabled"):
        reg.drop_prefix(name)
    gen2_counters = {"cache_hits": 1, "cycles": 2}
    col2 = EngineCollector(lambda: dict(gen2_counters), registry=reg)
    exp2 = MetricsExporter(registry=reg, port=port,  # same port: rebind
                           collectors=[col2.collect])
    exp2.fleet = _agg(0, 2, reg, generation=1)
    exp2.start()
    try:
        assert exp2.port == port
        _, body = _get(port, "/metrics")
        assert "hvd_engine_legacy_only" not in body  # dead engine gone
        assert "hvd_engine_cache_hits 1" in body     # new engine served
        # the dead engine's autotune DECISION mirrors die with it too
        # (the new engine hasn't published them yet)
        assert "hvd_autotune_fusion_bytes" not in body
        # fleet tree re-registered for the new generation: old-world
        # pushes bounce, new-world pushes land
        assert not exp2.fleet.ingest(
            _child_doc(_agg(1, 2, generation=0)))
        assert exp2.fleet.ingest(_child_doc(_agg(1, 2, generation=1)))
        _, fleet_body = _get(port, "/metrics/fleet")
        assert "hvd_fleet_generation 1" in fleet_body
        assert "hvd_fleet_ranks_reporting 2" in fleet_body
    finally:
        exp2.stop()
    # both generations down: the port serves nothing (no leaked thread)
    with pytest.raises((OSError, urllib.error.URLError)):
        _get(port, "/healthz")


def test_exporter_stop_stops_fleet_thread():
    exp = MetricsExporter(registry=Registry(), port=0)
    agg = _agg(0, 1, push_interval=0.05)
    exp.fleet = agg.start()
    exp.start()
    exp.stop()
    assert exp.fleet is None
    assert agg._thread is None  # joined, not leaked
