"""Multi-process tests of the native core: spawn N local workers over the
TCP transport and assert collective numerics — the analog of the reference's
``horovodrun``-driven test/parallel suite run on localhost Gloo."""

import os
import socket
import subprocess
import sys

import pytest

from horovod_tpu.core import core_available

WORKER = os.path.join(os.path.dirname(__file__), "core_worker.py")
HVD_WORKER = os.path.join(os.path.dirname(__file__), "hvd_worker.py")
ERROR_WORKER = os.path.join(os.path.dirname(__file__), "error_worker.py")
XLA_WORKER = os.path.join(os.path.dirname(__file__), "xla_worker.py")
ADASUM_WORKER = os.path.join(os.path.dirname(__file__), "adasum_worker.py")
EQUIV_WORKER = os.path.join(os.path.dirname(__file__), "equiv_worker.py")
PSETS_WORKER = os.path.join(os.path.dirname(__file__), "psets_worker.py")
JIT_SYNC_WORKER = os.path.join(os.path.dirname(__file__),
                               "jit_sync_worker.py")
MATRIX_WORKER = os.path.join(os.path.dirname(__file__), "matrix_worker.py")
STALL_WORKER = os.path.join(os.path.dirname(__file__), "stall_worker.py")
TORCH_WORKER = os.path.join(os.path.dirname(__file__), "torch_worker.py")
TF_WORKER = os.path.join(os.path.dirname(__file__), "tf_worker.py")
CACHE_WORKER = os.path.join(os.path.dirname(__file__), "cache_worker.py")
METRICS_WORKER = os.path.join(os.path.dirname(__file__), "metrics_worker.py")
QUANTIZED_WORKER = os.path.join(os.path.dirname(__file__),
                                "quantized_worker.py")
CHECKPOINT_WORKER = os.path.join(os.path.dirname(__file__),
                                 "checkpoint_worker.py")
CHAOS_WORKER = os.path.join(os.path.dirname(__file__), "chaos_worker.py")
FLEET_WORKER = os.path.join(os.path.dirname(__file__), "fleet_worker.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _launch(size, extra_env=None, timeout=240, worker=WORKER,
            topology=None):
    """topology=(local_size, cross_size) fakes a multi-host layout on
    localhost (reference analog: elastic tests faking hosts)."""
    port = _free_port()
    procs = []
    for rank in range(size):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        if topology:
            lsz, csz = topology
            local_rank, cross_rank = rank % lsz, rank // lsz
            local_sz = lsz
        else:
            local_rank, cross_rank, local_sz = rank, 0, size
        env.update({
            "HOROVOD_RANK": str(rank),
            "HOROVOD_SIZE": str(size),
            "HVD_TPU_COORD_ADDR": "127.0.0.1",
            "HVD_TPU_COORD_PORT": str(port),
            "HOROVOD_LOCAL_RANK": str(local_rank),
            "HOROVOD_LOCAL_SIZE": str(local_sz),
            "HOROVOD_CROSS_RANK": str(cross_rank),
            "HOROVOD_CROSS_SIZE": str(topology[1] if topology else 1),
        })
        env.update(extra_env or {})
        procs.append(subprocess.Popen(
            [sys.executable, worker], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    outs = []
    ok = True
    for rank, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(f"--- rank {rank} (rc={p.returncode}) ---\n"
                    + out.decode())
        ok = ok and p.returncode == 0
    assert ok, "\n".join(outs)


needs_core = pytest.mark.skipif(not core_available(),
                                reason="libhvdcore.so not built")


def _xla_multiproc_supported():
    """The XLA_EAGER multiprocess tests need a real accelerator: jax's
    CPU backend cannot run multi-controller computations (its
    jax.distributed "cluster" has no cross-process collective transport
    on CPU), so on CPU-only hosts these four tests have failed since
    the seed — a known-red quartet that buried real regressions.  Skip
    them (documented, not deleted): they run wherever TPU chips exist,
    and HVD_TEST_FORCE_XLA_MULTIPROC=1 forces them anywhere."""
    import glob as _glob
    if os.environ.get("HVD_TEST_FORCE_XLA_MULTIPROC", "") not in ("", "0"):
        return True
    return bool(_glob.glob("/dev/accel*"))  # TPU-VM device nodes


needs_xla_multiproc = pytest.mark.skipif(
    not _xla_multiproc_supported(),
    reason="jax CPU backend cannot run multiprocess XLA computations "
           "(pre-existing failure since seed; needs TPU chips, or "
           "HVD_TEST_FORCE_XLA_MULTIPROC=1 to force)")


@needs_core
@pytest.mark.parametrize("size", [2, 4])
def test_core_collectives(size):
    _launch(size)


@needs_core
def test_core_with_small_fusion_threshold():
    """Force multi-buffer fusion splitting."""
    _launch(2, {"HVD_TPU_FUSION_THRESHOLD": str(512)})


@needs_core
def test_core_hostname_coordinator():
    """The coordinator address may be a hostname, not an IP literal —
    TPU-VM fleets (and the Ray/Spark integrations) hand out hostnames;
    the transport resolves them via getaddrinfo (``cpp/transport.cc``
    ``ConnectTo``)."""
    try:
        socket.getaddrinfo(socket.gethostname(), None, socket.AF_INET)
    except socket.gaierror:
        pytest.skip("hostname has no IPv4 mapping in this environment")
    _launch(2, {"HVD_TPU_COORD_ADDR": socket.gethostname()})


@needs_core
def test_core_with_timeline(tmp_path):
    tl = str(tmp_path / "timeline.json")
    _launch(2, {"HVD_TPU_TIMELINE": tl})
    import json
    with open(tl) as f:
        events = json.load(f)
    assert any(e.get("name") == "EXECUTE" for e in events if e)


@needs_core
@pytest.mark.parametrize("size", [2, 3])
def test_hvd_full_stack(size):
    """Public hvd API over the core with jax-cpu arrays."""
    # generous timeout: N jax processes compiling on this 1-core box
    _launch(size, timeout=480, worker=HVD_WORKER)


@needs_core
# size 3 (odd-world ragged blocks) is slow-marked: the tier-1 budget is
# tight and the protocol is size-agnostic; ci/run.py's parallel tier
# still runs it (no marker filter there)
@pytest.mark.parametrize("size", [2, pytest.param(3,
                                                  marks=pytest.mark.slow)])
def test_quantized_eager_allreduce(size):
    """int8-quantized eager allreduce over the TCP core: payloads move as
    int8 codes + fp32 scales (allgather-of-codes, local reduce), numerics
    match the per-rank qdq expectation, the EF-wrapped optimizer syncs in
    the eager regime, and the metrics registry reports > 3.5x compression
    for the int8 path (ISSUE 2 acceptance)."""
    _launch(size, timeout=480, worker=QUANTIZED_WORKER)


@needs_core
def test_stall_shutdown_errors_waiters():
    """HOROVOD_STALL_SHUTDOWN_TIME_SECONDS: a tensor some ranks never
    submit errors out to its waiters instead of hanging, and the domain
    stays usable (reference: stall shutdown, test/integration/test_stall)."""
    _launch(2, {"HOROVOD_STALL_CHECK_TIME_SECONDS": "1",
                "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS": "4"},
            timeout=180, worker=STALL_WORKER)


@needs_core
def test_straggler_attribution_names_slow_rank():
    """The coordinator's straggler report charges per-tensor negotiation
    wait to the LAST announcing rank: with rank 1 deliberately sleeping
    before each submission, the report must name rank 1 (tentpole
    acceptance: who-is-holding-whom-up, aggregated per rank — the
    reference only ever showed this as per-tensor timeline spans)."""
    _launch(2, {"HVD_TEST_STRAGGLER_SECS": "0.6"},
            timeout=180, worker=STALL_WORKER)


def _free_port_pair():
    """Base port with base+1 also free — worker i binds base+local_rank,
    so reserving only the base leaves rank 1's bind to luck."""
    for _ in range(50):
        base = _free_port()
        s = socket.socket()
        try:
            s.bind(("127.0.0.1", base + 1))
        except OSError:
            continue
        finally:
            s.close()
        return base
    raise RuntimeError("no adjacent free port pair found")


@needs_core
@pytest.mark.slow
def test_hang_autopsy_names_stuck_rank(tmp_path):
    """End-to-end hang autopsy (docs/OBSERVABILITY.md "Flight recorder &
    hang autopsy"): a 2-process run where rank 1 silently stops
    submitting must — without operator action — leave an autopsy
    directory with per-rank stacks, engine state naming the missing
    rank/tensor, a flight-recorder dump, peer evidence fetched over
    /debug/*, and a merged multi-rank Perfetto trace whose collective
    spans correlate across rank tracks.  Assertions live in
    stall_worker.py autopsy mode."""
    bundle = tmp_path / "autopsy"
    _launch(2, {"HVD_TEST_AUTOPSY": "1",
                "HVD_TPU_AUTOPSY_DIR": str(bundle),
                "HVD_TPU_WATCHDOG_SECONDS": "3",
                "HVD_TPU_METRICS_PORT": str(_free_port_pair()),
                "HVD_TPU_TIMELINE": str(tmp_path / "tl.json"),
                "HVD_TPU_TIMELINE_ALL_RANKS": "1",
                "HOROVOD_STALL_CHECK_TIME_SECONDS": "1"},
            timeout=120, worker=STALL_WORKER)


@needs_core
@pytest.mark.slow
def test_transport_stall_surfaces_timeout():
    """Chaos transport fault + inactivity deadline (docs/CHAOS.md): a
    fault plan makes rank 0 DROP every frame it receives from rank 1
    after frame 200 — the alive-but-wedged peer — and with
    HVD_TPU_TRANSPORT_TIMEOUT_S set both ranks must surface
    HorovodInternalError (naming the transport timeout on the rank whose
    Recv starved) within the deadline instead of hanging forever.
    Assertions live in chaos_worker.py."""
    import json
    plan = json.dumps({"faults": [
        {"seam": "transport.recv", "kind": "drop", "rank": 0, "peer": 1,
         "start": 200}]})
    _launch(2, {"HVD_TPU_FAULT_PLAN": plan,
                "HVD_TPU_TRANSPORT_TIMEOUT_S": "3"},
            timeout=180, worker=CHAOS_WORKER)


@needs_core
def test_metrics_exporter_live_scrape():
    """2-process live run with HVD_TPU_METRICS_PORT: each worker's
    ``/metrics`` serves Prometheus text with the engine cache-hit rate,
    step-time histogram buckets and throughput gauges, ``/healthz``
    reports rank identity, and the exporter goes down with shutdown."""
    _launch(2, {"HVD_TPU_METRICS_PORT": str(_free_port_pair())},
            timeout=480, worker=METRICS_WORKER)


@needs_core
@pytest.mark.slow  # tier-1 budget rule: new multiprocess tests are
#                    slow-marked; the smoke/parallel CI tiers run it
#                    unfiltered (ci/matrix.yaml)
def test_fleet_scrape_survives_remesh():
    """ISSUE 7 acceptance: a 2-process job where ONLY rank 0's
    ``/metrics/fleet`` is scraped and it observes correctly merged
    samples from every rank (counter sums, gauge aggregation, per-rank
    step-time breakdown), surviving one elastic shutdown -> init
    re-mesh (fleet tree re-registered, ports rebound sanely)."""
    _launch(2, {"HVD_TPU_METRICS_PORT": str(_free_port_pair()),
                "HVD_TPU_FLEET_PUSH_SECONDS": "0.5"},
            timeout=480, worker=FLEET_WORKER)


@needs_core
@pytest.mark.slow  # tier-1 budget rule: multiprocess tests are
#                    slow-marked; the smoke/parallel CI tiers run it
#                    unfiltered (ci/matrix.yaml)
def test_fleet_merged_goodput_two_process():
    """ISSUE 16 acceptance (fleet leg): with a 2-step ledger window,
    rank 0's ``/metrics/fleet`` carries every rank's productive goodput
    fraction plus the worst-offender pair — and rank 1, which stalls
    between its step envelopes, is the rank the merged view names
    (assertions in fleet_worker.py, HVD_TEST_GOODPUT gate)."""
    _launch(2, {"HVD_TPU_METRICS_PORT": str(_free_port_pair()),
                "HVD_TPU_FLEET_PUSH_SECONDS": "0.5",
                "HVD_TPU_GOODPUT_WINDOW": "2",
                "HVD_TEST_GOODPUT": "1"},
            timeout=480, worker=FLEET_WORKER)


@needs_core
def test_torch_adapter_multiprocess():
    """Torch drop-in at size 2: dense + sparse allreduce and
    DistributedOptimizer equivalence to full-batch single-process SGD
    (reference analog: test/parallel/test_torch.py)."""
    _launch(2, timeout=480, worker=TORCH_WORKER)


@needs_core
@pytest.mark.slow  # ~15s tf.function compile; tier-1 budget (parallel
#                    tier runs it unfiltered)
def test_tf_tape_in_tf_function():
    """DistributedGradientTape traced by tf.function at size 2: averaged
    gradients match the locally-computed cross-rank mean, None gradients
    pass through, eager == traced (reference analog: the tf.function
    tape cases of test/parallel/test_tensorflow.py)."""
    pytest.importorskip("tensorflow")
    _launch(2, timeout=480, worker=TF_WORKER)


@needs_core
def test_core_error_paths():
    """Shape mismatch and duplicate in-flight names produce clean errors and
    the core keeps working afterwards."""
    _launch(2, timeout=120, worker=ERROR_WORKER)


@needs_xla_multiproc
@pytest.mark.parametrize("size", [2, 3])
def test_xla_eager_backend(size):
    """Eager collectives over the XLA data plane (jax.distributed global
    mesh) — the SPMD analog of the NCCL path."""
    _launch(size, timeout=480, worker=XLA_WORKER,
            extra_env={"HOROVOD_TPU_OPERATIONS": "XLA_EAGER"})


@needs_core
@pytest.mark.parametrize("size", [2, 3, 4])
def test_adasum_vhdd(size):
    """C++ VHDD Adasum vs the Python binary-tree oracle (incl. the
    non-power-of-two fold path at size 3)."""
    _launch(size, timeout=240, worker=ADASUM_WORKER)


@needs_core
def test_core_with_autotune(tmp_path):
    """Autotune enabled: collectives stay correct while the coordinator's
    GP tuner runs (coordinator-only; threshold broadcast with responses);
    HOROVOD_AUTOTUNE_LOG records the sample trace."""
    log = str(tmp_path / "autotune.csv")
    _launch(2, {"HVD_TPU_AUTOTUNE": "1", "HVD_TPU_CYCLE_TIME": "0.5",
                "HOROVOD_AUTOTUNE_WINDOW_SECONDS": "0.3",
                "HVD_TEST_TRAFFIC_SECONDS": "1.5",
                "HOROVOD_AUTOTUNE_LOG": log})
    with open(log) as f:
        lines = f.read().strip().splitlines()
    assert lines[0].startswith("sample,fusion_bytes,cycle_ms")
    assert len(lines) >= 2, lines  # at least one recorded sample


@needs_core
def test_autotune_explores_categorical_knobs(tmp_path):
    """On a faked 2-host x 2-local topology the autotuner's 4-D GP space
    includes the hierarchical and cache binary dims (VERDICT r3 weak #8;
    reference: parameter_manager.h:42-105): the sample trace must show
    BOTH hierarchical settings tried — i.e. the knob actually flipped
    mid-run, atomically across ranks — while collectives stay correct."""
    log = str(tmp_path / "autotune.csv")
    _launch(4, {"HVD_TPU_AUTOTUNE": "1", "HVD_TPU_CYCLE_TIME": "0.5",
                "HOROVOD_AUTOTUNE_WINDOW_SECONDS": "0.15",
                "HOROVOD_AUTOTUNE_WARMUP_SAMPLES": "1",
                "HVD_TEST_TRAFFIC_SECONDS": "2.0",
                "HVD_TEST_AUTOTUNE_MIN_SAMPLES": "10",
                "HOROVOD_AUTOTUNE_LOG": log},
            topology=(2, 2), timeout=360)
    with open(log) as f:
        header, *rows = f.read().strip().splitlines()
    assert header == ("sample,fusion_bytes,cycle_ms,hierarchical,cache,"
                      "bytes_per_sec")
    hier_vals = {r.split(",")[3] for r in rows}
    cache_vals = {r.split(",")[4] for r in rows}
    assert hier_vals == {"0", "1"}, rows  # the two-level path was tried
    assert "1" in cache_vals, rows


@needs_core
def test_core_group_fusion_disabled():
    """HOROVOD_DISABLE_GROUP_FUSION: grouped allreduces stay numerically
    correct when groups are kept out of shared fusion units."""
    _launch(2, {"HOROVOD_DISABLE_GROUP_FUSION": "1"})


TSAN_SO = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "horovod_tpu", "core",
    "libhvdcore_tsan.so")


TSAN_ENV = {"HVD_TPU_CORE_LIB": TSAN_SO,
            "LD_PRELOAD": "/lib/x86_64-linux-gnu/libtsan.so.2",
            "TSAN_OPTIONS": "exitcode=66 halt_on_error=1"}


@pytest.mark.skipif(not os.path.exists(TSAN_SO),
                    reason="build with `make -C cpp tsan` to enable")
def test_core_under_tsan():
    """Race hunting: the full collective battery under ThreadSanitizer
    (the reference ships no TSAN coverage — SURVEY.md §5)."""
    # dlopen of a tsan-instrumented .so requires the runtime preloaded
    _launch(2, dict(TSAN_ENV), timeout=480)


@pytest.mark.skipif(not os.path.exists(TSAN_SO),
                    reason="build with `make -C cpp tsan` to enable")
def test_cache_timeline_restart_under_tsan(tmp_path):
    """The round-4 concurrency surfaces under TSAN: dynamic timeline
    stop/start (lifecycle mutex), LRU eviction under pressure, fused
    allgather."""
    tl1, tl2 = str(tmp_path / "t1.json"), str(tmp_path / "t2.json")
    _launch(2, {**TSAN_ENV, "HOROVOD_CACHE_CAPACITY": "4",
                "HVD_TPU_FUSION_THRESHOLD": "512",
                "HVD_TEST_TL1": tl1, "HVD_TEST_TL2": tl2},
            worker=CACHE_WORKER, timeout=480)


@pytest.mark.skipif(not os.path.exists(TSAN_SO),
                    reason="build with `make -C cpp tsan` to enable")
def test_autotune_hier_under_tsan(tmp_path):
    """Categorical knob flips + both hierarchical paths under TSAN on the
    faked two-level topology."""
    _launch(4, {**TSAN_ENV, "HVD_TPU_AUTOTUNE": "1",
                "HVD_TPU_CYCLE_TIME": "0.5",
                "HOROVOD_AUTOTUNE_WINDOW_SECONDS": "0.2",
                "HOROVOD_AUTOTUNE_WARMUP_SAMPLES": "1",
                "HVD_TEST_TRAFFIC_SECONDS": "1.5",
                "HVD_TEST_AUTOTUNE_MIN_SAMPLES": "6",
                "HOROVOD_AUTOTUNE_LOG": str(tmp_path / "at.csv"),
                "HOROVOD_HIERARCHICAL_ALLGATHER": "1"},
            topology=(2, 2), timeout=480)


@needs_core
@pytest.mark.parametrize("size", [2, 3])
def test_cache_eviction_and_fused_allgather(size, tmp_path):
    """LRU ResponseCache eviction + pending-bit migration under a tiny
    HOROVOD_CACHE_CAPACITY, fused-allgather displacement vs a per-tensor
    oracle under a tiny fusion threshold, and dynamic timeline restart —
    the ADVICE r3 untested-subtlety triple."""
    tl1, tl2 = str(tmp_path / "tl1.json"), str(tmp_path / "tl2.json")
    _launch(size, {"HOROVOD_CACHE_CAPACITY": "4",
                   "HVD_TPU_FUSION_THRESHOLD": "512",
                   "HVD_TEST_TL1": tl1, "HVD_TEST_TL2": tl2},
            worker=CACHE_WORKER)
    import json
    for tl in (tl1, tl2):  # both restart generations parse + have events
        with open(tl) as f:
            events = [e for e in json.load(f) if e]
        assert events, tl


@needs_core
def test_core_leveled_rank_tagged_logging():
    """HOROVOD_LOG_LEVEL gates the C++ core's logging and every line
    carries rank + timestamp in the Python logger's format (VERDICT r3
    weak #5; reference: horovod/common/logging.{h,cc})."""
    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.update({
            "HOROVOD_RANK": str(rank), "HOROVOD_SIZE": "2",
            "HVD_TPU_COORD_ADDR": "127.0.0.1",
            "HVD_TPU_COORD_PORT": str(port),
            "HOROVOD_LOCAL_RANK": str(rank), "HOROVOD_LOCAL_SIZE": "2",
            "HOROVOD_CROSS_RANK": "0", "HOROVOD_CROSS_SIZE": "1",
            "HOROVOD_LOG_LEVEL": "INFO",
        })
        procs.append(subprocess.Popen(
            [sys.executable, WORKER], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    outs = [p.communicate(timeout=240)[0].decode() for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    for rank, out in enumerate(outs):
        line = next(l for l in out.splitlines()
                    if f"[hvdcore] [rank {rank}] INFO: core init" in l)
        # timestamp prefix: "[YYYY-MM-DD HH:MM:SS.mmm]"
        assert line.startswith("[2"), line
        assert "size=2" in line and "coordinator=" in line, line
        assert any(f"[rank {rank}] INFO: core shutdown" in l
                   for l in out.splitlines()), out
    # default threshold (WARNING) silences INFO lifecycle lines
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("HOROVOD_LOG_LEVEL", None)
    port = _free_port()
    procs = []
    for rank in range(2):
        e = dict(env)
        e.update({
            "HOROVOD_RANK": str(rank), "HOROVOD_SIZE": "2",
            "HVD_TPU_COORD_ADDR": "127.0.0.1",
            "HVD_TPU_COORD_PORT": str(port),
            "HOROVOD_LOCAL_RANK": str(rank), "HOROVOD_LOCAL_SIZE": "2",
            "HOROVOD_CROSS_RANK": "0", "HOROVOD_CROSS_SIZE": "1",
        })
        procs.append(subprocess.Popen(
            [sys.executable, WORKER], env=e,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    outs = [p.communicate(timeout=240)[0].decode() for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    assert not any("INFO: core init" in o for o in outs), outs


@needs_core
def test_core_hierarchical_allreduce():
    """HOROVOD_HIERARCHICAL_ALLREDUCE over a faked 2-host x 2-local
    topology: intra-host reduce -> leader ring -> intra-host broadcast
    (reference: NCCLHierarchicalAllreduce, nccl_operations.cc:233-420)."""
    _launch(4, {"HOROVOD_HIERARCHICAL_ALLREDUCE": "1"}, topology=(2, 2))


@needs_core
def test_core_hierarchical_allgather():
    """HOROVOD_HIERARCHICAL_ALLGATHER over a faked 2-host x 2-local
    topology (reference: MPIHierarchicalAllgather, mpi_operations.cc):
    core_worker's ragged + fused allgather numerics must hold on the
    node-leader path, and the hier_allgathers counter proves the
    two-level dispatch actually ran."""
    _launch(4, {"HOROVOD_HIERARCHICAL_ALLGATHER": "1",
                "HVD_TEST_EXPECT_HIER_AG": "1"}, topology=(2, 2))


@needs_core
def test_matrix_numerics_hierarchical():
    """The full dtype x shape x op sweep with BOTH hierarchical paths on,
    over the faked two-level topology — exact numerics end to end."""
    _launch(4, timeout=480, worker=MATRIX_WORKER,
            extra_env={"HOROVOD_HIERARCHICAL_ALLREDUCE": "1",
                       "HOROVOD_HIERARCHICAL_ALLGATHER": "1",
                       "HVD_TPU_FUSION_THRESHOLD": "512"},
            topology=(2, 2))


@needs_core
@pytest.mark.parametrize("size", [2, 4])
def test_distributed_equals_serial(size):
    """DP training over the core must match single-process full-batch
    training to float tolerance (equal shards => mean-of-means == mean)."""
    _launch(size, timeout=360, worker=EQUIV_WORKER)


@needs_core
def test_concurrent_disjoint_process_sets():
    """Two disjoint process sets run collectives concurrently with
    interleaved global-set ops (reference analog:
    test/parallel/test_process_sets_*)."""
    _launch(4, worker=PSETS_WORKER, timeout=480)


@needs_core
def test_process_set_registration_skew():
    """A rank that registers a set seconds after its peers must not deadlock
    the negotiation mesh: sets stay inactive until the domain-0 coordinator
    sees every rank announce them (regression for the r2 registration race;
    reference coordinates dynamic sets through the background thread,
    operations.cc:587-623)."""
    _launch(4, worker=PSETS_WORKER, timeout=480,
            extra_env={"HVD_TEST_REG_DELAY_RANK": "3",
                       "HVD_TEST_REG_DELAY_SECS": "2.5"})


@needs_xla_multiproc
def test_process_sets_on_xla_backend():
    """Process sets over the XLA data plane: per-set sub-meshes + cached
    programs (VERDICT r1 #3; reference analog: per-set NCCL comms,
    nccl_operations.cc:65-107)."""
    _launch(4, worker=PSETS_WORKER, timeout=600,
            extra_env={"HOROVOD_TPU_OPERATIONS": "XLA_EAGER"})


@needs_core
@pytest.mark.parametrize("size", [2, 4])
def test_numerics_matrix_core(size):
    """Full dtype x shape x op x process-set sweep on the TCP core with a
    small fusion threshold so large entries cross it (the depth the
    reference invests in test/parallel/test_torch.py)."""
    _launch(size, timeout=480, worker=MATRIX_WORKER,
            extra_env={"HVD_TPU_FUSION_THRESHOLD": "512"})


@needs_xla_multiproc
def test_numerics_matrix_xla():
    """The same sweep over the XLA eager data plane."""
    _launch(2, timeout=900, worker=MATRIX_WORKER,
            extra_env={"HOROVOD_TPU_OPERATIONS": "XLA_EAGER"})


@needs_core
def test_jitted_step_with_host_sync():
    """Cross-process gradient sync INSIDE jax.jit via ordered io_callback
    (SURVEY.md §7 hard part (d)); trajectory matches serial training."""
    _launch(2, timeout=360, worker=JIT_SYNC_WORKER)


# The sharded checkpoint store needs no collectives (its commit barrier
# is the shared filesystem), so these run even without libhvdcore.
# Both are slow-marked like test_quantized_eager_allreduce[3]: the
# tier-1 budget is tight, the in-process unit battery
# (test_checkpoint_store.py) covers the same protocol, and ci/run.py's
# smoke tier registers both explicitly (no marker filter there).

@pytest.mark.slow
def test_checkpoint_sharded_reshard_roundtrip(tmp_path):
    """ISSUE 3 acceptance: a checkpoint saved at world size 2 restores
    with identical global arrays at world sizes 3 and 1; the world-3
    generation then re-saves and world 1 restores THAT (elastic
    resharding both directions)."""
    d = str(tmp_path / "ckpt")
    env = {"CKPT_DIR": d, "JAX_PLATFORMS": "cpu"}
    # 120s per launch: the workers are light (no hvd init, no core —
    # just jax import + filesystem IO; observed <10s each hot), and the
    # three sequential launches must fit the smoke tier budget together
    _launch(2, dict(env, CKPT_MODE="save"), timeout=120,
            worker=CHECKPOINT_WORKER)
    _launch(3, dict(env, CKPT_MODE="restore", CKPT_EXPECT_STEP="11",
                    CKPT_SAVED_WORLD="2", CKPT_RESAVE_STEP="13"),
            timeout=120, worker=CHECKPOINT_WORKER)
    _launch(1, dict(env, CKPT_MODE="restore", CKPT_EXPECT_STEP="13",
                    CKPT_SAVED_WORLD="3"),
            timeout=120, worker=CHECKPOINT_WORKER)


@pytest.mark.slow
def test_checkpoint_crash_mid_save(tmp_path):
    """ISSUE 3 acceptance: kill -9 of one writer mid-save (partial npz
    on disk, no completion marker) leaves the previous checkpoint
    restorable — rank 0's commit times out, step 10 survives, GC
    reclaims the wreckage.  The killed rank's -SIGKILL exit is the
    EXPECTED outcome here, so this launches by hand instead of via
    ``_launch`` (which requires rc == 0 everywhere)."""
    import signal as _signal
    d = str(tmp_path / "ckpt")
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "HOROVOD_RANK": str(rank), "HOROVOD_SIZE": "2",
            "JAX_PLATFORMS": "cpu",
            "CKPT_MODE": "crash", "CKPT_DIR": d, "CKPT_CRASH_RANK": "1",
            "HVD_TPU_CHECKPOINT_COMMIT_TIMEOUT_S": "3",
        })
        procs.append(subprocess.Popen(
            [sys.executable, CHECKPOINT_WORKER], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    outs = []
    for rank, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(f"--- rank {rank} (rc={p.returncode}) ---\n"
                    + out.decode())
    blob = "\n".join(outs)
    assert procs[0].returncode == 0, blob
    assert procs[1].returncode == -_signal.SIGKILL, blob
    # the surviving commit is readable from this (third) process too
    from horovod_tpu.checkpoint import ShardedCheckpointer
    store = ShardedCheckpointer(d, rank=0, world_size=1)
    assert store.latest_step() == 10, blob
    out = store.restore_latest()
    assert int(out["step"]) == 10
    assert not any(n.endswith(".tmp") for n in os.listdir(d)), blob
