"""Live metrics-exporter worker (launched by test_core_multiprocess.py):
hvd.init() with HVD_TPU_METRICS_PORT set, drive cached allreduces and
telemetry steps, then scrape this worker's own ``/metrics`` over HTTP —
the in-process equivalent of ``curl localhost:$HVD_TPU_METRICS_PORT/metrics``
— and assert the Prometheus text carries the engine cache-hit rate, the
step-time histogram buckets, and the throughput gauge
(docs/OBSERVABILITY.md acceptance surface)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"

import urllib.request  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu.train.callbacks import TelemetryCallback  # noqa: E402


def scrape(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=15) as r:
        return r.status, r.read().decode()


def main():
    rank = int(os.environ["HOROVOD_RANK"])
    hvd.init()
    port = int(os.environ["HVD_TPU_METRICS_PORT"]) + hvd.local_rank()

    # repeated same-name allreduces: first negotiates (cache miss), the
    # rest hit the response cache -> cache_hit_rate becomes defined
    for _ in range(6):
        hvd.allreduce(jnp.ones(8), op=hvd.Sum, name="cached")

    # train-loop telemetry feeding the same registry the exporter serves
    telemetry = TelemetryCallback(units_per_step=32, unit="examples")
    for _ in range(3):
        telemetry.on_step_begin()
        hvd.allreduce(jnp.ones(4), op=hvd.Sum, name="step_grad")
        telemetry.on_step_end()

    status, body = scrape(port, "/metrics")
    assert status == 200, (status, body)
    assert "hvd_engine_cache_hit_rate" in body, body
    assert "hvd_step_time_seconds_bucket" in body, body
    assert "hvd_examples_per_second" in body, body
    assert "hvd_steps_total 3" in body, body
    assert 'hvd_collective_calls_total{kind="allreduce"}' in body, body

    status, health = scrape(port, "/healthz")
    assert status == 200 and '"status": "ok"' in health, health
    assert f'"rank": {rank}' in health, health

    # one-call dict view must agree with the scrape surface
    snap = hvd.metrics_snapshot()
    assert snap["engine"].get("cache_hits", 0) > 0, snap["engine"]
    assert snap["derived"]["cache_hit_rate"] > 0, snap["derived"]
    assert "hvd_step_time_seconds" in snap["registry"], list(snap["registry"])
    assert "ranks" in snap["stragglers"], snap["stragglers"]

    hvd.barrier()
    hvd.shutdown()

    # after shutdown the exporter must be down (no leaked server thread)
    try:
        scrape(port, "/healthz")
        raise AssertionError("exporter still serving after shutdown")
    except (OSError, urllib.error.URLError):
        pass
    print(f"metrics worker {rank}: OK", flush=True)


if __name__ == "__main__":
    main()
