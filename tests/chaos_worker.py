"""Chaos multiprocess worker: the transport-stall hardening demo.

Scenario (docs/CHAOS.md "Reproducing a CI chaos failure"): the fault plan
arms a ``transport.recv`` DROP on rank 0 for everything rank 1 sends
after frame N — the wire-level equivalent of a peer that is alive and
connected but wedged (SIGSTOP, dead NIC queue, half-open TCP).  Before
this PR's transport inactivity deadline, rank 0's coordinator Recv would
block forever and the job hung silently; with
``HVD_TPU_TRANSPORT_TIMEOUT_S`` set, the blocked Recv errors out, the
engine finalizes every waiter, and BOTH ranks surface
``HorovodInternalError`` (the elastic reset trigger) within the deadline.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from horovod_tpu.core.core_backend import CoreBackend  # noqa: E402
from horovod_tpu.ops.reduce_op import ReduceOp  # noqa: E402


def main():
    timeout_s = float(os.environ["HVD_TPU_TRANSPORT_TIMEOUT_S"])
    be = CoreBackend()
    rank = be.rank

    # healthy phase: the drop window starts at frame 200, far past this
    out = be.allreduce_async("warm", np.ones(4, np.float32),
                             ReduceOp.SUM).wait(60)
    np.testing.assert_allclose(out, 2.0)

    # idle cycles stream frames at ~1ms cadence; ride past the window
    # start so the next collective hits a fully-armed drop
    time.sleep(1.0)

    t0 = time.monotonic()
    h = be.allreduce_async("stalled", np.ones(4, np.float32), ReduceOp.SUM)
    from horovod_tpu.elastic import HorovodInternalError
    try:
        h.wait(10 * timeout_s)
        raise AssertionError("expected the stalled collective to error")
    except HorovodInternalError as e:
        elapsed = time.monotonic() - t0
        # the deadline, not the 10x wait budget, must have fired; slack
        # covers a loaded box, not another timeout
        assert elapsed < 4 * timeout_s, (elapsed, timeout_s)
        msg = str(e)
        if rank == 0:
            # rank 0's Recv hit the deadline directly: the error must
            # name the real cause, not a generic abort
            assert "transport timeout" in msg, msg
            c = be.counters()
            assert c.get("transport_chaos_injected", 0) > 0, c

    print(f"chaos worker {rank}: OK", flush=True)
    # rank 1's engine died from the coordinator vanishing; negotiated
    # shutdown consensus can't complete — exit hard like the autopsy demo
    os._exit(0)


if __name__ == "__main__":
    main()
