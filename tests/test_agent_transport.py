"""Unit tests for the agent-transport exec edge cases
(runner/elastic/agent.py): the paths that prevent a dead executor from
hanging a generation, which the end-to-end Spark/Ray tests only reach
when something actually dies."""

import json
import threading
import time

import pytest

from horovod_tpu.runner.elastic.agent import (AgentRegistryDiscovery,
                                              make_agent_exec,
                                              resolve_kv_addr)


class FakeKV:
    def __init__(self):
        self._d = {}

    def put(self, scope, key, value):
        self._d.setdefault(scope, {})[key] = value

    def get(self, scope, key):
        return self._d.get(scope, {}).get(key)

    def scope(self, scope):
        return dict(self._d.get(scope, {}))


class Slot:
    def __init__(self, hostname="h1", local_rank=0, rank=0):
        self.hostname = hostname
        self.local_rank = local_rank
        self.rank = rank


def _register(kv, agent_id, host, ts=None):
    kv.put("agents", agent_id, json.dumps(
        {"host": host, "ts": ts if ts is not None else time.time()}
    ).encode())


def test_exec_fails_fast_when_no_agent_for_slot():
    kv = FakeKV()
    disc = AgentRegistryDiscovery(kv)
    _exec = make_agent_exec(kv, disc, b"s" * 16)
    # no agents at all, and fewer agents than the slot's local_rank
    assert _exec(Slot(), ["cmd"], {}, []) == 1
    _register(kv, "h1@0", "h1")
    assert _exec(Slot(local_rank=1), ["cmd"], {}, []) == 1


def test_exec_gives_up_and_retires_cmd_when_agent_dies():
    """A dead agent never posts rc: once its heartbeat goes stale the
    exec returns failure AND blanks the command doc, so a respawned
    same-id agent cannot execute the dead generation's command."""
    kv = FakeKV()
    disc = AgentRegistryDiscovery(kv)
    _exec = make_agent_exec(kv, disc, b"s" * 16)
    _register(kv, "h1@0", "h1")
    rc = [None]

    def run():
        rc[0] = _exec(Slot(), ["worker"], {"HOROVOD_RANK": "0"}, [])

    t = threading.Thread(target=run, daemon=True)
    t.start()
    time.sleep(0.3)
    assert t.is_alive()  # waiting on rc while the agent looks healthy
    assert kv.get("cmd", "h1@0")  # the signed doc was posted
    # the "executor dies": heartbeat goes stale
    _register(kv, "h1@0", "h1", ts=time.time() - 1e6)
    t.join(timeout=10)
    assert not t.is_alive() and rc[0] == 1
    assert kv.get("cmd", "h1@0") == b""  # retired, not replayable


@pytest.mark.slow  # ~30s: deliberately waits out the kill deadline;
#                    tier-1 budget (integration tier runs it unfiltered)
def test_exec_kill_deadline_bounds_teardown_wait():
    """After a teardown kill, an agent that never acks is abandoned at
    the kill deadline instead of blocking the generation restart."""
    kv = FakeKV()
    disc = AgentRegistryDiscovery(kv)
    _exec = make_agent_exec(kv, disc, b"s" * 16)
    _register(kv, "h1@0", "h1")
    stopper = threading.Event()
    keepalive = threading.Thread(
        target=lambda: [(_register(kv, "h1@0", "h1"), time.sleep(0.5))
                        for _ in iter(lambda: not stopper.is_set(), False)],
        daemon=True)
    keepalive.start()
    ev = threading.Event()
    ev.set()  # failure already signalled -> kill path immediately
    try:
        start = time.time()
        rc = _exec(Slot(), ["worker"], {}, [ev])
        took = time.time() - start
    finally:
        stopper.set()
    assert rc == 1
    assert kv.scope("kill")  # the kill was posted (under the op's uuid)
    assert took < 60  # bounded by 3 * STALE_S, not forever


def test_resolve_kv_addr_loopback():
    import socket
    assert resolve_kv_addr(socket.gethostname()) == "127.0.0.1"
    assert resolve_kv_addr("elsewhere.example") == "elsewhere.example"
