"""Unit tests for the diagnostics subsystem: flight-recorder ring
semantics, span determinism, shard merging under skewed clocks, watchdog
arming/triggering, stall metrics, and the log-span join."""

import json
import logging
import os
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from horovod_tpu.diagnostics.flight_recorder import FlightRecorder  # noqa: E402
from horovod_tpu.diagnostics import spans  # noqa: E402
from horovod_tpu.diagnostics.merge import (load_shard,  # noqa: E402
                                           merge_directory, merge_shards)
from horovod_tpu.diagnostics.watchdog import Watchdog  # noqa: E402


# -- flight recorder ---------------------------------------------------------

def test_flight_recorder_bounded_drop_oldest():
    fr = FlightRecorder(capacity=8)
    for i in range(20):
        fr.record("ev", i=i)
    assert len(fr) == 8
    assert fr.dropped == 12
    events = fr.events()
    assert [e["i"] for e in events] == list(range(12, 20))  # oldest gone
    doc = fr.dump()
    assert doc["capacity"] == 8
    assert doc["dropped"] == 12
    assert doc["recorded"] == 8


def test_flight_recorder_thread_safe():
    fr = FlightRecorder(capacity=128)
    n_threads, per_thread = 8, 500

    def pump(t):
        for i in range(per_thread):
            fr.record("t", thread=t, i=i)

    threads = [threading.Thread(target=pump, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(fr) == 128
    assert fr.dropped == n_threads * per_thread - 128
    # seq is strictly increasing in the retained tail
    seqs = [e["seq"] for e in fr.events()]
    assert seqs == sorted(seqs)
    assert seqs[-1] == n_threads * per_thread


def test_flight_recorder_dump_to(tmp_path):
    fr = FlightRecorder(capacity=4)
    fr.record("x", a=1)
    path = str(tmp_path / "flight.json")
    fr.dump_to(path)
    doc = json.load(open(path))
    assert doc["events"][0]["kind"] == "x"


def test_record_event_never_raises():
    from horovod_tpu.diagnostics.flight_recorder import record_event
    record_event("ok", weird=object())  # non-serializable is fine in-ring


# -- spans -------------------------------------------------------------------

def test_span_ids_deterministic_per_name():
    spans.reset()
    assert spans.next_span("grads") == "grads#1"
    assert spans.next_span("grads") == "grads#2"
    assert spans.next_span("other") == "other#1"
    spans.reset()
    assert spans.next_span("grads") == "grads#1"  # what a peer computes


def test_active_span_is_thread_local():
    spans.reset()
    seen = {}
    with spans.active_span("a#1"):
        assert spans.current_span() == "a#1"

        def other():
            seen["other"] = spans.current_span()

        t = threading.Thread(target=other)
        t.start()
        t.join()
    assert seen["other"] is None
    assert spans.current_span() is None


def test_log_lines_carry_active_span(capsys):
    from horovod_tpu.common.logging import get_logger, reset_logger
    reset_logger()
    logger = get_logger()
    logger.setLevel(logging.WARNING)
    with spans.active_span("grads#7"):
        logger.warning("inside")
    logger.warning("outside")
    err = capsys.readouterr().err
    inside = [ln for ln in err.splitlines() if "inside" in ln][0]
    outside = [ln for ln in err.splitlines() if "outside" in ln][0]
    assert "[span grads#7]" in inside
    assert "[span" not in outside
    reset_logger()


# -- shard merging -----------------------------------------------------------

def _shard(path, rank, epoch_s, offset_s, events):
    """Write a synthetic host shard: meta anchored at shard ts=0."""
    doc = [{"ph": "i", "name": "SHARD_META", "pid": rank, "tid": "meta",
            "ts": 0.0, "s": "g",
            "args": {"epoch_us": epoch_s * 1e6, "rank": rank,
                     "source": "host",
                     "wall_offset_us": offset_s * 1e6}}]
    doc.extend(events)
    with open(path, "w") as f:
        json.dump(doc, f)
    return str(path)


def test_merge_aligns_skewed_clocks(tmp_path):
    # rank 1's wall clock runs 100s AHEAD of rank 0's; both ranks saw
    # the same collective at the same TRUE time (1s after their shard
    # start, shards started simultaneously in coordinator time)
    ev0 = [{"ph": "B", "name": "ALLREDUCE", "cat": "collective",
            "tid": "grads", "ts": 1e6, "args": {"span": "grads#1"}}]
    ev1 = [{"ph": "B", "name": "ALLREDUCE", "cat": "collective",
            "tid": "grads", "ts": 1e6, "args": {"span": "grads#1"}}]
    p0 = _shard(tmp_path / "t.rank0.json", 0, 1000.0, 0.0, ev0)
    p1 = _shard(tmp_path / "t.rank1.json", 1, 1100.0, 100.0, ev1)
    doc = merge_shards([p0, p1])
    bs = [e for e in doc["traceEvents"] if e.get("ph") == "B"]
    assert len(bs) == 2
    # aligned: identical coordinator-time timestamps, distinct tracks
    assert abs(bs[0]["ts"] - bs[1]["ts"]) < 1.0, bs
    assert {b["pid"] for b in bs} == {0, 1}
    names = {e["args"]["name"] for e in doc["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert names == {"rank 0", "rank 1"}


def test_merge_without_offset_shows_skew(tmp_path):
    # control: zero recorded offset leaves the 100s skew visible
    ev = [{"ph": "B", "name": "A", "cat": "c", "tid": "x", "ts": 0.0}]
    p0 = _shard(tmp_path / "t.rank0.json", 0, 1000.0, 0.0, list(ev))
    p1 = _shard(tmp_path / "t.rank1.json", 1, 1100.0, 0.0, list(ev))
    doc = merge_shards([p0, p1])
    bs = sorted((e for e in doc["traceEvents"] if e.get("ph") == "B"),
                key=lambda e: e["pid"])
    assert abs(bs[1]["ts"] - bs[0]["ts"]) > 99e6


def test_merge_skips_unreadable_shard(tmp_path):
    """A rank that died with an empty/garbled shard must not cost the
    other ranks' evidence."""
    ev = [{"ph": "B", "name": "A", "cat": "c", "tid": "x", "ts": 0.0}]
    good = _shard(tmp_path / "t.rank0.json", 0, 10.0, 0.0, ev)
    bad = tmp_path / "t.rank1.json"
    bad.write_text("")  # crash right after open
    doc = merge_shards([good, str(bad)])
    assert any(e.get("ph") == "B" for e in doc["traceEvents"])


def test_merge_repairs_truncated_shard(tmp_path):
    # a crash-cut shard: unterminated array, partial trailing object
    path = tmp_path / "t.rank0.json"
    path.write_text('[\n{"ph": "B", "name": "A", "cat": "c", "tid": "x",'
                    ' "ts": 5.0},\n{"ph": "E", "na')
    events = load_shard(str(path))
    assert len(events) == 1
    assert events[0]["name"] == "A"


def test_merge_directory_and_cli(tmp_path):
    ev = [{"ph": "B", "name": "A", "cat": "c", "tid": "x", "ts": 0.0}]
    _shard(tmp_path / "timeline.rank0.json", 0, 10.0, 0.0, list(ev))
    _shard(tmp_path / "timeline.rank1.json", 1, 10.0, 0.0, list(ev))
    out = merge_directory(str(tmp_path))
    assert out and out.endswith("merged_trace.json")
    doc = json.load(open(out))
    assert len({e["pid"] for e in doc["traceEvents"]}) >= 2
    # the CLI drives the same path
    from horovod_tpu.diagnostics.__main__ import main
    out2 = str(tmp_path / "cli_merged.json")
    assert main(["merge", "--dir", str(tmp_path), "-o", out2]) == 0
    assert json.load(open(out2))["traceEvents"]


def test_timeline_shard_roundtrip(tmp_path):
    """A real Timeline shard (any rank) merges with correlated spans."""
    from horovod_tpu.common.timeline import Timeline
    paths = []
    for rank in (0, 1):
        tl = Timeline(rank)
        path = str(tmp_path / f"timeline.rank{rank}.json")
        tl.start_shard(path, wall_offset_s=0.0)
        assert tl.enabled
        tl.collective_begin("grads", "allreduce", "grads#1")
        tl.collective_end("grads", "grads#1")
        tl.stop()
        paths.append(path)
    doc = merge_shards(paths, str(tmp_path / "merged.json"))
    spans_seen = {}
    for ev in doc["traceEvents"]:
        span = (ev.get("args") or {}).get("span")
        if ev.get("ph") == "B" and span:
            spans_seen.setdefault(span, set()).add(ev["pid"])
    assert spans_seen.get("grads#1") == {0, 1}


# -- watchdog ----------------------------------------------------------------

def test_watchdog_no_false_positive_during_healthy_loop():
    fired = []
    wd = Watchdog(timeout_s=0.6, on_trigger=fired.append,
                  check_interval_s=0.05).start()
    try:
        t_end = time.monotonic() + 1.8
        step = 0
        while time.monotonic() < t_end:
            time.sleep(0.1)
            step += 1
            wd.notify_progress(step)
        assert fired == []
        assert wd.trigger_count == 0
    finally:
        wd.stop()


def test_watchdog_triggers_once_on_stall():
    fired = []
    wd = Watchdog(timeout_s=0.3, on_trigger=fired.append,
                  check_interval_s=0.05).start()
    try:
        time.sleep(1.2)  # several timeout periods with zero progress
        assert wd.trigger_count == 1, fired  # one bundle per stall
        assert "no step progress" in fired[0]
    finally:
        wd.stop()


def test_watchdog_disarmed_by_zero_timeout():
    wd = Watchdog(timeout_s=0)
    wd.start()
    assert not wd.armed


def test_watchdog_env_default(monkeypatch):
    monkeypatch.delenv("HVD_TPU_WATCHDOG_SECONDS", raising=False)
    monkeypatch.delenv("HOROVOD_WATCHDOG_SECONDS", raising=False)
    assert Watchdog().timeout_s == 600.0
    monkeypatch.setenv("HVD_TPU_WATCHDOG_SECONDS", "42.5")
    assert Watchdog().timeout_s == 42.5


def test_write_autopsy_degrades_without_init(tmp_path):
    """Uninitialized process: stacks + flight + summary still land."""
    from horovod_tpu.diagnostics.autopsy import write_autopsy
    from horovod_tpu.diagnostics.flight_recorder import record_event
    record_event("unit_test_marker")
    bundle = write_autopsy(str(tmp_path / "bundle"), reason="unit test",
                           fetch_peers=False)
    files = os.listdir(bundle)
    assert any(f.startswith("stacks_rank") for f in files), files
    assert any(f.startswith("flight_rank") for f in files), files
    flight = json.load(open(os.path.join(
        bundle, [f for f in files if f.startswith("flight_rank")][0])))
    assert any(e["kind"] == "unit_test_marker" for e in flight["events"])
    summary = json.load(open(os.path.join(
        bundle, [f for f in files if f.startswith("summary_rank")][0])))
    assert summary["reason"] == "unit test"


def test_telemetry_callback_arms_watchdog(monkeypatch):
    from horovod_tpu.common.basics import _state
    from horovod_tpu.diagnostics import watchdog as wd_mod
    monkeypatch.setenv("HVD_TPU_WATCHDOG_SECONDS", "120")
    # arming requires an initialized world (uninitialized processes must
    # never leak a 600s daemon into a long test run — see below)
    monkeypatch.setattr(_state, "initialized", True)
    wd_mod.reset()
    try:
        from horovod_tpu.train.callbacks import TelemetryCallback
        cb = TelemetryCallback()
        assert cb.watchdog is not None and cb.watchdog.armed
        before = cb.watchdog._last_progress
        cb.on_step_begin()
        cb.on_step_end()
        assert cb.watchdog._last_progress >= before
    finally:
        wd_mod.reset()


def test_telemetry_callback_does_not_arm_uninitialized():
    """Without hvd.init there is no world to autopsy: the callback must
    NOT leave an armed watchdog behind (zero autopsies across the
    healthy unit suite)."""
    import horovod_tpu as hvd
    from horovod_tpu.diagnostics import watchdog as wd_mod
    if hvd.is_initialized():
        pytest.skip("another test left hvd initialized")
    wd_mod.reset()
    from horovod_tpu.train.callbacks import TelemetryCallback
    cb = TelemetryCallback()
    assert cb.watchdog is None
    assert wd_mod._WATCHDOG is None


def test_telemetry_on_train_end_stands_watchdog_down(monkeypatch):
    """After training, a long eval/export with no steps is legitimate:
    on_train_end suspends the watchdog instead of letting it fire."""
    from horovod_tpu.common.basics import _state
    from horovod_tpu.diagnostics import watchdog as wd_mod
    monkeypatch.setenv("HVD_TPU_WATCHDOG_SECONDS", "120")
    monkeypatch.setattr(_state, "initialized", True)
    wd_mod.reset()
    try:
        from horovod_tpu.train.callbacks import TelemetryCallback
        cb = TelemetryCallback()
        assert cb.watchdog.armed
        cb.on_train_end()
        assert not cb.watchdog.armed
    finally:
        wd_mod.reset()


def test_watchdog_suspend_resume_cycle():
    """hvd.shutdown suspends (remembers armed), hvd.init resumes — an
    elastic re-mesh must not silently disarm hang detection."""
    from horovod_tpu.diagnostics import watchdog as wd_mod
    wd_mod.reset()
    try:
        os.environ["HVD_TPU_WATCHDOG_SECONDS"] = "120"
        wd = wd_mod.ensure_watchdog()
        assert wd is not None and wd.armed
        wd_mod.suspend()
        assert not wd.armed
        wd_mod.resume()
        assert wd.armed
        wd_mod.notify_progress(7)  # still wired to the same instance
        assert wd._last_step == 7
    finally:
        os.environ.pop("HVD_TPU_WATCHDOG_SECONDS", None)
        wd_mod.reset()


# -- stall metrics mapping ---------------------------------------------------

def test_engine_collector_surfaces_stall_metrics():
    from horovod_tpu.metrics.engine import EngineCollector
    from horovod_tpu.metrics.registry import Registry
    reg = Registry()
    counters = {"cycles": 10, "stall_warnings": 0, "stalled_tensors": 0}
    col = EngineCollector(lambda: counters, registry=reg)
    col.collect()
    snap = reg.snapshot()
    assert snap["hvd_stall_warnings_total"]["value"] == 0
    counters.update(stall_warnings=3, stalled_tensors=2)
    col.collect()
    snap = reg.snapshot()
    assert snap["hvd_stall_warnings_total"]["value"] == 3
    assert snap["hvd_stalled_tensors"]["value"] == 2
    # counter semantics: a re-collect with the same totals adds nothing
    col.collect()
    assert reg.snapshot()["hvd_stall_warnings_total"]["value"] == 3
    # an elastic re-mesh resets the C++ counters: the new engine's
    # warnings must still land (delta < 0 ⇒ whole new total is new)
    counters.update(stall_warnings=2)
    col.collect()
    assert reg.snapshot()["hvd_stall_warnings_total"]["value"] == 5


# -- engine state API (single-process degradations) --------------------------

def test_engine_state_requires_init():
    import horovod_tpu as hvd
    from horovod_tpu.common.basics import NotInitializedError
    if hvd.is_initialized():
        pytest.skip("another test left hvd initialized")
    with pytest.raises(NotInitializedError):
        hvd.engine_state()


def test_suspects_from_engine_orders_by_wait():
    from horovod_tpu.diagnostics.autopsy import suspects_from_engine
    engine = {"engine_state": {"domains": [{"id": 0, "pending": [
        {"name": "a", "waited_s": 1.0, "ready_ranks": [0],
         "missing_ranks": [1]},
        {"name": "b", "waited_s": 9.0, "ready_ranks": [0, 2],
         "missing_ranks": [3]},
    ]}]}}
    sus = suspects_from_engine(engine)
    assert [s["tensor"] for s in sus] == ["b", "a"]
    assert sus[0]["missing_ranks"] == [3]
