"""End-to-end test of ``horovod_tpu.spark.run`` over a fake barrier-mode
Spark cluster (reference analog: ``test/integration/test_spark.py``
``test_happy_run`` against local-mode Spark).

pyspark is not in this image, so ``tests/fake_pyspark`` provides the exact
barrier-scheduling surface ``spark.run`` touches, with every task running
in its own subprocess (like a Spark executor) and the task function
shipped via cloudpickle. The distributed part is REAL: each task calls
``hvd.init()`` and the collectives run over the native TCP core between
the task processes.
"""

import os
import sys

import pytest

from horovod_tpu.core import core_available

FAKE_PYSPARK = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "fake_pyspark")

needs_core = pytest.mark.skipif(not core_available(),
                                reason="libhvdcore.so not built")


@pytest.fixture
def fake_pyspark(monkeypatch):
    monkeypatch.syspath_prepend(FAKE_PYSPARK)
    # the parent process may have a cached import failure for pyspark
    for mod in [m for m in sys.modules if m.split(".")[0] == "pyspark"]:
        monkeypatch.delitem(sys.modules, mod, raising=False)
    yield
    for mod in [m for m in sys.modules if m.split(".")[0] == "pyspark"]:
        sys.modules.pop(mod, None)


@needs_core
def test_spark_run_end_to_end(fake_pyspark):
    import horovod_tpu.spark as spark

    # a closure, not a module-level function: cloudpickle ships it by
    # value, exactly as a user-defined train fn travels from a Spark
    # driver notebook to the executors
    def allreduce_fn(scale):
        import jax.numpy as jnp
        import numpy as np
        import horovod_tpu as hvd

        out = hvd.allreduce(jnp.ones(4) * (hvd.rank() + 1) * scale,
                            op=hvd.Sum, name="spark_x")
        return {"rank": hvd.rank(), "size": hvd.size(),
                "sum": np.asarray(out).tolist()}

    results = spark.run(allreduce_fn, args=(2.0,), num_proc=2)

    assert len(results) == 2
    for rank, res in enumerate(results):
        assert res["rank"] == rank
        assert res["size"] == 2
        # sum over ranks of (rank+1)*2 = 2 + 4 = 6 per element
        assert res["sum"] == [6.0, 6.0, 6.0, 6.0]


@needs_core
def test_spark_run_env_passthrough(fake_pyspark):
    import horovod_tpu.spark as spark

    def fn():
        import os
        import horovod_tpu as hvd
        return (hvd.rank(), os.environ.get("HVD_SPARK_TEST_KNOB"))

    results = spark.run(fn, num_proc=2, env={"HVD_SPARK_TEST_KNOB": "42"})
    assert sorted(results) == [(0, "42"), (1, "42")]


def test_spark_run_requires_pyspark():
    """Without pyspark importable, run() raises the documented ImportError."""
    import horovod_tpu.spark as spark
    for mod in [m for m in sys.modules if m.split(".")[0] == "pyspark"]:
        sys.modules.pop(mod, None)
    if any(os.path.isdir(os.path.join(p, "pyspark")) for p in sys.path):
        pytest.skip("real or fake pyspark importable in this environment")
    with pytest.raises(ImportError, match="pyspark"):
        spark.run(lambda: None, num_proc=1)


@needs_core
def test_spark_run_elastic_recovers_from_worker_crash(fake_pyspark,
                                                      tmp_path):
    """run_elastic over fake Spark tasks acting as host agents: rank 1
    crashes in generation 0, the ElasticDriver restarts the generation on
    the same agents, and the retry completes with correct collectives
    (reference: ``horovod.spark.run_elastic``, ``spark/runner.py:309``)."""
    import horovod_tpu.spark as spark

    marker = str(tmp_path / "crashed_once")

    def train():
        import os
        import numpy as np
        import horovod_tpu as hvd

        hvd.init()
        if hvd.rank() == 1 and not os.path.exists(marker):
            open(marker, "w").close()
            os._exit(17)  # hard crash mid-job, pre-collective
        out = hvd.allreduce(np.ones(2) * (hvd.rank() + 1), op=hvd.Sum,
                            name="el")
        hvd.shutdown()
        return float(np.asarray(out)[0])

    results = spark.run_elastic(train, num_proc=2, min_np=2, max_np=2)
    assert os.path.exists(marker)  # the crash really happened
    assert results == [3.0, 3.0]
