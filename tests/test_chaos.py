"""Chaos harness battery: fault-plan parsing/validation, seeded
determinism, Python seam behavior, transport-spec compilation, and the
(slow) compound-fault soak under the elastic driver."""

import json
import os
import stat
import subprocess
import sys
import textwrap
import time

import pytest

from horovod_tpu import chaos
from horovod_tpu.chaos.plan import (FaultPlanError, compile_transport_spec,
                                    parse_plan)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_chaos(monkeypatch):
    monkeypatch.delenv("HVD_TPU_FAULT_PLAN", raising=False)
    monkeypatch.delenv("HVD_TPU_FAULT_SEED", raising=False)
    monkeypatch.delenv("HVD_TPU_CHAOS_TRANSPORT", raising=False)
    chaos.uninstall()
    yield
    chaos.uninstall()


# -- plan parsing / validation ---------------------------------------------

def test_parse_minimal_plan():
    p = parse_plan('{"faults": [{"seam": "kv.request", "kind": "error"}]}')
    assert p.seed == 0
    assert len(p.rules) == 1
    assert p.rules[0].matches_rank(0) and p.rules[0].matches_rank(7)


def test_bad_seam_name_rejected():
    with pytest.raises(FaultPlanError, match="unknown seam"):
        parse_plan('{"faults": [{"seam": "kv.reqest", "kind": "error"}]}')


def test_bad_kind_rejected():
    with pytest.raises(FaultPlanError, match="not valid for seam"):
        parse_plan('{"faults": [{"seam": "kv.request", "kind": "kill"}]}')


def test_unknown_keys_rejected():
    with pytest.raises(FaultPlanError, match="unknown keys"):
        parse_plan('{"faults": [{"seam": "step", "kind": "kill", '
                   '"when": 3}]}')
    with pytest.raises(FaultPlanError, match="unknown plan keys"):
        parse_plan('{"faults": [], "fualts": []}')


def test_malformed_json_rejected():
    with pytest.raises(FaultPlanError, match="not valid JSON"):
        parse_plan('{"faults": [')


def test_empty_or_negative_window_rejected():
    with pytest.raises(FaultPlanError, match="empty or negative"):
        parse_plan('{"faults": [{"seam": "step", "kind": "stall", '
                   '"start": 5, "stop": 5}]}')
    with pytest.raises(FaultPlanError, match="empty or negative"):
        parse_plan('{"faults": [{"seam": "step", "kind": "stall", '
                   '"start": -1}]}')


def test_zero_duration_delay_rejected():
    # a 0ms delay would count as injected while exercising nothing
    for doc in (
        {"seam": "kv.request", "kind": "delay"},
        {"seam": "checkpoint.write", "kind": "slow_fsync"},
        {"seam": "step", "kind": "stall"},
    ):
        with pytest.raises(FaultPlanError, match="> 0"):
            parse_plan(json.dumps({"faults": [doc]}))


def test_marker_on_transport_seam_rejected():
    with pytest.raises(FaultPlanError, match="marker"):
        parse_plan(json.dumps({"faults": [
            {"seam": "transport.recv", "kind": "drop",
             "marker": "/tmp/x"}]}))


def test_bad_probability_rejected():
    for p in (0.0, -0.5, 1.5):
        with pytest.raises(FaultPlanError, match="probability"):
            parse_plan(json.dumps({"faults": [
                {"seam": "kv.request", "kind": "error",
                 "probability": p}]}))


def test_overlapping_windows_rejected():
    doc = {"faults": [
        {"seam": "kv.request", "kind": "blackout", "start": 0, "stop": 10},
        {"seam": "kv.request", "kind": "blackout", "start": 5, "stop": 15},
    ]}
    with pytest.raises(FaultPlanError, match="overlapping windows"):
        parse_plan(json.dumps(doc))


def test_non_overlapping_variants_accepted():
    # disjoint windows: fine
    parse_plan(json.dumps({"faults": [
        {"seam": "kv.request", "kind": "blackout", "start": 0, "stop": 5},
        {"seam": "kv.request", "kind": "blackout", "start": 5, "stop": 9},
    ]}))
    # same window, different kinds: fine
    parse_plan(json.dumps({"faults": [
        {"seam": "kv.request", "kind": "blackout", "start": 0, "stop": 5},
        {"seam": "kv.request", "kind": "delay", "start": 0, "stop": 5,
         "delay_ms": 1},
    ]}))
    # same window+kind, disjoint ranks: fine
    parse_plan(json.dumps({"faults": [
        {"seam": "step", "kind": "kill", "rank": 0, "start": 3},
        {"seam": "step", "kind": "kill", "rank": [1, 2], "start": 3},
    ]}))
    # same window+kind, distinct transport peers: fine
    parse_plan(json.dumps({"faults": [
        {"seam": "transport.recv", "kind": "delay", "peer": 0,
         "delay_ms": 1},
        {"seam": "transport.recv", "kind": "delay", "peer": 1,
         "delay_ms": 1},
    ]}))


def test_rank_scoping():
    p = parse_plan(json.dumps({"faults": [
        {"seam": "step", "kind": "stall", "rank": [1, 3],
         "stall_s": 0.001}]}))
    r = p.rules[0]
    assert not r.matches_rank(0) and r.matches_rank(1) \
        and r.matches_rank(3)
    assert p.rules_for("step", 0) == []
    assert len(p.rules_for("step", 3)) == 1


def test_seeded_determinism_same_schedule():
    """Same plan + seed => identical fire schedule; different seed =>
    (almost surely) different."""
    doc = json.dumps({"seed": 11, "faults": [
        {"seam": "kv.request", "kind": "error", "probability": 0.4,
         "start": 0, "stop": 400}]})

    def schedule(raw, seed=None):
        p = parse_plan(raw, seed_override=seed)
        r = p.rules[0]
        return [i for i in range(400) if r.decides_fire(p.seed, i)]

    a, b = schedule(doc), schedule(doc)
    assert a == b
    assert 60 < len(a) < 300  # probability actually thins the schedule
    c = schedule(doc, seed=12)
    assert c != a


def test_file_and_seed_env_loading(tmp_path, monkeypatch):
    plan = {"seed": 3, "faults": [
        {"seam": "kv.request", "kind": "delay", "delay_ms": 1}]}
    f = tmp_path / "plan.json"
    f.write_text(json.dumps(plan))
    monkeypatch.setenv("HVD_TPU_FAULT_PLAN", str(f))
    monkeypatch.setenv("HVD_TPU_FAULT_SEED", "99")
    eng = chaos.install(rank=0)
    assert eng is not None and eng.plan.seed == 99
    monkeypatch.setenv("HVD_TPU_FAULT_SEED", "notanint")
    with pytest.raises(FaultPlanError, match="FAULT_SEED"):
        chaos.install(rank=0)
    monkeypatch.setenv("HVD_TPU_FAULT_SEED", "")
    monkeypatch.setenv("HVD_TPU_FAULT_PLAN", str(tmp_path / "missing.json"))
    with pytest.raises(FaultPlanError, match="unreadable"):
        chaos.install(rank=0)


# -- runtime seams ----------------------------------------------------------

def test_no_plan_means_dead_seams(monkeypatch):
    assert chaos.install() is None
    assert not chaos.active()
    assert chaos.fire("kv.request") == ()
    assert chaos.step_tick(5) == ()
    assert "HVD_TPU_CHAOS_TRANSPORT" not in os.environ


def test_error_kinds_raise(monkeypatch):
    monkeypatch.setenv("HVD_TPU_FAULT_PLAN", json.dumps({"faults": [
        {"seam": "kv.request", "kind": "blackout", "start": 1, "stop": 3},
        {"seam": "checkpoint.write", "kind": "io_error", "count": 1}]}))
    chaos.install(rank=0)
    assert chaos.fire("kv.request") == []          # invocation 0: clear
    with pytest.raises(ConnectionRefusedError):    # 1, 2: blackout
        chaos.fire("kv.request")
    with pytest.raises(ConnectionRefusedError):
        chaos.fire("kv.request")
    assert chaos.fire("kv.request") == []          # 3: window closed
    with pytest.raises(OSError, match="chaos"):
        chaos.fire("checkpoint.write")
    assert chaos.fire("checkpoint.write") == []    # count=1 exhausted


def test_delay_kind_sleeps(monkeypatch):
    monkeypatch.setenv("HVD_TPU_FAULT_PLAN", json.dumps({"faults": [
        {"seam": "checkpoint.write", "kind": "slow_fsync",
         "delay_ms": 60, "count": 1}]}))
    chaos.install(rank=0)
    t0 = time.monotonic()
    applied = chaos.fire("checkpoint.write")
    assert applied == [("checkpoint.write", "slow_fsync")]
    assert time.monotonic() - t0 >= 0.055


def test_rank_filter_applies(monkeypatch):
    monkeypatch.setenv("HVD_TPU_FAULT_PLAN", json.dumps({"faults": [
        {"seam": "kv.request", "kind": "error", "rank": 1}]}))
    chaos.install(rank=0)
    assert chaos.fire("kv.request") == []
    chaos.install(rank=1)
    with pytest.raises(ConnectionResetError):
        chaos.fire("kv.request")


def test_marker_makes_rule_once_across_installs(tmp_path, monkeypatch):
    marker = tmp_path / "fired"
    monkeypatch.setenv("HVD_TPU_FAULT_PLAN", json.dumps({"faults": [
        {"seam": "kv.request", "kind": "error", "marker": str(marker)}]}))
    chaos.install(rank=0)
    with pytest.raises(ConnectionResetError):
        chaos.fire("kv.request")
    assert marker.exists()
    # a fresh arm (≈ a replacement process) finds the marker: disarmed
    chaos.uninstall()
    chaos.install(rank=0)
    assert chaos.fire("kv.request") == []


def test_install_idempotent_for_same_rank_and_plan(monkeypatch):
    """hvd.init() and a raw CoreBackend() both call install(); the second
    call must keep the armed engine (and its invocation counters), not
    rebuild and replay every window."""
    monkeypatch.setenv("HVD_TPU_FAULT_PLAN", json.dumps({"faults": [
        {"seam": "kv.request", "kind": "error", "start": 0, "stop": 1}]}))
    eng = chaos.install(rank=0)
    with pytest.raises(ConnectionResetError):
        chaos.fire("kv.request")          # invocation 0: window fires
    assert chaos.install(rank=0) is eng   # no rebuild
    assert chaos.fire("kv.request") == []  # counter kept: window closed
    # a DIFFERENT rank re-arms (rank-scoped rules must re-evaluate)
    assert chaos.install(rank=1) is not eng


def test_step_seam_indexes_by_step(monkeypatch):
    monkeypatch.setenv("HVD_TPU_FAULT_PLAN", json.dumps({"faults": [
        {"seam": "step", "kind": "stall", "start": 3, "stop": 4,
         "stall_s": 0.001}]}))
    chaos.install(rank=0)
    assert chaos.step_tick(0) == []
    assert chaos.step_tick(3) == [("step", "stall")]
    assert chaos.step_tick(4) == []
    # re-presenting the same step fires again only within count limits
    assert chaos.step_tick(3) == [("step", "stall")]


def test_injection_stamped_in_flight_and_metrics(monkeypatch):
    from horovod_tpu.diagnostics.flight_recorder import recorder
    from horovod_tpu.metrics.registry import default_registry
    key = ('hvd_chaos_injected_total{kind="delay",seam="kv.request"}')
    before = default_registry().snapshot().get(key, {}).get("value", 0)
    monkeypatch.setenv("HVD_TPU_FAULT_PLAN", json.dumps({"faults": [
        {"seam": "kv.request", "kind": "delay", "delay_ms": 1,
         "count": 1}]}))
    chaos.install(rank=0)
    chaos.fire("kv.request")
    snap = default_registry().snapshot()
    assert snap[key]["value"] == before + 1
    kinds = [(e["kind"], e.get("seam")) for e in recorder().events()]
    assert ("chaos_armed", None) in kinds
    assert ("fault_injected", "kv.request") in kinds


# -- transport spec compilation --------------------------------------------

def test_transport_spec_compiled_per_rank(monkeypatch):
    monkeypatch.setenv("HVD_TPU_FAULT_PLAN", json.dumps({"faults": [
        {"seam": "transport.recv", "kind": "delay", "rank": 1, "peer": 0,
         "start": 10, "count": 5, "delay_ms": 25},
        {"seam": "transport.send", "kind": "close", "rank": 0,
         "start": 7}]}))
    chaos.install(rank=1)
    assert os.environ["HVD_TPU_CHAOS_TRANSPORT"] == \
        "dir=recv:kind=delay:peer=0:after=10:count=5:ms=25"
    chaos.install(rank=0)
    assert os.environ["HVD_TPU_CHAOS_TRANSPORT"] == \
        "dir=send:kind=close:peer=-1:after=7:count=0:ms=0"
    chaos.install(rank=2)  # no transport rules for rank 2: env cleared
    assert "HVD_TPU_CHAOS_TRANSPORT" not in os.environ


def test_transport_stop_window_becomes_count():
    p = parse_plan(json.dumps({"faults": [
        {"seam": "transport.recv", "kind": "drop", "start": 4,
         "stop": 9}]}))
    assert compile_transport_spec(p, 0) == \
        "dir=recv:kind=drop:peer=-1:after=4:count=5:ms=0"


def test_transport_probability_rejected(monkeypatch):
    monkeypatch.setenv("HVD_TPU_FAULT_PLAN", json.dumps({"faults": [
        {"seam": "transport.recv", "kind": "drop",
         "probability": 0.5}]}))
    with pytest.raises(FaultPlanError, match="transport"):
        chaos.install(rank=0)


def test_core_env_dump_carries_transport_timeout(monkeypatch):
    from horovod_tpu.core import core_available
    if not core_available():
        pytest.skip("libhvdcore.so not built")
    from horovod_tpu.core.bindings import core_config_dump
    monkeypatch.setenv("HVD_TPU_TRANSPORT_TIMEOUT_S", "12.5")
    dump = core_config_dump()
    assert float(dump["transport_timeout_s"]) == 12.5


# -- instrumented call sites ------------------------------------------------

def test_kv_seam_blackout_rides_retries(monkeypatch):
    """A KV blackout window shorter than the retry budget is absorbed:
    the client retries through it and the call still succeeds."""
    from horovod_tpu.runner.http_kv import KVStoreServer, kv_get, kv_put
    srv = KVStoreServer()
    srv.start()
    try:
        monkeypatch.setenv("HVD_TPU_FAULT_PLAN", json.dumps({"faults": [
            {"seam": "kv.request", "kind": "blackout", "start": 1,
             "stop": 3}]}))
        chaos.install(rank=0)
        kv_put("127.0.0.1", srv.port, "s", "k", b"v")       # inv 0: ok
        # invocations 1, 2 black out; retries reach inv 3 and succeed
        assert kv_get("127.0.0.1", srv.port, "s", "k") == b"v"
        assert chaos.engine().injected_total == 2
    finally:
        srv.stop()
        chaos.uninstall()


def test_kv_seam_blackout_longer_than_budget_surfaces(monkeypatch):
    from urllib.error import URLError
    from horovod_tpu.runner.http_kv import KVStoreServer, kv_get
    srv = KVStoreServer()
    srv.start()
    try:
        monkeypatch.setenv("HVD_TPU_FAULT_PLAN", json.dumps({"faults": [
            {"seam": "kv.request", "kind": "blackout"}]}))
        chaos.install(rank=0)
        with pytest.raises((OSError, URLError)):
            kv_get("127.0.0.1", srv.port, "s", "k", timeout=1.0)
    finally:
        srv.stop()
        chaos.uninstall()


# -- the compound-fault soak ------------------------------------------------

@pytest.mark.slow
def test_chaos_soak_compound_faults(tmp_path):
    """The acceptance scenario (ISSUE 5): a 3-process elastic job trains
    under a COMPOUND fault plan — rank 2 SIGKILLed mid-step by the chaos
    `step` seam, a KV blackout window over the elastic control plane,
    injected transport delays, and a slowed checkpoint writer — and must
    still finish: survivors catch HorovodInternalError, re-rendezvous via
    the driver's recovery world, the durable sharded checkpoint stays
    intact and restorable, and the flight dumps record every Python-seam
    injection (the killed rank's dump is flushed BEFORE the SIGKILL)."""
    from horovod_tpu.core import core_available
    if not core_available():
        pytest.skip("libhvdcore.so not built")

    ckpt = tmp_path / "ckpt"
    autopsy = tmp_path / "autopsy"
    log = tmp_path / "events.log"
    flights = tmp_path / "flights"
    flights.mkdir()
    plan = {
        "seed": 7,
        "faults": [
            # the headliner: rank 2 dies by SIGKILL at step 3; the marker
            # keeps its replacement (same rank, same step) alive
            {"seam": "step", "kind": "kill", "rank": 2, "start": 3,
             "stop": 4, "marker": str(tmp_path / "killed_once")},
            # control-plane blackout: each rank's 3rd..5th KV request
            # fails; the retry budget must absorb the window
            {"seam": "kv.request", "kind": "blackout", "start": 2,
             "stop": 5},
            # wire chaos: rank 1 delays frames from rank 0
            {"seam": "transport.recv", "kind": "delay", "rank": 1,
             "peer": 0, "start": 50, "count": 10, "delay_ms": 20},
            # storage chaos: rank 0's checkpoint writer gets slow fsyncs
            {"seam": "checkpoint.write", "kind": "slow_fsync", "rank": 0,
             "start": 1, "count": 2, "delay_ms": 40},
        ],
    }
    plan_file = tmp_path / "plan.json"
    plan_file.write_text(json.dumps(plan))

    prog = tmp_path / "train.py"
    prog.write_text(textwrap.dedent(f"""
        import os, sys, time
        sys.path.insert(0, {str(REPO)!r})
        import numpy as np
        import horovod_tpu as hvd
        from horovod_tpu import chaos, elastic
        from horovod_tpu.diagnostics.flight_recorder import recorder

        orig_rank = int(os.environ["HOROVOD_RANK"])
        hvd.init()
        with open({str(log)!r}, "a") as f:
            f.write(f"BOOT rank={{orig_rank}} pid={{os.getpid()}}\\n")

        state = elastic.ObjectState(name="soak", step=0, durable=True)

        @elastic.run
        def train(state):
            while True:
                chaos.step_tick(state.step)   # rank-kill schedule
                out = hvd.allreduce(
                    np.ones(2, np.float32), op=hvd.Sum,
                    name=f"s{{hvd.size()}}.{{state.step}}")
                state.step += 1
                time.sleep(0.3)
                state.commit()                # pickle + durable shards
                if state.step >= 8:
                    return float(np.asarray(out)[0])

        out = train(state)
        assert out == float(hvd.size()), (out, hvd.size())
        state.flush()   # drain async durable commits before exiting
        recorder().dump_to(os.path.join(
            {str(flights)!r}, f"rank{{hvd.rank()}}_pid{{os.getpid()}}.json"))
        with open({str(log)!r}, "a") as f:
            f.write(f"DONE rank={{hvd.rank()}} pid={{os.getpid()}} "
                    f"size={{hvd.size()}} step={{state.step}}\\n")
        hvd.shutdown()
    """))

    from horovod_tpu.runner.elastic.discovery import FixedHosts
    from horovod_tpu.runner.elastic.driver import ElasticDriver
    from horovod_tpu.runner.hosts import HostInfo
    env = dict(os.environ)
    env.update({
        "HVD_TPU_FAULT_PLAN": str(plan_file),
        "HVD_TPU_FAULT_SEED": "7",
        "HVD_TPU_CHECKPOINT_DIR": str(ckpt),
        "HVD_TPU_CHECKPOINT_COMMIT_TIMEOUT_S": "5",
        "HVD_TPU_AUTOPSY_DIR": str(autopsy),
        # belt for the braces: if the SIGKILL's socket reset were ever
        # swallowed, the transport deadline still surfaces the loss
        "HVD_TPU_TRANSPORT_TIMEOUT_S": "20",
    })
    driver = ElasticDriver(
        FixedHosts([HostInfo("localhost", 3)]),
        [sys.executable, str(prog)],
        min_np=2, max_np=3, reset_limit=4, ckpt_dir=str(tmp_path),
        env=env)
    rc = driver.run()
    lines = log.read_text().strip().splitlines() if log.exists() else []
    assert rc == 0, lines

    # -- the job recovered: 3 finishers at full size, exactly one kill --
    dones = [l for l in lines if l.startswith("DONE")]
    boots = [l for l in lines if l.startswith("BOOT")]
    assert len(dones) == 3, lines
    assert all("size=3" in d and "step=8" in d for d in dones), dones
    assert len(boots) >= 4, lines  # 3 originals + >=1 replacement
    assert (tmp_path / "killed_once").exists()

    # -- the durable checkpoint survived and restores (at world size 1,
    # exercising elastic resharding on the way) ------------------------
    from horovod_tpu.checkpoint import ShardedCheckpointer
    store = ShardedCheckpointer(
        str(ckpt / "hvd_state_soak.sharded"), rank=0, world_size=1)
    latest = store.latest_step()
    # the kill lands at step 3: durable progress PAST it proves the
    # post-recovery world kept committing; restore_latest re-verifies
    # every shard's sha256, so a torn commit could not satisfy this.
    # (The exact last step can trail 8 by a commit or two: trailing
    # commits are async and a counter re-sync after the crash may drop
    # one — the pickle tier covers generation restarts regardless.)
    assert latest is not None and latest >= 4, latest
    restored = store.restore_latest()
    assert restored is not None and 4 <= restored["step"] <= 8, restored

    # -- every injected Python-seam fault is in a flight dump -----------
    def events_of(path):
        return json.load(open(path)).get("events", [])

    injected = []
    for f in flights.glob("*.json"):
        injected += [e for e in events_of(f)
                     if e["kind"] == "fault_injected"]
    # the killed rank's ring was flushed to the autopsy dir pre-SIGKILL
    killed_dump = autopsy / "hvd_flight_rank2.json"
    assert killed_dump.exists(), list(autopsy.glob("*")) \
        if autopsy.exists() else "no autopsy dir"
    killed_events = events_of(killed_dump)
    killed_faults = [e for e in killed_events
                     if e["kind"] == "fault_injected"]
    assert any(e["seam"] == "step" and e["fault"] == "kill"
               for e in killed_faults), killed_faults
    assert any(e["kind"] == "chaos_terminating" for e in killed_events)

    by_seam = {}
    for e in injected + killed_faults:
        by_seam.setdefault((e["seam"], e["fault"]), 0)
        by_seam[(e["seam"], e["fault"])] += 1
    assert by_seam.get(("kv.request", "blackout"), 0) >= 3, by_seam
    assert by_seam.get(("checkpoint.write", "slow_fsync"), 0) >= 1, by_seam
    assert by_seam.get(("step", "kill"), 0) == 1, by_seam
    # transport delays are injected on the C++ side; the armed spec is
    # stamped into rank 1's ring at install time
    armed = []
    for f in flights.glob("*.json"):
        armed += [e for e in events_of(f) if e["kind"] == "chaos_armed"
                  and e.get("transport_spec")]
    assert any("dir=recv:kind=delay" in (e.get("transport_spec") or "")
               for e in armed), armed


def test_checkpoint_writer_seam_surfaces_async_error(monkeypatch):
    from horovod_tpu.checkpoint.writer import AsyncWriter
    monkeypatch.setenv("HVD_TPU_FAULT_PLAN", json.dumps({"faults": [
        {"seam": "checkpoint.write", "kind": "io_error", "count": 1}]}))
    chaos.install(rank=0)
    w = AsyncWriter()
    done = []
    w.submit(lambda: done.append(1))  # chaos fires inside the writer
    with pytest.raises(OSError, match="chaos"):
        w.wait()
    assert done == []  # the injected error preempted the job
    w.submit(lambda: done.append(2))  # count=1: next job goes through
    w.wait()
    assert done == [2]
    w.close()


# -- fleet-scale fault kinds (ISSUE 10) --------------------------------------

def test_partition_groups_parsing():
    p = parse_plan(json.dumps({"faults": [
        {"seam": "kv.partition", "kind": "partition",
         "groups": [[0, 1], [2, 3, "driver"]], "start": 2, "stop": 6}]}))
    r = p.rules[0]
    assert r.groups == (frozenset({0, 1}), frozenset({2, 3, "driver"}))
    # bidirectional: either direction across the cut matches
    assert r.matches_pair(0, 2) and r.matches_pair(2, 0)
    assert r.matches_pair(1, "driver")
    # within a side, or with an unknown peer: no match
    assert not r.matches_pair(0, 1)
    assert not r.matches_pair(2, 3)
    assert not r.matches_pair(0, None)


def test_partition_groups_validation():
    with pytest.raises(FaultPlanError, match="needs 'groups'"):
        parse_plan(json.dumps({"faults": [
            {"seam": "kv.partition", "kind": "partition"}]}))
    with pytest.raises(FaultPlanError, match="only valid for"):
        parse_plan(json.dumps({"faults": [
            {"seam": "kv.request", "kind": "error",
             "groups": [[0], [1]]}]}))
    with pytest.raises(FaultPlanError, match="exactly two"):
        parse_plan(json.dumps({"faults": [
            {"seam": "kv.partition", "kind": "partition",
             "groups": [[0], [1], [2]]}]}))
    with pytest.raises(FaultPlanError, match="non-empty"):
        parse_plan(json.dumps({"faults": [
            {"seam": "kv.partition", "kind": "partition",
             "groups": [[0], []]}]}))
    with pytest.raises(FaultPlanError, match="overlap"):
        parse_plan(json.dumps({"faults": [
            {"seam": "kv.partition", "kind": "partition",
             "groups": [[0, 1], [1, 2]]}]}))
    with pytest.raises(FaultPlanError, match="bad group member"):
        parse_plan(json.dumps({"faults": [
            {"seam": "kv.partition", "kind": "partition",
             "groups": [[0], ["coordinator"]]}]}))
    # two cuts over DISJOINT member sets are independent schedules
    parse_plan(json.dumps({"faults": [
        {"seam": "kv.partition", "kind": "partition",
         "groups": [[0], [1]], "start": 0, "stop": 5},
        {"seam": "kv.partition", "kind": "partition",
         "groups": [[2], [3]], "start": 0, "stop": 5}]}))
    # overlapping member sets + overlapping windows: ambiguous
    with pytest.raises(FaultPlanError, match="overlapping windows"):
        parse_plan(json.dumps({"faults": [
            {"seam": "kv.partition", "kind": "partition",
             "groups": [[0], [1]], "start": 0, "stop": 5},
            {"seam": "kv.partition", "kind": "partition",
             "groups": [[1], [2]], "start": 0, "stop": 5}]}))


def test_partition_fires_only_across_the_cut(monkeypatch):
    monkeypatch.setenv("HVD_TPU_FAULT_PLAN", json.dumps({"faults": [
        {"seam": "kv.partition", "kind": "partition",
         "groups": [[0, 1], ["driver"]]}]}))
    chaos.install(rank=0)
    # a request to the driver crosses the cut: refused, both invocations
    with pytest.raises(ConnectionRefusedError, match="partition"):
        chaos.fire("kv.partition", peer="driver")
    # a relay hop to rank 1 stays inside the left side: clean
    assert chaos.fire("kv.partition", peer=1) == []
    # an uninvolved rank never fires the rule
    chaos.install(rank=5)
    assert chaos.fire("kv.partition", peer="driver") == []


def test_partition_window_heals(monkeypatch):
    """The soak shape in miniature: the cut opens for a window of
    invocations and HEALS — later requests go through."""
    monkeypatch.setenv("HVD_TPU_FAULT_PLAN", json.dumps({"faults": [
        {"seam": "kv.partition", "kind": "partition",
         "groups": [[0], ["driver"]], "start": 0, "stop": 2}]}))
    chaos.install(rank=0)
    for _ in range(2):
        with pytest.raises(ConnectionRefusedError):
            chaos.fire("kv.partition", peer="driver")
    assert chaos.fire("kv.partition", peer="driver") == []  # healed


def test_preemption_notice_is_pure_signal(monkeypatch):
    """The preemption seam never raises or kills: the applied list IS
    the payload the watcher polls for."""
    monkeypatch.setenv("HVD_TPU_FAULT_PLAN", json.dumps({"faults": [
        {"seam": "preemption", "kind": "notice", "rank": 2, "count": 1}]}))
    chaos.install(rank=2)
    assert chaos.fire("preemption") == [("preemption", "notice")]
    assert chaos.fire("preemption") == []  # count exhausted
    chaos.install(rank=0)
    assert chaos.fire("preemption") == []  # rank-scoped


def test_marker_rank_template_per_rank(tmp_path, monkeypatch):
    """A correlated multi-rank rule with a ``{rank}`` marker fires once
    per GROUP MEMBER: the first member's marker must not disarm the
    rest of the group (that would turn a correlated loss into a
    single-rank loss)."""
    marker = tmp_path / "fired_{rank}"
    monkeypatch.setenv("HVD_TPU_FAULT_PLAN", json.dumps({"faults": [
        {"seam": "kv.request", "kind": "error", "rank": [2, 3],
         "marker": str(marker)}]}))
    for r in (2, 3):
        chaos.install(rank=r)
        with pytest.raises(ConnectionResetError):
            chaos.fire("kv.request")
        # re-arm (a replacement process): per-rank marker disarms
        chaos.uninstall()
        chaos.install(rank=r)
        assert chaos.fire("kv.request") == []
    assert (tmp_path / "fired_2").exists()
    assert (tmp_path / "fired_3").exists()


# -- the preemption watcher ---------------------------------------------------

@pytest.fixture()
def _clean_preemption():
    from horovod_tpu.elastic import preemption
    preemption.reset()
    yield preemption
    preemption.reset()


def test_preemption_chaos_notice_publishes_drain(
        monkeypatch, _clean_preemption):
    """The chaos seam -> watcher -> drain/<rank> in the driver KV: the
    full advance-notice path minus the real metadata server."""
    import json as _json
    from horovod_tpu.elastic.preemption import PreemptionWatcher
    from horovod_tpu.runner.http_kv import KVStoreServer
    root = KVStoreServer()
    root.start()
    try:
        monkeypatch.setenv("HVD_ELASTIC_KV", f"127.0.0.1:{root.port}")
        monkeypatch.setenv("HOROVOD_RANK", "2")
        monkeypatch.setenv("HVD_TPU_FAULT_PLAN", _json.dumps({"faults": [
            {"seam": "preemption", "kind": "notice", "rank": 2}]}))
        chaos.install(rank=2)
        w = PreemptionWatcher()
        src = w.check_once()
        assert src == "chaos"
        assert w.notify(src) is True
        notice = _json.loads(root.get("drain", "2"))
        assert notice["rank"] == 2 and notice["source"] == "chaos"
        assert notice["scope"] == "worker"
        # latched: one notice per doomed life
        assert w.draining is True
        assert w.check_once() is None
        assert w.notify("chaos") is False
    finally:
        root.stop()


def test_preemption_notice_without_driver_kv(
        monkeypatch, _clean_preemption):
    """No elastic driver KV: the notice has no consumer — notify warns
    and reports False, and ensure_watcher never arms at all."""
    from horovod_tpu.elastic import preemption
    monkeypatch.delenv("HVD_ELASTIC_KV", raising=False)
    w = preemption.PreemptionWatcher()
    assert w.notify("sigterm") is False
    assert preemption.ensure_watcher() is None


def test_notify_retries_after_transient_publish_failure(
        monkeypatch, _clean_preemption):
    """A transiently-failed publish must not cost the advance notice:
    the watcher un-latches, remembers the SOURCE (the chaos/SIGTERM
    signal is one-shot and cannot be re-consulted), and a later poll
    retries the delivery until it lands."""
    import json as _json
    from horovod_tpu.elastic.preemption import PreemptionWatcher
    from horovod_tpu.runner.http_kv import KVStoreServer
    monkeypatch.setenv("HOROVOD_RANK", "1")
    # nothing listens here: the publish fails fast
    monkeypatch.setenv("HVD_ELASTIC_KV", "127.0.0.1:1")
    w = PreemptionWatcher()
    assert w.notify("chaos") is False
    assert w.draining is False          # un-latched: retry possible
    assert w.check_once() == "chaos"    # the source survives the failure
    root = KVStoreServer()
    root.start()
    try:
        monkeypatch.setenv("HVD_ELASTIC_KV", f"127.0.0.1:{root.port}")
        assert w.notify(w.check_once()) is True
        notice = _json.loads(root.get("drain", "1"))
        assert notice["source"] == "chaos"
        assert w.draining is True
        assert w.check_once() is None   # latched for good now
    finally:
        root.stop()


def test_metadata_blip_does_not_latch_after_success(monkeypatch):
    """The never-succeeded latch exists for off-TPU boxes; on a real TPU
    VM (a probe HAS succeeded) a metadata blip must not permanently
    disable the primary production preemption signal."""
    from horovod_tpu.elastic.preemption import PreemptionWatcher
    w = PreemptionWatcher()
    w._metadata_ok_once = True  # as if a real probe landed earlier
    monkeypatch.setenv("HVD_TPU_METADATA_ENDPOINT", "http://127.0.0.1:1")
    for _ in range(5):
        assert w._metadata_notice() is False
    assert w._metadata_dead is False  # still polling


def test_ensure_watcher_singleton_and_knob(
        monkeypatch, _clean_preemption):
    from horovod_tpu.elastic import preemption
    monkeypatch.setenv("HVD_ELASTIC_KV", "127.0.0.1:1")
    monkeypatch.setenv("HVD_TPU_PREEMPTION_WATCH", "0")
    assert preemption.ensure_watcher() is None
    monkeypatch.setenv("HVD_TPU_PREEMPTION_WATCH", "1")
    w = preemption.ensure_watcher()
    assert w is not None
    assert preemption.ensure_watcher() is w  # idempotent (hvd.init)
    assert preemption.current_watcher() is w


def test_sigterm_hook_publishes_drain(monkeypatch, _clean_preemption):
    """Opt-in SIGTERM source: the eviction signal publishes a drain
    notice and the process KEEPS RUNNING (it exits later through the
    planned re-mesh, not the signal)."""
    import json as _json
    import os
    import signal
    import time
    from horovod_tpu.elastic import preemption
    from horovod_tpu.runner.http_kv import KVStoreServer
    root = KVStoreServer()
    root.start()
    try:
        monkeypatch.setenv("HVD_ELASTIC_KV", f"127.0.0.1:{root.port}")
        monkeypatch.setenv("HOROVOD_RANK", "1")
        monkeypatch.setenv("HVD_TPU_PREEMPTION_SIGTERM", "1")
        w = preemption.ensure_watcher()
        assert w is not None
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 5
        while root.get("drain", "1") is None:
            assert time.monotonic() < deadline, "notice never published"
            time.sleep(0.05)
        notice = _json.loads(root.get("drain", "1"))
        assert notice["source"] == "sigterm"
        assert notice["scope"] == "worker"
    finally:
        root.stop()


# -- proactive drain vs reactive kill (ISSUE 10 acceptance) ------------------

def _drain_worker_prog(log, flights, finish_step):
    """Worker for the drain/kill comparison runs: allreduce+commit loop
    with durable state, finishing once the world is back to FULL size at
    ``finish_step`` — so the run only succeeds if the lost capacity was
    actually re-admitted (drain cooldown expiry / crash replacement)."""
    return textwrap.dedent(f"""
        import os, sys, time
        sys.path.insert(0, {REPO!r})
        import numpy as np
        import horovod_tpu as hvd
        from horovod_tpu import chaos, elastic
        from horovod_tpu.diagnostics.flight_recorder import recorder

        orig_rank = int(os.environ["HOROVOD_RANK"])
        hvd.init()
        with open({str(log)!r}, "a") as f:
            f.write(f"BOOT rank={{orig_rank}} pid={{os.getpid()}}\\n")

        state = elastic.ObjectState(name="drainrun", step=0, durable=True)

        @elastic.run
        def train(state):
            while True:
                chaos.step_tick(state.step)
                out = hvd.allreduce(
                    np.ones(2, np.float32), op=hvd.Sum,
                    name=f"d{{hvd.size()}}.{{state.step}}")
                state.step += 1
                time.sleep(0.3)
                state.commit()
                if state.step >= {finish_step} and hvd.size() == 3:
                    return float(np.asarray(out)[0])

        out = train(state)
        assert out == float(hvd.size()), (out, hvd.size())
        state.flush()
        recorder().dump_to(os.path.join(
            {str(flights)!r}, f"rank{{hvd.rank()}}_pid{{os.getpid()}}.json"))
        with open({str(log)!r}, "a") as f:
            f.write(f"DONE rank={{hvd.rank()}} pid={{os.getpid()}} "
                    f"size={{hvd.size()}} step={{state.step}}\\n")
        hvd.shutdown()
    """)


def _run_drain_scenario(tmp_path, name, plan, extra_env, finish_step=12):
    from horovod_tpu.runner.elastic.discovery import FixedHosts
    from horovod_tpu.runner.elastic.driver import ElasticDriver
    from horovod_tpu.runner.hosts import HostInfo
    base = tmp_path / name
    base.mkdir()
    log = base / "events.log"
    flights = base / "flights"
    flights.mkdir()
    plan_file = base / "plan.json"
    plan_file.write_text(json.dumps(plan))
    prog = base / "train.py"
    prog.write_text(_drain_worker_prog(log, flights, finish_step))
    env = dict(os.environ)
    env.update({
        "HVD_TPU_FAULT_PLAN": str(plan_file),
        "HVD_TPU_CHECKPOINT_DIR": str(base / "ckpt"),
        "HVD_TPU_CHECKPOINT_COMMIT_TIMEOUT_S": "5",
        "HVD_TPU_AUTOPSY_DIR": str(base / "autopsy"),
        # deterministic off-TPU: the metadata probe fails fast instead
        # of waiting out a DNS/connect timeout per watcher poll
        "HVD_TPU_METADATA_ENDPOINT": "http://127.0.0.1:1",
        "HVD_TPU_PREEMPTION_POLL_S": "0.2",
        "HVD_TPU_TRANSPORT_TIMEOUT_S": "20",
    })
    env.update(extra_env)
    driver = ElasticDriver(
        FixedHosts([HostInfo("localhost", 3)]),
        [sys.executable, str(prog)],
        min_np=2, max_np=3, target_np=3, reset_limit=4,
        ckpt_dir=str(base), env=env)
    rc = driver.run()
    lines = log.read_text().strip().splitlines() if log.exists() else []
    remesh = []
    for f in flights.glob("*.json"):
        remesh += [e for e in json.load(open(f)).get("events", [])
                   if e["kind"] == "remesh_complete"]
    return rc, lines, remesh, driver


@pytest.mark.slow
def test_proactive_drain_vs_reactive_kill(tmp_path):
    """The ISSUE 10 drain acceptance, both halves in one test:

    *Planned*: a chaos ``preemption`` notice dooms rank 2 -> the watcher
    publishes ``drain/2`` -> the driver re-meshes the survivors AROUND
    the doomed worker (world doc stamped ``drain``), whose exit is
    DRAINED, the host is never blocklisted, and the reserved slot is
    re-admitted after ``HVD_TPU_DRAIN_COOLDOWN_S`` — proven by the
    world healing back to 3 before anyone may finish.  The survivors'
    ``failure_detect`` phase is ~0: the world doc arrived WITH the
    interrupt.

    *Reactive baseline*: the same worker under a ``step`` SIGKILL pays
    real detection — HorovodInternalError plus the driver's settle +
    publish latency — so the planned path's near-zero detect is a
    measured comparison, not an absolute claim."""
    from horovod_tpu.core import core_available
    if not core_available():
        pytest.skip("libhvdcore.so not built")

    # -- planned drain ------------------------------------------------------
    rc, lines, remesh, driver = _run_drain_scenario(
        tmp_path, "planned",
        {"faults": [{"seam": "preemption", "kind": "notice", "rank": 2,
                     "marker": str(tmp_path / "preempted_once")}]},
        {"HVD_TPU_DRAIN_COOLDOWN_S": "2"})
    assert rc == 0, lines
    boots = [l for l in lines if l.startswith("BOOT")]
    dones = {l.split()[1].split("=")[1]: l for l in lines
             if l.startswith("DONE")}
    # 3 original boots + exactly ONE regrowth replacement after cooldown
    assert len(boots) == 4, lines
    # survivors finished in the healed full-size world
    for r in ("0", "1"):
        parts = dict(p.split("=") for p in dones[r].split()[1:])
        assert parts["size"] == "3", dones
    # the drained host was never treated as bad
    assert not driver._hosts.is_blacklisted("localhost")
    # driver-side evidence: the notice was handled as a DRAIN
    from horovod_tpu.diagnostics.flight_recorder import recorder
    handled = [e for e in recorder().events()
               if e["kind"] == "drain_notice_handled"]
    assert any(e.get("drained_ranks") == [2] and
               e.get("notices", [{}])[0].get("source") == "chaos"
               for e in handled), handled
    # the planned re-mesh episode: trigger + ~zero failure_detect
    planned = [e for e in remesh if e.get("trigger") == "preemption_drain"]
    assert len(planned) >= 2, remesh  # both survivors measured it
    planned_detect = max(e.get("failure_detect_s", 0.0) for e in planned)
    assert planned_detect < 0.05, planned
    # the durable store took the final pre-drain commit and restores
    from horovod_tpu.checkpoint import ShardedCheckpointer
    store = ShardedCheckpointer(
        str(tmp_path / "planned" / "ckpt" / "hvd_state_drainrun.sharded"),
        rank=0, world_size=1)
    restored = store.restore_latest()
    assert restored is not None and restored["step"] >= 1, restored

    # -- reactive baseline --------------------------------------------------
    rc2, lines2, remesh2, _drv2 = _run_drain_scenario(
        tmp_path, "reactive",
        {"faults": [{"seam": "step", "kind": "kill", "rank": 2,
                     "start": 3, "stop": 4,
                     "marker": str(tmp_path / "killed_once")}]},
        {})
    assert rc2 == 0, lines2
    reactive = [e for e in remesh2 if e.get("trigger") == "internal_error"]
    assert len(reactive) >= 2, remesh2
    reactive_detect = min(e.get("failure_detect_s", 0.0)
                          for e in reactive)
    # the measured SLO gap: planned detection is effectively free,
    # reactive detection pays real latency (settle + reap + publish)
    assert planned_detect < reactive_detect, (planned_detect,
                                              reactive_detect)


@pytest.mark.slow
def test_drain_notice_survives_growth_and_unviable_window(
        tmp_path, monkeypatch):
    """A drain notice that CANNOT be honored yet is retried, and stays
    valid across a growth publish.  World of 2 at min_np=2: the chaos
    ``preemption`` notice for rank 1 has no viable planned world (the
    shrink would violate min_np), so the driver reverts its bookkeeping
    and defers the notice with backoff instead of burning it.  The
    chaos marker file then unlocks a third discovery slot; the growth
    publish bumps the generation WITHOUT renumbering, so the deferred
    notice — stamped under the old generation by a watcher that
    latches after its one publish — must still match (numbering_gen
    window, not strict generation equality).  The retry plans the
    drain: rank 1 exits DRAINED, nobody is blocklisted, and the world
    heals to 3 after the drain cooldown."""
    import stat as _stat
    from horovod_tpu.core import core_available
    if not core_available():
        pytest.skip("libhvdcore.so not built")
    from horovod_tpu.runner.elastic.discovery import HostDiscoveryScript
    from horovod_tpu.runner.elastic.driver import ElasticDriver

    log = tmp_path / "events.log"
    flights = tmp_path / "flights"
    flights.mkdir()
    marker = tmp_path / "preempted_once"
    plan_file = tmp_path / "plan.json"
    plan_file.write_text(json.dumps(
        {"faults": [{"seam": "preemption", "kind": "notice", "rank": 1,
                     "marker": str(marker)}]}))
    # the third slot appears only once the preemption has fired — the
    # notice is near-certain to be scanned (and found unviable) first
    disco = tmp_path / "discover.sh"
    disco.write_text(
        "#!/bin/bash\n"
        f"if [ -f {marker} ]; then echo localhost:3; "
        "else echo localhost:2; fi\n")
    disco.chmod(disco.stat().st_mode | _stat.S_IEXEC)
    prog = tmp_path / "train.py"
    prog.write_text(_drain_worker_prog(log, flights, finish_step=8))
    env = dict(os.environ)
    env.update({
        "HVD_TPU_FAULT_PLAN": str(plan_file),
        "HVD_TPU_CHECKPOINT_DIR": str(tmp_path / "ckpt"),
        "HVD_TPU_CHECKPOINT_COMMIT_TIMEOUT_S": "5",
        "HVD_TPU_AUTOPSY_DIR": str(tmp_path / "autopsy"),
        "HVD_TPU_METADATA_ENDPOINT": "http://127.0.0.1:1",
        "HVD_TPU_PREEMPTION_POLL_S": "0.2",
        "HVD_TPU_TRANSPORT_TIMEOUT_S": "20",
    })
    # driver-side knob: read from THIS process's environment, not the
    # worker env dict
    monkeypatch.setenv("HVD_TPU_DRAIN_COOLDOWN_S", "2")
    driver = ElasticDriver(
        HostDiscoveryScript(str(disco)), [sys.executable, str(prog)],
        min_np=2, max_np=3, reset_limit=4, ckpt_dir=str(tmp_path),
        env=env)
    rc = driver.run()
    lines = log.read_text().strip().splitlines() if log.exists() else []
    assert rc == 0, lines
    boots = [l for l in lines if l.startswith("BOOT")]
    dones = [l for l in lines if l.startswith("DONE")]
    # ranks 0,1 + the growth slot + the drain replacement + possibly
    # one more: a growth spawn is NOT essential, so a drain re-mesh
    # that lands after the growth publish plans it out of the world
    # and the post-cooldown regrowth re-spawns it
    assert 4 <= len(boots) <= 5, lines
    assert len(dones) == 3, lines
    for d in dones:
        assert "size=3" in d, lines  # finished in the healed full world
    assert not driver._hosts.is_blacklisted("localhost")
    from horovod_tpu.diagnostics.flight_recorder import recorder
    handled = [e for e in recorder().events()
               if e["kind"] == "drain_notice_handled"
               and e.get("drained_ranks") == [1]]
    assert any(e.get("notices", [{}])[0].get("source") == "chaos"
               for e in handled), handled


# -- partition + correlated-loss soak (ISSUE 10 acceptance) ------------------

@pytest.mark.slow
def test_chaos_soak_partition_and_correlated_loss(tmp_path):
    """Fleet-scale chaos soak: a 4-process elastic job on TWO virtual
    hosts (localhost + 127.0.0.1, 2 slots each) with the KV relay
    enabled survives (a) a ``kv.partition`` window cutting host group
    {2,3} off from {0,1} — relay hops across the cut are refused until
    the window heals, degrading to root fallback with no failed step —
    and (b) a CORRELATED ``step`` kill taking out BOTH ranks of host
    group 2 in one window ({rank} marker: each member dies exactly
    once).  The driver's loss-settle collapses the burst into one
    re-mesh; the world heals to full size and NO host is blocklisted
    (one originator charge, not two, lands on the doomed host)."""
    from horovod_tpu.core import core_available
    if not core_available():
        pytest.skip("libhvdcore.so not built")

    log = tmp_path / "events.log"
    flights = tmp_path / "flights"
    flights.mkdir()
    autopsy = tmp_path / "autopsy"
    plan = {
        "seed": 13,
        "faults": [
            # the cut: host group {2,3} vs {0,1} — crossing relay hops
            # (rank 2 -> parent 0, rank 3 -> parent 1) are refused for
            # each process's first 8 kv.partition invocations, then heal
            {"seam": "kv.partition", "kind": "partition",
             "groups": [[0, 1], [2, 3]], "start": 0, "stop": 8},
            # correlated loss: EVERY rank of host group 2 dies at step 6
            # (late enough that the relay tree has formed and the cut
            # has actually been exercised by then)
            {"seam": "step", "kind": "kill", "rank": [2, 3],
             "start": 6, "stop": 7,
             "marker": str(tmp_path / "ckill_{rank}")},
        ],
    }
    plan_file = tmp_path / "plan.json"
    plan_file.write_text(json.dumps(plan))

    prog = tmp_path / "train.py"
    prog.write_text(textwrap.dedent(f"""
        import os, sys, time
        sys.path.insert(0, {REPO!r})
        import numpy as np
        import horovod_tpu as hvd
        from horovod_tpu import chaos, elastic
        from horovod_tpu.diagnostics.flight_recorder import recorder

        orig_rank = int(os.environ["HOROVOD_RANK"])
        hvd.init()
        with open({str(log)!r}, "a") as f:
            f.write(f"BOOT rank={{orig_rank}} pid={{os.getpid()}}\\n")

        state = elastic.ObjectState(name="fleet", step=0)

        @elastic.run
        def train(state):
            while True:
                chaos.step_tick(state.step)
                out = hvd.allreduce(
                    np.ones(2, np.float32), op=hvd.Sum,
                    name=f"p{{hvd.size()}}.{{state.step}}")
                state.step += 1
                time.sleep(0.3)
                state.commit()
                if state.step >= 11 and hvd.size() == 4:
                    return float(np.asarray(out)[0])

        out = train(state)
        assert out == float(hvd.size()), (out, hvd.size())
        recorder().dump_to(os.path.join(
            {str(flights)!r}, f"rank{{hvd.rank()}}_pid{{os.getpid()}}.json"))
        with open({str(log)!r}, "a") as f:
            f.write(f"DONE rank={{hvd.rank()}} pid={{os.getpid()}} "
                    f"size={{hvd.size()}} step={{state.step}}\\n")
        hvd.shutdown()
    """))

    from horovod_tpu.runner.elastic.discovery import FixedHosts
    from horovod_tpu.runner.elastic.driver import ElasticDriver
    from horovod_tpu.runner.hosts import HostInfo
    env = dict(os.environ)
    env.update({
        "HVD_TPU_FAULT_PLAN": str(plan_file),
        "HVD_TPU_FAULT_SEED": "13",
        "HVD_TPU_KV_RELAY_ARITY": "2",   # the cut needs relay hops
        # at generation start every worker registers simultaneously, so
        # the first parent lookups miss; retry quickly so the tree forms
        # (and the cut is exercised) within the killed ranks' lifetime
        "HVD_TPU_KV_RELAY_RESOLVE_TTL_S": "0.2",
        "HVD_TPU_KV_RELAY_DEAD_S": "0.5",
        "HVD_TPU_AUTOPSY_DIR": str(autopsy),
        "HVD_TPU_METADATA_ENDPOINT": "http://127.0.0.1:1",
        "HVD_TPU_TRANSPORT_TIMEOUT_S": "20",
    })
    driver = ElasticDriver(
        FixedHosts([HostInfo("localhost", 2), HostInfo("127.0.0.1", 2)]),
        [sys.executable, str(prog)],
        min_np=2, max_np=4, target_np=4, reset_limit=4,
        ckpt_dir=str(tmp_path), env=env)
    rc = driver.run()
    lines = log.read_text().strip().splitlines() if log.exists() else []
    assert rc == 0, lines

    boots = [l for l in lines if l.startswith("BOOT")]
    dones = [l for l in lines if l.startswith("DONE")]
    # 4 originals + 2 replacements for the correlated loss
    assert len(boots) == 6, lines
    assert len(dones) == 4, lines
    assert all("size=4" in d for d in dones), dones
    # the correlated rule killed EVERY member of the host group once
    assert (tmp_path / "ckill_2").exists()
    assert (tmp_path / "ckill_3").exists()
    # one originator charge, one casualty: NO host blocklisted — not
    # the survivors' host, and not even the chaos-targeted one
    assert not driver._hosts.is_blacklisted("localhost")
    assert not driver._hosts.is_blacklisted("127.0.0.1")

    # every injection is visible: the killed ranks' pre-SIGKILL flushes
    # carry both the partition refusals and the kills
    def events_of(path):
        return json.load(open(path)).get("events", [])

    injected = []
    for r in (2, 3):
        dump = autopsy / f"hvd_flight_rank{r}.json"
        assert dump.exists(), (r, list(autopsy.glob("*"))
                               if autopsy.exists() else "no autopsy dir")
        injected += [e for e in events_of(dump)
                     if e["kind"] == "fault_injected"]
    for f in flights.glob("*.json"):
        injected += [e for e in events_of(f)
                     if e["kind"] == "fault_injected"]
    by_kind = {}
    for e in injected:
        key = (e["seam"], e["fault"])
        by_kind[key] = by_kind.get(key, 0) + 1
    assert by_kind.get(("step", "kill"), 0) == 2, by_kind
    assert by_kind.get(("kv.partition", "partition"), 0) >= 2, by_kind
