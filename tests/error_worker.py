"""Worker exercising the core's error paths: shape mismatch across ranks and
duplicate in-flight names (reference analog: error cases in
test/parallel/test_torch.py)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np  # noqa: E402
from horovod_tpu.core.core_backend import CoreBackend  # noqa: E402
from horovod_tpu.ops.reduce_op import ReduceOp  # noqa: E402


def main():
    be = CoreBackend()
    rank = be.rank
    # 1) mismatched shapes must produce a clean error on every rank
    x = np.ones(5 if rank == 0 else 10, np.float32)
    try:
        be.allreduce_async("mismatch", x, ReduceOp.SUM).wait(30)
        raise SystemExit(f"rank {rank}: mismatch did NOT error")
    except RuntimeError as e:
        assert "mismatched" in str(e), e
    # 2) duplicate name while in flight → immediate DUPLICATE error.
    #    Each rank first submits a name the PEER has not submitted yet, so
    #    the first op provably cannot complete before the duplicate lands
    #    (the wake-on-enqueue loop finishes same-name pairs in ~100 µs,
    #    which made a shared name racy)
    mine, theirs = f"dup.{rank}", f"dup.{1 - rank}"
    h1 = be.allreduce_async(mine, np.ones(4, np.float32), ReduceOp.SUM)
    try:
        be.allreduce_async(mine, np.ones(4, np.float32), ReduceOp.SUM).wait(5)
        raise SystemExit(f"rank {rank}: duplicate did NOT error")
    except RuntimeError as e:
        assert "duplicate" in str(e).lower(), e
    # barrier BEFORE anyone submits the peer's name: both duplicate checks
    # have now run while their firsts were provably still in flight
    be.barrier()
    h2 = be.allreduce_async(theirs, np.ones(4, np.float32), ReduceOp.SUM)
    np.testing.assert_allclose(h1.wait(30), 2.0)
    np.testing.assert_allclose(h2.wait(30), 2.0)
    # 3) grouped allreduce with one mismatched member: the whole group
    #    errors (poisoned-group path), no handle hangs
    bad = np.ones(7 if rank == 0 else 9, np.float32)
    h = be.grouped_allreduce_async(
        ["g.ok", "g.bad"], [np.ones(4, np.float32), bad], ReduceOp.SUM)
    try:
        h.wait(30)
        raise SystemExit(f"rank {rank}: grouped mismatch did NOT error")
    except RuntimeError as e:
        msg = str(e).lower()
        assert "mismatched" in msg or "group" in msg, e

    # 4) normal op still works after the errors
    out = be.allreduce_async("after", np.ones(3, np.float32),
                             ReduceOp.SUM).wait(30)
    np.testing.assert_allclose(out, 2.0)
    be.shutdown()
    print(f"error worker {rank}: OK")


if __name__ == "__main__":
    main()
