"""Unit battery for the shared retry policy engine
(:mod:`horovod_tpu.common.retry`): backoff/jitter bounds, total-deadline
budget, exception filtering, and per-call-site metrics emission."""

import random

import pytest

from horovod_tpu.common.retry import retry_call
from horovod_tpu.metrics.registry import default_registry


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def sleep(self, s):
        self.t += s


def test_success_first_try_no_sleep():
    sleeps = []
    assert retry_call(lambda: 42, site="t.first", sleep=sleeps.append) == 42
    assert sleeps == []


def test_retries_then_succeeds():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionResetError("boom")
        return "ok"

    sleeps = []
    out = retry_call(flaky, site="t.flaky", attempts=4,
                     sleep=sleeps.append, jitter=0.0)
    assert out == "ok"
    assert calls["n"] == 3
    assert len(sleeps) == 2  # two failures -> two backoffs


def test_exhaustion_raises_last_error():
    def always():
        raise TimeoutError("still down")

    with pytest.raises(TimeoutError):
        retry_call(always, site="t.exhaust", attempts=3,
                   retry_on=(TimeoutError,), sleep=lambda s: None)


def test_backoff_is_exponential_and_capped():
    sleeps = []

    def always():
        raise OSError("x")

    with pytest.raises(OSError):
        retry_call(always, site="t.backoff", attempts=5, base_delay_s=0.1,
                   backoff=2.0, max_delay_s=0.35, jitter=0.0,
                   sleep=sleeps.append)
    # retries 0..3 sleep; the 5th (last) attempt raises without sleeping
    assert sleeps == pytest.approx([0.1, 0.2, 0.35, 0.35])


def test_jitter_bounds():
    """Every jittered sleep stays within [delay*(1-j), delay*(1+j)]."""
    sleeps = []

    def always():
        raise OSError("x")

    with pytest.raises(OSError):
        retry_call(always, site="t.jitter", attempts=50, base_delay_s=0.1,
                   backoff=1.0, max_delay_s=0.1, jitter=0.5,
                   rng=random.Random(7), sleep=sleeps.append)
    assert len(sleeps) == 49
    assert all(0.05 - 1e-9 <= s <= 0.15 + 1e-9 for s in sleeps)
    # jitter actually varies (not a fixed multiplier)
    assert max(sleeps) - min(sleeps) > 0.01


def test_deadline_budget_stops_early():
    """A total-deadline budget stops retrying long before the attempt
    count would: 100 attempts with ~0.5s sleeps under a 1.2s budget."""
    clk = FakeClock()
    tries = {"n": 0}

    def always():
        tries["n"] += 1
        clk.t += 0.1  # each attempt itself costs wall time
        raise OSError("down")

    with pytest.raises(OSError):
        retry_call(always, site="t.deadline", attempts=100,
                   base_delay_s=0.5, backoff=1.0, max_delay_s=0.5,
                   jitter=0.0, deadline_s=1.2, sleep=clk.sleep, clock=clk)
    # attempt(0.1) + sleep(0.5) fits twice; the third attempt's sleep
    # would cross the 1.2s budget -> give up
    assert tries["n"] == 3
    assert clk.t <= 1.5


def test_deadline_never_starves_first_attempt():
    # even with a 0 deadline the first call runs (and its error counts
    # as exhaustion, not a crash in the budget math)
    with pytest.raises(OSError):
        retry_call(lambda: (_ for _ in ()).throw(OSError("x")),
                   site="t.zero", deadline_s=0.0, sleep=lambda s: None)


def test_non_retryable_propagates_immediately():
    calls = {"n": 0}

    def bad():
        calls["n"] += 1
        raise ValueError("logic bug")

    with pytest.raises(ValueError):
        retry_call(bad, site="t.filter", retry_on=(OSError,),
                   sleep=lambda s: None)
    assert calls["n"] == 1


def test_give_up_on_beats_retry_on_subclassing():
    """urllib's HTTPError subclasses OSError; give_up_on must win so a
    404 is not retried four times."""
    from urllib.error import HTTPError
    calls = {"n": 0}

    def not_found():
        calls["n"] += 1
        raise HTTPError("http://x", 404, "nf", {}, None)

    with pytest.raises(HTTPError):
        retry_call(not_found, site="t.giveup", retry_on=(OSError,),
                   give_up_on=(HTTPError,), sleep=lambda s: None)
    assert calls["n"] == 1


def test_metrics_emitted_per_site():
    reg = default_registry()
    site = "t.metrics.unique"
    key_a = 'hvd_retry_attempts_total{site="%s"}' % site
    key_e = 'hvd_retry_exhausted_total{site="%s"}' % site
    before_a = reg.snapshot().get(key_a, {}).get("value", 0)
    before_e = reg.snapshot().get(key_e, {}).get("value", 0)

    def always():
        raise OSError("x")

    with pytest.raises(OSError):
        retry_call(always, site=site, attempts=3, sleep=lambda s: None)
    snap = reg.snapshot()
    assert snap[key_a]["value"] == before_a + 3
    assert snap[key_e]["value"] == before_e + 1

    # a successful retry emits attempts but no exhaustion
    calls = {"n": 0}

    def once():
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("x")
        return 1

    retry_call(once, site=site, attempts=3, sleep=lambda s: None)
    snap = reg.snapshot()
    assert snap[key_a]["value"] == before_a + 4
    assert snap[key_e]["value"] == before_e + 1


def test_attempts_must_be_positive():
    with pytest.raises(ValueError):
        retry_call(lambda: 1, site="t.bad", attempts=0)


def test_single_attempt_is_a_plain_call_no_metrics():
    """attempts=1 means no retry policy — a failing probe must not raise
    false 'retry exhausted' alarms on /metrics (running_on_tpu_vm runs
    off-TPU on every CI box)."""
    reg = default_registry()
    site = "t.single.unique"
    with pytest.raises(OSError):
        retry_call(lambda: (_ for _ in ()).throw(OSError("off-tpu")),
                   site=site, attempts=1, sleep=lambda s: None)
    snap = reg.snapshot()
    assert ('hvd_retry_attempts_total{site="%s"}' % site) not in snap
    assert ('hvd_retry_exhausted_total{site="%s"}' % site) not in snap
