"""Control-plane HA tests (ISSUE 20): the driver journal's replay
semantics, crash takeover rebuilding a live generation from the journal,
the worker-side outage grace window, and the two chaos acceptance runs —
driver SIGKILLed mid-training (ride-through, zero re-mesh) and mid-
re-mesh (takeover completes the recovery the dead driver never
published).  docs/ELASTIC.md "Driver failover & takeover"."""

import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

from horovod_tpu.core import core_available
from horovod_tpu.runner.elastic import journal as journal_mod
from horovod_tpu.runner.elastic.journal import (DriverJournal,
                                                TakeoverRefused,
                                                load, read_journal, replay)
from horovod_tpu.runner.hosts import SlotInfo

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

needs_core = pytest.mark.skipif(not core_available(),
                                reason="libhvdcore.so not built")


# -- journal unit battery ----------------------------------------------------
def _fill(j: DriverJournal) -> None:
    j.append("job_open", secret="ab" * 16, kv_port=4567,
             driver_addr="localhost", ckpt_dir="/tmp/ck", min_np=1,
             max_np=3, target_np=2, pid=123, ts=1000.0)
    j.append("blocklist", host="badhost",
             evidence={"reason": "all_workers_failed"}, ts=1001.0)
    j.append("drain", host="oldhost", slots=2, remaining_s=60.0,
             ts=1002.0)
    j.append("token", scope="drain", key="k1", raw="payload")
    j.append("reset", count=2)
    # a pre-publish registration: stale the moment the world publishes
    # (the driver clears the notify scope), so replay must forget it
    j.append("notify", rank="9", addr="oldhost:1111")
    j.append("world_publish", doc={"generation": 0, "size": 2},
             world_gen=0, numbering_gen=0, essential_gen=0, np=2,
             coord_addr="localhost", coord_port=7777,
             slots=[], essential_keys=[[0, 0], [0, 1]],
             current_rank=[[[0, 0], 0], [[0, 1], 1]],
             expected_exits=[], drained_exits=[])
    j.append("spawn", key=[0, 0], host="localhost", rank=0, pid=111,
             ts=1003.0)
    j.append("spawn", key=[0, 1], host="localhost", rank=1, pid=222,
             ts=1003.5)
    j.append("exit", key=[0, 0], state="SUCCESS", rank=0,
             host="localhost")
    j.append("notify", rank="1", addr="localhost:9999")


def test_journal_append_and_replay(tmp_path):
    j = DriverJournal(str(tmp_path))
    _fill(j)
    j.close()
    state = load(j.path)
    assert state.meta["kv_port"] == 4567
    assert state.world_gen == 0 and state.numbering_gen == 0
    assert state.blocklist["badhost"]["evidence"] == {
        "reason": "all_workers_failed"}
    assert state.drains["oldhost"]["remaining_s"] == 60.0
    assert ("drain", "k1", "payload") in state.tokens
    assert state.reset_count == 2
    # the post-publish registration survives; the pre-publish one is
    # stale (scope cleared at publish) and replay forgot it the same way
    assert state.notify["1"]["addr"] == "localhost:9999"
    assert "9" not in state.notify
    assert state.exits[(0, 0)]["state"] == "SUCCESS"
    # rank 0 exited: only rank 1 is still live in the window
    assert set(state.live_workers()) == {(0, 1)}
    assert state.clean_exit is None and state.unknown == 0
    state.check_takeover()  # has a committed world: takeover viable


def test_journal_replay_idempotent(tmp_path):
    j = DriverJournal(str(tmp_path))
    _fill(j)
    j.close()
    records, torn = read_journal(j.path)
    once = replay(records, torn)
    twice = replay(records + records, torn)
    for attr in ("meta", "world", "live", "exits", "blocklist", "drains",
                 "tokens", "notify", "reset_count", "clean_exit"):
        assert getattr(once, attr) == getattr(twice, attr), attr


def test_journal_torn_tail_tolerated(tmp_path):
    j = DriverJournal(str(tmp_path))
    _fill(j)
    j.close()
    # a crash mid-append leaves a partial line with no newline
    with open(j.path, "ab") as f:
        f.write(b'{"t": "spawn", "key": [0, 2], "ho')
    records, torn = read_journal(j.path)
    assert torn is not None and journal_mod.torn_tail_type(torn) == "spawn"
    state = replay(records, torn)
    # every COMPLETE record survived; the torn spawn is dropped
    assert state.exits[(0, 0)]["state"] == "SUCCESS"
    assert (0, 2) not in state.live
    state.check_takeover()  # a torn spawn does not poison takeover


def test_torn_world_publish_refuses_takeover(tmp_path):
    j = DriverJournal(str(tmp_path))
    _fill(j)
    j.close()
    with open(j.path, "ab") as f:
        f.write(b'{"t": "world_publish", "doc": {"generation"')
    state = load(j.path)
    with pytest.raises(TakeoverRefused) as ei:
        state.check_takeover()
    # the refusal points the operator at the generation-restart backstop
    assert "backstop" in str(ei.value)


def test_no_world_and_clean_exit_refuse_takeover(tmp_path):
    j = DriverJournal(str(tmp_path))
    j.append("job_open", secret="ab" * 16, kv_port=1, ts=1.0)
    j.close()
    with pytest.raises(TakeoverRefused):
        load(j.path).check_takeover()
    j2 = DriverJournal(str(tmp_path))
    _fill(j2)
    j2.append("clean_exit", rc=0)
    j2.close()
    with pytest.raises(TakeoverRefused) as ei:
        load(j2.path).check_takeover()
    assert "on purpose" in str(ei.value)


def test_unknown_record_type_skipped_loudly(tmp_path):
    j = DriverJournal(str(tmp_path))
    _fill(j)
    j.append("hologram", key=[9, 9])  # a newer driver's record type
    j.close()
    state = load(j.path)
    assert state.unknown == 1
    # the rest of the state is unharmed
    assert state.reset_count == 2 and state.world is not None
    state.check_takeover()


def test_compaction_preserves_state(tmp_path):
    j = DriverJournal(str(tmp_path))
    _fill(j)
    before = load(j.path)
    assert j.maybe_compact(max_bytes=64) is True
    j.close()
    after = load(j.path)
    for attr in ("world", "blocklist", "drains", "tokens", "notify",
                 "reset_count", "clean_exit"):
        assert getattr(before, attr) == getattr(after, attr), attr
    # the live generation's spawn/exit window survives rotation
    assert after.exits[(0, 0)]["state"] == "SUCCESS"
    assert set(after.live_workers()) == {(0, 1)}
    # and the compacted file folds idempotently too
    records, torn = read_journal(j.path)
    assert replay(records + records, torn).tokens == after.tokens


def test_compaction_drops_pre_window_exits(tmp_path):
    """Exit history from generations before the published numbering
    window is dead weight — replay ignores it, so rotation drops it."""
    j = DriverJournal(str(tmp_path))
    _fill(j)
    # pre-window relic from an old re-mesh, then a newer world at gen 3
    j.append("exit", key=[1, 0], state="FAILURE", rank=0, host="gone")
    j.append("world_publish", doc={"generation": 3, "size": 1},
             world_gen=3, numbering_gen=3, essential_gen=3, np=1,
             coord_addr="localhost", coord_port=7777, slots=[],
             essential_keys=[[3, 0]], current_rank=[[[3, 0], 0]],
             expected_exits=[], drained_exits=[])
    assert j.maybe_compact(max_bytes=64) is True
    j.close()
    records, _ = read_journal(j.path)
    exit_keys = [tuple(r["key"]) for r in records if r["t"] == "exit"]
    assert (1, 0) not in exit_keys


# -- crash takeover: rebuild correctness (no workers involved) ---------------
def _free_port() -> int:
    from horovod_tpu.runner.http_kv import KVStoreServer
    kv = KVStoreServer()
    kv.start()
    port = kv.port
    kv.stop()
    return port


def test_takeover_rebuilds_driver_state(tmp_path):
    """A takeover driver replays the journal and becomes the dead
    driver: same secret, same KV port, the last committed world doc
    re-published VERBATIM, blocklist evidence and reset budget restored,
    handled tokens deduped as the raw bytes the KV will serve."""
    from horovod_tpu.runner.elastic.discovery import FixedHosts
    from horovod_tpu.runner.elastic.driver import ElasticDriver
    from horovod_tpu.runner.hosts import HostInfo

    port = _free_port()
    secret = "cd" * 16
    slots = [SlotInfo(hostname="localhost", rank=r, local_rank=r,
                      cross_rank=0, size=2, local_size=2, cross_size=1)
             for r in range(2)]
    doc = {"generation": 0, "size": 2, "coord_addr": "localhost",
           "coord_port": 7777, "slots": {}, "sig": "original-sig"}
    runtime = ElasticDriver._runtime_record(
        0, slots, "localhost", 7777, [(0, 0), (0, 1)],
        {(0, 0): 0, (0, 1): 1}, 0, 0)

    j = DriverJournal(str(tmp_path))
    j.append("job_open", secret=secret, kv_port=port,
             driver_addr="localhost", ckpt_dir=str(tmp_path),
             min_np=1, max_np=2, target_np=2, pid=1,
             ts=journal_mod.now_wall())
    evidence = {"reason": "quarantine", "rank": 1}
    j.append("blocklist", host="badhost", evidence=evidence,
             ts=journal_mod.now_wall())
    j.append("token", scope="action", key="a1", raw="req-bytes")
    j.append("reset", count=2)
    j.append("world_publish", doc=doc, **runtime)
    j.append("spawn", key=[0, 0], host="localhost", rank=0, pid=111,
             ts=journal_mod.now_wall())
    j.append("spawn", key=[0, 1], host="localhost", rank=1, pid=None,
             ts=journal_mod.now_wall())
    j.append("exit", key=[0, 0], state="SUCCESS", rank=0,
             host="localhost")
    j.append("notify", rank="1", addr="localhost:45678")
    j.close()
    pre = load(j.path)

    driver = ElasticDriver(
        FixedHosts([HostInfo("localhost", 2)]),
        [sys.executable, "-c", "pass"], min_np=1, max_np=2,
        ckpt_dir=str(tmp_path / "other"),
        journal_dir=str(tmp_path), takeover=True)
    g = None
    try:
        # identity adopted from the journal, not minted fresh
        assert driver._world_secret == bytes.fromhex(secret)
        assert driver._kv.port == port
        assert driver._ckpt_dir == str(tmp_path)
        assert driver._generation == pre.world_gen + 1

        g = driver._begin_takeover()
        # the last committed world is re-served VERBATIM (old signature
        # and all — its HMAC is over the canonical form)
        assert json.loads(driver._kv.get("world", "current")) == doc
        assert g.world_gen == 0 and g.essential_keys == [(0, 0), (0, 1)]
        # handled tokens dedupe as BYTES (what the KV scan yields)
        assert ("action", "a1", b"req-bytes") in g.handled_tokens
        # exclusion state identical pre/post takeover, evidence included
        assert driver._hosts.block_evidence("badhost") == evidence
        dump = driver._hosts.dump_state()
        assert set(dump["blocklist"]) == set(pre.blocklist)
        # the reset budget is the JOB's, not the process's
        assert driver._registry.reset_count == 2
        # the journaled listener registration is restored into the KV:
        # a survivor that never noticed the outage (its KV gets retried
        # straight through it) stays viable for in-place recovery
        assert driver._kv.get("notify", "1") == b"localhost:45678"
        # rank 0's journaled exit is preloaded; rank 1 is adopted live
        assert g.results[(0, 0)] == "SUCCESS"
        assert (0, 1) in g.threads and g.threads[(0, 1)].is_alive()
        # the takeover itself is journaled (the NEXT takeover sees it)
        assert load(j.path).takeovers
    finally:
        if g is not None:
            g.teardown.set()
        driver._kv.stop()
        if driver._journal is not None:
            driver._journal.close()


def test_takeover_remarks_unrecovered_failure_as_lost(tmp_path):
    """Worst case (acceptance B): the dead driver classified an
    essential worker FAILURE but crashed before publishing a recovery
    world.  Replay must re-mark it lost so the monitor loop plans the
    recovery the old driver never published — and the settle gate must
    hold that planning until survivors re-register."""
    from horovod_tpu.runner.elastic.discovery import FixedHosts
    from horovod_tpu.runner.elastic.driver import ElasticDriver
    from horovod_tpu.runner.hosts import HostInfo

    port = _free_port()
    slots = [SlotInfo(hostname="localhost", rank=r, local_rank=r,
                      cross_rank=0, size=2, local_size=2, cross_size=1)
             for r in range(2)]
    runtime = ElasticDriver._runtime_record(
        0, slots, "localhost", 7777, [(0, 0), (0, 1)],
        {(0, 0): 0, (0, 1): 1}, 0, 0)
    j = DriverJournal(str(tmp_path))
    j.append("job_open", secret="ee" * 16, kv_port=port,
             driver_addr="localhost", ckpt_dir=str(tmp_path),
             ts=journal_mod.now_wall())
    j.append("world_publish", doc={"generation": 0, "size": 2},
             **runtime)
    j.append("spawn", key=[0, 0], host="localhost", rank=0, pid=None,
             ts=journal_mod.now_wall())
    j.append("spawn", key=[0, 1], host="localhost", rank=1, pid=None,
             ts=journal_mod.now_wall())
    # the crash interrupted the re-mesh: FAILURE journaled, no recovery
    j.append("exit", key=[0, 1], state="FAILURE", rank=1,
             host="localhost")
    j.close()

    driver = ElasticDriver(
        FixedHosts([HostInfo("localhost", 2)]),
        [sys.executable, "-c", "pass"], min_np=1, max_np=2,
        journal_dir=str(tmp_path), takeover=True)
    g = None
    try:
        g = driver._begin_takeover()
        with g.fail_lock:
            assert (0, 1) in g.lost_keys
        assert g.worker_lost.is_set()
        # the settle gate holds recovery while the (empty) notify scope
        # proves no survivor has re-registered yet...
        assert driver._adoption_settling(g) is True
        # ...and clears the moment the survivor's listener re-registers
        driver._kv.put("notify", "0", b"localhost:1")
        assert driver._adoption_settling(g) is False
    finally:
        if g is not None:
            g.teardown.set()
        driver._kv.stop()
        if driver._journal is not None:
            driver._journal.close()


# -- worker ride-through: the outage grace window ----------------------------
def test_outage_grace_suppresses_retry_exhausted_alarms(monkeypatch):
    """During a declared driver outage the world poll's retry site
    relabels to ``elastic.driver_outage`` and exhaustion stops ticking
    ``hvd_retry_exhausted_total`` — a takeover window is a declared
    condition, not a fault (ISSUE 20 satellite: zero false alarms)."""
    from horovod_tpu.common.retry import retry_call
    from horovod_tpu.elastic import outage
    from horovod_tpu.metrics.registry import default_registry

    monkeypatch.setenv("HVD_TPU_DRIVER_OUTAGE_GRACE_S", "60")
    outage.reset()
    reg = default_registry()
    site = "elastic.driver_outage"
    reg.unregister("hvd_retry_exhausted_total", {"site": site})

    def boom():
        raise ConnectionRefusedError("driver dead")

    outage.note_failure()
    assert outage.active() and not outage.exceeded()
    with pytest.raises(ConnectionRefusedError):
        retry_call(boom, site=site, retry_on=(OSError,), attempts=2,
                   base_delay_s=0.01, max_delay_s=0.02,
                   count_exhausted=not outage.enabled())
    # exhaustion during the grace window: NO alarm tick
    c = reg.get("hvd_retry_exhausted_total", {"site": site})
    assert c is None or c.value == 0
    # the outage gauge is aging instead
    gauge = reg.get("hvd_driver_outage_seconds")
    assert gauge is not None and gauge.value > 0
    # recovery zeroes the gauge and stamps the heal for `history`
    outage.note_success()
    assert not outage.active()
    assert reg.get("hvd_driver_outage_seconds").value == 0
    assert outage.last_recovery_perf() is not None
    # with the window DISABLED the same exhaustion alarms as before
    monkeypatch.setenv("HVD_TPU_DRIVER_OUTAGE_GRACE_S", "0")
    outage.reset()
    with pytest.raises(ConnectionRefusedError):
        retry_call(boom, site=site, retry_on=(OSError,), attempts=2,
                   base_delay_s=0.01, max_delay_s=0.02,
                   count_exhausted=not outage.enabled())
    assert reg.get("hvd_retry_exhausted_total",
                   {"site": site}).value == 1


def test_outage_exceeded_names_the_finding(monkeypatch):
    monkeypatch.setenv("HVD_TPU_DRIVER_OUTAGE_GRACE_S", "0.01")
    from horovod_tpu.elastic import outage
    outage.reset()
    outage.note_failure()
    time.sleep(0.05)
    assert outage.exceeded()
    outage.reset()


# -- launcher flags ----------------------------------------------------------
def test_launch_takeover_flag_requires_elastic():
    from horovod_tpu.runner.launch import parse_args
    with pytest.raises(SystemExit):
        parse_args(["--takeover", "-np", "2", "--", "true"])
    args = parse_args(["--takeover", "--min-np", "2",
                       "--driver-journal-dir", "/tmp/j", "--", "true"])
    assert args.takeover and args.driver_journal_dir == "/tmp/j"


# -- chaos acceptance A: driver killed mid-training (ride-through) -----------
@pytest.mark.slow
@needs_core
def test_chaos_driver_killed_mid_training_rides_through(tmp_path):
    """The driver is SIGKILLed by the chaos ``driver`` seam at a
    mid-training poll tick; the supervisor respawns it into a journal
    takeover.  The workers never notice: zero re-mesh episodes, zero
    restarts, the per-rank step counters strictly monotonic with no
    repeats, and the takeover is journaled."""
    jdir = tmp_path / "journal"
    log = tmp_path / "events.log"
    plan = {"seed": 7, "faults": [
        {"seam": "driver", "kind": "kill", "start": 6, "stop": 7,
         "marker": str(tmp_path / "driver_killed")},
    ]}
    prog = tmp_path / "train.py"
    prog.write_text(textwrap.dedent(f"""
        import os, sys, time
        sys.path.insert(0, {REPO!r})
        import numpy as np
        import horovod_tpu as hvd
        from horovod_tpu import elastic

        hvd.init()
        with open({str(log)!r}, "a") as f:
            f.write(f"BOOT rank={{hvd.rank()}} pid={{os.getpid()}}\\n")
        state = elastic.ObjectState(name="ride", step=0)

        @elastic.run
        def train(state):
            while state.step < 12:
                out = hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum,
                                    name=f"s{{hvd.size()}}.{{state.step}}")
                with open({str(log)!r}, "a") as f:
                    f.write(f"STEP rank={{hvd.rank()}} "
                            f"step={{state.step}}\\n")
                state.step += 1
                time.sleep(0.25)
                state.commit()
            return float(np.asarray(out)[0])

        out = train(state)
        assert out == float(hvd.size()), out
        with open({str(log)!r}, "a") as f:
            f.write(f"DONE rank={{hvd.rank()}} size={{hvd.size()}} "
                    f"step={{state.step}}\\n")
        hvd.shutdown()
    """))
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        "HVD_TPU_FAULT_PLAN": json.dumps(plan),
        "HVD_TPU_DRIVER_OUTAGE_GRACE_S": "120",
        "HVD_ELASTIC_CKPT": str(tmp_path),
    })
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch",
         "-H", "localhost:3", "--min-np", "3", "-np", "3",
         "--driver-journal-dir", str(jdir), "--",
         sys.executable, str(prog)],
        env=env, capture_output=True, text=True, timeout=240)
    lines = log.read_text().strip().splitlines() if log.exists() else []
    err = proc.stderr[-4000:]
    assert proc.returncode == 0, (err, lines)
    # the chaos kill actually happened, and the supervisor took over
    assert (tmp_path / "driver_killed").exists()
    assert "respawning into journal takeover" in err, err
    state = load(str(jdir / "driver_journal.jsonl"))
    assert state.takeovers, "takeover never journaled"
    assert state.clean_exit == 0
    # ZERO re-mesh: every worker booted exactly once and finished
    boots = [l for l in lines if l.startswith("BOOT")]
    dones = [l for l in lines if l.startswith("DONE")]
    assert len(boots) == 3, lines
    assert len(dones) == 3 and \
        all("size=3" in d and "step=12" in d for d in dones), dones
    # step counters strictly monotonic per rank, no repeats (a re-mesh
    # or restart would replay from the last commit)
    for r in range(3):
        steps = [int(l.split("step=")[1]) for l in lines
                 if l.startswith(f"STEP rank={r} ")]
        assert steps == sorted(set(steps)) == list(range(12)), (r, steps)


# -- chaos acceptance B: driver killed mid-re-mesh ---------------------------
@pytest.mark.slow
@needs_core
def test_chaos_driver_killed_mid_remesh_takeover_completes_recovery(
        tmp_path):
    """Rank 2 is SIGKILLed; while the driver's poll loop is stalled by
    the chaos seam (the failure classified + journaled, the recovery
    world NOT yet published) the driver itself is SIGKILLed.  The
    takeover driver must finish the dead driver's job from the journal:
    re-mark the worker lost, wait for survivors to re-register, spawn a
    replacement, and heal the job to full size — an in-place recovery
    under the SAME generation, not a generation restart."""
    jdir = tmp_path / "journal"
    log = tmp_path / "events.log"
    plan = {"seed": 7, "faults": [
        # rank 2 dies at step 2; the marker spares its replacement
        {"seam": "step", "kind": "kill", "rank": 2, "start": 2,
         "stop": 3, "marker": str(tmp_path / "worker_killed")},
        # freeze the poll loop long enough for the death to be
        # classified and journaled, then kill the driver in the SAME
        # fire() — before the loop body can publish the recovery.  The
        # marker matters: the takeover driver restarts its poll tick at
        # 0, so a marker-less stall would re-fire inside the takeover
        # and starve the survivors' shrink-wait window
        {"seam": "driver", "kind": "stall", "start": 4, "stop": 5,
         "stall_s": 4.0, "marker": str(tmp_path / "driver_stalled")},
        {"seam": "driver", "kind": "kill", "start": 4, "stop": 5,
         "marker": str(tmp_path / "driver_killed")},
    ]}
    prog = tmp_path / "train.py"
    prog.write_text(textwrap.dedent(f"""
        import os, sys, time
        sys.path.insert(0, {REPO!r})
        import numpy as np
        import horovod_tpu as hvd
        from horovod_tpu import chaos, elastic

        gen = int(os.environ.get("HVD_ELASTIC_GENERATION", 0))
        hvd.init()
        with open({str(log)!r}, "a") as f:
            f.write(f"BOOT rank={{hvd.rank()}} gen={{gen}} "
                    f"pid={{os.getpid()}}\\n")
        state = elastic.ObjectState(name="remesh", step=0)

        @elastic.run
        def train(state):
            while state.step < 10:
                chaos.step_tick(state.step)
                out = hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum,
                                    name=f"s{{hvd.size()}}.{{state.step}}")
                state.step += 1
                time.sleep(0.3)
                state.commit()
            return float(np.asarray(out)[0])

        out = train(state)
        assert out == float(hvd.size()), out
        with open({str(log)!r}, "a") as f:
            f.write(f"DONE rank={{hvd.rank()}} gen={{gen}} "
                    f"size={{hvd.size()}} step={{state.step}}\\n")
        hvd.shutdown()
    """))
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        "HVD_TPU_FAULT_PLAN": json.dumps(plan),
        "HVD_TPU_DRIVER_OUTAGE_GRACE_S": "120",
        # survivors re-register quickly on localhost; don't let the
        # settle deadline mask a registration that never comes
        "HVD_TPU_DRIVER_TAKEOVER_SETTLE_S": "30",
        # survivors must outlast supervisor respawn + journal replay +
        # adoption settling before giving up on the recovery world; the
        # 15s default was tuned for a driver that never goes away
        "HVD_ELASTIC_SHRINK_WAIT_S": "60",
        "HVD_ELASTIC_CKPT": str(tmp_path),
    })
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch",
         "-H", "localhost:3", "--min-np", "2", "-np", "3",
         "--reset-limit", "4",
         "--driver-journal-dir", str(jdir), "--",
         sys.executable, str(prog)],
        env=env, capture_output=True, text=True, timeout=300)
    lines = log.read_text().strip().splitlines() if log.exists() else []
    err = proc.stderr[-4000:]
    assert proc.returncode == 0, (err, lines)
    assert (tmp_path / "worker_killed").exists()
    assert (tmp_path / "driver_killed").exists()
    state = load(str(jdir / "driver_journal.jsonl"))
    assert state.takeovers, "takeover never journaled"
    assert state.clean_exit == 0
    # the job healed to FULL size: three finishers, one replacement boot
    dones = [l for l in lines if l.startswith("DONE")]
    boots = [l for l in lines if l.startswith("BOOT")]
    assert len(dones) == 3 and \
        all("size=3" in d and "step=10" in d for d in dones), (dones,
                                                              err)
    assert len(boots) >= 4, lines  # 3 originals + the replacement
    # takeover, not a second generation restart: the survivors finished
    # in the SAME process and generation they booted with
    survivor_dones = [d for d in dones if "gen=0" in d]
    assert len(survivor_dones) >= 2, dones
