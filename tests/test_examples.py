"""CI-executes the documented examples end-to-end under a REAL hvdrun
launch (reference analog: Buildkite running test/integration/
test_static_run.py over the example scripts). The examples themselves
stay TPU-first (no CPU forcing inside them); the harness wraps each in
a bootstrap that pins the CPU platform the same way every worker script
in tests/ does — this box's sitecustomize would otherwise re-register
the real TPU platform and make the workers contend for the one chip."""

import os
import subprocess
import sys

import pytest

from horovod_tpu.core import core_available

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

needs_core = pytest.mark.skipif(not core_available(),
                                reason="libhvdcore.so not built")


def _cpu_bootstrap(example_rel_path, argv=()):
    """A ``python -c`` command that forces the CPU platform, then runs
    the example as ``__main__`` with the given argv."""
    path = os.path.join(REPO, example_rel_path)
    return [
        sys.executable, "-c",
        "import os, sys\n"
        "os.environ.setdefault('XLA_FLAGS',"
        " '--xla_force_host_platform_device_count=1')\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        f"sys.argv = [{path!r}] + {list(argv)!r}\n"
        "import runpy\n"
        f"runpy.run_path({path!r}, run_name='__main__')\n",
    ]


def _hvdrun(launch_args, example, argv=(), timeout=420):
    cmd = ([sys.executable, "-m", "horovod_tpu.runner.launch"]
           + launch_args + _cpu_bootstrap(example, argv))
    return subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                          timeout=timeout)


@needs_core
def test_example_mnist_dp_two_procs():
    """examples/jax/mnist_dp.py under ``hvdrun -np 2``: the documented
    hello-world trains 3 epochs data-parallel and prints rank-0 loss."""
    r = _hvdrun(["-np", "2", "-H", "localhost:2"],
                "examples/jax/mnist_dp.py")
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "epoch 2: loss" in r.stdout, r.stdout[-2000:]


@needs_core
def test_example_torch_synthetic_benchmark_two_procs():
    """examples/torch/torch_synthetic_benchmark.py under 2-proc hvdrun
    with tiny shapes: must print the canonical img/sec lines."""
    r = _hvdrun(["-np", "2", "-H", "localhost:2"],
                "examples/torch/torch_synthetic_benchmark.py",
                argv=["--batch-size", "8", "--image-size", "16",
                      "--num-warmup-batches", "1",
                      "--num-batches-per-iter", "2", "--num-iters", "2"])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "Img/sec per process" in r.stdout, r.stdout[-2000:]


@needs_core
@pytest.mark.slow  # ~17s elastic launch; tier-1 budget (examples tier
#                    runs it unfiltered)
def test_example_keras_elastic_two_procs():
    """examples/keras/keras_elastic_mnist.py under an ELASTIC hvdrun
    (fixed 2-host world): model.fit with the elastic callback trio runs
    its 3 epochs and reports completion."""
    r = _hvdrun(["-np", "2", "--min-np", "2", "--max-np", "2",
                 "-H", "localhost:2"],
                "examples/keras/keras_elastic_mnist.py", timeout=600)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "done at epoch 3" in r.stdout, r.stdout[-2000:]
