"""Topology derivation battery (ISSUE 8): (num_hosts × local_devices)
structure from jax device process indices, the HVD_TPU_VIRTUAL_HOSTS
override the CPU-mesh parity tests lean on, and the axis_index_groups
the hierarchical collective consumes."""

import types

import pytest

from horovod_tpu.common.topology import (MeshTopology, detect_topology,
                                         flat_topology)
from horovod_tpu.parallel import build_mesh


@pytest.fixture(scope="module")
def mesh():
    return build_mesh(dp=-1)  # all 8 virtual devices


def test_flat_topology_is_not_hierarchical():
    t = flat_topology(8)
    assert (t.num_hosts, t.local_size) == (1, 8)
    assert not t.is_hierarchical
    assert t.world == 8


def test_hierarchical_groups_cover_axis_disjointly():
    t = MeshTopology(2, 4)
    assert t.is_hierarchical
    assert t.intra_groups() == [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert t.inter_groups() == [[0, 4], [1, 5], [2, 6], [3, 7]]
    # every axis index appears exactly once per grouping
    for groups in (t.intra_groups(), t.inter_groups()):
        flat = sorted(i for g in groups for i in g)
        assert flat == list(range(8))


def test_single_process_mesh_derives_flat(mesh):
    t = detect_topology(mesh, "dp")
    assert t == flat_topology(8)


@pytest.mark.parametrize("hosts,local", [(2, 4), (4, 2), (8, 1)])
def test_virtual_hosts_override(mesh, monkeypatch, hosts, local):
    monkeypatch.setenv("HVD_TPU_VIRTUAL_HOSTS", str(hosts))
    t = detect_topology(mesh, "dp")
    assert (t.num_hosts, t.local_size) == (hosts, local)


def test_virtual_hosts_not_dividing_is_ignored(mesh, monkeypatch):
    monkeypatch.setenv("HVD_TPU_VIRTUAL_HOSTS", "3")
    assert detect_topology(mesh, "dp") == flat_topology(8)


def test_detect_without_mesh_uses_axis_size(monkeypatch):
    assert detect_topology(n=8) == flat_topology(8)
    monkeypatch.setenv("HVD_TPU_VIRTUAL_HOSTS", "2")
    assert detect_topology(n=8) == MeshTopology(2, 4)
    assert detect_topology(n=1) == flat_topology(1)


def _fake_mesh(procs):
    """A mesh-shaped stub whose 'dp' axis devices carry the given
    process indices (detect_topology reads only axis_names/devices)."""
    import numpy as np
    devs = np.array([types.SimpleNamespace(process_index=p)
                     for p in procs], dtype=object)
    return types.SimpleNamespace(axis_names=("dp",), devices=devs)


def test_process_indices_contiguous_derive_hierarchy():
    t = detect_topology(_fake_mesh([0, 0, 0, 0, 1, 1, 1, 1]), "dp")
    assert t == MeshTopology(2, 4)
    t = detect_topology(_fake_mesh([0, 0, 1, 1, 2, 2, 3, 3]), "dp")
    assert t == MeshTopology(4, 2)


def test_process_indices_interleaved_degrade_to_flat():
    # a host's devices split across the axis would make the 'intra'
    # hop cross the slow fabric twice — refuse the hierarchy
    assert detect_topology(
        _fake_mesh([0, 1, 0, 1, 0, 1, 0, 1]), "dp") == flat_topology(8)


def test_process_indices_uneven_degrade_to_flat():
    assert detect_topology(
        _fake_mesh([0, 0, 0, 1, 1, 2, 2, 2]), "dp") == flat_topology(8)
