"""Unit tests for the step time-series history layer
(docs/OBSERVABILITY.md "Step time-series history"): ring bounds, JSONL
persistence + rotation + torn-tail tolerance, the sampling stride, the
``python -m horovod_tpu.metrics`` CLI (history table + one-shot top
frame), and the bench trajectory gate in ``ci/check_bench.py``."""

import json
import os
import subprocess
import sys

import pytest

from horovod_tpu.metrics.timeseries import (SeriesWriter,
                                            StepSeriesRecorder,
                                            TimeSeriesRing, read_series)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- ring -------------------------------------------------------------------

def test_ring_bounded_drop_oldest():
    ring = TimeSeriesRing(capacity=3)
    for i in range(5):
        ring.append({"step": i})
    assert [p["step"] for p in ring.points()] == [2, 3, 4]
    assert [p["step"] for p in ring.points(last_n=2)] == [3, 4]
    assert len(ring) == 3


# -- JSONL writer / reader --------------------------------------------------

def test_writer_roundtrip_and_rank_tagging(tmp_path):
    d = str(tmp_path)
    for rank in (0, 1):
        w = SeriesWriter(d, rank=rank)
        for i in range(3):
            assert w.write({"ts": rank * 100 + i, "step": i})
        w.close()
    mine = read_series(d, rank=1)
    assert [p["step"] for p in mine] == [0, 1, 2]
    assert all(p["rank"] == 1 for p in mine)
    everyone = read_series(d)
    assert len(everyone) == 6
    assert [p["ts"] for p in everyone] == sorted(
        p["ts"] for p in everyone)  # time-sorted across ranks


def test_writer_rotation_keeps_one_generation(tmp_path):
    w = SeriesWriter(str(tmp_path), rank=0, max_bytes=200)
    for i in range(50):
        w.write({"step": i, "pad": "x" * 20})
    w.close()
    assert os.path.exists(w.path)
    assert os.path.exists(w.path + ".1")
    assert os.path.getsize(w.path) <= 200 + 64  # bounded, not unbounded
    points = read_series(str(tmp_path), rank=0)
    # rotated generation read first: order preserved, newest point last
    assert points[-1]["step"] == 49
    assert [p["step"] for p in points] == sorted(
        p["step"] for p in points)


def test_reader_skips_torn_tail_line(tmp_path):
    path = tmp_path / "obs_rank0.jsonl"
    path.write_text(json.dumps({"step": 1}) + "\n"
                    + json.dumps({"step": 2}) + "\n"
                    + '{"step": 3, "trunc')  # crash mid-append
    points = read_series(str(tmp_path), rank=0)
    assert [p["step"] for p in points] == [1, 2]


def test_recorder_sampling_stride_and_env_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("HVD_TPU_OBS_DIR", str(tmp_path))
    monkeypatch.setenv("HVD_TPU_OBS_SAMPLE_EVERY", "2")
    rec = StepSeriesRecorder(rank=3)
    for i in range(6):
        rec.record_step(i + 1, 0.01 * (i + 1), units=32)
    rec.close()
    assert len(rec.ring) == 3  # steps 1, 3, 5 sampled
    points = read_series(str(tmp_path), rank=3)
    assert [p["step"] for p in points] == [1, 3, 5]
    assert points[0]["units_per_s"] == pytest.approx(3200, rel=0.01)


def test_step_timer_feeds_the_series(monkeypatch, tmp_path):
    from horovod_tpu.metrics import timeseries
    from horovod_tpu.metrics.registry import Registry
    from horovod_tpu.train.callbacks import StepTimer
    monkeypatch.setenv("HVD_TPU_OBS_DIR", str(tmp_path))
    timeseries.reset()
    try:
        timer = StepTimer(unit="images", registry=Registry())
        for _ in range(2):
            with timer.step(units=8):
                pass
        points = timeseries.recorder().ring.points()
        assert [p["step"] for p in points[-2:]] == [1, 2]
        assert read_series(str(tmp_path))  # persisted too
    finally:
        timeseries.reset()


# -- CLI --------------------------------------------------------------------

def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "horovod_tpu.metrics", *args],
        capture_output=True, text=True, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), timeout=120)


def test_cli_history_table_and_json(tmp_path):
    w = SeriesWriter(str(tmp_path), rank=0)
    for i in range(4):
        w.write({"ts": 1700000000 + i, "step": i + 1,
                 "step_time_s": 0.25, "units_per_s": 128.0})
    w.close()
    out = _cli("history", "--dir", str(tmp_path), "--last", "3")
    assert out.returncode == 0, out.stderr
    assert "step_time_s" in out.stdout and "0.25" in out.stdout
    assert "3 point(s)" in out.stdout
    js = _cli("history", "--dir", str(tmp_path), "--json")
    assert js.returncode == 0
    assert len(js.stdout.strip().splitlines()) == 4
    empty = _cli("history", "--dir", str(tmp_path / "nope"))
    assert empty.returncode == 1


def test_cli_history_remesh_honors_json_and_last(tmp_path):
    """--remesh composes with --json (JSONL out, not the table) and
    --last (episode slicing) like the step view does."""
    w = SeriesWriter(str(tmp_path), rank=0)
    for i in range(3):
        w.write({"ts": 1700000000 + i, "trigger": f"t{i}",
                 "remesh": {"drain": 0.1}, "remesh_total_s": 0.5,
                 "complete": True})
    w.write({"ts": 1700000009, "step": 1, "step_time_s": 0.2})
    w.close()
    js = _cli("history", "--dir", str(tmp_path), "--remesh", "--json")
    assert js.returncode == 0, js.stderr
    lines = [json.loads(l) for l in js.stdout.strip().splitlines()]
    assert len(lines) == 3 and all("remesh" in p for p in lines)
    last = _cli("history", "--dir", str(tmp_path), "--remesh",
                "--last", "1")
    assert last.returncode == 0
    assert "t2" in last.stdout and "t0" not in last.stdout


def test_cli_top_renders_fleet_frame():
    """One-shot frame against a live exporter serving a fleet view."""
    from horovod_tpu.metrics.exporter import MetricsExporter
    from horovod_tpu.metrics.fleet import FleetAggregator
    from horovod_tpu.metrics.registry import Registry
    reg = Registry()
    reg.counter("hvd_steps_total").inc(12)
    reg.histogram("hvd_step_time_seconds").observe(0.02)
    exp = MetricsExporter(registry=reg, port=0)
    exp.fleet = FleetAggregator(rank=0, size=1, base_port=9090,
                                registry=reg, push_interval=60.0)
    exp.start()
    try:
        out = _cli("top", "--url", f"http://127.0.0.1:{exp.port}",
                   "--once")
        assert out.returncode == 0, out.stderr
        assert "ranks reporting : 1/1" in out.stdout
        assert "steps total     : 12" in out.stdout
    finally:
        exp.stop()


def test_cli_top_render_is_pure():
    from horovod_tpu.metrics.__main__ import parse_prometheus, render_top
    series = parse_prometheus(
        "hvd_fleet_size 4\nhvd_fleet_ranks_reporting 3\n"
        "hvd_fleet_straggler_rank 2\n"
        'hvd_fleet_rank_step_time_seconds{rank="2"} 0.5\n'
        'hvd_anomaly_total{kind="step_time_drift"} 2\n'
        "# a comment\nbogus line\n")
    frame = render_top(series, "test")
    assert "3/4" in frame and "RANKS MISSING" in frame
    assert "straggler rank  : 2" in frame
    assert "step_time_drift×2" in frame
    assert "rank    2" in frame  # per-rank bar chart row


# -- bench trajectory gate --------------------------------------------------

def _check_bench():
    sys.path.insert(0, os.path.join(REPO, "ci"))
    try:
        import check_bench
        return check_bench
    finally:
        sys.path.pop(0)


def test_trajectory_gate_flags_drift_not_noise():
    cb = _check_bench()
    flat = [0.1] * 12
    noisy = [0.1, 0.12, 0.09, 0.11, 0.1, 0.13, 0.1, 0.09, 0.12, 0.11]
    drifting = [0.1] * 4 + [0.12] * 4 + [0.2] * 4  # tail 2x the head
    assert cb.check_trajectory(flat) is None
    assert cb.check_trajectory(noisy) is None
    assert cb.check_trajectory(drifting) is not None
    assert cb.check_trajectory([0.1] * 3) is None  # too short to judge
    assert cb.check_trajectory("not-a-list") is not None
    assert cb.check_trajectory([0.1, None, 0.1]) is not None


def test_trajectory_cli_gate(tmp_path):
    good = tmp_path / "good.json"
    bad = tmp_path / "bad.json"
    good.write_text(json.dumps(
        {"value": 1.0, "step_time_series": [0.1] * 12}))
    bad.write_text(json.dumps(
        {"value": 1.0, "step_time_series": [0.1] * 6 + [0.3] * 6}))
    base = [sys.executable, os.path.join(REPO, "ci", "check_bench.py"),
            "--trajectory"]
    ok = subprocess.run(base + [str(good)], capture_output=True,
                        text=True, timeout=60)
    assert ok.returncode == 0, ok.stdout
    fail = subprocess.run(base + [str(bad)], capture_output=True,
                          text=True, timeout=60)
    assert fail.returncode == 1
    assert "drift" in fail.stdout
