"""Worker: the WHOLE train step (including cross-process gradient sync) runs
under jax.jit — the io_callback bridge to the negotiating core (SURVEY §7
hard part (d))."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

import horovod_tpu as hvd  # noqa: E402


def main():
    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    rng = np.random.RandomState(0)
    W_true = rng.randn(8, 2).astype(np.float32)
    X = rng.randn(32, 8).astype(np.float32)
    Y = X @ W_true
    shard = 32 // size
    Xs = jnp.asarray(X[rank * shard:(rank + 1) * shard])
    Ys = jnp.asarray(Y[rank * shard:(rank + 1) * shard])

    params = {"w": jnp.zeros((8, 2))}
    tx = hvd.DistributedOptimizer(optax.sgd(0.1), host_sync_in_jit=True)
    st = tx.init(params)

    @jax.jit
    def step(params, st, x, y):
        loss, g = jax.value_and_grad(
            lambda p: jnp.mean((x @ p["w"] - y) ** 2))(params)
        u, st = tx.update(g, st, params)  # io_callback -> core allreduce
        return optax.apply_updates(params, u), st, loss

    for _ in range(40):
        params, st, loss = step(params, st, Xs, Ys)
        jax.block_until_ready(loss)

    # must equal serial full-batch training (equal shards)
    ref = {"w": jnp.zeros((8, 2))}
    rtx = optax.sgd(0.1)
    rst = rtx.init(ref)
    gf = jax.jit(jax.value_and_grad(
        lambda p, x, y: jnp.mean((x @ p["w"] - y) ** 2)))
    for _ in range(40):
        _, g = gf(ref, jnp.asarray(X), jnp.asarray(Y))
        u, rst = rtx.update(g, rst, ref)
        ref = optax.apply_updates(ref, u)
    np.testing.assert_allclose(np.asarray(params["w"]),
                               np.asarray(ref["w"]), rtol=1e-5, atol=1e-6)
    print(f"rank {rank}: jitted-step distributed == serial ✓", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
