"""Spark estimator API tests (reference analog: test/integration/
test_spark.py estimator tests). pyspark is not in this image, so the
DataFrame boundary is exercised two ways: pandas directly (the
estimators duck-type ``toPandas``) and ``tests/fake_pyspark``'s
partitioned DataFrame whose ``rdd.mapPartitionsWithIndex`` runs one
subprocess per partition like a Spark executor; training runs under the
local launcher — the same code path a Spark cluster takes after the
barrier-job handshake."""

import os
import sys

import numpy as np
import pandas as pd
import pytest

from horovod_tpu.core import core_available
from horovod_tpu.spark import (HorovodEstimator, KerasEstimator, LocalStore,
                               TorchEstimator)

needs_core = pytest.mark.skipif(not core_available(),
                                reason="libhvdcore.so not built")

FAKE_PYSPARK = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "fake_pyspark")


@pytest.fixture
def fake_pyspark(monkeypatch):
    monkeypatch.syspath_prepend(FAKE_PYSPARK)
    for mod in [m for m in sys.modules if m.split(".")[0] == "pyspark"]:
        monkeypatch.delitem(sys.modules, mod, raising=False)
    yield
    for mod in [m for m in sys.modules if m.split(".")[0] == "pyspark"]:
        sys.modules.pop(mod, None)


def _regression_df(n=80, d=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    w = rng.randn(d).astype(np.float32)
    y = X @ w + 0.01 * rng.randn(n).astype(np.float32)
    df = pd.DataFrame({f"x{i}": X[:, i] for i in range(d)})
    df["y"] = y
    return df


def test_params_surface(tmp_path):
    """Reference-style setX/getX accessors returning self."""
    est = TorchEstimator(feature_cols=["a"], label_cols=["b"],
                         store=LocalStore(str(tmp_path)))
    assert est.setEpochs(7) is est
    assert est.getEpochs() == 7
    assert est.setBatchSize(16).getBatchSize() == 16
    assert est.getFeatureCols() == ["a"]


@needs_core
def test_torch_estimator_fit_transform(tmp_path):
    torch = pytest.importorskip("torch")
    df = _regression_df()
    model = torch.nn.Linear(4, 1)
    est = TorchEstimator(
        model=model, optimizer="SGD", loss="MSELoss",
        feature_cols=[f"x{i}" for i in range(4)], label_cols=["y"],
        store=LocalStore(str(tmp_path)), num_proc=2, epochs=8,
        batch_size=16, learning_rate=0.05, validation=0.2, verbose=0)
    trained = est.fit(df)
    assert trained.history["loss"][-1] < trained.history["loss"][0] * 0.2
    out = trained.transform(df.head(10))
    assert "y__output" in out.columns
    err = np.mean((out["y__output"].to_numpy()
                   - out["y"].to_numpy()) ** 2)
    assert err < 0.5


@needs_core
def test_torch_estimator_sample_weight_col(tmp_path):
    """sample_weight_col: zero-weight rows (with deliberately corrupted
    labels) must not influence training (reference: sample_weight_col)."""
    torch = pytest.importorskip("torch")
    df = _regression_df(n=80)
    df["w"] = 1.0
    corrupt = np.arange(0, 80, 2)
    df.loc[corrupt, "y"] = 100.0   # poison...
    df.loc[corrupt, "w"] = 0.0     # ...but weightless
    est = TorchEstimator(
        model=torch.nn.Linear(4, 1), optimizer="SGD", loss="MSELoss",
        feature_cols=[f"x{i}" for i in range(4)], label_cols=["y"],
        store=LocalStore(str(tmp_path)), num_proc=2, epochs=10,
        batch_size=16, learning_rate=0.05, verbose=0,
        sample_weight_col="w")
    trained = est.fit(df)
    clean = df[df["w"] == 1.0]
    out = trained.transform(clean.head(10))
    err = np.mean((out["y__output"].to_numpy()
                   - out["y"].to_numpy()) ** 2)
    assert err < 0.5, err  # poisoned rows would blow this up


@needs_core
def test_keras_estimator_sample_weight_col(tmp_path):
    """Keras backend: the weight column rides to model.fit's
    sample_weight on each worker."""
    tf = pytest.importorskip("tensorflow")
    df = _regression_df(n=60)
    df["w"] = 1.0
    corrupt = np.arange(0, 60, 2)
    df.loc[corrupt, "y"] = 100.0
    df.loc[corrupt, "w"] = 0.0
    model = tf.keras.Sequential(
        [tf.keras.layers.Input((4,)), tf.keras.layers.Dense(1)])
    est = KerasEstimator(
        model=model, optimizer="SGD", loss="mse",
        feature_cols=[f"x{i}" for i in range(4)], label_cols=["y"],
        store=LocalStore(str(tmp_path)), num_proc=2, epochs=8,
        batch_size=16, learning_rate=0.05, verbose=0,
        sample_weight_col="w")
    trained = est.fit(df)
    clean = df[df["w"] == 1.0]
    out = trained.transform(clean.head(10))
    err = np.mean((out["y__output"].to_numpy()
                   - out["y"].to_numpy()) ** 2)
    assert err < 1.0, err


@needs_core
def test_torch_estimator_transformation_fn(tmp_path):
    """transformation_fn (cloudpickled by value) runs on each worker's
    shard before training: here it UNDOES a deliberate label corruption,
    so convergence proves it really executed (reference param)."""
    torch = pytest.importorskip("torch")
    df = _regression_df()
    df["y"] = df["y"] + 1000.0  # corrupted at materialization time

    def fix(pdf):
        out = pdf.copy()
        out["y"] = out["y"] - 1000.0
        return out

    est = TorchEstimator(
        model=torch.nn.Linear(4, 1), optimizer="SGD", loss="MSELoss",
        feature_cols=[f"x{i}" for i in range(4)], label_cols=["y"],
        store=LocalStore(str(tmp_path)), num_proc=2, epochs=8,
        batch_size=16, learning_rate=0.05, verbose=0,
        transformation_fn=fix)
    trained = est.fit(df)
    out = trained.transform(df.head(10))
    err = np.mean((out["y__output"].to_numpy()
                   - (out["y"].to_numpy() - 1000.0)) ** 2)
    assert err < 0.5, err  # without the transform, labels are +1000 off


@needs_core
def test_keras_estimator_transformation_fn(tmp_path):
    """Keras backend: the transform runs before sample-weight extraction
    too — it SETS the weight column that zeroes poisoned rows."""
    tf = pytest.importorskip("tensorflow")
    df = _regression_df(n=60)
    corrupt = np.arange(0, 60, 2)
    df.loc[corrupt, "y"] = 100.0

    def add_weights(pdf):
        out = pdf.copy()
        out["w"] = (out["y"] < 50.0).astype("float32")
        return out

    model = tf.keras.Sequential(
        [tf.keras.layers.Input((4,)), tf.keras.layers.Dense(1)])
    est = KerasEstimator(
        model=model, optimizer="SGD", loss="mse",
        feature_cols=[f"x{i}" for i in range(4)], label_cols=["y"],
        store=LocalStore(str(tmp_path)), num_proc=2, epochs=8,
        batch_size=16, learning_rate=0.05, verbose=0,
        sample_weight_col="w", transformation_fn=add_weights)
    trained = est.fit(df)
    clean = df[df["y"] < 50.0]
    out = trained.transform(clean.head(10))
    err = np.mean((out["y__output"].to_numpy()
                   - out["y"].to_numpy()) ** 2)
    assert err < 1.0, err


@pytest.mark.slow  # ~30s: two full fits; tier-1 budget (integration
#                    tier runs it unfiltered)
@needs_core
def test_torch_estimator_train_steps_cap(tmp_path):
    """train_steps_per_epoch bounds each epoch's optimizer steps
    (reference param of the same name): with identical seeds and epochs,
    the capped fit (1 step/epoch) must end at a clearly WORSE loss than
    the uncapped one — a cap regression would make them equal."""
    torch = pytest.importorskip("torch")
    df = _regression_df(n=160)

    def run(cap, sub):
        torch.manual_seed(0)
        est = TorchEstimator(
            model=torch.nn.Linear(4, 1), optimizer="SGD", loss="MSELoss",
            feature_cols=[f"x{i}" for i in range(4)], label_cols=["y"],
            store=LocalStore(str(tmp_path / sub)), num_proc=2, epochs=2,
            batch_size=16, learning_rate=0.05, verbose=0,
            train_steps_per_epoch=cap)
        return est.fit(df)

    capped = run(1, "capped")      # 2 steps total per worker
    full = run(None, "full")       # 10 steps total per worker
    assert len(capped.history["loss"]) == 2
    assert capped.history["loss"][-1] > full.history["loss"][-1] * 2, (
        capped.history["loss"], full.history["loss"])


@needs_core
def test_torch_estimator_metrics_param(tmp_path):
    """The metrics param rides to the workers (cloudpickled BY VALUE, as
    a user's notebook-defined metric would) and produces per-epoch,
    rank-averaged history entries under the callable's __name__
    (reference: torch estimator metrics param)."""
    torch = pytest.importorskip("torch")

    def mae(pred, target):
        import torch
        return torch.mean(torch.abs(pred - target))

    df = _regression_df()
    est = TorchEstimator(
        model=torch.nn.Linear(4, 1), optimizer="SGD", loss="MSELoss",
        feature_cols=[f"x{i}" for i in range(4)], label_cols=["y"],
        store=LocalStore(str(tmp_path)), num_proc=2, epochs=4,
        batch_size=16, learning_rate=0.05, verbose=0, metrics=[mae])
    trained = est.fit(df)
    assert len(trained.history["mae"]) == 4
    assert trained.history["mae"][-1] < trained.history["mae"][0]
    assert all(np.isfinite(v) for v in trained.history["mae"])


class _EpochStamp:
    """User callback double: proves the estimator's callbacks param rides
    into model.fit on the workers (cloudpickled, keras-API via __call__
    construction on the worker to avoid pickling live tf state)."""

    def __new__(cls, path):
        import tensorflow as tf

        class _Impl(tf.keras.callbacks.Callback):
            def on_epoch_end(self, epoch, logs=None):
                with open(path, "a") as f:
                    f.write(f"{epoch}\n")

        return _Impl()


@needs_core
def test_keras_estimator_fit_transform(tmp_path):
    tf = pytest.importorskip("tensorflow")
    df = _regression_df(n=60)
    model = tf.keras.Sequential(
        [tf.keras.layers.Input((4,)), tf.keras.layers.Dense(1)])
    stamp = str(tmp_path / "epochs.log")
    est = KerasEstimator(
        model=model, optimizer="SGD", loss="mse",
        feature_cols=[f"x{i}" for i in range(4)], label_cols=["y"],
        store=LocalStore(str(tmp_path)), num_proc=2, epochs=6,
        batch_size=16, learning_rate=0.05, verbose=0,
        callbacks=[_EpochStamp(stamp)])
    trained = est.fit(df)
    assert trained.history["loss"][-1] < trained.history["loss"][0]
    out = trained.transform(df.head(8))
    assert "y__output" in out.columns
    assert np.isfinite(out["y__output"].to_numpy()).all()
    # the user callback ran on the workers: 6 epochs x 2 ranks
    with open(stamp) as f:
        assert len(f.read().split()) == 12


def test_filesystem_store_contract_memory_scheme():
    """FilesystemStore over fsspec's memory:// — the full Store contract
    (join/makedirs/write/read/exists) through a non-local scheme
    (reference: fsspec-backed stores, ``spark/common/store.py:36-530``)."""
    pytest.importorskip("fsspec")
    from horovod_tpu.spark.store import FilesystemStore, Store

    store = Store.create("memory://est_test")
    assert isinstance(store, FilesystemStore)
    ckpt = store.get_checkpoint_path("run1")
    assert "://" not in ckpt or ckpt.startswith("memory")
    store.makedirs(ckpt)
    p = store.join(ckpt, "weights.bin")
    assert not store.exists(p)
    store.write(p, b"\x00\x01\x02")
    assert store.exists(p)
    assert store.read(p) == b"\x00\x01\x02"
    store.write(store.join(ckpt, "spec.json"), b'{"a": 1}')
    assert store.read_text(store.join(ckpt, "spec.json")) == '{"a": 1}'
    # path algebra must be pure string ops (object-store keys, not os.path)
    assert store.join("a/b", "c", "d") == "a/b/c/d"


@needs_core
def test_torch_estimator_over_nonlocal_store(tmp_path):
    """Estimator fit+transform where EVERY artifact (parquet shards, model
    spec, checkpoints) moves through a FilesystemStore on an fsspec
    filesystem faking a remote scheme (DirFileSystem: fs-relative keys, so
    any os.path leakage in the estimator would break loudly). The store is
    pickled into the worker subprocesses like a gs:// store would be."""
    torch = pytest.importorskip("torch")
    fsspec = pytest.importorskip("fsspec")
    from fsspec.implementations.dirfs import DirFileSystem
    from horovod_tpu.spark.store import FilesystemStore

    root = tmp_path / "fake_bucket"
    root.mkdir()
    store = FilesystemStore("artifacts", fs=DirFileSystem(str(root)))

    df = _regression_df()
    est = TorchEstimator(
        model=torch.nn.Linear(4, 1), optimizer="SGD", loss="MSELoss",
        feature_cols=[f"x{i}" for i in range(4)], label_cols=["y"],
        store=store, num_proc=2, epochs=8, batch_size=16,
        learning_rate=0.05, validation=0.2, verbose=0)
    trained = est.fit(df)
    assert trained.history["loss"][-1] < trained.history["loss"][0] * 0.2
    out = trained.transform(df.head(10))
    assert "y__output" in out.columns
    # the artifacts really live under the fake bucket, not a local-path
    # side channel
    run_id = est.getRunId()
    assert (root / "artifacts" / "runs" / run_id / "checkpoint"
            / "final.pkl").exists()
    assert (root / "artifacts" / f"intermediate_train_data.{run_id}"
            / "data.parquet").exists()


@needs_core
@pytest.mark.slow  # ~19s distributed fit; tier-1 budget (integration
#                    tier runs it unfiltered)
def test_estimator_distributed_materialization(fake_pyspark, tmp_path):
    """A partitioned (fake-)Spark DataFrame is materialized by the
    EXECUTORS — one parquet shard per partition written through the
    pickled Store by subprocess tasks — and the dataset never moves
    through the driver (``toPandas`` is never called). Validation split
    and shuffle happen per partition; workers read disjoint shard sets
    by rank (reference: ``spark/common/util.py`` distributed prepare)."""
    torch = pytest.importorskip("torch")
    from pyspark.sql import SparkSession

    df_pandas = _regression_df(n=80)
    spark = SparkSession.builder.getOrCreate()
    sdf = spark.createDataFrame(df_pandas).repartition(4)

    est = TorchEstimator(
        model=torch.nn.Linear(4, 1), optimizer="SGD", loss="MSELoss",
        feature_cols=[f"x{i}" for i in range(4)], label_cols=["y"],
        store=LocalStore(str(tmp_path)), num_proc=2, epochs=8,
        batch_size=16, learning_rate=0.05, validation=0.25, verbose=0)
    trained = est.fit(sdf)

    assert sdf.toPandas_calls == 0  # the driver never collected the data
    run_id = est.getRunId()
    store = est.getStore()
    train_files = store.ls(store.get_train_data_path(run_id))
    val_files = store.ls(store.get_val_data_path(run_id))
    assert len([p for p in train_files if p.endswith(".parquet")]) == 4
    assert len([p for p in val_files if p.endswith(".parquet")]) == 4
    # split sizes: 25% of each 20-row partition -> 15 train / 5 val each
    import pandas as pd2
    n_train = sum(len(pd2.read_parquet(p)) for p in train_files)
    n_val = sum(len(pd2.read_parquet(p)) for p in val_files)
    assert (n_train, n_val) == (60, 20)
    assert trained.history["loss"][-1] < trained.history["loss"][0] * 0.2
    out = trained.transform(df_pandas.head(10))
    assert "y__output" in out.columns


@pytest.mark.slow  # ~38s: two distributed fits; tier-1 budget
#                    (integration tier runs it unfiltered)
@needs_core
def test_run_id_reuse_clears_stale_shards(fake_pyspark, tmp_path):
    """Refitting with the SAME run_id must not mix the previous fit's
    shards into the new dataset: fit clears the data dirs first (the
    shard glob in read_shard would otherwise pick up leftovers from a
    different partition count or the single-parquet pandas path)."""
    torch = pytest.importorskip("torch")
    from pyspark.sql import SparkSession

    spark = SparkSession.builder.getOrCreate()
    est = TorchEstimator(
        model=torch.nn.Linear(4, 1), optimizer="SGD", loss="MSELoss",
        feature_cols=[f"x{i}" for i in range(4)], label_cols=["y"],
        store=LocalStore(str(tmp_path)), num_proc=2, epochs=1,
        batch_size=16, learning_rate=0.05, verbose=0, run_id="fixed")
    est.fit(spark.createDataFrame(_regression_df(n=80)).repartition(8))
    store = est.getStore()
    train_dir = store.get_train_data_path("fixed")
    assert len(store.ls(train_dir)) == 8
    est.fit(spark.createDataFrame(_regression_df(n=40)).repartition(2))
    files = store.ls(train_dir)
    assert len(files) == 2  # no part-00002..7 leftovers
    import pandas as pd2
    assert sum(len(pd2.read_parquet(p)) for p in files) == 40


def test_read_shard_file_level_assignment(tmp_path):
    """With >= size part files, ranks read DISJOINT file sets; the union
    covers every row exactly once."""
    from horovod_tpu.spark.estimator import read_shard
    store = LocalStore(str(tmp_path))
    path = store.join(str(tmp_path), "shards")
    store.makedirs(path)
    all_ids = []
    for i in range(5):
        pdf = pd.DataFrame({"id": np.arange(i * 10, i * 10 + 10)})
        import io
        buf = io.BytesIO()
        pdf.to_parquet(buf)
        store.write(store.join(path, f"part-{i:05d}.parquet"),
                    buf.getvalue())
        all_ids.extend(pdf["id"].tolist())
    shard0 = read_shard(store, path, 0, 2)
    shard1 = read_shard(store, path, 1, 2)
    got = sorted(shard0["id"].tolist() + shard1["id"].tolist())
    assert got == sorted(all_ids)
    assert set(shard0["id"]).isdisjoint(set(shard1["id"]))
    # files 0,2,4 -> rank 0 (30 rows); files 1,3 -> rank 1 (20 rows)
    assert (len(shard0), len(shard1)) == (30, 20)


def test_estimator_single_proc_no_core(tmp_path):
    """num_proc=1 works without the native core (LocalBackend)."""
    torch = pytest.importorskip("torch")
    df = _regression_df(n=40)
    est = TorchEstimator(
        model=torch.nn.Linear(4, 1), optimizer="SGD", loss="MSELoss",
        feature_cols=[f"x{i}" for i in range(4)], label_cols=["y"],
        store=LocalStore(str(tmp_path)), num_proc=1, epochs=5,
        batch_size=8, learning_rate=0.05, verbose=0)
    trained = est.fit(df)
    assert trained.history["loss"][-1] < trained.history["loss"][0]


def test_reference_module_path_aliases():
    """Reference import paths horovod.spark.torch / horovod.spark.keras
    resolve under horovod_tpu the same way."""
    from horovod_tpu.spark.keras import KerasEstimator, KerasModel
    from horovod_tpu.spark.torch import TorchEstimator, TorchModel
    import horovod_tpu.spark as s
    assert KerasEstimator is s.KerasEstimator
    assert TorchEstimator is s.TorchEstimator
    assert KerasModel is s.KerasModel and TorchModel is s.TorchModel
