"""Spark estimator API tests (reference analog: test/integration/
test_spark.py estimator tests). pyspark is not in this image, so the
DataFrame boundary is exercised with pandas (the estimators duck-type
``toPandas``) and training runs under the local launcher — the same code
path a Spark cluster takes after the barrier-job handshake."""

import numpy as np
import pandas as pd
import pytest

from horovod_tpu.core import core_available
from horovod_tpu.spark import (HorovodEstimator, KerasEstimator, LocalStore,
                               TorchEstimator)

needs_core = pytest.mark.skipif(not core_available(),
                                reason="libhvdcore.so not built")


def _regression_df(n=80, d=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    w = rng.randn(d).astype(np.float32)
    y = X @ w + 0.01 * rng.randn(n).astype(np.float32)
    df = pd.DataFrame({f"x{i}": X[:, i] for i in range(d)})
    df["y"] = y
    return df


def test_params_surface(tmp_path):
    """Reference-style setX/getX accessors returning self."""
    est = TorchEstimator(feature_cols=["a"], label_cols=["b"],
                         store=LocalStore(str(tmp_path)))
    assert est.setEpochs(7) is est
    assert est.getEpochs() == 7
    assert est.setBatchSize(16).getBatchSize() == 16
    assert est.getFeatureCols() == ["a"]


@needs_core
def test_torch_estimator_fit_transform(tmp_path):
    torch = pytest.importorskip("torch")
    df = _regression_df()
    model = torch.nn.Linear(4, 1)
    est = TorchEstimator(
        model=model, optimizer="SGD", loss="MSELoss",
        feature_cols=[f"x{i}" for i in range(4)], label_cols=["y"],
        store=LocalStore(str(tmp_path)), num_proc=2, epochs=8,
        batch_size=16, learning_rate=0.05, validation=0.2, verbose=0)
    trained = est.fit(df)
    assert trained.history["loss"][-1] < trained.history["loss"][0] * 0.2
    out = trained.transform(df.head(10))
    assert "y__output" in out.columns
    err = np.mean((out["y__output"].to_numpy()
                   - out["y"].to_numpy()) ** 2)
    assert err < 0.5


@needs_core
def test_keras_estimator_fit_transform(tmp_path):
    tf = pytest.importorskip("tensorflow")
    df = _regression_df(n=60)
    model = tf.keras.Sequential(
        [tf.keras.layers.Input((4,)), tf.keras.layers.Dense(1)])
    est = KerasEstimator(
        model=model, optimizer="SGD", loss="mse",
        feature_cols=[f"x{i}" for i in range(4)], label_cols=["y"],
        store=LocalStore(str(tmp_path)), num_proc=2, epochs=6,
        batch_size=16, learning_rate=0.05, verbose=0)
    trained = est.fit(df)
    assert trained.history["loss"][-1] < trained.history["loss"][0]
    out = trained.transform(df.head(8))
    assert "y__output" in out.columns
    assert np.isfinite(out["y__output"].to_numpy()).all()


def test_estimator_single_proc_no_core(tmp_path):
    """num_proc=1 works without the native core (LocalBackend)."""
    torch = pytest.importorskip("torch")
    df = _regression_df(n=40)
    est = TorchEstimator(
        model=torch.nn.Linear(4, 1), optimizer="SGD", loss="MSELoss",
        feature_cols=[f"x{i}" for i in range(4)], label_cols=["y"],
        store=LocalStore(str(tmp_path)), num_proc=1, epochs=5,
        batch_size=8, learning_rate=0.05, verbose=0)
    trained = est.fit(df)
    assert trained.history["loss"][-1] < trained.history["loss"][0]
