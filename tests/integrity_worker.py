"""Wire-integrity multiprocess worker: the bit_flip detect/undetect pair.

Scenario (docs/CHAOS.md "Wire integrity"): the fault plan arms a
``transport.send`` BIT_FLIP on rank 1 — one payload byte of the first
frame of at least ``min_bytes`` it sends to rank 0 is XOR'd AFTER the
send-side CRC was computed, i.e. the corruption happens ON THE WIRE.

* ``HVD_TEST_INTEGRITY_MODE=detect`` (checksum on, the default): rank
  0's reader must catch the mismatch — the failed collective surfaces
  ``HorovodInternalError`` NAMING peer 1 and the checksum, the engine
  counter ``transport_checksum_failures`` counts it, and the connection
  reset makes rank 1 fail too.  Both ranks then recover the way
  ``elastic.run`` would: disarm, shutdown, re-init, retry — and the
  retried collective is correct.
* ``HVD_TEST_INTEGRITY_MODE=undetect`` (``HVD_TPU_WIRE_CHECKSUM=0``):
  the IDENTICAL flip sails through — the job completes without any
  error while the allreduce result is silently WRONG — proving the
  checksum is load-bearing, not decorative.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from horovod_tpu.core.core_backend import CoreBackend  # noqa: E402
from horovod_tpu.elastic import HorovodInternalError  # noqa: E402
from horovod_tpu.ops.reduce_op import ReduceOp  # noqa: E402

N = 4096  # 16 KiB payload: ring chunks are ~8 KiB, far over min_bytes


def _await_counter(be, key, minimum=1, timeout=5.0):
    """The loop thread mirrors transport counters once per cycle; a
    read racing the event by one cycle must not flake the test."""
    import time
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        c = be.counters()
        if c.get(key, 0) >= minimum:
            return c
        time.sleep(0.01)
    raise AssertionError(f"{key} never reached {minimum}: "
                         f"{be.counters()}")


def main():
    mode = os.environ.get("HVD_TEST_INTEGRITY_MODE", "detect")
    be = CoreBackend()
    rank = be.rank

    # healthy phase: tiny frames, under the bit_flip min_bytes gate
    out = be.allreduce_async("warm", np.ones(4, np.float32),
                             ReduceOp.SUM).wait(60)
    np.testing.assert_allclose(out, 2.0)

    if mode == "detect":
        h = be.allreduce_async("big", np.ones(N, np.float32),
                               ReduceOp.SUM)
        try:
            h.wait(60)
            raise AssertionError(
                "expected the flipped frame to fail the collective")
        except HorovodInternalError as e:
            msg = str(e)
            if rank == 0:
                # the receiver of the corrupted frame must NAME the
                # corrupting peer and the failed check
                assert "checksum" in msg, msg
                assert "peer 1" in msg, msg
                _await_counter(be, "transport_checksum_failures")
        # recover through the elastic path's mechanics: disarm the
        # fault, tear the core down, re-init, retry — exactly what
        # elastic.run's HorovodInternalError branch does around
        # state.restore()
        os.environ.pop("HVD_TPU_FAULT_PLAN", None)
        from horovod_tpu import chaos
        chaos.uninstall()
        be.shutdown()
        be2 = CoreBackend()
        out = be2.allreduce_async("after", np.ones(8, np.float32),
                                  ReduceOp.SUM).wait(60)
        np.testing.assert_allclose(out, 2.0)
        if rank == 0:
            # the evidence SURVIVES the recovery: counters accumulate
            # across transport lives (a fresh transport's 0 must not
            # erase the recorded failure — a scrape after the few-second
            # recovery window still sees it)
            c = be2.counters()
            assert c.get("transport_checksum_failures", 0) >= 1, c
        be2.barrier()
        be2.shutdown()
    else:  # undetect: checksum off, the same flip passes silently
        assert os.environ.get("HVD_TPU_WIRE_CHECKSUM") == "0"
        h = be.allreduce_async("big", np.ones(N, np.float32),
                               ReduceOp.SUM)
        out = np.asarray(h.wait(120))  # completes — no error at all
        if rank == 1:
            # the flip really happened (send-side injection counter)
            c = _await_counter(be, "transport_chaos_injected")
        else:
            c = be.counters()
        assert c.get("transport_checksum_failures", 0) == 0, c
        if rank == 0:
            # ... and the reduced result is silently WRONG: this is the
            # failure mode the checksum exists to make impossible
            assert not np.array_equal(out, np.full(N, 2.0, np.float32)), \
                "flip armed but result uncorrupted — seam dead?"
        be.barrier()
        be.shutdown()

    print(f"integrity worker {rank}: OK ({mode})", flush=True)


if __name__ == "__main__":
    main()
